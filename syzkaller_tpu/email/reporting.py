"""The email reporting loop: Dashboard bugs out, commands in
(reference: dashboard/app/reporting.go state machine +
pkg/email round-trip).

Transport is a Mailbox interface (send/receive of raw RFC822 bytes):
production would bind SMTP/IMAP; tests bind an in-memory pair and
drive the full new -> reported -> fixed/invalid/dup lifecycle.
"""

from __future__ import annotations

import time
from typing import Optional

from syzkaller_tpu.email.parse import Email, parse_email
from syzkaller_tpu.email.render import render_report
from syzkaller_tpu.utils import log


class Mailbox:
    """In-memory transport double (production: SMTP out, IMAP in)."""

    def __init__(self):
        self.outgoing: list[bytes] = []
        self.incoming: list[bytes] = []

    def send(self, raw: bytes) -> None:
        self.outgoing.append(raw)

    def deliver(self, raw: bytes) -> None:
        self.incoming.append(raw)

    def receive(self) -> Optional[bytes]:
        if self.incoming:
            return self.incoming.pop(0)
        return None


class EmailReporting:
    """(reference: reporting.go reportingPoll + incomingMail)"""

    def __init__(self, dashboard, mailbox: Mailbox,
                 from_addr: str = "tz-bot@localhost",
                 to: Optional[list[str]] = None):
        self.dash = dashboard
        self.mailbox = mailbox
        self.from_addr = from_addr
        self.to = to or ["kernel-dev@localhost"]
        # msg-id <-> bug threading, persisted on the bug records so
        # replies survive a reporting-process restart.
        self.msg_to_bug: dict[str, str] = dashboard.report_threads()

    # -- outbound --------------------------------------------------------

    def poll_and_send(self) -> int:
        """Send a report mail for every bug due for reporting;
        returns how many were sent."""
        sent = 0
        for rep in self.dash.poll_reports():
            bug_id = rep["id"]
            # per-stage Message-ID: after '#syz upstream' the next
            # stage must start a FRESH thread, not collapse into (or
            # dedup against) the moderation-stage mail
            stage = rep.get("stage", "")
            suffix = f"-{stage}" if stage else ""
            msg_id = f"<tz-bug-{bug_id}{suffix}@localhost>"
            payload = self.dash.bug_report_payload(bug_id)
            self.mailbox.send(render_report(payload, self.from_addr,
                                            self.to, msg_id))
            self.msg_to_bug[msg_id] = bug_id
            self.dash.set_report_msg_id(bug_id, msg_id)
            sent += 1
        return sent

    # -- inbound ---------------------------------------------------------

    def process_incoming(self) -> int:
        """Drain the inbox, applying '#syz' commands to their bugs;
        returns how many commands were applied."""
        applied = 0
        while True:
            raw = self.mailbox.receive()
            if raw is None:
                return applied
            em = parse_email(raw)
            bug_id = self.msg_to_bug.get(em.in_reply_to)
            if bug_id is None:
                log.logf(1, "email: reply to unknown thread %r",
                         em.in_reply_to)
                continue
            applied += self._apply(bug_id, em)

    def _apply(self, bug_id: str, em: Email) -> int:
        n = 0
        for cmd in em.commands:
            if cmd.name == "fix":
                if not cmd.args:
                    self._nack(em, "fix command needs a commit title")
                    continue
                self.dash.update_bug(bug_id, fix_commit=cmd.args)
            elif cmd.name == "dup":
                if not cmd.args:
                    self._nack(em, "dup command needs a bug title")
                    continue
                try:
                    self.dash.update_bug(bug_id, dup_of=cmd.args)
                except KeyError as e:
                    self._nack(em, str(e))
                    continue
            elif cmd.name == "invalid":
                self.dash.update_bug(bug_id, status="invalid")
            elif cmd.name == "undup":
                self.dash.update_bug(bug_id, undup=True)
            elif cmd.name == "test":
                parts = cmd.args.split()
                if not em.patch:
                    self._nack(em, "test command needs a patch in the body")
                    continue
                repo = parts[0] if parts else ""
                branch = parts[1] if len(parts) > 1 else ""
                self.dash.add_job(bug_id, em.patch, kernel_repo=repo,
                                  kernel_branch=branch)
            elif cmd.name == "upstream":
                if not self.dash.upstream_bug(bug_id):
                    self._nack(em, "bug is already at the last "
                                   "reporting stage")
                    continue
            else:
                self._nack(em, f"unknown command {cmd.name!r}")
                continue
            n += 1
        return n

    def _nack(self, em: Email, why: str) -> None:
        """Error reply back to the sender (reference: reporting.go
        replyTo with the error text)."""
        from email.message import EmailMessage

        m = EmailMessage()
        m["Subject"] = "Re: " + em.subject
        m["From"] = self.from_addr
        m["To"] = em.from_addr
        m["In-Reply-To"] = em.msg_id
        m.set_content(f"Your command could not be processed: {why}\n")
        self.mailbox.send(bytes(m))
        log.logf(1, "email: bad command from %s: %s", em.from_addr, why)


def _now() -> float:
    return time.time()
