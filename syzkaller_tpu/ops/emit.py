"""Exec-bytes emission from mutated program tensors.

The reference re-serializes every mutant with a typed tree walk
(reference: prog/encodingexec.go:57-192).  The device pipeline instead
serializes each corpus template ONCE (with fixed-capacity data regions
and an ExecRecord of patch positions) and turns every mutant into

    memcpy(template words) + vectorized value/meta patches
    + data-region splices + alive-segment slicing

— the "serialize-to-exec is a gather" contract from SURVEY.md §7.
Call removal is a pure post-patch slice of per-call word ranges; a
dangling RESULT reference to a removed call's copyout degrades to the
arg's default value inside the executor, which is exactly the
reference's remove-call semantics for broken resource edges
(reference: prog/prog.go:428-503).

Known deliberate approximations vs the typed path (both converge on
triage, where accepted inputs are decoded and re-encoded typed):
  - only directly-linked (buf, len) pairs are kept consistent after
    data mutation (see ops/mutate._fixup_lens); struct-spanning size
    fields keep their template values,
  - data regions grown on device reuse the template's guest address
    (no reallocation on growth).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from syzkaller_tpu.models.encodingexec import (
    EXEC_BUFFER_SIZE,
    ExecRecord,
    serialize_for_exec,
)
from syzkaller_tpu.models.any_squash import call_contains_any
from syzkaller_tpu.ops.tensor import DATA, FLAGS, INT, LEN, PROC, ProgTensor

MASK64 = np.uint64(0xFFFFFFFFFFFFFFFF)
MAX_COPYOUT = 256  # executor copyout table size (executor/wire.h:53)

# word_call sentinels (ExecTemplate.word_call).
WORD_EOF = -1
WORD_ORPHAN = -2


@dataclass
class ExecTemplate:
    """Per-corpus-program assembly metadata (host side)."""

    words: np.ndarray  # uint64[W] template stream incl. trailing EOF
    call_bounds: np.ndarray  # int32[ncalls, 2] word ranges
    ncalls: int
    ncopyouts: int  # copyout indices the template consumes (donor
    # splices rebase past these; budget: executor/wire.h kMaxCopyout)
    # Slot-aligned patch arrays (length = cfg.max_slots):
    val_word: np.ndarray  # int32[S], -1 = slot has no value word
    meta_word: np.ndarray  # int32[S]
    len_word: np.ndarray  # int32[S], DATA slots only
    data_word: np.ndarray  # int32[S] payload start word
    data_cap: np.ndarray  # int32[S]
    data_off: np.ndarray  # int32[S] arena offset (static on device)
    aux0: np.ndarray  # uint64[S]
    # PROC slots encode conditionally (reference: prog/prog.go:66-74
    # Value()): default (= 0xFF..F) serializes as plain 0 without
    # stride, concrete values as start+v with the per-proc stride and
    # the type's endianness in the meta word.  Both metas are derived
    # from the TYPE at build time — the template's meta reflects only
    # the template's value.
    proc_meta_default: np.ndarray  # uint64[S]
    proc_meta_concrete: np.ndarray  # uint64[S]
    # Pre-split masks/indices for the assembly fast path:
    value_slots: np.ndarray  # int32[k] slots patched via val_word
    proc_slots: np.ndarray  # int32[k2] PROC slots (conditional stride)
    data_slots: np.ndarray  # int32[k3] DATA slots
    is_proc: np.ndarray  # bool[S]
    calls_any: np.ndarray  # bool[ncalls]: call contains a squashed ANY
    # (consumed by the pipeline's signal_prio for undecoded mutants)
    # Precomputed alive-slicing mask source: owning call per template
    # word.  WORD_EOF marks the trailing EOF word (kept by every
    # mutant); WORD_ORPHAN marks words outside any call segment
    # (dropped whenever a call is sliced — matching _slice_alive,
    # which concatenates only alive segments + EOF).
    word_call: np.ndarray  # int32[W]
    seg_tiled: bool  # call segments tile [0, W-1) in order
    insert_cut: np.ndarray  # int64[ncalls+1] splice word offset by pos


def build_exec_template(t: ProgTensor,
                        buffer_size: int = EXEC_BUFFER_SIZE) -> ExecTemplate:
    """Serialize t.template once, recording patch positions for every
    device-mutable slot."""
    rec = ExecRecord()
    caps = {id(t.slot_args[s]): int(t.cap[s])
            for s in range(len(t.slot_args)) if t.kind[s] == DATA}
    stream = serialize_for_exec(t.template, buffer_size, data_caps=caps,
                                record=rec)
    words = np.frombuffer(stream, dtype="<u8").copy()

    S = t.kind.shape[0]
    val_word = np.full(S, -1, dtype=np.int32)
    meta_word = np.full(S, -1, dtype=np.int32)
    len_word = np.full(S, -1, dtype=np.int32)
    data_word = np.full(S, -1, dtype=np.int32)
    data_cap = np.zeros(S, dtype=np.int32)
    proc_meta_default = np.zeros(S, dtype=np.uint64)
    proc_meta_concrete = np.zeros(S, dtype=np.uint64)

    for s, arg in enumerate(t.slot_args):
        k = int(t.kind[s])
        if k in (INT, FLAGS, PROC, LEN):
            vw = rec.val_word.get(id(arg))
            if vw is not None:
                val_word[s] = vw
                meta_word[s] = rec.meta_word[id(arg)]
            if k == PROC:
                typ = arg.typ
                base = (arg.size()
                        | (typ.bitfield_offset() << 16)
                        | (typ.bitfield_length() << 24))
                proc_meta_default[s] = base
                proc_meta_concrete[s] = (
                    base
                    | (int(bool(getattr(typ, "big_endian", False))) << 8)
                    | (typ.values_per_proc << 32))
        elif k == DATA:
            dw = rec.data_word.get(id(arg))
            if dw is not None:
                len_word[s], data_word[s], data_cap[s] = dw

    kinds = np.asarray(t.kind)
    value_slots = np.nonzero((val_word >= 0) & (kinds != PROC))[0] \
        .astype(np.int32)
    proc_slots = np.nonzero((val_word >= 0) & (kinds == PROC))[0] \
        .astype(np.int32)
    data_slots = np.nonzero(len_word >= 0)[0].astype(np.int32)

    target = t.template.target
    calls_any = np.array(
        [call_contains_any(target, c) for c in t.template.calls], dtype=bool)

    call_bounds = np.array(rec.call_bounds or np.empty((0, 2)),
                           dtype=np.int32).reshape(-1, 2)
    word_call = np.full(len(words), WORD_ORPHAN, dtype=np.int32)
    for i, (a, b) in enumerate(call_bounds):
        word_call[a:b] = i
    word_call[-1] = WORD_EOF
    # Segments tile the stream (call i ends where i+1 starts, EOF
    # last) for every serializer-produced template; the flag guards
    # the splice fast path against a future layout that interleaves.
    seg_tiled = bool(
        len(call_bounds) == 0
        or (call_bounds[0, 0] == 0
            and (call_bounds[1:, 0] == call_bounds[:-1, 1]).all()
            and call_bounds[-1, 1] == len(words) - 1))
    # Insertion word offset after `pos` alive calls when every call is
    # alive: insert_cut[pos] (length ncalls+1).
    insert_cut = np.concatenate(
        [np.zeros(1, np.int64),
         call_bounds[:, 1].astype(np.int64)]) \
        if len(call_bounds) else np.zeros(1, np.int64)

    return ExecTemplate(
        words=words,
        call_bounds=call_bounds,
        ncalls=t.ncalls,
        ncopyouts=rec.ncopyouts,
        val_word=val_word, meta_word=meta_word,
        len_word=len_word, data_word=data_word, data_cap=data_cap,
        data_off=np.asarray(t.off, dtype=np.int32).copy(),
        aux0=np.asarray(t.aux0).copy(),
        proc_meta_default=proc_meta_default,
        proc_meta_concrete=proc_meta_concrete,
        value_slots=value_slots, proc_slots=proc_slots,
        data_slots=data_slots,
        is_proc=(kinds == PROC) & (val_word >= 0),
        calls_any=calls_any,
        word_call=word_call,
        seg_tiled=seg_tiled,
        insert_cut=insert_cut,
    )


def assemble(et: ExecTemplate, val: np.ndarray, len_: np.ndarray,
             arena: np.ndarray, call_alive: np.ndarray) -> bytes:
    """Assemble exec wire bytes for one mutant.

    val/len_/arena/call_alive are the mutated tensor rows (numpy, host).
    Patches are applied on the full template first; call removal is
    then a slice of per-call ranges, so no patch index ever shifts."""
    w = et.words.copy()

    vs = et.value_slots
    if vs.size:
        w[et.val_word[vs]] = val[vs]

    ps = et.proc_slots
    if ps.size:
        pv = val[ps]
        is_default = pv == MASK64
        w[et.val_word[ps]] = np.where(is_default, np.uint64(0),
                                      et.aux0[ps] + pv)
        w[et.meta_word[ps]] = np.where(is_default, et.proc_meta_default[ps],
                                       et.proc_meta_concrete[ps])

    u8 = w.view(np.uint8)
    for s in et.data_slots:
        ln = int(len_[s])
        cap = int(et.data_cap[s])
        ln = min(ln, cap)
        w[et.len_word[s]] = np.uint64(ln | (cap << 32))
        start = int(et.data_word[s]) * 8
        off = int(et.data_off[s])
        u8[start:start + ln] = arena[off:off + ln]
        # Zero the region tail: bit-exact with the typed serializer's
        # zero padding, and no stale template bytes on the wire.
        u8[start + ln:start + cap + (-cap) % 8] = 0

    return _slice_alive(et, w, call_alive)


def _slice_alive(et: ExecTemplate, w: np.ndarray,
                 call_alive: np.ndarray) -> bytes:
    """Drop dead calls' segments (patches were applied to the full
    template, so indices never shift) and keep the EOF word."""
    nc = et.ncalls
    if bool(call_alive[:nc].all()):
        return w.tobytes()
    parts = [w[a:b] for (a, b), alive
             in zip(et.call_bounds, call_alive[:nc]) if alive]
    parts.append(w[-1:])  # EOF
    return np.concatenate(parts).tobytes()


def assemble_delta(et: ExecTemplate, batch, j: int) -> bytes:
    """Assemble exec bytes for mutant j of a DeltaBatch
    (ops/delta.DeltaBatch): same patch rules as assemble(), applied
    only to the changed slots the delta carries.  ~O(changes) per
    mutant instead of O(slots)."""
    w = et.words.copy()
    u8 = None

    for i in range(int(batch.nvals[j])):
        s = int(batch.val_idx[j, i])
        if s < 0:
            continue
        vw = int(et.val_word[s])
        if vw < 0:
            continue
        v = batch.vals[j, i]
        if et.is_proc[s]:
            if v == MASK64:
                w[vw] = 0
                w[int(et.meta_word[s])] = et.proc_meta_default[s]
            else:
                w[vw] = et.aux0[s] + v
                w[int(et.meta_word[s])] = et.proc_meta_concrete[s]
        else:
            w[vw] = v

    for i in range(int(batch.ndata[j])):
        s = int(batch.data_slot[j, i])
        if s < 0 or int(et.len_word[s]) < 0:
            continue
        cap = int(et.data_cap[s])
        ln = min(int(batch.data_len[j, i]), cap)
        w[int(et.len_word[s])] = np.uint64(ln | (cap << 32))
        if u8 is None:
            u8 = w.view(np.uint8)
        start = int(et.data_word[s]) * 8
        po = int(batch.data_off[j, i])
        u8[start:start + ln] = batch.payload[j, po:po + ln]
        u8[start + ln:start + cap + (-cap) % 8] = 0

    alive = batch.call_alive(j, max(et.ncalls, 1))
    return _slice_alive(et, w, alive)


def assemble_batch(ets: list, batch, js: np.ndarray) -> list:
    """Assemble exec streams for mutants `js` of a DeltaBatch in one
    vectorized numpy pass per template group (the host-side hot path:
    a Python-per-mutant loop here was 4x slower than the device kernel,
    so value patches scatter across the whole group at once).

    ets is the exec-template snapshot indexable by batch.template_idx.
    Returns a list aligned with js; entries are bytes-like — zero-copy
    (offset, length) memoryviews into a contiguous per-group output
    arena on the fast path, plain bytes on the per-mutant fallback —
    or None (missing template / assembly failure).  Views pin their
    arena, so a batch's memory lives exactly as long as its last
    undelivered mutant."""
    out: list = [None] * len(js)
    if len(js) == 0:
        return out
    js = np.asarray(js, dtype=np.int64)
    tidx = batch.template_idx[js]
    order = np.argsort(tidx, kind="stable")
    bounds = np.flatnonzero(np.diff(tidx[order])) + 1
    for grp in np.split(order, bounds):
        ti = int(tidx[grp[0]])
        et = ets[ti] if 0 <= ti < len(ets) else None
        if et is None:
            continue
        rows = js[grp]
        try:
            datas = _assemble_group(et, batch, rows)
        except Exception:
            # Degrade to the per-mutant path so one bad row cannot
            # sink its whole template group.
            datas = []
            for j in rows:
                try:
                    datas.append(assemble_delta(et, batch, int(j)))
                except Exception:
                    datas.append(None)
        for pos, data in zip(grp, datas):
            out[int(pos)] = data
    return out


class TemplateTable:
    """Stacked per-template assembly metadata over one exec-template
    snapshot: every slot-aligned patch array becomes a (T, S) table
    and the word streams flatten into one array with offsets — so a
    whole batch of full-alive mutants assembles in ONE vectorized
    pass (assemble_batch_table) with no per-template Python at all.
    Built once per corpus snapshot and cached by the pipeline; dead
    slots (no template) stay masked via `valid`."""

    __slots__ = ("ets", "valid", "w_len", "w_off", "words_flat",
                 "wc_flat", "full_bits", "val_word", "meta_word",
                 "len_word", "data_word", "data_cap", "aux0",
                 "proc_meta_default", "proc_meta_concrete", "is_proc",
                 "ncalls", "ncopyouts", "seg_tiled", "cut_off",
                 "cut_flat")

    def __init__(self, ets: list):
        self.ets = ets
        T = len(ets)
        first = next((et for et in ets if et is not None), None)
        S = first.val_word.shape[0] if first is not None else 0
        self.valid = np.array([et is not None for et in ets], dtype=bool)
        self.w_len = np.array([et.words.size if et is not None else 0
                               for et in ets], dtype=np.int64)
        self.w_off = np.cumsum(self.w_len) - self.w_len
        self.words_flat = np.concatenate(
            [et.words for et in ets if et is not None]) \
            if first is not None else np.empty(0, np.uint64)
        self.wc_flat = np.concatenate(
            [et.word_call for et in ets if et is not None]) \
            if first is not None else np.empty(0, np.int32)
        self.full_bits = np.array(
            [0 if et is None
             else ((1 << et.ncalls) - 1 if et.ncalls < 64 else 2**64 - 1)
             for et in ets], dtype=np.uint64)
        # Insert-splice metadata (splice_batch_table): per-template
        # call counts, copyout bases, tiling flags, and the flattened
        # insert_cut tables (ragged, ncalls+1 entries each).
        self.ncalls = np.array([et.ncalls if et is not None else 0
                                for et in ets], dtype=np.int64)
        self.ncopyouts = np.array(
            [et.ncopyouts if et is not None else 0 for et in ets],
            dtype=np.int64)
        self.seg_tiled = np.array(
            [bool(et.seg_tiled) if et is not None else False
             for et in ets], dtype=bool)
        cut_len = np.array(
            [et.insert_cut.size if et is not None else 1 for et in ets],
            dtype=np.int64)
        self.cut_off = np.cumsum(cut_len) - cut_len
        self.cut_flat = np.concatenate(
            [et.insert_cut if et is not None else np.zeros(1, np.int64)
             for et in ets]) if T else np.zeros(0, np.int64)

        def stack(attr, fill, dtype):
            tbl = np.full((T, S), fill, dtype=dtype)
            for i, et in enumerate(ets):
                if et is not None:
                    tbl[i] = getattr(et, attr)
            return tbl

        self.val_word = stack("val_word", -1, np.int32)
        self.meta_word = stack("meta_word", -1, np.int32)
        self.len_word = stack("len_word", -1, np.int32)
        self.data_word = stack("data_word", -1, np.int32)
        self.data_cap = stack("data_cap", 0, np.int64)
        self.aux0 = stack("aux0", 0, np.uint64)
        self.proc_meta_default = stack("proc_meta_default", 0, np.uint64)
        self.proc_meta_concrete = stack("proc_meta_concrete", 0, np.uint64)
        self.is_proc = stack("is_proc", False, bool)


def assemble_batch_table(table: TemplateTable, batch,
                         js: np.ndarray) -> list:
    """Assemble exec streams for mutants `js` in ONE vectorized pass
    across ALL templates: base-copy every row's template words into a
    single contiguous per-batch output arena (ragged gather), scatter
    every value/PROC patch through the stacked (T, S) tables, run the
    ragged payload memcpys globally, and return zero-copy memoryview
    slices.  Rows with dead calls (alive slicing) or a missing
    template degrade to the per-group assemble_batch path, which is
    bit-exact by construction.  Aligned with js; None = failure."""
    js = np.asarray(js, dtype=np.int64)
    out: list = [None] * len(js)
    if len(js) == 0:
        return out
    tid = batch.template_idx[js].astype(np.int64)
    in_range = (tid >= 0) & (tid < len(table.valid))
    tidc = np.where(in_range, tid, 0)
    valid_t = table.valid[tidc] & in_range
    main = np.flatnonzero(valid_t)
    if not main.size:
        return out
    try:
        datas = _assemble_rows_table(table, batch, js[main], tidc[main])
    except Exception:
        # One bad row cannot sink the whole pass: degrade to the
        # per-group path (which itself degrades per-mutant).
        datas = assemble_batch(table.ets, batch, js[main])
    for p, d in zip(main, datas):
        out[int(p)] = d
    return out


def _assemble_rows_table(table: TemplateTable, batch, mjs: np.ndarray,
                         mt: np.ndarray) -> list:
    """The global pass behind assemble_batch_table: one full-width
    arena, three scatter/gather families, and — only for rows with
    dead calls — a flat keep-mask compress through the stacked
    word->call map into a side arena.  Zero per-row work.

    Rows are processed template-sorted so the base copy collapses to
    one broadcast memcpy per unique template (contiguous arena
    block) instead of a ragged gather; outputs are mapped back to the
    callers' row order at the end."""
    order = np.argsort(mt, kind="stable")
    mjs = mjs[order]
    mt = mt[order]
    w_len = table.w_len[mt]
    ends = np.cumsum(w_len)
    starts = ends - w_len
    arena = np.empty(int(ends[-1]) if len(ends) else 0, np.uint64)
    grp_bounds = np.flatnonzero(np.diff(mt)) + 1
    for lo, hi in zip(np.concatenate([[0], grp_bounds]),
                      np.concatenate([grp_bounds, [len(mt)]])):
        et = table.ets[mt[lo]]
        arena[starts[lo]:ends[hi - 1]].reshape(hi - lo, -1)[:] = et.words

    # -- value patches --
    K = batch.val_idx.shape[1]
    slots = batch.val_idx[mjs].ravel().astype(np.int64)
    sel = np.flatnonzero(slots >= 0)
    if sel.size:
        rr = sel // K
        ss = slots[sel]
        tr = mt[rr]
        vw = table.val_word[tr, ss].astype(np.int64)
        g = vw >= 0
        if not g.all():
            sel, rr, ss, tr, vw = (a[g] for a in (sel, rr, ss, tr, vw))
        v = batch.vals[mjs].ravel()[sel]
        dest = starts[rr] + vw
        isp = table.is_proc[tr, ss]
        if isp.any():
            ni = np.flatnonzero(~isp)
            arena[dest[ni]] = v[ni]
            pi = np.flatnonzero(isp)
            vv = v[pi]
            dflt = vv == MASK64
            tp, sp = tr[pi], ss[pi]
            with np.errstate(over="ignore"):
                arena[dest[pi]] = np.where(
                    dflt, np.uint64(0), table.aux0[tp, sp] + vv)
            mw = table.meta_word[tp, sp].astype(np.int64)
            arena[starts[rr[pi]] + mw] = np.where(
                dflt, table.proc_meta_default[tp, sp],
                table.proc_meta_concrete[tp, sp])
        else:
            arena[dest] = v

    # -- data patches (global ragged zero + payload copy) --
    D = batch.data_slot.shape[1]
    ds = batch.data_slot[mjs].ravel().astype(np.int64)
    dsel = np.flatnonzero(ds >= 0)
    if dsel.size:
        drr = dsel // D
        dss = ds[dsel]
        dtr = mt[drr]
        lw = table.len_word[dtr, dss].astype(np.int64)
        g = lw >= 0
        if not g.all():
            dsel, drr, dss, dtr, lw = (
                a[g] for a in (dsel, drr, dss, dtr, lw))
        if dsel.size:
            caps = table.data_cap[dtr, dss]
            lens = np.minimum(
                batch.data_len[mjs].ravel()[dsel].astype(np.int64), caps)
            if np.any(lens < 0):
                raise ValueError("negative data length in delta row")
            arena[starts[drr] + lw] = (lens | (caps << 32)) \
                .astype(np.uint64)
            u8 = arena.view(np.uint8)
            dst0 = (starts[drr]
                    + table.data_word[dtr, dss].astype(np.int64)) * 8
            e, k = _ragged_spans(caps + (-caps) % 8)
            u8[dst0[e] + k] = 0
            pidx = batch.pool_idx[mjs].astype(np.int64)[drr]
            cp = np.flatnonzero(pidx >= 0)
            if cp.size and len(batch._pool):
                offs = batch.data_off[mjs].ravel()[dsel[cp]] \
                    .astype(np.int64)
                ln_e = lens[cp]
                if np.any(offs < 0) or np.any(offs + ln_e > batch.spec.P):
                    raise ValueError("payload span exceeds pool slot")
                src0 = pidx[cp] * batch.spec.P + offs
                e, k = _ragged_spans(ln_e)
                u8[dst0[cp][e] + k] = batch._pool.reshape(-1)[src0[e] + k]

    # -- alive slicing: rows with dead calls compress through the
    # word->call map into a side arena; full rows stay where they are
    # (the patched arena already IS their stream, orphans included —
    # matching _slice_alive's full path) --
    ab = batch.alive_bits[mjs] & table.full_bits[mt]
    is_full = ab == table.full_bits[mt]
    u8v = memoryview(arena.view(np.uint8))
    inv = np.empty(len(order), np.int64)
    inv[order] = np.arange(len(order))
    if bool(is_full.all()):
        return [u8v[int(starts[i]) * 8:int(ends[i]) * 8] for i in inv]
    dead = np.flatnonzero(~is_full)
    e, k = _ragged_spans(w_len[dead])
    src = starts[dead][e] + k
    wcv = table.wc_flat[table.w_off[mt[dead]][e] + k].astype(np.int64)
    keep = wcv == WORD_EOF
    call = wcv >= 0
    keep[call] = ((ab[dead][e][call]
                   >> wcv[call].astype(np.uint64)) & 1) != 0
    sub = arena[src[keep]]
    counts = np.bincount(e[keep], minlength=len(dead)).astype(np.int64)
    dends = np.cumsum(counts)
    su8 = memoryview(sub.view(np.uint8))
    dmap = np.full(len(mjs), -1, np.int64)
    dmap[dead] = np.arange(len(dead))
    datas: list = []
    for i in inv:
        if is_full[i]:
            datas.append(u8v[int(starts[i]) * 8:int(ends[i]) * 8])
        else:
            dp = int(dmap[i])
            hi = int(dends[dp]) * 8
            datas.append(su8[hi - int(counts[dp]) * 8:hi])
    return datas


class DonorBankTable:
    """The donor bank flattened for the one-pass splicer: raw
    (un-rebased) block words, per-block offsets/lengths, and the
    flattened copyout-word positions so rebasing happens as one ragged
    in-arena add.  Built once per bank — base-independent, unlike
    build_donor_table."""

    __slots__ = ("w_flat", "w_off", "w_len", "cw_flat", "cw_off",
                 "cw_len", "ncopyouts")

    def __init__(self, blocks: list):
        self.w_len = np.array([b.words.size for b in blocks],
                              dtype=np.int64)
        self.w_off = np.cumsum(self.w_len) - self.w_len
        self.w_flat = np.concatenate([b.words for b in blocks]) \
            if blocks else np.empty(0, np.uint64)
        self.cw_len = np.array([b.copyout_words.size for b in blocks],
                               dtype=np.int64)
        self.cw_off = np.cumsum(self.cw_len) - self.cw_len
        self.cw_flat = np.concatenate(
            [np.asarray(b.copyout_words, dtype=np.int64)
             for b in blocks]) if blocks else np.empty(0, np.int64)
        self.ncopyouts = np.array([b.ncopyouts for b in blocks],
                                  dtype=np.int64)


def splice_batch_table(table: TemplateTable, dtab: DonorBankTable,
                       batch, ins: np.ndarray) -> tuple:
    """One-pass insert splicing across ALL templates: rows whose
    template is tiled and fully alive (the overwhelming case — insert
    mutants keep the template's alive bitmap) are assembled as four
    global ragged operations into one arena: template prefix, donor
    words, an in-place copyout-rebase add, template suffix (+ EOF).
    Returns (views aligned with ins, fast-row mask); rows outside the
    fast conditions are left for the caller's per-group path."""
    ins = np.asarray(ins, dtype=np.int64)
    out: list = [None] * len(ins)
    if len(ins) == 0:
        return out, np.zeros(0, bool)
    tid = batch.template_idx[ins].astype(np.int64)
    in_range = (tid >= 0) & (tid < len(table.valid))
    tidc = np.where(in_range, tid, 0)
    d = batch.donor[ins].astype(np.int64)
    d_ok = (d >= 0) & (d < len(dtab.w_len))
    dc = np.where(d_ok, d, 0)
    full = table.full_bits[tidc]
    fast = (in_range & table.valid[tidc] & table.seg_tiled[tidc]
            & d_ok
            & ((batch.alive_bits[ins] & full) == full)
            & (table.ncopyouts[tidc] + dtab.ncopyouts[dc] <= MAX_COPYOUT))
    m = np.flatnonzero(fast)
    if not m.size:
        return out, fast
    t = tidc[m]
    dm = dc[m]
    pos = np.minimum(batch.pos[ins[m]].astype(np.int64), table.ncalls[t])
    cut = table.cut_flat[table.cut_off[t] + pos]
    w_t = table.w_len[t]
    dl = dtab.w_len[dm]
    total = w_t + dl
    ends = np.cumsum(total)
    starts = ends - total
    arena = np.empty(int(ends[-1]), np.uint64)
    # Template words land in one fused pass: words past the cut shift
    # right by the donor length (the gap the donor fills).
    e, k = _ragged_spans(w_t)
    arena[starts[e] + k + np.where(k >= cut[e], dl[e], 0)] = \
        table.words_flat[table.w_off[t][e] + k]
    e, k = _ragged_spans(dl)
    arena[(starts + cut)[e] + k] = dtab.w_flat[dtab.w_off[dm][e] + k]
    e, k = _ragged_spans(dtab.cw_len[dm])
    if e.size:
        # Rebase the spliced-in copyout indices in place: positions
        # are unique per row, so the fancy add never collides.
        at = (starts + cut)[e] + dtab.cw_flat[dtab.cw_off[dm][e] + k]
        arena[at] += table.ncopyouts[t][e].astype(np.uint64)
    u8 = memoryview(arena.view(np.uint8))
    for idx, p in enumerate(m):
        out[int(p)] = u8[int(starts[idx]) * 8:int(ends[idx]) * 8]
    return out, fast


def shard_by_template(template_idx: np.ndarray, js: np.ndarray,
                      shards: int) -> list:
    """Split mutants `js` into at most `shards` balanced work shards
    WITHOUT splitting a template group (assemble_batch amortizes its
    patch pass per group, so a split group costs two passes).  Greedy
    smallest-shard assignment over size-sorted groups; returns a list
    of js-subset arrays, largest first, empty shards dropped."""
    js = np.asarray(js, dtype=np.int64)
    if shards <= 1 or len(js) == 0:
        return [js] if len(js) else []
    tidx = template_idx[js]
    order = np.argsort(tidx, kind="stable")
    bounds = np.flatnonzero(np.diff(tidx[order])) + 1
    groups = np.split(js[order], bounds)
    groups.sort(key=len, reverse=True)
    bins: list = [[] for _ in range(min(shards, len(groups)))]
    sizes = [0] * len(bins)
    for g in groups:
        i = sizes.index(min(sizes))
        bins[i].append(g)
        sizes[i] += len(g)
    return [np.concatenate(b) for b in bins if b]


def _ragged_spans(lengths: np.ndarray):
    """Flattened advanced-indexing coordinates for variable-length
    spans: (entry index e, within-span offset k) for every byte of
    every span, with no Python loop.  Positions into a flat buffer are
    then `starts[e] + k` for any per-entry starts array.  int32: the
    index arrays are the pass's main memory traffic, and spans here
    are bounded far below 2^31."""
    lengths = np.asarray(lengths, dtype=np.int64)
    total = int(lengths.sum())
    if total == 0:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    e = np.repeat(np.arange(lengths.size, dtype=np.int32), lengths)
    k = np.arange(total, dtype=np.int32)
    k -= np.repeat((np.cumsum(lengths) - lengths).astype(np.int32),
                   lengths)
    return e, k


def _assemble_group(et: ExecTemplate, batch, rows: np.ndarray) -> list:
    """Vectorized assemble_delta over mutants `rows` sharing one
    template: one (m, W) patch pass (value scatters + flattened
    ragged payload memcpys), then one boolean-gather pass through the
    precomputed word->call mask into a contiguous output arena.
    Returns per-mutant memoryview slices of that arena — no per-mutant
    tobytes() copy anywhere."""
    m = len(rows)
    W = et.words.shape[0]
    w = np.broadcast_to(et.words, (m, W)).copy()

    # -- value patches (vectorized scatter) --
    slots = batch.val_idx[rows]  # (m, K) int16, -1 padded
    valid = slots >= 0
    s = np.where(valid, slots, 0).astype(np.int64)
    vw = et.val_word[s]  # (m, K)
    valid &= vw >= 0
    vals = batch.vals[rows]  # (m, K) uint64
    isp = et.is_proc[s]

    r, c = np.nonzero(valid & ~isp)
    if r.size:
        w[r, vw[r, c]] = vals[r, c]

    r, c = np.nonzero(valid & isp)
    if r.size:
        sv = s[r, c]
        v = vals[r, c]
        dflt = v == MASK64
        with np.errstate(over="ignore"):
            w[r, vw[r, c]] = np.where(dflt, np.uint64(0), et.aux0[sv] + v)
        w[r, et.meta_word[sv]] = np.where(
            dflt, et.proc_meta_default[sv], et.proc_meta_concrete[sv])

    # -- data patches: len words scattered, then the ragged payload
    # memcpys as TWO flattened advanced-indexing passes (zero the full
    # cap-padded regions, copy the live payload bytes over them) —
    # bit-exact with the per-mutant path, which overwrites [0, ln)
    # with payload and [ln, cappad) with zeros --
    dslots = batch.data_slot[rows]  # (m, D)
    dvalid = dslots >= 0
    if dvalid.any():
        ds = np.where(dvalid, dslots, 0).astype(np.int64)
        lw = et.len_word[ds]
        dvalid &= lw >= 0
        caps = et.data_cap[ds].astype(np.int64)
        lens = np.minimum(batch.data_len[rows].astype(np.int64), caps)
        r, c = np.nonzero(dvalid)
        if r.size:
            if np.any(lens[r, c] < 0):
                # A negative length raises per-mutant in assemble_delta;
                # degrade to that path rather than wrap silently here.
                raise ValueError("negative data length in delta row")
            w[r, lw[r, c]] = (lens[r, c] | (caps[r, c] << 32)) \
                .astype(np.uint64)
            u8 = w.view(np.uint8).reshape(-1)  # one flat (m*W*8,) view
            dst0 = r.astype(np.int64) * (W * 8) \
                + et.data_word[ds[r, c]].astype(np.int64) * 8
            cap_e = caps[r, c]
            e, k = _ragged_spans(cap_e + (-cap_e) % 8)
            u8[dst0[e] + k] = 0
            # Payload copy: rows without a pool slot (pool_idx < 0)
            # read all-zero payloads — the zero fill above already IS
            # that copy, so only pooled entries move bytes.
            pidx = batch.pool_idx[rows[r]].astype(np.int64)
            cp = np.flatnonzero(pidx >= 0)
            if cp.size and len(batch._pool):
                pool_flat = batch._pool.reshape(-1)
                ln_e = lens[r, c][cp]
                offs = batch.data_off[rows[r[cp]], c[cp]].astype(np.int64)
                if np.any(offs < 0) or np.any(offs + ln_e > batch.spec.P):
                    # A span past its pool slot would read the next
                    # slot's bytes; assemble_delta raises instead —
                    # degrade to it.
                    raise ValueError("payload span exceeds pool slot")
                src0 = pidx[cp] * batch.spec.P + offs
                e, k = _ragged_spans(ln_e)
                u8[dst0[cp][e] + k] = pool_flat[src0[e] + k]

    # -- alive slicing via the precomputed word->call mask, into one
    # contiguous per-group arena --
    nc = et.ncalls
    full = np.uint64((1 << nc) - 1) if nc < 64 else np.uint64(2**64 - 1)
    alive_bits = batch.alive_bits[rows] & full
    if bool((alive_bits == full).all()):
        # Every call alive: the patched block already is the arena.
        arena = w
        counts = np.full(m, W, dtype=np.int64)
    else:
        wc = et.word_call
        shift = np.where(wc >= 0, wc, 0).astype(np.uint64)
        keep = ((alive_bits[:, None] >> shift[None, :]) & 1) != 0
        keep[:, wc == WORD_EOF] = True
        keep[:, wc == WORD_ORPHAN] = False
        counts = keep.sum(axis=1, dtype=np.int64)
        arena = w.reshape(-1)[keep.reshape(-1)]

    u8 = memoryview(arena.reshape(-1).view(np.uint8))
    ends = np.cumsum(counts) * 8
    datas: list = []
    for i in range(m):
        hi = int(ends[i])
        datas.append(u8[hi - int(counts[i]) * 8:hi])
    return datas


def build_donor_table(base_copyouts: int, blocks: list) -> tuple:
    """The whole donor bank rebased past `base_copyouts`, flattened
    for ragged gathering: (flat words, per-block offsets, per-block
    lengths, per-block budget-ok mask).  One table serves every
    template with the same copyout count — callers cache by base
    (bounded: base <= MAX_COPYOUT)."""
    lens = np.array([b.words.size for b in blocks], dtype=np.int64)
    offs = np.cumsum(lens) - lens
    ok = np.array([base_copyouts + b.ncopyouts <= MAX_COPYOUT
                   for b in blocks], dtype=bool)
    flat = np.concatenate(
        [b.rebased_words(base_copyouts) for b in blocks]) \
        if blocks else np.empty(0, np.uint64)
    return flat, offs, lens, ok


def splice_insert_group(et: ExecTemplate, alive_bits: np.ndarray,
                        donors: np.ndarray, poses: np.ndarray,
                        blocks: list, table: Optional[tuple] = None) -> list:
    """Vectorized splice_insert over insert mutants sharing one
    template: donor words come from a pre-rebased flat bank table
    (build_donor_table), and the template's alive segments plus the
    donor words land in a single contiguous output arena via three
    ragged flattened-index copies (before-splice words, donor words,
    after-splice words + EOF) — no per-mutant Python.  Returns
    memoryview slices of the arena aligned with the inputs; None
    where the combined copyout budget would overflow."""
    m = len(donors)
    out: list = [None] * m
    nc = et.ncalls
    W = et.words.shape[0]
    full = np.uint64((1 << nc) - 1) if nc < 64 else np.uint64(2**64 - 1)
    ab = alive_bits & full
    if nc:
        calls = np.arange(nc, dtype=np.uint64)
        alive = ((ab[:, None] >> calls[None, :]) & 1) != 0  # (m, nc)
        rank = np.cumsum(alive, axis=1) - alive  # exclusive alive rank
        n_alive = alive.sum(axis=1)
    else:
        alive = np.zeros((m, 0), bool)
        rank = np.zeros((m, 0), np.int64)
        n_alive = np.zeros(m, np.int64)
    pos = np.minimum(poses.astype(np.int64), n_alive)

    if table is None:
        table = build_donor_table(et.ncopyouts, blocks)
    dflat, doff_u, dlen_u, ok_u = table
    donors = np.asarray(donors, dtype=np.int64)
    rows_ok = np.flatnonzero(ok_u[donors])
    if rows_ok.size == 0:
        return out

    pos_o = pos[rows_ok]
    dl = dlen_u[donors[rows_ok]]
    dsrc0 = doff_u[donors[rows_ok]]
    if et.seg_tiled and bool((ab[rows_ok] == full).all()):
        # Every call alive on a tiled template: the splice is two
        # contiguous template slices around the cut word — no mask
        # arrays at all, just ragged index math.
        cut = et.insert_cut[np.minimum(pos_o, nc)]
        n_a = cut
        n_c = W - cut
        total = n_a + dl + n_c
        ends = np.cumsum(total)
        starts = ends - total
        arena = np.empty(int(ends[-1]) if len(ends) else 0, np.uint64)
        e, k = _ragged_spans(n_a)
        arena[starts[e] + k] = et.words[k]
        e, k = _ragged_spans(dl)
        arena[(starts + n_a)[e] + k] = dflat[dsrc0[e] + k]
        e, k = _ragged_spans(n_c)
        arena[(starts + n_a + dl)[e] + k] = et.words[cut[e] + k]
    else:
        alive_o = alive[rows_ok]
        rank_o = rank[rows_ok]
        wc = et.word_call
        is_call = wc >= 0
        if nc:
            cw = np.where(is_call, wc, 0)
            word_alive = alive_o[:, cw] & is_call[None, :]
            word_rank = rank_o[:, cw]
        else:
            word_alive = np.zeros((len(rows_ok), W), bool)
            word_rank = np.zeros((len(rows_ok), W), np.int64)
        in_a = word_alive & (word_rank < pos_o[:, None])
        in_c = word_alive & (word_rank >= pos_o[:, None])
        in_c[:, wc == WORD_EOF] = True  # EOF rides the tail part

        n_a = in_a.sum(axis=1, dtype=np.int64)
        n_c = in_c.sum(axis=1, dtype=np.int64)
        total = n_a + dl + n_c
        ends = np.cumsum(total)
        starts = ends - total
        arena = np.empty(int(ends[-1]) if len(ends) else 0, np.uint64)
        wb = np.broadcast_to(et.words, (len(rows_ok), W))
        e, k = _ragged_spans(n_a)
        arena[starts[e] + k] = wb[in_a]
        e, k = _ragged_spans(dl)
        arena[(starts + n_a)[e] + k] = dflat[dsrc0[e] + k]
        e, k = _ragged_spans(n_c)
        arena[(starts + n_a + dl)[e] + k] = wb[in_c]

    u8 = memoryview(arena.view(np.uint8))
    for idx, i in enumerate(rows_ok):
        out[int(i)] = u8[int(starts[idx]) * 8:int(ends[idx]) * 8]
    return out


def splice_insert_group_flat(et: ExecTemplate, alive_bits: np.ndarray,
                             donors: np.ndarray, poses: np.ndarray,
                             dtab: DonorBankTable) -> list:
    """splice_insert_group against the base-independent flat donor
    bank (ISSUE 18): donor words come straight out of DonorBankTable
    row slices and the copyout rebase happens as one ragged in-arena
    add (`arena[at] += et.ncopyouts`, the splice_batch_table trick) —
    no per-copyout-base bank re-stack (`build_donor_table`) ever
    materializes.  Bit-exact with the re-stacked path: rebasing a
    donor word before or after it lands in the arena commutes.
    Returns memoryview slices aligned with the inputs; None where the
    combined copyout budget would overflow."""
    m = len(donors)
    out: list = [None] * m
    nc = et.ncalls
    W = et.words.shape[0]
    full = np.uint64((1 << nc) - 1) if nc < 64 else np.uint64(2**64 - 1)
    ab = alive_bits & full
    if nc:
        calls = np.arange(nc, dtype=np.uint64)
        alive = ((ab[:, None] >> calls[None, :]) & 1) != 0  # (m, nc)
        rank = np.cumsum(alive, axis=1) - alive  # exclusive alive rank
        n_alive = alive.sum(axis=1)
    else:
        alive = np.zeros((m, 0), bool)
        rank = np.zeros((m, 0), np.int64)
        n_alive = np.zeros(m, np.int64)
    pos = np.minimum(poses.astype(np.int64), n_alive)

    donors = np.asarray(donors, dtype=np.int64)
    ok = et.ncopyouts + dtab.ncopyouts[donors] <= MAX_COPYOUT
    rows_ok = np.flatnonzero(ok)
    if rows_ok.size == 0:
        return out

    pos_o = pos[rows_ok]
    dm = donors[rows_ok]
    dl = dtab.w_len[dm]
    dsrc0 = dtab.w_off[dm]
    if et.seg_tiled and bool((ab[rows_ok] == full).all()):
        cut = et.insert_cut[np.minimum(pos_o, nc)]
        n_a = cut
        n_c = W - cut
        total = n_a + dl + n_c
        ends = np.cumsum(total)
        starts = ends - total
        arena = np.empty(int(ends[-1]) if len(ends) else 0, np.uint64)
        e, k = _ragged_spans(n_a)
        arena[starts[e] + k] = et.words[k]
        e, k = _ragged_spans(dl)
        arena[(starts + n_a)[e] + k] = dtab.w_flat[dsrc0[e] + k]
        e, k = _ragged_spans(n_c)
        arena[(starts + n_a + dl)[e] + k] = et.words[cut[e] + k]
    else:
        alive_o = alive[rows_ok]
        rank_o = rank[rows_ok]
        wc = et.word_call
        is_call = wc >= 0
        if nc:
            cw = np.where(is_call, wc, 0)
            word_alive = alive_o[:, cw] & is_call[None, :]
            word_rank = rank_o[:, cw]
        else:
            word_alive = np.zeros((len(rows_ok), W), bool)
            word_rank = np.zeros((len(rows_ok), W), np.int64)
        in_a = word_alive & (word_rank < pos_o[:, None])
        in_c = word_alive & (word_rank >= pos_o[:, None])
        in_c[:, wc == WORD_EOF] = True  # EOF rides the tail part

        n_a = in_a.sum(axis=1, dtype=np.int64)
        n_c = in_c.sum(axis=1, dtype=np.int64)
        total = n_a + dl + n_c
        ends = np.cumsum(total)
        starts = ends - total
        arena = np.empty(int(ends[-1]) if len(ends) else 0, np.uint64)
        wb = np.broadcast_to(et.words, (len(rows_ok), W))
        e, k = _ragged_spans(n_a)
        arena[starts[e] + k] = wb[in_a]
        e, k = _ragged_spans(dl)
        arena[(starts + n_a)[e] + k] = dtab.w_flat[dsrc0[e] + k]
        e, k = _ragged_spans(n_c)
        arena[(starts + n_a + dl)[e] + k] = wb[in_c]
    if et.ncopyouts:
        # Rebase the spliced-in copyout indices in place: positions
        # are unique per row, so the fancy add never collides.
        e, k = _ragged_spans(dtab.cw_len[dm])
        if e.size:
            at = (starts + n_a)[e] + dtab.cw_flat[dtab.cw_off[dm][e] + k]
            arena[at] += np.uint64(et.ncopyouts)

    u8 = memoryview(arena.view(np.uint8))
    for idx, i in enumerate(rows_ok):
        out[int(i)] = u8[int(starts[idx]) * 8:int(ends[idx]) * 8]
    return out


def mutant_call_ids(et: ExecTemplate, call_alive: np.ndarray) -> list[int]:
    """Template call indices surviving in the mutant, in order — maps
    the executor's call_index back to template calls."""
    return [i for i in range(et.ncalls) if call_alive[i]]


def splice_insert(et: ExecTemplate, call_alive: np.ndarray, block,
                  pos: int) -> Optional[bytes]:
    """Exec bytes for an insert-class mutant: the template's alive-call
    segments with the donor block's words spliced in after `pos` alive
    calls, donor copyout indices rebased past the template's
    (ops/insert.DonorBlock).  Returns None when the combined copyout
    budget would overflow the executor table."""
    if et.ncopyouts + block.ncopyouts > MAX_COPYOUT:
        return None
    w = et.words
    segs = [w[a:b] for (a, b), alive
            in zip(et.call_bounds, call_alive[:et.ncalls]) if alive]
    pos = min(int(pos), len(segs))
    dw = block.rebased_words(et.ncopyouts)
    parts = segs[:pos] + [dw] + segs[pos:] + [w[-1:]]  # EOF
    return np.concatenate(parts).tobytes()


def parse_stream(stream: bytes) -> list[int]:
    """Well-formedness walk of an exec stream; returns the call table
    ids in order.  Raises ValueError on malformed input.  Mirrors the
    executor's interpreter skeleton (executor/executor.cc Interp) —
    used by tests and pipeline debugging, not the hot path."""
    from syzkaller_tpu.models.encodingexec import (
        EXEC_ARG_CONST, EXEC_ARG_CSUM, EXEC_ARG_DATA, EXEC_ARG_RESULT,
        EXEC_INSTR_COPYIN, EXEC_INSTR_COPYOUT, EXEC_INSTR_EOF, words_of)

    words = words_of(stream)
    pos = 0
    calls: list[int] = []

    def next_word() -> int:
        nonlocal pos
        if pos >= len(words):
            raise ValueError("truncated stream")
        pos += 1
        return words[pos - 1]

    def parse_arg() -> None:
        nonlocal pos
        kind = next_word()
        if kind == EXEC_ARG_CONST:
            pos += 2
        elif kind == EXEC_ARG_RESULT:
            pos += 5
        elif kind == EXEC_ARG_DATA:
            lenword = next_word()
            ln, cap = lenword & 0xFFFFFFFF, lenword >> 32
            region = max(ln, cap)
            pos += (region + 7) // 8
        elif kind == EXEC_ARG_CSUM:
            pos += 2  # size, csum kind
            nchunks = next_word()
            pos += 3 * nchunks
        else:
            raise ValueError(f"bad arg kind {kind}")
        if pos > len(words):
            raise ValueError("truncated arg")

    while True:
        w = next_word()
        if w == EXEC_INSTR_EOF:
            break
        if w == EXEC_INSTR_COPYIN:
            next_word()  # addr
            parse_arg()
        elif w == EXEC_INSTR_COPYOUT:
            pos += 3
        else:
            calls.append(w & 0xFFFFFFFF)
            next_word()  # copyout idx
            nargs = next_word()
            for _ in range(nargs):
                parse_arg()
    return calls
