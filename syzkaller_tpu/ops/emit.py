"""Exec-bytes emission from mutated program tensors.

The reference re-serializes every mutant with a typed tree walk
(reference: prog/encodingexec.go:57-192).  The device pipeline instead
serializes each corpus template ONCE (with fixed-capacity data regions
and an ExecRecord of patch positions) and turns every mutant into

    memcpy(template words) + vectorized value/meta patches
    + data-region splices + alive-segment slicing

— the "serialize-to-exec is a gather" contract from SURVEY.md §7.
Call removal is a pure post-patch slice of per-call word ranges; a
dangling RESULT reference to a removed call's copyout degrades to the
arg's default value inside the executor, which is exactly the
reference's remove-call semantics for broken resource edges
(reference: prog/prog.go:428-503).

Known deliberate approximations vs the typed path (both converge on
triage, where accepted inputs are decoded and re-encoded typed):
  - only directly-linked (buf, len) pairs are kept consistent after
    data mutation (see ops/mutate._fixup_lens); struct-spanning size
    fields keep their template values,
  - data regions grown on device reuse the template's guest address
    (no reallocation on growth).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from syzkaller_tpu.models.encodingexec import (
    EXEC_BUFFER_SIZE,
    ExecRecord,
    serialize_for_exec,
)
from syzkaller_tpu.models.any_squash import call_contains_any
from syzkaller_tpu.ops.tensor import DATA, FLAGS, INT, LEN, PROC, ProgTensor

MASK64 = np.uint64(0xFFFFFFFFFFFFFFFF)
MAX_COPYOUT = 256  # executor copyout table size (executor/wire.h:53)


@dataclass
class ExecTemplate:
    """Per-corpus-program assembly metadata (host side)."""

    words: np.ndarray  # uint64[W] template stream incl. trailing EOF
    call_bounds: np.ndarray  # int32[ncalls, 2] word ranges
    ncalls: int
    ncopyouts: int  # copyout indices the template consumes (donor
    # splices rebase past these; budget: executor/wire.h kMaxCopyout)
    # Slot-aligned patch arrays (length = cfg.max_slots):
    val_word: np.ndarray  # int32[S], -1 = slot has no value word
    meta_word: np.ndarray  # int32[S]
    len_word: np.ndarray  # int32[S], DATA slots only
    data_word: np.ndarray  # int32[S] payload start word
    data_cap: np.ndarray  # int32[S]
    data_off: np.ndarray  # int32[S] arena offset (static on device)
    aux0: np.ndarray  # uint64[S]
    # PROC slots encode conditionally (reference: prog/prog.go:66-74
    # Value()): default (= 0xFF..F) serializes as plain 0 without
    # stride, concrete values as start+v with the per-proc stride and
    # the type's endianness in the meta word.  Both metas are derived
    # from the TYPE at build time — the template's meta reflects only
    # the template's value.
    proc_meta_default: np.ndarray  # uint64[S]
    proc_meta_concrete: np.ndarray  # uint64[S]
    # Pre-split masks/indices for the assembly fast path:
    value_slots: np.ndarray  # int32[k] slots patched via val_word
    proc_slots: np.ndarray  # int32[k2] PROC slots (conditional stride)
    data_slots: np.ndarray  # int32[k3] DATA slots
    is_proc: np.ndarray  # bool[S]
    calls_any: np.ndarray  # bool[ncalls]: call contains a squashed ANY
    # (consumed by the pipeline's signal_prio for undecoded mutants)


def build_exec_template(t: ProgTensor,
                        buffer_size: int = EXEC_BUFFER_SIZE) -> ExecTemplate:
    """Serialize t.template once, recording patch positions for every
    device-mutable slot."""
    rec = ExecRecord()
    caps = {id(t.slot_args[s]): int(t.cap[s])
            for s in range(len(t.slot_args)) if t.kind[s] == DATA}
    stream = serialize_for_exec(t.template, buffer_size, data_caps=caps,
                                record=rec)
    words = np.frombuffer(stream, dtype="<u8").copy()

    S = t.kind.shape[0]
    val_word = np.full(S, -1, dtype=np.int32)
    meta_word = np.full(S, -1, dtype=np.int32)
    len_word = np.full(S, -1, dtype=np.int32)
    data_word = np.full(S, -1, dtype=np.int32)
    data_cap = np.zeros(S, dtype=np.int32)
    proc_meta_default = np.zeros(S, dtype=np.uint64)
    proc_meta_concrete = np.zeros(S, dtype=np.uint64)

    for s, arg in enumerate(t.slot_args):
        k = int(t.kind[s])
        if k in (INT, FLAGS, PROC, LEN):
            vw = rec.val_word.get(id(arg))
            if vw is not None:
                val_word[s] = vw
                meta_word[s] = rec.meta_word[id(arg)]
            if k == PROC:
                typ = arg.typ
                base = (arg.size()
                        | (typ.bitfield_offset() << 16)
                        | (typ.bitfield_length() << 24))
                proc_meta_default[s] = base
                proc_meta_concrete[s] = (
                    base
                    | (int(bool(getattr(typ, "big_endian", False))) << 8)
                    | (typ.values_per_proc << 32))
        elif k == DATA:
            dw = rec.data_word.get(id(arg))
            if dw is not None:
                len_word[s], data_word[s], data_cap[s] = dw

    kinds = np.asarray(t.kind)
    value_slots = np.nonzero((val_word >= 0) & (kinds != PROC))[0] \
        .astype(np.int32)
    proc_slots = np.nonzero((val_word >= 0) & (kinds == PROC))[0] \
        .astype(np.int32)
    data_slots = np.nonzero(len_word >= 0)[0].astype(np.int32)

    target = t.template.target
    calls_any = np.array(
        [call_contains_any(target, c) for c in t.template.calls], dtype=bool)

    return ExecTemplate(
        words=words,
        call_bounds=np.array(rec.call_bounds or np.empty((0, 2)),
                             dtype=np.int32).reshape(-1, 2),
        ncalls=t.ncalls,
        ncopyouts=rec.ncopyouts,
        val_word=val_word, meta_word=meta_word,
        len_word=len_word, data_word=data_word, data_cap=data_cap,
        data_off=np.asarray(t.off, dtype=np.int32).copy(),
        aux0=np.asarray(t.aux0).copy(),
        proc_meta_default=proc_meta_default,
        proc_meta_concrete=proc_meta_concrete,
        value_slots=value_slots, proc_slots=proc_slots,
        data_slots=data_slots,
        is_proc=(kinds == PROC) & (val_word >= 0),
        calls_any=calls_any,
    )


def assemble(et: ExecTemplate, val: np.ndarray, len_: np.ndarray,
             arena: np.ndarray, call_alive: np.ndarray) -> bytes:
    """Assemble exec wire bytes for one mutant.

    val/len_/arena/call_alive are the mutated tensor rows (numpy, host).
    Patches are applied on the full template first; call removal is
    then a slice of per-call ranges, so no patch index ever shifts."""
    w = et.words.copy()

    vs = et.value_slots
    if vs.size:
        w[et.val_word[vs]] = val[vs]

    ps = et.proc_slots
    if ps.size:
        pv = val[ps]
        is_default = pv == MASK64
        w[et.val_word[ps]] = np.where(is_default, np.uint64(0),
                                      et.aux0[ps] + pv)
        w[et.meta_word[ps]] = np.where(is_default, et.proc_meta_default[ps],
                                       et.proc_meta_concrete[ps])

    u8 = w.view(np.uint8)
    for s in et.data_slots:
        ln = int(len_[s])
        cap = int(et.data_cap[s])
        ln = min(ln, cap)
        w[et.len_word[s]] = np.uint64(ln | (cap << 32))
        start = int(et.data_word[s]) * 8
        off = int(et.data_off[s])
        u8[start:start + ln] = arena[off:off + ln]
        # Zero the region tail: bit-exact with the typed serializer's
        # zero padding, and no stale template bytes on the wire.
        u8[start + ln:start + cap + (-cap) % 8] = 0

    return _slice_alive(et, w, call_alive)


def _slice_alive(et: ExecTemplate, w: np.ndarray,
                 call_alive: np.ndarray) -> bytes:
    """Drop dead calls' segments (patches were applied to the full
    template, so indices never shift) and keep the EOF word."""
    nc = et.ncalls
    if bool(call_alive[:nc].all()):
        return w.tobytes()
    parts = [w[a:b] for (a, b), alive
             in zip(et.call_bounds, call_alive[:nc]) if alive]
    parts.append(w[-1:])  # EOF
    return np.concatenate(parts).tobytes()


def assemble_delta(et: ExecTemplate, batch, j: int) -> bytes:
    """Assemble exec bytes for mutant j of a DeltaBatch
    (ops/delta.DeltaBatch): same patch rules as assemble(), applied
    only to the changed slots the delta carries.  ~O(changes) per
    mutant instead of O(slots)."""
    w = et.words.copy()
    u8 = None

    for i in range(int(batch.nvals[j])):
        s = int(batch.val_idx[j, i])
        if s < 0:
            continue
        vw = int(et.val_word[s])
        if vw < 0:
            continue
        v = batch.vals[j, i]
        if et.is_proc[s]:
            if v == MASK64:
                w[vw] = 0
                w[int(et.meta_word[s])] = et.proc_meta_default[s]
            else:
                w[vw] = et.aux0[s] + v
                w[int(et.meta_word[s])] = et.proc_meta_concrete[s]
        else:
            w[vw] = v

    for i in range(int(batch.ndata[j])):
        s = int(batch.data_slot[j, i])
        if s < 0 or int(et.len_word[s]) < 0:
            continue
        cap = int(et.data_cap[s])
        ln = min(int(batch.data_len[j, i]), cap)
        w[int(et.len_word[s])] = np.uint64(ln | (cap << 32))
        if u8 is None:
            u8 = w.view(np.uint8)
        start = int(et.data_word[s]) * 8
        po = int(batch.data_off[j, i])
        u8[start:start + ln] = batch.payload[j, po:po + ln]
        u8[start + ln:start + cap + (-cap) % 8] = 0

    alive = batch.call_alive(j, max(et.ncalls, 1))
    return _slice_alive(et, w, alive)


def assemble_batch(ets: list, batch, js: np.ndarray) -> list:
    """Assemble exec bytes for mutants `js` of a DeltaBatch in one
    vectorized numpy pass per template group (the host-side hot path:
    a Python-per-mutant loop here was 4x slower than the device kernel,
    so value patches scatter across the whole group at once).

    ets is the exec-template snapshot indexable by batch.template_idx.
    Returns a list aligned with js; entries are bytes or None (missing
    template / assembly failure)."""
    out: list = [None] * len(js)
    if len(js) == 0:
        return out
    js = np.asarray(js, dtype=np.int64)
    tidx = batch.template_idx[js]
    order = np.argsort(tidx, kind="stable")
    bounds = np.flatnonzero(np.diff(tidx[order])) + 1
    for grp in np.split(order, bounds):
        ti = int(tidx[grp[0]])
        et = ets[ti] if 0 <= ti < len(ets) else None
        if et is None:
            continue
        rows = js[grp]
        try:
            datas = _assemble_group(et, batch, rows)
        except Exception:
            # Degrade to the per-mutant path so one bad row cannot
            # sink its whole template group.
            datas = []
            for j in rows:
                try:
                    datas.append(assemble_delta(et, batch, int(j)))
                except Exception:
                    datas.append(None)
        for pos, data in zip(grp, datas):
            out[int(pos)] = data
    return out


def _assemble_group(et: ExecTemplate, batch, rows: np.ndarray) -> list:
    """Vectorized assemble_delta over mutants `rows` sharing one
    template: one (m, W) patch pass + per-row byte extraction."""
    m = len(rows)
    w = np.broadcast_to(et.words, (m, et.words.shape[0])).copy()

    # -- value patches (vectorized scatter) --
    slots = batch.val_idx[rows]  # (m, K) int16, -1 padded
    valid = slots >= 0
    s = np.where(valid, slots, 0).astype(np.int64)
    vw = et.val_word[s]  # (m, K)
    valid &= vw >= 0
    vals = batch.vals[rows]  # (m, K) uint64
    isp = et.is_proc[s]

    r, c = np.nonzero(valid & ~isp)
    if r.size:
        w[r, vw[r, c]] = vals[r, c]

    r, c = np.nonzero(valid & isp)
    if r.size:
        sv = s[r, c]
        v = vals[r, c]
        dflt = v == MASK64
        with np.errstate(over="ignore"):
            w[r, vw[r, c]] = np.where(dflt, np.uint64(0), et.aux0[sv] + v)
        w[r, et.meta_word[sv]] = np.where(
            dflt, et.proc_meta_default[sv], et.proc_meta_concrete[sv])

    # -- data patches (len words vectorized; payload spans looped — a
    # few variable-length memcpys per batch) --
    dslots = batch.data_slot[rows]  # (m, D)
    dvalid = dslots >= 0
    if dvalid.any():
        ds = np.where(dvalid, dslots, 0).astype(np.int64)
        lw = et.len_word[ds]
        dvalid &= lw >= 0
        caps = et.data_cap[ds].astype(np.int64)
        lens = np.minimum(batch.data_len[rows].astype(np.int64), caps)
        r, c = np.nonzero(dvalid)
        if r.size:
            w[r, lw[r, c]] = (lens[r, c] | (caps[r, c] << 32)) \
                .astype(np.uint64)
            u8 = w.view(np.uint8).reshape(m, -1)
            for i, j in zip(r, c):
                sl = int(ds[i, j])
                ln = int(lens[i, j])
                cap = int(caps[i, j])
                start = int(et.data_word[sl]) * 8
                po = int(batch.data_off[rows[i], j])
                u8[i, start:start + ln] = batch.payload[rows[i], po:po + ln]
                u8[i, start + ln:start + cap + (-cap) % 8] = 0

    # -- alive slicing --
    nc = et.ncalls
    full = np.uint64((1 << nc) - 1) if nc < 64 else np.uint64(2**64 - 1)
    alive_bits = batch.alive_bits[rows] & full
    datas: list = []
    for i in range(m):
        if alive_bits[i] == full:
            datas.append(w[i].tobytes())
        else:
            alive = ((alive_bits[i] >> np.arange(
                max(nc, 1), dtype=np.uint64)) & 1).astype(bool)
            datas.append(_slice_alive(et, w[i], alive))
    return datas


def mutant_call_ids(et: ExecTemplate, call_alive: np.ndarray) -> list[int]:
    """Template call indices surviving in the mutant, in order — maps
    the executor's call_index back to template calls."""
    return [i for i in range(et.ncalls) if call_alive[i]]


def splice_insert(et: ExecTemplate, call_alive: np.ndarray, block,
                  pos: int) -> Optional[bytes]:
    """Exec bytes for an insert-class mutant: the template's alive-call
    segments with the donor block's words spliced in after `pos` alive
    calls, donor copyout indices rebased past the template's
    (ops/insert.DonorBlock).  Returns None when the combined copyout
    budget would overflow the executor table."""
    if et.ncopyouts + block.ncopyouts > MAX_COPYOUT:
        return None
    w = et.words
    segs = [w[a:b] for (a, b), alive
            in zip(et.call_bounds, call_alive[:et.ncalls]) if alive]
    pos = min(int(pos), len(segs))
    dw = block.rebased_words(et.ncopyouts)
    parts = segs[:pos] + [dw] + segs[pos:] + [w[-1:]]  # EOF
    return np.concatenate(parts).tobytes()


def parse_stream(stream: bytes) -> list[int]:
    """Well-formedness walk of an exec stream; returns the call table
    ids in order.  Raises ValueError on malformed input.  Mirrors the
    executor's interpreter skeleton (executor/executor.cc Interp) —
    used by tests and pipeline debugging, not the hot path."""
    from syzkaller_tpu.models.encodingexec import (
        EXEC_ARG_CONST, EXEC_ARG_CSUM, EXEC_ARG_DATA, EXEC_ARG_RESULT,
        EXEC_INSTR_COPYIN, EXEC_INSTR_COPYOUT, EXEC_INSTR_EOF, words_of)

    words = words_of(stream)
    pos = 0
    calls: list[int] = []

    def next_word() -> int:
        nonlocal pos
        if pos >= len(words):
            raise ValueError("truncated stream")
        pos += 1
        return words[pos - 1]

    def parse_arg() -> None:
        nonlocal pos
        kind = next_word()
        if kind == EXEC_ARG_CONST:
            pos += 2
        elif kind == EXEC_ARG_RESULT:
            pos += 5
        elif kind == EXEC_ARG_DATA:
            lenword = next_word()
            ln, cap = lenword & 0xFFFFFFFF, lenword >> 32
            region = max(ln, cap)
            pos += (region + 7) // 8
        elif kind == EXEC_ARG_CSUM:
            pos += 2  # size, csum kind
            nchunks = next_word()
            pos += 3 * nchunks
        else:
            raise ValueError(f"bad arg kind {kind}")
        if pos > len(words):
            raise ValueError("truncated arg")

    while True:
        w = next_word()
        if w == EXEC_INSTR_EOF:
            break
        if w == EXEC_INSTR_COPYIN:
            next_word()  # addr
            parse_arg()
        elif w == EXEC_INSTR_COPYOUT:
            pos += 3
        else:
            calls.append(w & 0xFFFFFFFF)
            next_word()  # copyout idx
            nargs = next_word()
            for _ in range(nargs):
                parse_arg()
    return calls
