"""Batched JAX/Pallas kernels: the TPU hot loop.

Everything here operates on flat program tensors (ops/tensor.py) with
a leading batch dimension, jit/vmap-compiled, with static shapes and
lax control flow only.  64-bit integer mode is required for syscall
argument values; enable it before any tracing below.
"""

import jax

jax.config.update("jax_enable_x64", True)
