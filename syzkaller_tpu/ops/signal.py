"""Coverage-signal bitmap kernels.

The reference keeps Signal as a Go map per process and merges maps
over RPC (reference: pkg/signal/signal.go:16,73-131).  On device the
global signal is one dense uint8 plane of 2^FOLD_BITS buckets storing
(max seen priority + 1), 0 = unseen.  Edge hashes are 32-bit; they are
folded into the plane the same way the executor folds its dedup table
(reference: executor/executor.h:677-706) — xor-fold then mask.

Batched ops (all jit/vmap, static shapes):
  diff_batch   per-program novelty mask + count vs the plane
  merge        scatter-max accepted programs' edges into the plane
  to_signal    host-side conversion for corpus bookkeeping

Novelty decisions are bit-exact with the CPU Signal on folded hashes;
the fold itself trades a measurable false-negative rate for memory
(2^26 buckets = 64 MB), as the survey prescribes (SURVEY.md §7 hard
part d).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

FOLD_BITS = 26
PLANE_SIZE = 1 << FOLD_BITS

#: Region count for the coverage heat map (ISSUE 7): the plane is
#: bucketed into 256 contiguous regions of 2^18 buckets each, so the
#: occupancy histogram is a 1 KB device->host transfer that localizes
#: WHERE in edge-index space the fuzzer is finding coverage.
COVERAGE_REGIONS = 256


def fold_hash(edges):
    """xor-fold a 32-bit edge hash into FOLD_BITS."""
    edges = edges.astype(jnp.uint32)
    return ((edges ^ (edges >> jnp.uint32(FOLD_BITS)))
            & jnp.uint32(PLANE_SIZE - 1)).astype(jnp.int32)


def fold_hash_np(edges: np.ndarray) -> np.ndarray:
    """Host-side fold_hash (numpy): the same xor-fold the kernels
    use, for the triage engine's plane mirror (syzkaller_tpu/triage)
    and host-side parity checks."""
    e = np.asarray(edges).astype(np.uint32, copy=False)
    return ((e ^ (e >> np.uint32(FOLD_BITS)))
            & np.uint32(PLANE_SIZE - 1)).astype(np.int64)


def new_plane() -> jax.Array:
    return jnp.zeros(PLANE_SIZE, dtype=jnp.uint8)


@jax.jit
def diff_batch(plane, edges, nedges, prios):
    """Per-program novelty vs the plane.

    plane: uint8[PLANE]; edges: uint32[B, E]; nedges: int32[B];
    prios: uint8[B] (0..3).
    Returns (new_mask: bool[B, E], new_count: int32[B]) where new_mask
    marks edges unseen at >= prio (reference: pkg/signal/signal.go:90-102).
    """
    idx = fold_hash(edges)
    seen = plane[idx]  # uint8[B, E]
    E = edges.shape[1]
    valid = jnp.arange(E)[None, :] < nedges[:, None]
    new = (seen < (prios[:, None] + 1)) & valid
    # Dedup within each program: only one occurrence of a bucket counts
    # (a Go map write is idempotent).  Invalid lanes get unique
    # sentinels so they never steal a bucket's "first" mark.
    sentinel = PLANE_SIZE + jnp.arange(E, dtype=jnp.int32)[None, :]
    didx = jnp.where(valid, idx, sentinel)
    new = new & _unique_mask(didx)
    return new, new.sum(axis=1).astype(jnp.int32)


def _unique_mask(idx):
    """bool[B, E]: one True per distinct value per row (sort-based)."""
    order = jnp.argsort(idx, axis=1)
    sorted_idx = jnp.take_along_axis(idx, order, axis=1)
    first_sorted = jnp.concatenate(
        [jnp.ones_like(sorted_idx[:, :1], dtype=bool),
         sorted_idx[:, 1:] != sorted_idx[:, :-1]], axis=1)
    rank = jnp.argsort(order, axis=1)
    return jnp.take_along_axis(first_sorted, rank, axis=1)


@jax.jit
def novel_any(plane, edges, nedges, prios):
    """Per-program possibly-novel flag vs the plane: diff_batch's
    predicate without the within-row dedup.  A bucket counted twice
    still flags the row, so the boolean is bit-identical to
    `diff_batch(...)[1] > 0` while skipping the sort-based unique
    mask — the dominant cost of diff_batch on CPU backends (~1.3 ms
    of 1.6 ms at (64, 64)).  The triage engine's pre-filter only
    needs the flag; exact counts stay diff_batch's job."""
    idx = fold_hash(edges)
    seen = plane[idx]
    E = edges.shape[1]
    valid = jnp.arange(E)[None, :] < nedges[:, None]
    return ((seen < (prios[:, None] + 1)) & valid).any(axis=1)


def _merge_impl(plane, edges, nedges, prios, accept):
    idx = fold_hash(edges)
    valid = (jnp.arange(edges.shape[1])[None, :] < nedges[:, None]) \
        & accept[:, None]
    val = jnp.where(valid, prios[:, None] + 1, 0).astype(jnp.uint8)
    return plane.at[idx.reshape(-1)].max(val.reshape(-1))


@jax.jit
def merge(plane, edges, nedges, prios, accept):
    """Scatter accepted programs' edges into the plane at max prio.

    accept: bool[B] — only accepted programs contribute
    (reference merge semantics: pkg/signal/signal.go:117-131)."""
    return _merge_impl(plane, edges, nedges, prios, accept)


#: merge with the plane DONATED: the scatter updates the 64 MB plane
#: in place instead of copying it per call.  For owners that never
#: reuse the input buffer (the triage engine reassigns its plane on
#: every merge); mesh/test callers that read the old plane afterwards
#: must use `merge`.
merge_into = jax.jit(_merge_impl, donate_argnums=0)


#: Default size (log2 buckets) of the MUTANT dedup plane — the
#: signal-plane trick applied one stage earlier.  2^22 uint8 buckets
#: = 4 MB of HBM marks every packed delta row the device has ever
#: emitted; a repeat row (remove-call mutants collide constantly:
#: only ~calls × templates distinct outcomes exist) is dropped ON
#: DEVICE before the pool claim, so it never crosses D2H at all.
#: The fold trades a ~B/2^22 false-drop rate per batch for that 4 MB
#: — same memory/recall bargain as FOLD_BITS above.
MUTANT_PLANE_BITS_DEFAULT = 22


def resolve_mutant_plane_bits() -> int:
    """TZ_MUTANT_PLANE_BITS (envsafe) clamped to a sane plane size:
    10 bits (1 KB, tests) .. 28 bits (256 MB)."""
    from syzkaller_tpu.health.envsafe import env_int

    bits = env_int("TZ_MUTANT_PLANE_BITS", MUTANT_PLANE_BITS_DEFAULT)
    return min(max(int(bits), 10), 28)


def new_mutant_plane(bits: int = MUTANT_PLANE_BITS_DEFAULT) -> jax.Array:
    return jnp.zeros(1 << bits, dtype=jnp.uint8)


def pack_plane(arr) -> bytes:
    """Host-side codec for checkpointing a plane (signal mirror or a
    mutant plane pulled D2H): the durable checkpoint's zlib section
    format (durable/checkpoint.pack_section) — one codec everywhere,
    so a plane packed by any owner unpacks on the jax-free recovery
    path bit-for-bit."""
    from syzkaller_tpu.durable.checkpoint import pack_section

    return pack_section(arr)


def unpack_plane(blob: bytes, size: int):
    """Inverse of pack_plane: uint8[size] numpy (never a device
    array — recovery re-uploads through the owner's existing H2D
    path, not through device code here)."""
    from syzkaller_tpu.durable.checkpoint import unpack_section

    return unpack_section(blob, size)


#: Default resolution (log2 buckets) of the hub novelty digest: a
#: 2^16-bucket uint8 digest packs to a few KB per Sync while still
#: splitting the 2^26 plane 1024-ways — enough selectivity to withhold
#: most already-known programs from a sync reply (hub/state.py).
DIGEST_BITS_DEFAULT = 16


def resolve_digest_bits() -> int:
    """TZ_HUB_DIGEST_BITS (envsafe) clamped to 8..FOLD_BITS."""
    from syzkaller_tpu.health.envsafe import env_int

    bits = env_int("TZ_HUB_DIGEST_BITS", DIGEST_BITS_DEFAULT)
    return min(max(int(bits), 8), FOLD_BITS)


def digest_fold(folds, bits: int) -> np.ndarray:
    """Plane bucket index -> digest bucket index: the digest bucket is
    the TOP `bits` of the FOLD_BITS fold, so a digest built from the
    dense plane (digest_plane) and one built from a fold list
    (digest_from_folds) agree bucket-for-bucket."""
    return np.asarray(folds, dtype=np.int64) >> (FOLD_BITS - bits)


def digest_plane(plane_np: np.ndarray, bits: int) -> np.ndarray:
    """Export a uint8 occupancy digest (2^bits buckets) of a dense
    2^FOLD_BITS plane: bucket b is 1 iff any plane bucket whose fold
    index has top bits b is occupied.  Host-only numpy (one reshape +
    max reduction) — never jitted; the federation index rides the
    same plane the device merges into, at sync-sized resolution."""
    plane = np.asarray(plane_np)
    group = plane.size >> bits
    if group <= 0 or plane.size != (group << bits):
        raise ValueError(
            f"plane size {plane.size} not divisible into 2^{bits} "
            "digest buckets")
    return (plane.reshape(1 << bits, group).max(axis=1) > 0) \
        .astype(np.uint8)


def digest_from_folds(folds, bits: int) -> np.ndarray:
    """Digest from a sparse fold list (a manager's known signal as
    folded edge hashes) — the hub-client export path."""
    d = np.zeros(1 << bits, np.uint8)
    f = np.asarray(folds, dtype=np.int64)
    if f.size:
        d[digest_fold(f, bits)] = 1
    return d


def digest_covers(digest: np.ndarray, folds) -> bool:
    """True when every fold's digest bucket is already occupied — the
    program is predicted-known to the digest's owner, so the hub can
    withhold it from the sync reply.  An empty fold list is never
    covered (no signal info -> always ship); fold collisions make
    this a false-positive-prone predicate by design, trading a rare
    withheld-but-novel program for the sync bytes saved."""
    f = np.asarray(folds, dtype=np.int64)
    if f.size == 0:
        return False
    bits = int(np.asarray(digest).size).bit_length() - 1
    return bool(np.all(np.asarray(digest)[digest_fold(f, bits)] != 0))


def hash_rows(rows):
    """FNV-1a over each packed delta row's bytes: uint8[B, row_bytes]
    -> uint32[B].  Runs inside the fused step jit, so the loop over
    row bytes is a device fori_loop, not B×228 host ops."""
    h0 = jnp.full(rows.shape[:1], 0x811C9DC5, jnp.uint32)

    def body(j, h):
        return (h ^ rows[:, j].astype(jnp.uint32)) \
            * jnp.uint32(0x01000193)

    return jax.lax.fori_loop(0, rows.shape[1], body, h0)


def fold_mutant_idx(h, bits: int):
    """Fold a row hash into its mutant-plane bucket index.  Shared by
    the single-device mutant_novelty and the cov-sharded mesh step so
    bucket assignment is identical on both paths — a mesh re-shard
    rebuilt from the host mirror keeps the exact same dedup state."""
    return ((h ^ (h >> jnp.uint32(bits)))
            & jnp.uint32((1 << bits) - 1)).astype(jnp.int32)


def mutant_novelty(plane, rows):
    """Cross-batch mutant dedup vs the mutant plane: fold each row's
    FNV hash into the plane, flag rows whose bucket is unseen, mark
    the buckets.  Returns (novel: bool[B], updated plane).

    Within-batch duplicates BOTH read the pre-update plane, so both
    pass — the plane is cross-batch dedup only; exact within-batch
    dedup would cost a sort the fused step doesn't need (a same-batch
    repeat is rare and harmless, it just ships twice once)."""
    bits = int(plane.shape[0]).bit_length() - 1
    h = hash_rows(rows)
    idx = fold_mutant_idx(h, bits)
    novel = plane[idx] == 0
    return novel, plane.at[idx].set(jnp.uint8(1))


def stage_batch(edges: np.ndarray, nedges: np.ndarray,
                prios: np.ndarray):
    """The H2D edge of one padded novelty batch: upload the staged
    host buffers and return device arrays ready for novel_any /
    diff_batch / merge.  One named function so the transfer plane's
    `staging.h2d` fault seam and `triage.h2d_wait` span wrap exactly
    the upload (triage/engine._dispatch_chunk), and so the host
    staging buffers (ops/staging arenas) are free for reuse as soon
    as this returns — jax copies host literals at device_put time,
    it never aliases a mutable numpy buffer."""
    return (jnp.asarray(edges), jnp.asarray(nedges),
            jnp.asarray(prios))


@jax.jit
def plane_count(plane):
    return (plane > 0).sum()


@jax.jit
def coverage_stats(plane):
    """Flush-cadence coverage analytics (ISSUE 7): the exact plane
    occupancy popcount plus the region-bucketed occupancy histogram
    (COVERAGE_REGIONS regions over edge-index space — the heat map).
    One fused reduction where the data lives: the occupancy is the
    histogram's sum, so the plane is read once.  The plane shape is
    pinned (uint8[PLANE_SIZE]), so this compiles exactly ONCE per
    process and is invoked per flush interval, never per batch."""
    regions = (plane.reshape(COVERAGE_REGIONS, -1) > 0).sum(
        axis=1, dtype=jnp.int32)
    return regions.sum(), regions


@jax.jit
def plane_drift(plane, mirror):
    """Device-vs-host-mirror drift audit: the number of buckets where
    the device plane disagrees with the rebuild-authority mirror
    (triage/engine host mirror).  Zero by construction after every
    backlog application; a nonzero count means silent plane
    corruption (a half-open ring rebuild that resurrected stale
    device memory, a donation bug, bad HBM) and the mirror must be
    re-uploaded.  Shapes pinned — compiles once."""
    return (plane != mirror).sum(dtype=jnp.int32)


def analytics_cache_size() -> int:
    """Summed jit-cache size of this module's compile-once analytics
    kernels — the sizer the CompileObservatory watches around the
    triage analytics pass (telemetry/compiles.py), and what the
    warm-rig `assert_no_new_compiles` guards pin.  Each kernel's
    plane shape is static, so a warm process holds exactly one
    executable per kernel and this sum never moves again."""
    return (coverage_stats._cache_size() + plane_drift._cache_size()
            + plane_count._cache_size())


def to_signal(plane_np: np.ndarray):
    """Host conversion of the plane into a models Signal (folded)."""
    from syzkaller_tpu.signal import Signal

    nz = np.nonzero(plane_np)[0]
    return Signal({int(i): int(plane_np[i]) - 1 for i in nz})
