"""Device-side call insertion: donor bank + ChoiceTable sampling.

Call insertion is ~51% of the reference's mutation iterations
(reference: prog/mutation.go:73-95) and was host-only until now.  The
TPU formulation (SURVEY.md §7.5):

  * HOST, once per target: pre-generate a standalone "donor block"
    per enabled syscall — the call plus any resource-constructor
    calls createResource recursion emits (reference:
    prog/rand.go:248-321) — RELOCATED into the upper half of the
    data area so donor pointer addresses can never collide with a
    template's (templates allocate bottom-up).  Each block is
    serialized once to exec words with an ExecRecord.
  * DEVICE, per mutant: sample a context call from the template's
    alive calls, draw the donor syscall from the ChoiceTable's
    prefix-sum prio row for that context (binary search — the
    categorical sampler of prog/prio.go:198-245), and a
    biased-toward-end insert position.
  * HOST, per batch: assembly splices the donor block's words into
    the template's alive-call stream at the chosen boundary,
    rebasing the donor's copyout-index words by the template's
    copyout count so result references stay disjoint (kMaxCopyout
    budget: executor/wire.h:53).

The typed decode (triage path) re-inserts the donor's cloned typed
calls at the same boundary, so minimized/corpus programs are fully
structural again.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from syzkaller_tpu.models.analysis import analyze
from syzkaller_tpu.models.encodingexec import ExecRecord, serialize_for_exec
from syzkaller_tpu.models.generation import generate_particular_call
from syzkaller_tpu.models.any_squash import call_contains_any
from syzkaller_tpu.models.prog import (
    Call,
    PointerArg,
    Prog,
    foreach_arg,
)
from syzkaller_tpu.models.rand import RandGen


@dataclass
class DonorBlock:
    """One pre-generated, relocated, pre-serialized insertion unit."""

    syscall_id: int
    calls: list[Call]  # typed form (relocated); cloned on use
    words: np.ndarray  # uint64 exec words of the block, NO EOF
    copyout_words: np.ndarray  # int32 word idxs holding copyout indices
    ncopyouts: int
    call_ids: list[int]  # meta ids, in order
    calls_any: list[bool]  # squashed-ANY flag per call

    def rebased_words(self, base_copyouts: int) -> np.ndarray:
        w = self.words.copy()
        if self.copyout_words.size and base_copyouts:
            w[self.copyout_words] += np.uint64(base_copyouts)
        return w


def _relocate(calls: list[Call], offset: int) -> None:
    """Shift every pointer/vma address into the donor half of the data
    area (addresses are data-area offsets; target.physical_addr adds
    the base)."""
    for c in calls:
        def shift(arg, ctx) -> None:
            if isinstance(arg, PointerArg) and not arg.is_null():
                arg.address += offset

        foreach_arg(c, shift)


class DonorBank:
    """Per-target bank of donor blocks, one per constructible syscall,
    plus the device-side sampling tables."""

    def __init__(self, target, ct=None, seed: int = 0,
                 max_block_calls: int = 3):
        self.target = target
        self.blocks: list[DonorBlock] = []
        # syscall id -> bank index (-1: not constructible standalone)
        nid = max((c.id for c in target.syscalls), default=0) + 1
        self.by_syscall = np.full(nid, -1, dtype=np.int32)
        rng = RandGen(target, seed ^ 0xD0)
        half = (target.num_pages // 2) * target.page_size
        metas = ct.enabled_calls if ct is not None else target.syscalls
        for meta in metas:
            try:
                s = analyze(ct, Prog(target=target, calls=[]), None)
                calls = generate_particular_call(rng, s, meta)
            except Exception:
                continue
            if not calls or len(calls) > max_block_calls:
                continue
            _relocate(calls, half)
            block = Prog(target=target, calls=calls)
            rec = ExecRecord()
            try:
                stream = serialize_for_exec(block, record=rec)
            except Exception:
                continue
            words = np.frombuffer(stream, dtype="<u8")[:-1].copy()  # no EOF
            self.by_syscall[meta.id] = len(self.blocks)
            self.blocks.append(DonorBlock(
                syscall_id=meta.id,
                calls=calls,
                words=words,
                copyout_words=np.array(rec.copyout_words, dtype=np.int32),
                ncopyouts=rec.ncopyouts,
                call_ids=[c.meta.id for c in calls],
                calls_any=[call_contains_any(target, c) for c in calls],
            ))

    def __len__(self) -> int:
        return len(self.blocks)


def choice_table_rows(target, ct) -> np.ndarray:
    """Lower the ChoiceTable to a device array: runs[nid, nid] is the
    prefix-sum priority row per context call id (uniform ramp where
    the table has no row).  Sampling = binary search of a uniform draw
    in runs[ctx] (reference: prog/prio.go:230-245)."""
    nid = max((c.id for c in target.syscalls), default=0) + 1
    runs = np.zeros((nid, nid), dtype=np.uint32)
    uniform = np.cumsum(np.ones(nid, dtype=np.uint32))
    for cid in range(nid):
        row = ct.run[cid] if ct is not None and cid < len(ct.run) else None
        if row is None:
            runs[cid] = uniform
        else:
            r = np.asarray(row, dtype=np.uint32)
            if r.shape[0] < nid:
                r = np.pad(r, (0, nid - r.shape[0]), mode="edge")
            runs[cid] = r if r[-1] > 0 else uniform
    return runs
