"""Batched comparison-hint engine on device (SURVEY.md §7.7).

shrinkExpand (reference: prog/hints.go:164-218) is branchy but
fixed-structure: 13 cast variants (widths 8/4/2/1 truncated, 4/2/1
sign-extended, each little/big endian, minus the no-op 1-byte swap)
per candidate value.  The CPU path walks them per arg byte-window; on
device the whole call's candidate windows run as ONE vmap over a
[B] value vector against the CompMap lowered to a sorted key array +
padded value matrix (binary search via jnp.searchsorted).

Parity contract: for every value, the (deduped, sorted) replacer set
equals models.hints.shrink_expand exactly — tests/test_hints_device.py
drives both on random CompMaps, and mutate_with_hints_device must
yield byte-identical mutant programs in the same order as the CPU
mutate_with_hints.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from syzkaller_tpu import telemetry
from syzkaller_tpu.health.envsafe import env_int
from syzkaller_tpu.models.hints import MAX_DATA_LENGTH, CompMap
from syzkaller_tpu.models.rand import SPECIAL_INTS_SET
from syzkaller_tpu.models.prog import Arg, ConstArg, DataArg, Prog, foreach_arg
from syzkaller_tpu.models.types import CsumType, Dir, ProcType
from syzkaller_tpu.utils.ints import MASK64 as MASK64_INT
from syzkaller_tpu.utils.ints import load_int, store_int

# Cast variants (width_bytes, sign_extend, big_endian), mirroring the
# reference iteration order (prog/hints.go:173-186): positive widths
# truncate, negative (here sign_extend=True) OR-in the high bits.
VARIANTS: tuple[tuple[int, bool, bool], ...] = tuple(
    (abs(w), w < 0, be)
    for w in (8, 4, 2, 1, -4, -2, -1)
    for be in (False, True)
    if not (abs(w) == 1 and be))

_SPECIAL_SORTED = np.array(sorted(SPECIAL_INTS_SET), dtype=np.uint64)

#: Sorted-key padding: searchsorted stays sound over a padded row
#: because the pad compares >= every real key, and any hit in the pad
#: region is rejected by the `i < nkeys` validity guard.
UINT64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)


# Observability: how often real TRACE_CMP data overflows the per-key
# operand budget (drives the vmax choice; VERDICT r3 item #9).
FALLBACK_STATS = {"maps": 0, "keys": 0, "overflow_keys": 0}

#: Comparands routed OFF the device arrays by the vmax/kmax budgets
#: (ISSUE 19 satellite: the old silent-truncation surface, now
#: counted).  These operands are not lost — they take the exact CPU
#: shrink_expand supplement — but every increment is device batching
#: the budget refused, so a climbing rate says "raise TZ_HINTS_VMAX".
_M_COMPS_DROPPED = telemetry.counter(
    "tz_hints_comps_dropped_total",
    "comparison operands over the vmax/kmax device budget, routed to "
    "the exact CPU supplement instead of the batched kernel")


def resolve_hints_vmax() -> int:
    """TZ_HINTS_VMAX with the repo's clamp discipline: the per-key
    operand budget of the device comp-map tables (docs/health.md).
    Bounded to [1, 1024] so a typo cannot allocate a table whose vmax
    dimension dwarfs the comparison data it carries."""
    return min(1024, max(1, env_int("TZ_HINTS_VMAX", 16)))


class DeviceCompMap:
    """A CompMap lowered to device arrays: sorted uint64 keys + a
    [n, vmax] padded operand matrix (CSR with fixed row width).

    Keys whose operand set overflows vmax are NOT silently truncated:
    they are split out into `overflow` (a CompMap holding only those
    keys) which callers supplement with the exact CPU shrink_expand —
    so one hot comparison key no longer degrades the whole call to
    the CPU path."""

    def __init__(self, keys: np.ndarray, vals: np.ndarray,
                 nvals: np.ndarray, overflow_operands: int,
                 overflow: Optional[CompMap] = None):
        self.keys = keys
        self.vals = vals
        self.nvals = nvals
        # operands living in overflow keys — purely informational:
        # exactness is preserved (those keys take the CPU supplement)
        self.overflow_operands = overflow_operands
        self.overflow = overflow  # None = no overflowing keys

    @classmethod
    def from_comp_map(cls, cm: CompMap, vmax: Optional[int] = None,
                      kmax: Optional[int] = None) -> "DeviceCompMap":
        """Lower a CompMap to device arrays.  `vmax` defaults to the
        TZ_HINTS_VMAX knob (resolve_hints_vmax); `kmax`, when given,
        additionally routes keys past the per-map key budget into the
        overflow CompMap (the stacked lane tables have a fixed K
        dimension).  Every operand either side of the budget split is
        counted — off-device routing increments
        tz_hints_comps_dropped_total — and none is lost: overflow
        keys take the exact CPU shrink_expand supplement."""
        if vmax is None:
            vmax = resolve_hints_vmax()
        all_keys = sorted(cm.m.keys())
        dev_keys = []
        overflow: Optional[CompMap] = None
        overflow_operands = 0
        for k in all_keys:
            if len(cm.m[k]) > vmax or \
                    (kmax is not None and len(dev_keys) >= kmax):
                if overflow is None:
                    overflow = CompMap()
                overflow.m[k] = set(cm.m[k])
                overflow_operands += len(cm.m[k])
            else:
                dev_keys.append(k)
        FALLBACK_STATS["maps"] += 1
        FALLBACK_STATS["keys"] += len(all_keys)
        FALLBACK_STATS["overflow_keys"] += \
            0 if overflow is None else len(overflow.m)
        if overflow_operands:
            _M_COMPS_DROPPED.inc(overflow_operands)
        keys = np.array(dev_keys, dtype=np.uint64)
        n = len(keys)
        vals = np.zeros((max(n, 1), vmax), dtype=np.uint64)
        nvals = np.zeros(max(n, 1), dtype=np.int32)
        for i, k in enumerate(dev_keys):
            vs = sorted(cm.m[int(k)])
            vals[i, :len(vs)] = vs
            nvals[i] = len(vs)
        return cls(keys, vals, nvals, overflow_operands, overflow)

    def __len__(self) -> int:
        return len(self.keys)


def _swap_const(v, width: int):
    """Byte-swap the low `width` (static) bytes of a uint64."""
    import jax.numpy as jnp

    U64 = jnp.uint64
    if width == 1:
        return v & U64(0xFF)
    out = U64(0)
    for i in range(width):
        byte = (v >> U64(8 * (width - 1 - i))) & U64(0xFF)
        out = out | (byte << U64(8 * i))
    return out


def make_shrink_expand(dmap: DeviceCompMap):
    """Build the jitted batched kernel:
    vals[B] -> (replacers[B, NV, vmax], valid[B, NV, vmax])
    where NV = len(VARIANTS)."""
    import jax
    import jax.numpy as jnp

    U64 = jnp.uint64
    MASK64 = U64(0xFFFFFFFFFFFFFFFF)
    keys = jnp.asarray(dmap.keys)
    vmat = jnp.asarray(dmap.vals)
    nvals = jnp.asarray(dmap.nvals)
    special = jnp.asarray(_SPECIAL_SORTED)
    n = len(dmap.keys)
    vmax = dmap.vals.shape[1]

    def is_special(x):
        i = jnp.searchsorted(special, x)
        i = jnp.minimum(i, len(_SPECIAL_SORTED) - 1)
        return special[i] == x

    def one(v):
        reps = []
        oks = []
        for width, sext, be in VARIANTS:
            size = width * 8
            mask = U64((1 << size) - 1) if size < 64 else MASK64
            inv = (~mask) & MASK64
            if sext:
                mutant = (v | inv) & MASK64
            else:
                mutant = v & mask
            if be:
                mutant = _swap_const(mutant, width)
            if n == 0:
                reps.append(jnp.zeros(vmax, U64))
                oks.append(jnp.zeros(vmax, jnp.bool_))
                continue
            i = jnp.minimum(jnp.searchsorted(keys, mutant), n - 1)
            found = keys[i] == mutant
            row = vmat[i]
            row_ok = (jnp.arange(vmax) < nvals[i]) & found
            new_hi = row & inv
            # The other operand wider than the cast value is dead code
            # unless it is the sign extension (hints.go:199-204).
            ok_hi = (new_hi == U64(0)) | (new_hi == inv)
            nv = row & mask
            if be:
                nv = jax.vmap(lambda x: _swap_const(x, width))(nv)
            ok = row_ok & ok_hi & ~jax.vmap(is_special)(nv)
            reps.append(((v & inv) | nv) & MASK64)
            oks.append(ok)
        return jnp.stack(reps), jnp.stack(oks)

    return jax.jit(jax.vmap(one))


def shrink_expand_batch(vals: np.ndarray,
                        dmap: DeviceCompMap) -> list[list[int]]:
    """Batched shrink_expand: one device call for all candidate
    values; returns per-value sorted deduped replacer lists (the same
    sets models.hints.shrink_expand yields)."""
    if len(vals) == 0:
        return []
    kernel = make_shrink_expand(dmap)
    import jax.numpy as jnp

    reps, oks = kernel(jnp.asarray(vals.astype(np.uint64)))
    reps = np.asarray(reps).reshape(len(vals), -1)
    oks = np.asarray(oks).reshape(len(vals), -1)
    out = []
    for j in range(len(vals)):
        out.append(sorted(set(reps[j][oks[j]].tolist())))
    return out


# -- stacked multi-map tables (ISSUE 19: the fused hint lane) -----------

def stack_comp_maps(dmaps: list[DeviceCompMap], m_rows: int,
                    k_cols: int, out: Optional[dict] = None) -> dict:
    """Stack several programs' DeviceCompMaps into one padded device
    table set: keys[M, K] (pad UINT64_MAX so per-row searchsorted
    order survives), nkeys[M], vmat[M, K, V], nvals[M, K].  `out`
    buffers (StagingArena slots) are written in place; only the rows
    actually used are touched beyond the key-row pad — the kernel's
    nkeys/nvals validity guards mask everything else, so stale arena
    bytes in unused map rows are harmless."""
    if not dmaps:
        raise ValueError("stack_comp_maps needs at least one map")
    vmax = dmaps[0].vals.shape[1]
    if out is None:
        out = {
            "keys": np.empty((m_rows, k_cols), dtype=np.uint64),
            "nkeys": np.zeros(m_rows, dtype=np.int32),
            "vmat": np.zeros((m_rows, k_cols, vmax), dtype=np.uint64),
            "nvals": np.zeros((m_rows, k_cols), dtype=np.int32),
        }
    nkeys = out["nkeys"]
    for i, d in enumerate(dmaps):
        if d.vals.shape[1] != vmax:
            raise ValueError("stacked maps must share vmax")
        nk = len(d)
        out["keys"][i, :nk] = d.keys
        out["keys"][i, nk:] = UINT64_MAX  # keep the row sorted
        nkeys[i] = nk
        out["vmat"][i, :nk] = d.vals[:nk]
        out["nvals"][i, :nk] = d.nvals[:nk]
    nkeys[len(dmaps):] = 0  # unused rows: every lookup misses
    return out


_STACKED_KERNEL = None


def stacked_shrink_expand_kernel():
    """The fused hint kernel, built ONCE per process (module-level
    jit: distinct (B, M, K, V) pow2 buckets each compile exactly one
    executable, and same-bucket flushes re-hit the cache — unlike
    make_shrink_expand, which closes over one map's arrays and
    recompiles per map):

        (vals[B], map_of[B], keys[M,K], nkeys[M],
         vmat[M,K,V], nvals[M,K]) -> (reps[B,NV,V], oks[B,NV,V])

    Row b expands value vals[b] against map map_of[b]'s tables —
    thousands of (prog, call, comparand) sites in one device batch."""
    global _STACKED_KERNEL
    if _STACKED_KERNEL is None:
        import jax
        import jax.numpy as jnp

        U64 = jnp.uint64
        MASK64 = U64(0xFFFFFFFFFFFFFFFF)
        special = jnp.asarray(_SPECIAL_SORTED)

        def is_special(x):
            i = jnp.searchsorted(special, x)
            i = jnp.minimum(i, len(_SPECIAL_SORTED) - 1)
            return special[i] == x

        def one(v, m, keys, nkeys, vmat, nvals):
            M, K = keys.shape
            V = vmat.shape[2]
            m = jnp.clip(m, 0, M - 1)  # padded rows point at map 0
            krow = keys[m]
            nk = nkeys[m]
            reps = []
            oks = []
            for width, sext, be in VARIANTS:
                size = width * 8
                mask = U64((1 << size) - 1) if size < 64 else MASK64
                inv = (~mask) & MASK64
                if sext:
                    mutant = (v | inv) & MASK64
                else:
                    mutant = v & mask
                if be:
                    mutant = _swap_const(mutant, width)
                i = jnp.minimum(jnp.searchsorted(krow, mutant), K - 1)
                found = (krow[i] == mutant) & (i < nk)
                row = vmat[m, i]
                row_ok = (jnp.arange(V) < nvals[m, i]) & found
                new_hi = row & inv
                ok_hi = (new_hi == U64(0)) | (new_hi == inv)
                nv = row & mask
                if be:
                    nv = jax.vmap(lambda x: _swap_const(x, width))(nv)
                ok = row_ok & ok_hi & ~jax.vmap(is_special)(nv)
                reps.append(((v & inv) | nv) & MASK64)
                oks.append(ok)
            return jnp.stack(reps), jnp.stack(oks)

        _STACKED_KERNEL = jax.jit(
            jax.vmap(one, in_axes=(0, 0, None, None, None, None)))
    return _STACKED_KERNEL


def shrink_expand_batch_stacked(vals: np.ndarray, map_of: np.ndarray,
                                tables: dict) -> list[list[int]]:
    """Fleet-batched shrink_expand: per-value sorted deduped replacer
    lists, each value expanded against its own map (tables from
    stack_comp_maps).  Per map, the result equals shrink_expand_batch
    — and therefore models.hints.shrink_expand — exactly."""
    if len(vals) == 0:
        return []
    import jax.numpy as jnp

    kernel = stacked_shrink_expand_kernel()
    reps, oks = kernel(
        jnp.asarray(vals.astype(np.uint64)),
        jnp.asarray(map_of.astype(np.int32)),
        jnp.asarray(tables["keys"]), jnp.asarray(tables["nkeys"]),
        jnp.asarray(tables["vmat"]), jnp.asarray(tables["nvals"]))
    reps = np.asarray(reps).reshape(len(vals), -1)
    oks = np.asarray(oks).reshape(len(vals), -1)
    out = []
    for j in range(len(vals)):
        out.append(sorted(set(reps[j][oks[j]].tolist())))
    return out


# -- the two host passes, shared by the per-program and lane paths ------

def collect_hint_jobs(p: Prog, call_index: int
                      ) -> tuple[Prog, list[tuple[Arg, int, int]],
                                 list[int]]:
    """Pass 1: clone the program and collect every candidate window
    of the call in traversal order (reference: prog/hints.go:82-103).
    Returns (clone, jobs, vals); jobs are (arg, window_off, window)
    with window_off = -1 marking a ConstArg."""
    p = p.clone()
    c = p.calls[call_index]
    jobs: list[tuple[Arg, int, int]] = []
    vals: list[int] = []

    def collect(arg: Arg, ctx) -> None:
        typ = arg.typ
        if typ is None or typ.dir == Dir.OUT:
            return
        if isinstance(typ, (ProcType, CsumType)):
            return
        if isinstance(arg, ConstArg):
            jobs.append((arg, -1, 0))
            vals.append(arg.val & MASK64_INT)
        elif isinstance(arg, DataArg):
            data = arg.data
            size = min(len(data), MAX_DATA_LENGTH)
            for i in range(size):
                window = min(8, len(data) - i)
                buf = bytes(data[i:i + 8]).ljust(8, b"\x00")
                jobs.append((arg, i, window))
                vals.append(load_int(buf, 0, 8))

    foreach_arg(c, collect)
    return p, jobs, vals


def apply_hint_mutants(p: Prog, jobs: list[tuple[Arg, int, int]],
                       replacer_lists: list[list[int]],
                       exec_cb: Callable[[Prog], None]) -> int:
    """Pass 2: apply each window's replacers in CPU order — one exec
    per replacer, original bytes restored after each window
    (reference: prog/hints.go:66-132).  Returns mutants executed."""
    from syzkaller_tpu.models import validation

    n = 0

    def run() -> None:
        if validation.debug:
            validation.validate_prog(p)
        exec_cb(p)

    for (arg, off, window), replacers in zip(jobs, replacer_lists):
        if isinstance(arg, ConstArg):
            original = arg.val
            for r in replacers:
                arg.val = r
                run()
                n += 1
            arg.val = original
        else:
            data = arg.data
            original = bytes(data[off:off + 8]).ljust(8, b"\x00")
            for r in replacers:
                store_int(data, off, r, window)
                run()
                n += 1
            data[off:off + window] = original[:window]
    return n


def mutate_with_hints_device(p: Prog, call_index: int, comps: CompMap,
                             exec_cb: Callable[[Prog], None],
                             vmax: Optional[int] = None) -> None:
    """Device-batched equivalent of models.hints.mutate_with_hints:
    collect every candidate window of the call into one value vector,
    run shrink_expand as one vmap'd kernel, then apply replacements in
    the CPU path's exact order (reference: prog/hints.go:66-132).

    Per-key exactness: keys whose operand sets overflow the device
    budget are supplemented by the CPU shrink_expand for those keys
    only — the rest of the map stays on device, and the merged
    replacer set equals the full CPU result exactly."""
    dmap = DeviceCompMap.from_comp_map(comps, vmax=vmax)

    p, jobs, vals = collect_hint_jobs(p, call_index)
    if not jobs:
        return

    replacer_lists = shrink_expand_batch(np.array(vals, dtype=np.uint64),
                                         dmap)
    if dmap.overflow is not None:
        # Exact CPU supplement for the overflowing keys only; the
        # union over the key partition equals the full-map result.
        from syzkaller_tpu.models.hints import shrink_expand

        replacer_lists = [
            sorted(set(lst) | shrink_expand(v, dmap.overflow))
            for lst, v in zip(replacer_lists, vals)]

    apply_hint_mutants(p, jobs, replacer_lists, exec_cb)
