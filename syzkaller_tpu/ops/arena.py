"""Device-resident HBM corpus arena (ISSUE 18).

The fused drain (ISSUE 9/10/14/15) mutates, sim-executes, and triages
on device, but until this module every batch still *started* on host:
a uniform host-side corpus pick plus an H2D corpus-flush scatter.  The
arena closes that loop — the serialized exec-word corpus lives in
pow2-slab device buffers, the per-batch template pick is a weighted
cumulative-weight search ON DEVICE, and the host keeps only the
durable authority copy:

  - SLABS: one device array per ProgTensor field (the same
    val/len/arena/flag layout `DevicePipeline._corpus_dev` held),
    capacity-padded to whole 2^TZ_ARENA_SLAB_BITS-row slabs and sized
    against the HBM ledger's headroom (`slab_capacity`; the ledger
    registers them under owner="arena" so the residency rollup and
    the reconcile sweep see them),
  - SAMPLING: `pick_rows` draws B uint32 words from the SAME threefry
    substream the host sampler used and searches the cumulative
    weight vector: with unit weights `searchsorted(cumw, u % total,
    'right')` degenerates EXACTLY to the legacy `bits % n` pick, so
    turning the arena on does not move a single sample — weighting is
    free on top (`pick_rows_host` is the bit-exact numpy oracle the
    parity tests run),
  - EPOCHS: every device-state invalidation (breaker re-entry, mesh
    re-shard, checkpoint restore) bumps `epoch` and marks every
    occupied row pending — the next flush is ONE scatter from host
    authority through the shared StagingArena slot rotation (same
    ("corpus", bucket) keys as the pre-arena path, so the PR 5
    allocation pins stay flat), zero new jits,
  - DISTILLATION: a batched `Minimize`-style lane (reference:
    prog/minimization.go, pkg/signal.Minimize) proposes suffix
    truncations per row, sim-executes original + candidates as one
    fused batch (sim/kernel.sim_exec_batch), and keeps the shortest
    candidate whose predicted edge folds cover the original's —
    the host oracle (`distill_verdicts_host`) reruns the bisection
    through sim_exec_host + digest_covers at full FOLD_BITS
    resolution, where digest bucket == fold, so device and host
    verdicts are provably identical.

docs/perf.md "The corpus arena" covers the slab layout, the sampling
kernel, the distillation cost model, and the headroom sizing rule;
docs/observability.md catalogues the tz_arena_* series.
"""

from __future__ import annotations

import threading
import zlib

import numpy as np

from syzkaller_tpu import telemetry
from syzkaller_tpu.health import env_int, fault_point
from syzkaller_tpu.ops.delta import pow2_rows

_M_ROWS = telemetry.gauge(
    "tz_arena_rows", "occupied corpus rows in the device arena")
_M_CAPACITY = telemetry.gauge(
    "tz_arena_capacity_rows", "device slab capacity in rows")
_M_EPOCH = telemetry.gauge(
    "tz_arena_epoch", "arena epoch (bumped per device invalidation)")
_M_SLAB_BYTES = telemetry.gauge(
    "tz_arena_slab_bytes", "resident device slab bytes")
_M_UPLOADS = telemetry.counter(
    "tz_arena_uploads_total",
    "authority re-upload scatters into the device slabs")
_M_UPLOAD_BYTES = telemetry.counter(
    "tz_arena_upload_bytes_total",
    "H2D corpus row bytes staged by those scatters")
_M_RETIRED = telemetry.counter(
    "tz_arena_retired_rows_total",
    "arena rows superseded by a distilled truncation")
_M_DISTILL_ROUNDS = telemetry.counter(
    "tz_arena_distill_rounds_total",
    "fused distillation bisection batches run")
_M_DISTILL_CANDS = telemetry.counter(
    "tz_arena_distill_candidates_total",
    "candidate truncations sim-executed by the distill lane")
_M_HEAT_FOLDS = telemetry.counter(
    "tz_arena_heat_folds_total",
    "device heat vectors folded into the sampling weights")

#: Sentinel for invalid edge folds in the device cover check: real
#: folds are < 2^FOLD_BITS (26), so the max uint32 never collides.
_FOLD_SENTINEL = np.uint32(0xFFFFFFFF)


def resolve_arena_device() -> bool:
    """TZ_ARENA_DEVICE kill switch: 0 pins unit sampling weights and
    disables the distill lane, reproducing the pre-arena host-staged
    behavior bit for bit (the slabs still hold the corpus — only the
    weighted pick and the on-device retirement are switched off)."""
    return env_int("TZ_ARENA_DEVICE", 1) != 0


def resolve_slab_bits() -> int:
    """TZ_ARENA_SLAB_BITS with the plane-knob clamp discipline
    (ops/signal.resolve_mutant_plane_bits): 2^10 = 1024-row slabs by
    default, bounded to [4, 20] so a typo cannot demand a 2^31-row
    allocation."""
    bits = env_int("TZ_ARENA_SLAB_BITS", 10)
    return min(20, max(4, bits))


def resolve_distill_every() -> int:
    """TZ_ARENA_DISTILL_EVERY: distill-lane cadence in drained
    batches; 0 (default) keeps the lane off — distillation is opt-in
    because it spends device time on corpus hygiene, not mutants."""
    return max(0, env_int("TZ_ARENA_DISTILL_EVERY", 0))


def resolve_distill_rows() -> int:
    """TZ_ARENA_DISTILL_ROWS: rows bisected per distill round,
    clamped to [1, 128] — the round's device batch is rows x
    candidates, and the compile shape is pinned by this value."""
    return min(128, max(1, env_int("TZ_ARENA_DISTILL_ROWS", 8)))


def slab_capacity(requested: int, row_bytes: int,
                  headroom_bytes: int | None = None,
                  slab_bits: int | None = None) -> int:
    """Device slab capacity for a `requested`-row ring: rounded UP to
    whole 2^slab_bits-row slabs (growth inside a slab never reallocs,
    so the jitted step's corpus shapes are fixed at construction),
    then trimmed back toward the request when the slack alone would
    eat more than a quarter of the ledger's current headroom
    (`tz_hbm_headroom_bytes` — the PR 16 forecast input this rule was
    built for).  Never below `requested`: the ring needs its slots,
    and the breaker path would rather demote than under-allocate."""
    if slab_bits is None:
        slab_bits = resolve_slab_bits()
    slab = 1 << slab_bits
    cap = ((max(1, requested) + slab - 1) // slab) * slab
    if headroom_bytes is None:
        headroom_bytes = telemetry.HBM.headroom()
    budget = max(0, int(headroom_bytes)) // 4
    while cap - slab >= requested \
            and (cap - requested) * max(1, row_bytes) > budget:
        cap -= slab
    return cap


def cumw_from_weights(weights: np.ndarray, n: int,
                      capacity: int) -> tuple[np.ndarray, int]:
    """(cumulative weight vector uint32[capacity], total): occupied
    rows [0, n) contribute their weights, the tail repeats the total
    so a searchsorted past the corpus never lands there.  Totals are
    bounded by n * max-weight << 2^32 (weights are small ints)."""
    w = np.zeros(capacity, np.uint64)
    w[:n] = weights[:n]
    cw = np.cumsum(w)
    total = int(cw[-1]) if capacity else 0
    return cw.astype(np.uint32), total


def pick_rows(cumw, total, bits_u32):
    """The on-device weighted pick: u = bits mod total, then the
    first row whose cumulative weight exceeds u.  With unit weights
    cumw is [1, 2, .., n, n, ..] and total == n, so idx == u — the
    exact legacy `bits % max(n, 1)` stream.  Traceable (called inside
    the jitted step); `bits_u32` is the raw threefry draw."""
    import jax.numpy as jnp

    u = bits_u32 % jnp.maximum(total, 1).astype(jnp.uint32)
    idx = jnp.searchsorted(cumw, u, side="right")
    return jnp.clip(idx, 0, cumw.shape[0] - 1).astype(jnp.int32)


def pick_rows_host(cumw: np.ndarray, total: int,
                   bits_u32: np.ndarray) -> np.ndarray:
    """Numpy oracle for pick_rows on the same uint32 draws — the
    randomized parity tests run both on seeded streams and require
    bit equality."""
    u = (np.asarray(bits_u32, np.uint32) % np.uint32(max(total, 1)))
    idx = np.searchsorted(np.asarray(cumw, np.uint32), u, side="right")
    return np.clip(idx, 0, len(cumw) - 1).astype(np.int32)


class CorpusArena:
    """Epoch-versioned device corpus slabs + host authority.

    Single device-writer contract: `stage`/`retire_row`/`set_weight`
    may run from any thread (guarded by the arena lock); `flush`,
    `invalidate`, and `fold_heat` run from the owning pipeline's
    worker thread, same as the rest of its device attributes."""

    def __init__(self, capacity: int, staging=None,
                 slab_bits: int | None = None,
                 headroom_bytes: int | None = None):
        from syzkaller_tpu.ops.staging import StagingArena

        self.ring_capacity = capacity
        self.slab_bits = resolve_slab_bits() if slab_bits is None \
            else slab_bits
        self.device_enabled = resolve_arena_device()
        self._headroom_hint = headroom_bytes
        self.capacity = 0  # resolved at first stage (row bytes known)
        self.host: dict[str, np.ndarray] | None = None
        self.weights: np.ndarray | None = None
        self.n = 0
        self.epoch = 0
        self.uploads = 0
        self.upload_bytes = 0
        self.retired = 0
        self.heat_folds = 0
        self._lock = threading.Lock()
        self._pending: dict[int, int] = {}  # slot -> staleness tick
        self._tick = 0
        self._dev: dict | None = None
        self._cumw_dev = None
        self._total = 0
        self._weights_dirty = True
        self._staging = staging if staging is not None \
            else StagingArena(slots=2)
        self._hbm_slabs = telemetry.HBM.register(
            "arena", "slabs", bound_to=self)
        self._hbm_cumw = telemetry.HBM.register(
            "arena", "cumw", bound_to=self)

    # -- host authority ----------------------------------------------------

    def _ensure_host(self, proto: dict) -> None:
        if self.host is not None:
            return
        row_bytes = int(sum(np.asarray(v).nbytes
                            for v in proto.values()))
        self.capacity = slab_capacity(
            self.ring_capacity, row_bytes,
            headroom_bytes=self._headroom_hint,
            slab_bits=self.slab_bits)
        self.host = {
            k: np.zeros((self.capacity,) + np.shape(v),
                        dtype=np.asarray(v).dtype)
            for k, v in proto.items()}
        self.weights = np.zeros(self.capacity, np.uint32)
        _M_CAPACITY.set(self.capacity)

    def stage(self, i: int, arrays: dict, weight: int = 1) -> None:
        """Copy one row into host authority and mark it pending for
        the next flush.  `weight` seeds the sampling weight (unit by
        default — the bit-exact legacy stream)."""
        with self._lock:
            self._ensure_host(arrays)
            for k, v in arrays.items():
                self.host[k][i] = v
            self.weights[i] = weight
            self._tick += 1
            self._pending[i] = self._tick
            self.n = max(self.n, i + 1)
            self._weights_dirty = True
        _M_ROWS.set(self.n)

    def set_weight(self, i: int, weight: int) -> None:
        with self._lock:
            if self.weights is None or not 0 <= i < self.capacity:
                return
            self.weights[i] = weight
            self._weights_dirty = True

    def fold_heat(self, heat: np.ndarray, cap: int = 7) -> None:
        """Fold a device-observed heat vector (per-row admitted-mutant
        counts the prescored step scatter-adds on device) into the
        sampling weights: weight = 1 + min(heat, cap).  This is the
        sim-feedback loop — novelty yield observed ON DEVICE biases
        the next epoch's picks without any per-batch host traffic
        (the heat rides the step's outputs; this fold runs at distill
        cadence, not per batch)."""
        if not self.device_enabled:
            return
        with self._lock:
            if self.weights is None:
                return
            h = np.asarray(heat[:self.n], np.uint32)
            occupied = self.weights[:self.n] > 0
            self.weights[:self.n] = np.where(
                occupied, 1 + np.minimum(h, cap), 0)
            self._weights_dirty = True
            self.heat_folds += 1
        _M_HEAT_FOLDS.inc()

    # -- device state ------------------------------------------------------

    def invalidate(self) -> None:
        """Breaker re-entry / mesh re-shard / restore: the device
        slabs are gone; every occupied row re-stages from host
        authority — ONE scatter at the next flush, no new jits (the
        scatter bucket shapes are the same pow2 set), and the epoch
        bump makes the rebuild observable."""
        with self._lock:
            self._dev = None
            self._cumw_dev = None
            self._weights_dirty = True
            self._tick += 1
            self._pending = {i: self._tick for i in range(self.n)}
            self.epoch += 1
        self._hbm_slabs.update(None)
        self._hbm_cumw.update(None)
        _M_EPOCH.set(self.epoch)
        telemetry.record_event(
            "arena.epoch",
            f"arena epoch {self.epoch}: {self.n} rows re-stage from "
            "host authority")

    def begin_flush(self, jnp):
        """Phase A of a flush — call under the owning pipeline's
        template lock, so the staged row data is atomic with the
        template snapshot the batch's mutants decode against: lazily
        allocate the device slabs, then memcpy the pending authority
        rows into the shared StagingArena buffers (host work only).
        Returns the opaque token commit_flush consumes."""
        with self._lock:
            n = self.n
            if self.host is None or n == 0:
                return ("empty", 0, None)
            if self._dev is None:
                self._dev = {
                    k: jnp.zeros(v.shape, dtype=v.dtype)
                    for k, v in self.host.items()}
                self._tick += 1
                self._pending = {i: self._tick for i in range(n)}
            pending = dict(self._pending)
            if not pending:
                return ("clean", n, None)
            idx_list = sorted(pending)
            n_rows = len(idx_list)
            bucket = pow2_rows(n_rows)
            fields = {"idx": ((bucket,), np.int32)}
            for k, v in self._dev.items():
                fields["row:" + k] = ((bucket,) + v.shape[1:], v.dtype)
            bufs = self._staging.acquire(("corpus", bucket), fields)
            idx = bufs["idx"]
            idx[:n_rows] = idx_list
            idx[n_rows:] = idx_list[-1]
            staged_bytes = 0
            for k in self._dev:
                rows = bufs["row:" + k]
                rows[:n_rows] = self.host[k][idx_list]
                rows[n_rows:] = rows[n_rows - 1]
                staged_bytes += rows.nbytes
            return ("staged", n, (pending, idx_list, bufs, staged_bytes))

    def commit_flush(self, jnp, token):
        """Phase B — the device work, no pipeline lock held: scatter
        the staged rows into the slabs (one .at[].set per field) and
        refresh the cumulative-weight vector if dirty.  Returns
        (device slabs, n, cumw device vector, total) — the arena
        handle the jitted step consumes.  On a device failure the
        pending set is left intact (entries are only removed after a
        successful scatter, and only if their staleness tick is
        unchanged), so the worker's retry re-uploads exactly what
        this call could not."""
        kind, n, payload = token
        if kind == "empty":
            return None, 0, None, 0
        if kind == "staged":
            pending, idx_list, bufs, staged_bytes = payload
            idx = bufs["idx"]
            with telemetry.span("pipeline.h2d_wait"):
                fault_point("staging.h2d")
                for k in self._dev:
                    self._dev[k] = \
                        self._dev[k].at[idx].set(bufs["row:" + k])
            self.uploads += 1
            self.upload_bytes += staged_bytes
            _M_UPLOADS.inc()
            _M_UPLOAD_BYTES.inc(staged_bytes)
            with self._lock:
                for i in idx_list:
                    if self._pending.get(i) == pending[i]:
                        del self._pending[i]
            self._hbm_slabs.update(self._dev)
            _M_SLAB_BYTES.set(sum(int(v.nbytes)
                                  for v in self._dev.values()))
        if self._weights_dirty or self._cumw_dev is None:
            fault_point("device.arena")
            with self._lock:
                if self.device_enabled:
                    w = self.weights
                else:
                    # Kill switch: unit weights — the legacy uniform
                    # stream, bit for bit.
                    w = np.zeros(self.capacity, np.uint32)
                    w[:n] = 1
                cw, total = cumw_from_weights(w, n, self.capacity)
                self._weights_dirty = False
            self._cumw_dev = jnp.asarray(cw)
            self._total = total
            self._hbm_cumw.update(self._cumw_dev)
        return self._dev, n, self._cumw_dev, self._total

    def flush(self, jnp):
        """begin_flush + commit_flush in one call (tests, the mesh
        re-shard path; the pipeline splits the phases so its template
        snapshot stays atomic with the staging drain)."""
        return self.commit_flush(jnp, self.begin_flush(jnp))

    def note_retired(self, k: int) -> None:
        """Count `k` rows superseded by a distilled truncation (the
        truncated row re-stages over the same slot, so retirement is
        an in-place shrink, not an eviction)."""
        if k <= 0:
            return
        with self._lock:
            self.retired += k
        _M_RETIRED.inc(k)

    def restore_epoch(self, epoch: int) -> None:
        """Continue the epoch counter across a checkpoint restore so
        the series stays monotonic for dashboards."""
        with self._lock:
            self.epoch = max(self.epoch, int(epoch))
        _M_EPOCH.set(self.epoch)

    def snapshot(self) -> dict:
        return {
            "device_enabled": self.device_enabled,
            "capacity": self.capacity,
            "rows": self.n,
            "epoch": self.epoch,
            "slab_bits": self.slab_bits,
            "uploads": self.uploads,
            "upload_bytes": self.upload_bytes,
            "retired": self.retired,
            "heat_folds": self.heat_folds,
            "pending": len(self._pending),
            "total_weight": self._total,
        }

    # -- mesh sharding -----------------------------------------------------

    def shard_rows(self, shard: int, n_shards: int) -> np.ndarray:
        """Occupied row indices owned by `shard` when the arena is
        split contiguously over the 'batch' mesh axis — the re-shard-
        on-chip-loss path slices host authority with this and
        device_puts per surviving shard (parallel/fault_domain)."""
        if self.n == 0 or n_shards <= 0:
            return np.zeros(0, np.int64)
        per = -(-self.n // n_shards)  # ceil
        lo = min(shard * per, self.n)
        hi = min(lo + per, self.n)
        return np.arange(lo, hi, dtype=np.int64)

    def authority_rows(self, idx: np.ndarray) -> dict:
        """Host-authority copies of the given rows (the mesh engine's
        re-shard source; a copy so device_put never aliases the
        mutable authority arrays)."""
        with self._lock:
            if self.host is None:
                return {}
            return {k: v[idx].copy() for k, v in self.host.items()}


# -- durable authority codec (pack_plane-style; ISSUE 12 path) ------------


def pack_arena(progs: list[bytes], weights: np.ndarray,
               epoch: int) -> tuple[dict, bytes]:
    """Checkpoint section codec: length-prefixed serialized programs
    + per-row sampling weights, zlib level 1 (the corpus is text-like
    and the cadence write must stay cheap — same bargain as
    signal.pack_plane).  Returns (meta, blob) for a DurableStore
    provider."""
    parts = []
    for p in progs:
        b = bytes(p)
        parts.append(len(b).to_bytes(4, "little"))
        parts.append(b)
    blob = zlib.compress(b"".join(parts), 1)
    meta = {"n": len(progs), "epoch": int(epoch),
            "weights": [int(w) for w in
                        np.asarray(weights[:len(progs)], np.uint32)]}
    return meta, blob


def unpack_arena(meta: dict, blob: bytes) \
        -> tuple[list[bytes], np.ndarray, int]:
    """Inverse of pack_arena — numpy/zlib only, safe on the jax-free
    recovery path.  Returns (serialized programs, weights, epoch)."""
    raw = zlib.decompress(bytes(blob))
    n = int(meta.get("n", 0))
    progs: list[bytes] = []
    off = 0
    for _ in range(n):
        ln = int.from_bytes(raw[off:off + 4], "little")
        off += 4
        progs.append(raw[off:off + ln])
        off += ln
    weights = np.asarray(meta.get("weights", [1] * n), np.uint32)
    if weights.size < n:
        weights = np.pad(weights, (0, n - weights.size),
                         constant_values=1)
    return progs, weights, int(meta.get("epoch", 0))


# -- the distillation lane ------------------------------------------------


def truncation_keep_counts(n_alive: int, max_cands: int) -> list[int]:
    """The bisection ladder for one row: candidate alive-call keep
    counts, shortest-first would bias the verdict scan, so they come
    DESCENDING — n-1 (the single-suffix-drop probe) then halves
    (n//2, n//4, .., 1).  Padded by the caller to the static
    candidate shape with n (a no-op candidate that trivially covers
    and never wins the min-keep pick)."""
    ks: list[int] = []
    if n_alive - 1 >= 1:
        ks.append(n_alive - 1)
    k = n_alive // 2
    while k >= 1 and len(ks) < max_cands:
        if k not in ks:
            ks.append(k)
        k //= 2
    return ks[:max_cands]


def truncated_alive(call_alive: np.ndarray, keep: int) -> np.ndarray:
    """Suffix truncation: keep the first `keep` alive calls.  Suffix
    drops can never dangle a forward result reference (results only
    flow forward), which is what makes the candidate set safe to
    re-encode without a typed repair pass."""
    mask = np.zeros_like(call_alive, dtype=bool)
    pos = np.flatnonzero(call_alive)[:keep]
    mask[pos] = True
    return mask


def alive_mask_bits(call_alive: np.ndarray) -> int:
    """bool[C] -> the uint64 alive bitmap the sim kernel consumes."""
    bits = 0
    for c in np.flatnonzero(call_alive):
        bits |= 1 << int(c)
    return bits


def build_distill_batch(arena: CorpusArena, templates, ets,
                        slots: list[int], max_calls: int,
                        max_cands: int):
    """Host staging for one distill round: per selected row, the
    lowered sim table, the template's slot values from arena
    authority, and the candidate alive bitmaps (slot 0 = original).
    Returns (table_rows dict (R,C..), ncalls (R,), alive (R, M) u64,
    vals (R, S), keeps (R, M) int; M = max_cands + 1) — all numpy;
    the caller uploads and dispatches."""
    from syzkaller_tpu.sim.kernel import TABLE_FIELDS
    from syzkaller_tpu.sim.table import build_sim_table

    R = len(slots)
    M = max_cands + 1
    tables = [build_sim_table(ets[i], max_calls) for i in slots]
    table_rows = {
        k: np.stack([getattr(t, k) for t in tables])
        for k in TABLE_FIELDS}
    ncalls = np.array([t.ncalls for t in tables], np.int32)
    with arena._lock:
        vals = arena.host["val"][slots].copy()
    alive = np.zeros((R, M), np.uint64)
    keeps = np.zeros((R, M), np.int64)
    for r, i in enumerate(slots):
        ca = templates[i].call_alive
        n_alive = int(ca.sum())
        alive[r, 0] = alive_mask_bits(ca)
        keeps[r, 0] = n_alive
        ks = truncation_keep_counts(n_alive, max_cands)
        for c in range(max_cands):
            k = ks[c] if c < len(ks) else n_alive
            alive[r, c + 1] = alive_mask_bits(truncated_alive(ca, k))
            keeps[r, c + 1] = k
    return table_rows, ncalls, alive, vals, keeps


def make_distill_check(backend: str):
    """The fused bisection batch: sim-exec original + candidates in
    one dispatch, fold predicted edges (ops/signal.fold_hash), and
    per candidate test whether its valid folds COVER the original's
    (sorted-membership — exact, no digest collisions).  One jit per
    (R, M) shape; the lane pins both, so the warm rig compiles this
    once."""
    import jax
    import jax.numpy as jnp

    from syzkaller_tpu.ops.signal import fold_hash
    from syzkaller_tpu.sim.kernel import sim_exec_batch

    def check(table_rows, ncalls, alive, vals):
        R, M = alive.shape
        rep = lambda a: jnp.repeat(a, M, axis=0)  # noqa: E731
        tr = {k: rep(v) for k, v in table_rows.items()}
        edges, valid, _r, _e, _s = sim_exec_batch(
            tr, rep(ncalls), alive.reshape(-1), rep(vals),
            backend, interpret=True)
        CE = edges.shape[1] * edges.shape[2]
        folds = fold_hash(edges).reshape(R, M, CE)
        valid = valid.reshape(R, M, CE)
        f = jnp.where(valid, folds, _FOLD_SENTINEL)
        orig = f[:, 0, :]                      # (R, CE)
        cand_sorted = jnp.sort(f, axis=-1)     # (R, M, CE)

        def member(cs, o):
            p = jnp.searchsorted(cs, o)
            return cs[jnp.clip(p, 0, cs.shape[0] - 1)] == o

        hits = jax.vmap(lambda cs_row, o:
                        jax.vmap(lambda cs: member(cs, o))(cs_row))(
            cand_sorted, orig)                 # (R, M, CE)
        o_real = orig != _FOLD_SENTINEL        # (R, CE)
        covers = jnp.all(hits | ~o_real[:, None, :], axis=-1)
        n_orig = o_real.sum(axis=-1).astype(jnp.int32)
        return covers, n_orig

    return jax.jit(check)


def distill_verdicts_host(table_rows, ncalls, alive, vals):
    """The host bisection oracle: rerun every (row, candidate) pair
    through sim_exec_host and decide coverage with the existing
    digest machinery at bits=FOLD_BITS — the digest bucket IS the
    fold at that resolution, so `digest_covers` is exact membership
    and the verdict matrix must equal the device check's bit for bit
    (a row whose original has no valid edges is trivially covered on
    both sides)."""
    from syzkaller_tpu.ops.signal import (
        FOLD_BITS,
        digest_covers,
        digest_from_folds,
        fold_hash_np,
    )
    from syzkaller_tpu.sim.kernel import TABLE_FIELDS
    from syzkaller_tpu.sim.table import SimTable, sim_exec_host

    R, M = alive.shape
    covers = np.zeros((R, M), bool)
    for r in range(R):
        fields = {k: table_rows[k][r] for k in TABLE_FIELDS}
        table = SimTable(ncalls=int(ncalls[r]), **fields)
        folds_by_cand = []
        for m in range(M):
            edges, valid, _ret, _err, _st = sim_exec_host(
                table, vals=vals[r], alive_bits=int(alive[r, m]))
            folds_by_cand.append(fold_hash_np(edges[valid]))
        orig = folds_by_cand[0]
        for m in range(M):
            if orig.size == 0:
                covers[r, m] = True
                continue
            digest = digest_from_folds(folds_by_cand[m], FOLD_BITS)
            covers[r, m] = digest_covers(digest, orig)
    return covers


class DistillLane:
    """Cadenced Minimize-style corpus distillation over the arena.

    The lane owns its cadence clock and the jitted cover-check
    executable (one compile at the pinned (rows, candidates) shape);
    the pipeline drives `tick()` per drained batch and runs
    `round()` from its worker thread when the cadence fires, under
    the `device.arena` fault seam."""

    def __init__(self, max_calls: int, backend: str = "vmap",
                 every: int | None = None, rows: int | None = None,
                 max_cands: int = 4):
        self.max_calls = max_calls
        self.backend = backend
        self.every = resolve_distill_every() if every is None else every
        self.rows = resolve_distill_rows() if rows is None else rows
        self.max_cands = max_cands
        self.rounds = 0
        self.retired = 0
        self.errors = 0
        self._batches = 0
        self._cursor = 0
        self._check = None

    def tick(self) -> bool:
        """One drained batch; True when a distill round is due."""
        if not self.every:
            return False
        self._batches += 1
        return self._batches % self.every == 0

    def select_slots(self, templates, n: int) -> list[int]:
        """The next `rows` occupied slots with at least two alive
        calls, cursor-walked so rounds sweep the whole ring."""
        out: list[int] = []
        if n == 0:
            return out
        for k in range(n):
            i = (self._cursor + k) % n
            t = templates[i]
            if t is None or int(t.call_alive.sum()) < 2:
                continue
            out.append(i)
            if len(out) >= self.rows:
                break
        self._cursor = (self._cursor + n) % max(n, 1) \
            if len(out) < self.rows else (out[-1] + 1) % n
        return out

    def check(self, table_rows, ncalls, alive, vals):
        """Dispatch the fused bisection batch; returns numpy
        (covers (R, M) bool, n_orig (R,) int32)."""
        import jax.numpy as jnp

        if self._check is None:
            self._check = make_distill_check(self.backend)
        covers, n_orig = self._check(
            {k: jnp.asarray(v) for k, v in table_rows.items()},
            jnp.asarray(ncalls), jnp.asarray(alive),
            jnp.asarray(vals))
        R, M = alive.shape
        self.rounds += 1
        _M_DISTILL_ROUNDS.inc()
        _M_DISTILL_CANDS.inc(R * (M - 1))
        return np.asarray(covers), np.asarray(n_orig)

    def choose(self, covers: np.ndarray, keeps: np.ndarray) \
            -> list[int | None]:
        """Per row: the winning candidate index (smallest keep count
        among covering candidates strictly shorter than the
        original), or None when nothing shorter covers."""
        R, M = covers.shape
        out: list[int | None] = []
        for r in range(R):
            best, best_k = None, int(keeps[r, 0])
            for m in range(1, M):
                k = int(keeps[r, m])
                if covers[r, m] and k < best_k:
                    best, best_k = m, k
            out.append(best)
        return out

    def snapshot(self) -> dict:
        return {"every": self.every, "rows": self.rows,
                "max_cands": self.max_cands, "rounds": self.rounds,
                "retired": self.retired, "errors": self.errors}
