"""The shared transfer plane: pinned staging arenas + depth control.

PRs 3-4 made the device kernels and the host assembler fast enough
that the remaining per-batch cost on both hot paths is host<->device
transfer *bookkeeping*: the triage flush leader re-allocated and
re-padded a (B, E) batch per flush (~0.1 ms/batch at the bench
shape), the pipeline's corpus flush re-stacked its scatter rows per
flush, and the per-batch triage H2D was serialized against the
previous batch's verdict fetch.  This module is the shared fix — the
same double-buffered pinned-staging discipline large-batch inference
serving uses, and the transfer-side twin of the pipeline's
`dispatch_depth` launch overlap:

  StagingArena      persistent pre-padded host buffers per pow2
                    bucket.  Producers write rows IN PLACE into a
                    rotating slot pair instead of allocating + zeroing
                    per batch; a slot is only rewritten after its
                    in-flight consumer resolved, so an upload can
                    still be reading slot k-1 while the leader pads
                    batch k into slot k.  Shapes are pow2-bucketed by
                    the caller (ops/delta.pow2_rows), so the device
                    side never sees a new shape and nothing re-jits.

  DepthController   the drain->assemble overlap made self-tuning:
                    feeds the measured `pipeline.pool_drain` vs
                    `pipeline.assemble_worker` span percentiles back
                    into the pipeline's `assemble_depth` (clamped,
                    hysteretic, with a cooldown between moves) so the
                    assembly pool stops idling behind D2H on
                    multi-core hosts — and stops hoarding arenas on
                    hosts where assembly is the slow stage.
                    `TZ_ASSEMBLE_DEPTH=auto|N` selects the controller
                    or pins a fixed depth (health.envsafe parsing: a
                    malformed value degrades to auto, never kills
                    startup).

Consumers: ops/pipeline.DevicePipeline (corpus-flush scatter staging,
assemble-depth control) and triage/engine.TriageEngine (flush-leader
batch staging + `TZ_TRIAGE_DISPATCH_DEPTH` H2D/verdict overlap).  The
`staging.h2d` fault seam (health/faultinject) guards the upload edge
both consumers share; docs/perf.md "The transfer plane" documents the
buffer lifecycle and tuning.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from syzkaller_tpu import telemetry
from syzkaller_tpu.health.envsafe import env_auto_int

# Transfer-plane telemetry (docs/observability.md): arena footprint +
# the two live depths.  Gauges are process-wide sums/currents shared
# by every arena/controller instance.
_M_ARENA_BYTES = telemetry.gauge(
    "tz_staging_arena_bytes",
    "bytes held by persistent host staging arenas")
_M_ARENA_ALLOCS = telemetry.counter(
    "tz_staging_arena_allocs_total",
    "staging-arena buffer allocations (growth events; steady state "
    "allocates nothing)")
_M_ASSEMBLE_DEPTH = telemetry.gauge(
    "tz_staging_assemble_depth",
    "drained batches the pipeline keeps fanned out over the assembly "
    "pool (TZ_ASSEMBLE_DEPTH; auto = DepthController)")
_M_DISPATCH_DEPTH = telemetry.gauge(
    "tz_staging_h2d_dispatch_depth",
    "triage H2D uploads kept in flight ahead of the verdict fetch "
    "(TZ_TRIAGE_DISPATCH_DEPTH; 1 while the breaker is not closed)")

#: Process-wide arena footprint (all instances), guarded by one lock:
#: growth is rare (log2 buckets x slots), reads go through the gauge.
_footprint_lock = threading.Lock()
_footprint_bytes = 0
_hbm_handle = None


def _account(nbytes: int) -> None:
    global _footprint_bytes, _hbm_handle
    with _footprint_lock:
        _footprint_bytes += nbytes
        _M_ARENA_BYTES.set(_footprint_bytes)
        # Residency ledger (ISSUE 17): the pinned staging buffers are
        # long-lived host memory — one opaque byte-count entry for
        # the process-wide footprint (per-bucket identity lives in
        # the arenas; the ledger answers "how much, whose?").
        if _hbm_handle is None:
            _hbm_handle = telemetry.HBM.register(
                "staging", "arena", _footprint_bytes, device="host")
        else:
            _hbm_handle.update(_footprint_bytes, device="host")


class StagingArena:
    """Persistent pow2-bucketed host staging buffers with slot
    rotation.

    acquire(key, fields) returns a dict of named numpy buffers for
    one transfer batch.  The first acquire of a (key, shapes) bucket
    allocates `slots` copies; every later acquire rotates through
    them and returns the SAME arrays — the caller overwrites the rows
    it stages and relies on its device kernel's validity masking (a
    row-count field, not zeroed padding) to ignore stale bytes, so
    steady state performs zero allocations and zero full-buffer
    clears.

    Rotation is the double-buffer contract: with `slots` >= the
    consumer's in-flight depth, a slot is never rewritten before the
    upload that read it resolved, so batch k can be staged while
    batch k-1's H2D/verdict round-trip is still in flight.  Buffers
    are ordinary page-locked-by-the-OS numpy memory ("pinned" in the
    CUDA sense is not a JAX host API; what matters here is identity —
    the transfer layer sees a stable address instead of a fresh
    allocation per batch).

    Not thread-safe by itself: each consumer owns its arena and
    serializes acquires under its own lock (the triage device lock,
    the pipeline corpus lock)."""

    __slots__ = ("slots", "_bufs", "_turn", "allocations", "nbytes")

    def __init__(self, slots: int = 2):
        self.slots = max(1, int(slots))
        # (key, shape/dtype signature) -> [slot][field] -> ndarray
        self._bufs: dict = {}
        self._turn: dict = {}
        self.allocations = 0  # growth events (tests pin steady state)
        self.nbytes = 0

    def acquire(self, key, fields: dict) -> dict:
        """Staging buffers for one batch.  `fields` maps field name ->
        (shape, dtype); shape[0] is the caller's pow2 row bucket so
        the signature set stays bounded.  Returns {name: ndarray}."""
        sig = (key, tuple(sorted(
            (n, tuple(s), np.dtype(d).str) for n, (s, d) in fields.items())))
        slots = self._bufs.get(sig)
        if slots is None:
            slots = []
            grew = 0
            for _ in range(self.slots):
                bufs = {n: np.zeros(s, dtype=d)
                        for n, (s, d) in fields.items()}
                grew += sum(b.nbytes for b in bufs.values())
                slots.append(bufs)
            self._bufs[sig] = slots
            self._turn[sig] = 0
            self.allocations += 1
            self.nbytes += grew
            _M_ARENA_ALLOCS.inc()
            _account(grew)
        turn = self._turn[sig]
        self._turn[sig] = (turn + 1) % len(slots)
        return slots[turn]

    def bucket_count(self) -> int:
        return len(self._bufs)


class DepthController:
    """Clamped, hysteretic controller for the pipeline's
    drain->assemble overlap depth.

    The signal is the measured span ratio D2H : assembly —
    `pipeline.pool_drain` p50 over `pipeline.assemble_worker` p50
    from the process registry (the histograms PR 3 already records).
    When the pool fetch dominates, the assembly pool is idling behind
    the link: raising `assemble_depth` keeps more drained batches
    fanned out while the drain thread blocks in the next fetch.  When
    assembly dominates, extra depth only pins batch arenas in memory:
    lower it back toward 1.

    Hysteresis (raise above `raise_ratio`, lower below `lower_ratio`,
    and a `cooldown` of update calls between moves) keeps the depth
    from flapping on noisy percentiles; `min_samples` keeps it inert
    until both histograms carry real data, so a fresh pipeline (and
    the tier-1 suite) runs at the initial depth.  update() allocates
    nothing and never touches the device — zero jits by
    construction."""

    __slots__ = ("depth", "lo", "hi", "raise_ratio", "lower_ratio",
                 "min_samples", "cooldown", "interval", "_calls",
                 "_cool", "_drain_hist", "_work_hist")

    def __init__(self, initial: int = 2, lo: int = 1, hi: int = 4,
                 raise_ratio: float = 1.3, lower_ratio: float = 0.6,
                 min_samples: int = 32, cooldown: int = 4,
                 interval: int = 8, drain_hist=None, work_hist=None):
        self.lo = max(1, lo)
        self.hi = max(self.lo, hi)
        self.depth = min(self.hi, max(self.lo, initial))
        self.raise_ratio = raise_ratio
        self.lower_ratio = lower_ratio
        self.min_samples = min_samples
        self.cooldown = max(0, cooldown)
        self.interval = max(1, interval)
        self._calls = 0
        self._cool = 0
        self._drain_hist = drain_hist if drain_hist is not None else \
            telemetry.REGISTRY.histogram(
                telemetry.span_metric_name("pipeline.pool_drain"))
        self._work_hist = work_hist if work_hist is not None else \
            telemetry.REGISTRY.histogram(
                telemetry.span_metric_name("pipeline.assemble_worker"))
        _M_ASSEMBLE_DEPTH.set(self.depth)

    def update(self) -> int:
        """One controller tick (the pipeline worker calls this per
        collected batch; only every `interval`-th tick evaluates).
        Returns the current depth."""
        self._calls += 1
        if self._calls % self.interval:
            return self.depth
        if self._cool > 0:
            self._cool -= 1
            return self.depth
        if self._drain_hist.count < self.min_samples or \
                self._work_hist.count < self.min_samples:
            return self.depth
        drain = self._drain_hist.percentile(0.5)
        work = self._work_hist.percentile(0.5)
        if work <= 0.0:
            return self.depth
        ratio = drain / work
        moved = None
        if ratio > self.raise_ratio and self.depth < self.hi:
            self.depth += 1
            moved = "raise"
        elif ratio < self.lower_ratio and self.depth > self.lo:
            self.depth -= 1
            moved = "lower"
        if moved:
            self._cool = self.cooldown
            _M_ASSEMBLE_DEPTH.set(self.depth)
            telemetry.record_event(
                "staging.assemble_depth",
                f"{moved} to {self.depth} (d2h/assembly p50 ratio "
                f"{ratio:.2f})")
        return self.depth


def resolve_assemble_depth(default: int, hi: int = None):
    """Parse TZ_ASSEMBLE_DEPTH=auto|N (health.envsafe discipline):
    returns (depth, controller) where controller is a DepthController
    seeded at `depth` for auto mode and None for a pinned depth.
    Unset and malformed values both resolve to auto at the compiled-in
    default — self-tuning is the production behavior, a typo must not
    change it.  `hi` raises the controller's ceiling for callers whose
    batch shape outgrew the default (the pipeline scales it with
    TZ_PIPELINE_BATCH past the 2048 flagship shape)."""
    v = env_auto_int("TZ_ASSEMBLE_DEPTH", None)
    if v is None:
        ctrl = DepthController(initial=max(1, default),
                               hi=4 if hi is None else max(1, hi))
        return ctrl.depth, ctrl
    depth = max(1, v)
    _M_ASSEMBLE_DEPTH.set(depth)
    return depth, None


def note_dispatch_depth(depth: int) -> None:
    """Record the triage engine's effective H2D dispatch depth (the
    gauge bench_watch's transfer-plane line renders)."""
    _M_DISPATCH_DEPTH.set(depth)
