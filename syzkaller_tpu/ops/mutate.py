"""Batched program mutation on device.

The vmap'd equivalent of the reference's per-program mutation loop
(reference: prog/mutation.go:14-142,394-521) over program tensors.
The device owns the high-volume ops — argument value mutation (int/
flags/proc/len), the 7-op byte-level data engine, and call removal;
structural tree ops (call insertion, corpus splice, ANY-squash) are
host-side and routed by fuzzer.proc.PipelineMutator, which draws
a host-sampled op class so the overall op distribution matches the
reference's weights.

Everything is static-shape: spans live in a fixed arena, shifts are
masked index arithmetic over the whole arena vector (VPU-friendly),
values are uint64 scalars per slot.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax, random

from syzkaller_tpu.ops import rng as d
from syzkaller_tpu.ops.tensor import DATA, EMPTY, FLAGS, INT, LEN, PROC

U64 = jnp.uint64
MASK64 = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def _width_mask(width):
    """(1 << 8*width) - 1 without overflow at width 8."""
    bits = (width.astype(jnp.uint64) * U64(8)) % U64(64)
    full = width.astype(jnp.uint64) >= U64(8)
    m = (U64(1) << bits) - U64(1)
    return jnp.where(full, MASK64, m)


def _swap_int(v, width):
    """Byte-swap the low `width` bytes (width in {1,2,4,8})."""
    b = [(v >> U64(8 * i)) & U64(0xFF) for i in range(8)]
    def build(n):
        out = U64(0)
        for i in range(n):
            out = out | (b[n - 1 - i] << U64(8 * i))
        return out
    return jnp.select([width == 1, width == 2, width == 4],
                      [v & U64(0xFF), build(2), build(4)], build(8))


# -- value mutation ------------------------------------------------------


def _mutate_int_value(key, val, width, aux0, aux1, kind):
    """mutateInt for INT slots (reference: prog/mutation.go:174-188):
    1/2 regenerate, else +1..4 / -1..4 / xor random bit."""
    k_bin, k_branch, k_d1, k_d2, k_bit, k_regen, k_range = random.split(key, 7)
    # regenerate: plain ints use rand_int, range ints use rand_range_int
    is_range = aux1 != U64(0)
    regen = jnp.where(is_range,
                      d.rand_range_int(k_range, aux0, jnp.maximum(aux1, aux0)),
                      d.rand_int(k_regen))
    branch = d._categorical(k_branch, _INT_ARITH_P)
    plus = val + d.intn(k_d1, 4).astype(U64) + U64(1)
    minus = val - d.intn(k_d1, 4).astype(U64) - U64(1)
    xored = val ^ (U64(1) << d.intn(k_bit, 64).astype(U64))
    arith = jnp.select([branch == 0, branch == 1], [plus, minus], xored)
    return jnp.where(d.bin_(k_bin), regen, arith)


_INT_ARITH_P = jnp.cumsum(jnp.array([1 / 3, 1 / 3, 1 / 3]))


def _mutate_flags_value(key, val, flag_set, flag_vals, flag_counts):
    k_bin, k_regen, k_arith = random.split(key, 3)
    fs = jnp.maximum(flag_set, 0)
    regen = d.flags_value(k_regen, flag_vals[fs], flag_counts[fs])
    k_branch, k_d1, k_bit = random.split(k_arith, 3)
    branch = d._categorical(k_branch, _INT_ARITH_P)
    arith = jnp.select(
        [branch == 0, branch == 1],
        [val + d.intn(k_d1, 4).astype(U64) + U64(1),
         val - d.intn(k_d1, 4).astype(U64) - U64(1)],
        val ^ (U64(1) << d.intn(k_bit, 64).astype(U64)))
    return jnp.where(d.bin_(k_bin), regen, arith)


def _mutate_proc_value(key, aux1):
    # regenerate: rand(values_per_proc) (reference: prog/rand.go:634-636)
    return d.intn(key, jnp.maximum(aux1.astype(jnp.int64), 1)).astype(U64)


def _mutate_len_value(key, val, elem_size):
    """mutate_size (reference: prog/size.go:119-175)."""
    ks = random.split(key, 8)
    elem = jnp.maximum(elem_size, U64(1))
    rand_any = d.rand64(ks[1])
    # small adjust
    down = d.rand_range_int(ks[2], U64(0), jnp.maximum(val, U64(1)) - U64(1))
    up = d.rand_range_int(ks[3], val + U64(1), val + U64(1000))
    small = jnp.where((val != U64(0)) & d.bin_(ks[4]), down, up)
    # overflow provoking
    maxv = jnp.select(
        [d.one_of(ks[5], 3) & d.one_of(ks[6], 2) & d.one_of(ks[7], 2),
         d.one_of(ks[5], 3) & d.one_of(ks[6], 2),
         d.one_of(ks[5], 3)],
        [U64(0xFF), U64(0xFFFF), U64(0xFFFFFFFF)], MASK64)
    # maxv // elem without u64 division: exact shift for pow2 elem
    # sizes (the common case), f32 approximation otherwise.
    log2 = U64(63) - lax.clz(elem).astype(U64)
    is_pow2 = (elem & (elem - U64(1))) == U64(0)
    approx = (maxv.astype(jnp.float32) /
              elem.astype(jnp.float32)).astype(U64)
    n = jnp.where(is_pow2, maxv >> log2, approx)
    delta = (U64(1000) - d.biased_rand(ks[0], 1000, 10).astype(U64))
    k_dir = random.fold_in(key, 99)
    minus = (elem == U64(1)) | d.one_of(k_dir, 10)
    overflow = jnp.where(minus, n - delta, n + delta)
    k_a, k_b = random.split(random.fold_in(key, 100))
    return jnp.where(d.one_of(k_a, 100), rand_any,
                     jnp.where(d.bin_(k_b), small, overflow))


# -- data (arena) mutation ----------------------------------------------
#
# TPU note: batched dynamic gathers/scatters over the whole arena
# serialize on TPU (measured ~180 ms per op at [512, 8192]).  All
# dynamic shifts/loads/stores are therefore expressed as
# binary-decomposed STATIC rolls — log2(n) conditional full-vector
# selects, which the VPU streams at HBM bandwidth (~100x faster).


def _roll_right(a, n, nbits):
    """Roll a 1-D vector right by dynamic n (< 2**nbits) using static
    rolls selected per bit of n."""
    for b in range(nbits):
        amt = 1 << b
        rolled = jnp.concatenate([a[-amt:], a[:-amt]])
        a = jnp.where((n >> b) & 1 != 0, rolled, a)
    return a


def _roll_left(a, n, nbits):
    for b in range(nbits):
        amt = 1 << b
        rolled = jnp.concatenate([a[amt:], a[:amt]])
        a = jnp.where((n >> b) & 1 != 0, rolled, a)
    return a


def _arena_bits(arena) -> int:
    """Roll-width for dynamic positions — derived from the (static)
    arena length so non-default TensorConfig.arena sizes stay correct."""
    return max(int(arena.shape[0] - 1).bit_length(), 1)


def _load_le(arena, pos, width):
    """Little-endian load of `width` bytes at dynamic pos."""
    window = _roll_left(arena, pos, _arena_bits(arena))[:8].astype(U64)
    shifts = (jnp.arange(8) * 8).astype(U64)
    valid = jnp.arange(8) < width
    return jnp.sum(jnp.where(valid, window << shifts, U64(0)))


def _store_le(arena, pos, width, value):
    new_bytes = ((value >> (jnp.arange(8) * 8).astype(U64)) & U64(0xFF)
                 ).astype(jnp.uint8)
    A = arena.shape[0]
    head = jnp.zeros(A, jnp.uint8).at[:8].set(new_bytes)
    mask_head = jnp.arange(A) < width
    placed = _roll_right(jnp.where(mask_head, head, jnp.uint8(0)),
                         pos, _arena_bits(arena))
    mask = _roll_right(mask_head, pos, _arena_bits(arena))
    return jnp.where(mask, placed, arena)


def _mutate_data_span(key, arena, off, length, cap, min_len, max_len):
    """One application of a random byte-level op on span [off, off+length)
    with growth capped at cap (reference: prog/mutation.go:404-521).
    Returns (arena, new_length, ok)."""
    max_len = jnp.minimum(max_len, cap.astype(U64)).astype(jnp.int32)
    min_len = min_len.astype(jnp.int32)
    A = arena.shape[0]
    idx = jnp.arange(A, dtype=jnp.int32)
    rel = idx - off
    k_op, k1, k2, k3, k4, k5, k6, k_rb = random.split(key, 8)
    op = d.intn(k_op, 7)
    # One full-width random byte vector shared by insert/append (direct
    # generation beats a 256-table gather on TPU).  Generated outside
    # the switch deliberately: under vmap all switch branches execute
    # anyway, so hoisting costs nothing and keeps one RNG call.
    rand_bytes = random.bits(k_rb, (A,), dtype=jnp.uint8)

    # 1) flip a bit
    def op_flip():
        kp, kb = random.split(k1)
        pos = off + d.intn(kp, jnp.maximum(length, 1)).astype(jnp.int32)
        bit = d.intn(kb, 8).astype(jnp.uint8)
        flip_mask = _roll_right(
            jnp.zeros(A, jnp.uint8).at[0].set(jnp.uint8(1) << bit),
            pos, _arena_bits(arena))
        new = arena ^ flip_mask
        ok = length > 0
        return jnp.where(ok, new, arena), length, ok

    # 2) insert random bytes at pos, maybe truncating back
    def op_insert():
        kn, kp, kb = random.split(k2, 3)
        n = jnp.minimum(d.intn(kn, 16).astype(jnp.int32) + 1,
                        jnp.minimum(max_len - length, cap - length))
        pos = d.intn(kp, jnp.maximum(length, 1)).astype(jnp.int32)
        in_span = (rel >= 0) & (rel < cap)
        shifted = _roll_right(arena, n & 31, 5)
        new = jnp.where(in_span & (rel >= pos) & (rel < pos + n),
                        rand_bytes,
                        jnp.where(in_span & (rel >= pos + n), shifted,
                                  arena))
        keep_len = d.bin_(kb)
        new_len = jnp.where(keep_len, length, length + n)
        ok = (length > 0) & (n > 0)
        return (jnp.where(ok, new, arena),
                jnp.where(ok, new_len, length), ok)

    # 3) remove bytes at pos, maybe re-extending with zeros
    def op_remove():
        kn, kp, kb = random.split(k3, 3)
        n = jnp.minimum(d.intn(kn, 16).astype(jnp.int32) + 1, length)
        pos = jnp.where(
            n < length,
            d.intn(kp, jnp.maximum(length - n, 1)).astype(jnp.int32), 0)
        in_span = (rel >= 0) & (rel < cap)
        shifted = _roll_left(arena, n & 31, 5)
        new = jnp.where(in_span & (rel >= pos), shifted, arena)
        pad_zeros = d.bin_(kb)
        short = length - n
        # re-extend with zeros to the original length
        new = jnp.where(
            pad_zeros & in_span & (rel >= short) & (rel < length),
            jnp.uint8(0), new)
        new_len = jnp.where(pad_zeros, length, short)
        ok = length > min_len
        return (jnp.where(ok, new, arena),
                jnp.where(ok, new_len, length), ok)

    # 4) append random bytes
    def op_append():
        kn = k4
        want = 256 - d.biased_rand(kn, 256, 10).astype(jnp.int32)
        n = jnp.minimum(want, jnp.minimum(max_len - length, cap - length))
        in_new = (rel >= length) & (rel < length + n)
        new = jnp.where(in_new, rand_bytes, arena)
        ok = length < max_len
        return (jnp.where(ok, new, arena),
                jnp.where(ok, length + n, length), ok)

    # 5) replace an int with a random value
    def op_replace():
        kw, kp, kv = random.split(k5, 3)
        w = (1 << d.intn(kw, 4)).astype(jnp.int32)
        ok = length >= w
        pos = off + d.intn(kp, jnp.maximum(length - w + 1, 1)).astype(jnp.int32)
        new = _store_le(arena, pos, w, d.uint64(kv))
        return jnp.where(ok, new, arena), length, ok

    # 6) add/subtract a small delta from an int
    def op_addsub():
        kw, kp, kd, ke = random.split(k6, 4)
        w = (1 << d.intn(kw, 4)).astype(jnp.int32)
        ok = length >= w
        pos = off + d.intn(kp, jnp.maximum(length - w + 1, 1)).astype(jnp.int32)
        v = _load_le(arena, pos, w)
        delta = d.intn(kd, 2 * 35 + 1) - 35
        delta = jnp.where(delta == 0, 1, delta).astype(jnp.int64)
        dd = lax.convert_element_type(delta, jnp.uint64)
        swapped = d.one_of(ke, 10)
        v1 = jnp.where(swapped,
                       _swap_int(_swap_int(v, w) + dd, w),
                       v + dd)
        new = _store_le(arena, pos, w, v1)
        return jnp.where(ok, new, arena), length, ok

    # 7) set an int to an interesting value
    def op_interesting():
        kw, kp, kv, ke = random.split(random.fold_in(key, 7), 4)
        w = (1 << d.intn(kw, 4)).astype(jnp.int32)
        ok = length >= w
        pos = off + d.intn(kp, jnp.maximum(length - w + 1, 1)).astype(jnp.int32)
        v = d.rand_int(kv)
        v = jnp.where(d.one_of(ke, 10), _swap_int(v, 8), v)
        new = _store_le(arena, pos, w, v)
        return jnp.where(ok, new, arena), length, ok

    return lax.switch(op, [op_flip, op_insert, op_remove, op_append,
                           op_replace, op_addsub, op_interesting])


# -- the per-program mutation round -------------------------------------


def _mutate_slot(key, state, flag_vals, flag_counts):
    """Pick one eligible slot and mutate it in place."""
    k_pick, k_mut, k_data = random.split(key, 3)
    kind = state["kind"]
    alive = state["call_alive"][jnp.clip(state["call"], 0, None).astype(jnp.int32)]
    eligible = (kind != EMPTY) & alive
    s = d.masked_choice(k_pick, eligible)
    s_safe = jnp.maximum(s, 0)
    sk = kind[s_safe]
    val = state["val"][s_safe]
    width = state["width"][s_safe]
    aux0 = state["aux0"][s_safe]
    aux1 = state["aux1"][s_safe]
    fs = state["flag_set"][s_safe]

    new_int = _mutate_int_value(k_mut, val, width, aux0, aux1, sk)
    new_flags = _mutate_flags_value(k_mut, val, fs, flag_vals, flag_counts)
    new_proc = _mutate_proc_value(k_mut, aux1)
    new_len = _mutate_len_value(k_mut, val, aux0)
    new_val = jnp.select(
        [sk == INT, sk == FLAGS, sk == PROC, sk == LEN],
        [new_int, new_flags, new_proc, new_len], val)

    # data op: loop until an op succeeds and a 1/3 coin says stop,
    # approximated by 3 bounded attempts (reference: mutation.go:394-400)
    def data_body(i, carry):
        arena, length, done = carry
        kk = random.fold_in(k_data, i)
        a2, l2, ok = _mutate_data_span(
            kk, arena, state["off"][s_safe], length, state["cap"][s_safe],
            state["aux0"][s_safe], state["aux1"][s_safe])
        stop = ok & d.one_of(random.fold_in(kk, 1), 3)
        arena = jnp.where(done, arena, a2)
        length = jnp.where(done, length, l2)
        return arena, length, done | stop

    arena, new_dlen, _ = lax.fori_loop(
        0, 3, data_body, (state["arena"], state["len_"][s_safe], False))

    is_data = (sk == DATA) & (s >= 0)
    is_val = (sk != DATA) & (s >= 0)
    state = dict(state)
    state["val"] = state["val"].at[s_safe].set(
        jnp.where(is_val, new_val, val))
    state["arena"] = jnp.where(is_data, arena, state["arena"])
    state["len_"] = state["len_"].at[s_safe].set(
        jnp.where(is_data, new_dlen, state["len_"][s_safe]))
    state["preserve_sizes"] = state["preserve_sizes"] | ((sk == LEN) & (s >= 0))
    state["touched"] = state["touched"].at[s_safe].set(
        state["touched"][s_safe] | (s >= 0))
    return state


def _remove_call(key, state):
    alive = state["call_alive"]
    ci = d.masked_choice(key, alive)
    ok = (ci >= 0) & (alive.sum() > 0)
    ci_safe = jnp.maximum(ci, 0)
    new_alive = alive.at[ci_safe].set(jnp.where(ok, False, alive[ci_safe]))
    state = dict(state)
    state["call_alive"] = new_alive
    return state


def _fixup_lens(state):
    """Recompute LEN slots that measure a device DATA slot after data
    mutation (the device analogue of assignSizesCall for the direct
    (buf, len) pairs the tensor encoding links; reference:
    prog/size.go:40-117).  Skipped when a LEN slot was itself mutated,
    matching the reference's preserve contract."""
    lt = state["len_target"]
    is_link = (state["kind"] == LEN) & (lt >= 0)
    tgt = jnp.maximum(lt, 0)
    # val = bytes * 8 / bit_size (aux1; 1 = bit-length fields, 8 =
    # byte lengths), matching generate_size for buffer targets
    # (reference: prog/size.go:11-34).
    bits = state["len_"][tgt].astype(U64) << U64(3)
    gran = jnp.maximum(state["aux1"], U64(1))
    # Arena-bounded lengths (< 2^24) divide exactly: shift for pow2
    # granularity (the only kind the DSL emits), f32 otherwise
    # (no u64 div on TPU).
    log2 = U64(63) - lax.clz(gran).astype(U64)
    is_pow2 = (gran & (gran - U64(1))) == U64(0)
    approx = (bits.astype(jnp.float32) / gran.astype(jnp.float32)).astype(U64)
    fix = jnp.where(is_pow2, bits >> log2, approx)
    take = is_link & ~state["preserve_sizes"]
    state = dict(state)
    state["val"] = jnp.where(take, fix, state["val"])
    # A fixed-up LEN only counts as changed when its measured data
    # actually changed (otherwise fix == the template value).
    state["touched"] = state["touched"] | (take & state["touched"][tgt])
    return state


def _mutate_one(state, key, flag_vals, flag_counts, rounds):
    """The outer weighted loop (reference: prog/mutation.go:19-132),
    restricted to device ops: 10/11 mutate-arg, 1/11 remove-call, with
    a 1/3 stop coin per round, bounded at `rounds`."""
    state = dict(state)
    state["preserve_sizes"] = jnp.bool_(False)
    # Per-slot change journal: lets the pipeline ship sparse deltas
    # instead of full rows over the (slow) host link (ops/delta.py).
    state["touched"] = jnp.zeros(state["kind"].shape[0], dtype=jnp.bool_)

    # The loop carries ONLY the mutable leaves (~3.7 KB: val, arena,
    # len_, call_alive, journals) — carrying the full state dict would
    # stream the immutable ~8 KB (kind/aux/off/cap/...) through HBM
    # every round and select over it for nothing.
    mut_keys = ("val", "arena", "len_", "call_alive",
                "preserve_sizes", "touched")

    def body(i, carry):
        st = dict(state)
        st.update(zip(mut_keys, carry[0]))
        active = carry[1]
        kk = random.fold_in(key, i)
        k_op, k_do, k_stop = random.split(kk, 3)
        do_remove = d.n_out_of(k_op, 1, 11)
        mutated = _mutate_slot(k_do, st, flag_vals, flag_counts)
        removed = _remove_call(k_do, st)
        pick = lambda a, b, c: jnp.where(
            active, jnp.where(do_remove, b, a), c)
        new_mut = tuple(pick(mutated[k], removed[k], st[k])
                        for k in mut_keys)
        active = active & ~d.one_of(k_stop, 3)
        return new_mut, active

    carry0 = tuple(state[k] for k in mut_keys)
    carry, _ = lax.fori_loop(0, rounds, body, (carry0, jnp.bool_(True)))
    state.update(zip(mut_keys, carry))
    return _fixup_lens(state)


def make_mutator(rounds: int = 4, backend: str | None = None):
    """Build the jitted batched mutator.

    mutate_batch(batch, key, flag_vals, flag_counts) -> batch
    where batch is a dict of stacked program-tensor arrays.

    `backend` selects the execution shape, not the math: "vmap" is
    the batched-switch path below, "pallas" runs the same
    `_mutate_one` one grid cell per program (ops/pallas_mutate —
    real branches on TPU, interpret-mode fallback elsewhere), and
    None resolves TZ_MUTATE_BACKEND=pallas|vmap|auto (auto = Pallas
    only on TPU).  Both paths are bit-exact over the same key."""
    from syzkaller_tpu.ops.pallas_mutate import (
        make_pallas_mutator,
        resolve_mutate_backend,
    )

    if resolve_mutate_backend(backend) == "pallas":
        return make_pallas_mutator(rounds)

    @functools.partial(jax.jit, static_argnames=())
    def mutate_batch(batch: dict, key, flag_vals, flag_counts) -> dict:
        b = batch["kind"].shape[0]
        keys = random.split(key, b)
        fn = lambda state, k: _mutate_one(state, k, flag_vals, flag_counts,
                                          rounds)
        return jax.vmap(fn)(batch, keys)

    return mutate_batch
