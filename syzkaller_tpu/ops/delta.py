"""Sparse-delta transfer format for device mutants.

Full mutated rows are ~12 KB (val/len/arena/call tables); the host
link to a tunneled TPU runs at ~40 MB/s with ~20 ms per-transfer
latency (measured), which caps full-row draining at ~3k mutants/s.
But one mutation round touches at most `rounds` slots, so each mutant
is shipped as ONE fixed-layout byte row holding only:

  header    template index, change counts, flags, op class, donor
            bank index + insert position, call-alive bitmap, payload
            pool slot (-1 = no data changes)
  values    up to K (slot, value) pairs (touched value slots,
            including device-recomputed LEN fixups)
  data      up to D (slot, new_len, payload_off) entries
  payload   POOLED: only ~6% of mutants change data bytes (measured),
            so payload space is a shared pool of B/pool_div slots of P
            bytes each, claimed by prefix-sum over the batch — the
            other 94% of rows ship just the ~228-byte core.  This is
            what makes the tunneled host link (~9 MB/s synchronous)
            stop being the pipeline ceiling.

Op classes: OP_MUTATE (value/data/remove mutation of the template) and
OP_INSERT (donor, pos valid: splice the donor block's exec segment at
alive-call boundary pos — ops/insert.py).

Two transfer layouts share the same row format: make_pooler returns a
single flat uint8 array (rows ++ pool, one transfer — the sharded
mesh path), while make_compact_pooler returns rows, pool, and the
claimed-slot count separately so the pipeline fetches only the
power-of-two `pool_bucket` prefix of the pool a batch actually used
(compacted D2H; the prefix-sum assignment packs claimed slots at the
front).  The host reconstructs exec bytes by patching the template
stream (ops/emit.assemble_delta) and rebuilds full tensor rows only
for the rare triaged mutant (reference volume argument: triage is
~1/1000 of executions, syz-fuzzer/proc.go:100).

Mutants whose change set exceeds K/D/P — or that lose the race for a
pool slot — are flagged OVERFLOW and dropped (counted; with rounds=4,
max_blob<=P and pool_div=8 vs the ~6% data rate, this is rare by
construction).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np


def _infer_batch(total: int, spec: DeltaSpec) -> int:
    """Solve batch size from a flat rows++pool buffer length:
    total == B*row_bytes + max(1, B//pool_div)*P.  Solve for each
    plausible pool-slot count q (the floor-division makes the direct
    inverse inexact by up to pool_div-1 rows)."""
    q_est = max(1, total // (spec.row_bytes * spec.pool_div + spec.P))
    for q in range(max(1, q_est - 2), q_est + 3):
        rem = total - q * spec.P
        if rem <= 0 or rem % spec.row_bytes:
            continue
        b = rem // spec.row_bytes
        if spec.pool_slots(b) == q and spec.batch_bytes(b) == total:
            return b
    raise ValueError(f"cannot infer batch size from {total} bytes")

FLAG_OVERFLOW = 1
FLAG_PRESERVE = 2

OP_MUTATE = 0
OP_INSERT = 1

# nvals ndata flags op | template_idx | alive_bits | donor | pos pad3
# | pool_idx
HDR_BYTES = 28


@dataclass(frozen=True)
class DeltaSpec:
    """Static layout of one delta row + the shared payload pool."""

    K: int = 16  # max changed value slots
    D: int = 4  # max changed data slots
    P: int = 1024  # payload bytes per pool slot (8-aligned)
    pool_div: int = 8  # pool slots = batch_size // pool_div

    @property
    def row_bytes(self) -> int:
        # hdr + val_idx(2K) + vals(8K) + data_slot(2D) +
        # data_len(4D) + data_off(4D); payload lives in the pool
        return HDR_BYTES + 10 * self.K + 10 * self.D

    def pool_slots(self, batch_size: int) -> int:
        return max(1, batch_size // self.pool_div)

    def batch_bytes(self, batch_size: int) -> int:
        return batch_size * self.row_bytes + \
            self.pool_slots(batch_size) * self.P

    # Field offsets within a row.
    @property
    def o_val_idx(self) -> int:
        return HDR_BYTES

    @property
    def o_vals(self) -> int:
        return HDR_BYTES + 2 * self.K

    @property
    def o_data_slot(self) -> int:
        return HDR_BYTES + 10 * self.K

    @property
    def o_data_len(self) -> int:
        return self.o_data_slot + 2 * self.D

    @property
    def o_data_off(self) -> int:
        return self.o_data_len + 4 * self.D


def make_packer(spec: DeltaSpec):
    """Device-side packer: (state, template_idx) -> uint8[ROW].
    vmap-able; all static shapes, rolls instead of dynamic scatters."""
    import jax.numpy as jnp
    from jax import lax

    from syzkaller_tpu.ops.mutate import _roll_left, _roll_right
    from syzkaller_tpu.ops.tensor import DATA, EMPTY

    K, D, P = spec.K, spec.D, spec.P
    p_bits = max((P - 1).bit_length(), 1)

    def u8cast(x):
        b = lax.bitcast_convert_type(x, jnp.uint8)
        return b.reshape(-1)

    def compact(mask, M):
        """Indices of the first M set positions (-1 padded), + count."""
        S = mask.shape[0]
        r = jnp.cumsum(mask) - 1
        tgt = jnp.where(mask, jnp.minimum(r, M - 1), M)
        idx = jnp.full(M, -1, jnp.int32).at[tgt].set(
            jnp.arange(S, dtype=jnp.int32), mode="drop")
        return idx, mask.sum()

    def pack(state, template_idx, op=None, donor=None, pos=None):
        kind = state["kind"]
        touched = state["touched"]
        if op is None:
            op = jnp.uint8(0)
        if donor is None:
            donor = jnp.int32(-1)
        if pos is None:
            pos = jnp.uint8(0)
        # Insert rows carry no state changes: mask the journals.
        is_ins = op != 0
        val_changed = touched & (kind != DATA) & (kind != EMPTY) & ~is_ins
        data_changed = touched & (kind == DATA) & ~is_ins

        val_idx, nvals = compact(val_changed, K)
        vals = state["val"][jnp.maximum(val_idx, 0)]
        vals = jnp.where(val_idx >= 0, vals, jnp.uint64(0))

        data_idx, ndata = compact(data_changed, D)
        lens = state["len_"][jnp.maximum(data_idx, 0)]
        lens = jnp.where(data_idx >= 0, lens, 0)
        pads = (lens + 7) & ~7
        offs = jnp.concatenate(
            [jnp.zeros(1, lens.dtype), jnp.cumsum(pads)[:-1]])
        total = pads.sum()

        arena = state["arena"]
        a_bits = max(int(arena.shape[0] - 1).bit_length(), 1)
        payload = jnp.zeros(P, jnp.uint8)
        pidx = jnp.arange(P, dtype=jnp.int32)
        for k in range(D):
            slot = jnp.maximum(data_idx[k], 0)
            src = _roll_left(arena, state["off"][slot], a_bits)
            win = src[:P] if arena.shape[0] >= P else jnp.pad(
                src, (0, P - arena.shape[0]))
            placed = _roll_right(win, offs[k], p_bits)
            mask = (data_idx[k] >= 0) & (pidx >= offs[k]) \
                & (pidx < offs[k] + lens[k])
            payload = jnp.where(mask, placed, payload)

        overflow = (nvals > K) | (ndata > D) | (total > P)
        flags = jnp.where(overflow, FLAG_OVERFLOW, 0).astype(jnp.uint8) \
            | jnp.where(state["preserve_sizes"],
                        FLAG_PRESERVE, 0).astype(jnp.uint8)
        C = state["call_alive"].shape[0]
        alive_bits = jnp.sum(
            jnp.where(state["call_alive"],
                      jnp.uint64(1) << jnp.arange(C, dtype=jnp.uint64),
                      jnp.uint64(0)))

        hdr = jnp.concatenate([
            jnp.stack([jnp.minimum(nvals, 255).astype(jnp.uint8),
                       jnp.minimum(ndata, 255).astype(jnp.uint8),
                       flags, jnp.asarray(op, jnp.uint8)]),
            u8cast(template_idx.astype(jnp.int32)),
            u8cast(alive_bits),
            u8cast(jnp.asarray(donor, jnp.int32)),
            jnp.stack([jnp.asarray(pos, jnp.uint8),
                       jnp.uint8(0), jnp.uint8(0), jnp.uint8(0)]),
            u8cast(jnp.int32(-1)),  # pool_idx: assigned by pack_pool
        ])
        row = jnp.concatenate([
            hdr,
            u8cast(val_idx.astype(jnp.int16)),
            u8cast(vals),
            u8cast(data_idx.astype(jnp.int16)),
            u8cast(lens.astype(jnp.int32)),
            u8cast(offs.astype(jnp.int32)),
        ])
        needs_pool = (ndata > 0) & ~overflow
        return row, payload, needs_pool

    return pack


def make_pooler(spec: DeltaSpec, batch_size: int):
    """Batch-level pool assignment: rows claim payload slots by prefix
    sum, losers are flagged OVERFLOW, and the result is ONE flat uint8
    buffer (rows ++ pool) — the single device->host transfer."""
    import jax.numpy as jnp

    POOL = spec.pool_slots(batch_size)
    assign = _make_pool_assigner(spec, POOL)

    def pool_batch(rows, payloads, needs):
        rows, pool, _n_used = assign(rows, payloads, needs)
        return jnp.concatenate([rows.reshape(-1), pool.reshape(-1)])

    return pool_batch


def make_compact_pooler(spec: DeltaSpec, batch_size: int):
    """Compacted-D2H variant of make_pooler: identical prefix-sum pool
    assignment, but rows, pool, and the used-slot count come back as
    SEPARATE device arrays.  The prefix sum packs every claimed slot at
    the front of the pool, so the host only fetches the
    `pool_bucket(n_used)` prefix — a power-of-two slot count, keeping
    the transfer-shape set static (log2(POOL) variants, nothing
    re-jits) while the ~94% of batches that touch few payload slots
    stop shipping a full pool over the latency-bound link."""
    import jax.numpy as jnp

    return _make_pool_assigner(spec, spec.pool_slots(batch_size))


def _make_pool_assigner(spec: DeltaSpec, POOL: int):
    import jax.numpy as jnp
    from jax import lax

    def assign(rows, payloads, needs):
        idx = jnp.cumsum(needs.astype(jnp.int32)) - 1
        pool_idx = jnp.where(needs, idx, -1)
        lost = pool_idx >= POOL
        pool_idx = jnp.where(lost, -1, pool_idx)
        flags = rows[:, 2] | jnp.where(
            lost, jnp.uint8(FLAG_OVERFLOW), jnp.uint8(0))
        rows = rows.at[:, 2].set(flags)
        pidx_u8 = lax.bitcast_convert_type(
            pool_idx.astype(jnp.int32)[:, None], jnp.uint8)
        rows = rows.at[:, 24:28].set(pidx_u8.reshape(-1, 4))
        scatter = jnp.where(pool_idx >= 0, pool_idx, POOL)
        pool = jnp.zeros((POOL + 1, spec.P), jnp.uint8) \
            .at[scatter].set(payloads, mode="drop")[:POOL]
        n_used = jnp.minimum(
            needs.astype(jnp.int32).sum(), jnp.int32(POOL))
        return rows, pool, n_used

    return assign


def compact_rows(rows, keep):
    """Scatter-compact the kept rows to the array front (the fused
    mutate→emit-compact path, ISSUE 10): row i with keep[i] moves to
    slot `cumsum(keep)[i]-1`, dropped rows are overwritten by zeros,
    and the kept count comes back as a device scalar.  The same
    static-shape discipline as the pool prefix sum above — the host
    then fetches only the `pow2_rows(n_kept)` row prefix, so a batch
    where the mutant plane drops 95% of rows ships 1/16th of the
    bytes without any shape churn.  Returns (rows', n_kept)."""
    import jax.numpy as jnp

    tgt = jnp.where(keep, jnp.cumsum(keep.astype(jnp.int32)) - 1,
                    rows.shape[0])
    out = jnp.zeros_like(rows).at[tgt].set(rows, mode="drop")
    return out, keep.astype(jnp.int32).sum()


def pow2_rows(n: int, lo: int = 1, hi: Optional[int] = None) -> int:
    """Power-of-two row bucket covering `n`, clamped to [lo, hi].

    The one bucketing rule every transfer on both hot paths follows
    (the compacted pool fetch below, the triage flush batches, the
    corpus-flush scatter staging in ops/staging): a pow2 row count
    keeps each transfer's shape set bounded at log2(hi/lo)+1
    variants, so arena buffers are reused and nothing ever re-jits on
    a varying batch size."""
    b = 1 << max(0, (max(int(n), max(1, lo)) - 1).bit_length())
    if hi is not None:
        b = min(b, int(hi))
    return b


def pool_bucket(n_used: int, pool_slots: int) -> int:
    """Power-of-two transfer bucket covering `n_used` claimed payload
    slots (0 = nothing to fetch).  Bucketing keeps the D2H slice-shape
    set static so the pool fetch never compiles more than
    log2(pool_slots)+1 distinct slices."""
    n = int(n_used)
    if n <= 0:
        return 0
    return pow2_rows(n, lo=1, hi=int(pool_slots))


class DeltaBatch:
    """Host view over a fetched flat delta buffer (rows ++ payload
    pool) — pure numpy slicing, no per-mutant parsing."""

    def __init__(self, flat: np.ndarray, spec: DeltaSpec,
                 batch_size: Optional[int] = None,
                 pool: Optional[np.ndarray] = None):
        if flat.ndim == 2:
            # already-split rows: pool is the separately-fetched
            # (possibly bucket-compacted) payload array, or absent
            # entirely (pool-free test path).
            if flat.shape[1] != spec.row_bytes:
                raise ValueError(
                    f"row width {flat.shape[1]} != spec {spec.row_bytes}")
            batch_size = flat.shape[0]
        else:
            if batch_size is None:
                # solve B from the flat length (row+pool layout)
                batch_size = _infer_batch(flat.size, spec)
            elif flat.size != spec.batch_bytes(batch_size):
                raise ValueError(
                    f"flat buffer {flat.size} bytes != batch_bytes"
                    f"({batch_size}) = {spec.batch_bytes(batch_size)}")
        self.spec = spec
        if flat.ndim == 1:
            nrow = batch_size * spec.row_bytes
            buf = flat[:nrow].reshape(batch_size, spec.row_bytes)
            self._pool = flat[nrow:].reshape(-1, spec.P)
        else:
            buf = flat
            if pool is not None:
                if pool.ndim != 2 or pool.shape[1] != spec.P:
                    raise ValueError(
                        f"pool shape {pool.shape} != (*, {spec.P})")
                self._pool = pool
            else:
                self._pool = np.zeros((0, spec.P), np.uint8)
        self.buf = buf
        self.nvals = buf[:, 0]
        self.ndata = buf[:, 1]
        self.flags = buf[:, 2]
        self.op = buf[:, 3]
        self.template_idx = buf[:, 4:8].copy().view("<i4")[:, 0]
        self.alive_bits = buf[:, 8:16].copy().view("<u8")[:, 0]
        self.donor = buf[:, 16:20].copy().view("<i4")[:, 0]
        self.pos = buf[:, 20]
        self.pool_idx = buf[:, 24:28].copy().view("<i4")[:, 0]
        o = spec.o_val_idx
        self.val_idx = buf[:, o:o + 2 * spec.K].copy().view("<i2")
        o = spec.o_vals
        self.vals = buf[:, o:o + 8 * spec.K].copy().view("<u8")
        o = spec.o_data_slot
        self.data_slot = buf[:, o:o + 2 * spec.D].copy().view("<i2")
        o = spec.o_data_len
        self.data_len = buf[:, o:o + 4 * spec.D].copy().view("<i4")
        o = spec.o_data_off
        self.data_off = buf[:, o:o + 4 * spec.D].copy().view("<i4")
        self._payload = None
        # Lineage trace context (telemetry/lineage.py), attached by
        # the pipeline at fetch time: one per batch, None when the
        # batch is unsampled.  Every ExecMutant of the batch reads it
        # through this reference — zero per-mutant storage.
        self.trace = None

    @property
    def payload(self) -> np.ndarray:
        """[B, P] per-mutant payload view, gathered from the pool on
        first use (rows without data changes read zeros)."""
        if self._payload is None:
            if len(self._pool) == 0:
                self._payload = np.zeros(
                    (self.buf.shape[0], self.spec.P), np.uint8)
            else:
                idx = np.clip(self.pool_idx, 0, len(self._pool) - 1)
                gathered = self._pool[idx]
                gathered[self.pool_idx < 0] = 0
                self._payload = gathered
        return self._payload

    def __len__(self) -> int:
        return self.buf.shape[0]

    def overflowed(self, j: int) -> bool:
        return bool(self.flags[j] & FLAG_OVERFLOW)

    def preserve_sizes(self, j: int) -> bool:
        return bool(self.flags[j] & FLAG_PRESERVE)

    def call_alive(self, j: int, max_calls: int) -> np.ndarray:
        bits = self.alive_bits[j]
        return ((bits >> np.arange(max_calls, dtype=np.uint64)) & 1) \
            .astype(bool)

    def rebuild_row(self, j: int, template) -> dict:
        """Full tensor row for mutant j from its template + the delta
        (used only for triage decode)."""
        row = {k: np.array(v, copy=True) for k, v in
               template.arrays().items()}
        for i in range(int(self.nvals[j])):
            s = int(self.val_idx[j, i])
            if s >= 0:
                row["val"][s] = self.vals[j, i]
        for i in range(int(self.ndata[j])):
            s = int(self.data_slot[j, i])
            if s < 0:
                continue
            ln = int(self.data_len[j, i])
            off = int(row["off"][s])
            po = int(self.data_off[j, i])
            row["len_"][s] = ln
            row["arena"][off:off + ln] = self.payload[j, po:po + ln]
        row["call_alive"] = self.call_alive(
            j, template.call_alive.shape[0])
        row["preserve_sizes"] = np.bool_(self.preserve_sizes(j))
        return row
