"""Sparse-delta transfer format for device mutants.

Full mutated rows are ~12 KB (val/len/arena/call tables); the host
link to a tunneled TPU runs at ~40 MB/s with ~20 ms per-transfer
latency (measured), which caps full-row draining at ~3k mutants/s.
But one mutation round touches at most `rounds` slots, so each mutant
is shipped as ONE fixed-layout byte row holding only:

  header    template index, change counts, flags, op class, donor
            bank index + insert position, call-alive bitmap
  values    up to K (slot, value) pairs (touched value slots,
            including device-recomputed LEN fixups)
  data      up to D (slot, new_len, payload_off) entries
  payload   the changed data spans' bytes, 8-aligned, capped at P

Op classes: OP_MUTATE (value/data/remove mutation of the template) and
OP_INSERT (donor, pos valid: splice the donor block's exec segment at
alive-call boundary pos — ops/insert.py).

The whole batch is a single uint8[B, ROW] array — one transfer per
batch.  The host reconstructs exec bytes by patching the template
stream (ops/emit.assemble_delta) and rebuilds full tensor rows only
for the rare triaged mutant (reference volume argument: triage is
~1/1000 of executions, syz-fuzzer/proc.go:100).

Mutants whose change set exceeds K/D/P are flagged OVERFLOW and the
caller re-mutates them host-side (counted; with rounds=4 and
max_blob<=P/2 this is rare by construction).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

FLAG_OVERFLOW = 1
FLAG_PRESERVE = 2

OP_MUTATE = 0
OP_INSERT = 1

HDR_BYTES = 24  # nvals ndata flags op | template_idx | alive_bits | donor pos pad3


@dataclass(frozen=True)
class DeltaSpec:
    """Static layout of one delta row."""

    K: int = 16  # max changed value slots
    D: int = 4  # max changed data slots
    P: int = 2048  # payload bytes (8-aligned)

    @property
    def row_bytes(self) -> int:
        # hdr + val_idx(2K) + vals(8K) + data_slot(2D) +
        # data_len(4D) + data_off(4D) + payload(P)
        return HDR_BYTES + 10 * self.K + 10 * self.D + self.P

    # Field offsets within a row.
    @property
    def o_val_idx(self) -> int:
        return HDR_BYTES

    @property
    def o_vals(self) -> int:
        return HDR_BYTES + 2 * self.K

    @property
    def o_data_slot(self) -> int:
        return HDR_BYTES + 10 * self.K

    @property
    def o_data_len(self) -> int:
        return self.o_data_slot + 2 * self.D

    @property
    def o_data_off(self) -> int:
        return self.o_data_len + 4 * self.D

    @property
    def o_payload(self) -> int:
        return self.o_data_off + 4 * self.D


def make_packer(spec: DeltaSpec):
    """Device-side packer: (state, template_idx) -> uint8[ROW].
    vmap-able; all static shapes, rolls instead of dynamic scatters."""
    import jax.numpy as jnp
    from jax import lax

    from syzkaller_tpu.ops.mutate import _roll_left, _roll_right
    from syzkaller_tpu.ops.tensor import DATA, EMPTY

    K, D, P = spec.K, spec.D, spec.P
    p_bits = max((P - 1).bit_length(), 1)

    def u8cast(x):
        b = lax.bitcast_convert_type(x, jnp.uint8)
        return b.reshape(-1)

    def compact(mask, M):
        """Indices of the first M set positions (-1 padded), + count."""
        S = mask.shape[0]
        r = jnp.cumsum(mask) - 1
        tgt = jnp.where(mask, jnp.minimum(r, M - 1), M)
        idx = jnp.full(M, -1, jnp.int32).at[tgt].set(
            jnp.arange(S, dtype=jnp.int32), mode="drop")
        return idx, mask.sum()

    def pack(state, template_idx, op=None, donor=None, pos=None):
        kind = state["kind"]
        touched = state["touched"]
        if op is None:
            op = jnp.uint8(0)
        if donor is None:
            donor = jnp.int32(-1)
        if pos is None:
            pos = jnp.uint8(0)
        # Insert rows carry no state changes: mask the journals.
        is_ins = op != 0
        val_changed = touched & (kind != DATA) & (kind != EMPTY) & ~is_ins
        data_changed = touched & (kind == DATA) & ~is_ins

        val_idx, nvals = compact(val_changed, K)
        vals = state["val"][jnp.maximum(val_idx, 0)]
        vals = jnp.where(val_idx >= 0, vals, jnp.uint64(0))

        data_idx, ndata = compact(data_changed, D)
        lens = state["len_"][jnp.maximum(data_idx, 0)]
        lens = jnp.where(data_idx >= 0, lens, 0)
        pads = (lens + 7) & ~7
        offs = jnp.concatenate(
            [jnp.zeros(1, lens.dtype), jnp.cumsum(pads)[:-1]])
        total = pads.sum()

        arena = state["arena"]
        a_bits = max(int(arena.shape[0] - 1).bit_length(), 1)
        payload = jnp.zeros(P, jnp.uint8)
        pidx = jnp.arange(P, dtype=jnp.int32)
        for k in range(D):
            slot = jnp.maximum(data_idx[k], 0)
            src = _roll_left(arena, state["off"][slot], a_bits)
            win = src[:P] if arena.shape[0] >= P else jnp.pad(
                src, (0, P - arena.shape[0]))
            placed = _roll_right(win, offs[k], p_bits)
            mask = (data_idx[k] >= 0) & (pidx >= offs[k]) \
                & (pidx < offs[k] + lens[k])
            payload = jnp.where(mask, placed, payload)

        overflow = (nvals > K) | (ndata > D) | (total > P)
        flags = jnp.where(overflow, FLAG_OVERFLOW, 0).astype(jnp.uint8) \
            | jnp.where(state["preserve_sizes"],
                        FLAG_PRESERVE, 0).astype(jnp.uint8)
        C = state["call_alive"].shape[0]
        alive_bits = jnp.sum(
            jnp.where(state["call_alive"],
                      jnp.uint64(1) << jnp.arange(C, dtype=jnp.uint64),
                      jnp.uint64(0)))

        hdr = jnp.concatenate([
            jnp.stack([jnp.minimum(nvals, 255).astype(jnp.uint8),
                       jnp.minimum(ndata, 255).astype(jnp.uint8),
                       flags, jnp.asarray(op, jnp.uint8)]),
            u8cast(template_idx.astype(jnp.int32)),
            u8cast(alive_bits),
            u8cast(jnp.asarray(donor, jnp.int32)),
            jnp.stack([jnp.asarray(pos, jnp.uint8),
                       jnp.uint8(0), jnp.uint8(0), jnp.uint8(0)]),
        ])
        row = jnp.concatenate([
            hdr,
            u8cast(val_idx.astype(jnp.int16)),
            u8cast(vals),
            u8cast(data_idx.astype(jnp.int16)),
            u8cast(lens.astype(jnp.int32)),
            u8cast(offs.astype(jnp.int32)),
            payload,
        ])
        return row

    return pack


class DeltaBatch:
    """Host view over a fetched uint8[B, ROW] delta batch — pure numpy
    slicing, no per-mutant parsing."""

    def __init__(self, buf: np.ndarray, spec: DeltaSpec):
        assert buf.ndim == 2 and buf.shape[1] == spec.row_bytes
        self.spec = spec
        self.buf = buf
        self.nvals = buf[:, 0]
        self.ndata = buf[:, 1]
        self.flags = buf[:, 2]
        self.op = buf[:, 3]
        self.template_idx = buf[:, 4:8].copy().view("<i4")[:, 0]
        self.alive_bits = buf[:, 8:16].copy().view("<u8")[:, 0]
        self.donor = buf[:, 16:20].copy().view("<i4")[:, 0]
        self.pos = buf[:, 20]
        o = spec.o_val_idx
        self.val_idx = buf[:, o:o + 2 * spec.K].copy().view("<i2")
        o = spec.o_vals
        self.vals = buf[:, o:o + 8 * spec.K].copy().view("<u8")
        o = spec.o_data_slot
        self.data_slot = buf[:, o:o + 2 * spec.D].copy().view("<i2")
        o = spec.o_data_len
        self.data_len = buf[:, o:o + 4 * spec.D].copy().view("<i4")
        o = spec.o_data_off
        self.data_off = buf[:, o:o + 4 * spec.D].copy().view("<i4")
        self.payload = buf[:, spec.o_payload:]

    def __len__(self) -> int:
        return self.buf.shape[0]

    def overflowed(self, j: int) -> bool:
        return bool(self.flags[j] & FLAG_OVERFLOW)

    def preserve_sizes(self, j: int) -> bool:
        return bool(self.flags[j] & FLAG_PRESERVE)

    def call_alive(self, j: int, max_calls: int) -> np.ndarray:
        bits = self.alive_bits[j]
        return ((bits >> np.arange(max_calls, dtype=np.uint64)) & 1) \
            .astype(bool)

    def rebuild_row(self, j: int, template) -> dict:
        """Full tensor row for mutant j from its template + the delta
        (used only for triage decode)."""
        row = {k: np.array(v, copy=True) for k, v in
               template.arrays().items()}
        for i in range(int(self.nvals[j])):
            s = int(self.val_idx[j, i])
            if s >= 0:
                row["val"][s] = self.vals[j, i]
        for i in range(int(self.ndata[j])):
            s = int(self.data_slot[j, i])
            if s < 0:
                continue
            ln = int(self.data_len[j, i])
            off = int(row["off"][s])
            po = int(self.data_off[j, i])
            row["len_"][s] = ln
            row["arena"][off:off + ln] = self.payload[j, po:po + ln]
        row["call_alive"] = self.call_alive(
            j, template.call_alive.shape[0])
        row["preserve_sizes"] = np.bool_(self.preserve_sizes(j))
        return row
