"""Program tensor: the flat, fixed-shape device encoding of syscall
programs.

The reference mutates a pointer-rich typed tree; TPUs need dense
tensors with static shapes.  A program becomes:

  call table   call_id:int32[C], call_alive:bool[C], ncalls:int32
  slot table   one row per *mutable scalar or data region* discovered
               by a tree walk at encode time:
                 kind:int8[S]       (EMPTY/INT/FLAGS/PROC/LEN/DATA)
                 call:int8[S]       owning call index
                 width:int8[S]      byte width of value slots
                 aux0,aux1:uint64[S] kind-specific (ranges, proc
                                    start/per, data min/max len)
                 flag_set:int32[S]  index into the target flag table
                 val:uint64[S]      current value (value slots)
                 off,len,cap:int32[S] arena span (data slots)
  arena        uint8[A] byte storage for all data slots

The CPU-side codec keeps, per corpus program, the slot->Arg paths
needed to decode a mutated tensor back into a typed Prog (metadata
never ships to the device).  Encode is one tree walk; decode clones
the template and writes mutated values/spans back, then re-runs size
assignment — so exec serialization sees a normal typed program.

This realizes the survey's design: mutation ops become vmap-able
index/scatter ops over these arrays while tree-recursive structure
ops (call insertion, squash, splice) stay on the host
(reference hot loop: prog/mutation.go:14-142; format cousin:
prog/encodingexec.go:7-18).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from syzkaller_tpu.models.checksum import calc_checksums_call
from syzkaller_tpu.models.mutation import MutationArgs
from syzkaller_tpu.models.prog import (
    Call,
    ConstArg,
    DataArg,
    Prog,
    foreach_arg,
)
from syzkaller_tpu.models.size import assign_sizes_call
from syzkaller_tpu.models.types import (
    ArrayType,
    BufferKind,
    BufferType,
    Dir,
    FlagsType,
    IntKind,
    IntType,
    LenType,
    ProcType,
    VmaType,
)

# Slot kinds.
EMPTY, INT, FLAGS, PROC, LEN, DATA = 0, 1, 2, 3, 4, 5

MAX_BLOB_DEVICE = 4096  # per-slot growth cap on device (vs 100K on CPU)


@dataclass
class TensorConfig:
    max_calls: int = 32
    max_slots: int = 224
    arena: int = 8192
    # Per-slot blob ceiling: larger buffers stay host-mutated.  Kept
    # well under the arena so several data slots fit, and bounded so
    # a single mutant's changed spans fit a delta-transfer payload.
    max_blob: int = MAX_BLOB_DEVICE

    def __post_init__(self):
        # The device length-fixup path divides non-power-of-2 LEN
        # granularities in float32 (ops/mutate.py _fixup_lens); the
        # 24-bit mantissa keeps that division exact only while every
        # length stays below 2^24.  Growing past it would produce
        # silently wrong length words in exec streams — fail loudly at
        # config time instead (VERDICT r2 weak #6).
        assert self.arena < (1 << 24) and self.max_blob < (1 << 24), \
            "arena/max_blob must stay < 2^24 (f32-exact device division)"

    def like(self) -> dict:
        return dict(max_calls=self.max_calls, max_slots=self.max_slots,
                    arena=self.arena, max_blob=self.max_blob)


@dataclass
class FlagTables:
    """Global flag-set value table shared by a whole target."""

    vals: np.ndarray  # uint64[NF, MAXV]
    counts: np.ndarray  # int32[NF]
    index: dict[tuple[int, ...], int]

    @classmethod
    def empty(cls, maxv: int = 16) -> "FlagTables":
        return cls(np.zeros((1, maxv), dtype=np.uint64),
                   np.zeros(1, dtype=np.int32), {})

    def intern(self, vals: tuple[int, ...]) -> int:
        key = tuple(vals)
        idx = self.index.get(key)
        if idx is not None:
            return idx
        maxv = self.vals.shape[1]
        row = np.zeros(maxv, dtype=np.uint64)
        n = min(len(vals), maxv)
        row[:n] = np.array(vals[:n], dtype=np.uint64)
        self.vals = np.vstack([self.vals, row[None]])
        self.counts = np.append(self.counts, np.int32(n))
        idx = len(self.counts) - 1
        self.index[key] = idx
        return idx


@dataclass
class ProgTensor:
    """Host (numpy) form of one encoded program."""

    cfg: TensorConfig
    call_id: np.ndarray
    call_alive: np.ndarray
    ncalls: int
    kind: np.ndarray
    call: np.ndarray
    width: np.ndarray
    aux0: np.ndarray
    aux1: np.ndarray
    flag_set: np.ndarray
    val: np.ndarray
    off: np.ndarray
    len_: np.ndarray
    cap: np.ndarray
    len_target: np.ndarray  # int32[S]: for LEN slots, the DATA slot they
    # measure (-1 if none) — lets the device recompute length fields
    # after data mutation without a host size-assignment pass.
    arena: np.ndarray
    # CPU-only metadata: per slot, the path to the Arg in the template.
    template: Prog = None  # type: ignore[assignment]
    slot_args: list = field(default_factory=list)

    def arrays(self) -> dict[str, np.ndarray]:
        return dict(call_id=self.call_id, call_alive=self.call_alive,
                    ncalls=np.int32(self.ncalls), kind=self.kind,
                    call=self.call, width=self.width, aux0=self.aux0,
                    aux1=self.aux1, flag_set=self.flag_set, val=self.val,
                    off=self.off, len_=self.len_, cap=self.cap,
                    len_target=self.len_target, arena=self.arena)


class ProgramTooLarge(Exception):
    pass


def encode_prog(p: Prog, cfg: TensorConfig, flags: FlagTables) -> ProgTensor:
    """Flatten a typed program into tensor form.  Walks the same arg set
    the reference's mutationArgs collector visits
    (reference: prog/mutation.go:345-392), so device-mutable slots
    match what Mutate would touch."""
    if len(p.calls) > cfg.max_calls:
        raise ProgramTooLarge(f"{len(p.calls)} calls > {cfg.max_calls}")
    t = ProgTensor(
        cfg=cfg,
        call_id=np.full(cfg.max_calls, -1, dtype=np.int32),
        call_alive=np.zeros(cfg.max_calls, dtype=bool),
        ncalls=len(p.calls),
        kind=np.zeros(cfg.max_slots, dtype=np.int8),
        call=np.zeros(cfg.max_slots, dtype=np.int8),
        width=np.zeros(cfg.max_slots, dtype=np.int8),
        aux0=np.zeros(cfg.max_slots, dtype=np.uint64),
        aux1=np.zeros(cfg.max_slots, dtype=np.uint64),
        flag_set=np.full(cfg.max_slots, -1, dtype=np.int32),
        val=np.zeros(cfg.max_slots, dtype=np.uint64),
        off=np.zeros(cfg.max_slots, dtype=np.int32),
        len_=np.zeros(cfg.max_slots, dtype=np.int32),
        cap=np.zeros(cfg.max_slots, dtype=np.int32),
        len_target=np.full(cfg.max_slots, -1, dtype=np.int32),
        arena=np.zeros(cfg.arena, dtype=np.uint8),
        template=p,
    )
    slot = 0
    arena_pos = 0
    len_measures: dict[int, int] = {}  # slot -> id(measured inner arg)

    for ci, c in enumerate(p.calls):
        t.call_id[ci] = c.meta.id
        t.call_alive[ci] = True
        # Calls carrying inet checksums bake chunk sizes into their
        # exec csum instructions; device data-length mutation would
        # leave those stale, so their data stays host-mutated
        # (value slots are still fine: they never change sizes).
        has_csum = calc_checksums_call(c) is not None
        # Collect device-mutable args exactly as MutationArgs does.
        ma = MutationArgs(p.target)
        foreach_arg(c, ma.collect)
        for arg, ctx in zip(ma.args, ma.ctxes):
            typ = arg.typ
            row: Optional[dict] = None
            if isinstance(typ, IntType) and isinstance(arg, ConstArg):
                row = dict(kind=INT, width=typ.type_size,
                           aux0=typ.range_begin, aux1=typ.range_end,
                           val=arg.val)
                if typ.kind != IntKind.RANGE:
                    row["aux0"] = row["aux1"] = 0
            elif isinstance(typ, FlagsType) and isinstance(arg, ConstArg):
                row = dict(kind=FLAGS, width=typ.type_size,
                           flag_set=flags.intern(typ.vals), val=arg.val)
            elif isinstance(typ, ProcType) and isinstance(arg, ConstArg):
                row = dict(kind=PROC, width=typ.type_size,
                           aux0=typ.values_start, aux1=typ.values_per_proc,
                           val=arg.val)
            elif isinstance(typ, LenType) and isinstance(arg, ConstArg):
                elem_size, measured, ok = _len_elem_size(typ, ctx)
                if not ok:
                    continue
                # aux0: element scale for mutate_size; aux1: the
                # LenType bit granularity for the device length fixup
                # (val = bytes * 8 / aux1, matching generate_size;
                # reference: prog/size.go:11-34).
                row = dict(kind=LEN, width=typ.type_size, aux0=elem_size,
                           aux1=(typ.bit_size or 8), val=arg.val)
                if measured is not None:
                    len_measures[slot] = id(measured)
            elif isinstance(typ, BufferType) and isinstance(arg, DataArg) \
                    and typ.dir != Dir.OUT and not has_csum:
                if typ.kind in (BufferKind.BLOB_RAND, BufferKind.BLOB_RANGE) \
                        or (typ.kind == BufferKind.STRING and not typ.values):
                    data = bytes(arg.data)
                    min_len, max_len = 0, cfg.max_blob
                    if typ.kind == BufferKind.BLOB_RANGE:
                        min_len, max_len = typ.range_begin, \
                            min(typ.range_end, cfg.max_blob)
                    elif typ.kind == BufferKind.STRING and typ.type_size:
                        min_len = max_len = typ.type_size
                    if len(data) > cfg.max_blob:
                        continue  # oversized blob: CPU-only mutation
                    cap = min(_round_cap(max(len(data) * 2, 64)),
                              cfg.arena - arena_pos, max_len)
                    cap = max(cap, len(data))
                    if arena_pos + cap > cfg.arena:
                        continue  # arena full: slot stays CPU-only
                    t.arena[arena_pos:arena_pos + len(data)] = \
                        np.frombuffer(data, dtype=np.uint8)
                    row = dict(kind=DATA, off=arena_pos, len_=len(data),
                               cap=cap, aux0=min_len, aux1=max_len)
                    arena_pos += cap
            if row is None:
                continue
            if slot >= cfg.max_slots:
                raise ProgramTooLarge("slot table full")
            t.kind[slot] = row.get("kind", EMPTY)
            t.call[slot] = ci
            t.width[slot] = row.get("width", 0)
            t.aux0[slot] = np.uint64(row.get("aux0", 0))
            t.aux1[slot] = np.uint64(row.get("aux1", 0))
            t.flag_set[slot] = row.get("flag_set", -1)
            t.val[slot] = np.uint64(row.get("val", 0))
            t.off[slot] = row.get("off", 0)
            t.len_[slot] = row.get("len_", 0)
            t.cap[slot] = row.get("cap", 0)
            t.slot_args.append(arg)
            slot += 1
    # Pad slot_args so indices line up with slot table rows.
    assert len(t.slot_args) == slot
    # Wire LEN slots to the DATA slot they measure (when both are
    # device-resident) so the device can keep length fields consistent
    # after data mutation (the host decode path re-runs full size
    # assignment; the device exec path patches only these links).
    slot_of_arg = {id(a): i for i, a in enumerate(t.slot_args)}
    for len_slot, measured_id in len_measures.items():
        tgt = slot_of_arg.get(measured_id)
        if tgt is not None and t.kind[tgt] == DATA:
            t.len_target[len_slot] = tgt
    return t


def _round_cap(n: int) -> int:
    c = 64
    while c < n:
        c *= 2
    return c


def _len_elem_size(typ: LenType, ctx) -> tuple[int, Optional[object], bool]:
    """Element size for mutate_size plus the measured sibling arg,
    resolved at encode time (reference: prog/size.go:119-141)."""
    from syzkaller_tpu.models.prog import inner_arg

    measured = None
    if ctx.parent is not None:
        for f in ctx.parent:
            if typ.buf == f.typ.field_name:
                measured = inner_arg(f)
                break
    elem_size = typ.bit_size // 8
    if elem_size:
        return elem_size, measured, True
    elem_size = 1
    if measured is not None:
        it = measured.typ
        if isinstance(it, VmaType):
            return 0, None, False
        if isinstance(it, ArrayType):
            assert it.elem is not None
            if it.elem.varlen:
                return 0, None, False
            elem_size = it.elem.size()
    return elem_size, measured, True


def decode_prog(t: ProgTensor, mutated: dict[str, np.ndarray],
                preserve_sizes: bool = False) -> Prog:
    """Write a mutated tensor back into a clone of the template.

    Only the device-mutable state (slot values, data spans, call
    aliveness) can change; structure is the template's.  Size fields
    are reassigned afterwards unless a LEN slot itself was mutated
    (matching the reference's updateSizes/preserve contract,
    reference: prog/mutation.go:100-121)."""
    p = t.template.clone()
    # Map template args -> cloned args by walk order.
    tmpl_args: list = []
    clone_args: list = []
    for c in t.template.calls:
        foreach_arg(c, lambda a, ctx: tmpl_args.append(a))
    for c in p.calls:
        foreach_arg(c, lambda a, ctx: clone_args.append(a))
    amap = {id(a): b for a, b in zip(tmpl_args, clone_args)}

    kind = mutated["kind"]
    val = mutated["val"]
    off = mutated["off"]
    len_ = mutated["len_"]
    arena = mutated["arena"]
    call_alive = mutated["call_alive"]

    for s, arg in enumerate(t.slot_args):
        target_arg = amap[id(arg)]
        k = int(kind[s])
        if k in (INT, FLAGS, PROC, LEN):
            target_arg.val = int(val[s])
        elif k == DATA:
            o, n = int(off[s]), int(len_[s])
            target_arg.data = bytearray(arena[o:o + n].tobytes())

    # Drop removed calls (back-to-front keeps indices stable) and fix
    # dangling resource refs via remove_call.
    for ci in range(t.ncalls - 1, -1, -1):
        if not bool(call_alive[ci]):
            p.remove_call(ci)

    if not preserve_sizes:
        for c in p.calls:
            assign_sizes_call(c)
    for c in p.calls:
        p.target.sanitize_call(c)
    return p


def stack_batch(tensors: list[ProgTensor]) -> dict[str, np.ndarray]:
    """Stack host tensors into batch arrays ready for device upload."""
    keys = tensors[0].arrays().keys()
    out = {}
    for k in keys:
        out[k] = np.stack([t.arrays()[k] for t in tensors])
    return out
