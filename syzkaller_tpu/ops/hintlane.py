"""HintLane: batched device hints as a first-class pipeline lane
(ISSUE 19 tentpole).

The per-program device hints path (ops/hints.mutate_with_hints_device)
runs one kernel per program: make_shrink_expand closes over that
program's comp-map arrays, so every smash-phase hint pass pays its own
host round-trip AND its own jit compile — invisible to the composer,
the accounting ledger, and the coverage lane attribution.  This engine
promotes comparison-operand hints to the same shape every other hot
path in this repo already has:

  - procs collect executor TRACE_CMP maps fleet-wide and stage them
    cross-proc; whoever reaches the device lock first becomes the
    flush leader and expands EVERYTHING staged (its own windows and
    every other proc's) as ONE stacked device batch — the triage
    engine's leader/follower discipline applied to mutation,
  - comp-map tables are stacked into padded pow2 device arrays
    (keys[M,K] / vmat[M,K,V], ops/hints.stack_comp_maps) written IN
    PLACE into persistent StagingArena slots; candidate values carry a
    map_of column so one module-level jitted kernel
    (stacked_shrink_expand_kernel) serves every flush — pow2 buckets
    in all dims keep the compiled-shape set bounded, and nothing ever
    re-jits in steady state (the warm-rig compile guard pins this),
  - the kernel elapsed books to the accounting ledger as
    `tz_acct_device_ms_total{lane="hints"}` and hint-mutant novelty
    attributes to `tz_coverage_novel_edges_total{lane="hints"}`
    (fuzzer/proc.py _LANE_BY_STAT), so the PR 11 composer can price
    and schedule the lane like any tenant (compose_drain below),
  - with the pipeline's sim prescore attached, replacer rows are
    pre-filtered through a speculation fold of (call site, comparand)
    — the magic-comparand edge model the PR 14 sim kernel carries,
    evaluated at lane granularity: a fold already probed this epoch
    is suppressed (counted, re-admitted when the sim plane decays),
  - breaker/watchdog semantics mirror triage: device calls run under
    the `device.hints` fault seam, any failure demotes the lane to
    the exact per-program CPU path (models.hints.shrink_expand per
    window) — degraded throughput, ZERO lost comparison traces — and
    the next device success re-promotes.

Bit-exactness contract: with no sim attached, the replacer set per
window equals the per-program host path (mutate_with_hints) exactly —
tests/test_hints_device.py drives both over randomized comp maps.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from syzkaller_tpu import telemetry
from syzkaller_tpu.health import (
    CircuitBreaker,
    Watchdog,
    env_int,
    fault_point,
)
from syzkaller_tpu.health.breaker import CLOSED
from syzkaller_tpu.models.hints import CompMap, shrink_expand
from syzkaller_tpu.models.prog import Prog
from syzkaller_tpu.ops.delta import pow2_rows
from syzkaller_tpu.ops.hints import (
    DeviceCompMap,
    apply_hint_mutants,
    collect_hint_jobs,
    resolve_hints_vmax,
    shrink_expand_batch_stacked,
    stack_comp_maps,
    stacked_shrink_expand_kernel,
)
from syzkaller_tpu.ops.staging import StagingArena
from syzkaller_tpu.utils import log

# Hint-lane telemetry (docs/observability.md "The hints lane").
_M_BATCHES = telemetry.counter(
    "tz_hints_batches_total", "fused hint batches flushed to the device")
_M_VALUES = telemetry.counter(
    "tz_hints_values_total",
    "candidate comparison windows expanded through the lane")
_M_MUTANTS = telemetry.counter(
    "tz_hints_mutants_total", "hint mutants produced by the lane")
_M_STAGED_BYTES = telemetry.counter(
    "tz_hints_staged_bytes_total",
    "comp-map table + value bytes staged H2D by hint flushes")
_M_SUPPRESSED = telemetry.counter(
    "tz_hints_sim_suppressed_total",
    "hint replacers suppressed by the sim speculation fold "
    "(re-admitted when the sim plane decays)")
_M_CPU_VALUES = telemetry.counter(
    "tz_hints_cpu_fallback_values_total",
    "windows expanded on the exact CPU path while demoted "
    "(zero lost comparison traces)")
_M_ERRORS = telemetry.counter(
    "tz_hints_device_errors_total",
    "device failures on the hint kernel (chunk expanded on CPU)")
_M_DEMOTIONS = telemetry.counter(
    "tz_hints_demotions_total", "device->CPU hint-lane demotions")
_M_REPROMOTIONS = telemetry.counter(
    "tz_hints_repromotions_total", "CPU->device hint-lane re-promotions")
_M_BATCH_VALUES = telemetry.gauge(
    "tz_hints_batch_values",
    "candidate windows in the most recent fused hint batch")

#: Fibonacci-hash multiplier for the speculation fold.
_GOLDEN = 0x9E3779B97F4A7C15
_FOLD_BITS = 16


def fold_suppress(replacer_lists: list[list[int]], plane: np.ndarray,
                  salt: int) -> tuple[list[list[int]], int]:
    """The lane's speculative prescore: fold each (call-site salt,
    replacer) pair into the plane; a fold already probed this epoch is
    suppressed.  Returns (kept lists, suppressed count).  Pure
    function — bench.py --hints measures its fraction standalone."""
    mask = (1 << _FOLD_BITS) - 1
    kept: list[list[int]] = []
    suppressed = 0
    for lst in replacer_lists:
        keep = []
        for r in lst:
            idx = (((r ^ (r >> 31)) * _GOLDEN + salt)
                   >> (64 - _FOLD_BITS)) & mask
            if plane[idx]:
                suppressed += 1
            else:
                plane[idx] = 1
                keep.append(r)
        kept.append(keep)
    return kept, suppressed


@dataclass
class HintLaneStats:
    values: int = 0  # candidate windows entering run()
    device_batches: int = 0  # fused flushes that resolved on device
    mutants: int = 0  # hint mutants handed to exec_cb
    suppressed: int = 0  # replacers held back by the sim fold
    cpu_fallback_values: int = 0  # windows expanded on CPU (demoted)
    device_errors: int = 0  # failures on the hint kernel
    demotions: int = 0  # device->CPU transitions
    repromotions: int = 0  # CPU->device transitions
    staged_bytes: int = 0  # cumulative H2D table+value bytes


class _Entry:
    """One proc's staged hint expansion: its candidate values, its
    lowered comp map, and a completion event the flush leader sets
    once replacers (or the failure verdict) are in."""

    __slots__ = ("vals", "dmap", "replacers", "failed", "done")

    def __init__(self, vals: np.ndarray, dmap: DeviceCompMap):
        self.vals = vals
        self.dmap = dmap
        self.replacers: Optional[list[list[int]]] = None
        self.failed = False
        self.done = threading.Event()


class HintLane:
    """Shared by every proc of one fuzzer process; see module doc.

    Knobs (health.envsafe; docs/health.md): TZ_HINTS_BATCH (candidate
    windows per fused device batch), TZ_HINTS_KMAX (per-map key
    budget; keys past it take the exact CPU supplement, counted in
    tz_hints_comps_dropped_total), TZ_HINTS_VMAX (per-key operand
    budget, resolved in ops/hints)."""

    #: Stacked maps per flush; with B/MAPS ≈ 64 windows per map a
    #: full batch still fits typical smash-phase call shapes.
    MAPS = 64

    def __init__(self, batch: int = 4096, kmax: int = 512,
                 vmax: Optional[int] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 watchdog: Optional[Watchdog] = None,
                 owns_breaker: Optional[bool] = None):
        self.B = max(64, env_int("TZ_HINTS_BATCH", batch))
        self.kmax = max(16, env_int("TZ_HINTS_KMAX", kmax))
        self.vmax = resolve_hints_vmax() if vmax is None else vmax
        self._arena = StagingArena(slots=2)
        self.owns_breaker = (breaker is None) if owns_breaker is None \
            else owns_breaker
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=max(1, env_int("TZ_BREAKER_THRESHOLD", 4)))
        self.watchdog = watchdog if watchdog is not None else Watchdog()
        self.stats = HintLaneStats()
        self._staged: list[_Entry] = []
        self._stage_lock = threading.Lock()
        self._device_lock = threading.Lock()  # flush-leader mutex
        self._compiled = False
        self._demoted = False
        # Speculative prescore (sim/prescore.SimPrescore): the fold
        # plane decays with the sim's re-admission epochs, so a
        # suppressed comparand becomes probeable again exactly when
        # the pipeline's speculation plane forgets it.
        self._sim = None
        self._sim_epoch = -1
        self._plane = np.zeros(1 << _FOLD_BITS, dtype=np.uint8)
        # Composer supply (serve/composer.attach_lane): staged
        # (prog, call, comps) sources and the mutant outbox
        # compose_drain fills batches from.
        self._sources: deque = deque()
        self._outbox: deque = deque()

    @classmethod
    def for_pipeline(cls, pipeline, **kw) -> "HintLane":
        """Co-resident form: one health verdict for the device —
        shares the DevicePipeline's breaker and watchdog, and rides
        its sim prescore's epoch clock for suppression decay."""
        lane = cls(breaker=pipeline.breaker, watchdog=pipeline.watchdog,
                   owns_breaker=False, **kw)
        pipeline.attach_hints(lane)
        return lane

    def attach_sim(self, sim) -> None:
        """Enable the speculative prescore over hint replacers; `sim`
        is the pipeline's SimPrescore (epoch clock + demotion state)."""
        self._sim = sim

    # -- the expand path ---------------------------------------------------

    def run(self, p: Prog, call_index: int, comps: CompMap,
            exec_cb: Callable[[Prog], None]) -> int:
        """Expand one call's comparison traces into executed hint
        mutants.  Drop-in for mutate_with_hints_device: same mutant
        sequence (modulo sim suppression), but the device batch is
        shared fleet-wide through the flush leader.  Returns the
        number of mutants executed."""
        pclone, jobs, vals = collect_hint_jobs(p, call_index)
        if not jobs:
            return 0
        self.stats.values += len(vals)
        _M_VALUES.inc(len(vals))
        varr = np.array(vals, dtype=np.uint64)
        if not self._gate():
            self._note_demoted(f"circuit breaker {self.breaker.state}")
            replacers = self._cpu_replacers(vals, comps)
        else:
            dmap = DeviceCompMap.from_comp_map(
                comps, vmax=self.vmax, kmax=self.kmax)
            entry = _Entry(varr, dmap)
            self._flush(entry)
            if entry.failed:
                # Zero lost traces: the staged windows expand on the
                # exact CPU path instead.
                replacers = self._cpu_replacers(vals, comps)
            else:
                replacers = entry.replacers
                if dmap.overflow is not None:
                    replacers = [
                        sorted(set(lst) | shrink_expand(v, dmap.overflow))
                        for lst, v in zip(replacers, vals)]
        replacers = self._prescore(p, call_index, replacers)
        n = apply_hint_mutants(pclone, jobs, replacers, exec_cb)
        self.stats.mutants += n
        if n:
            _M_MUTANTS.inc(n)
        return n

    def _cpu_replacers(self, vals: list[int],
                       comps: CompMap) -> list[list[int]]:
        """The demoted path: today's exact per-window CPU walk."""
        self.stats.cpu_fallback_values += len(vals)
        _M_CPU_VALUES.inc(len(vals))
        return [sorted(shrink_expand(v, comps)) for v in vals]

    def _prescore(self, p: Prog, call_index: int,
                  replacers: list[list[int]]) -> list[list[int]]:
        if self._sim is None or self._sim.demoted():
            return replacers
        epochs = getattr(self._sim, "epochs", 0)
        if epochs != self._sim_epoch:
            self._plane[:] = 0  # sim plane decayed: re-admit all
            self._sim_epoch = epochs
        salt = zlib.crc32(p.calls[call_index].meta.name.encode())
        kept, suppressed = fold_suppress(replacers, self._plane, salt)
        if suppressed:
            self.stats.suppressed += suppressed
            _M_SUPPRESSED.inc(suppressed)
        return kept

    def _gate(self) -> bool:
        if self.owns_breaker:
            return self.breaker.allow()
        return self.breaker.state == CLOSED

    # -- staging + flush ---------------------------------------------------

    def _flush(self, entry: _Entry) -> None:
        """Stage this expansion and drive flushes until it resolves:
        the flush leader expands every staged proc's windows in one
        stacked batch; losers wait on their entry."""
        with self._stage_lock:
            self._staged.append(entry)
        while not entry.done.is_set():
            if self._device_lock.acquire(timeout=0.01):
                try:
                    self._drain_staged()
                finally:
                    self._device_lock.release()
            else:
                entry.done.wait(timeout=0.02)

    def _drain_staged(self) -> None:
        """Expand staged chunks until the stage is empty (holds
        _device_lock).  A chunk packs up to MAPS maps; its
        concatenated values run in B-sized slices against the same
        staged tables."""
        while True:
            chunk: list[_Entry] = []
            with self._stage_lock:
                total = 0
                while self._staged and len(chunk) < self.MAPS:
                    e = self._staged[0]
                    if chunk and total + len(e.vals) > self.B:
                        break
                    chunk.append(self._staged.pop(0))
                    total += len(e.vals)
            if not chunk:
                return
            self._dispatch_chunk(chunk)

    def _dispatch_chunk(self, chunk: list[_Entry]) -> None:
        """One fused flush: stack the chunk's comp maps into arena
        slots, expand the concatenated value vector on device, slice
        replacer lists back per entry.  Any failure marks the whole
        chunk for the exact CPU path — degraded throughput, zero lost
        comparison traces — and feeds the breaker."""
        try:
            fault_point("device.hints")
            m = pow2_rows(len(chunk), lo=4, hi=self.MAPS)
            k = pow2_rows(max(max((len(e.dmap) for e in chunk),
                                  default=1), 1),
                          lo=16, hi=self.kmax)
            vals = np.concatenate([e.vals for e in chunk])
            map_of = np.concatenate([
                np.full(len(e.vals), i, dtype=np.int32)
                for i, e in enumerate(chunk)])
            total = len(vals)
            b = pow2_rows(min(total, self.B), lo=64, hi=self.B)
            bufs = self._arena.acquire((b, m, k), {
                "vals": ((b,), np.uint64),
                "map_of": ((b,), np.int32),
                "keys": ((m, k), np.uint64),
                "nkeys": ((m,), np.int32),
                "vmat": ((m, k, self.vmax), np.uint64),
                "nvals": ((m, k), np.int32),
            })
            stack_comp_maps([e.dmap for e in chunk], m, k, out=bufs)
            table_bytes = (bufs["keys"].nbytes + bufs["nkeys"].nbytes
                           + bufs["vmat"].nbytes + bufs["nvals"].nbytes)
            self._note_staged(table_bytes)
            out: list[list[int]] = []
            for start in range(0, total, b):
                n = min(b, total - start)
                bufs["vals"][:n] = vals[start:start + n]
                bufs["vals"][n:] = 0
                bufs["map_of"][:n] = map_of[start:start + n]
                bufs["map_of"][n:] = 0
                self._note_staged(bufs["vals"].nbytes
                                  + bufs["map_of"].nbytes)
                with telemetry.span("hints.device"):
                    t0 = time.perf_counter()
                    lists = self.watchdog.call(
                        lambda: shrink_expand_batch_stacked(
                            bufs["vals"], bufs["map_of"], bufs),
                        "device.hints", compile=not self._compiled)
                    elapsed = time.perf_counter() - t0
                self._compiled = True
                # Accounting ledger (ISSUE 14): the hint kernel's
                # residency, booked to the lane so the DeviceTimeLedger
                # and yield pricing can see what hints cost.
                telemetry.ACCOUNTING.note_batch(
                    elapsed, lane_rows={"hints": n})
                telemetry.PROFILER.note("hints", elapsed)
                out.extend(lists[:n])
                self.stats.device_batches += 1
                _M_BATCHES.inc()
                _M_BATCH_VALUES.set(n)
        except Exception as e:
            self.stats.device_errors += 1
            _M_ERRORS.inc()
            self.breaker.record_failure()
            log.logf(0, "hint lane device error (breaker %s): %s",
                     self.breaker.state, str(e)[:200])
            for en in chunk:
                en.failed = True
                en.done.set()
            return
        if self.owns_breaker:
            self.breaker.record_success()
        self._note_promoted()
        off = 0
        for en in chunk:
            en.replacers = out[off:off + len(en.vals)]
            off += len(en.vals)
            en.done.set()

    def _note_staged(self, nbytes: int) -> None:
        self.stats.staged_bytes += nbytes
        _M_STAGED_BYTES.inc(nbytes)

    # -- composer supply (serve/composer.attach_lane) ----------------------

    def stage_source(self, p: Prog, call_index: int,
                     comps: CompMap) -> None:
        """Queue one (prog, call, comp-map) source for composer-driven
        expansion; compose_drain materializes its mutants on demand."""
        self._sources.append((p, call_index, comps))

    def pending_rows(self) -> int:
        """Outstanding supply (the lane tenant's backlog hint): queued
        mutants plus a conservative one-mutant floor per staged
        source."""
        return len(self._outbox) + len(self._sources)

    def compose_drain(self, n_rows: int, row_bytes: int = 64):
        """`drain_fn` form for BatchComposer.attach_lane: expand
        staged sources through the fused batch until n_rows exec-ready
        hint payloads (serialize_for_exec bytes) are available; excess
        mutants stay in the outbox for the next compose.  Returns
        (rows, payloads) — rows are the payload prefixes as the
        novelty-verdict input, zero-padded when supply runs short."""
        from syzkaller_tpu.models.encodingexec import serialize_for_exec

        while len(self._outbox) < n_rows and self._sources:
            p, ci, comps = self._sources.popleft()
            self.run(p, ci, comps,
                     lambda mp: self._outbox.append(
                         serialize_for_exec(mp)))
        take = min(n_rows, len(self._outbox))
        payloads = [self._outbox.popleft() for _ in range(take)]
        payloads += [b""] * (n_rows - take)
        rows = np.zeros((n_rows, row_bytes), dtype=np.uint8)
        for i, pay in enumerate(payloads):
            pre = np.frombuffer(pay[:row_bytes], dtype=np.uint8)
            rows[i, :len(pre)] = pre
        return rows, payloads

    # -- health ------------------------------------------------------------

    def _note_demoted(self, reason: str) -> None:
        if self._demoted:
            return
        self._demoted = True
        self.stats.demotions += 1
        _M_DEMOTIONS.inc()
        telemetry.record_event("hints.demote", reason)
        log.logf(0, "HINT LANE DEMOTED to per-program CPU path: %s",
                 reason)

    def _note_promoted(self) -> None:
        if not self._demoted:
            return
        self._demoted = False
        self.stats.repromotions += 1
        _M_REPROMOTIONS.inc()
        telemetry.record_event("hints.repromote", "device answering")
        log.logf(0, "hint lane re-promoted to the device batch")

    def demoted(self) -> bool:
        return self._demoted

    def snapshot(self) -> dict:
        """Lane state for health_snapshot surfaces and tests."""
        s = self.stats
        return {
            "demoted": self._demoted,
            "values": s.values,
            "device_batches": s.device_batches,
            "mutants": s.mutants,
            "suppressed": s.suppressed,
            "cpu_fallback_values": s.cpu_fallback_values,
            "device_errors": s.device_errors,
            "demotions": s.demotions,
            "repromotions": s.repromotions,
            "staged_bytes": s.staged_bytes,
            "batch_values": self.B,
            "kmax": self.kmax,
            "vmax": self.vmax,
            "staging_arena_bytes": self._arena.nbytes,
        }
