"""Device-side fuzzing RNG distributions.

Re-derivations of the reference's biased distributions
(reference: prog/rand.go:57-151) from jax.random primitives, shaped so
every function is vmap-able: all take a key and return a scalar (or
per-key scalars under vmap).  Statistical parity with models/rand.py
is covered by tests/test_ops_rng.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import random

from syzkaller_tpu.models.rand import SPECIAL_INTS

SPECIAL_INTS_ARR = jnp.array(SPECIAL_INTS, dtype=jnp.uint64)

MASK64 = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def U64_M(n: int) -> jax.Array:
    """Mask for modulo by a power of two."""
    assert n & (n - 1) == 0
    return jnp.uint64(n - 1)


def intn(key, n) -> jax.Array:
    """Uniform-ish [0, n) via u32 modulo; n may be traced, must be
    < 2^31.  The modulo bias is negligible for fuzzing distributions
    and u32 division compiles ~10x faster than the u64 path on XLA:CPU
    (measured; u64 div lowers to a software routine per instance)."""
    n32 = jnp.asarray(n).astype(jnp.uint32)
    v = random.bits(key, dtype=jnp.uint32) % jnp.maximum(n32, jnp.uint32(1))
    return v.astype(jnp.int64)


def n_out_of(key, n: int, out_of: int) -> jax.Array:
    return intn(key, out_of) < n


def one_of(key, n: int) -> jax.Array:
    return intn(key, n) == 0


def bin_(key) -> jax.Array:
    return random.bernoulli(key)


def uint64(key) -> jax.Array:
    return random.bits(key, dtype=jnp.uint64)


def rand64(key) -> jax.Array:
    """63 random bits, top bit set half the time
    (reference: prog/rand.go:48-54)."""
    k1, k2 = random.split(key)
    v = random.bits(k1, dtype=jnp.uint64) >> jnp.uint64(1)
    top = jnp.where(random.bernoulli(k2), jnp.uint64(1) << jnp.uint64(63),
                    jnp.uint64(0))
    return v | top


def rand_int(key) -> jax.Array:
    """The magic integer distribution (reference: prog/rand.go:67-91).

    Branch probabilities composed into a single categorical:
      mod 10: 100/182, special: 50/182, mod 256: 10/182,
      mod 4K: 10/182, mod 64K: 10/182, mod 2^31: 2/182
    then: keep 100/107, negate 5/107, shift-left 2/107.
    """
    k1, k2, k3, k4, k5 = random.split(key, 5)
    v = rand64(k1)
    bucket = _categorical(k2, _RAND_INT_P1)
    special = SPECIAL_INTS_ARR[intn(k3, len(SPECIAL_INTS))]
    # All moduli except 10 are powers of two -> masks; %10 runs in u32
    # (u64 division is pathologically slow to compile on XLA:CPU).
    mod10 = (v.astype(jnp.uint32) % jnp.uint32(10)).astype(jnp.uint64)
    v = jnp.select(
        [bucket == 0, bucket == 1, bucket == 2, bucket == 3, bucket == 4],
        [mod10, special, v & U64_M(256), v & U64_M(4 << 10),
         v & U64_M(64 << 10)],
        v & U64_M(1 << 31))
    post = _categorical(k4, _RAND_INT_P2)
    shift = intn(k5, 63).astype(jnp.uint64)
    v = jnp.select([post == 0, post == 1],
                   [v, (-v.astype(jnp.int64)).astype(jnp.uint64)],
                   v << shift)
    return v


_RAND_INT_P1 = jnp.cumsum(jnp.array([100, 50, 10, 10, 10, 2]) / 182.0)
_RAND_INT_P2 = jnp.cumsum(jnp.array([100, 5, 2]) / 107.0)


def _categorical(key, cum_probs) -> jax.Array:
    u = random.uniform(key, dtype=jnp.float32)
    return jnp.searchsorted(cum_probs.astype(jnp.float32), u)


def mulhi64(a, b) -> jax.Array:
    """floor(a*b / 2^64) via 32-bit limbs — no u64 division, no f64
    (both are slow/unsupported on TPU)."""
    m32 = jnp.uint64(0xFFFFFFFF)
    a0, a1 = a & m32, a >> jnp.uint64(32)
    b0, b1 = b & m32, b >> jnp.uint64(32)
    p0 = a0 * b0
    p1 = a0 * b1
    p2 = a1 * b0
    p3 = a1 * b1
    mid = (p0 >> jnp.uint64(32)) + (p1 & m32) + (p2 & m32)
    return p3 + (p1 >> jnp.uint64(32)) + (p2 >> jnp.uint64(32)) \
        + (mid >> jnp.uint64(32))


def rand_range_int(key, begin, end) -> jax.Array:
    """(reference: prog/rand.go:93-98).  The in-range draw maps a
    uniform u64 into [0, span) with mulhi instead of modulo (u64 div is
    pathologically slow to compile on XLA:CPU and emulated on TPU)."""
    k1, k2, k3 = random.split(key, 3)
    span = jnp.maximum(end - begin + jnp.uint64(1), jnp.uint64(1))
    in_range = begin + mulhi64(uint64(k2), span)
    return jnp.where(one_of(k1, 100), rand_int(k3), in_range)


def biased_rand(key, n: int, k: int) -> jax.Array:
    """Quadratic bias towards n-1 (reference: prog/rand.go:100-107)."""
    nf, kf = float(n), float(k)
    rf = nf * (kf / 2 + 1) * random.uniform(key, dtype=jnp.float32)
    bf = (-1.0 + jnp.sqrt(1 + 2 * kf * rf / nf)) * nf / kf
    return jnp.minimum(bf.astype(jnp.int64), n - 1)


def flags_value(key, vals, count) -> jax.Array:
    """Flag sampling (reference: prog/rand.go:138-152).
    vals: uint64[MAXV] padded flag values, count: number valid.
    Branches: OR-loop 90/111, single 10/111, zero 10/111, rand64 1/111.
    The OR-loop draws geometric(1/2) values, capped at 4.
    """
    k1, k2, k3, k4 = random.split(key, 4)
    count32 = jnp.maximum(jnp.asarray(count).astype(jnp.uint32), jnp.uint32(1))
    branch = _categorical(k1, _FLAGS_P)
    idxs = (random.bits(k2, (4,), dtype=jnp.uint32) % count32).astype(jnp.int32)
    picks = vals[idxs]
    # geometric number of OR'd values: 1 + #consecutive-heads (cap 4)
    coins = random.bernoulli(k3, shape=(3,))
    ncoins = 1 + jnp.cumprod(~coins).sum()
    take = jnp.arange(4) < ncoins
    masked = jnp.where(take, picks, jnp.uint64(0))
    or_val = masked[0] | masked[1] | masked[2] | masked[3]
    return jnp.select(
        [branch == 0, branch == 1, branch == 2],
        [or_val, picks[0], jnp.uint64(0)],
        rand64(k4))


_FLAGS_P = jnp.cumsum(jnp.array([90, 10, 10, 1]) / 111.0)


def masked_choice(key, mask) -> jax.Array:
    """Uniformly choose an index where mask is True; -1 if none."""
    n = mask.shape[0]
    count = mask.sum()
    pick = intn(key, jnp.maximum(count, 1))
    # index of the pick-th True element
    cum = jnp.cumsum(mask) - 1
    idx = jnp.argmax((cum == pick) & mask)
    return jnp.where(count > 0, idx, -1)
