"""Device-resident mutation pipeline: corpus tensors live on device,
mutants come back as exec-ready bytes.

Round-1's engine shipped templates host->device on every batch, re-jit
on varying shapes, and decoded every mutant back to a typed tree
(~3-15 mutants/s end to end).  This pipeline closes that gap:

  - the corpus is a ring of stacked program tensors RESIDENT on
    device; adds are staged host-side and flushed as one scatter,
  - one jitted step at a STATIC batch shape samples templates
    uniformly (reference corpus pick: syz-fuzzer/proc.go:92) and
    mutates them in a single fused vmap — no per-batch recompile,
  - the D2H transfer is COMPACTED: delta rows ship in full (every row
    is a mutant) but the payload pool ships only the pow2-bucketed
    prefix of slots the batch actually claimed (ops/delta
    make_compact_pooler; bucketing keeps the slice-shape set static
    so nothing re-jits on the latency-bound tunneled link),
  - drained rows become exec wire bytes via the vectorized
    patch-table assembler (ops/emit.py): per template group, one
    patch pass + one gather into a contiguous output arena whose
    (offset, length) memoryview slices ARE the mutants' exec bytes —
    handed zero-copy through to the executor's shmem write.  No typed
    decode on the hot path; ExecMutant decodes lazily for the rare
    triaged input,
  - assembly runs on a pool of TZ_ASSEMBLE_WORKERS threads, sharded
    by template group so a group's vectorized pass never splits; the
    drain thread keeps `assemble_depth` batches in the pool and
    delivers them strictly in drain order — the depth self-tunes from
    the measured pool_drain vs assemble_worker span percentiles
    (TZ_ASSEMBLE_DEPTH=auto|N, ops/staging.DepthController), and the
    corpus-flush scatter stages its rows through the same persistent
    transfer-plane arenas the triage engine uses (ops/staging),
  - a background worker keeps `prefetch` assembled batches queued
    while executors drain the previous one (double buffering,
    SURVEY.md §7 hard part (c)); docs/perf.md covers the stage
    anatomy and the tuning knobs.

fuzzer.proc.PipelineMutator draws the reference op ladder per mutant
and routes the device classes here — insert (donor-bank splice with
ChoiceTable sampling, ops/insert.py), arg-mutate and remove, together
~79% of iteration weight — while squash/splice stay host-side, so the
integrated op distribution matches the reference weighted loop
(reference: prog/mutation.go:19-131).
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from syzkaller_tpu import telemetry
from syzkaller_tpu.telemetry import lineage
from syzkaller_tpu.health import (
    CircuitBreaker,
    FaultInjected,
    Watchdog,
    env_float,
    env_int,
    fault_point,
    warn_unknown_tz_vars,
)
from syzkaller_tpu.models.prog import Prog
from syzkaller_tpu.ops.delta import (
    FLAG_OVERFLOW,
    OP_INSERT,
    DeltaBatch,
    DeltaSpec,
    compact_rows,
    make_compact_pooler,
    make_packer,
    pool_bucket,
    pow2_rows,
)
from syzkaller_tpu.ops.arena import CorpusArena, DistillLane
from syzkaller_tpu.ops.emit import (
    DonorBankTable,
    ExecTemplate,
    TemplateTable,
    assemble_batch_table,
    build_exec_template,
    mutant_call_ids,
    shard_by_template,
    splice_batch_table,
    splice_insert,
    splice_insert_group_flat,
)
from syzkaller_tpu.ops.staging import StagingArena, resolve_assemble_depth
from syzkaller_tpu.ops.tensor import (
    FlagTables,
    ProgTensor,
    TensorConfig,
    decode_prog,
    encode_prog,
)

# Reference per-iteration op-class marginals
# (reference: prog/mutation.go:19-131).
P_SQUASH = 1 / 5
P_SPLICE = (1 - P_SQUASH) * (1 / 100)
P_INSERT = (1 - P_SQUASH) * (99 / 100) * (20 / 31)
P_ARG_MUTATE = (1 - P_SQUASH) * (99 / 100) * (11 / 31) * (10 / 11)
P_REMOVE = (1 - P_SQUASH) * (99 / 100) * (11 / 31) * (1 / 11)

# Device classes: insert (donor-bank splice, ops/insert.py) + the
# arg-mutate/remove kernel loop.  Squash/splice stay host-side
# (fuzzer.proc.PipelineMutator routes the ladder).
P_DEVICE = P_INSERT + P_ARG_MUTATE + P_REMOVE
P_HOST_STRUCTURAL = P_SQUASH + P_SPLICE
# Conditional insert share among device classes.
P_INSERT_GIVEN_DEVICE = P_INSERT / P_DEVICE

# Hot-loop telemetry (docs/observability.md): process-wide, shared by
# every pipeline instance.  Phase latencies come from span() contexts
# at the call sites (pipeline.flush/compile/launch/drain/assemble);
# these are the companion counts and queue/batch shape gauges.
_M_BATCHES = telemetry.counter(
    "tz_pipeline_batches_total", "mutant batches drained")
_M_MUTANTS = telemetry.counter(
    "tz_pipeline_mutants_total", "exec-ready mutants produced")
_M_OVERFLOWS = telemetry.counter(
    "tz_pipeline_overflows_total", "delta rows over the K/D/P budget")
_M_ASSEMBLE_ERRORS = telemetry.counter(
    "tz_pipeline_assemble_errors_total", "mutants dropped at assembly")
_M_WORKER_ERRORS = telemetry.counter(
    "tz_pipeline_worker_errors_total", "device failures in the worker")
_M_DELIVERY_ERRORS = telemetry.counter(
    "tz_pipeline_delivery_errors_total", "batches dropped at queue.put")
_M_BACKOFF_WAITS = telemetry.counter(
    "tz_pipeline_backoff_waits_total",
    "worker waits behind an open breaker")
_M_BACKOFF_SECONDS = telemetry.counter(
    "tz_pipeline_backoff_wait_seconds_total",
    "seconds the worker spent waiting behind an open breaker")
_M_QUEUE_DEPTH = telemetry.gauge(
    "tz_pipeline_queue_depth", "assembled batches waiting for procs")
_M_BATCH_SIZE = telemetry.gauge(
    "tz_pipeline_batch_size", "mutants per device batch")
_M_ASYNC_COPY_FALLBACKS = telemetry.counter(
    "tz_pipeline_async_copy_fallback_total",
    "copy_to_host_async calls that fell back to the synchronous drain")
_M_D2H_BYTES = telemetry.counter(
    "tz_pipeline_d2h_bytes_total",
    "compacted delta bytes fetched device->host")
_M_D2H_BATCH_BYTES = telemetry.gauge(
    "tz_pipeline_d2h_batch_bytes",
    "compacted bytes fetched for the most recent batch")
_M_ASSEMBLE_QUEUE_DEPTH = telemetry.gauge(
    "tz_pipeline_assemble_queue_depth",
    "assembly shards queued for the worker pool")
_M_ASSEMBLE_POOL_SIZE = telemetry.gauge(
    "tz_pipeline_assemble_pool_size",
    "assembler threads serving the pipeline")
_M_MUTATE_BACKEND = telemetry.gauge(
    "tz_mutate_backend",
    "mutation-core backend in use (0 = vmap, 1 = pallas)")
_M_FUSED_BATCHES = telemetry.counter(
    "tz_pipeline_fused_batches_total",
    "batches drained through the fused mutate->compact->novel path")
_M_FUSED_NOVEL_ROWS = telemetry.counter(
    "tz_pipeline_fused_novel_rows_total",
    "plane-novel delta rows fetched by the fused drain")


class ExecMutant:
    """A device-produced mutant: exec bytes now, typed program on
    demand (only triage/logging ever needs the tree).  exec_bytes is
    bytes-like — on the fast path a zero-copy (offset, length)
    memoryview into its batch's output arena (ops/emit), which the
    IPC layer writes straight into the executor's shmem; the view
    pins the arena, so batch memory lives exactly as long as its last
    undelivered mutant.  Holds a view into its DeltaBatch; the full
    tensor row is rebuilt from template + delta only when prog() is
    called.

    Insert-class mutants additionally carry the donor block and the
    alive-call boundary it was spliced at (ops/insert.py)."""

    __slots__ = ("exec_bytes", "template", "et", "batch", "j",
                 "donor", "donor_pos", "_anys", "_prog")

    def __init__(self, exec_bytes, template: ProgTensor,
                 et: ExecTemplate, batch: DeltaBatch, j: int,
                 donor=None, donor_pos: int = 0):
        self.exec_bytes = exec_bytes
        self.template = template
        self.et = et
        self.batch = batch
        self.j = j
        self.donor = donor
        self.donor_pos = donor_pos
        self._anys: Optional[list[bool]] = None
        self._prog: Optional[Prog] = None

    @property
    def target(self):
        return self.template.template.target

    @property
    def trace(self):
        """The batch's lineage trace context (None = unsampled).  A
        property over the batch reference, so unsampled mutants carry
        zero per-mutant allocation overhead (telemetry/lineage.py)."""
        return self.batch.trace

    def _any_flags(self) -> list[bool]:
        """Per-mutant-call squashed-ANY flags, in executor call order
        (template alive calls with the donor block spliced in)."""
        if self._anys is None:
            alive = self.batch.call_alive(
                self.j, self.template.call_alive.shape[0])
            anys = [bool(self.et.calls_any[i])
                    for i in mutant_call_ids(self.et, alive)]
            if self.donor is not None:
                pos = min(self.donor_pos, len(anys))
                anys[pos:pos] = list(self.donor.calls_any)
            self._anys = anys
        return self._anys

    def num_calls(self) -> int:
        return len(self._any_flags())

    def contains_any_call(self, call_index: int) -> bool:
        """Whether the mutant call is a squashed-ANY form, without
        decoding (device ops never introduce ANY; the template's and
        donor's per-call flags are exact)."""
        anys = self._any_flags()
        if call_index >= len(anys):
            return False
        return anys[call_index]

    def signal_prio(self, errno: int, call_index: int) -> int:
        """Edge priority for an executed mutant call, computed without
        typed decode (reference: syz-fuzzer/fuzzer.go:513-521)."""
        prio = 0
        if errno == 0:
            prio |= 1 << 1
        if not self.contains_any_call(call_index):
            prio |= 1 << 0
        return prio

    def prog(self) -> Prog:
        """Decode to a typed program (cached; reference semantics:
        ops/tensor.decode_prog).  Insert mutants re-insert the donor's
        cloned typed calls at the spliced boundary."""
        if self._prog is None:
            row = self.batch.rebuild_row(self.j, self.template)
            p = decode_prog(
                self.template, row,
                preserve_sizes=bool(row["preserve_sizes"]))
            if self.donor is not None:
                dclone = Prog(target=p.target,
                              calls=self.donor.calls).clone()
                pos = min(self.donor_pos, len(p.calls))
                p.calls[pos:pos] = dclone.calls
            self._prog = p
        return self._prog


@dataclass
class PipelineStats:
    batches: int = 0
    mutants: int = 0
    adds: int = 0
    evictions: int = 0
    assemble_errors: int = 0
    overflows: int = 0  # delta rows exceeding the K/D/P budget
    inserts: int = 0  # insert-class mutants produced
    worker_errors: int = 0  # device failures survived by the worker
    delivery_errors: int = 0  # batches dropped at the queue.put seam
    async_copy_fallbacks: int = 0  # copy_to_host_async not available
    d2h_bytes: int = 0  # compacted bytes fetched device->host
    d2h_batches: int = 0  # batches those bytes cover
    fused_batches: int = 0  # batches drained through the fused path
    fused_novel_rows: int = 0  # plane-novel rows those batches shipped
    sim_batches: int = 0  # batches drained through the sim prescore
    sim_suppressed: int = 0  # plane-novel rows the prescore held back


class AssembledBatch(list):
    """One drained batch of ExecMutants.  A plain list to consumers;
    additionally carries the drain sequence number so delivery
    ordering across the assembly pool is observable (tests, and the
    bench's supply-ordering assertions), the batch's lineage trace
    context (None = unsampled), and — when the serving plane composed
    this batch from multiple tenants' demand (serve/composer.py) —
    the per-row tenant-id column (`tenants`, int32[B] indices into
    the composer's tenant order; None for single-consumer drains):
    row j's mutant belongs to tenant tenants[j], and result
    distribution must honor that or it is the cross-tenant leak the
    serve conservation test forbids."""

    __slots__ = ("seq", "trace", "tenants")

    def __init__(self, mutants=(), seq: int = -1, trace=None,
                 tenants=None):
        super().__init__(mutants)
        self.seq = seq
        self.trace = trace
        self.tenants = tenants


class _AssemblyTask:
    """One unit of pool work: a callable + its eventual result."""

    __slots__ = ("fn", "args", "result", "error", "done")

    def __init__(self, fn, args):
        self.fn = fn
        self.args = args
        self.result = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()

    def run(self) -> None:
        try:
            self.result = self.fn(*self.args)
        except BaseException as e:  # delivered to the waiter
            self.error = e
        self.done.set()

    def wait(self, stop: Optional[threading.Event] = None) -> bool:
        """Block until the task ran (True) or `stop` fired first
        (False).  Re-raises the task's exception on completion."""
        if stop is None:
            self.done.wait()
        else:
            while not self.done.wait(timeout=0.2):
                if stop.is_set():
                    return False
        if self.error is not None:
            raise self.error
        return True


class AssemblyPool:
    """N daemon assembler threads draining a shared task queue.

    workers=0 (or a stopped pool) runs every submit inline in the
    caller — the deterministic single-thread mode tests and the
    post-shutdown bench path rely on.  Threads spawn lazily on first
    submit so constructing a pipeline stays thread-free."""

    def __init__(self, workers: int, name: str = "tz-assemble"):
        self.workers = max(0, workers)
        self.name = name
        self._tasks: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._threads: list[threading.Thread] = []
        _M_ASSEMBLE_POOL_SIZE.set(self.workers)

    def submit(self, fn, *args) -> _AssemblyTask:
        task = _AssemblyTask(fn, args)
        if self.workers == 0 or self._stop.is_set():
            task.run()
            return task
        if not self._threads:
            with self._lock:
                if not self._threads and not self._stop.is_set():
                    for i in range(self.workers):
                        t = threading.Thread(
                            target=self._worker_loop, daemon=True,
                            name=f"{self.name}-{i}")
                        self._threads.append(t)
                        t.start()
        self._tasks.put(task)
        _M_ASSEMBLE_QUEUE_DEPTH.set(self._tasks.qsize())
        return task

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            try:
                task = self._tasks.get(timeout=0.2)
            except queue.Empty:
                continue
            _M_ASSEMBLE_QUEUE_DEPTH.set(self._tasks.qsize())
            with telemetry.span("pipeline.assemble_worker"):
                task.run()

    def queue_depth(self) -> int:
        return self._tasks.qsize()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=10)
        # Orphaned tasks would strand a waiter forever; run them
        # inline (stop() is called from the owner after the worker
        # loop exits, so nothing races these results).
        try:
            while True:
                self._tasks.get_nowait().run()
        except queue.Empty:
            pass


# Lean device shapes for the pipeline: mutation cost is dominated by
# arena-roll traffic (measured 2.8x faster at 2048 than 8192), and the
# delta payload must hold a mutant's changed spans.
PIPELINE_TENSOR_CONFIG = TensorConfig(
    max_calls=32, max_slots=128, arena=2048, max_blob=768)

# The tunneled host link moves ~9 MB/s on synchronous copies, so wire
# bytes per mutant ARE the throughput ceiling.  DeltaSpec's defaults
# (228-byte core row + pooled 1 KB payload slots for the ~6% of
# mutants that change data bytes) are tuned for exactly this pipeline;
# P=1024 holds one full changed blob (max_blob 768, 8-aligned), and
# mutants that exceed the budgets are flagged OVERFLOW and dropped
# (counted in stats; a dropped mutant costs only its batch slot).
PIPELINE_DELTA_SPEC = DeltaSpec()


def _shared_step(spec, B: int, R: int, backend: str, fused: bool,
                 n_blocks: int, max_insert_calls: int,
                 prescore: bool = False, sim_backend: str = ""):
    """The jitted mutate->pack step, shared process-wide.

    The ChoiceTable prefix-sum rows and the donor index enter as
    TRACED arguments instead of closure constants, so the compiled
    executable depends only on the static shape key above — a second
    DevicePipeline at the same (spec, batch, rounds) reuses the first
    one's compile instead of paying XLA again.  That matters anywhere
    engines churn: per-Proc pipelines, breaker-driven rebuilds, and
    every test rig in a shared process.

    This is THE process compile point, so its cache occupancy is
    published to the CompileObservatory (ISSUE 17) — the actual XLA
    build is observed at first dispatch in `_launch`, where the wall
    time is real.
    """
    fn = _shared_step_cached(spec, B, R, backend, fused, n_blocks,
                             max_insert_calls, prescore, sim_backend)
    telemetry.COMPILES.set_cache_size(
        "pipeline.step", _shared_step_cached.cache_info().currsize)
    return fn


@functools.lru_cache(maxsize=None)
def _shared_step_cached(spec, B: int, R: int, backend: str,
                        fused: bool, n_blocks: int,
                        max_insert_calls: int, prescore: bool = False,
                        sim_backend: str = ""):
    import jax
    import jax.numpy as jnp
    from jax import random

    from syzkaller_tpu.ops import rng as d
    from syzkaller_tpu.ops.arena import pick_rows
    from syzkaller_tpu.ops.mutate import _mutate_one
    from syzkaller_tpu.ops.pallas_mutate import make_pallas_mutate_pack
    from syzkaller_tpu.ops.signal import mutant_novelty

    pack = make_packer(spec)
    pool = make_compact_pooler(spec, B)
    p_insert = P_INSERT_GIVEN_DEVICE if n_blocks > 0 else 0.0
    pallas_pack = make_pallas_mutate_pack(spec, R) \
        if backend == "pallas" else None

    def sample_and_pack(corpus, cumw, total, key, flag_vals,
                        flag_counts, runs, by_syscall):
        """Template sampling + per-row class draws + the mutation
        core, shared by the fused and unfused step graphs.  The
        template pick is the arena's on-device weighted search
        (ops/arena.pick_rows): with unit weights it degenerates to
        the legacy `bits % n` draw bit for bit, so the compiled
        graph is ONE executable for weighted and uniform sampling
        alike (TZ_ARENA_DEVICE=0 just pins unit weights).  The
        class/donor sampling stays a (tiny) vmap on both backends
        and splits each row key exactly as the pre-Pallas fused
        vmap did, so every backend/fusion combination consumes
        the same threefry stream."""
        nid = runs.shape[0]

        def sample_insert(st, k):
            """Donor + position for an insert mutant: ChoiceTable
            categorical over the context call's prefix-sum prio row
            (reference: prog/prio.go:230-245) + biased-to-end insert
            position (reference: prog/mutation.go:79)."""
            k_ctx, k_x, k_fb, k_pos = random.split(k, 4)
            alive = st["call_alive"]
            ctx_slot = d.masked_choice(k_ctx, alive)
            ctx_id = st["call_id"][jnp.maximum(ctx_slot, 0)]
            row = runs[jnp.clip(ctx_id, 0, nid - 1)]
            x = (d.intn(k_x, jnp.maximum(row[-1], 1).astype(jnp.int64))
                 .astype(jnp.uint32) + 1)
            sid = jnp.searchsorted(row, x)
            donor = by_syscall[jnp.clip(sid, 0, nid - 1)]
            donor = jnp.where(
                donor < 0,
                d.intn(k_fb, max(n_blocks, 1)).astype(jnp.int32), donor)
            n_alive = alive.sum().astype(jnp.int32)
            pos = d.biased_rand(k_pos, st["call_alive"].shape[0] + 1, 5) \
                .astype(jnp.int32)
            pos = jnp.minimum(pos, n_alive)
            # Respect the program-length budget: a full template
            # falls back to the mutate class.
            ok = n_alive < max_insert_calls
            return donor, pos.astype(jnp.uint8), ok

        k_idx, k_mut = random.split(key)
        idx = pick_rows(cumw, total,
                        random.bits(k_idx, (B,), dtype=jnp.uint32))
        batch = {k: v[idx] for k, v in corpus.items()}
        keys = random.split(k_mut, B)

        def classes(st, k):
            k_class, k_ins, k_mut1 = random.split(k, 3)
            is_insert = d.intn(k_class, 1 << 20) < int(
                p_insert * (1 << 20))
            donor, pos, ins_ok = sample_insert(st, k_ins)
            is_insert = is_insert & ins_ok
            op = jnp.where(is_insert, jnp.uint8(1), jnp.uint8(0))
            donor = jnp.where(is_insert, donor, jnp.int32(-1))
            return op, donor, pos, k_mut1

        op, donor, pos, mut_keys = jax.vmap(classes)(batch, keys)
        if pallas_pack is not None:
            return pallas_pack(batch, jax.random.key_data(mut_keys),
                               idx, op, donor, pos,
                               flag_vals, flag_counts)

        def one(st, k, i, o, dn, po):
            mutated = _mutate_one(st, k, flag_vals, flag_counts, R)
            # Insert mutants keep the TEMPLATE structure: the
            # packer masks the value/data journals by op, and the
            # alive bitmap must be the unmutated one.
            mutated["call_alive"] = jnp.where(
                o != 0, st["call_alive"], mutated["call_alive"])
            return pack(mutated, i, op=o, donor=dn, pos=po)

        return jax.vmap(one)(batch, mut_keys, idx, op, donor, pos)

    def step(corpus: dict, cumw, total: int, key, flag_vals,
             flag_counts, runs, by_syscall):
        rows, payloads, needs = sample_and_pack(
            corpus, cumw, total, key, flag_vals, flag_counts, runs,
            by_syscall)
        return pool(rows, payloads, needs)

    def fused_step(corpus: dict, cumw, total: int, key, flag_vals,
                   flag_counts, plane, runs, by_syscall):
        """mutate -> emit-compact -> novel_any as ONE dispatch
        (ISSUE 10): the mutant plane drops already-seen rows ON
        DEVICE — they claim no pool slot and are compacted out of
        the row prefix, so a non-novel mutant never crosses D2H.
        Returns (rows compacted novel-first, pool prefix, n_used,
        n_novel, updated plane)."""
        rows, payloads, needs = sample_and_pack(
            corpus, cumw, total, key, flag_vals, flag_counts, runs,
            by_syscall)
        novel, plane = mutant_novelty(plane, rows)
        # Pool claims happen on the PRE-compaction row order, so
        # pool_idx is already embedded in each row's bytes and
        # survives the reorder below.
        rows, pool_arr, n_used = pool(rows, payloads, needs & novel)
        rows, n_novel = compact_rows(rows, novel)
        return rows, pool_arr, n_used, n_novel, plane

    def fused_prescore_step(corpus: dict, cumw, total: int, key,
                            flag_vals, flag_counts, plane, sim_plane,
                            sim_tables, heat, runs, by_syscall):
        """The fused drain with the ISSUE 15 sim-exec prescore fused
        in: mutate -> plane dedup -> SIMULATED execution of every
        plane-novel mutant (syzkaller_tpu/sim) -> predicted-edge fold
        into the speculation plane -> novel_any-style admit verdict.
        Only rows whose PREDICTED edges hit a fresh speculation-plane
        bucket cross D2H; the rest are suppressed on device (counted,
        and re-admissible after the plane's decay epoch — see
        sim/prescore.py for the no-starvation argument).  Insert-class
        mutants are force-admitted: their donor splice happens host-
        side, so simulating the base template alone would mispredict
        them wholesale.  The admit verdict also scatter-adds into the
        arena's per-row `heat` vector ON DEVICE (ISSUE 18): novelty
        yield accrues to the sampled template's slot with zero
        per-batch host traffic, and the arena folds the accumulated
        heat into its sampling weights at distill cadence
        (CorpusArena.fold_heat)."""
        from syzkaller_tpu.ops.pallas_mutate import _use_interpret
        from syzkaller_tpu.sim.kernel import (
            TABLE_FIELDS,
            apply_deltas,
            decode_rows,
            predict_and_mark,
            sim_exec_batch,
        )

        rows, payloads, needs = sample_and_pack(
            corpus, cumw, total, key, flag_vals, flag_counts, runs,
            by_syscall)
        novel, plane = mutant_novelty(plane, rows)
        # Reconstruct each mutant's value slots from its delta row
        # and gather its template's lowered sim table — the sim-exec
        # kernel then runs the WHOLE batch in one dispatch.
        op, tidx, alive, val_idx, vals_j = decode_rows(rows, spec.K)
        vals = apply_deltas(corpus["val"], tidx, val_idx, vals_j)
        cap = corpus["val"].shape[0]
        ti = jnp.clip(tidx, 0, cap - 1)
        table_rows = {k: sim_tables[k][ti] for k in TABLE_FIELDS}
        ncalls = sim_tables["ncalls"][ti]
        edges, valid, _ret, _errno, _status = sim_exec_batch(
            table_rows, ncalls, alive, vals, sim_backend,
            interpret=_use_interpret())
        bits = int(sim_plane.shape[0]).bit_length() - 1
        pred, sim_plane = predict_and_mark(edges, valid, sim_plane,
                                           bits)
        admit = novel & (pred | (op == OP_INSERT))
        heat = heat.at[ti].add(admit.astype(jnp.uint32))
        rows, pool_arr, n_used = pool(rows, payloads, needs & admit)
        n_suppressed = (novel & ~admit).sum().astype(jnp.int32)
        rows, n_novel = compact_rows(rows, admit)
        return (rows, pool_arr, n_used, n_novel, plane, sim_plane,
                n_suppressed, heat)

    if prescore:
        return jax.jit(fused_prescore_step)
    return jax.jit(fused_step if fused else step)


class DevicePipeline:
    """Corpus-on-device mutation engine producing exec-ready bytes."""

    def __init__(self, target, cfg: Optional[TensorConfig] = None,
                 capacity: int = 2048, batch_size: int = 2048,
                 rounds: int = 4, seed: int = 0, prefetch: int = 2,
                 spec: Optional[DeltaSpec] = None, ct=None,
                 max_insert_calls: int = 30, dispatch_depth: int = 2,
                 assemble_workers: Optional[int] = None,
                 assemble_depth: int = 2,
                 backend: Optional[str] = None):
        import jax
        import jax.numpy as jnp
        from jax import random

        from syzkaller_tpu.ops.insert import DonorBank, choice_table_rows
        from syzkaller_tpu.ops.pallas_mutate import resolve_mutate_backend
        from syzkaller_tpu.ops.signal import resolve_mutant_plane_bits

        self._jax = jax
        self._jnp = jnp
        self._random = random
        self.target = target
        self.cfg = cfg or PIPELINE_TENSOR_CONFIG
        self.spec = spec or PIPELINE_DELTA_SPEC
        self.flags = FlagTables.empty()
        self.capacity = capacity
        # TZ_PIPELINE_BATCH overrides the constructor batch (envsafe:
        # a malformed value keeps the argument) — the flagship shape
        # moved past 2048 with the Pallas mutation core (ISSUE 10)
        # and the knob lets deployments walk it without code changes.
        batch_size = max(1, env_int("TZ_PIPELINE_BATCH", batch_size))
        self.batch_size = batch_size
        self.stats = PipelineStats()
        _M_BATCH_SIZE.set(batch_size)

        self._lock = threading.Lock()
        self.templates: list[Optional[ProgTensor]] = [None] * capacity
        self.exec_templates: list[Optional[ExecTemplate]] = [None] * capacity
        self._n = 0  # occupied prefix length
        self._next_evict = 0
        self._flags_dev = None
        self._flags_len = 0
        self._key = random.key(seed)

        # Donor bank + ChoiceTable sampling tables for device-side
        # call insertion (ops/insert.py; reference weights give insert
        # ~64% of the device's op draws).
        if ct is None:
            from syzkaller_tpu.models.prio import build_choice_table

            ct = build_choice_table(target)
        self.bank = DonorBank(target, ct, seed=seed)
        runs_np = choice_table_rows(target, ct)
        self._runs_dev = jnp.asarray(runs_np)
        self._by_syscall_dev = jnp.asarray(self.bank.by_syscall)
        n_blocks = len(self.bank)
        # Device-residency ledger (ISSUE 17, telemetry/hbm.py): every
        # long-lived device buffer this pipeline owns registers under
        # owner="pipeline".  The prio/donor tables live for the
        # pipeline's lifetime; corpus/flags/plane handles start empty
        # and track the rebuild cycle (_flush_pending, _launch,
        # _reset_device_state) so a half-open ring rebuild REPLACES
        # entries instead of leaking them.
        self._hbm_prio = telemetry.HBM.register(
            "pipeline", "prio",
            [self._runs_dev, self._by_syscall_dev], bound_to=self)
        self._hbm_flags = telemetry.HBM.register(
            "pipeline", "flags", bound_to=self)
        self._hbm_plane = telemetry.HBM.register(
            "pipeline", "plane", bound_to=self)

        # Mutation-core backend (ISSUE 10, docs/perf.md "The mutation
        # core"): Pallas grid-over-batch kernels on TPU (real branch
        # dispatch per grid cell), the bit-exact vmap path everywhere
        # else or on TZ_MUTATE_BACKEND=vmap.
        self._backend = resolve_mutate_backend(backend)
        _M_MUTATE_BACKEND.set(1 if self._backend == "pallas" else 0)

        # TZ_PIPELINE_FUSED=0 is the kill switch back to the
        # full-batch drain (every row ships, no mutant plane).
        self._fused = env_int("TZ_PIPELINE_FUSED", 1) != 0
        self._plane_bits = resolve_mutant_plane_bits()
        self._mutant_plane = None  # device plane; built at first launch
        # The step executable is keyed on the static shape only — the
        # prio/donor tables ride along as traced arguments at dispatch
        # (self._runs_dev / self._by_syscall_dev), so engines at the
        # same shape share one compile (_shared_step).
        self._rounds = rounds
        self._n_blocks = n_blocks
        self._max_insert_calls = max_insert_calls
        self._seed = seed
        self._step = _shared_step(self.spec, batch_size, rounds,
                                  self._backend, self._fused,
                                  n_blocks, max_insert_calls)
        # Speculative sim-exec prescore (ISSUE 15, syzkaller_tpu/sim):
        # OFF by default; TZ_SIM_PRESCORE=1 (or enable_sim_prescore())
        # fuses a simulated-execution stage after the mutant plane so
        # only predicted-novel rows cross D2H.
        self._sim = None
        self._step_sim = None

        self._queue: queue.Queue = queue.Queue(maxsize=prefetch)
        # In-flight device dispatches the worker keeps ahead of the
        # drain.  Depth 1 serializes [transfer + host assembly] with
        # the next batch's compute; depth 2 pipelines all three stages
        # (compute N+2 ‖ d2h-transfer N+1 ‖ assemble N), which matters
        # on the tunneled chip where the per-batch link transfer is
        # comparable to the kernel time itself.  A malformed env value
        # falls back to the constructor argument (health.envsafe).
        self._dispatch_depth = max(1, env_int(
            "TZ_PIPELINE_DISPATCH_DEPTH", dispatch_depth))
        # Host assembly runs on a pool of TZ_ASSEMBLE_WORKERS threads,
        # template-group sharded so a group's vectorized patch pass is
        # never split.  0 = assemble inline in the drain thread (the
        # pre-pool single-thread behavior).  The default never spawns
        # more assembler threads than spare cores — on a single-core
        # host the pool only adds context switches under the GIL.
        # assemble_depth bounds how many drained batches may sit in
        # assembly at once — together with the prefetch queue cap this
        # is the backpressure chain:
        # procs <- prefetch queue <- assembling deque <- drain.
        if assemble_workers is None:
            import os

            assemble_workers = min(2, max(0, (os.cpu_count() or 1) - 1))
        self._assemble_workers = max(0, env_int(
            "TZ_ASSEMBLE_WORKERS", assemble_workers))
        # assemble_depth is self-tuning by default (TZ_ASSEMBLE_DEPTH
        # =auto|N, ops/staging.DepthController): the worker feeds the
        # measured pool_drain vs assemble_worker span percentiles back
        # into the depth after each collected batch, so the assembly
        # pool stops idling behind D2H on hosts where the link is the
        # slow stage.  A pinned N reproduces the fixed-depth behavior.
        # The controller's ceiling follows the batch shape: past the
        # 2048 flagship batch each drained batch carries ~2x the
        # assembly work, so the pool may hold proportionally more
        # batches before the drain thread must block on a join.
        self._assemble_depth, self._depth_ctrl = \
            resolve_assemble_depth(max(1, assemble_depth),
                                   hi=max(4, batch_size // 1024))
        self._pool = AssemblyPool(self._assemble_workers)
        # Transfer plane (ops/staging): persistent host staging for
        # the corpus-flush scatter — rows re-stack into rotating pow2
        # arena slots instead of fresh np.stack allocations per flush.
        self._staging = StagingArena(slots=2)
        # Device-resident corpus arena (ISSUE 18, ops/arena): the
        # serialized corpus lives in pow2-bucketed device slabs, the
        # per-batch template pick runs ON DEVICE against the arena's
        # cumulative-weight vector, and the host keeps only the
        # durable authority copy.  Shares this pipeline's staging
        # rotation so the corpus-flush scatter's allocation pins
        # (test_staging) hold across arena growth.
        self.arena = CorpusArena(capacity, staging=self._staging)
        # Cadenced Minimize-style distillation over the arena
        # (TZ_ARENA_DISTILL_EVERY; off by default) + the device heat
        # vector the prescored step accumulates novelty yield into.
        self._distill = DistillLane(self.cfg.max_calls)
        self._heat_dev = None
        self._seq = 0  # drain sequence: AssembledBatch.seq values
        # Stacked template table (emit.TemplateTable) for the one-pass
        # batch assembler, cached per exec-template snapshot content
        # (adds/evictions invalidate; steady-state batches reuse), and
        # the flattened donor bank for the one-pass insert splicer.
        self._table_key: Optional[tuple] = None
        self._table: Optional[TemplateTable] = None
        self._dbank_table: Optional[DonorBankTable] = None
        # Self-healing runtime (syzkaller_tpu/health, docs/health.md):
        # the breaker paces recovery after device failures (closed →
        # open → half-open probe with host-snapshot rebuild → closed)
        # and the watchdog bounds wedge-prone blocking calls.  Both
        # are plain attributes so tests and deployments can tune
        # recovery latency without waiting out real backoffs.
        self.breaker = CircuitBreaker(
            failure_threshold=max(1, env_int("TZ_BREAKER_THRESHOLD", 4)),
            backoff_initial=env_float("TZ_BREAKER_BACKOFF_S", 1.0),
            backoff_cap=env_float("TZ_BREAKER_BACKOFF_CAP_S", 60.0),
            seed=seed)
        # 30 s steady-state deadline: the flagship batch completes in
        # well under a second on every measured backend, so 30 s is
        # >30x the worst observed batch while still converting a
        # wedged PJRT call into DeviceWedged 4x sooner than the old
        # 120 s default.  TZ_WATCHDOG_DEADLINE_S restores any value
        # (docs/health.md "Watchdog deadlines").
        self.watchdog = Watchdog(
            deadline_s=env_float("TZ_WATCHDOG_DEADLINE_S", 30.0),
            compile_deadline_s=env_float("TZ_WATCHDOG_COMPILE_S", 600.0))
        self._compiled = False  # first dispatch carries the jit compile
        # Co-resident triage engine (syzkaller_tpu/triage): shares
        # this pipeline's breaker/watchdog and its device session, so
        # a half-open ring rebuild must also invalidate the signal
        # plane (attach_triage wires it).
        self.triage_engine = None
        # Batched hints lane (ops/hintlane): shares this pipeline's
        # breaker/watchdog; attach_hints wires it.
        self._hint_lane = None
        # Fault-domain mesh engine (parallel/fault_domain): when
        # attached, health_snapshot carries the per-shard breaker
        # states so bench_watch's wedge diagnostics see chip loss.
        self._mesh_engine = None
        self._have_corpus = threading.Event()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="device-pipeline", daemon=True)
        self._started = False
        if env_int("TZ_SIM_PRESCORE", 0) != 0 and self._fused:
            self.enable_sim_prescore()
        # Typo guard: a misspelled TZ_* knob parses as "unset" and
        # silently changes nothing — flag it once at engine start.
        warn_unknown_tz_vars()

    @property
    def _corpus_dev(self):
        """The arena's device slabs (compat alias: bench and older
        tests read the pre-arena attribute of the same name)."""
        return self.arena._dev

    # Pre-breaker tuning knobs kept as proxies: tests and deployments
    # set these to shrink recovery latency (test_pipeline.py).
    @property
    def retry_backoff_initial(self) -> float:
        return self.breaker.backoff_initial

    @retry_backoff_initial.setter
    def retry_backoff_initial(self, v: float) -> None:
        self.breaker.configure_backoff(initial=v)

    @property
    def retry_backoff_cap(self) -> float:
        return self.breaker.backoff_cap

    @retry_backoff_cap.setter
    def retry_backoff_cap(self, v: float) -> None:
        self.breaker.configure_backoff(cap=v)

    def attach_triage(self, engine) -> None:
        """Register the co-resident triage engine for plane
        invalidation on host-snapshot ring rebuilds."""
        self.triage_engine = engine
        if self._sim is not None:
            engine.attach_sim(self._sim)
        if self._hint_lane is not None:
            engine.attach_hints(self._hint_lane)

    def attach_hints(self, lane) -> None:
        """Register the co-resident batched hints lane
        (ops/hintlane.HintLane): it shares this pipeline's breaker and
        watchdog (one health verdict for the device) and, when the sim
        prescore is on, rides its epoch clock for replacer-suppression
        decay."""
        self._hint_lane = lane
        if self._sim is not None:
            lane.attach_sim(self._sim)
        if self.triage_engine is not None:
            self.triage_engine.attach_hints(lane)

    def enable_sim_prescore(self, backend=None) -> None:
        """Turn on the speculative sim-exec prescore stage (ISSUE 15).
        Builds the per-pipeline SimPrescore state and the prescored
        step executable; the plain fused step stays compiled as the
        demotion target.  Requires the fused drain (the prescore IS a
        fusion stage); idempotent."""
        if not self._fused:
            raise RuntimeError(
                "sim prescore requires the fused drain "
                "(TZ_PIPELINE_FUSED=1)")
        if self._sim is not None:
            return
        from syzkaller_tpu.sim.prescore import SimPrescore

        self._sim = SimPrescore(
            capacity=self.capacity, max_calls=self.cfg.max_calls,
            backend=backend, seed=self._seed)
        self._step_sim = _shared_step(
            self.spec, self.batch_size, self._rounds, self._backend,
            True, self._n_blocks, self._max_insert_calls,
            True, self._sim.backend)
        if self.triage_engine is not None:
            self.triage_engine.attach_sim(self._sim)
        if self._hint_lane is not None:
            self._hint_lane.attach_sim(self._sim)

    def disable_sim_prescore(self) -> None:
        """Back to the plain fused drain (kill switch / test
        teardown).  The shared step cache keeps the prescored
        executable for a later re-enable."""
        self._sim = None
        self._step_sim = None

    def attach_mesh(self, engine) -> None:
        """Register the co-resident fault-domain mesh engine
        (parallel/fault_domain.MeshEngine): its per-shard health rides
        this pipeline's health_snapshot, and if a triage engine is
        also attached the mesh seeds its signal authority from the
        same host mirror."""
        self._mesh_engine = engine
        # The arena joins the mesh's fault domain (ISSUE 18): chip
        # loss re-shards its slabs from host authority.  Guarded so
        # fault-drill stubs without the hook still attach.
        attach_arena = getattr(engine, "attach_arena", None)
        if attach_arena is not None:
            attach_arena(self.arena)
        if self.triage_engine is not None:
            engine.attach_triage(self.triage_engine)

    def attach_durable(self, store, recovered=None) -> None:
        """Wire the device-side durable sections (ISSUE 13): the
        triage engine's signal-plane mirror journals/checkpoints
        through `store`, the fused drain's mutant plane becomes a
        checkpoint section, and a recovered image re-installs through
        the existing host-mirror paths — one H2D re-upload each via
        `_ensure_plane_locked`/`jnp.asarray`, zero new jit compiles
        (the warm-rig compile guard in test_health_faults pins this).
        Call after attach_triage; `recovered` is the store's
        RecoveredState (or None on a cold start)."""
        rec = recovered or {}
        if self.triage_engine is not None:
            self.triage_engine.durable = store
            store.register("signal_plane",
                           self.triage_engine.durable_provider)
            mirror = rec.get("signal_mirror")
            if mirror is not None:
                try:
                    self.triage_engine.restore_mirror(mirror)
                except ValueError:
                    pass  # plane size changed across the restart
        store.register("mutant_plane", self.durable_mutant_plane)
        mp = rec.get("mutant_plane")
        if mp is not None:
            self.restore_mutant_plane(mp.get("plane"),
                                      bits=mp.get("bits"))
        # Corpus-arena authority (ISSUE 18): serialized programs +
        # sampling weights + epoch checkpoint as one section; a warm
        # restart re-stages every row through add() — ONE flush
        # scatter at the next launch, zero new jits, zero re-triage.
        store.register("corpus_arena", self.durable_corpus_arena)
        ca = rec.get("corpus_arena")
        if ca is not None:
            self.restore_corpus_arena(ca)

    def durable_mutant_plane(self) -> tuple:
        """Checkpoint section: the fused drain's device mutant plane,
        pulled D2H at checkpoint cadence (one blocking transfer; the
        plane is 2^bits bytes)."""
        from syzkaller_tpu.ops.signal import pack_plane

        plane = self._mutant_plane
        if plane is None:
            arr = np.zeros(1 << self._plane_bits, np.uint8)
        else:
            arr = np.asarray(plane, dtype=np.uint8)
        return ({"bits": int(self._plane_bits),
                 "size": int(arr.size)}, pack_plane(arr))

    def restore_mutant_plane(self, plane, bits=None) -> None:
        """Install a recovered mutant plane: one H2D upload through
        the same jnp.asarray path _launch would otherwise use to
        build a zero plane — no new jit.  A bits mismatch (operator
        changed TZ_MUTANT_PLANE_BITS) discards the recovered plane;
        dedup history is advisory, so a cold plane only re-ships old
        mutants once."""
        if plane is None:
            return
        if bits is not None and int(bits) != self._plane_bits:
            return
        arr = np.asarray(plane, dtype=np.uint8)
        if arr.size != (1 << self._plane_bits):
            return
        self._mutant_plane = self._jnp.asarray(arr)
        self._hbm_plane.update(self._mutant_plane)

    def durable_corpus_arena(self) -> tuple:
        """Checkpoint section: the arena's durable authority — every
        occupied row's typed program serialized (models/encoding) +
        its sampling weight + the arena epoch (ops/arena.pack_arena).
        Host-only work: the device slabs are never read back, because
        host authority is always current (stage() writes through)."""
        from syzkaller_tpu.models.encoding import serialize_prog
        from syzkaller_tpu.ops.arena import pack_arena

        with self._lock:
            n = self._n
            progs = []
            for i in range(n):
                t = self.templates[i]
                try:
                    progs.append(serialize_prog(t.template)
                                 if t is not None and
                                 t.template is not None else b"")
                except Exception:
                    progs.append(b"")
            if self.arena.weights is not None:
                weights = self.arena.weights[:n].copy()
            else:
                weights = np.ones(n, np.uint32)
        return pack_arena(progs, weights, self.arena.epoch)

    def restore_corpus_arena(self, section: dict) -> None:
        """Install a recovered corpus-arena section: deserialize each
        program and re-enter it through add() — the encode path is
        deterministic, so the rebuilt templates and exec templates
        match what the checkpoint's rows described, and the next
        flush is the arena's ONE re-upload scatter (no re-jit, no
        re-triage — coverage authority restores separately).  A row
        that no longer deserializes (syscall table drift across the
        restart) is skipped, not fatal."""
        from syzkaller_tpu.models.encoding import deserialize_prog
        from syzkaller_tpu.ops.arena import unpack_arena

        try:
            progs, weights, epoch = unpack_arena(
                section.get("meta") or {}, section.get("blob") or b"")
        except Exception:
            return
        restored = 0
        for k, raw in enumerate(progs):
            if not raw:
                continue
            try:
                p = deserialize_prog(self.target, bytes(raw))
            except Exception:
                continue
            if self.add(p):
                w = int(weights[k]) if k < len(weights) else 1
                if w != 1:
                    self.arena.set_weight(self._n - 1, w)
                restored += 1
        self.arena.restore_epoch(epoch)
        if restored:
            telemetry.record_event(
                "arena.epoch",
                f"arena restore: {restored} rows re-staged from the "
                f"checkpoint authority (epoch {self.arena.epoch})")

    def _compile_key(self, prescore: bool) -> dict:
        """The static shape key of the step executable, as the
        CompileObservatory records it — a storm incident diffs two of
        these to name the churning field."""
        return {
            "B": self.batch_size, "R": self._rounds,
            "backend": self._backend, "fused": self._fused,
            "n_blocks": self._n_blocks,
            "max_insert_calls": self._max_insert_calls,
            "prescore": prescore,
        }

    def _step_cache_size(self) -> int:
        """Summed jit-cache size of this pipeline's step executables
        (the observatory's build sizer; also what the shared warm-rig
        compile guard watches).  A step swapped for a plain wrapper
        (fault-injection tests, the health latch's host fallback) has
        no jit cache and contributes 0 — the sizer must never be the
        thing that kills the worker."""
        n = 0
        for fn in (self._step, self._step_sim):
            sizer = getattr(fn, "_cache_size", None)
            if sizer is not None:
                n += sizer()
        return n

    def health_snapshot(self) -> dict:
        """Breaker + watchdog state for tests and the status page."""
        out = {
            "breaker": self.breaker.snapshot(),
            "watchdog": self.watchdog.snapshot(),
            "worker_errors": self.stats.worker_errors,
            "delivery_errors": self.stats.delivery_errors,
            "assemble_workers": self._assemble_workers,
            "assemble_queue_depth": self._pool.queue_depth(),
            "assemble_depth": self._assemble_depth,
            "assemble_depth_auto": self._depth_ctrl is not None,
            "staging_arena_bytes": self._staging.nbytes,
            "hbm": telemetry.HBM.snapshot(),
            "compiles": telemetry.COMPILES.snapshot(),
        }
        out["arena"] = self.arena.snapshot()
        out["arena"]["distill"] = self._distill.snapshot()
        if self.triage_engine is not None:
            out["triage"] = self.triage_engine.snapshot()
        if self._hint_lane is not None:
            out["hints"] = self._hint_lane.snapshot()
        if self._mesh_engine is not None:
            out["mesh"] = self._mesh_engine.health_snapshot()
        if self._sim is not None:
            out["sim"] = self._sim.snapshot()
        return out

    # -- corpus management -------------------------------------------------

    def add(self, p: Prog) -> bool:
        """Encode p into the device corpus ring (stage host-side;
        flushed as one scatter before the next step).  Returns False
        if p does not tensorize."""
        try:
            t = encode_prog(p.clone(), self.cfg, self.flags)
            et = build_exec_template(t)
        except Exception:
            return False
        with self._lock:
            if self._n < self.capacity:
                i = self._n
                self._n += 1
            else:
                i = self._next_evict
                self._next_evict = (self._next_evict + 1) % self.capacity
                self.stats.evictions += 1
            self.templates[i] = t
            self.exec_templates[i] = et
            self.arena.stage(i, t.arrays())
            self.stats.adds += 1
        self._have_corpus.set()
        return True

    def __len__(self) -> int:
        with self._lock:
            return self._n

    def _flush_pending(self):
        """Apply staged corpus rows to the arena's device slabs (one
        scatter per field, through the arena's begin/commit split).
        Returns (device corpus, n, template snapshot, exec-template
        snapshot, cumw device vector, total sampling weight) — the
        snapshots are taken under the same lock as the arena's
        staging drain (begin_flush), so they describe exactly the
        state the device slabs will hold.  On a device failure the
        arena keeps its pending set, so the worker's retry re-uploads
        exactly what this call could not — the pre-arena re-queue
        contract, now the arena's."""
        jnp = self._jnp
        with self._lock:
            n = self._n
            tmpl = list(self.templates)
            ets = list(self.exec_templates)
            token = self.arena.begin_flush(jnp)
        if n == 0:
            return None, 0, tmpl, ets, None, 0
        corpus, _n_arena, cumw, total = \
            self.arena.commit_flush(jnp, token)
        if corpus is None:
            return None, 0, tmpl, ets, None, 0
        # Flag tables grow as new sets are interned; pad the row count
        # to a power of two so growth doesn't re-jit the step, and
        # re-upload only on growth (the host link is latency-bound).
        # _flags_len is committed only AFTER a successful upload, so a
        # device failure between the two retries the upload instead of
        # leaving a stale device table that under-indexes new sets.
        if self._flags_dev is None or self._flags_len != len(self.flags.counts):
            fv_np, fc_np = self.flags.vals, self.flags.counts
            new_len = len(fc_np)
            rows = pow2_rows(new_len)
            if rows > new_len:
                # The padded tables stage through the same rotating
                # transfer-plane arena as the corpus scatter above
                # (ops/staging): one allocation per pow2 bucket,
                # reused across every later growth re-upload, instead
                # of a fresh np.vstack/np.append pair per flush.
                bufs = self._staging.acquire(("flags", rows), {
                    "vals": ((rows, fv_np.shape[1]), fv_np.dtype),
                    "counts": ((rows,), fc_np.dtype)})
                bufs["vals"][:new_len] = fv_np
                bufs["vals"][new_len:] = 0
                bufs["counts"][:new_len] = fc_np
                bufs["counts"][new_len:] = 0
                fv_np, fc_np = bufs["vals"], bufs["counts"]
            self._flags_dev = (self._jnp.asarray(fv_np),
                               self._jnp.asarray(fc_np))
            self._flags_len = new_len
            self._hbm_flags.update(list(self._flags_dev))
        return corpus, n, tmpl, ets, cumw, total

    # -- the device loop ---------------------------------------------------

    def _launch(self):
        with telemetry.span("pipeline.flush"):
            corpus, n, tmpl, ets, cumw, total = self._flush_pending()
        if corpus is None:
            return None
        # Lineage: one trace context per batch, minted at flush time
        # (TZ_TRACE_SAMPLE; None on the unsampled fast path).
        trace = lineage.mint()
        self._key, sub = self._random.split(self._key)
        fv, fc = self._flags_dev
        # The first dispatch carries the jit trace + (tunneled) XLA
        # compile, so it runs under the compile seam/deadline; steady
        # state runs under the launch seam.  A wedged PJRT call is
        # converted into DeviceWedged by the watchdog instead of
        # hanging the worker forever (BENCH_WEDGE_DIAGNOSIS.md).
        op = "device.launch" if self._compiled else "device.compile"
        # Capture the plane into a local: a concurrent
        # _reset_device_state (breaker re-entry) may null the
        # attribute between this check and the dispatch below, and the
        # jitted step must never see None.  A stale plane is fine —
        # dedup history is advisory and the shapes are pinned.
        plane = self._mutant_plane
        if self._fused and plane is None:
            from syzkaller_tpu.ops.signal import new_mutant_plane

            plane = new_mutant_plane(self._plane_bits)
            self._mutant_plane = plane
        # Speculative prescore (ISSUE 15): stage the sim tables +
        # speculation plane OUTSIDE the dispatch, behind the sim's own
        # breaker and the device.sim fault seam.  ANY failure here
        # demotes to the plain fused step — pass-through, zero lost
        # mutants (the plain path still ships every plane-novel row).
        sim = self._sim
        use_sim = False
        sim_tables = sim_plane = heat = None
        if sim is not None and self._step_sim is not None \
                and sim.breaker.allow():
            try:
                fault_point("device.sim")
                sim_tables = sim.device_tables(ets)
                sim_plane = sim.ensure_plane()
                # The arena heat vector rides the prescored step's
                # outputs (functional update, same discipline as the
                # planes); zeros after an invalidation.
                heat = self._heat_dev
                if heat is None:
                    heat = self._jnp.zeros(
                        (corpus["val"].shape[0],), self._jnp.uint32)
                    self._heat_dev = heat
                use_sim = True
            except Exception as e:
                sim.note_failure(e)

        def dispatch():
            fault_point(op)
            if use_sim:
                try:
                    return self._step_sim(
                        corpus, cumw, total, sub, fv, fc, plane,
                        sim_plane, sim_tables, heat, self._runs_dev,
                        self._by_syscall_dev)
                except FaultInjected:
                    raise
                except Exception as e:
                    sim.note_failure(e)
            if self._fused:
                return self._step(corpus, cumw, total, sub, fv, fc,
                                  plane, self._runs_dev,
                                  self._by_syscall_dev)
            return self._step(corpus, cumw, total, sub, fv, fc,
                              self._runs_dev, self._by_syscall_dev)

        # Spans time the host-observed dispatch (XLA returns async:
        # steady-state launch is enqueue cost; the blocking transfer
        # is timed separately by pipeline.drain).  Literal span names
        # at each site keep tools/lint_metrics.py's grep exact.  The
        # deadline stays DYNAMIC (no deadline_s pin): a knob tightened
        # mid-dispatch applies to the call already in flight.
        if self._compiled:
            with telemetry.span("pipeline.launch"):
                result = self.watchdog.call(dispatch, op)
        else:
            # First dispatch: the jit trace + XLA build happen here,
            # so this is where the CompileObservatory gets the real
            # wall time.  The sizer gates the note on actual jit-cache
            # growth — a warm rig reusing the shared executable
            # records nothing (no storm false-positives, and the
            # `assert_no_new_compiles` guards stay exact).
            with telemetry.span("pipeline.compile"):
                with telemetry.COMPILES.observe(
                        "pipeline.step", self._compile_key(use_sim),
                        sizer=self._step_cache_size):
                    result = self.watchdog.call(dispatch, op,
                                                compile=True)
        self._compiled = True
        # Start the device->host copies now: the tunneled link has a
        # ~70 ms per-sync fixed cost that fully hides behind the next
        # batch's compute (the worker dispatches N+1 before draining N).
        # Unfused, rows + count cover the bulk (the pool bucket waits
        # on the used-slot count).  FUSED, the rows prefix itself
        # depends on the novel count, so only the two scalars start
        # async — the whole point is that the row bulk for non-novel
        # mutants never transfers at all.  An array without an async
        # path (CPU tests, older plugins) falls back to the
        # synchronous drain, counted instead of swallowed silently.
        n_suppr_dev = None
        if len(result) == 8:
            # Prescored fused drain (ISSUE 15): also carry the updated
            # speculation plane, the suppressed-row count, and the
            # arena heat vector (ISSUE 18 — stays resident; the
            # distill cadence folds it into the sampling weights).
            (rows_dev, pool_dev, n_used_dev, n_novel_dev, plane,
             sim_plane_new, n_suppr_dev, heat_new) = result
            self._mutant_plane = plane
            sim.commit(sim_plane_new)
            self._heat_dev = heat_new
            async_arrs = (n_used_dev, n_novel_dev, n_suppr_dev)
        elif self._fused:
            rows_dev, pool_dev, n_used_dev, n_novel_dev, plane = result
            self._mutant_plane = plane
            async_arrs = (n_used_dev, n_novel_dev)
        else:
            rows_dev, pool_dev, n_used_dev = result
            n_novel_dev = None
            async_arrs = (rows_dev, n_used_dev)
        if self._fused:
            # The fused step returns a NEW plane array every batch
            # (functional update): re-point the ledger entry at it so
            # the reconcile identity check follows the live buffer.
            # This handle update is the steady-state ledger tax —
            # bench.py --device pins it ≤ 50 µs/batch.
            self._hbm_plane.update(self._mutant_plane)
        for arr in async_arrs:
            try:
                arr.copy_to_host_async()
            except Exception:
                self.stats.async_copy_fallbacks += 1
                _M_ASYNC_COPY_FALLBACKS.inc()
        # t_dispatch anchors the always-on profiler's dispatch→ready
        # attribution for the fused mutate step (telemetry/profiler).
        return ((rows_dev, pool_dev, n_used_dev, n_novel_dev,
                 n_suppr_dev), tmpl, ets, (trace, time.perf_counter()))

    def _fetch(self, launched):
        """The device->host transfers for one launched batch.
        Unfused: the full delta rows + used-slot count
        (pipeline.drain), then only the pow2-bucketed prefix of the
        payload pool the batch actually claimed (pipeline.pool_drain).
        Fused (ISSUE 10): the plane-novel row count first
        (mutate.fused), then only the compacted novel-row prefix —
        rows the mutant plane already saw never cross D2H at all.
        Blocking syncs where a wedged tunnel stalls, so every fetch
        runs under the watchdog.  Returns (DeltaBatch, template
        snapshot, exec-template snapshot)."""
        (rows_dev, pool_dev, n_used_dev, n_novel_dev, n_suppr_dev), \
            tmpl, ets, meta = launched
        trace, t_dispatch = meta
        if n_suppr_dev is not None:
            # Prescored batch (ISSUE 15): sync the suppression count
            # under its own span so the speculation stage's cost and
            # yield are separately attributable.
            with telemetry.span("sim.prescore"):
                n_sup = int(self.watchdog.call(
                    lambda: np.asarray(n_suppr_dev), "device.drain"))
            sim = self._sim
            if sim is not None:
                sim.note_batch(n_sup, self.batch_size)
            self.stats.sim_batches += 1
            self.stats.sim_suppressed += n_sup
        if n_novel_dev is not None:
            # Fused drain (ISSUE 10): sync the novel count first —
            # that scalar is the fusion boundary — then fetch only
            # the pow2-bucketed row prefix the compaction packed the
            # plane-novel rows into.  lo=64 keeps the bucket set
            # bounded below so near-empty batches still reuse one
            # staging shape.
            with telemetry.span("mutate.fused"):
                n_novel = int(self.watchdog.call(
                    lambda: np.asarray(n_novel_dev), "device.drain"))
            row_bucket = pow2_rows(max(n_novel, 1), lo=64,
                                   hi=self.batch_size)
            with telemetry.span("pipeline.drain"):
                rows = self.watchdog.call(
                    lambda: np.asarray(rows_dev[:row_bucket]),
                    "device.drain")
            rows_wire_bytes = rows.nbytes  # the bucketed prefix
            rows = rows[:n_novel]
            with telemetry.span("pipeline.drain"):
                n_used = int(self.watchdog.call(
                    lambda: np.asarray(n_used_dev), "device.drain"))
            self.stats.fused_batches += 1
            self.stats.fused_novel_rows += n_novel
            _M_FUSED_BATCHES.inc()
            _M_FUSED_NOVEL_ROWS.inc(n_novel)
        else:
            with telemetry.span("pipeline.drain"):
                rows = self.watchdog.call(lambda: np.asarray(rows_dev),
                                          "device.drain")
                n_used = int(self.watchdog.call(
                    lambda: np.asarray(n_used_dev), "device.drain"))
            rows_wire_bytes = rows.nbytes
        # Always-on per-kernel attribution (telemetry/profiler.py):
        # dispatch → delta-rows-ready is the fused mutate step's
        # host-observed device residency; the compacted pool fetch is
        # the emit-compact scatter's sync point.  Pure host float
        # math — no device work, no jits, no allocations.
        t_pool = time.perf_counter()
        mutate_s = t_pool - t_dispatch
        telemetry.PROFILER.note("mutate", mutate_s)
        with telemetry.span("pipeline.pool_drain"):
            bucket = pool_bucket(
                n_used, self.spec.pool_slots(self.batch_size))
            if bucket:
                pool = self.watchdog.call(
                    lambda: np.asarray(pool_dev[:bucket]), "device.drain")
            else:
                pool = np.zeros((0, self.spec.P), np.uint8)
        pool_s = time.perf_counter() - t_pool
        telemetry.PROFILER.note("emit_compact", pool_s)
        # Accounting ledger (ISSUE 14): the same sync-point deltas,
        # booked as device time under the default keys — the composer
        # and triage engine meter their own tenant/lane-attributed
        # residency separately.
        telemetry.ACCOUNTING.note_batch(mutate_s + pool_s)
        nbytes = rows_wire_bytes + pool.nbytes \
            + np.asarray(n_used_dev).nbytes
        self.stats.d2h_bytes += nbytes
        self.stats.d2h_batches += 1
        _M_D2H_BYTES.inc(nbytes)
        _M_D2H_BATCH_BYTES.set(nbytes)
        # Headroom forecast input (ISSUE 17): the observed per-batch
        # working set at the CURRENT (flagship) batch shape — what
        # one in-flight batch needs on top of the resident set.
        telemetry.HBM.note_transient(
            "pipeline", nbytes * self._dispatch_depth)
        batch = DeltaBatch(rows, self.spec, pool=pool)
        batch.trace = trace
        return batch, tmpl, ets

    def _drain(self, launched) -> "AssembledBatch":
        """Fetch + assemble one launched batch synchronously (tests
        and the bench's standalone assembly measurements; the worker
        loop overlaps the same stages instead)."""
        batch, tmpl, ets = self._fetch(launched)
        return self._assemble(batch, tmpl, ets)

    def _assemble(self, batch: DeltaBatch, tmpl, ets) -> "AssembledBatch":
        with telemetry.span("pipeline.assemble"):
            return self._collect(self._submit_assembly((batch, tmpl, ets)))

    def _submit_assembly(self, fetched):
        """Fan one fetched batch out over the assembly pool: mutate
        rows are template-group sharded (groups never split — the
        vectorized patch pass amortizes per group), insert rows are
        one splice task.  Returns the pending handle _collect turns
        into an AssembledBatch."""
        batch, tmpl, ets = fetched
        seq = self._seq
        self._seq += 1
        ok = (batch.flags & FLAG_OVERFLOW) == 0
        overflows = int(np.count_nonzero(~ok))
        self.stats.overflows += overflows
        if overflows:
            _M_OVERFLOWS.inc(overflows)
        ok &= (batch.template_idx >= 0) & (batch.template_idx < len(tmpl))
        is_ins = batch.op == OP_INSERT
        js = np.flatnonzero(ok & ~is_ins)
        table = self._template_table(ets)
        shards = shard_by_template(batch.template_idx, js,
                                   max(1, self._assemble_workers))
        tasks = [(s, self._pool.submit(assemble_batch_table, table,
                                       batch, s))
                 for s in shards]
        ins = np.flatnonzero(ok & is_ins)
        ins_task = None
        if ins.size:
            if self._dbank_table is None:
                self._dbank_table = DonorBankTable(self.bank.blocks)
            ins_task = self._pool.submit(
                self._splice_inserts, batch, tmpl, ets, ins, table)
        return seq, batch, tmpl, ets, tasks, ins_task

    def _template_table(self, ets) -> TemplateTable:
        """Stacked assembly tables for this snapshot (cached: the
        tables only change when the template set does, so steady-state
        batches pay one id-tuple comparison)."""
        key = tuple(map(id, ets))
        if self._table_key != key:
            self._table = TemplateTable(ets)
            self._table_key = key
        return self._table

    def _collect(self, pending_batch) -> "AssembledBatch":
        """Join one batch's assembly shards into delivery order.  The
        per-shard lists stay js-aligned, so recombining loses nothing;
        stats run here (the drain thread) so they stay single-writer."""
        seq, batch, tmpl, ets, tasks, ins_task = pending_batch
        out = AssembledBatch(seq=seq, trace=batch.trace)
        for s, task in tasks:
            if not task.wait(self._stop):
                return out  # shutting down; partial batch is discarded
            # tolist() up front: per-row numpy scalar conversions in
            # this loop were a measurable slice of the assemble stage.
            for j, i, data in zip(s.tolist(),
                                  batch.template_idx[s].tolist(),
                                  task.result):
                if data is None:
                    self.stats.assemble_errors += 1
                    _M_ASSEMBLE_ERRORS.inc()
                    continue
                t = tmpl[i]
                if t is None:
                    continue
                out.append(ExecMutant(data, t, ets[i], batch, j))
        if ins_task is not None:
            if not ins_task.wait(self._stop):
                return out
            mutants, errors = ins_task.result
            out.extend(mutants)
            self.stats.inserts += len(mutants)
            if errors:
                self.stats.assemble_errors += errors
                _M_ASSEMBLE_ERRORS.inc(errors)
        self.stats.batches += 1
        self.stats.mutants += len(out)
        _M_BATCHES.inc()
        _M_MUTANTS.inc(len(out))
        return out

    def _splice_inserts(self, batch: DeltaBatch, tmpl, ets,
                        ins: np.ndarray, table=None):
        """Insert mutants: pristine template segments + donor splice.
        The one-pass splicer (emit.splice_batch_table) handles every
        tiled fully-alive row across ALL templates in four global
        ragged operations; the remainder (dead calls, budget
        overflows) goes through the per-template-group splicer.  Runs
        as one pool task; returns (mutants, error count)."""
        out: list[ExecMutant] = []
        errors = 0
        blocks = self.bank.blocks
        ins = np.asarray(ins, dtype=np.int64)
        if table is not None and self._dbank_table is not None:
            try:
                datas, fast = splice_batch_table(
                    table, self._dbank_table, batch, ins)
            except Exception:
                datas, fast = [None] * len(ins), np.zeros(len(ins), bool)
            fidx = np.flatnonzero(fast)
            fj = ins[fidx]
            for idx, j, i, dn, po in zip(
                    fidx.tolist(), fj.tolist(),
                    batch.template_idx[fj].tolist(),
                    batch.donor[fj].tolist(), batch.pos[fj].tolist()):
                out.append(ExecMutant(datas[idx], tmpl[i], ets[i],
                                      batch, j, donor=blocks[dn],
                                      donor_pos=po))
            ins = ins[~fast]
            if not ins.size:
                return out, errors
        donors = batch.donor[ins]
        d_ok = (donors >= 0) & (donors < len(blocks))
        tidx = batch.template_idx[ins]
        order = np.argsort(tidx, kind="stable")
        bounds = np.flatnonzero(np.diff(tidx[order])) + 1
        for grp in np.split(order, bounds):
            ti = int(tidx[grp[0]])
            t = tmpl[ti] if 0 <= ti < len(tmpl) else None
            et = ets[ti] if 0 <= ti < len(ets) else None
            if t is None or et is None:
                continue
            sel = grp[d_ok[grp]]
            if not sel.size:
                continue
            rows = ins[sel]
            # The arena-flat donor path (ISSUE 18): donor words come
            # straight out of the shared DonorBankTable flat arrays
            # and the copyout rebase is an in-arena add — no per-base
            # build_donor_table re-stack, so the old per-ncopyouts
            # table cache is gone entirely.
            if self._dbank_table is None:
                self._dbank_table = DonorBankTable(blocks)
            try:
                datas = splice_insert_group_flat(
                    et, batch.alive_bits[rows], donors[sel],
                    batch.pos[rows], self._dbank_table)
            except Exception:
                # Degrade to the per-mutant splice so one bad row
                # cannot sink its template group.
                datas = []
                for j in rows:
                    try:
                        datas.append(splice_insert(
                            et, batch.call_alive(j, max(et.ncalls, 1)),
                            blocks[int(batch.donor[j])],
                            int(batch.pos[j])))
                    except Exception:
                        datas.append(None)
            for j, data in zip(rows, datas):
                if data is None:
                    errors += 1
                    continue
                out.append(ExecMutant(
                    data, t, et, batch, int(j),
                    donor=blocks[int(batch.donor[j])],
                    donor_pos=int(batch.pos[j])))
        return out, errors

    def _reset_device_state(self) -> None:
        """Drop device buffers and re-stage every live template from
        the host-side snapshot.  Recovery path for failures that
        invalidate existing device buffers (a backend/session restart,
        not just a refused compile): the host templates are the
        authoritative corpus, so the next successful flush rebuilds
        the ring from scratch."""
        with self._lock:
            self._flags_dev = None
            self._flags_len = 0
            # The mutant dedup plane lived in the same device session;
            # rebuild it zeroed.  Losing cross-batch dedup history is
            # safe — previously-seen rows just ship once more.  Same
            # for the arena heat vector: unfolded heat is advisory
            # sampling bias, not corpus state.
            self._mutant_plane = None
            self._heat_dev = None
            # The ledger must drop the dead buffers with them: a
            # half-open rebuild that left stale entries would read as
            # an hbm.drift leak at the next reconcile.
            self._hbm_flags.update(None)
            self._hbm_plane.update(None)
        # Epoch bump: every occupied arena row re-stages from host
        # authority — ONE scatter at the next flush, zero new jits.
        self.arena.invalidate()
        if self.triage_engine is not None:
            # The signal plane is co-resident with the corpus ring: a
            # restarted backend invalidated its buffer too, so it must
            # re-upload from the host mirror on the same re-entry.
            self.triage_engine.invalidate_device_plane()
        if self._sim is not None:
            # Same session: the stacked sim tables and speculation
            # plane re-upload from host state on the next launch.
            self._sim.invalidate_device_state()

    def _distill_round(self) -> None:
        """One cadenced distillation round (ISSUE 18): pull the
        device heat vector into the sampling weights, then run the
        fused bisection batch — sim-exec original + suffix-truncation
        candidates for the lane's next row window, keep the shortest
        candidate whose predicted edge folds cover the original's,
        and retire the superseded rows by truncating their templates
        in place and re-staging the shrunken rows over the same
        slots.  Runs from the worker thread between batches, under
        the device.arena seam; device time books to lane=distill."""
        from syzkaller_tpu.ops.arena import (
            build_distill_batch,
            truncated_alive,
        )

        lane = self._distill
        with self._lock:
            n = self._n
            tmpl = list(self.templates)
            ets = list(self.exec_templates)
        # Heat fold first: even a round with no eligible rows turns
        # the device-observed novelty yield into sampling weights.
        heat = self._heat_dev
        if heat is not None:
            self.arena.fold_heat(np.asarray(heat))
        slots = lane.select_slots(tmpl, n)
        if not slots:
            return
        fault_point("device.arena")
        t0 = time.perf_counter()
        with telemetry.span("arena.distill"):
            table_rows, ncalls, alive, vals, keeps = \
                build_distill_batch(self.arena, tmpl, ets, slots,
                                    self.cfg.max_calls,
                                    lane.max_cands)
            covers, _n_orig = lane.check(table_rows, ncalls, alive,
                                         vals)
        elapsed = time.perf_counter() - t0
        wins = lane.choose(covers, keeps)
        retired = 0
        for r, m in enumerate(wins):
            if m is None:
                continue
            i = slots[r]
            with self._lock:
                t = self.templates[i]
                if t is not tmpl[i]:
                    continue  # slot re-used mid-round: verdict stale
                mask = truncated_alive(t.call_alive,
                                       int(keeps[r, m]))
                t.call_alive[:] = mask
                self.arena.stage(i, t.arrays())
            retired += 1
        lane.retired += retired
        self.arena.note_retired(retired)
        # Accounting (ISSUE 14): the round's device residency books
        # to lane=distill — tz_acct_device_ms_total{lane="distill"}
        # is the composer's view of what hygiene costs.
        telemetry.ACCOUNTING.note_batch(
            elapsed,
            lane_rows={"distill": len(slots) * (lane.max_cands + 1)})
        telemetry.record_event(
            "arena.distill",
            f"distill round {lane.rounds}: {len(slots)} rows, "
            f"{retired} retired")

    def _worker_loop(self) -> None:
        from collections import deque

        from syzkaller_tpu.health.breaker import HALF_OPEN
        from syzkaller_tpu.utils import log

        pending: deque = deque()  # launched, not yet drained
        assembling: deque = deque()  # drained, fanned out on the pool
        while not self._stop.is_set():
            if not self._have_corpus.wait(timeout=0.2):
                continue
            # A device failure must not kill the worker thread: the
            # tunneled backend can refuse COMPILES while the session
            # stays up (BENCH_WEDGE_DIAGNOSIS.md §8 mode 3), and a
            # dead worker would pin the fuzzer's health latch demoted
            # forever.  The circuit breaker owns the recovery policy:
            # a failure streak trips it open (in-flight work dropped,
            # consumers demote to CPU mutation), probes re-enter with
            # exponential backoff + jitter, and EVERY half-open
            # re-entry rebuilds the device ring from the host-side
            # snapshot — not just the 4th error, so long failure
            # streaks keep re-triggering rebuilds (ADVICE.md r5).
            if not self.breaker.allow():
                wait = min(0.2, max(0.02,
                                    self.breaker.seconds_until_probe()))
                _M_BACKOFF_WAITS.inc()
                _M_BACKOFF_SECONDS.inc(wait)
                if self._stop.wait(timeout=wait):
                    return
                continue
            probing = self.breaker.state == HALF_OPEN
            try:
                if self.breaker.consume_rebuild():
                    # Re-entering half-open: the backend may have
                    # restarted and invalidated the old buffers —
                    # rebuild the ring from the host template snapshot
                    # before the probe batch.
                    log.logf(0, "device pipeline: rebuilding device "
                                "state from the host corpus snapshot "
                                "(probe #%d)",
                             self.breaker.counters.half_opens)
                    self._reset_device_state()
                # Keep `dispatch_depth` batches in flight before
                # draining the oldest, and `assemble_depth` drained
                # batches fanned out over the assembly pool before
                # joining the oldest — device compute, d2h transfer,
                # and host assembly overlap as independent pipeline
                # stages, and assembly itself runs template-group
                # sharded across the pool.  A probe window flies a
                # single batch end to end: the point is a cheap health
                # verdict, not throughput.
                depth = 1 if probing else self._dispatch_depth
                a_depth = 1 if probing else self._assemble_depth
                while len(pending) < depth and not self._stop.is_set():
                    launched = self._launch()
                    if launched is None:
                        break
                    pending.append(launched)
                if pending:
                    fetched = self._fetch(pending.popleft())
                    assembling.append(self._submit_assembly(fetched))
                if not assembling:
                    continue
                if len(assembling) < a_depth and pending:
                    continue  # keep draining while the pool chews
                with telemetry.span("pipeline.assemble"):
                    batch = self._collect(assembling.popleft())
            except Exception as e:
                pending.clear()
                assembling.clear()
                self.stats.worker_errors += 1
                _M_WORKER_ERRORS.inc()
                state = self.breaker.record_failure()
                log.logf(0, "device pipeline worker error (#%d, "
                            "breaker %s, next probe in %.1fs): %s",
                         self.stats.worker_errors, state,
                         self.breaker.seconds_until_probe(),
                         str(e)[:200])
                continue
            if self._stop.is_set():
                return
            self.breaker.record_success()
            # Cadenced arena distillation (ISSUE 18): opt-in via
            # TZ_ARENA_DISTILL_EVERY; a failed round counts and skips
            # — corpus hygiene must never trip the device breaker.
            if self.arena.device_enabled and self._distill.tick():
                try:
                    self._distill_round()
                except Exception as e:
                    self._distill.errors += 1
                    log.logf(0, "arena distill round failed "
                                "(#%d): %s", self._distill.errors,
                             str(e)[:200])
            # Self-tuning drain->assemble overlap: one controller tick
            # per collected batch feeds the measured pool_drain vs
            # assemble_worker percentiles back into assemble_depth
            # (clamped + hysteretic; a pinned TZ_ASSEMBLE_DEPTH=N has
            # no controller).  Host-only arithmetic — no device work,
            # no jits.
            if self._depth_ctrl is not None:
                self._assemble_depth = self._depth_ctrl.update()
            try:
                # The delivery seam (one invocation per produced
                # batch, so occurrence plans stay deterministic under
                # queue backpressure): a scripted failure drops the
                # batch — costing only its slot — but must not kill
                # the worker or trip the device breaker.
                fault_point("queue.put")
            except FaultInjected as e:
                self.stats.delivery_errors += 1
                _M_DELIVERY_ERRORS.inc()
                log.logf(0, "device pipeline: batch dropped at "
                            "delivery seam: %s", e)
                continue
            while not self._stop.is_set():
                try:
                    self._queue.put(batch, timeout=0.2)
                    _M_QUEUE_DEPTH.set(self._queue.qsize())
                    # Lineage: the batch reached the prefetch queue —
                    # flush → delivery is the device+assembly
                    # residency hop of a sampled mutant's track.
                    lineage.hop(batch.trace, "pipeline.deliver")
                    break
                except queue.Full:
                    continue

    # -- consumer API ------------------------------------------------------

    def start(self) -> None:
        if not self._started:
            self._started = True
            self._worker.start()

    def stop(self) -> None:
        """Stop the worker and join it: a daemon thread killed inside
        an XLA dispatch aborts the process at interpreter exit.
        Consumers blocked in next()/next_batch() wake within their
        poll interval and see queue.Empty/None."""
        self._stop.set()
        if self._started:
            # Unblock a worker stuck on a full queue.
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._worker.join(timeout=30)
        self._pool.stop()

    def next_batch(self,
                   timeout: Optional[float] = None) -> "AssembledBatch":
        """One assembled batch — a list of ExecMutants carrying its
        drain sequence number (blocks until the worker produces one,
        the timeout expires, or the pipeline is stopped — the last two
        raise queue.Empty)."""
        self.start()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._stop.is_set():
                raise queue.Empty
            wait = 0.2
            if deadline is not None:
                wait = min(wait, deadline - time.monotonic())
                if wait <= 0:
                    raise queue.Empty
            try:
                batch = self._queue.get(timeout=wait)
                _M_QUEUE_DEPTH.set(self._queue.qsize())
                return batch
            except queue.Empty:
                continue

    def next(self, timeout: float = 10.0) -> Optional[ExecMutant]:
        """Single-mutant convenience used by proc loops."""
        with self._lock:
            buf = getattr(self, "_buf", None)
            if buf:
                return buf.pop()
        try:
            batch = self.next_batch(timeout=timeout)
        except queue.Empty:
            return None
        with self._lock:
            self._buf = batch
            return self._buf.pop() if self._buf else None
