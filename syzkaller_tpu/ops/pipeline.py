"""Device-resident mutation pipeline: corpus tensors live on device,
mutants come back as exec-ready bytes.

Round-1's engine shipped templates host->device on every batch, re-jit
on varying shapes, and decoded every mutant back to a typed tree
(~3-15 mutants/s end to end).  This pipeline closes that gap:

  - the corpus is a ring of stacked program tensors RESIDENT on
    device; adds are staged host-side and flushed as one scatter,
  - one jitted step at a STATIC batch shape samples templates
    uniformly (reference corpus pick: syz-fuzzer/proc.go:92) and
    mutates them in a single fused vmap — no per-batch recompile,
  - mutated rows come back as numpy and become exec wire bytes via
    the patch-table assembler (ops/emit.py) — no typed decode on the
    hot path; ExecMutant decodes lazily for the rare triaged input,
  - a background worker keeps `prefetch` assembled batches queued
    while executors drain the previous one (double buffering,
    SURVEY.md §7 hard part (c)).

Structural ops the device cannot express (squash/splice/insert) stay
host-side: fuzzer.proc.PipelineMutator draws the reference op ladder
per mutant and routes the device classes (~28% of iterations:
arg-mutate + remove) here, so the integrated op distribution matches
the reference weighted loop (reference: prog/mutation.go:19-131).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from syzkaller_tpu.models.prog import Prog
from syzkaller_tpu.ops.delta import (
    FLAG_OVERFLOW,
    DeltaBatch,
    DeltaSpec,
    make_packer,
)
from syzkaller_tpu.ops.emit import (
    ExecTemplate,
    assemble_batch,
    build_exec_template,
    mutant_call_ids,
)
from syzkaller_tpu.ops.tensor import (
    FlagTables,
    ProgTensor,
    TensorConfig,
    decode_prog,
    encode_prog,
)

# Fraction of reference mutation iterations whose op class the device
# kernels cannot express (squash 1/5, splice 1/100 of the rest, insert
# 20/31 of the rest); the complement routes to the device.  Used by
# tests/bench to reason about the integrated throughput mix
# (reference weights: prog/mutation.go:19-131).
P_HOST_STRUCTURAL = 0.2 + 0.8 * (1 / 100) + 0.8 * (99 / 100) * (20 / 31)


class ExecMutant:
    """A device-produced mutant: exec bytes now, typed program on
    demand (only triage/logging ever needs the tree).  Holds a view
    into its DeltaBatch; the full tensor row is rebuilt from template
    + delta only when prog() is called."""

    __slots__ = ("exec_bytes", "template", "et", "batch", "j",
                 "_calls", "_prog")

    def __init__(self, exec_bytes: bytes, template: ProgTensor,
                 et: ExecTemplate, batch: DeltaBatch, j: int):
        self.exec_bytes = exec_bytes
        self.template = template
        self.et = et
        self.batch = batch
        self.j = j
        self._calls: Optional[list[int]] = None
        self._prog: Optional[Prog] = None

    @property
    def target(self):
        return self.template.template.target

    def call_map(self) -> list[int]:
        """Mutant call position -> template call index."""
        if self._calls is None:
            alive = self.batch.call_alive(
                self.j, self.template.call_alive.shape[0])
            self._calls = mutant_call_ids(self.et, alive)
        return self._calls

    def num_calls(self) -> int:
        return len(self.call_map())

    def contains_any_call(self, call_index: int) -> bool:
        """Whether the mutant call is a squashed-ANY form, without
        decoding (device ops never introduce ANY; the template's
        per-call flags are exact)."""
        cm = self.call_map()
        if call_index >= len(cm):
            return False
        return bool(self.et.calls_any[cm[call_index]])

    def signal_prio(self, errno: int, call_index: int) -> int:
        """Edge priority for an executed mutant call, computed without
        typed decode (reference: syz-fuzzer/fuzzer.go:513-521)."""
        prio = 0
        if errno == 0:
            prio |= 1 << 1
        if not self.contains_any_call(call_index):
            prio |= 1 << 0
        return prio

    def prog(self) -> Prog:
        """Decode to a typed program (cached; reference semantics:
        ops/tensor.decode_prog)."""
        if self._prog is None:
            row = self.batch.rebuild_row(self.j, self.template)
            self._prog = decode_prog(
                self.template, row,
                preserve_sizes=bool(row["preserve_sizes"]))
        return self._prog


@dataclass
class PipelineStats:
    batches: int = 0
    mutants: int = 0
    adds: int = 0
    evictions: int = 0
    assemble_errors: int = 0
    overflows: int = 0  # delta rows exceeding the K/D/P budget


# Lean device shapes for the pipeline: mutation cost is dominated by
# arena-roll traffic (measured 2.8x faster at 2048 than 8192), and the
# delta payload must hold a mutant's changed spans.
PIPELINE_TENSOR_CONFIG = TensorConfig(
    max_calls=32, max_slots=128, arena=2048, max_blob=768)


class DevicePipeline:
    """Corpus-on-device mutation engine producing exec-ready bytes."""

    def __init__(self, target, cfg: Optional[TensorConfig] = None,
                 capacity: int = 2048, batch_size: int = 512,
                 rounds: int = 4, seed: int = 0, prefetch: int = 2,
                 spec: Optional[DeltaSpec] = None):
        import jax
        import jax.numpy as jnp
        from jax import random

        from syzkaller_tpu.ops.mutate import _mutate_one

        self._jax = jax
        self._jnp = jnp
        self._random = random
        self.target = target
        self.cfg = cfg or PIPELINE_TENSOR_CONFIG
        self.spec = spec or DeltaSpec()
        self.flags = FlagTables.empty()
        self.capacity = capacity
        self.batch_size = batch_size
        self.stats = PipelineStats()

        self._lock = threading.Lock()
        self.templates: list[Optional[ProgTensor]] = [None] * capacity
        self.exec_templates: list[Optional[ExecTemplate]] = [None] * capacity
        self._n = 0  # occupied prefix length
        self._next_evict = 0
        self._pending_rows: list[tuple[int, dict]] = []
        self._corpus_dev: Optional[dict] = None
        self._flags_dev = None
        self._flags_len = 0
        self._key = random.key(seed)

        B, R = batch_size, rounds
        pack = make_packer(self.spec)

        def step(corpus: dict, n: int, key, flag_vals, flag_counts):
            k_idx, k_mut = random.split(key)
            idx = (random.bits(k_idx, (B,), dtype=jnp.uint32)
                   % jnp.maximum(n, 1).astype(jnp.uint32)).astype(jnp.int32)
            batch = {k: v[idx] for k, v in corpus.items()}
            keys = random.split(k_mut, B)

            def one(st, k, i):
                mutated = _mutate_one(st, k, flag_vals, flag_counts, R)
                return pack(mutated, i)

            return jax.vmap(one)(batch, keys, idx)

        self._step = jax.jit(step)

        self._queue: queue.Queue = queue.Queue(maxsize=prefetch)
        self._have_corpus = threading.Event()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="device-pipeline", daemon=True)
        self._started = False

    # -- corpus management -------------------------------------------------

    def add(self, p: Prog) -> bool:
        """Encode p into the device corpus ring (stage host-side;
        flushed as one scatter before the next step).  Returns False
        if p does not tensorize."""
        try:
            t = encode_prog(p.clone(), self.cfg, self.flags)
            et = build_exec_template(t)
        except Exception:
            return False
        with self._lock:
            if self._n < self.capacity:
                i = self._n
                self._n += 1
            else:
                i = self._next_evict
                self._next_evict = (self._next_evict + 1) % self.capacity
                self.stats.evictions += 1
            self.templates[i] = t
            self.exec_templates[i] = et
            self._pending_rows.append((i, t.arrays()))
            self.stats.adds += 1
        self._have_corpus.set()
        return True

    def __len__(self) -> int:
        with self._lock:
            return self._n

    def _flush_pending(self):
        """Apply staged corpus rows to the device arrays (one scatter
        per field).  Returns (device corpus, n, template snapshot,
        exec-template snapshot) — the snapshots are taken under the
        same lock as the pending drain, so they describe exactly the
        state the device arrays will hold."""
        jnp = self._jnp
        with self._lock:
            pending, self._pending_rows = self._pending_rows, []
            n = self._n
            tmpl = list(self.templates)
            ets = list(self.exec_templates)
        if n == 0:
            return None, 0, tmpl, ets
        if self._corpus_dev is None:
            proto = pending[0][1] if pending else tmpl[0].arrays()
            self._corpus_dev = {
                k: jnp.zeros((self.capacity,) + np.shape(v),
                             dtype=np.asarray(v).dtype)
                for k, v in proto.items()}
        if pending:
            # Ring wrap can stage two rows for the same slot; XLA
            # scatter order with duplicate indices is unspecified, so
            # keep only the LAST row per index (matching the host
            # template snapshot).
            last = {i: r for i, r in pending}
            idx = np.array(list(last.keys()), dtype=np.int32)
            for k in self._corpus_dev:
                rows = np.stack([np.asarray(r[k]) for r in last.values()])
                self._corpus_dev[k] = self._corpus_dev[k].at[idx].set(rows)
        # Flag tables grow as new sets are interned; pad the row count
        # to a power of two so growth doesn't re-jit the step, and
        # re-upload only on growth (the host link is latency-bound).
        if self._flags_dev is None or self._flags_len != len(self.flags.counts):
            fv_np, fc_np = self.flags.vals, self.flags.counts
            self._flags_len = len(fc_np)
            rows = 1 << max(0, (len(fc_np) - 1).bit_length())
            if rows > len(fc_np):
                fv_np = np.vstack([fv_np, np.zeros(
                    (rows - len(fc_np), fv_np.shape[1]), dtype=fv_np.dtype)])
                fc_np = np.append(fc_np, np.zeros(rows - len(fc_np),
                                                  dtype=fc_np.dtype))
            self._flags_dev = (self._jnp.asarray(fv_np),
                               self._jnp.asarray(fc_np))
        return self._corpus_dev, n, tmpl, ets

    # -- the device loop ---------------------------------------------------

    def _launch(self):
        corpus, n, tmpl, ets = self._flush_pending()
        if corpus is None:
            return None
        self._key, sub = self._random.split(self._key)
        fv, fc = self._flags_dev
        rows_dev = self._step(corpus, n, sub, fv, fc)
        # Start the device->host copy now: the tunneled link has a
        # ~70 ms per-sync fixed cost that fully hides behind the next
        # batch's compute (the worker dispatches N+1 before draining N).
        try:
            rows_dev.copy_to_host_async()
        except Exception:
            pass  # CPU arrays in tests have no async path
        return rows_dev, tmpl, ets

    def _drain(self, launched) -> list[ExecMutant]:
        rows_dev, tmpl, ets = launched
        buf = np.asarray(rows_dev)  # the one device->host transfer
        batch = DeltaBatch(buf, self.spec)
        ok = (batch.flags & FLAG_OVERFLOW) == 0
        self.stats.overflows += int(np.count_nonzero(~ok))
        ok &= (batch.template_idx >= 0) & (batch.template_idx < len(tmpl))
        js = np.flatnonzero(ok)
        datas = assemble_batch(ets, batch, js)
        out: list[ExecMutant] = []
        for j, data in zip(js, datas):
            if data is None:
                self.stats.assemble_errors += 1
                continue
            i = int(batch.template_idx[j])
            t = tmpl[i]
            if t is None:
                continue
            out.append(ExecMutant(data, t, ets[i], batch, int(j)))
        self.stats.batches += 1
        self.stats.mutants += len(out)
        return out

    def _worker_loop(self) -> None:
        pending = None
        while not self._stop.is_set():
            if not self._have_corpus.wait(timeout=0.2):
                continue
            if pending is None:
                pending = self._launch()
                continue
            nxt = self._launch()  # dispatch N+1 before assembling N
            batch = self._drain(pending)
            pending = nxt
            while not self._stop.is_set():
                try:
                    self._queue.put(batch, timeout=0.2)
                    break
                except queue.Full:
                    continue

    # -- consumer API ------------------------------------------------------

    def start(self) -> None:
        if not self._started:
            self._started = True
            self._worker.start()

    def stop(self) -> None:
        """Stop the worker and join it: a daemon thread killed inside
        an XLA dispatch aborts the process at interpreter exit.
        Consumers blocked in next()/next_batch() wake within their
        poll interval and see queue.Empty/None."""
        self._stop.set()
        if self._started:
            # Unblock a worker stuck on a full queue.
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._worker.join(timeout=30)

    def next_batch(self, timeout: Optional[float] = None) -> list[ExecMutant]:
        """One assembled batch (blocks until the worker produces one,
        the timeout expires, or the pipeline is stopped — the last two
        raise queue.Empty)."""
        self.start()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._stop.is_set():
                raise queue.Empty
            wait = 0.2
            if deadline is not None:
                wait = min(wait, deadline - time.monotonic())
                if wait <= 0:
                    raise queue.Empty
            try:
                return self._queue.get(timeout=wait)
            except queue.Empty:
                continue

    def next(self, timeout: float = 10.0) -> Optional[ExecMutant]:
        """Single-mutant convenience used by proc loops."""
        with self._lock:
            buf = getattr(self, "_buf", None)
            if buf:
                return buf.pop()
        try:
            batch = self.next_batch(timeout=timeout)
        except queue.Empty:
            return None
        with self._lock:
            self._buf = batch
            return self._buf.pop() if self._buf else None
