"""Pallas TPU mutation core: grid-over-batch kernels for the
mutate -> delta-pack -> pool-compact hot loop.

The vmap'd `_mutate_one` executes EVERY mutation-op branch of its
`lax.switch` for every slot of every program in the batch — on TPU
the whole 7-op byte engine plus the four value mutators run
unconditionally per round, and only one result survives the select.
Pallas changes the execution shape, not the math: the batch becomes
the GRID (one kernel invocation per program, `BlockSpec((1, ...))`
row blocks), so each grid cell is an unbatched trace where
`lax.switch` lowers to a real branch — a cell that drew `op_flip`
never touches the insert/remove/append roll pyramids at all.  The
arithmetic inside each branch is unchanged (the kernels call the
SAME `_mutate_one` / `make_packer` bodies ops/mutate and ops/delta
export), so the Pallas path is bit-exact with the vmap path by
construction: same threefry keys in, same bytes out.  That identity
is what lets `TZ_MUTATE_BACKEND=vmap` stay a drop-in fallback and
what tests/test_pallas_mutate.py pins over randomized keys.

Three kernels:

  mutate        per-cell `_mutate_one` (the `_mutate_slot` value ops
                and the `_mutate_data_span` byte-arena engine),
                returning the full mutated state batch — the
                `make_mutator(backend="pallas")` path,
  mutate+pack   the pipeline core: per-cell mutate, insert-class
                journal masking, and the ops/delta row/payload pack
                fused into one kernel so the packed 228-byte row is
                produced where the state already sits in registers,
  pool assign   the scatter-gather pool compactor as a GRID-SEQUENTIAL
                kernel: TPU grid cells run in order, so the pool-slot
                prefix sum is one SMEM scratch counter carried across
                cells instead of a batch-wide cumsum + scatter.

Mechanics shared by the per-row kernels: PRNG keys cross the
pallas_call boundary as raw `key_data` words (uint32[B, 2]) and are
re-wrapped inside the kernel — threefry is ordinary jax arithmetic,
so the in-kernel stream is identical to the vmap path's — and the
RNG/mutator module constants (`_INT_ARITH_P`, the interesting-int
table, ...) are hoisted into explicit kernel inputs via
`jax.closure_convert`, since a Pallas kernel may not capture array
constants.  On CPU backends the kernels run in interpret mode (slow,
grid serialized through the evaluator — correctness fallback only);
`resolve_mutate_backend` therefore auto-selects vmap off-TPU and
Pallas on TPU, with `TZ_MUTATE_BACKEND=pallas|vmap` as the override
(health.envsafe discipline: a typo degrades to auto).  docs/perf.md
"The mutation core" covers the kernel anatomy and when each backend
engages.
"""

from __future__ import annotations

import functools

from syzkaller_tpu.health.envsafe import env_choice

#: Batch fields whose leading axis is the grid (everything
#: ProgTensor.arrays() stacks); kept sorted so in_spec order is
#: deterministic across processes.
_STATE_KEYS = ("arena", "aux0", "aux1", "call", "call_alive",
               "call_id", "cap", "flag_set", "kind", "len_",
               "len_target", "ncalls", "off", "val", "width")
#: _mutate_one adds these journals to its result state.
_OUT_EXTRA = ("preserve_sizes", "touched")


def resolve_mutate_backend(explicit: str | None = None) -> str:
    """The backend the mutation core should run on: an explicit
    argument wins, then TZ_MUTATE_BACKEND=pallas|vmap|auto, then
    auto-detect — Pallas only where it compiles to real kernels
    (TPU); every other backend gets the bit-exact vmap path so
    tier-1 never pays the interpret-mode grid serialization."""
    if explicit in ("pallas", "vmap"):
        return explicit
    choice = env_choice("TZ_MUTATE_BACKEND", "auto",
                        ("auto", "pallas", "vmap"))
    if choice in ("pallas", "vmap"):
        return choice
    import jax

    return "pallas" if jax.default_backend() == "tpu" else "vmap"


def _use_interpret() -> bool:
    """Interpret mode everywhere a Mosaic lowering doesn't exist —
    the CPU fallback that keeps tier-1 runnable without a TPU."""
    import jax

    return jax.default_backend() != "tpu"


def _row_spec(rest):
    """BlockSpec((1, *rest)) row block over the grid — grid cell i
    sees exactly program i's row."""
    from jax.experimental import pallas as pl

    nd = len(rest)
    return pl.BlockSpec((1,) + tuple(rest),
                        lambda i, _nd=nd: (i,) + (0,) * _nd)


def _full_spec(shape):
    """Whole-array block, the same view for every grid cell (shared
    flag tables, hoisted constants, the payload pool)."""
    from jax.experimental import pallas as pl

    nd = len(shape)
    return pl.BlockSpec(tuple(shape), lambda i, _nd=nd: (0,) * _nd)


def _grid_apply(per_row, row_arrays, full_arrays, out_shapes,
                out_dtypes, interpret):
    """Run `per_row(*rows_i, *full_arrays)` once per grid cell i.

    row_arrays are (B, *rest) — cell i receives the squeezed row i of
    each; full_arrays are broadcast whole.  Array constants the
    per-row function closes over (RNG tables) are hoisted into extra
    kernel inputs via closure_convert — Pallas kernels may not
    capture non-scalar constants.  Returns one (B, *shape) output per
    entry of out_shapes."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b = row_arrays[0].shape[0]
    ex = [jax.ShapeDtypeStruct(a.shape[1:], a.dtype)
          for a in row_arrays]
    ex += [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in full_arrays]
    # jax.closure_convert only hoists inexact-dtype constants (it is
    # built for custom-derivative plumbing), so the uint64 RNG tables
    # would stay captured; trace to a jaxpr ourselves and hoist EVERY
    # constant into a kernel input.
    closed_jaxpr = jax.make_jaxpr(per_row)(*ex)
    consts = closed_jaxpr.consts
    n_args = len(ex)

    def closed(*args):
        return jax.core.eval_jaxpr(
            closed_jaxpr.jaxpr, args[n_args:], *args[:n_args])
    # 0-d constants ride as (1,) blocks (Pallas blocks need a dim).
    const_nd0 = [c.ndim == 0 for c in consts]
    const_in = [jnp.asarray(c)[None] if nd0 else jnp.asarray(c)
                for c, nd0 in zip(consts, const_nd0)]
    n_row, n_full = len(row_arrays), len(full_arrays)

    def kernel(*refs):
        row_refs = refs[:n_row]
        full_refs = refs[n_row:n_row + n_full]
        const_refs = refs[n_row + n_full:n_row + n_full + len(consts)]
        out_refs = refs[n_row + n_full + len(consts):]
        args = [r[...][0] for r in row_refs]
        args += [r[...] for r in full_refs]
        args += [r[...][0] if nd0 else r[...]
                 for r, nd0 in zip(const_refs, const_nd0)]
        outs = closed(*args)
        for ref, val in zip(out_refs, outs):
            ref[...] = jnp.asarray(val)[None]

    in_specs = [_row_spec(a.shape[1:]) for a in row_arrays]
    in_specs += [_full_spec(a.shape) for a in full_arrays]
    in_specs += [_full_spec(c.shape) for c in const_in]
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=in_specs,
        out_specs=[_row_spec(tuple(s)) for s in out_shapes],
        out_shape=[jax.ShapeDtypeStruct((b,) + tuple(s), d)
                   for s, d in zip(out_shapes, out_dtypes)],
        interpret=interpret,
    )(*row_arrays, *full_arrays, *const_in)


def make_pallas_mutator(rounds: int = 4,
                        interpret: bool | None = None):
    """The Pallas twin of ops.mutate.make_mutator: same signature
    (batch, key, flag_vals, flag_counts) -> mutated batch, same bits
    out, but one grid cell per program so the mutation-op switch
    dispatches a real branch per cell."""
    import jax
    import jax.numpy as jnp
    from jax import random

    from syzkaller_tpu.ops.mutate import _mutate_one

    if interpret is None:
        interpret = _use_interpret()
    out_keys = _STATE_KEYS + _OUT_EXTRA

    @functools.partial(jax.jit, static_argnames=())
    def _mutate_batch(batch: dict, key, flag_vals, flag_counts) -> dict:
        b = batch["kind"].shape[0]
        kd = jax.random.key_data(random.split(key, b))

        def per_row(*args):
            state = dict(zip(_STATE_KEYS, args[:len(_STATE_KEYS)]))
            kd_i, fv, fc = args[len(_STATE_KEYS):]
            out = _mutate_one(state, jax.random.wrap_key_data(kd_i),
                              fv, fc, rounds)
            return tuple(out[k] for k in out_keys)

        out_shapes = [batch[k].shape[1:] for k in _STATE_KEYS]
        out_shapes += [(), batch["kind"].shape[1:]]
        out_dtypes = [batch[k].dtype for k in _STATE_KEYS]
        out_dtypes += [jnp.bool_, jnp.bool_]
        outs = _grid_apply(
            per_row,
            [batch[k] for k in _STATE_KEYS] + [kd],
            [flag_vals, flag_counts],
            out_shapes, out_dtypes, interpret)
        return dict(zip(out_keys, outs))

    def mutate_batch(batch: dict, key, flag_vals, flag_counts) -> dict:
        # CompileObservatory point (ISSUE 17): the standalone mutator
        # is its own jit entry (tests, bench --mutate), so its first
        # dispatch is a build the process ledger should see.  The
        # sizer gates on real jit-cache growth — warm calls add one
        # cheap host check, no note.
        from syzkaller_tpu import telemetry

        with telemetry.COMPILES.observe(
                "mutate.core",
                {"rounds": rounds, "interpret": interpret},
                sizer=_mutate_batch._cache_size):
            return _mutate_batch(batch, key, flag_vals, flag_counts)

    mutate_batch._cache_size = _mutate_batch._cache_size
    return mutate_batch


def make_pallas_mutate_pack(spec, rounds: int,
                            interpret: bool | None = None):
    """The pipeline's fused per-program core as ONE kernel:
    mutate, mask the journals for insert-class rows (which keep the
    template structure), and pack the sparse delta row + pooled
    payload — all inside the grid cell, so the packed bytes are
    produced without a second pass over the mutated state.

    Returns pack_batch(batch, key_data, template_idx, op, donor, pos,
    flag_vals, flag_counts) -> (rows, payloads, needs) with the exact
    bytes the vmap pack path emits (pool_idx still unassigned)."""
    import jax
    import jax.numpy as jnp

    from syzkaller_tpu.ops.delta import make_packer
    from syzkaller_tpu.ops.mutate import _mutate_one

    if interpret is None:
        interpret = _use_interpret()
    pack = make_packer(spec)

    def pack_batch(batch, key_data, template_idx, op, donor, pos,
                   flag_vals, flag_counts):
        def per_row(*args):
            state = dict(zip(_STATE_KEYS, args[:len(_STATE_KEYS)]))
            kd_i, ti, op_i, donor_i, pos_i, fv, fc = \
                args[len(_STATE_KEYS):]
            mutated = _mutate_one(
                state, jax.random.wrap_key_data(kd_i), fv, fc, rounds)
            # Insert rows keep the TEMPLATE structure (the packer
            # masks the value/data journals by op, and the alive
            # bitmap must be the unmutated one) — same masking as
            # the pipeline's vmap `one`.
            mutated["call_alive"] = jnp.where(
                op_i != 0, state["call_alive"], mutated["call_alive"])
            return pack(mutated, ti, op=op_i, donor=donor_i, pos=pos_i)

        return _grid_apply(
            per_row,
            [batch[k] for k in _STATE_KEYS]
            + [key_data, template_idx, op, donor, pos],
            [flag_vals, flag_counts],
            [(spec.row_bytes,), (spec.P,), ()],
            [jnp.uint8, jnp.uint8, jnp.bool_],
            interpret)

    return pack_batch


def make_pallas_pool_assigner(spec, POOL: int,
                              interpret: bool | None = None):
    """ops.delta._make_pool_assigner as a grid-sequential kernel.

    TPU grid cells execute in order, so the batch-wide prefix sum
    that claims pool slots degenerates to ONE SMEM scratch counter:
    cell i reads the running claim count, patches its row's flags +
    pool_idx bytes in place, and dynamic-stores its payload at the
    claimed slot — no cumsum materialization, no batch-wide scatter.
    Same (rows, pool, n_used) contract and bytes as the vmap
    assigner (losers flagged OVERFLOW, claimed slots packed at the
    pool front, n_used capped at POOL)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from syzkaller_tpu.ops.delta import FLAG_OVERFLOW

    if interpret is None:
        interpret = _use_interpret()

    def kernel(row_ref, payload_ref, needs_ref, row_out_ref,
               pool_ref, n_used_ref, count_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            count_ref[0] = jnp.int32(0)
            pool_ref[...] = jnp.zeros((POOL, spec.P), jnp.uint8)

        need = needs_ref[...][0]
        cur = count_ref[0]
        lost = need & (cur >= POOL)
        claimed = need & ~lost
        pool_idx = jnp.where(claimed, cur, jnp.int32(-1))
        row = row_ref[...][0]
        row = row.at[2].set(
            row[2] | jnp.where(lost, jnp.uint8(FLAG_OVERFLOW),
                               jnp.uint8(0)))
        row = lax.dynamic_update_slice(
            row, lax.bitcast_convert_type(
                pool_idx.astype(jnp.int32)[None], jnp.uint8)[0], (24,))
        row_out_ref[...] = row[None]

        # Claimed payloads pack at the pool front in claim order.
        @pl.when(claimed)
        def _store():
            pool_ref[pl.ds(jnp.minimum(cur, POOL - 1), 1), :] = \
                payload_ref[...]

        nxt = cur + need.astype(jnp.int32)
        count_ref[0] = nxt
        n_used_ref[...] = jnp.minimum(nxt, jnp.int32(POOL))[None]

    def assign(rows, payloads, needs):
        b = rows.shape[0]
        rows_out, pool, n_used = pl.pallas_call(
            kernel,
            grid=(b,),
            in_specs=[_row_spec((spec.row_bytes,)),
                      _row_spec((spec.P,)), _row_spec(())],
            out_specs=[_row_spec((spec.row_bytes,)),
                       _full_spec((POOL, spec.P)), _full_spec((1,))],
            out_shape=[
                jax.ShapeDtypeStruct((b, spec.row_bytes), jnp.uint8),
                jax.ShapeDtypeStruct((POOL, spec.P), jnp.uint8),
                jax.ShapeDtypeStruct((1,), jnp.int32),
            ],
            scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
            interpret=interpret,
        )(rows, payloads, needs)
        return rows_out, pool, n_used[0]

    return assign
