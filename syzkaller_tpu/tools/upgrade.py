"""tz-upgrade: migrate a corpus.db to the current format
(reference: tools/syz-upgrade — re-serialize every program through the
current descriptions, dropping ones that no longer parse).

Programs from older description revisions survive where the text
parser's excess-argument tolerance allows (models/encoding.py
eat_excessive, mirroring the reference's cross-version corpus
policy); programs that reference removed syscalls are dropped and
counted.
"""

from __future__ import annotations

import argparse
import sys

from syzkaller_tpu.db import open_db
from syzkaller_tpu.db.db import CUR_VERSION
from syzkaller_tpu.models.encoding import deserialize_prog, serialize_prog
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.utils.hashsig import hash_string


def upgrade_db(path: str, target_os: str = "test",
               arch: str = "64", force: bool = False) -> tuple[int, int]:
    """Returns (kept, dropped).  Refuses a total wipe unless `force`:
    dropping EVERY record almost always means the wrong -os/-arch was
    given, and the rewrite is irreversible."""
    target = get_target(target_os, arch)
    db = open_db(path)
    kept, dropped = {}, 0
    for key, rec in db.records.items():
        try:
            p = deserialize_prog(target, rec.val)
            text = serialize_prog(p)
        except Exception:
            dropped += 1
            continue
        kept[hash_string(text)] = (text, rec.seq)
    if db.records and not kept and not force:
        raise SystemExit(
            f"refusing to drop all {dropped} records (wrong -os/-arch "
            f"for this corpus? use -force to really wipe)")
    # rewrite: delete everything, re-save the survivors, bump version
    for key in list(db.records):
        db.delete(key)
    for key, (text, seq) in kept.items():
        db.save(key, text, seq)
    db.bump_version(CUR_VERSION)
    db.flush()
    return len(kept), dropped


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-upgrade")
    ap.add_argument("db", help="corpus.db to upgrade in place")
    ap.add_argument("-os", dest="target_os", default="test")
    ap.add_argument("-arch", default="64")
    ap.add_argument("-force", action="store_true",
                    help="allow dropping every record")
    args = ap.parse_args(argv)
    kept, dropped = upgrade_db(args.db, args.target_os, args.arch,
                               force=args.force)
    print(f"upgraded: kept {kept}, dropped {dropped}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
