"""tz-bench-watch: measure early and often, survive the wedge.

The tunneled TPU backend can wedge for hours (every jax op blocks).
This watcher drives measurement attempts DIRECTLY — the bench
subprocess's own PJRT client is the probe.  Round-5 thread-level
evidence (BENCH_WEDGE_DIAGNOSIS.md §"lease flap") showed why a
separate probe client is actively harmful: the plugin's Client_Create
sits in an endless sleep-retry reconnect loop (main thread in
nanosleep, tokio IO worker in ep_poll) until the far-side pool grants
a session, and the pool serves one client at a time — so a probe
client that wins the grant *starves the measurement client that
follows it* (observed live: probe served 03:17:19, measurement client
12 s later starved >600 s).  A long-running measurement attempt is
therefore both the probe and a standing lease-catcher: it queues in
the retry loop and converts the grant directly into a recorded
artifact instead of a throwaway 64x64 matmul.

Whenever an attempt lands, it records: the flagship bench (appends to
BENCH_HISTORY.jsonl via bench.py's journal) and, once, the A/B
edges-per-hour artifact (BENCH_AB_r<N>.json).  After `--want` flagship
entries plus the A/B artifact it exits and leaves the chip alone —
sustained bench load is itself a wedge trigger.

Reference analog: syz-manager's -bench minutely snapshots
(/root/reference/syz-manager/manager.go:299-333) — continuous recorded
measurement, not one attempt at shutdown.

Usage: python -m syzkaller_tpu.tools.bench_watch [--want 3] [--ab-secs 60]
       [--probe-interval 600] [--round 5]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Where each bench subprocess dumps its telemetry snapshot
#: (bench.dump_telemetry, armed via TZ_TELEMETRY_SNAPSHOT below).
#: Re-dumped after every warmup batch, so even an attempt killed by
#: the outer timeout leaves per-phase evidence for diagnose_wedge.
TELEMETRY_SNAP = os.path.join(REPO, "TELEMETRY_SNAPSHOT.json")

#: The watcher's own incident journal (telemetry/flight.py
#: append_attempt): every wedged/failed measurement attempt is
#: recorded here — the round's evidence accumulates in one file
#: instead of failing the round on the first wedge (ROADMAP
#: lease-catching carry-over from BENCH_r05).
INCIDENT_PATH = os.path.join(REPO, "tz_flight_bench_watch.json")

#: Bounded in-watcher retries for the lease-starvation signature (the
#: bench subprocess timing out in PJRT Client_Create): each retry
#: backs off and re-queues as a standing lease-catcher.
LEASE_RETRIES = 2
LEASE_BACKOFF_S = 120.0


#: Append-per-write log target (opened fresh each call): shell
#: redirection pins an inode, and anything that swaps the file on
#: disk (observed live in r5: writes after a swap went to the deleted
#: inode for an hour) silently swallows the evidence log.  None =
#: stdout only.
LOG_PATH: str | None = None


def log(msg: str) -> None:
    line = f"[bench-watch {time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    if LOG_PATH:
        try:
            with open(LOG_PATH, "a") as f:
                f.write(line + "\n")
        except OSError:
            pass


def _thread_table(pid: int) -> list[str]:
    """comm + kernel wait channel of every thread of `pid`.

    This is the evidence layer that pinpointed the round-5 wedge mode:
    a hung Client_Create shows main=hrtimer_nanosleep (the plugin's
    reconnect backoff) + tokio-rt-worker=ep_poll (IO runtime waiting
    on the socket) — an endless retry loop, not a deadlock.
    """
    rows = []
    try:
        for tid in sorted(os.listdir(f"/proc/{pid}/task")):
            base = f"/proc/{pid}/task/{tid}"
            try:
                with open(f"{base}/comm") as f:
                    comm = f.read().strip()
                with open(f"{base}/wchan") as f:
                    wchan = f.read().strip() or "?"
            except OSError:
                continue
            rows.append(f"tid {tid} {comm}: wchan={wchan}")
    except OSError:
        pass
    return rows


def _ms(v: float) -> str:
    return f"{v * 1e3:.1f}ms" if v < 10.0 else f"{v:.1f}s"


def wedge_report(snap: dict) -> list[str]:
    """Render a telemetry snapshot (telemetry.snapshot() shape) into
    wedge-diagnostic lines: per-phase latency percentiles, breaker
    transition counts + timestamps, the last-wedge age, and the
    transition event timeline.  Pure function — pinned by tests with
    no live TPU (docs/observability.md 'reading a wedge')."""
    lines: list[str] = []
    for name in sorted(snap.get("histograms") or {}):
        h = snap["histograms"][name]
        if not name.endswith("_seconds") or not h.get("count"):
            continue
        lines.append(
            f"phase {name}: n={h['count']} p50={_ms(h['p50'])} "
            f"p90={_ms(h['p90'])} p99={_ms(h['p99'])} "
            f"max={_ms(h['max'])}")
    counters = snap.get("counters") or {}
    trans = {k: v for k, v in sorted(counters.items())
             if k.startswith("tz_breaker_") and v}
    if trans:
        lines.append("breaker transitions: " + " ".join(
            f"{k[len('tz_breaker_'):-len('_total')]}={int(v)}"
            for k, v in trans.items()))
    gauges = snap.get("gauges") or {}
    # Drain->assemble stage health (the perf-PR sub-metrics): the
    # compacted transfer cost per batch, the assembly pool's shape,
    # and the realized host-assembly rate derived from the assemble
    # span — an A/B between snapshots shows where a regression sits.
    d2h = gauges.get("tz_pipeline_d2h_batch_bytes") or 0
    if d2h:
        lines.append(f"d2h per batch: {d2h / 1024:.1f} KiB (compacted)")
    pool_size = gauges.get("tz_pipeline_assemble_pool_size") or 0
    if pool_size:
        depth = gauges.get("tz_pipeline_assemble_queue_depth") or 0
        lines.append(f"assembly pool: {int(pool_size)} workers, "
                     f"queue depth {int(depth)}")
    asm = (snap.get("histograms") or {}).get(
        "tz_pipeline_assemble_seconds") or {}
    mutants = counters.get("tz_pipeline_mutants_total") or 0
    if asm.get("sum") and mutants:
        lines.append(
            f"host assembly: {mutants / asm['sum']:.0f} mutants/s "
            f"over {asm['count']} batches")
    # Transfer plane (the pinned-staging + overlap PR): arena
    # footprint, the two live depths, and the realized triage H2D
    # overlap — next to the d2h/assembly lines so an A/B between
    # snapshots localizes a transfer-side regression.
    arena = gauges.get("tz_staging_arena_bytes") or 0
    a_depth = gauges.get("tz_staging_assemble_depth") or 0
    d_depth = gauges.get("tz_staging_h2d_dispatch_depth") or 0
    if arena or a_depth or d_depth:
        line = (f"transfer plane: arenas {arena / 1024:.1f} KiB, "
                f"assemble depth {int(a_depth)}, "
                f"h2d dispatch depth {int(d_depth)}")
        t_batches = counters.get("tz_triage_batches_total") or 0
        overlaps = counters.get("tz_triage_h2d_overlap_total") or 0
        if t_batches:
            line += f", h2d overlap {overlaps / t_batches:.1%}"
        stale = counters.get("tz_triage_stale_slots_total") or 0
        if stale:
            line += f", {int(stale)} stale slots"
        lines.append(line)
    # Mutation core (ISSUE 10): backend, batch shape, and the fused
    # drain's novel fraction — a fused frac of 1.0 with a large
    # corpus means the mutant plane is undersized (or freshly
    # rebuilt); a collapsing frac with a stalling mutant rate means
    # the corpus went stale and mutations are repeating.
    backend_g = gauges.get("tz_mutate_backend")
    batch_g = gauges.get("tz_pipeline_batch_size") or 0
    f_batches = counters.get("tz_pipeline_fused_batches_total") or 0
    if backend_g is not None or f_batches:
        backend = "pallas" if backend_g else "vmap"
        line = f"mutation core: backend {backend}"
        if batch_g:
            line += f", batch {int(batch_g)}"
        if f_batches and batch_g:
            novel = counters.get(
                "tz_pipeline_fused_novel_rows_total") or 0
            line += (f", fused frac "
                     f"{novel / (f_batches * batch_g):.1%} "
                     f"over {int(f_batches)} batches")
        lines.append(line)
    # Sim prescore (ISSUE 15): the speculative drain's suppression
    # fraction and demotion state — suppression collapsing to 0% with
    # batches still flowing means the speculation plane just decayed
    # (an epoch boundary, not a wedge); a demoted prescore means the
    # drain fell back to pass-through and ships every plane-novel row.
    s_batches = counters.get("tz_sim_prescore_batches_total") or 0
    if s_batches:
        s_backend = gauges.get("tz_sim_backend")
        line = (f"sim prescore: backend "
                f"{'pallas' if s_backend else 'vmap'}, "
                f"{int(s_batches)} batches")
        s_sup = counters.get("tz_sim_suppressed_rows_total") or 0
        if batch_g:
            line += (f", suppressed "
                     f"{s_sup / (s_batches * batch_g):.1%}")
        s_epochs = counters.get("tz_sim_readmit_epochs_total") or 0
        if s_epochs:
            line += f", {int(s_epochs)} readmit epochs"
        s_demos = counters.get("tz_sim_demotions_total") or 0
        if s_demos:
            line += f", {int(s_demos)} demotions"
        lines.append(line)
    # Corpus arena (ISSUE 18): residency + upload cadence + the
    # distillation lane's hygiene yield.  Steady rows with a flat
    # upload count is the healthy resident state (zero H2D corpus
    # bytes per batch); uploads climbing batch-over-batch means the
    # slabs are thrashing (breaker churn or an invalidate loop), and
    # an epoch that keeps bumping names the demote/re-shard cause.
    a_rows = gauges.get("tz_arena_rows") or 0
    a_cap = gauges.get("tz_arena_capacity_rows") or 0
    if a_rows or a_cap:
        slab_kib = (gauges.get("tz_arena_slab_bytes") or 0) / 1024
        line = (f"corpus arena: {int(a_rows)}/{int(a_cap)} rows, "
                f"epoch {int(gauges.get('tz_arena_epoch') or 0)}, "
                f"slabs {slab_kib:.1f} KiB")
        ups = counters.get("tz_arena_uploads_total") or 0
        if ups:
            up_kib = (counters.get("tz_arena_upload_bytes_total")
                      or 0) / 1024
            line += f", {int(ups)} uploads ({up_kib:.1f} KiB)"
        d_rounds = counters.get("tz_arena_distill_rounds_total") or 0
        if d_rounds:
            retired = counters.get("tz_arena_retired_rows_total") or 0
            line += (f", distill {int(d_rounds)} rounds "
                     f"({int(retired)} rows retired)")
        lines.append(line)
    # Hints lane (ISSUE 19): fused comparison-operand expansion
    # throughput and fallback posture.  Values climbing with zero
    # batches means every window is taking the per-program CPU path
    # (lane demoted — check the breaker); a high suppressed fraction
    # is healthy steady state (the speculation fold deduplicating
    # repeat comparands), but suppression at 100% with mutants at 0
    # means the sim plane stopped decaying.
    h_batches = counters.get("tz_hints_batches_total") or 0
    h_cpu = counters.get("tz_hints_cpu_fallback_values_total") or 0
    if h_batches or h_cpu:
        h_vals = counters.get("tz_hints_values_total") or 0
        h_mut = counters.get("tz_hints_mutants_total") or 0
        line = (f"hints lane: {int(h_batches)} batches, "
                f"{int(h_vals)} windows -> {int(h_mut)} mutants")
        h_kib = (counters.get("tz_hints_staged_bytes_total") or 0) \
            / 1024
        if h_kib:
            line += f", staged {h_kib:.1f} KiB"
        h_sup = counters.get("tz_hints_sim_suppressed_total") or 0
        if h_sup:
            line += f", suppressed {h_sup / max(1, h_sup + h_mut):.1%}"
        h_drop = counters.get("tz_hints_comps_dropped_total") or 0
        if h_drop:
            line += f", {int(h_drop)} comps off-device"
        if h_cpu:
            line += f", {int(h_cpu)} windows on CPU"
        h_demos = counters.get("tz_hints_demotions_total") or 0
        if h_demos:
            line += f", {int(h_demos)} demotions"
        lines.append(line)
    # Triage plane health (ISSUE 4): pre-filter hit rate and the
    # realized device-checked call rate — next to the demotion count
    # so a CPU-path regression is visible in the same A/B snapshot.
    t_hits = counters.get("tz_triage_plane_hits_total") or 0
    t_miss = counters.get("tz_triage_plane_misses_total") or 0
    if t_hits + t_miss:
        tdev = (snap.get("histograms") or {}).get(
            "tz_triage_device_seconds") or {}
        line = (f"triage plane: {int(t_hits + t_miss)} calls "
                f"pre-filtered, hit rate "
                f"{t_hits / (t_hits + t_miss):.1%}")
        if tdev.get("sum"):
            line += f", {(t_hits + t_miss) / tdev['sum']:.0f} calls/s"
        fn = gauges.get("tz_triage_fold_false_negative_rate") or 0
        if fn:
            line += f", fold-FN est {fn:.2%}"
        demos = counters.get("tz_triage_demotions_total") or 0
        if demos:
            line += f", {int(demos)} demotions"
        lines.append(line)
    # Coverage intelligence (ISSUE 7): is the fuzzer still learning?
    # The stalled-coverage line sits next to the health layers so a
    # wedge window and a coverage plateau are distinguishable at a
    # glance (a wedged device stops producing; a plateaued fuzzer
    # produces plenty and learns nothing).
    cov_occ = gauges.get("tz_coverage_occupancy") or 0
    cov_stalled = gauges.get("tz_coverage_stalled") or 0
    if cov_occ or cov_stalled:
        cov_rate = gauges.get("tz_coverage_novelty_rate") or 0
        line = (f"coverage: {int(cov_occ)} plane buckets occupied, "
                f"novelty {cov_rate:.3f} edges/s")
        if cov_stalled:
            line += " — STALLED (plateau detector latched)"
        drift = gauges.get("tz_coverage_plane_drift") or 0
        if drift:
            line += f", plane drift {int(drift)} buckets"
        lines.append(line)
    # Control-plane health (ISSUE 9): fleet liveness, retry/replay
    # volume, and the admission-control state — a wedge that shows up
    # here first (reaped leases, throttle open) is a fleet problem,
    # not a kernel-under-test problem.
    live = gauges.get("tz_manager_connected_fuzzers") or 0
    reaped = counters.get("tz_manager_leases_reaped_total") or 0
    retries = counters.get("tz_rpc_retries_total") or 0
    replays = counters.get("tz_manager_reply_replays_total") or 0
    throttle = gauges.get("tz_manager_throttle_state") or 0
    if live or reaped or retries or replays or throttle:
        state = {0: "closed", 1: "half_open", 2: "open"}.get(
            int(throttle), "?")
        line = (f"control plane: {int(live)} live fuzzers, "
                f"{int(reaped)} reaped, {int(retries)} rpc retries, "
                f"{int(replays)} replayed from cache, "
                f"admission {state}")
        reissued = counters.get(
            "tz_manager_candidates_reissued_total") or 0
        if reissued:
            line += f", {int(reissued)} candidates reissued"
        dropped = counters.get("tz_manager_inputs_dropped_total") or 0
        if dropped:
            line += f", {int(dropped)} inputs dropped"
        lines.append(line)
    # Serving-plane health (ISSUE 12): tenant count, queue custody,
    # and the QoS credit distribution — a starved or runaway tenant
    # shows here (credit pinned at the floor, queue deep) before it
    # shows anywhere device-side.
    serve_tenants = gauges.get("tz_serve_tenants") or 0
    serve_reaped = counters.get("tz_serve_leases_reaped_total") or 0
    if serve_tenants or serve_reaped:
        line = f"serving plane: {int(serve_tenants)} tenants"
        depths = {}
        credits = {}
        for k, v in gauges.items():
            if k.startswith('tz_serve_queue_depth{'):
                depths[k.split('tenant="', 1)[1].rstrip('"}')] = v
            elif k.startswith('tz_serve_credit{'):
                credits[k.split('tenant="', 1)[1].rstrip('"}')] = v
        if depths:
            line += ", queues " + " ".join(
                f"{t}:{int(v)}" for t, v in sorted(depths.items()))
        if credits:
            line += ", credits " + " ".join(
                f"{t}:{v:.2f}" for t, v in sorted(credits.items()))
        demand = gauges.get("tz_serve_demand_rows") or 0
        if demand:
            line += f", demand {int(demand)} rows"
        if serve_reaped:
            line += f", {int(serve_reaped)} leases reaped"
        requeued = counters.get("tz_serve_results_requeued_total") or 0
        dropped = counters.get("tz_serve_results_dropped_total") or 0
        if requeued or dropped:
            line += (f" ({int(requeued)} results requeued, "
                     f"{int(dropped)} dropped with reaped leases)")
        lines.append(line)
    # Durability plane (ISSUE 13): checkpoint freshness, WAL growth,
    # and the recovery verdict — a manager that died and warm-started
    # announces it here, and a stale checkpoint age next to a fat WAL
    # means the snapshot thread is wedged while the journal absorbs
    # every mutation (replay cost is growing unbounded).
    ckpts = counters.get("tz_durable_ckpts_total") or 0
    rec_state = gauges.get("tz_durable_recovery_state")
    if ckpts or rec_state is not None:
        verdict = {0: "cold start", 1: "warm restart",
                   2: "recovery FAILED -> cold"}.get(
            int(rec_state or 0), "?")
        line = f"durability: {verdict}, {int(ckpts)} checkpoints"
        last_ts = gauges.get("tz_durable_ckpt_last_ts") or 0
        if last_ts:
            age = max(0.0, (snap.get("ts") or time.time()) - last_ts)
            line += f", last {age:.0f}s ago"
        wal = gauges.get("tz_durable_wal_bytes") or 0
        if wal:
            line += f", WAL {wal / 1024:.1f} KiB"
        trunc = counters.get("tz_durable_wal_truncations_total") or 0
        werr = counters.get("tz_durable_wal_errors_total") or 0
        cerr = counters.get("tz_durable_ckpt_errors_total") or 0
        if trunc or werr or cerr:
            line += (f" ({int(trunc)} torn tails truncated, "
                     f"{int(werr)} wal errors, "
                     f"{int(cerr)} ckpt errors)")
        lines.append(line)
    # Accounting & SLO plane (ISSUE 14): the device-time ledger and
    # the burn-rate scorecard — a burning SLO names itself here, and
    # the top device-ms consumer says WHO is eating the chip while the
    # objective degrades (the first question of any wedge triage).
    acct_tenant = {}
    for k, v in counters.items():
        if k.startswith('tz_acct_device_ms_total{tenant="') and v:
            acct_tenant[k.split('tenant="', 1)[1].rstrip('"}')] = v
    burning = []
    for k, v in gauges.items():
        if k.startswith('tz_slo_burn{') and v:
            burning.append(k.split('slo="', 1)[1].rstrip('"}'))
    if acct_tenant or burning:
        line = ("slo: BURNING " + " ".join(sorted(burning))
                if burning else "slo: ok")
        burns = counters.get("tz_slo_burns_total") or 0
        if burns:
            line += f" ({int(burns)} burns total)"
        if acct_tenant:
            total = sum(acct_tenant.values()) or 1.0
            top, top_ms = max(acct_tenant.items(), key=lambda kv: kv[1])
            line += (f", device-ms ledger {total:.0f} ms, top tenant "
                     f"{top} ({100.0 * top_ms / total:.0f}%)")
        resets = counters.get("tz_telemetry_merge_resets_total") or 0
        if resets:
            line += f", {int(resets)} fuzzer counter resets absorbed"
        lines.append(line)
    # Fault-domain mesh health (ISSUE 11): topology width, per-shard
    # breaker states, and the last re-shard age — a demoted shard
    # shows here as e.g. "3:open" while the engine keeps serving from
    # N−1, so chip loss and a wedge are distinguishable at a glance.
    mesh_live = gauges.get("tz_mesh_devices_live") or 0
    mesh_demoted = gauges.get("tz_mesh_devices_demoted") or 0
    if mesh_live or mesh_demoted:
        line = (f"mesh: {int(mesh_live)} live / "
                f"{int(mesh_demoted)} demoted")
        states = {}
        for k, v in gauges.items():
            if k.startswith('tz_mesh_shard_breaker_state{'):
                shard = k.split('shard="', 1)[1].rstrip('"}')
                states[int(shard)] = {0: "closed", 1: "half_open",
                                      2: "open"}.get(int(v), "?")
        if states:
            line += ", shards " + " ".join(
                f"{s}:{st}" for s, st in sorted(states.items()))
        reshard_ts = gauges.get("tz_mesh_last_reshard_ts") or 0
        if reshard_ts:
            age = max(0.0, (snap.get("ts") or time.time()) - reshard_ts)
            line += f", last re-shard {age:.0f}s ago"
        demotes = counters.get("tz_mesh_demote_total") or 0
        repromotes = counters.get("tz_mesh_repromote_total") or 0
        if demotes or repromotes:
            line += (f" ({int(demotes)} demotions, "
                     f"{int(repromotes)} re-admissions)")
        lines.append(line)
    # Hub federation health (ISSUE 16): live vs reaped manager
    # sessions, the bytes digest-diff sync kept off the wire, each
    # manager's sync breaker, and the last leader-failover age — a
    # flapping manager shows as e.g. "mB:open" while the rest of the
    # pod keeps exchanging, and a recent failover timestamp says the
    # hub you are watching is a warm-restarted successor.
    hub_live = gauges.get("tz_hub_managers_size") or 0
    hub_reaped = counters.get("tz_hub_leases_reaped_total") or 0
    hub_saved = counters.get("tz_hub_sync_saved_bytes_total") or 0
    hub_failover = gauges.get("tz_hub_last_failover_ts") or 0
    if hub_live or hub_reaped or hub_saved or hub_failover:
        line = (f"hub: {int(hub_live)} managers live / "
                f"{int(hub_reaped)} reaped")
        if hub_saved:
            line += f", sync saved {hub_saved / 1024:.1f} KiB"
        hub_states = {}
        for k, v in gauges.items():
            if k.startswith('tz_hub_breaker_state{'):
                mgr = k.split('manager="', 1)[1].rstrip('"}')
                hub_states[mgr] = {0: "closed", 1: "half_open",
                                   2: "open"}.get(int(v), "?")
        if hub_states:
            line += ", breakers " + " ".join(
                f"{m}:{st}" for m, st in sorted(hub_states.items()))
        if hub_failover:
            age = max(0.0, (snap.get("ts") or time.time())
                      - hub_failover)
            line += f", last failover {age:.0f}s ago"
        lines.append(line)
    # Device residency observatory (ISSUE 17): who holds HBM and what
    # keeps compiling — headroom collapsing toward zero across an A/B
    # is a buffer leak, and a climbing build count on a warm rig is
    # the compile-storm failure mode that eats the batch budget.
    hbm_groups = {}
    for k, v in gauges.items():
        if k.startswith('tz_hbm_live_bytes{') and v:
            owner = k.split('owner="', 1)[1].split('"', 1)[0]
            dev = k.split('device="', 1)[1].split('"', 1)[0]
            kind = k.split('kind="', 1)[1].split('"', 1)[0]
            hbm_groups[f"{owner}/{kind}@{dev}"] = v
    headroom = gauges.get("tz_hbm_headroom_bytes")
    if hbm_groups:
        line = "device residency: " + " ".join(
            f"{g}:{v / 1e6:.1f}MB"
            for g, v in sorted(hbm_groups.items()))
        if headroom is not None:
            line += f", headroom {headroom / 1e9:.2f}GB"
        drifts = counters.get("tz_hbm_drift_total") or 0
        if drifts:
            line += f", {int(drifts)} reconcile DRIFTS"
        lines.append(line)
    builds = {}
    for k, v in counters.items():
        if k.startswith('tz_compile_builds_total{') and v:
            builds[k.split('graph="', 1)[1].rstrip('"}')] = v
    if builds:
        line = "compiles: " + " ".join(
            f"{g}={int(v)}" for g, v in sorted(builds.items()))
        storms = counters.get("tz_compile_storms_total") or 0
        if storms:
            line += f" — {int(storms)} STORMS"
        lines.append(line)
    attr = {}
    for k, v in counters.items():
        if k.startswith('tz_coverage_novel_edges_total{') and v:
            attr[k.split('lane="', 1)[1].rstrip('"}')] = v
    if attr:
        lines.append("novel edges by lane: " + " ".join(
            f"{s}={int(v)}" for s, v in sorted(attr.items())))
    last_wedge = gauges.get("tz_watchdog_last_wedge_ts") or 0
    if last_wedge:
        age = max(0.0, (snap.get("ts") or time.time()) - last_wedge)
        lines.append(
            f"last wedge: "
            f"{time.strftime('%H:%M:%S', time.localtime(last_wedge))} "
            f"({age:.0f}s before snapshot)")
    events = snap.get("events") or []
    for ts, name, detail in events[-12:]:
        stamp = time.strftime("%H:%M:%S", time.localtime(ts))
        lines.append(f"  {stamp} {name}"
                     + (f" ({detail})" if detail else ""))
    if not lines:
        lines.append("telemetry snapshot carried no phase latencies "
                     "or health transitions")
    return lines


def report_telemetry(path: str | None = None) -> None:
    """Log the last bench attempt's telemetry snapshot, if any — the
    per-phase view of WHERE the pipeline spent its time before the
    wedge (closes the ROADMAP item: breaker transition counters wired
    into bench_watch's wedge diagnostics)."""
    path = path or TELEMETRY_SNAP
    try:
        with open(path) as f:
            snap = json.load(f)
    except (OSError, ValueError):
        log(f"diagnose: no telemetry snapshot at {path} "
            "(bench never reached its first warmup batch)")
        return
    log("diagnose: telemetry from the last bench attempt "
        f"(snapshot ts {snap.get('ts', 0):.0f}):")
    for line in wedge_report(snap):
        log(f"  {line}")


def flight_report(incident: dict) -> list[str]:
    """Render a flight-recorder incident payload
    (telemetry/flight.py snapshot/dump shape) into diagnostic lines:
    the breaker timeline, the last-N spans, the queue-depth history,
    and any recorded measurement attempts.  Pure function — pinned by
    tests with no live TPU."""
    lines: list[str] = []
    reason = incident.get("reason") or "?"
    ts = incident.get("ts") or 0
    stamp = time.strftime("%H:%M:%S", time.localtime(ts)) if ts else "?"
    lines.append(f"incident: {reason} at {stamp} "
                 f"(pid {incident.get('pid', '?')})"
                 + (f" — {incident['detail']}"
                    if incident.get("detail") else ""))
    for ets, name, detail in (incident.get("breaker_timeline")
                              or [])[-12:]:
        estamp = time.strftime("%H:%M:%S", time.localtime(ets))
        lines.append(f"  {estamp} {name}"
                     + (f" ({detail})" if detail else ""))
    spans = incident.get("spans") or []
    if spans:
        per: dict[str, int] = {}
        for _ts, name, _dur in spans:
            per[name] = per.get(name, 0) + 1
        lines.append("last spans: " + " ".join(
            f"{n}={c}" for n, c in sorted(per.items())))
        for sts, name, dur in spans[-6:]:
            sstamp = time.strftime("%H:%M:%S", time.localtime(sts))
            lines.append(f"  {sstamp} {name} {_ms(dur)}")
    depths = incident.get("queue_depths") or []
    for sample in depths[-4:]:
        vals = " ".join(f"{k.replace('tz_', '')}={v:g}"
                        for k, v in sorted(sample.items())
                        if k != "ts")
        dstamp = time.strftime("%H:%M:%S",
                               time.localtime(sample.get("ts", 0)))
        lines.append(f"  depths {dstamp}: {vals}")
    for att in (incident.get("attempts") or [])[-6:]:
        astamp = time.strftime("%H:%M:%S",
                               time.localtime(att.get("ts", 0)))
        lines.append(f"  attempt {astamp} {att.get('kind')}: "
                     f"{str(att.get('reason'))[:80]}")
    if len(lines) == 1:
        lines.append("  (incident carried no timeline/spans/depths)")
    return lines


def report_flight(paths: list[str] | None = None) -> None:
    """Log the newest flight-recorder incident file(s): the automatic
    DeviceWedged/breaker-open dumps from bench subprocesses
    (TZ_FLIGHT_DIR=REPO, armed by run_bench) plus the watcher's own
    attempt journal."""
    import glob

    if paths is None:
        paths = sorted(glob.glob(os.path.join(REPO, "tz_flight_*.json")),
                       key=lambda p: os.path.getmtime(p)
                       if os.path.exists(p) else 0)[-3:]
    if not paths:
        log("diagnose: no flight-recorder incident files")
        return
    for path in paths:
        try:
            with open(path) as f:
                incident = json.load(f)
        except (OSError, ValueError):
            continue
        log(f"diagnose: flight recorder {os.path.basename(path)}:")
        for line in flight_report(incident):
            log(f"  {line}")


def coverage_report(payload: dict) -> list[str]:
    """Render a /api/coverage payload (manager/html.py
    `_coverage_payload`, or a bare CoverageTracker.snapshot()) into
    diagnostic lines: trajectory tail, novelty rate, the stall
    verdict, per-lane attribution, drift status, heat-map summary.
    Pure function — pinned by tests with no live manager."""
    cov = payload.get("local") or payload
    lines: list[str] = []
    stalled = payload.get("stalled", cov.get("stalled"))
    verdict = "STALLED" if stalled else "learning"
    lines.append(
        f"coverage: {verdict} — occupancy {cov.get('occupancy', 0)}, "
        f"novelty {cov.get('novelty_rate_ewma', 0):.3f} edges/s, "
        f"{cov.get('novel_edges_total', 0)} novel edges total, "
        f"last novel {cov.get('last_novel_age_s', 0):.0f}s ago")
    if cov.get("stalls"):
        lines.append(f"  stalls: {cov['stalls']} (window "
                     f"{cov.get('stall_window_s', 0):.0f}s, threshold "
                     f"{cov.get('stall_edges', 0)} edges)")
    for ts, occ, delta in (cov.get("growth_curve") or [])[-6:]:
        stamp = time.strftime("%H:%M:%S", time.localtime(ts))
        lines.append(f"  {stamp} occupancy={occ}"
                     + (f" +{delta}" if delta else ""))
    attr = (cov.get("attribution") or {}).get("by_source") or {}
    if attr:
        lines.append("  by lane: " + " ".join(
            f"{s}={n}" for s, n in
            sorted(attr.items(), key=lambda kv: -kv[1])))
    drift = cov.get("drift") or {}
    if drift.get("audits"):
        state = (f"{drift['buckets']} buckets DRIFTED"
                 if drift.get("buckets") else "clean")
        lines.append(f"  drift audit: {state} "
                     f"({drift['audits']} audits)")
    regions = cov.get("heat_regions")
    if regions:
        occupied = sum(1 for r in regions if r)
        hot = max(range(len(regions)), key=lambda i: regions[i])
        lines.append(f"  heat map: {occupied}/{len(regions)} regions "
                     f"occupied, hottest region {hot} "
                     f"({regions[hot]} buckets)")
    return lines


def device_report(payload: dict) -> list[str]:
    """Render a /api/device payload (manager/html.py
    `_device_payload`: {"hbm": ..., "compiles": ...}) into
    diagnostic lines — the residency table, the headroom/reconcile
    verdict, and the per-family compile ledger.  Pure function —
    pinned by tests with no live manager."""
    hbm = payload.get("hbm") or {}
    comp = payload.get("compiles") or {}
    lines: list[str] = []
    lines.append(
        f"residency: "
        f"{hbm.get('device_resident_bytes', 0) / 1e6:.1f} MB "
        f"device-resident of "
        f"{hbm.get('capacity_bytes', 0) / 1e9:.1f} GB, headroom "
        f"{hbm.get('headroom_bytes', 0) / 1e9:.2f} GB, transient "
        f"{hbm.get('transient_bytes', 0) / 1e6:.1f} MB")
    for k, v in sorted((hbm.get("buffers") or {}).items()):
        lines.append(f"  {k}: {v / 1e6:.1f} MB")
    rec = hbm.get("last_reconcile") or {}
    if rec:
        verdict = (f"DRIFT {rec.get('drift_bytes', 0)} B"
                   if rec.get("flagged") else
                   f"drift {rec.get('drift_bytes', 0)} B (tolerated)")
        lines.append(
            f"  reconcile: {verdict} over {rec.get('entries', 0)} "
            f"entries, backend {rec.get('backend_bytes', 0) / 1e6:.1f}"
            f" MB vs tracked "
            f"{rec.get('tracked_bytes', 0) / 1e6:.1f} MB")
    else:
        lines.append("  reconcile: never ran")
    graphs = comp.get("graphs") or {}
    if graphs:
        lines.append(
            "compiles: " + " ".join(
                f"{g}={f['builds']}({f['shapes']} shapes)"
                for g, f in sorted(graphs.items()))
            + (f" — {comp['storms']} STORMS"
               if comp.get("storms") else ""))
    for ts, graph, key, secs in (comp.get("recent") or [])[-4:]:
        stamp = time.strftime("%H:%M:%S", time.localtime(ts))
        lines.append(f"  {stamp} built {graph} in {secs:.2f}s")
    return lines


def report_device(url: str | None = None) -> None:
    """Fetch and log the manager's /api/device residency payload (the
    device-residency layer of diagnose_wedge).  Without a manager URL
    the tz_hbm_*/tz_compile_* lines in wedge_report already cover the
    local snapshot view."""
    url = url or os.environ.get("TZ_MANAGER_HTTP", "")
    if not url:
        log("diagnose: no TZ_MANAGER_HTTP set — device residency "
            "limited to the telemetry-snapshot lines above")
        return
    try:
        import urllib.request

        with urllib.request.urlopen(
                url.rstrip("/") + "/api/device", timeout=10) as r:
            payload = json.loads(r.read().decode())
    except Exception as e:
        log(f"diagnose: /api/device unreachable at {url}: {e}")
        return
    log("diagnose: device residency (/api/device):")
    for line in device_report(payload):
        log(f"  {line}")


def report_coverage(url: str | None = None) -> None:
    """Fetch and log the manager's /api/coverage rollup (the
    coverage-trajectory layer of diagnose_wedge).  The manager URL
    comes from TZ_MANAGER_HTTP; without one, the snapshot-based
    coverage line in wedge_report already covers the local view."""
    url = url or os.environ.get("TZ_MANAGER_HTTP", "")
    if not url:
        log("diagnose: no TZ_MANAGER_HTTP set — coverage trajectory "
            "limited to the telemetry-snapshot line above")
        return
    try:
        import urllib.request

        with urllib.request.urlopen(
                url.rstrip("/") + "/api/coverage", timeout=10) as r:
            payload = json.loads(r.read().decode())
    except Exception as e:
        log(f"diagnose: /api/coverage unreachable at {url}: {e}")
        return
    log("diagnose: coverage intelligence (/api/coverage):")
    for line in coverage_report(payload):
        log(f"  {line}")


def diagnose_wedge(stack_timeout_s: float = 45.0) -> None:
    """On measurement timeout: capture WHAT hangs, not just that it hangs.

    Eight layers, logged in order:
    1. Python stack of the hung init (faulthandler dump while
       jax.devices() blocks) — distinguishes backend-init vs dispatch.
    2. Thread table of the hung subprocess (/proc wchan) — tells an
       idle retry loop (nanosleep + ep_poll) from a hard deadlock.
    3. The transport endpoint the axon plugin dials
       (PALLAS_AXON_POOL_IPS : relay port) — TCP connect/greeting
       behavior tells loopback-listener state from upstream state.
    4. Who owns the listener (ss -tlnp), so 'wedged?' has a subject.
    5. The last attempt's telemetry snapshot (report_telemetry).
    6. Flight-recorder incident files (report_flight).
    7. The coverage trajectory (report_coverage).
    8. Device residency + compile ledger (report_device).
    """
    code = ("import faulthandler\n"
            f"faulthandler.dump_traceback_later({stack_timeout_s - 5},"
            " exit=True)\n"
            "import jax\n"
            "jax.devices()\n"
            "print('DEVICES-OK')\n")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, cwd=REPO)
    # Sample the thread table while it is (presumably) hung, before
    # the faulthandler exit fires.
    time.sleep(min(20.0, stack_timeout_s / 2))
    threads = _thread_table(proc.pid)
    try:
        stdout, stderr = proc.communicate(timeout=stack_timeout_s)
        out = (stdout + stderr).strip()
    except subprocess.TimeoutExpired:
        proc.kill()
        stdout, stderr = proc.communicate()
        out = ((stdout or "") + (stderr or "")).strip()
    if "DEVICES-OK" in out:
        log("diagnose: backend init succeeded this time (transient)")
        return
    # Keep only the hang frames, not the jax import noise.
    frames = [ln for ln in out.splitlines()
              if "File \"" in ln or "Thread" in ln or "Timeout" in ln]
    log("diagnose: hung init stack (innermost first):")
    for ln in frames[:12]:
        log(f"  {ln.strip()}")
    log("diagnose: hung-process threads (nanosleep+ep_poll = plugin "
        "reconnect-retry loop waiting for a pool lease):")
    for row in threads[:8]:
        log(f"  {row}")
    pool_ip = os.environ.get("PALLAS_AXON_POOL_IPS", "").split(",")[0]
    if pool_ip:
        import socket
        for port in (2024,):
            try:
                s = socket.socket()
                s.settimeout(5)
                s.connect((pool_ip, port))
                s.settimeout(3)
                try:
                    data = s.recv(64)
                    state = (f"connect ok, server sent {data!r}"
                             if data else
                             "connect ok, server closed immediately "
                             "(EOF) — upstream/vsock bridge dead, "
                             "listener is readiness-only")
                except socket.timeout:
                    state = ("connect ok, silent server (no greeting "
                             "in 3s) — handshake peer absent")
                s.close()
            except OSError as e:
                state = f"connect failed: {e}"
            log(f"diagnose: {pool_ip}:{port} → {state}")
    try:
        res = subprocess.run(["ss", "-tlnp"], capture_output=True,
                             text=True, timeout=10)
        for ln in res.stdout.splitlines():
            if ":2024" in ln:
                log(f"diagnose: listener: {ln.strip()}")
    except (OSError, subprocess.TimeoutExpired):
        pass
    # Layer 5: what the engine itself measured before it stalled —
    # per-phase latency percentiles + breaker/wedge timeline from the
    # last attempt's telemetry snapshot.
    report_telemetry()
    # Layer 6: the flight-recorder incident files — the automated
    # form of the round-5 hand diagnosis (breaker timeline, last-N
    # spans, queue-depth history, recorded attempts).
    report_flight()
    # Layer 7: the coverage trajectory — a wedged chip and a
    # plateaued fuzzer look identical from the flagship number alone;
    # the growth curve + stall verdict separates them.
    report_coverage()
    # Layer 8: device residency + compile ledger — a wedge with HBM
    # headroom gone is an OOM-adjacent stall, and a storming compile
    # family says the executable cache is being lost and rebuilt.
    report_device()


def flagship_entries() -> int:
    """On-chip flagship entries in the journal.

    Mirrors bench.py's journal_last_healthy filter: entries carrying a
    'platform' key are platform-pinned (e.g. TZ_BENCH_PLATFORM=cpu)
    and must NOT satisfy --want — the watcher exists to record
    *accelerator* measurements.
    """
    path = os.path.join(REPO, "BENCH_HISTORY.jsonl")
    n = 0
    try:
        with open(path) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if e.get("metric") == "exec_ready_mutants_per_sec_per_chip" \
                        and e.get("value", 0) > 0 \
                        and not e.get("platform") \
                        and not e.get("harness_artifact") \
                        and not e.get("reconstructed"):
                    n += 1
    except OSError:
        pass
    return n


def ab_result_eligible(r: dict) -> bool:
    """Same eligibility bar as flagship_entries: an error JSON, a
    platform-pinned (CPU) run, or a malformed payload must not
    permanently mark the round's accelerator A/B done."""
    return not (r.get("error") or r.get("platform")
                or r.get("metric") != "new_edges_sim_kernel_ab"
                or not r.get("engine_on"))


def record_attempt(kind: str, reason: str, attempt: int = 1) -> None:
    """One failed/wedged attempt into the round's incident journal
    (telemetry/flight.py append_attempt; bounded, best-effort)."""
    from syzkaller_tpu.telemetry import flight

    flight.append_attempt(INCIDENT_PATH, {
        "kind": kind, "reason": reason, "attempt": attempt})


def run_bench(args: list[str], timeout_s: float,
              lease_retries: int = LEASE_RETRIES,
              lease_backoff_s: float = LEASE_BACKOFF_S) -> dict | None:
    # Give the pipeline warmup most of the subprocess budget: the
    # warmup's first batch is where a starved PJRT client waits for
    # the pool lease, so a short warmup timeout would abandon the
    # standing-lease-catcher role (module docstring) early.  A/B runs
    # need a bigger post-warmup window: after the lease lands they
    # still run the timed leg AND the engine-off leg, and a lease
    # caught late in the warmup window must not be killed by the
    # outer timeout with only one leg measured (r5 lost an A/B
    # artifact exactly this way).
    post_warmup = 900 if "--ab" in args else 300
    warmup = max(60, int(timeout_s - post_warmup))
    env = dict(os.environ, TZ_BENCH_WARMUP_TIMEOUT_S=str(warmup),
               TZ_TELEMETRY_SNAPSHOT=TELEMETRY_SNAP,
               TZ_FLIGHT_DIR=REPO)
    # Lease-catching (BENCH_r05 carry-over): a subprocess timeout is
    # the Client_Create starvation signature — retry with backoff a
    # BOUNDED number of times, recording every attempt in the
    # incident journal, instead of burning the whole probe interval
    # on the first wedge.
    for attempt in range(1 + max(0, lease_retries)):
        if attempt:
            log(f"lease-catch retry {attempt}/{lease_retries} for "
                f"bench {args} after {lease_backoff_s:.0f}s backoff")
            time.sleep(lease_backoff_s)
        try:
            res = subprocess.run([sys.executable, "bench.py",
                                  "--no-preflight"] + args,
                                 capture_output=True, text=True,
                                 timeout=timeout_s, cwd=REPO, env=env)
        except subprocess.TimeoutExpired:
            log(f"bench {args} timed out after {timeout_s:.0f}s "
                f"(attempt {attempt + 1}/{1 + lease_retries})")
            record_attempt("timeout",
                           f"bench {args} exceeded {timeout_s:.0f}s "
                           "(lease never granted?)", attempt + 1)
            continue
        if res.returncode != 0:
            log(f"bench {args} failed: {res.stderr.strip()[-300:]}")
            record_attempt("error", res.stderr.strip()[-300:],
                           attempt + 1)
            return None
        try:
            return json.loads(res.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            log(f"bench {args} emitted no JSON: {res.stdout[-200:]}")
            record_attempt("no_json", res.stdout[-200:], attempt + 1)
            return None
    return None


def main() -> None:
    ap = argparse.ArgumentParser(prog="tz-bench-watch")
    ap.add_argument("--want", type=int, default=3,
                    help="flagship journal entries to collect")
    ap.add_argument("--ab-secs", type=float, default=60.0)
    ap.add_argument("--probe-interval", type=float, default=600.0)
    ap.add_argument("--measure-interval", type=float, default=900.0,
                    help="spacing between flagship measurements")
    ap.add_argument("--round", type=int, default=5)
    ap.add_argument("--diagnose-every", type=int, default=6,
                    help="capture a full wedge diagnostic every N "
                         "failed probes (0 = never)")
    ap.add_argument("--lease-retries", type=int, default=LEASE_RETRIES,
                    help="bounded in-attempt retries on a subprocess "
                         "timeout (the Client_Create starvation "
                         "signature); each is journaled in "
                         "tz_flight_bench_watch.json")
    ap.add_argument("--lease-backoff", type=float,
                    default=LEASE_BACKOFF_S,
                    help="seconds between lease-catch retries")
    ap.add_argument("--log-file", default="",
                    help="also append every log line here (inode-swap"
                         "-proof, reopened per write)")
    opts = ap.parse_args()
    if opts.log_file:
        global LOG_PATH
        LOG_PATH = opts.log_file

    ab_path = os.path.join(REPO, f"BENCH_AB_r{opts.round:02d}.json")
    failed_attempts = 0
    prefer_ab = True
    while True:
        have = flagship_entries()
        ab_done = os.path.exists(ab_path)
        if have >= opts.want and ab_done:
            # Gravy before leaving the chip alone: one on-chip
            # discovery-scaling run (VERDICT r4 ask #2's simulation
            # variant, measured where the speedup is real).
            scaled = os.path.join(REPO,
                                  f"BENCH_AB_SCALED_r{opts.round:02d}.json")
            if not os.path.exists(scaled):
                r = run_bench(["--ab-scaled"], timeout_s=2700,
                              lease_retries=opts.lease_retries,
                              lease_backoff_s=opts.lease_backoff)
                if r is not None and not r.get("error") \
                        and not r.get("platform"):
                    with open(scaled, "w") as f:
                        json.dump(r, f)
                        f.write("\n")
                    log(f"scaled A/B artifact written: {scaled}")
            log(f"done: {have} flagship entries + A/B artifact; "
                "leaving the chip alone")
            return
        # No separate probe client: the measurement subprocess IS the
        # probe.  Its PJRT client queues in the plugin's reconnect
        # loop and converts a pool-lease grant directly into a
        # recorded artifact (see module docstring).  Priority: one
        # flagship first (proves the chip), then alternate between the
        # A/B artifact and journal-depth flagships — a failing A/B
        # (e.g. the tunnel's remote-compile service down while cached
        # executables still load) must not starve flagship collection.
        want_ab = (have >= 1 and not ab_done
                   and (prefer_ab or have >= opts.want))
        if want_ab:
            what = "A/B"
            r = run_bench(["--ab", str(opts.ab_secs)], timeout_s=2700,
                          lease_retries=opts.lease_retries,
                          lease_backoff_s=opts.lease_backoff)
            if r is not None and not ab_result_eligible(r):
                log(f"A/B attempt produced an ineligible result "
                    f"(error={r.get('error')!r} "
                    f"platform={r.get('platform')!r}); not recording")
                r = None
            if r is not None:
                with open(ab_path, "w") as f:
                    json.dump(r, f)
                    f.write("\n")
                log(f"A/B artifact written: {ab_path}")
        else:
            what = "flagship"
            r = run_bench([], timeout_s=2700,
                          lease_retries=opts.lease_retries,
                          lease_backoff_s=opts.lease_backoff)
            if r is not None and r.get("value", 0) > 0:
                log(f"flagship: {r.get('value')} mutants/s "
                    f"(vs_baseline {r.get('vs_baseline')})")
            elif r is not None:
                r = None  # an error JSON is a failed attempt
        if r is None:
            failed_attempts += 1
            prefer_ab = not want_ab  # alternate the next attempt kind
            log(f"{what} attempt #{failed_attempts} did not land "
                "(lease never granted or bench failed); retrying")
            if opts.diagnose_every and \
                    failed_attempts % opts.diagnose_every == 1:
                diagnose_wedge()
            time.sleep(opts.probe_interval)
            continue
        failed_attempts = 0
        prefer_ab = True
        time.sleep(opts.measure_interval)


if __name__ == "__main__":
    main()
