"""tz-bench-watch: measure early and often, survive the wedge.

The tunneled TPU backend can wedge for hours (every jax op blocks).
This watcher probes the device on a cadence and, whenever it answers,
records real measurements: the flagship bench (appends to
BENCH_HISTORY.jsonl via bench.py's journal) and, once, the A/B
edges-per-hour artifact (BENCH_AB_r<N>.json).  After `--want` flagship
entries plus the A/B artifact it exits and leaves the chip alone —
sustained bench load is itself a wedge trigger.

Reference analog: syz-manager's -bench minutely snapshots
(/root/reference/syz-manager/manager.go:299-333) — continuous recorded
measurement, not one attempt at shutdown.

Usage: python -m syzkaller_tpu.tools.bench_watch [--want 3] [--ab-secs 60]
       [--probe-interval 600] [--round 4]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(f"[bench-watch {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe(timeout_s: float = 240.0) -> bool:
    code = ("import jax, jax.numpy as jnp;"
            "x = jnp.ones((64, 64));"
            "print('OK', float((x @ x).sum()))")
    try:
        res = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s, cwd=REPO)
    except subprocess.TimeoutExpired:
        return False
    return res.returncode == 0 and "OK" in res.stdout


def flagship_entries() -> int:
    path = os.path.join(REPO, "BENCH_HISTORY.jsonl")
    n = 0
    try:
        with open(path) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if e.get("metric") == "exec_ready_mutants_per_sec_per_chip" \
                        and e.get("value", 0) > 0:
                    n += 1
    except OSError:
        pass
    return n


def run_bench(args: list[str], timeout_s: float) -> dict | None:
    try:
        res = subprocess.run([sys.executable, "bench.py",
                              "--no-preflight"] + args,
                             capture_output=True, text=True,
                             timeout=timeout_s, cwd=REPO)
    except subprocess.TimeoutExpired:
        log(f"bench {args} timed out after {timeout_s:.0f}s")
        return None
    if res.returncode != 0:
        log(f"bench {args} failed: {res.stderr.strip()[-300:]}")
        return None
    try:
        return json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        log(f"bench {args} emitted no JSON: {res.stdout[-200:]}")
        return None


def main() -> None:
    ap = argparse.ArgumentParser(prog="tz-bench-watch")
    ap.add_argument("--want", type=int, default=3,
                    help="flagship journal entries to collect")
    ap.add_argument("--ab-secs", type=float, default=60.0)
    ap.add_argument("--probe-interval", type=float, default=600.0)
    ap.add_argument("--measure-interval", type=float, default=900.0,
                    help="spacing between flagship measurements")
    ap.add_argument("--round", type=int, default=4)
    opts = ap.parse_args()

    ab_path = os.path.join(REPO, f"BENCH_AB_r{opts.round:02d}.json")
    while True:
        have = flagship_entries()
        ab_done = os.path.exists(ab_path)
        if have >= opts.want and ab_done:
            log(f"done: {have} flagship entries + A/B artifact; "
                "leaving the chip alone")
            return
        if not probe():
            log("device wedged/unreachable; retrying later")
            time.sleep(opts.probe_interval)
            continue
        log("device healthy")
        # Priority: one flagship first (proves the chip), then the
        # never-yet-recorded A/B artifact, then the remaining flagship
        # entries for journal depth.
        if have >= 1 and not ab_done:
            r = run_bench(["--ab", str(opts.ab_secs)], timeout_s=1800)
            if r is not None:
                with open(ab_path, "w") as f:
                    json.dump(r, f)
                    f.write("\n")
                log(f"A/B artifact written: {ab_path}")
        else:
            r = run_bench([], timeout_s=1800)
            if r is not None:
                log(f"flagship: {r.get('value')} mutants/s "
                    f"(vs_baseline {r.get('vs_baseline')})")
        time.sleep(opts.measure_interval)


if __name__ == "__main__":
    main()
