"""tz-benchcmp: render manager -bench JSON series into an HTML chart
(reference: tools/syz-benchcmp/benchcmp.go:1-36)."""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

_METRICS = ("corpus", "signal", "max_signal", "crashes", "triaged")


def load_series(path: str) -> list[dict]:
    out = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if line:
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


def render_html(series: dict[str, list[dict]]) -> str:
    """One self-contained HTML page, an inline-SVG line chart per
    metric, no external dependencies."""
    parts = ["<html><head><title>bench comparison</title>",
             "<style>body{font-family:monospace} svg{border:1px solid "
             "#ccc;margin:8px}</style></head><body>"]
    colors = ["#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#8c564b"]
    for metric in _METRICS:
        has = any(any(metric in rec for rec in recs)
                  for recs in series.values())
        if not has:
            continue
        parts.append(f"<h3>{metric}</h3><svg width='640' height='240' "
                     f"viewBox='0 0 640 240'>")
        maxv = max((rec.get(metric, 0) for recs in series.values()
                    for rec in recs), default=1) or 1
        maxn = max((len(recs) for recs in series.values()), default=1)
        for si, (name, recs) in enumerate(series.items()):
            pts = []
            for i, rec in enumerate(recs):
                x = 20 + 600 * i / max(maxn - 1, 1)
                y = 220 - 200 * rec.get(metric, 0) / maxv
                pts.append(f"{x:.1f},{y:.1f}")
            color = colors[si % len(colors)]
            if pts:
                parts.append(f"<polyline fill='none' stroke='{color}' "
                             f"points='{' '.join(pts)}'/>")
                parts.append(f"<text x='25' y='{20 + 14 * si}' "
                             f"fill='{color}'>{name}</text>")
        parts.append(f"<text x='560' y='16'>{maxv}</text></svg>")
    parts.append("</body></html>")
    return "".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-benchcmp")
    ap.add_argument("benches", nargs="+", help="bench JSON files")
    ap.add_argument("-o", "--out", default="benchcmp.html")
    args = ap.parse_args(argv)
    series = {Path(b).name: load_series(b) for b in args.benches}
    html = render_html(series)
    Path(args.out).write_text(html)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
