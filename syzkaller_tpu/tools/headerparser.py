"""tz-headerparser: draft syzlang structs from C header definitions
(reference: tools/syz-headerparser — parses struct definitions out of
kernel headers and emits description skeletons for a human to
refine).

Parses `struct name { ... };` blocks with scalar/array/pointer/nested
fields and prints the equivalent syzlang struct declarations plus a
TODO note per field whose type needs human judgment.  This is a
description-authoring aid, not a compiler: the output is a starting
point.
"""

from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path

_INT_TYPES = {
    "char": "int8", "unsigned char": "int8", "signed char": "int8",
    "__u8": "int8", "__s8": "int8", "u8": "int8", "s8": "int8",
    "uint8_t": "int8", "int8_t": "int8",
    "short": "int16", "unsigned short": "int16",
    "__u16": "int16", "__s16": "int16", "u16": "int16", "s16": "int16",
    "uint16_t": "int16", "int16_t": "int16", "__be16": "int16be",
    "__le16": "int16",
    "int": "int32", "unsigned int": "int32", "unsigned": "int32",
    "__u32": "int32", "__s32": "int32", "u32": "int32", "s32": "int32",
    "uint32_t": "int32", "int32_t": "int32", "__be32": "int32be",
    "__le32": "int32",
    "long": "intptr", "unsigned long": "intptr", "size_t": "intptr",
    "long long": "int64", "unsigned long long": "int64",
    "__u64": "int64", "__s64": "int64", "u64": "int64", "s64": "int64",
    "uint64_t": "int64", "int64_t": "int64", "__be64": "int64be",
    "__le64": "int64",
}

_STRUCT_RE = re.compile(
    r"struct\s+(\w+)\s*\{(.*?)\}\s*(?:__attribute__\s*\(\([^)]*\)\))?\s*;",
    re.DOTALL)
_FIELD_RE = re.compile(
    r"^\s*(?P<type>[A-Za-z_][\w \t]*?)\s*"
    r"(?P<ptr>\*+)?\s*"
    r"(?P<name>\w+)\s*"
    r"(?:\[(?P<arr>[^\]]*)\])?\s*"
    r"(?::\s*(?P<bits>\d+))?\s*;")


def _strip_comments(src: str) -> str:
    src = re.sub(r"/\*.*?\*/", "", src, flags=re.DOTALL)
    return re.sub(r"//[^\n]*", "", src)


def _lower_type(ctype: str, ptr: bool, arr: str, bits: str
                ) -> tuple[str, str]:
    """Returns (syzlang type, note)."""
    ctype = re.sub(r"\b(const|volatile|struct)\b", "", ctype).strip()
    ctype = re.sub(r"\s+", " ", ctype)
    if ptr:
        base = "ptr64[inout, array[int8]]"
        if arr is not None:
            # pointer ARRAY: N pointers, not one; non-literal bounds
            # still need the array wrapper + a visible marker
            if arr.strip().isdigit():
                return f"array[{base}, {arr.strip()}]", "TODO: pointee type"
            return f"array[{base}]", "TODO: pointee type + array bound"
        return base, "TODO: pointee type"
    base = _INT_TYPES.get(ctype)
    if base is None:
        # unknown name: nested struct or typedef — reference by name
        base, note = ctype, "TODO: define or map this type"
    else:
        note = ""
    if bits:
        return f"{base}:{bits}", note
    if arr is not None:
        arr = arr.strip()
        if arr and arr.isdigit():
            return f"array[{base}, {arr}]", note
        return f"array[{base}]", note or "TODO: array bound"
    return base, note


def parse_header(src: str) -> list[tuple[str, list[tuple[str, str, str]]]]:
    """[(struct_name, [(field, syz_type, note)])] for each struct."""
    out = []
    src = _strip_comments(src)
    for m in _STRUCT_RE.finditer(src):
        name, body = m.group(1), m.group(2)
        if "{" in body:  # nested anonymous blocks need a human
            continue
        fields = []
        for line in body.split(";"):
            fm = _FIELD_RE.match(line + ";")
            if not fm:
                # anything non-empty we can't parse (multi-declarator
                # `int a, b;`, function pointers, ...) must leave a
                # visible marker — silently dropping fields shifts
                # every later offset
                if line.strip():
                    fields.append((f"unparsed{len(fields)}", "int8",
                                   f"TODO: could not parse "
                                   f"{line.strip()!r}"))
                continue
            typ, note = _lower_type(fm.group("type"),
                                    bool(fm.group("ptr")),
                                    fm.group("arr"), fm.group("bits"))
            fields.append((fm.group("name"), typ, note))
        if fields:
            out.append((name, fields))
    return out


def render(structs) -> str:
    out = []
    for name, fields in structs:
        out.append(f"{name} {{")
        width = max(len(f) for f, _, _ in fields)
        for fname, typ, note in fields:
            line = f"\t{fname.ljust(width)}\t{typ}"
            if note:
                line += f"\t# {note}"
            out.append(line)
        out.append("}")
        out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-headerparser")
    ap.add_argument("headers", nargs="+")
    args = ap.parse_args(argv)
    any_out = False
    for path in args.headers:
        structs = parse_header(Path(path).read_text(errors="replace"))
        if structs:
            any_out = True
            print(f"# drafted from {path}")
            print(render(structs))
    return 0 if any_out else 1


if __name__ == "__main__":
    sys.exit(main())
