"""tz-demo: the whole product in one command.

Runs the full stack the way the reference's "run syz-manager" does
(/root/reference/docs/setup.md): a Manager with a local VM pool, real
fuzzer subprocesses (optionally with the jax mutation engine) driving
the native executor over the simulated kernel, console monitoring,
crash dedup, automatic reproducer extraction, C source emission, and
a live dashboard instance receiving the crash report.

Exits 0 once every artifact exists in the workdir:
  corpus.db grown  | crashes/<sig>/description | crashes/<sig>/repro.prog
  crashes/<sig>/repro.c | a bug filed in the dashboard

Usage: python -m syzkaller_tpu demo --workdir DIR [--minutes 5]
       [--engine jax|cpu] [--vms 2] [--procs 2]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import threading
import time
from typing import Optional


def _fuzzer_cmd(rpc_addr: str, procs: int, engine: str):
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    def fn(inst, index: int) -> str:
        return (f"PYTHONPATH={repo} {sys.executable} -m syzkaller_tpu "
                f"fuzzer -name fuzzer-{index} -manager {rpc_addr} "
                f"-os test -arch 64 -procs {procs} -engine {engine}")

    return fn


def artifact_status(workdir: str, dash) -> dict:
    crashdirs = [d for d in glob.glob(os.path.join(
        workdir, "crashes", "*")) if os.path.isdir(d)]
    corpus_db = os.path.join(workdir, "corpus.db")
    bugs = dash.visible_bugs() if dash is not None else []
    return {
        "corpus.db": os.path.exists(corpus_db)
        and os.path.getsize(corpus_db) > 0,
        "crash": any(os.path.exists(os.path.join(d, "description"))
                     for d in crashdirs),
        "repro.prog": any(os.path.exists(os.path.join(d, "repro.prog"))
                          for d in crashdirs),
        "repro.c": any(os.path.exists(os.path.join(d, "repro.c"))
                       for d in crashdirs),
        "dashboard_bug": len(bugs) > 0,
    }


def run_demo(workdir: str, minutes: float = 5.0, engine: str = "jax",
             vms: int = 2, procs: int = 2,
             log=print) -> dict:
    """Returns the final artifact-status dict (all True = success)."""
    from syzkaller_tpu.dashboard.app import Dashboard, serve_dashboard
    from syzkaller_tpu.manager.html import serve_http
    from syzkaller_tpu.manager.manager import Manager
    from syzkaller_tpu.manager.mgrconfig import load_config

    os.makedirs(workdir, exist_ok=True)
    dash_dir = os.path.join(workdir, "dashboard")
    dash_srv, dash = serve_dashboard(dash_dir,
                                     clients={"demo": "demo-key"})
    dash_host, dash_port = dash_srv.server_address[:2]
    cfg = load_config({
        "name": "demo",
        "workdir": workdir,
        "target": "test/64",
        "type": "local",
        "count": vms,
        "procs": procs,
        "engine": engine,
        "reproduce": True,
        "http": "127.0.0.1:0",
        "dashboard_client": "demo",
        "dashboard_addr": f"http://{dash_host}:{dash_port}",
        "dashboard_key": "demo-key",
    })
    mgr = Manager(cfg)
    http_srv = serve_http(mgr, ("127.0.0.1", 0))
    log(f"demo: manager rpc {mgr.rpc_addr}, "
        f"ui http://{http_srv.server_address[0]}:"
        f"{http_srv.server_address[1]}, "
        f"dashboard http://{dash_host}:{dash_port}, "
        f"{vms} local VMs x {procs} procs, engine={engine}")

    rpc_host, rpc_port = mgr.rpc_addr
    # Instances live long enough for the hint-discovery chain (two
    # triage+smash generations find the sim kernel's two-stage crash
    # magic); crashes still recycle the instance immediately.
    loop_thread = threading.Thread(
        target=mgr.vm_loop,
        args=(_fuzzer_cmd(f"{rpc_host}:{rpc_port}", procs, engine),),
        kwargs={"instance_timeout_s": max(600.0, minutes * 60)},
        daemon=True)
    loop_thread.start()

    deadline = time.time() + minutes * 60
    status = {}
    try:
        while time.time() < deadline:
            time.sleep(5)
            status = artifact_status(workdir, dash)
            snap = mgr.serv.snapshot()
            log(f"demo: corpus {snap['corpus']}, signal {snap['signal']}, "
                f"execs {snap['stats'].get('exec total', 0)}, "
                + " ".join(f"{k}={'Y' if v else 'n'}"
                           for k, v in status.items()))
            if all(status.values()):
                log("demo: all artifacts produced")
                break
    finally:
        mgr.shutdown()
        loop_thread.join(timeout=30)
        http_srv.shutdown()
        dash_srv.shutdown()
    status = artifact_status(workdir, dash)
    log("demo: final " + json.dumps(status))
    return status


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="tz-demo", description=__doc__)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--minutes", type=float, default=5.0)
    ap.add_argument("--engine", default="jax", choices=["cpu", "jax"])
    ap.add_argument("--vms", type=int, default=2)
    ap.add_argument("--procs", type=int, default=2)
    args = ap.parse_args(argv)
    status = run_demo(args.workdir, minutes=args.minutes,
                      engine=args.engine, vms=args.vms, procs=args.procs)
    return 0 if all(status.values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
