"""tz-fmt: canonical formatter for syzlang description files
(reference: tools/syz-fmt/syz-fmt.go — parse via pkg/ast, re-emit).

Formatting IS the AST's own canonical rendering: parse the file and
write Description.format() back.  `-w` rewrites files in place (only
when the content changed); without it the formatted text goes to
stdout.  `-d` exits nonzero if any file differs (CI check mode).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from syzkaller_tpu.compiler.parser import ParseError, parse


def format_text(src: str, filename: str = "<src>") -> str:
    return parse(src, filename).format()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-fmt")
    ap.add_argument("-w", action="store_true",
                    help="write result back to the file")
    ap.add_argument("-d", action="store_true",
                    help="exit 1 if any file is not canonically "
                         "formatted (implies no output)")
    ap.add_argument("files", nargs="+")
    args = ap.parse_args(argv)

    dirty = 0
    errors = 0
    for fname in args.files:
        path = Path(fname)
        try:
            src = path.read_text()
        except OSError as e:
            print(f"{fname}: {e}", file=sys.stderr)
            errors += 1
            continue
        try:
            out = format_text(src, fname)
        except ParseError as e:
            print(f"{fname}: {e}", file=sys.stderr)
            errors += 1
            continue
        changed = out != src
        dirty += changed
        if args.d:
            if changed:
                print(f"{fname}: not formatted", file=sys.stderr)
        elif args.w:
            if changed:
                path.write_text(out)
                print(f"formatted {fname}")
        else:
            # stdout mode always emits the (canonical) source, changed
            # or not — consumers pipe it
            sys.stdout.write(out)
    if errors:  # every file was still visited (gofmt behavior)
        return 2
    return 1 if (args.d and dirty) else 0


if __name__ == "__main__":
    sys.exit(main())
