"""tz-stress: local stress fuzzing without a manager.

Generate/mutate + execute in a loop, printing exec and signal stats
(reference: tools/syz-stress/stress.go:24-50).
"""

from __future__ import annotations

import argparse
import sys
import time

from syzkaller_tpu.fuzzer.fuzzer import Fuzzer, FuzzerConfig
from syzkaller_tpu.fuzzer.proc import Proc
from syzkaller_tpu.fuzzer.workqueue import WorkQueue
from syzkaller_tpu.ipc.env import make_env
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.utils import log


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-stress")
    ap.add_argument("-os", dest="target_os", default="test")
    ap.add_argument("-arch", default="64")
    ap.add_argument("-procs", type=int, default=1)
    ap.add_argument("-duration", type=float, default=10.0,
                    help="seconds")
    ap.add_argument("-engine", default="cpu", choices=["cpu", "jax"])
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_level(args.v)

    target = get_target(args.target_os, args.arch)
    fuzzer = Fuzzer(target, WorkQueue(), cfg=FuzzerConfig())
    mutator = None
    if args.engine == "jax":
        # Honor $TZ_JAX_PLATFORM before anything touches jax: the
        # tunneled accelerator plugin ignores JAX_PLATFORMS, and on a
        # wedged tunnel the very first module-level jnp constant would
        # otherwise block forever in backend init (utils/jaxenv).
        from syzkaller_tpu.utils.jaxenv import (enable_compilation_cache,
                                                pin_jax_platform)

        enable_compilation_cache()
        pin_jax_platform()

        from syzkaller_tpu.fuzzer.proc import PipelineMutator
        from syzkaller_tpu.ops.pipeline import DevicePipeline

        mutator = PipelineMutator(DevicePipeline(target, ct=fuzzer.ct))

    import threading

    stop = threading.Event()
    procs = []
    threads = []
    for pid in range(args.procs):
        proc = Proc(fuzzer, pid, make_env(pid),
                    mutator=mutator,
                    device_hints=args.engine == "jax")
        procs.append(proc)
        t = threading.Thread(target=proc.loop, args=(1 << 62,),
                             kwargs={"stop": stop}, daemon=True)
        threads.append(t)
        t.start()

    t0 = time.time()
    last = 0
    try:
        while time.time() - t0 < args.duration:
            time.sleep(min(5.0, args.duration))
            execs = fuzzer.exec_count()
            print(f"executed {execs} programs (+{execs - last}), "
                  f"corpus {fuzzer.corpus_len()}, "
                  f"signal {len(fuzzer.max_signal)}")
            last = execs
    finally:
        stop.set()
        if mutator is not None:
            mutator.pipeline.stop()
        for t in threads:
            t.join(timeout=5)
        for proc in procs:
            proc.env.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
