"""tz-lint-metrics: keep metric names, code, and docs in sync.

The telemetry layer's contract is that every metric name is (a)
registered exactly once through the telemetry registry API, and (b)
catalogued in docs/observability.md.  Drift in either direction rots
the observability spine silently — a typo'd name literal creates a
parallel metric nobody scrapes, and a stale catalogue sends operators
hunting for series that no longer exist.  This linter greps the source
tree (no imports, so it runs in milliseconds inside the tier-1 suite —
tests/test_tools.py invokes it):

  1. registration scan: every `counter("...")` / `gauge("...")` /
     `histogram("...")` literal and every `span("...")` literal (spans
     register `tz_<name>_seconds`), plus the fuzzer Stat counters
     derived from the STAT_NAMES table the same way fuzzer.py derives
     them at import,
  2. literal check: any metric-shaped string literal (`tz_*_total`,
     `tz_*_seconds`, ...) anywhere in the source must be a registered
     name — catches typos and copy-paste drift at use sites,
  3. catalogue check: the set of registered names and the set of
     backticked `tz_*` names in docs/observability.md must be equal.

Usage: python -m syzkaller_tpu.tools.lint_metrics [repo_root]
"""

from __future__ import annotations

import os
import re
import sys

#: Shapes a metric name can take; a literal matching this anywhere in
#: the tree must be registered.  Prefix-only literals ("tz_breaker_")
#: used for startswith() filtering intentionally do not match.
#: `rate`/`occupancy` cover the triage-plane gauges (ISSUE 4:
#: fold-false-negative rate, plane bucket occupancy).
METRIC_SHAPE = re.compile(
    r"^tz_[a-z0-9_]+_(?:total|seconds|bytes|depth|size|ts|rate"
    r"|occupancy)$")

_REG_RE = re.compile(
    r"""(?:counter|gauge|histogram)\(\s*['"]([a-z0-9_.]+)['"]""")
_SPAN_RE = re.compile(r"""span\(\s*['"]([a-z0-9_.]+)['"]""")
_LIT_RE = re.compile(r"""['"](tz_[a-z0-9_]+)['"]""")
_STAT_NAME_RE = re.compile(r'Stat\.[A-Z_0-9]+:\s*"([a-z ]+)"')
_DOC_NAME_RE = re.compile(r"`(tz_[a-z0-9_]+)`")


def _span_metric_name(span_name: str) -> str:
    # Mirrors telemetry.span_metric_name without importing it: the
    # linter must stay import-free so it lints a broken tree too.
    return "tz_" + span_name.replace(".", "_") + "_seconds"


def _source_files(root: str) -> list[str]:
    out = []
    pkg = os.path.join(root, "syzkaller_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for f in files:
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return sorted(out)


def scan_sources(root: str):
    """(registered names, metric-shaped literals as (file, line, name))
    over syzkaller_tpu/ + bench.py."""
    self_path = os.path.abspath(__file__)
    registered: set[str] = set()
    literals: list[tuple[str, int, str]] = []
    for path in _source_files(root):
        if os.path.abspath(path) == self_path:
            continue
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root)
        # Registration calls routinely wrap the name onto the next
        # line, so these run over the whole file (\s spans newlines);
        # the literal check stays per-line for usable line numbers.
        for m in _REG_RE.finditer(src):
            if m.group(1).startswith("tz_"):
                registered.add(m.group(1))
        for m in _SPAN_RE.finditer(src):
            if "." in m.group(1):
                registered.add(_span_metric_name(m.group(1)))
        for lineno, line in enumerate(src.splitlines(), 1):
            for m in _LIT_RE.finditer(line):
                if METRIC_SHAPE.match(m.group(1)):
                    literals.append((rel, lineno, m.group(1)))
        if rel == os.path.join("syzkaller_tpu", "fuzzer", "fuzzer.py"):
            # Stat counters are registered programmatically from
            # STAT_NAMES; derive the same names the module does.
            for m in _STAT_NAME_RE.finditer(src):
                registered.add(
                    "tz_fuzzer_" + m.group(1).replace(" ", "_")
                    + "_total")
    return registered, literals


def doc_names(docs_path: str) -> set[str]:
    try:
        with open(docs_path) as f:
            return set(_DOC_NAME_RE.findall(f.read()))
    except OSError:
        return set()


def lint(root: str, docs_path: str | None = None) -> list[str]:
    """All problems found, as printable strings (empty = clean)."""
    if docs_path is None:
        docs_path = os.path.join(root, "docs", "observability.md")
    registered, literals = scan_sources(root)
    problems = []
    for rel, lineno, name in literals:
        if name not in registered:
            problems.append(
                f"{rel}:{lineno}: metric-shaped literal {name!r} is "
                "never registered through the telemetry API")
    documented = doc_names(docs_path)
    if not documented:
        problems.append(f"{docs_path}: missing or has no `tz_*` "
                        "catalogue entries")
    for name in sorted(registered - documented):
        problems.append(
            f"{name}: registered in code but missing from the "
            f"catalogue in {os.path.basename(docs_path)}")
    for name in sorted(n for n in documented - registered
                       if METRIC_SHAPE.match(n)):
        problems.append(
            f"{name}: catalogued in {os.path.basename(docs_path)} but "
            "not registered anywhere in the source tree")
    return problems


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    problems = lint(root)
    for p in problems:
        print(p)
    if problems:
        print(f"lint_metrics: {len(problems)} problem(s)")
        return 1
    print("lint_metrics: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
