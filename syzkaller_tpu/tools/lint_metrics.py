"""tz-lint-metrics: keep metric names, code, and docs in sync.

The telemetry layer's contract is that every metric name is (a)
registered exactly once through the telemetry registry API, and (b)
catalogued in docs/observability.md.  Drift in either direction rots
the observability spine silently — a typo'd name literal creates a
parallel metric nobody scrapes, and a stale catalogue sends operators
hunting for series that no longer exist.  This linter greps the source
tree (no imports, so it runs in milliseconds inside the tier-1 suite —
tests/test_tools.py invokes it):

  1. registration scan: every `counter("...")` / `gauge("...")` /
     `histogram("...")` literal and every `span("...")` literal (spans
     register `tz_<name>_seconds`), plus the fuzzer Stat counters
     derived from the STAT_NAMES table the same way fuzzer.py derives
     them at import,
  2. literal check: any metric-shaped string literal (`tz_*_total`,
     `tz_*_seconds`, ...) anywhere in the source must be a registered
     name — catches typos and copy-paste drift at use sites,
  3. catalogue check: the set of registered names and the set of
     backticked `tz_*` names in docs/observability.md must be equal,
  4. span/event/stage-name check (ISSUE 6): every `span("a.b")`,
     `record_event("a.b")`, and lineage `hop(ctx, "a.b")` literal —
     plus the lineage stage table in telemetry/lineage.py — must
     appear backticked in docs/observability.md, and every backticked
     dotted name in the doc whose namespace the code uses must exist
     in code.  Spans added in PRs 3-5 previously had no drift guard.
  5. HBM owner check (ISSUE 17): the OWNERS tuple declared in
     telemetry/hbm.py and the owner literals at `HBM.register("...")`
     call sites must cover each other — an unregistered owner label
     fragments the residency rollup, and a dead OWNERS entry is a
     subsystem that silently lost its ledger wiring.

Usage: python -m syzkaller_tpu.tools.lint_metrics [repo_root]
"""

from __future__ import annotations

import os
import re
import sys

#: Shapes a metric name can take; a literal matching this anywhere in
#: the tree must be registered.  Prefix-only literals ("tz_breaker_")
#: used for startswith() filtering intentionally do not match.
#: `rate`/`occupancy` cover the triage-plane gauges (ISSUE 4:
#: fold-false-negative rate, plane bucket occupancy); `state` covers
#: the durable-recovery outcome gauge (ISSUE 13).
METRIC_SHAPE = re.compile(
    r"^tz_[a-z0-9_]+_(?:total|seconds|bytes|depth|size|ts|rate"
    r"|occupancy|state)$")

_REG_RE = re.compile(
    r"""(?:counter|gauge|histogram)\(\s*['"]([a-z0-9_.]+)['"]""")
_SPAN_RE = re.compile(r"""span\(\s*['"]([a-z0-9_.]+)['"]""")
_EVENT_RE = re.compile(
    r"""record_event\(\s*['"]([a-z0-9_.]+)['"]""")
_HOP_RE = re.compile(
    r"""\bhop\(\s*[^,()'"]+,\s*['"]([a-z0-9_.]+)['"]""")
_DOTTED_LIT_RE = re.compile(r"""['"]([a-z0-9_]+\.[a-z0-9_]+)['"]""")
_LIT_RE = re.compile(r"""['"](tz_[a-z0-9_]+)['"]""")
_STAT_NAME_RE = re.compile(r'Stat\.[A-Z_0-9]+:\s*"([a-z ]+)"')
_DOC_NAME_RE = re.compile(r"`(tz_[a-z0-9_]+)`")
_DOC_DOTTED_RE = re.compile(r"`([a-z0-9_]+\.[a-z0-9_]+)`")
#: HBM ledger owner labels: the declared vocabulary in
#: telemetry/hbm.py and the literals at register() call sites
#: (HBM.register in the tree, ledger.register in bench.py).
_OWNERS_DECL_RE = re.compile(r"^OWNERS\s*=\s*\(([^)]*)\)", re.M)
_OWNER_CALL_RE = re.compile(
    r"""(?:HBM|ledger)\.register\(\s*\n?\s*['"]([a-z0-9_]+)['"]""")
_QUOTED_RE = re.compile(r"""['"]([a-z0-9_]+)['"]""")
#: Backticked dotted names in the doc that end like file paths are
#: prose, not span/event names.
_FILEISH = (".py", ".md", ".go", ".json", ".jsonl", ".js", ".txt")


def _span_metric_name(span_name: str) -> str:
    # Mirrors telemetry.span_metric_name without importing it: the
    # linter must stay import-free so it lints a broken tree too.
    return "tz_" + span_name.replace(".", "_") + "_seconds"


def _source_files(root: str) -> list[str]:
    out = []
    pkg = os.path.join(root, "syzkaller_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for f in files:
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return sorted(out)


def scan_sources(root: str):
    """(registered names, metric-shaped literals as (file, line, name),
    dotted span/event/stage names) over syzkaller_tpu/ + bench.py."""
    self_path = os.path.abspath(__file__)
    registered: set[str] = set()
    literals: list[tuple[str, int, str]] = []
    dotted: set[str] = set()
    for path in _source_files(root):
        if os.path.abspath(path) == self_path:
            continue
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root)
        # Registration calls routinely wrap the name onto the next
        # line, so these run over the whole file (\s spans newlines);
        # the literal check stays per-line for usable line numbers.
        for m in _REG_RE.finditer(src):
            if m.group(1).startswith("tz_"):
                registered.add(m.group(1))
        for m in _SPAN_RE.finditer(src):
            if "." in m.group(1):
                registered.add(_span_metric_name(m.group(1)))
                dotted.add(m.group(1))
        for m in _EVENT_RE.finditer(src):
            if "." in m.group(1):
                dotted.add(m.group(1))
        for m in _HOP_RE.finditer(src):
            dotted.add(m.group(1))
        if rel == os.path.join("syzkaller_tpu", "telemetry",
                               "lineage.py"):
            # The lineage stage table: every dotted literal in the
            # module is a lifecycle stage name (the hop call sites
            # elsewhere only cover the stages the engine reaches).
            for m in _DOTTED_LIT_RE.finditer(src):
                dotted.add(m.group(1))
        for lineno, line in enumerate(src.splitlines(), 1):
            for m in _LIT_RE.finditer(line):
                if METRIC_SHAPE.match(m.group(1)):
                    literals.append((rel, lineno, m.group(1)))
        if rel == os.path.join("syzkaller_tpu", "fuzzer", "fuzzer.py"):
            # Stat counters are registered programmatically from
            # STAT_NAMES; derive the same names the module does.
            for m in _STAT_NAME_RE.finditer(src):
                registered.add(
                    "tz_fuzzer_" + m.group(1).replace(" ", "_")
                    + "_total")
    return registered, literals, dotted


def scan_owners(root: str):
    """(declared OWNERS from telemetry/hbm.py, owner literals at
    HBM.register call sites as (file, owner))."""
    declared: set[str] = set()
    hbm_path = os.path.join(root, "syzkaller_tpu", "telemetry",
                            "hbm.py")
    try:
        with open(hbm_path) as f:
            m = _OWNERS_DECL_RE.search(f.read())
        if m:
            declared = set(_QUOTED_RE.findall(m.group(1)))
    except OSError:
        pass
    used: list[tuple[str, str]] = []
    for path in _source_files(root):
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root)
        for m in _OWNER_CALL_RE.finditer(src):
            used.append((rel, m.group(1)))
    return declared, used


def doc_names(docs_path: str) -> set[str]:
    try:
        with open(docs_path) as f:
            return set(_DOC_NAME_RE.findall(f.read()))
    except OSError:
        return set()


def doc_dotted_names(docs_path: str) -> set[str]:
    """Backticked `a.b` names in the doc, minus file-path prose."""
    try:
        with open(docs_path) as f:
            text = f.read()
    except OSError:
        return set()
    return {n for n in _DOC_DOTTED_RE.findall(text)
            if not n.endswith(_FILEISH)}


def lint(root: str, docs_path: str | None = None) -> list[str]:
    """All problems found, as printable strings (empty = clean)."""
    if docs_path is None:
        docs_path = os.path.join(root, "docs", "observability.md")
    registered, literals, dotted = scan_sources(root)
    problems = []
    for rel, lineno, name in literals:
        if name not in registered:
            problems.append(
                f"{rel}:{lineno}: metric-shaped literal {name!r} is "
                "never registered through the telemetry API")
    documented = doc_names(docs_path)
    if not documented:
        problems.append(f"{docs_path}: missing or has no `tz_*` "
                        "catalogue entries")
    for name in sorted(registered - documented):
        problems.append(
            f"{name}: registered in code but missing from the "
            f"catalogue in {os.path.basename(docs_path)}")
    for name in sorted(n for n in documented - registered
                       if METRIC_SHAPE.match(n)):
        problems.append(
            f"{name}: catalogued in {os.path.basename(docs_path)} but "
            "not registered anywhere in the source tree")
    # Span/event/stage names (ISSUE 6): both directions.  The doc
    # side is filtered to namespaces the code actually uses, so prose
    # like `time.perf_counter` never false-positives, while a stale
    # `pipeline.old_phase` does get flagged.
    doc_dotted = doc_dotted_names(docs_path)
    namespaces = {n.split(".", 1)[0] for n in dotted}
    for name in sorted(dotted - doc_dotted):
        problems.append(
            f"{name}: span/event/stage name used in code but missing "
            f"from {os.path.basename(docs_path)}")
    for name in sorted(n for n in doc_dotted - dotted
                       if n.split(".", 1)[0] in namespaces):
        problems.append(
            f"{name}: span/event/stage name catalogued in "
            f"{os.path.basename(docs_path)} but not used anywhere in "
            "the source tree")
    # HBM owner vocabulary (ISSUE 17): both directions.
    declared_owners, owner_sites = scan_owners(root)
    if declared_owners:
        for rel, owner in sorted(set(owner_sites)):
            if owner not in declared_owners:
                problems.append(
                    f"{rel}: HBM.register owner {owner!r} is not in "
                    "telemetry/hbm.py OWNERS")
        used_owners = {o for _rel, o in owner_sites}
        for owner in sorted(declared_owners - used_owners):
            problems.append(
                f"{owner}: declared in telemetry/hbm.py OWNERS but no "
                "HBM.register call site uses it")
    return problems


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    problems = lint(root)
    for p in problems:
        print(p)
    if problems:
        print(f"lint_metrics: {len(problems)} problem(s)")
        return 1
    print("lint_metrics: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
