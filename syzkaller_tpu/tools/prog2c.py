"""tz-prog2c: program → C translator
(reference: tools/syz-prog2c/prog2c.go)."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from syzkaller_tpu.csource import Options, write_csource
from syzkaller_tpu.models.encoding import deserialize_prog
from syzkaller_tpu.models.target import get_target


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-prog2c")
    ap.add_argument("file")
    ap.add_argument("-os", dest="target_os", default="test")
    ap.add_argument("-arch", default="64")
    ap.add_argument("-threaded", action="store_true")
    ap.add_argument("-repeat", action="store_true")
    ap.add_argument("-procs", type=int, default=1)
    ap.add_argument("-sandbox", default="none")
    ap.add_argument("-build", action="store_true",
                    help="also compile (prints binary path)")
    args = ap.parse_args(argv)

    target = get_target(args.target_os, args.arch)
    p = deserialize_prog(target, Path(args.file).read_bytes())
    opts = Options(threaded=args.threaded, repeat=args.repeat,
                   procs=args.procs, sandbox=args.sandbox)
    src = write_csource(p, opts)
    sys.stdout.write(src.decode())
    if args.build:
        from syzkaller_tpu.csource import build_csource

        print(f"\n// built: {build_csource(src)}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
