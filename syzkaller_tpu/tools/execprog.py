"""tz-execprog: execute programs from files/corpus against an executor.

The repro & bench driver (reference: tools/syz-execprog/execprog.go:26-36
— flags -repeat, -procs, -cover, -hints, -fault_call/-fault_nth,
-coverfile).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from syzkaller_tpu.ipc.env import ExecFlags, ExecOpts, ExecutorCrash, make_env
from syzkaller_tpu.models.encoding import ParseError, deserialize_prog
from syzkaller_tpu.models.encodingexec import serialize_for_exec
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.utils import log


def load_programs(target, paths: list[str]) -> list:
    progs = []
    for path in paths:
        data = Path(path).read_bytes()
        # a file may contain many programs separated by blank lines
        for chunk in data.split(b"\n\n"):
            if not chunk.strip():
                continue
            try:
                progs.append(deserialize_prog(target, chunk))
            except ParseError as e:
                log.logf(0, "skipping bad program in %s: %s", path, e)
    return progs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-execprog")
    ap.add_argument("files", nargs="+")
    ap.add_argument("-os", dest="target_os", default="test")
    ap.add_argument("-arch", default="64")
    ap.add_argument("-repeat", type=int, default=1,
                    help="0 = infinite")
    ap.add_argument("-procs", type=int, default=1)
    ap.add_argument("-cover", action="store_true")
    ap.add_argument("-coverfile", default="")
    ap.add_argument("-hints", action="store_true",
                    help="collect comparisons and run hint mutants")
    ap.add_argument("-fault_call", type=int, default=-1)
    ap.add_argument("-fault_nth", type=int, default=0)
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_level(args.v)

    target = get_target(args.target_os, args.arch)
    progs = load_programs(target, args.files)
    if not progs:
        print("no programs to execute", file=sys.stderr)
        return 1

    flags = ExecFlags(0)
    if args.cover or args.coverfile:
        flags |= ExecFlags.COLLECT_COVER | ExecFlags.DEDUP_COVER
    if args.hints:
        flags |= ExecFlags.COLLECT_COMPS
    if args.fault_call >= 0:
        flags |= ExecFlags.FAULT
    opts = ExecOpts(flags=flags, fault_call=args.fault_call,
                    fault_nth=args.fault_nth)

    env = make_env(0)
    executed = 0
    try:
        rep = 0
        while args.repeat == 0 or rep < args.repeat:
            rep += 1
            for i, p in enumerate(progs):
                try:
                    res = env.exec(opts, serialize_for_exec(p))
                except ExecutorCrash as e:
                    print(f"program {i} crashed the kernel:\n{e.log}")
                    return 2
                executed += 1
                if args.cover:
                    for ci in res.info:
                        print(f"call #{ci.call_index}: errno={ci.errno} "
                              f"signal={len(ci.signal)} "
                              f"cover={len(ci.cover)}")
                if args.coverfile:
                    with open(args.coverfile, "a") as f:
                        for ci in res.info:
                            for pc in ci.cover:
                                f.write(f"0x{int(pc):x}\n")
                if args.hints:
                    _run_hints(env, p, res)
        print(f"executed {executed} programs")
        return 0
    finally:
        env.close()


def _run_hints(env, p, res) -> None:
    from syzkaller_tpu.models.hints import CompMap, mutate_with_hints

    for ci in res.info:
        if not ci.comps:
            continue
        comps = CompMap()
        for op1, op2 in ci.comps:
            comps.add_comp(op1, op2)
        count = 0

        def exec_cb(mutant) -> None:
            nonlocal count
            count += 1
            env.exec(ExecOpts(), serialize_for_exec(mutant))

        mutate_with_hints(p, ci.call_index, comps, exec_cb)
        log.logf(1, "call %d: %d hint mutants", ci.call_index, count)


if __name__ == "__main__":
    sys.exit(main())
