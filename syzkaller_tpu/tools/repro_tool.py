"""tz-repro: extract a reproducer from a crash log
(reference: tools/syz-repro/repro.go)."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.repro.repro import Reproducer, make_env_tester


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-repro")
    ap.add_argument("log")
    ap.add_argument("-os", dest="target_os", default="test")
    ap.add_argument("-arch", default="64")
    ap.add_argument("-title", default="", help="match this crash title")
    ap.add_argument("-no-c", action="store_true")
    args = ap.parse_args(argv)

    target = get_target(args.target_os, args.arch)
    tester = make_env_tester(target, title_filter=args.title or None)
    r = Reproducer(target, tester, extract_c=not args.no_c)
    result = r.run(Path(args.log).read_bytes())
    if result is None:
        print("reproduction failed", file=sys.stderr)
        return 1
    print("# " + result.opts_desc)
    sys.stdout.write(result.prog_text.decode())
    if result.c_src:
        print("\n// ---- C reproducer ----")
        sys.stdout.write(result.c_src.decode())
    return 0


if __name__ == "__main__":
    sys.exit(main())
