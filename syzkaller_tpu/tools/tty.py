"""tz-tty: console/serial reader with crash highlighting
(reference: tools/syz-tty — dump a serial console, decoding the
Windows KD protocol where needed, and flag kernel oopses live).

Reads a device node, pipe, or file; `-kd` runs the stream through the
KD DbgPrint decoder (utils/kd.py); every line is scanned with the
report oops table and crash lines are prefixed so a human tailing a
flaky board sees them immediately.
"""

from __future__ import annotations

import argparse
import sys

from syzkaller_tpu.report import get_reporter
from syzkaller_tpu.utils import kd


def process_stream(reader, out, use_kd: bool = False,
                   target_os: str = "linux", max_bytes: int = 1 << 30
                   ) -> int:
    """Pump reader->out; returns number of crash lines seen."""
    rep = get_reporter(target_os)
    crashes = 0
    pending = b""
    text_buf = b""
    total = 0
    while total < max_bytes:
        chunk = reader.read(4096)
        if not chunk:
            break
        total += len(chunk)
        if use_kd:
            text, pending = kd.decode(pending + chunk)
        else:
            text = chunk
        text_buf += text
        while b"\n" in text_buf:
            line, text_buf = text_buf.split(b"\n", 1)
            shown = line.decode("utf-8", "replace")
            if rep.contains_crash(line + b"\n"):
                crashes += 1
                out.write(f"*** CRASH: {shown}\n")
            else:
                out.write(shown + "\n")
    if text_buf:
        # the stream often dies MID-line at the crash: scan the
        # unterminated tail too
        shown = text_buf.decode("utf-8", "replace")
        if rep.contains_crash(text_buf + b"\n"):
            crashes += 1
            out.write(f"*** CRASH: {shown}\n")
        else:
            out.write(shown + "\n")
    return crashes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-tty")
    ap.add_argument("device", help="tty device, pipe, or log file")
    ap.add_argument("-kd", action="store_true",
                    help="decode Windows KD DbgPrint packets")
    ap.add_argument("-os", dest="target_os", default="linux")
    args = ap.parse_args(argv)
    with open(args.device, "rb", buffering=0) as f:
        crashes = process_stream(f, sys.stdout, use_kd=args.kd,
                                 target_os=args.target_os)
    return 0 if crashes == 0 else 3


if __name__ == "__main__":
    sys.exit(main())
