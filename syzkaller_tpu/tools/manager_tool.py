"""tz-manager: the manager daemon CLI
(reference: syz-manager/manager.go:119 main)."""

from __future__ import annotations

import argparse
import sys

from syzkaller_tpu.manager.manager import Manager
from syzkaller_tpu.manager.mgrconfig import load_config
from syzkaller_tpu.utils import log


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-manager")
    ap.add_argument("-config", required=True)
    ap.add_argument("-bench", default="",
                    help="write periodic stat snapshots to this file")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_level(args.v)

    cfg = load_config(args.config)
    mgr = Manager(cfg)
    if args.bench:
        mgr.start_bench(args.bench)
    host, port = mgr.rpc_addr
    print(f"manager RPC on {host}:{port}", flush=True)
    if mgr.http_server is not None:
        h, p = mgr.http_server.server_address
        print(f"HTTP UI on http://{h}:{p}/", flush=True)

    from syzkaller_tpu.ci.instance import framework_cmd

    def fuzzer_cmd(inst, index):
        fwd = inst.forward(port)
        return framework_cmd(
            "syzkaller_tpu.fuzzer.main", "-name", f"fuzzer-{index}",
            "-manager", fwd, "-os", cfg.target_os,
            "-arch", cfg.target_arch, "-procs", str(cfg.procs),
            "-engine", cfg.engine)

    try:
        mgr.vm_loop(fuzzer_cmd)
    except KeyboardInterrupt:
        pass
    finally:
        mgr.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
