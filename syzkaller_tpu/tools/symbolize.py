"""tz-symbolize: symbolize a crash report against a vmlinux
(reference: tools/syz-symbolize)."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from syzkaller_tpu.report import get_reporter


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-symbolize")
    ap.add_argument("log")
    ap.add_argument("-os", dest="target_os", default="linux")
    ap.add_argument("-kernel_obj", default="")
    args = ap.parse_args(argv)

    reporter = get_reporter(args.target_os, kernel_obj=args.kernel_obj)
    rep = reporter.parse(Path(args.log).read_bytes())
    if rep is None:
        print("no crash found in log", file=sys.stderr)
        return 1
    reporter.symbolize(rep)
    print(f"TITLE: {rep.title}")
    if rep.corrupted:
        print(f"CORRUPTED: {rep.corrupted_reason}")
    if rep.guilty_file:
        print(f"GUILTY: {rep.guilty_file}")
    sys.stdout.buffer.write(rep.report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
