"""tz-crush: replay a crash log's programs over and over to re-trigger
the crash (reference: tools/syz-crush/crush.go)."""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from syzkaller_tpu.ipc.env import ExecOpts, ExecutorCrash, make_env
from syzkaller_tpu.models.encodingexec import serialize_for_exec
from syzkaller_tpu.models.parse import parse_log
from syzkaller_tpu.models.target import get_target


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-crush")
    ap.add_argument("log")
    ap.add_argument("-os", dest="target_os", default="test")
    ap.add_argument("-arch", default="64")
    ap.add_argument("-duration", type=float, default=30.0)
    ap.add_argument("-procs", type=int, default=1)
    args = ap.parse_args(argv)

    target = get_target(args.target_os, args.arch)
    entries = parse_log(target, Path(args.log).read_bytes())
    if not entries:
        print("no programs in log", file=sys.stderr)
        return 1
    print(f"replaying {len(entries)} programs for {args.duration}s")
    env = make_env(0)
    deadline = time.time() + args.duration
    runs = 0
    try:
        while time.time() < deadline:
            for e in entries:
                runs += 1
                try:
                    env.exec(ExecOpts(), serialize_for_exec(e.p))
                except ExecutorCrash as ex:
                    print(f"crash reproduced after {runs} runs:\n{ex.log}")
                    return 0
        print(f"no crash after {runs} runs")
        return 3
    finally:
        env.close()


if __name__ == "__main__":
    sys.exit(main())
