"""tz-parse: extract the programs from a fuzzer console log
(reference: tools/syz-parse — split a log into deserializable
programs and write/print them).

Uses the same log scanner as repro extraction (models/parse.py);
programs that no longer deserialize are skipped with a note.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from syzkaller_tpu.models.encoding import serialize_prog
from syzkaller_tpu.models.parse import parse_log
from syzkaller_tpu.models.target import get_target


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-parse")
    ap.add_argument("log", help="fuzzer console log")
    ap.add_argument("-os", dest="target_os", default="test")
    ap.add_argument("-arch", default="64")
    ap.add_argument("-o", default=None,
                    help="write progN files into this directory "
                         "instead of stdout")
    args = ap.parse_args(argv)
    target = get_target(args.target_os, args.arch)
    data = Path(args.log).read_bytes()
    entries = parse_log(target, data)
    if not entries:
        print("no programs found", file=sys.stderr)
        return 1
    outdir = Path(args.o) if args.o else None
    if outdir:
        outdir.mkdir(parents=True, exist_ok=True)
    for i, ent in enumerate(entries):
        text = serialize_prog(ent.p)
        if outdir:
            (outdir / f"prog{i}").write_bytes(text)
        else:
            sys.stdout.write(f"# proc {ent.proc}\n")
            sys.stdout.write(text.decode())
            sys.stdout.write("\n")
    if outdir:
        print(f"wrote {len(entries)} programs to {outdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
