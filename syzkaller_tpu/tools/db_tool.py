"""tz-db: corpus.db pack/unpack/merge
(reference: tools/syz-db/syz-db.go)."""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

from syzkaller_tpu.db import open_db
from syzkaller_tpu.utils.hashsig import hash_string


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-db")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_pack = sub.add_parser("pack", help="directory of programs → db")
    p_pack.add_argument("dir")
    p_pack.add_argument("db")
    p_unpack = sub.add_parser("unpack", help="db → directory of programs")
    p_unpack.add_argument("db")
    p_unpack.add_argument("dir")
    p_merge = sub.add_parser("merge", help="merge dbs into the first")
    p_merge.add_argument("dst")
    p_merge.add_argument("srcs", nargs="+")
    args = ap.parse_args(argv)

    if args.cmd == "pack":
        db = open_db(args.db)
        n = 0
        for path in sorted(Path(args.dir).iterdir()):
            if path.is_file():
                data = path.read_bytes()
                db.save(hash_string(data), data, 0)
                n += 1
        db.flush()
        print(f"packed {n} programs")
    elif args.cmd == "unpack":
        db = open_db(args.db)
        os.makedirs(args.dir, exist_ok=True)
        for key, rec in db.records.items():
            Path(args.dir, key).write_bytes(rec.val)
        print(f"unpacked {len(db.records)} programs")
    elif args.cmd == "merge":
        dst = open_db(args.dst)
        added = 0
        for src_path in args.srcs:
            src = open_db(src_path)
            for key, rec in src.records.items():
                if key not in dst.records:
                    dst.save(key, rec.val, rec.seq)
                    added += 1
        dst.flush()
        print(f"merged {added} new programs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
