"""tz-mutate: mutate a single program and print the result.

Baseline config #1 (reference: tools/syz-mutate/mutate.go:30-77 —
flags -seed, -len, -enable; reads a program, applies one Mutate,
writes it out).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from syzkaller_tpu.models.encoding import deserialize_prog, serialize_prog
from syzkaller_tpu.models.generation import generate_prog
from syzkaller_tpu.models.mutation import mutate_prog
from syzkaller_tpu.models.rand import RandGen
from syzkaller_tpu.models.target import get_target


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tz-mutate")
    ap.add_argument("file", nargs="?", default="",
                    help="program to mutate (empty: generate one)")
    ap.add_argument("-os", dest="target_os", default="test")
    ap.add_argument("-arch", default="64")
    ap.add_argument("-seed", type=int, default=-1)
    ap.add_argument("-len", dest="length", type=int, default=30)
    ap.add_argument("-n", type=int, default=1,
                    help="number of mutations to apply")
    args = ap.parse_args(argv)

    target = get_target(args.target_os, args.arch)
    import random as pyrandom

    seed = args.seed if args.seed >= 0 \
        else pyrandom.randrange(1 << 30)
    rng = RandGen(target, seed)
    if args.file:
        p = deserialize_prog(target, Path(args.file).read_bytes())
    else:
        p = generate_prog(target, rng, args.length)
    for _ in range(args.n):
        mutate_prog(p, rng, args.length, corpus=[p.clone()])
    sys.stdout.write(serialize_prog(p).decode())
    return 0


if __name__ == "__main__":
    sys.exit(main())
