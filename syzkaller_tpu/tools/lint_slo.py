"""tz-lint-slo: keep the SLO table internally consistent and honest.

The burn-rate engine (telemetry/slo.py) is declarative on purpose:
`SLO_TABLE` is the single place an objective's target, bounds, budget,
and source metric live.  That makes the table the thing that rots — a
target default drifting outside its clamp range, a budget of 0 (burn
divides by it), fast/slow windows inverted so the "fast" confirmation
never beats the "slow" one, or an objective wired to a metric that was
renamed out from under it.  Each of those fails silently at runtime
(the engine clamps, skips, or just never fires); this linter fails
loudly in tier-1 instead (tests/test_tools.py invokes it).

Checks, per objective and globally:

  1. window order: FAST_S_DEFAULT < SLOW_S_DEFAULT — multi-window
     burn alerting is meaningless if the confirmation window is not
     the longer one,
  2. table shape: unique names, kind in {floor, ceiling}, budget in
     (0, 1], lo < hi, and the default target inside [lo, hi],
  3. metric existence: every `metric` an objective reads must be a
     name registered through the telemetry API or derived from a span
     (reuses lint_metrics' source scan, so renames are caught even
     when the SLO module still imports cleanly).

Unlike lint_metrics this linter DOES import the slo module — the
table is data, and re-parsing it from source would just be a second,
worse parser.  Usage: python -m syzkaller_tpu.tools.lint_slo [root]
"""

from __future__ import annotations

import os
import sys

from syzkaller_tpu.tools import lint_metrics


def lint(root: str, table=None, fast_s=None, slow_s=None) -> list[str]:
    """All problems found, as printable strings (empty = clean).
    `table`/`fast_s`/`slow_s` override the live module values so tests
    can exercise the failure modes without editing the real table."""
    from syzkaller_tpu.telemetry import slo

    if table is None:
        table = slo.SLO_TABLE
    if fast_s is None:
        fast_s = slo.FAST_S_DEFAULT
    if slow_s is None:
        slow_s = slo.SLOW_S_DEFAULT
    problems: list[str] = []
    if not fast_s < slow_s:
        problems.append(
            f"burn windows inverted: FAST_S_DEFAULT ({fast_s}) must be "
            f"< SLOW_S_DEFAULT ({slow_s})")
    registered, _literals, _dotted = lint_metrics.scan_sources(root)
    seen: set[str] = set()
    for obj in table:
        name = obj.get("name", "<unnamed>")
        where = f"slo table [{name}]"
        if name in seen:
            problems.append(f"{where}: duplicate objective name")
        seen.add(name)
        kind = obj.get("kind")
        if kind not in ("floor", "ceiling"):
            problems.append(
                f"{where}: kind {kind!r} is not floor|ceiling")
        budget = obj.get("budget")
        if not isinstance(budget, (int, float)) or not 0 < budget <= 1:
            problems.append(
                f"{where}: error budget {budget!r} must be in (0, 1]")
        lo, hi = obj.get("lo"), obj.get("hi")
        default = obj.get("default")
        if lo is None or hi is None or not lo < hi:
            problems.append(
                f"{where}: clamp range [{lo!r}, {hi!r}] is not "
                "a valid lo < hi interval")
        elif default is None or not lo <= default <= hi:
            problems.append(
                f"{where}: default target {default!r} outside its own "
                f"clamp range [{lo}, {hi}] — the env knob "
                f"{obj.get('env')} could never reach it")
        env = obj.get("env", "")
        if not env.startswith("TZ_SLO_"):
            problems.append(
                f"{where}: env knob {env!r} must be TZ_SLO_*")
        metric = obj.get("metric")
        if metric and metric not in registered:
            problems.append(
                f"{where}: reads metric {metric!r} which is not "
                "registered anywhere in the source tree")
    return problems


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    problems = lint(root)
    for p in problems:
        print(p)
    if problems:
        print(f"lint_slo: {len(problems)} problem(s)")
        return 1
    print("lint_slo: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
