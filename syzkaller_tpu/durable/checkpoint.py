"""Atomic, versioned, checksummed checkpoint images.

One file holds the whole warm-restart image:

    <I magic> <I version> <Q json_len> json <Q blob_len> blob
    <I crc32(json + blob)>

The json carries per-section metadata plus (offset, length) slices
into the blob for each section's bulk bytes (zlib-packed planes,
result payloads).  The write is temp-file + flush + fsync + rename —
the exact db._compact discipline — so a reader sees either the old
complete image or the new complete image, never a torn one.  The
`durable.ckpt_write` fault seam sits between the fsync and the
rename: a scripted fault models dying with the image fully written
but not yet published, which must leave the previous checkpoint (and
the WAL) authoritative.

Readers raise CheckpointError on any structural or checksum problem;
the store falls back to WAL-only (or cold) recovery and quarantines
the bad file as `<path>.corrupt` for the operator.
"""

from __future__ import annotations

import os
import struct
import zlib

from syzkaller_tpu import telemetry
from syzkaller_tpu.health.faultinject import fault_point

try:
    import json
except ImportError:  # pragma: no cover
    json = None

MAGIC = 0x745A636B  # "tzck"
CUR_VERSION = 1

_HDR = struct.Struct("<II")  # magic, version
_LEN = struct.Struct("<Q")
_CRC = struct.Struct("<I")

_M_CKPTS = telemetry.counter(
    "tz_durable_ckpts_total", "checkpoint images written")
_M_ERRORS = telemetry.counter(
    "tz_durable_ckpt_errors_total",
    "checkpoint writes that failed (scripted seam or I/O error); "
    "the previous image and the WAL stay authoritative")
_G_LAST_TS = telemetry.gauge(
    "tz_durable_ckpt_last_ts",
    "wallclock of the last successful checkpoint (0 = never)")
_G_BYTES = telemetry.gauge(
    "tz_durable_ckpt_bytes", "size of the last checkpoint image")


class CheckpointError(Exception):
    """Structural/checksum failure reading a checkpoint image."""


def pack_section(arr) -> bytes:
    """zlib-pack a uint8 plane for the image blob (planes are mostly
    zeros early in a campaign; level 1 keeps the cadence write cheap)."""
    import numpy as np

    a = np.ascontiguousarray(np.asarray(arr, dtype=np.uint8))
    return zlib.compress(a.tobytes(), 1)


def unpack_section(blob: bytes, size: int):
    """Inverse of pack_section — numpy only, safe on the jax-free
    recovery path."""
    import numpy as np

    raw = zlib.decompress(bytes(blob))
    if len(raw) != size:
        raise CheckpointError(
            f"plane section is {len(raw)} bytes, expected {size}")
    return np.frombuffer(raw, dtype=np.uint8).copy()


def write_checkpoint(path: str, sections: dict, ts: float) -> int:
    """Publish `sections` ({name: (meta_dict, blob_bytes)}) atomically
    at `path`; returns the image size.  Raises on seam faults and I/O
    errors — the caller (DurableStore.checkpoint_now) accounts the
    failure and leaves the WAL intact."""
    blob_parts: list[bytes] = []
    meta: dict = {"ts": round(float(ts), 3), "sections": {}}
    off = 0
    for name, (sec_meta, sec_blob) in sections.items():
        sec_blob = bytes(sec_blob)
        meta["sections"][name] = {
            "meta": sec_meta, "off": off, "len": len(sec_blob)}
        blob_parts.append(sec_blob)
        off += len(sec_blob)
    jb = json.dumps(meta, separators=(",", ":"),
                    sort_keys=True).encode()
    blob = b"".join(blob_parts)
    crc = zlib.crc32(jb)
    crc = zlib.crc32(blob, crc)
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(_HDR.pack(MAGIC, CUR_VERSION))
            f.write(_LEN.pack(len(jb)))
            f.write(jb)
            f.write(_LEN.pack(len(blob)))
            f.write(blob)
            f.write(_CRC.pack(crc))
            f.flush()
            os.fsync(f.fileno())
        # Seam between fsync and publish: a scripted fault dies with
        # the new image complete but unrenamed — the previous image
        # must stay authoritative and the stale tmp must be cleaned
        # on the next open.
        fault_point("durable.ckpt_write")
        os.replace(tmp, path)
    except BaseException:
        _M_ERRORS.inc()
        raise
    size = os.path.getsize(path)
    _M_CKPTS.inc()
    _G_LAST_TS.set(round(float(ts), 3))
    _G_BYTES.set(size)
    return size


def read_checkpoint(path: str) -> dict:
    """Validate and decode an image into {name: (meta, blob_bytes)}
    plus the "__ts__" stamp; raises CheckpointError on anything
    structurally or cryptographically wrong."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise CheckpointError(f"unreadable checkpoint: {e}") from e
    if len(data) < _HDR.size + 2 * _LEN.size + _CRC.size:
        raise CheckpointError(f"checkpoint too short ({len(data)}B)")
    magic, ver = _HDR.unpack_from(data, 0)
    if magic != MAGIC:
        raise CheckpointError(f"bad magic {magic:#x}")
    if ver != CUR_VERSION:
        raise CheckpointError(f"unsupported version {ver}")
    pos = _HDR.size
    (jlen,) = _LEN.unpack_from(data, pos)
    pos += _LEN.size
    if pos + jlen + _LEN.size + _CRC.size > len(data):
        raise CheckpointError("truncated json section")
    jb = data[pos:pos + jlen]
    pos += jlen
    (blen,) = _LEN.unpack_from(data, pos)
    pos += _LEN.size
    if pos + blen + _CRC.size > len(data):
        raise CheckpointError("truncated blob section")
    blob = data[pos:pos + blen]
    pos += blen
    (want_crc,) = _CRC.unpack_from(data, pos)
    crc = zlib.crc32(jb)
    crc = zlib.crc32(blob, crc)
    if crc != want_crc:
        raise CheckpointError(
            f"checksum mismatch ({crc:#x} != {want_crc:#x})")
    try:
        meta = json.loads(jb.decode())
    except Exception as e:
        raise CheckpointError(f"undecodable meta: {e}") from e
    out: dict = {"__ts__": meta.get("ts", 0.0)}
    for name, sec in (meta.get("sections") or {}).items():
        o, ln = int(sec["off"]), int(sec["len"])
        if o < 0 or o + ln > len(blob):
            raise CheckpointError(f"section {name} slice out of range")
        out[name] = (sec.get("meta") or {}, blob[o:o + ln])
    return out
