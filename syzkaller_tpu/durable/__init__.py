"""Durable state & warm restart (docs/health.md "Durability &
recovery").

Manager process death used to be a cold-start catastrophe: corpus.db
survived, but the uint8[2^26] signal-plane mirror, the mutant plane,
per-tenant serve planes + QoS credits, the coverage growth ring, and
the PR 8 candidate-custody / serve delivery ledgers all rebuilt from
nothing, paying a full corpus re-triage.  This package makes that
death a warm restart:

  * checkpoint.py — atomic, versioned, checksummed on-disk images
    (temp-file + fsync + rename, the db._compact discipline),
  * wal.py — a compact write-ahead log journaling plane merges,
    custody transitions, and credit updates between checkpoints,
  * recovery.py — checksum validation, torn-tail truncation, and
    jax-free replay that converges to the pre-crash state,
  * store.py — the DurableStore orchestrator: checkpoint cadence
    (TZ_CKPT_INTERVAL_S), WAL size cap (TZ_CKPT_WAL_MAX_MB), the
    journal fan-in the subsystems write through, and open-time
    recovery.
"""

from syzkaller_tpu.durable.checkpoint import (CheckpointError,
                                              read_checkpoint,
                                              write_checkpoint)
from syzkaller_tpu.durable.store import DurableStore, RecoveredState
from syzkaller_tpu.durable.wal import WriteAheadLog, read_wal

__all__ = [
    "CheckpointError", "DurableStore", "RecoveredState",
    "WriteAheadLog", "read_checkpoint", "read_wal", "write_checkpoint",
]
