"""Write-ahead log: the between-checkpoints half of durability.

Every record is a self-validating frame

    <I payload_len> <I crc32(payload)> payload
    payload = <H kind_len> kind <I meta_len> meta_json blob

appended + flushed (+ fsync'd unless TZ_CKPT_WAL_FSYNC=0) under the
store's journal barrier.  A crash mid-append leaves a torn tail; the
reader validates length + crc per frame and physically truncates the
file to the last whole record (counted, `durable.wal_truncate` on the
timeline) — replay then converges to exactly the state as of the last
durable record, which is the contract the SIGKILL drill pins.

A successful checkpoint resets the log to its header (the checkpoint
image subsumes every journaled record); a FAILED checkpoint must
leave the log intact, which is why reset() lives here as an explicit
call and not inside append().

The `durable.wal_append` fault seam sits before the write so the
crash-consistency tests can script an append failing mid-stride.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Optional

from syzkaller_tpu import telemetry
from syzkaller_tpu.health.faultinject import fault_point
from syzkaller_tpu.utils import log

try:
    import json
except ImportError:  # pragma: no cover
    json = None

MAGIC = 0x745A774C  # "tzwL"
CUR_VERSION = 1

_HDR = struct.Struct("<II")  # magic, version
_REC = struct.Struct("<II")  # payload length, crc32(payload)
_KIND = struct.Struct("<H")  # kind length
_META = struct.Struct("<I")  # meta-json length

_M_RECORDS = telemetry.counter(
    "tz_durable_wal_records_total",
    "records appended to the write-ahead log")
_M_TRUNCS = telemetry.counter(
    "tz_durable_wal_truncations_total",
    "torn WAL tails physically truncated on open")
_M_ERRORS = telemetry.counter(
    "tz_durable_wal_errors_total",
    "WAL appends that failed (scripted seam or I/O error) — the "
    "record is lost; recovery converges to the last durable one")
_G_BYTES = telemetry.gauge(
    "tz_durable_wal_bytes",
    "WAL bytes accumulated since the last checkpoint")


class WalRecord:
    """One journaled operation: a kind tag, a small JSON meta dict,
    and an optional raw blob (plane indices, result payloads)."""

    __slots__ = ("kind", "meta", "blob")

    def __init__(self, kind: str, meta: dict, blob: bytes = b""):
        self.kind = kind
        self.meta = meta
        self.blob = blob

    def __repr__(self) -> str:  # tests / debugging
        return (f"WalRecord({self.kind!r}, {self.meta!r}, "
                f"blob[{len(self.blob)}])")


def _encode(kind: str, meta: dict, blob: bytes) -> bytes:
    kb = kind.encode()
    mb = json.dumps(meta, separators=(",", ":"),
                    sort_keys=True).encode()
    payload = _KIND.pack(len(kb)) + kb + _META.pack(len(mb)) + mb \
        + bytes(blob)
    return _REC.pack(len(payload), zlib.crc32(payload)) + payload


def _decode_payload(payload: bytes) -> WalRecord:
    (klen,) = _KIND.unpack_from(payload, 0)
    pos = _KIND.size
    kind = payload[pos:pos + klen].decode()
    pos += klen
    (mlen,) = _META.unpack_from(payload, pos)
    pos += _META.size
    meta = json.loads(payload[pos:pos + mlen].decode())
    return WalRecord(kind, meta, payload[pos + mlen:])


class WriteAheadLog:
    """Append side.  Not thread-safe by itself — the DurableStore's
    journal barrier serializes every append and the checkpoint reset."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        fresh = not os.path.exists(path) \
            or os.path.getsize(path) < _HDR.size
        self._f = open(path, "ab")
        if fresh:
            self._f.write(_HDR.pack(MAGIC, CUR_VERSION))
            self._f.flush()
            os.fsync(self._f.fileno())
        self.bytes_since_ckpt = max(
            0, os.path.getsize(path) - _HDR.size)
        self.records_appended = 0
        _G_BYTES.set(self.bytes_since_ckpt)

    def append(self, kind: str, meta: Optional[dict] = None,
               blob: bytes = b"") -> None:
        """Journal one record durably; raises on scripted seam faults
        and I/O errors (the store decides whether to swallow)."""
        frame = _encode(kind, meta or {}, blob)
        with telemetry.span("durable.wal_append"):
            fault_point("durable.wal_append")
            self._f.write(frame)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
        self.bytes_since_ckpt += len(frame)
        self.records_appended += 1
        _M_RECORDS.inc()
        _G_BYTES.set(self.bytes_since_ckpt)

    def reset(self) -> None:
        """Truncate back to the header after a successful checkpoint
        (the image subsumes every journaled record)."""
        self._f.truncate(_HDR.size)
        self._f.flush()
        os.fsync(self._f.fileno())
        self.bytes_since_ckpt = 0
        _G_BYTES.set(0)

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


def read_wal(path: str) -> list[WalRecord]:
    """Validate + decode every whole record; physically truncate the
    file to the last good frame when the tail is torn or corrupt, so
    post-recovery appends land after valid bytes (the same discipline
    db.open_db applies to corpus.db)."""
    if not os.path.exists(path):
        return []
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < _HDR.size:
        return []
    magic, _ver = _HDR.unpack_from(data, 0)
    if magic != MAGIC:
        log.logf(0, "WAL %s: bad magic %#x; discarding", path, magic)
        _M_TRUNCS.inc()
        telemetry.record_event(
            "durable.wal_truncate", f"{path}: bad magic, discarded")
        with open(path, "r+b") as f:
            f.truncate(0)
        return []
    records: list[WalRecord] = []
    pos = _HDR.size
    good = pos
    while pos + _REC.size <= len(data):
        plen, crc = _REC.unpack_from(data, pos)
        end = pos + _REC.size + plen
        if end > len(data):
            break
        payload = data[pos + _REC.size:end]
        if zlib.crc32(payload) != crc:
            break
        try:
            records.append(_decode_payload(payload))
        except Exception:
            break
        pos = end
        good = pos
    if good < len(data):
        torn = len(data) - good
        _M_TRUNCS.inc()
        telemetry.record_event(
            "durable.wal_truncate",
            f"{path}: {torn} torn tail bytes after "
            f"{len(records)} good records")
        log.logf(0, "WAL %s: truncating %d torn tail bytes "
                 "(%d records recovered)", path, torn, len(records))
        with open(path, "r+b") as f:
            f.truncate(good)
    return records
