"""WAL replay: checkpoint image + journal -> pre-crash state.

Replay is deliberately jax-free (numpy + the pure-python Signal):
recovery runs before any device work, and the recovered signal mirror
is re-uploaded through the triage engine's existing
`_ensure_plane_locked` rebuild path (one H2D, zero new jit compiles)
rather than through any device code here.

Replay rules (docs/health.md "Durability & recovery"):

  * plane records ("merge", "tplane") are idempotent max/set-merges —
    journaled after the in-memory mutation, so a checkpoint racing an
    append at worst double-applies them harmlessly,
  * ledger records (cand_*/serve_*) are exact transitions journaled
    under the store's barrier — replay reproduces the custody ledgers
    bit-for-bit, then COLLAPSES them: a restarted manager re-mints
    its session epoch, so every fuzzer/tenant re-Connects, which
    returns in-flight custody to the queues anyway.  Collapsing at
    recovery (inflight/owned -> candidate queue; serve inflight ->
    queue front) conserves the multisets with zero loss and zero
    double-count,
  * "corpus_add" carries the post-merge input dict and the signal
    diff, so replaying it is idempotent and order-independent with
    respect to the checkpoint,
  * unknown kinds are skipped (forward compatibility: a newer writer
    journals kinds an older reader ignores rather than dying on).
"""

from __future__ import annotations

import numpy as np

from syzkaller_tpu.durable.checkpoint import unpack_section
from syzkaller_tpu.signal import Signal
from syzkaller_tpu.utils import log


def _sig(serialized) -> Signal:
    if not serialized:
        return Signal()
    return Signal.deserialize(serialized[0], serialized[1])


def _idx(blob: bytes) -> np.ndarray:
    return np.frombuffer(bytes(blob), dtype=np.uint32).astype(np.int64)


class _Ledger:
    """One fuzzer's custody during replay (mirrors FuzzerState's
    inflight/owned without importing the manager)."""

    __slots__ = ("inflight", "owned")

    def __init__(self, inflight=None, owned=None):
        self.inflight: list = [list(b) for b in (inflight or [])]
        self.owned: list = list(owned or [])


class _Tenant:
    """One serve tenant's delivery ledger + QoS state during replay."""

    __slots__ = ("pending", "inflight", "credit", "novelty_ewma",
                 "stalled", "rows_spent", "delivered", "demand_rows")

    def __init__(self, meta=None, payloads=None):
        meta = meta or {}
        self.pending: list = list(payloads or [])  # [(rid, bytes)]
        self.inflight: list = []  # [(seq, [(rid, bytes)])]
        self.credit = float(meta.get("credit", 1.0))
        self.novelty_ewma = float(meta.get("novelty_ewma", 0.0))
        self.stalled = bool(meta.get("stalled", False))
        self.rows_spent = int(meta.get("rows_spent", 0))
        self.delivered = int(meta.get("delivered", 0))
        self.demand_rows = int(meta.get("demand_rows", 0))

    def settle(self, seq: int, ack_seq: int) -> None:
        keep, requeued = [], []
        for bseq, items in self.inflight:
            if bseq <= ack_seq:
                self.delivered += len(items)
            elif bseq < seq:
                requeued.extend(items)
            else:
                keep.append((bseq, items))
        self.inflight = keep
        if requeued:
            self.pending[:0] = requeued


class _HubMgr:
    """One hub-side manager's delivery custody during replay.  The hub
    ships programs by cursor (last_seq into the global seq index), so
    inflight entries carry (reply seq, cursor start, cursor end) plus
    the repro payloads actually handed out; rollback = the min start
    of the abandoned suffix (acks are a high-water mark, so abandoned
    batches always form a suffix of the cursor range)."""

    __slots__ = ("last_seq", "inflight", "pending", "seen")

    def __init__(self, meta=None, blob: bytes = b""):
        meta = meta or {}
        self.last_seq = int(meta.get("last_seq") or 0)
        self.inflight: list = []  # [rseq, start, end, [payloads]]
        for rseq, start, end, off, lens in meta.get("inflight") or []:
            payloads, o = [], int(off)
            for ln in lens:
                payloads.append(bytes(blob[o:o + ln]))
                o += ln
            self.inflight.append([int(rseq), int(start), int(end),
                                  payloads])
        self.pending: list = []
        o = int(meta.get("pending_off") or 0)
        for ln in meta.get("pending_lens") or []:
            self.pending.append(bytes(blob[o:o + ln]))
            o += ln
        self.seen = set(meta.get("seen") or [])

    def settle(self, seq: int, ack_seq: int) -> None:
        keep, requeued = [], []
        rollback = None
        for entry in self.inflight:
            rseq, start, _end, payloads = entry
            if rseq <= ack_seq:
                continue  # delivered
            if rseq < seq:
                rollback = start if rollback is None \
                    else min(rollback, start)
                requeued.extend(payloads)
            else:
                keep.append(entry)
        self.inflight = keep
        if rollback is not None:
            self.last_seq = min(self.last_seq, rollback)
        if requeued:
            self.pending[:0] = requeued


def replay(ckpt: dict, records: list) -> dict:
    """Apply `records` (wal.WalRecord list) on top of a decoded
    checkpoint image (checkpoint.read_checkpoint output, or {} for
    WAL-only recovery).  Returns the recovered-state dict the domain
    objects restore from (store.RecoveredState wraps it)."""
    out: dict = {"ckpt_ts": ckpt.get("__ts__", 0.0),
                 "wal_records": len(records)}

    # -- seed from the checkpoint image ------------------------------------
    control = None
    if "control" in ckpt:
        meta, _blob = ckpt["control"]
        control = {
            "queue": [dict(c) for c in meta.get("queue") or []],
            "corpus": {k: dict(v)
                       for k, v in (meta.get("corpus") or {}).items()},
            "corpus_signal": _sig(meta.get("corpus_signal")),
            "max_signal": _sig(meta.get("max_signal")),
            "cover": set(int(pc) for pc in meta.get("cover") or []),
            "triaged": int(meta.get("triaged") or 0),
        }
        fuzzers = {name: _Ledger(st.get("inflight"), st.get("owned"))
                   for name, st in (meta.get("fuzzers") or {}).items()}
    else:
        fuzzers = {}

    mirror = None
    if "signal_plane" in ckpt:
        meta, blob = ckpt["signal_plane"]
        mirror = unpack_section(blob, int(meta["size"]))

    mutant = None
    if "mutant_plane" in ckpt:
        meta, blob = ckpt["mutant_plane"]
        mutant = {"bits": int(meta["bits"]),
                  "plane": unpack_section(blob, int(meta["size"]))}

    tplanes: dict = {}
    tp_bits = None
    tp_epochs: dict = {}
    if "tenant_planes" in ckpt:
        meta, blob = ckpt["tenant_planes"]
        tp_bits = int(meta["bits"])
        for name, sec in (meta.get("tenants") or {}).items():
            o, ln = int(sec["off"]), int(sec["len"])
            tplanes[name] = unpack_section(blob[o:o + ln], 1 << tp_bits)
            tp_epochs[name] = int(sec.get("epoch") or 0)

    serve = None
    tenants: dict = {}
    if "serve" in ckpt:
        meta, blob = ckpt["serve"]
        serve = {"rid": int(meta.get("rid") or 0)}
        for name, tm in (meta.get("tenants") or {}).items():
            payloads = []
            for rid, off, ln in tm.get("items") or []:
                payloads.append((rid, bytes(blob[off:off + ln])))
            tenants[name] = _Tenant(tm, payloads)

    coverage = None
    if "coverage" in ckpt:
        meta, _blob = ckpt["coverage"]
        coverage = dict(meta)

    # Accounting ledger + SLO latches (ISSUE 14): checkpoint-only
    # sections (no journal records — the ledger tolerates losing one
    # cadence interval of metering), passed through verbatim.
    accounting = None
    if "accounting" in ckpt:
        meta, _blob = ckpt["accounting"]
        accounting = dict(meta)
    slo = None
    if "slo" in ckpt:
        meta, _blob = ckpt["slo"]
        slo = dict(meta)

    # Corpus arena (ISSUE 18): checkpoint-only durable authority —
    # serialized programs + sampling weights + epoch.  Passed through
    # opaque (jax-free here); DevicePipeline.restore_corpus_arena
    # re-tensorizes and re-uploads in one flush on attach.
    arena_sec = None
    if "corpus_arena" in ckpt:
        meta, blob = ckpt["corpus_arena"]
        arena_sec = {"meta": dict(meta), "blob": bytes(blob)}

    hub = None
    hub_mgrs: dict = {}
    if "hub" in ckpt:
        meta, blob = ckpt["hub"]
        hub = {"next_seq": int(meta.get("next_seq") or 1)}
        for name, hm in (meta.get("managers") or {}).items():
            hub_mgrs[name] = _HubMgr(hm, blob)

    # -- replay the journal ------------------------------------------------
    for rec in records:
        kind, meta, blob = rec.kind, rec.meta, rec.blob
        if kind == "merge":
            size = int(meta.get("size") or 0)
            if mirror is None:
                mirror = np.zeros(size, np.uint8)
            if size and mirror.size != size:
                log.logf(0, "durable: merge record size %d != mirror "
                         "%d; skipped", size, mirror.size)
                continue
            np.maximum.at(mirror, _idx(blob),
                          np.uint8(int(meta.get("prio") or 0) + 1))
        elif kind == "tplane":
            bits = int(meta.get("bits") or 0)
            if tp_bits is None:
                tp_bits = bits
            name = meta.get("tenant") or "tenant"
            plane = tplanes.get(name)
            if plane is None:
                plane = tplanes[name] = np.zeros(1 << tp_bits, np.uint8)
            idx = _idx(blob)
            if idx.size and idx.max() < plane.size:
                plane[idx] = 1
        elif kind == "cand_add":
            if control is None:
                control = _empty_control()
            control["queue"].extend(
                dict(c) for c in meta.get("cands") or [])
        elif kind == "cand_issue":
            if control is None:
                control = _empty_control()
            cands = [dict(c) for c in meta.get("cands") or []]
            queue = control["queue"]
            for c in cands:
                try:
                    queue.remove(c)
                except ValueError:
                    pass  # pre-checkpoint issue raced the snapshot
            f = fuzzers.setdefault(meta.get("name") or "fuzzer",
                                   _Ledger())
            f.inflight.append([int(meta.get("seq") or 0), cands])
            control["triaged"] += len(cands)
        elif kind == "cand_settle":
            f = fuzzers.setdefault(meta.get("name") or "fuzzer",
                                   _Ledger())
            seq = int(meta.get("seq") or 0)
            ack = int(meta.get("ack_seq") or 0)
            executed = int(meta.get("executed") or 0)
            keep = []
            for bseq, batch in f.inflight:
                if bseq <= ack:
                    f.owned.extend(batch)
                elif bseq < seq:
                    if control is None:
                        control = _empty_control()
                    control["queue"].extend(batch)
                else:
                    keep.append([bseq, batch])
            f.inflight = keep
            if executed:
                del f.owned[:min(executed, len(f.owned))]
        elif kind == "cand_requeue":
            f = fuzzers.pop(meta.get("name") or "fuzzer", None)
            if f is not None:
                if control is None:
                    control = _empty_control()
                for _bseq, batch in f.inflight:
                    control["queue"].extend(batch)
                control["queue"].extend(f.owned)
        elif kind == "corpus_add":
            if control is None:
                control = _empty_control()
            inp = dict(meta.get("input") or {})
            control["corpus"][meta.get("key")] = inp
            diff = _sig(meta.get("diff"))
            control["corpus_signal"].merge(diff)
            control["max_signal"].merge(diff)
            control["cover"].update(
                int(pc) for pc in inp.get("cover") or [])
        elif kind == "max_sig":
            if control is None:
                control = _empty_control()
            control["max_signal"].merge(_sig(meta.get("sig")))
        elif kind == "serve_offer":
            if serve is None:
                serve = {"rid": 0}
            t = tenants.setdefault(meta.get("tenant") or "tenant",
                                   _Tenant())
            rids = meta.get("rids") or []
            lens = meta.get("lens") or []
            off = 0
            for rid, ln in zip(rids, lens):
                t.pending.append((rid, bytes(blob[off:off + ln])))
                off += ln
            t.rows_spent += int(meta.get("rows_spent") or 0)
            serve["rid"] = max(int(serve.get("rid") or 0),
                               int(meta.get("rid_after") or 0))
        elif kind == "serve_issue":
            t = tenants.setdefault(meta.get("tenant") or "tenant",
                                   _Tenant())
            n = min(int(meta.get("n") or 0), len(t.pending))
            items, t.pending = t.pending[:n], t.pending[n:]
            t.inflight.append((int(meta.get("seq") or 0), items))
        elif kind == "serve_settle":
            t = tenants.setdefault(meta.get("tenant") or "tenant",
                                   _Tenant())
            t.settle(int(meta.get("seq") or 0),
                     int(meta.get("ack_seq") or 0))
        elif kind == "serve_connect":
            t = tenants.get(meta.get("tenant") or "tenant")
            if t is not None:
                t.settle(1 << 62, 0)
                t.demand_rows = 0
        elif kind == "serve_reap":
            tenants.pop(meta.get("tenant") or "tenant", None)
        elif kind == "credit":
            for name, c in (meta.get("credits") or {}).items():
                tenants.setdefault(name, _Tenant()).credit = float(c)
            for name, w in (meta.get("ewma") or {}).items():
                t = tenants.get(name)
                if t is not None:
                    t.novelty_ewma = float(w)
            for name, s in (meta.get("stalled") or {}).items():
                t = tenants.get(name)
                if t is not None:
                    t.stalled = bool(s)
        elif kind == "hub_connect":
            if hub is None:
                hub = {"next_seq": 1}
            m = hub_mgrs.setdefault(meta.get("name") or "manager",
                                    _HubMgr())
            # Un-acked replies died with the old session; the fresh
            # lease starts from the cursor the hub persisted.
            m.settle(1 << 62, 0)
            m.last_seq = int(meta.get("last_seq") or 0)
        elif kind == "hub_issue":
            m = hub_mgrs.setdefault(meta.get("name") or "manager",
                                    _HubMgr())
            lens = meta.get("repro_lens") or []
            payloads, off = [], 0
            for ln in lens:
                payloads.append(bytes(blob[off:off + ln]))
                off += ln
            # The issued repros left the pending queue at issue time.
            del m.pending[:len(payloads)]
            m.inflight.append([int(meta.get("rseq") or 0),
                               int(meta.get("start") or 0),
                               int(meta.get("end") or 0), payloads])
            m.last_seq = max(m.last_seq, int(meta.get("end") or 0))
        elif kind == "hub_settle":
            m = hub_mgrs.setdefault(meta.get("name") or "manager",
                                    _HubMgr())
            m.settle(int(meta.get("seq") or 0),
                     int(meta.get("ack_seq") or 0))
        elif kind == "hub_reap":
            m = hub_mgrs.get(meta.get("name") or "manager")
            if m is not None:
                m.settle(1 << 62, 0)
        elif kind == "hub_repro":
            m = hub_mgrs.setdefault(meta.get("to") or "manager",
                                    _HubMgr())
            lens = meta.get("lens") or []
            off = 0
            for ln in lens:
                m.pending.append(bytes(blob[off:off + ln]))
                off += ln
            m.seen.update(meta.get("hashes") or [])
        elif kind == "cov":
            if coverage is None:
                coverage = {"ring": []}
            coverage.setdefault("ring", []).append(
                [float(meta.get("ts") or 0.0),
                 int(meta.get("occ") or 0),
                 int(meta.get("delta") or 0)])
            coverage["ewma_rate"] = float(meta.get("ewma") or 0.0)
            coverage["novel_total"] = int(
                meta.get("total") or coverage.get("novel_total") or 0)
            coverage["occupancy"] = int(meta.get("occ") or 0)
        # unknown kinds: skipped (see module doc)

    # -- collapse custody (the restart re-Connect does this anyway) --------
    if control is not None:
        for f in fuzzers.values():
            for _bseq, batch in f.inflight:
                control["queue"].extend(batch)
            control["queue"].extend(f.owned)
        out["control"] = control
    if serve is not None or tenants:
        serve = serve or {"rid": 0}
        serve["tenants"] = {}
        for name, t in tenants.items():
            t.settle(1 << 62, 0)  # inflight -> queue front
            serve["tenants"][name] = {
                "pending": t.pending,
                "credit": t.credit,
                "novelty_ewma": t.novelty_ewma,
                "stalled": t.stalled,
                "rows_spent": t.rows_spent,
                "delivered": t.delivered,
            }
        out["serve"] = serve
    if mirror is not None:
        out["signal_mirror"] = mirror
    if mutant is not None:
        out["mutant_plane"] = mutant
    if tplanes:
        out["tenant_planes"] = {"bits": tp_bits, "planes": tplanes,
                                "epochs": tp_epochs}
    if hub is not None or hub_mgrs:
        hub = hub or {"next_seq": 1}
        hub["managers"] = {}
        for name, m in hub_mgrs.items():
            m.settle(1 << 62, 0)  # collapse: un-acked -> redeliver
            hub["managers"][name] = {
                "last_seq": m.last_seq,
                "pending_repros": m.pending,
                "seen": sorted(m.seen),
            }
        out["hub"] = hub
    if coverage is not None:
        out["coverage"] = coverage
    if accounting is not None:
        out["accounting"] = accounting
    if slo is not None:
        out["slo"] = slo
    if arena_sec is not None:
        out["corpus_arena"] = arena_sec
    return out


def _empty_control() -> dict:
    return {"queue": [], "corpus": {}, "corpus_signal": Signal(),
            "max_signal": Signal(), "cover": set(), "triaged": 0}
