"""DurableStore: checkpoint cadence + journal fan-in + recovery.

One store per manager workdir (`<workdir>/durable/`): `state.ckpt` is
the atomic image, `state.wal` the journal since that image.  The
subsystems (ManagerRPC, ServePlane, TenantPlanes, TriageEngine,
CoverageTracker) hold a reference and write through `journal()`;
checkpoint providers are registered as callables returning
`(meta_dict, blob_bytes)` per section.

Locking: `barrier()` (an RLock) is the OUTERMOST lock in the process.
Ledger mutations (manager custody, serve delivery) acquire it around
their domain lock + journal so a checkpoint can never land between a
mutation and its journal record — the non-idempotent transitions are
exactly-once across the snapshot boundary.  Plane/coverage records
journal OUTSIDE their domain locks instead (their replays are
idempotent max/set-merges, so a rare double-apply across the boundary
is harmless); this keeps the lock order barrier -> domain -> wal
acyclic in both styles.

A failed WAL append (scripted `durable.wal_append` fault, disk error)
is swallowed and counted — losing one journal record regresses
durability to the previous record, never correctness.  A failed
checkpoint (`durable.ckpt_write` fault) leaves the WAL un-reset, so
the previous image + journal stay authoritative.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from syzkaller_tpu import telemetry
from syzkaller_tpu.durable import recovery as _recovery
from syzkaller_tpu.durable.checkpoint import (CheckpointError,
                                              read_checkpoint,
                                              write_checkpoint)
from syzkaller_tpu.durable.wal import (WriteAheadLog, _M_ERRORS,
                                       read_wal)
from syzkaller_tpu.health.envsafe import env_float, env_int
from syzkaller_tpu.utils import log

#: Recovery outcomes for tz_durable_recovery_state.
RECOVERY_NONE = 0  # cold start: no image, no journal
RECOVERY_WARM = 1  # image and/or journal replayed
RECOVERY_FAILED = 2  # corrupt image quarantined; degraded/cold start

_M_RECOVERIES = telemetry.counter(
    "tz_durable_recoveries_total",
    "warm recoveries completed (checkpoint and/or WAL replayed)")
_G_RECOVERY = telemetry.gauge(
    "tz_durable_recovery_state",
    "last recovery outcome (0 cold/none, 1 warm, 2 corrupt image -> "
    "degraded)")


class RecoveredState(dict):
    """The recovery.replay() output: a dict of per-subsystem state
    ("control", "serve", "signal_mirror", "mutant_plane",
    "tenant_planes", "coverage") plus bookkeeping keys."""

    def summary(self) -> str:
        parts = []
        c = self.get("control")
        if c is not None:
            parts.append(f"corpus={len(c['corpus'])} "
                         f"queue={len(c['queue'])}")
        if "signal_mirror" in self:
            parts.append("signal_plane")
        if "mutant_plane" in self:
            parts.append("mutant_plane")
        if "tenant_planes" in self:
            parts.append(
                f"tenant_planes={len(self['tenant_planes']['planes'])}")
        s = self.get("serve")
        if s is not None:
            parts.append(f"tenants={len(s.get('tenants') or {})}")
        if "coverage" in self:
            parts.append("coverage")
        parts.append(f"wal_records={self.get('wal_records', 0)}")
        return " ".join(parts)


class DurableStore:
    """See module doc.  Construct directly (tests) or via open()
    (honors the TZ_CKPT_* knobs and returns None when disabled)."""

    def __init__(self, dirpath: str,
                 interval_s: Optional[float] = None,
                 wal_fsync: Optional[bool] = None,
                 wal_cap_mb: Optional[float] = None,
                 clock: Callable[[], float] = time.time):
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self.ckpt_path = os.path.join(dirpath, "state.ckpt")
        self.wal_path = os.path.join(dirpath, "state.wal")
        self.interval_s = env_float("TZ_CKPT_INTERVAL_S", 60.0) \
            if interval_s is None else float(interval_s)
        self.wal_cap_bytes = int(max(1.0, (
            env_float("TZ_CKPT_WAL_MAX_MB", 64.0)
            if wal_cap_mb is None else float(wal_cap_mb))) * (1 << 20))
        fsync = bool(env_int("TZ_CKPT_WAL_FSYNC", 1)) \
            if wal_fsync is None else bool(wal_fsync)
        self._clock = clock
        #: The process-wide journal barrier (see module doc): public —
        #: ledger owners wrap mutation+journal in `with store.barrier:`.
        self.barrier = threading.RLock()
        self._providers: dict[str, Callable[[], tuple]] = {}
        self._ckpt_due = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_ckpt_ts = 0.0
        self.last_ckpt_error: Optional[str] = None
        self.ckpts_written = 0
        self.wal_errors = 0
        self.recovered: Optional[RecoveredState] = None
        self.recovery_state = RECOVERY_NONE
        self.closed = False
        self._recover()
        self.wal = WriteAheadLog(self.wal_path, fsync=fsync)

    # -- construction ------------------------------------------------------

    @classmethod
    def open(cls, workdir: str, **kw) -> Optional["DurableStore"]:
        """The manager entry point: `<workdir>/durable/`, disabled
        entirely by TZ_CKPT_INTERVAL_S=0 (returns None)."""
        interval = kw.pop("interval_s", None)
        if interval is None:
            interval = env_float("TZ_CKPT_INTERVAL_S", 60.0)
        if interval <= 0:
            return None
        return cls(os.path.join(workdir, "durable"),
                   interval_s=interval, **kw)

    # -- recovery ----------------------------------------------------------

    def _recover(self) -> None:
        # A crash between the image fsync and the rename leaves a
        # stale tmp that would otherwise sit forever.
        tmp = self.ckpt_path + ".tmp"
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
                log.logf(0, "durable: removed stale %s", tmp)
            except OSError:
                pass
        ckpt: dict = {}
        failed = False
        if os.path.exists(self.ckpt_path):
            try:
                ckpt = read_checkpoint(self.ckpt_path)
            except CheckpointError as e:
                failed = True
                quarantine = self.ckpt_path + ".corrupt"
                try:
                    os.replace(self.ckpt_path, quarantine)
                except OSError:
                    quarantine = "<unlinkable>"
                log.logf(0, "durable: corrupt checkpoint (%s); "
                         "quarantined to %s", e, quarantine)
                telemetry.FLIGHT.dump(
                    "durable_recovery_degraded",
                    f"corrupt checkpoint: {e}",
                    extra={"quarantined": quarantine})
        records = read_wal(self.wal_path)
        if not ckpt and not records:
            self.recovery_state = \
                RECOVERY_FAILED if failed else RECOVERY_NONE
            _G_RECOVERY.set(self.recovery_state)
            return
        with telemetry.span("durable.wal_replay"):
            state = RecoveredState(_recovery.replay(ckpt, records))
        self.recovered = state
        self.recovery_state = RECOVERY_FAILED if failed \
            else RECOVERY_WARM
        _G_RECOVERY.set(self.recovery_state)
        _M_RECOVERIES.inc()
        telemetry.record_event("durable.recover", state.summary())
        log.logf(0, "durable: warm recovery (%s)%s", state.summary(),
                 " [image was corrupt; WAL-only]" if failed else "")

    # -- journal -----------------------------------------------------------

    def journal(self, kind: str, meta: Optional[dict] = None,
                blob: bytes = b"") -> None:
        """Append one record; never raises (a lost record costs
        durability back to the previous record, not correctness)."""
        with self.barrier:
            if self.closed:
                # A holder journaling after close (e.g. an analytics
                # tick racing shutdown) is a no-op, not an error.
                return
            try:
                self.wal.append(kind, meta, blob)
            except (OSError, ConnectionError, ValueError) as e:
                self.wal_errors += 1
                _M_ERRORS.inc()
                telemetry.record_event(
                    "durable.wal_error", f"{kind}: {e}")
                return
            if self.wal.bytes_since_ckpt >= self.wal_cap_bytes:
                self._ckpt_due.set()

    # -- checkpointing -----------------------------------------------------

    def register(self, name: str,
                 provider: Callable[[], tuple]) -> None:
        """Register a section provider: () -> (meta_dict, blob)."""
        self._providers[name] = provider

    def checkpoint_now(self) -> bool:
        """Snapshot every provider and publish atomically; reset the
        WAL only on success.  Returns True when the image published."""
        with self.barrier:
            sections = {}
            for name, provider in self._providers.items():
                try:
                    meta, blob = provider()
                except Exception as e:
                    # One broken provider must not block the rest of
                    # the image (a missing section degrades to colder
                    # recovery for that subsystem only).
                    log.logf(0, "durable: provider %s failed: %s",
                             name, e)
                    continue
                sections[name] = (meta, blob)
            ts = self._clock()
            try:
                with telemetry.span("durable.ckpt_write"):
                    size = write_checkpoint(
                        self.ckpt_path, sections, ts)
            except (OSError, ConnectionError) as e:
                self.last_ckpt_error = str(e)
                telemetry.record_event("durable.ckpt_error", str(e))
                log.logf(0, "durable: checkpoint failed: %s", e)
                return False
            self.wal.reset()
            self.last_ckpt_ts = ts
            self.last_ckpt_error = None
            self.ckpts_written += 1
            telemetry.record_event(
                "durable.ckpt",
                f"{len(sections)} sections, {size} bytes")
        return True

    # -- cadence -----------------------------------------------------------

    def start(self) -> None:
        """Begin the checkpoint cadence (TZ_CKPT_INTERVAL_S), with
        early wakeups when the WAL passes TZ_CKPT_WAL_MAX_MB."""
        if self._thread is not None or self.interval_s <= 0:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="tz-durable-ckpt")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._ckpt_due.wait(self.interval_s)
            if self._stop.is_set():
                return
            self._ckpt_due.clear()
            try:
                self.checkpoint_now()
            except Exception as e:  # the cadence survives anything
                log.logf(0, "durable: cadence checkpoint error: %s", e)

    def stop(self) -> None:
        self._stop.set()
        self._ckpt_due.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def close(self, final_checkpoint: bool = True) -> None:
        """Clean shutdown: stop the cadence, publish a final image
        (making the next start an exact warm restart), release the
        WAL handle."""
        self.stop()
        if final_checkpoint:
            try:
                self.checkpoint_now()
            except Exception as e:
                log.logf(0, "durable: final checkpoint failed: %s", e)
        with self.barrier:
            self.closed = True
            self.wal.close()

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """The /api + bench rollup block."""
        return {
            "dir": self.dir,
            "interval_s": self.interval_s,
            "checkpoints": self.ckpts_written,
            "last_ckpt_ts": round(self.last_ckpt_ts, 3),
            "last_ckpt_age_s": round(
                self._clock() - self.last_ckpt_ts, 1)
            if self.last_ckpt_ts else None,
            "last_ckpt_error": self.last_ckpt_error,
            "wal_bytes": self.wal.bytes_since_ckpt,
            "wal_records": self.wal.records_appended,
            "wal_errors": self.wal_errors,
            "recovery_state": self.recovery_state,
            "recovered": self.recovered.summary()
            if self.recovered is not None else None,
        }
