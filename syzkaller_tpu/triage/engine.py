"""TriageEngine: the device signal plane as the production novelty path.

The reference runs the per-call novelty test as a Go map walk under
one mutex (pkg/signal/signal.go:90-102 via syz-fuzzer/fuzzer.go:494);
this repo's CPU shape was the same — every proc serialized behind
`Fuzzer._lock` doing Python dict diffs (`Signal.diff_raw`) for every
executed call, even though >99.9% of calls carry nothing new.  The
jitted dense-plane kernels in ops/signal.py (diff_batch / merge) were
until now used only by the experimental mesh step.

This engine makes them the hot path:

  - procs submit raw per-call signal arrays (CallInfo.signal) into a
    cross-proc staging buffer; whoever reaches the device lock first
    becomes the flush leader and ships the whole staged batch H2D as
    ONE padded (B, E) static-shape novel_any call (diff_batch's
    predicate without the sort-based dedup — the flag is identical,
    the sort was the dominant cost) — batching across procs amortizes
    the H2D sync and the dispatch, and the shapes are pinned
    (B = TZ_TRIAGE_BATCH, E = TZ_TRIAGE_MAX_EDGES) so nothing ever
    re-jits,
  - the flush leader stages rows through the shared transfer plane
    (ops/staging): padded batches are written IN PLACE into
    persistent pow2-bucketed arena slots (no per-flush allocation or
    re-pad), and up to TZ_TRIAGE_DISPATCH_DEPTH uploads fly ahead of
    the oldest batch's verdict fetch, so batch k's H2D overlaps batch
    k-1's in-flight novel_any — the triage twin of the pipeline's
    dispatch_depth.  Verdicts resolve in strict dispatch order; depth
    1 is the serial fallback, and the effective depth demotes to 1
    whenever the breaker is not closed,
  - calls the plane flags as possibly-novel (and calls whose signal
    exceeds the E budget) fall through to the exact CPU Signal diff
    under the fuzzer lock — max_signal/new_signal bookkeeping and
    triage-work enqueue are bit-identical to the pure-CPU path; the
    common "nothing new" verdict never touches the Python sets or the
    lock,
  - confirmed diffs and manager-distributed max-signal merges
    (Fuzzer.add_max_signal) scatter into a host MIRROR of the plane
    immediately and into the device plane lazily (ops/signal.merge at
    the same (B, E) shape) at the next flush.  The mirror is the
    rebuild authority: the device plane is invalidated on any device
    failure and on every breaker half-open re-entry (the pipeline's
    host-snapshot rebuild covers the co-resident plane), and is
    re-uploaded from the mirror in one transfer,
  - breaker/watchdog semantics mirror the pipeline worker's: an open
    breaker demotes triage to the CPU path instantly (symmetric with
    PipelineMutator's fast-demote; the plane mirror keeps absorbing
    confirmed signal while demoted, so re-promotion carries no
    hit-rate regression), device calls run under the watchdog and the
    `device.triage` fault seam, and a device failure confirms the
    whole staged chunk on CPU — zero lost signal by construction.

The one approximation is the fold: the plane stores 2^FOLD_BITS
buckets of (max seen prio + 1), so a truly-novel 32-bit edge whose
fold collides with an occupied bucket is filtered without a CPU
confirm (a false negative).  Its probability is bounded by the plane
occupancy fraction, tracked incrementally and exported as
`tz_triage_fold_false_negative_rate`; at 2^26 buckets a 1M-edge
max_signal costs ~1.5%.  `TZ_TRIAGE_DEVICE=0` is the kill switch back
to today's pure-CPU path (docs/perf.md "The triage path").
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

import numpy as np

from syzkaller_tpu import telemetry
from syzkaller_tpu.telemetry import lineage
from syzkaller_tpu.health import (
    CircuitBreaker,
    Watchdog,
    env_float,
    env_int,
    fault_point,
    warn_unknown_tz_vars,
)
from syzkaller_tpu.health.breaker import CLOSED
from syzkaller_tpu.ops import signal as dsig
from syzkaller_tpu.ops.delta import pow2_rows
from syzkaller_tpu.ops.staging import StagingArena, note_dispatch_depth
from syzkaller_tpu.utils import log

# Triage-path telemetry (docs/observability.md): counts at each fork
# of the decision tree plus the plane-health gauges.  Span latencies
# come from span() contexts at the call sites (triage.device wraps
# one padded batch end to end, triage.confirm the exact CPU diff).
_M_CALLS = telemetry.counter(
    "tz_triage_calls_total", "calls checked through the triage engine")
_M_BATCHES = telemetry.counter(
    "tz_triage_batches_total", "device pre-filter batches flushed")
_M_HITS = telemetry.counter(
    "tz_triage_plane_hits_total",
    "calls the plane flagged possibly-novel (CPU confirm)")
_M_MISSES = telemetry.counter(
    "tz_triage_plane_misses_total",
    "calls the plane filtered as nothing-new (fast path)")
_M_OVERFLOWS = telemetry.counter(
    "tz_triage_overflow_calls_total",
    "calls over the per-call edge budget (confirmed on CPU directly)")
_M_CPU_FALLBACK = telemetry.counter(
    "tz_triage_cpu_fallback_calls_total",
    "calls checked on the CPU path while demoted")
_M_ERRORS = telemetry.counter(
    "tz_triage_device_errors_total",
    "device failures on the triage call (chunk confirmed on CPU)")
_M_DEMOTIONS = telemetry.counter(
    "tz_triage_demotions_total", "device->CPU triage demotions")
_M_REPROMOTIONS = telemetry.counter(
    "tz_triage_repromotions_total", "CPU->device triage re-promotions")
_M_REBUILDS = telemetry.counter(
    "tz_triage_plane_rebuilds_total",
    "device plane re-uploads from the host mirror")
_M_H2D_OVERLAPS = telemetry.counter(
    "tz_triage_h2d_overlap_total",
    "batches whose H2D upload was dispatched while a previous "
    "batch's verdict fetch was still in flight")
_M_STALE_SLOTS = telemetry.counter(
    "tz_triage_stale_slots_total",
    "in-flight staged batches invalidated by a plane rebuild "
    "(whole chunk confirmed on CPU; zero lost signal)")
_M_BATCH_SIZE = telemetry.gauge(
    "tz_triage_batch_size", "calls in the most recent device batch")
_M_OCCUPANCY = telemetry.gauge(
    "tz_triage_plane_occupancy",
    "occupied plane buckets (exact popcount at flush cadence)")
_M_FN_RATE = telemetry.gauge(
    "tz_triage_fold_false_negative_rate",
    "estimated probability a novel edge is filtered by a fold "
    "collision (= plane occupancy fraction)")


@dataclass
class TriageStats:
    calls: int = 0  # calls entering check()
    device_batches: int = 0  # padded batches flushed to the device
    plane_hits: int = 0  # flagged possibly-novel -> CPU confirm
    plane_misses: int = 0  # filtered nothing-new (no lock, no dicts)
    overflow_calls: int = 0  # signal over the E budget -> CPU confirm
    cpu_fallback_calls: int = 0  # checked on CPU while demoted
    device_errors: int = 0  # failures on the triage device call
    demotions: int = 0  # device->CPU transitions
    repromotions: int = 0  # CPU->device transitions
    plane_rebuilds: int = 0  # mirror re-uploads
    h2d_overlaps: int = 0  # uploads dispatched over an in-flight fetch
    stale_slots: int = 0  # in-flight batches invalidated by a rebuild


class _Request:
    """One proc's check() worth of staged queries: a single completion
    event + countdown shared by its entries (per-entry Events were a
    measurable slice of the batch at 64 calls/program).  Only the
    current flush leader decrements `pending` (the device lock
    serializes leaders), so the countdown needs no lock of its own."""

    __slots__ = ("pending", "done")

    def __init__(self, n: int):
        self.pending = n
        self.done = threading.Event()


class _Entry:
    """One staged per-call novelty query."""

    __slots__ = ("edges", "prio", "flagged", "req", "lane")

    def __init__(self, edges: np.ndarray, prio: int, req: _Request,
                 lane: str = "exploration"):
        self.edges = edges
        self.prio = prio
        self.flagged = True  # conservative until the plane answers
        self.req = req
        self.lane = lane  # workqueue lane for the accounting ledger


class TriageEngine:
    """Shared by every proc of one fuzzer process; see module doc.

    Constructor knobs are overridable by env (health.envsafe — a
    malformed value falls back to the argument, never kills startup):
    TZ_TRIAGE_BATCH (calls per padded device batch), TZ_TRIAGE_MAX_EDGES
    (per-call edge budget; larger signals confirm on CPU directly),
    TZ_TRIAGE_FLUSH_S (leader linger to gather a fuller batch; 0 =
    flush immediately), TZ_TRIAGE_DISPATCH_DEPTH (staged H2D uploads
    kept in flight ahead of the verdict fetch; 1 = serial, the
    fallback/kill path).  TZ_TRIAGE_DEVICE=0 disables construction
    entirely (fuzzer/main.py)."""

    def __init__(self, batch: int = 256, max_edges: int = 512,
                 flush_s: float = 0.0, dispatch_depth: int = 2,
                 breaker: Optional[CircuitBreaker] = None,
                 watchdog: Optional[Watchdog] = None,
                 owns_breaker: Optional[bool] = None):
        self.B = max(1, env_int("TZ_TRIAGE_BATCH", batch))
        self.E = max(8, env_int("TZ_TRIAGE_MAX_EDGES", max_edges))
        self.flush_s = max(0.0, env_float("TZ_TRIAGE_FLUSH_S", flush_s))
        # Transfer plane (ops/staging, docs/perf.md "The transfer
        # plane"): batch k's padded rows are written into a persistent
        # pow2-bucketed arena slot and uploaded while batch k-1's
        # novel_any verdicts are still in flight — the triage twin of
        # the pipeline's dispatch_depth.  Depth 1 reproduces the
        # serial flush (pad -> H2D -> verdict per chunk).  Slot count
        # = depth, so a slot is never rewritten before its batch's
        # verdicts resolved.
        self._dispatch_depth = max(1, env_int(
            "TZ_TRIAGE_DISPATCH_DEPTH", dispatch_depth))
        self._arena = StagingArena(slots=self._dispatch_depth)
        self._cols = np.arange(self.E, dtype=np.int32)
        self._epoch = 0  # bumped by invalidate: stales in-flight slots
        self._dispatch_seq = 0  # strict-FIFO verdict delivery order
        self._resolve_seq = 0
        note_dispatch_depth(self._dispatch_depth)
        # Coverage intelligence cadence (ISSUE 7, telemetry/coverage):
        # the exact occupancy popcount + region heat map run every
        # analytics interval, the device-vs-mirror drift audit every
        # audit interval — per flush interval, never per batch, and
        # the kernels compile exactly once (pinned plane shape).
        self._analytics_interval = max(0.0, env_float(
            "TZ_COVERAGE_INTERVAL_S", 5.0))
        self._audit_interval = max(0.0, env_float(
            "TZ_COVERAGE_AUDIT_S", 60.0))
        now = time.monotonic()
        self._last_analytics = now
        self._last_audit = now
        self._analytics_compiled = False
        warn_unknown_tz_vars()
        # Standalone engines own their breaker and drive the full
        # closed->open->half-open->closed protocol themselves; an
        # engine sharing a pipeline's breaker (for_pipeline) only
        # READS it — the pipeline worker owns probing, and triage
        # stays on CPU until the worker re-closes it.
        self.owns_breaker = (breaker is None) if owns_breaker is None \
            else owns_breaker
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            failure_threshold=max(1, env_int("TZ_BREAKER_THRESHOLD", 4)),
            backoff_initial=env_float("TZ_BREAKER_BACKOFF_S", 1.0),
            backoff_cap=env_float("TZ_BREAKER_BACKOFF_CAP_S", 60.0))
        # 30 s default (was 120 s): >30x the worst measured batch on
        # every backend, so a wedge is declared 4x sooner without any
        # false-positive margin lost — rationale in docs/health.md
        # "Watchdog deadlines"; the knob restores any value.
        self.watchdog = watchdog if watchdog is not None else Watchdog(
            deadline_s=env_float("TZ_WATCHDOG_DEADLINE_S", 30.0),
            compile_deadline_s=env_float("TZ_WATCHDOG_COMPILE_S", 600.0))
        self.stats = TriageStats()
        # The host mirror is the plane's rebuild authority: uint8
        # buckets of (max seen prio + 1), identical layout to the
        # device plane.  Occupancy is maintained incrementally (a full
        # count over 2^26 buckets per merge would dwarf the merge).
        self._mirror = np.zeros(dsig.PLANE_SIZE, dtype=np.uint8)
        self._occupancy = 0
        self._plane_dev = None  # device plane; None = rebuild pending
        # Device-residency ledger (ISSUE 17): the 64 MB signal plane
        # and its host-mirror rebuild authority, owner="triage".  The
        # plane handle follows every rebuild/invalidation so the
        # reconcile pass (which rides the audit cadence below) always
        # checks the LIVE buffer.
        self._hbm_plane = telemetry.HBM.register(
            "triage", "plane", bound_to=self)
        self._hbm_mirror = telemetry.HBM.register(
            "triage", "mirror", self._mirror, bound_to=self)
        self._compiled = False  # first diff carries the jit compile
        self._pending: list[tuple[np.ndarray, int]] = []  # merge backlog
        self._staged: list[_Entry] = []
        self._stage_lock = threading.Lock()
        self._merge_lock = threading.Lock()
        self._device_lock = threading.Lock()  # flush-leader mutex
        self._demoted = False
        # Serving plane (serve/plane.py): when attached, per-tenant
        # novelty-plane occupancy/FN-rate rides the analytics rollup.
        self._tenant_planes = None
        # Speculative prescore (syzkaller_tpu/sim): when attached,
        # snapshot() carries the prescore verdict-path state so the
        # triage surface shows what the filter upstream of it did.
        self._sim_prescore = None
        self._hint_lane = None
        # Durability (syzkaller_tpu/durable): when attached, merges
        # journal their folded indices and the mirror becomes a
        # checkpoint section (durable_provider / restore_mirror).
        self.durable = None

    @classmethod
    def for_pipeline(cls, pipeline, **kw) -> "TriageEngine":
        """Co-resident form: shares the DevicePipeline's breaker and
        watchdog (one health verdict for the device) and registers for
        plane invalidation on the pipeline's half-open ring rebuild."""
        eng = cls(breaker=pipeline.breaker, watchdog=pipeline.watchdog,
                  owns_breaker=False, **kw)
        pipeline.attach_triage(eng)
        return eng

    # -- plane maintenance -------------------------------------------------

    def attach(self, fuzzer) -> None:
        """Seed the mirror from the fuzzer's current max_signal (the
        manager's Connect payload lands before the engine exists)."""
        with fuzzer._lock:
            sig = fuzzer.max_signal.copy()
        self.merge_signal(sig)

    def merge_signal(self, sig) -> None:
        """Fold a Signal into the plane: mirror now, device at the
        next flush.  Callers guarantee sig is already merged into
        max_signal — the plane must under-approximate max_signal
        (staleness only costs extra CPU confirms), never exceed it."""
        if sig.empty():
            return
        by_prio: dict[int, list[int]] = {}
        for e, p in sig.m.items():
            by_prio.setdefault(int(p), []).append(int(e))
        for prio, elems in by_prio.items():
            self._merge_edges(np.asarray(elems, dtype=np.uint32), prio)

    def _merge_edges(self, edges: np.ndarray, prio: int) -> None:
        # Occupancy is NOT maintained incrementally here any more
        # (ISSUE 7 satellite): the per-merge np.unique accumulation
        # could drift from the mirror between rebuilds (absorb_plane,
        # double-merged diffs).  The exact popcount at flush cadence
        # (_run_analytics_locked) is now the only occupancy source.
        with self._merge_lock:
            idx = dsig.fold_hash_np(edges)
            np.maximum.at(self._mirror, idx, np.uint8(prio + 1))
            self._pending.append((edges, prio))
        if self.durable is not None:
            # Journaled AFTER the mutation and OUTSIDE the merge lock
            # (lock order: barrier -> domain; replay is an idempotent
            # max-merge, so a checkpoint racing this append at worst
            # double-applies the indices harmlessly).  The folded
            # indices — not the raw edges — keep replay jax-free.
            self.durable.journal(
                "merge",
                {"prio": int(prio), "size": int(self._mirror.size)},
                idx.astype(np.uint32).tobytes())

    def invalidate_device_plane(self) -> None:
        """Drop the device plane; the next flush re-uploads the host
        mirror.  Called on device failures and by the pipeline's
        half-open ring rebuild (plane co-residency: a restarted
        backend invalidated this buffer too).  The epoch bump stales
        every in-flight staged slot the same way: a batch uploaded
        against the dead plane resolves as a full CPU confirm instead
        of trusting verdicts from invalidated buffers."""
        self._plane_dev = None
        self._epoch += 1
        self._hbm_plane.update(None)

    def _bucket(self, n: int) -> int:
        """Pow2 row-count bucket in [8, B]: small submissions ship
        small transfers (the tunneled link charges per byte) while
        the distinct compiled shapes stay bounded at log2(B/8)+1."""
        return pow2_rows(n, lo=min(8, self.B), hi=self.B)

    def _ensure_plane_locked(self):
        """Device plane ready for a diff (holds _device_lock): rebuild
        from the mirror if invalidated, else apply the merge backlog
        through the jitted scatter at bucketed (rows, E) shapes."""
        import jax.numpy as jnp

        if self._plane_dev is None:
            # One 64 MB H2D replaces the backlog entirely (the mirror
            # already holds every pending merge).  Held under the
            # merge lock so a concurrent merge cannot land in the
            # mirror after the snapshot but vanish from the backlog.
            with self._merge_lock:
                self._pending.clear()
                self._plane_dev = jnp.asarray(self._mirror)
            self._hbm_plane.update(self._plane_dev)
            self.stats.plane_rebuilds += 1
            _M_REBUILDS.inc()
            return
        with self._merge_lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        rows: list[tuple[np.ndarray, int]] = []
        for edges, prio in pending:
            for i in range(0, edges.size, self.E):
                rows.append((edges[i:i + self.E], prio))
        for start in range(0, len(rows), self.B):
            chunk = rows[start:start + self.B]
            b = self._bucket(len(chunk))
            e = np.zeros((b, self.E), dtype=np.uint32)
            n = np.zeros(b, dtype=np.int32)
            pr = np.zeros(b, dtype=np.uint8)
            for i, (edges, prio) in enumerate(chunk):
                e[i, :edges.size] = edges
                n[i] = edges.size
                pr[i] = prio
            # Donated: the scatter lands in place — a non-donating
            # merge copied the 64 MB plane per application.
            self._plane_dev = dsig.merge_into(
                self._plane_dev, jnp.asarray(e), jnp.asarray(n),
                jnp.asarray(pr), jnp.ones(b, dtype=bool))
        # The donated merges reassigned the plane reference: re-point
        # the ledger entry at the live buffer (reconcile identity).
        self._hbm_plane.update(self._plane_dev)

    # -- plane sharing (parallel/mesh.py) ----------------------------------

    def share_plane(self):
        """The device plane, current as of every absorbed merge, for
        co-use by the mesh fuzz step (parallel/mesh.shard_engine_plane)
        — one 64 MB plane per process instead of one per consumer.
        The engine's donated merges reassign its own reference, so
        consumers must re-share after letting the engine run."""
        with self._device_lock:
            self._ensure_plane_locked()
            return self._plane_dev

    def mirror_copy(self) -> np.ndarray:
        """Copy of the host-mirror rebuild authority.  The fault-domain
        mesh engine (parallel/fault_domain.MeshEngine) seeds its own
        re-shard source from this, so a chip-loss re-shard rebuilds
        from exactly the signal this engine has accepted."""
        with self._merge_lock:
            return self._mirror.copy()

    def durable_provider(self) -> tuple:
        """Checkpoint section for the signal plane: the host mirror,
        zlib-packed (DurableStore.register("signal_plane", ...))."""
        from syzkaller_tpu.durable.checkpoint import pack_section

        with self._merge_lock:
            blob = pack_section(self._mirror)
            size = self._mirror.size
        return {"size": int(size)}, blob

    def restore_mirror(self, mirror) -> None:
        """Install a recovered host mirror as the rebuild authority.
        The device plane is dropped, NOT uploaded here: the next flush
        re-uploads through the existing _ensure_plane_locked rebuild
        (one H2D via the same jnp.asarray path — zero new jit
        compiles, the property the warm-rig guard pins), and the epoch
        bump stales any in-flight staged slot exactly like
        invalidate_device_plane."""
        arr = np.asarray(mirror, dtype=np.uint8)
        if arr.size != self._mirror.size:
            raise ValueError(
                f"recovered mirror has {arr.size} buckets; this "
                f"engine's plane is {self._mirror.size}")
        with self._device_lock, self._merge_lock:
            self._mirror = arr.copy()
            self._note_occupancy(int(np.count_nonzero(self._mirror)))
            self._pending.clear()
            self._plane_dev = None
            self._epoch += 1
            self._hbm_mirror.update(self._mirror)
            self._hbm_plane.update(None)

    def share_plane_sharded(self, mesh):
        """The rebuild authority uploaded cov-sharded over a mesh —
        the shard-aware form of the PR 4 host-mirror rebuild path.
        Unlike share_plane() this always uploads fresh from the
        mirror (the caller is re-sharding after a topology change, so
        any cached single-device plane is the wrong layout)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        with self._merge_lock:
            mirror = self._mirror.copy()
        return jax.device_put(
            mirror, NamedSharding(mesh, PartitionSpec("cov")))

    def absorb_plane(self, plane) -> None:
        """Max-merge an externally updated plane (a mesh step's
        output) back into the mirror.  Only valid when the absorbed
        signal is the engine's own authority (the standalone mesh
        form); a fuzzer-attached engine must instead route external
        signal through Fuzzer.add_max_signal, or the plane would
        over-approximate max_signal and filter real novelty."""
        arr = np.asarray(plane, dtype=np.uint8)
        with self._device_lock, self._merge_lock:
            np.maximum(self._mirror, arr, out=self._mirror)
            self._note_occupancy(int(np.count_nonzero(self._mirror)))
            self._pending.clear()
            self._plane_dev = None  # rebuilt from the merged mirror
            self._hbm_plane.update(None)

    # -- coverage analytics (ISSUE 7) --------------------------------------

    def _note_occupancy(self, occ: int) -> None:
        self._occupancy = occ
        _M_OCCUPANCY.set(occ)
        _M_FN_RATE.set(occ / dsig.PLANE_SIZE)

    def _maybe_analytics_locked(self) -> None:
        """Flush-cadence gate (holds _device_lock): run the analytics
        reductions when the interval elapsed; the drift audit rides
        along at its own (longer) cadence."""
        now = time.monotonic()
        if now - self._last_analytics < self._analytics_interval:
            return
        audit = now - self._last_audit >= self._audit_interval
        self._run_analytics_locked(audit=audit)

    def _maybe_analytics_cpu(self) -> None:
        """Demoted-path cadence: the mirror still answers occupancy,
        so the growth curve keeps moving while the device is down.
        Non-blocking — skipped when a flush leader holds the lock."""
        if time.monotonic() - self._last_analytics \
                < self._analytics_interval:
            return
        if self._device_lock.acquire(blocking=False):
            try:
                self._maybe_analytics_locked()
            finally:
                self._device_lock.release()

    def attach_tenant_planes(self, planes) -> None:
        """Thread the serving plane's per-tenant novelty planes
        (serve/plane.TenantPlanes) into this engine's analytics
        rollup: run_analytics() and snapshot() gain a "tenants" key
        with per-tenant {occupancy, fn_rate, epoch} — the multi-
        tenant extension of the PR 7 coverage accounting."""
        self._tenant_planes = planes

    def attach_sim(self, sim) -> None:
        """Register the pipeline's speculative prescore
        (sim/prescore.SimPrescore): snapshot() gains a "sim_prescore"
        key — suppression totals, re-admission epochs and the
        prescore breaker — so the triage surface reports the filter
        that decides which mutants ever reach its verdict path."""
        self._sim_prescore = sim

    def attach_hints(self, lane) -> None:
        """Register the batched hints lane (ops/hintlane.HintLane):
        snapshot() gains a "hint_lane" key so the triage surface
        reports the mutation source whose rows it triages alongside
        the prescore that filters them."""
        self._hint_lane = lane

    def run_analytics(self, audit: bool = False) -> dict:
        """Force one analytics pass (bench.py --coverage, tests);
        returns {occupancy, regions, drift} plus a per-tenant
        "tenants" rollup when serving-plane planes are attached."""
        with self._device_lock:
            res = self._run_analytics_locked(audit=audit)
        if self._tenant_planes is not None:
            res["tenants"] = self._tenant_planes.analytics()
        return res

    def _run_analytics_locked(self, audit: bool = False) -> dict:
        """The coverage reductions, computed where the data lives
        (holds _device_lock): exact occupancy popcount + region heat
        map on the device plane (ops/signal.coverage_stats — compiled
        once, the plane shape is pinned), and optionally the
        device-vs-mirror drift audit.  With no device plane (demoted,
        TZ_TRIAGE_DEVICE path) the mirror answers instead — same
        numbers, host cost.  A detected drift invalidates the plane so
        the next flush re-uploads the authority mirror.  Advisory:
        a failure is logged and skipped, never fed to the breaker."""
        self._last_analytics = time.monotonic()
        drift = None
        try:
            with telemetry.span("coverage.analytics"):
                if self._plane_dev is not None:
                    self._ensure_plane_locked()  # backlog → plane
                    plane = self._plane_dev

                    def _fetch():
                        # Blocking value reads INSIDE the guard: the
                        # int()/asarray sync is where a wedged
                        # backend would hang.
                        o, r = dsig.coverage_stats(plane)
                        return int(o), np.asarray(r)

                    with telemetry.COMPILES.observe(
                            "triage.analytics",
                            {"plane_bits": dsig.FOLD_BITS},
                            sizer=dsig.analytics_cache_size):
                        occ, regions = self.watchdog.call(
                            _fetch, "device.coverage",
                            compile=not self._analytics_compiled)
                    self._analytics_compiled = True
                    if audit:
                        drift = self._audit_locked(plane)
                else:
                    folded = self._mirror.reshape(
                        dsig.COVERAGE_REGIONS, -1)
                    regions = np.count_nonzero(folded, axis=1)
                    occ = int(regions.sum())
                    if audit:
                        drift = 0  # nothing co-resident to drift
                        self._last_audit = time.monotonic()
        except Exception as e:
            log.logf(0, "coverage analytics skipped: %s", str(e)[:200])
            return {"occupancy": self._occupancy, "regions": None,
                    "drift": None}
        if audit and telemetry.HBM.reconcile_armed():
            # Residency reconcile rides the audit cadence (ISSUE 17):
            # ledger-tracked bytes vs the backend live-buffer report.
            # Advisory like the drift audit — never raises, never
            # feeds the breaker.
            try:
                telemetry.HBM.reconcile()
            except Exception as e:
                log.logf(0, "hbm reconcile skipped: %s", str(e)[:200])
        self._note_occupancy(occ)
        telemetry.COVERAGE.sample(occ, regions, drift)
        # SLO evaluation rides the flush-leader cadence (ISSUE 14):
        # the engine rate-limits itself (TZ_SLO_INTERVAL_S) and never
        # raises, so the analytics path stays advisory.
        telemetry.SLO.tick()
        return {"occupancy": occ, "regions": regions, "drift": drift}

    def _audit_locked(self, plane) -> Optional[int]:
        """Device-vs-mirror drift audit (holds _device_lock): one
        64 MB mirror upload + xor/popcount.  Skipped while merges are
        pending (the plane legitimately lags the mirror then).  A
        nonzero count is silent plane corruption — e.g. a half-open
        ring rebuild that resurrected stale device memory — so the
        plane is dropped and rebuilt from the authority mirror."""
        import jax.numpy as jnp

        self._last_audit = time.monotonic()
        with self._merge_lock:
            if self._pending:
                return None  # mirror ahead by design; not corruption
            mirror_dev = jnp.asarray(self._mirror)
        drift = self.watchdog.call(
            lambda: int(dsig.plane_drift(plane, mirror_dev)),
            "device.coverage")
        if drift:
            telemetry.record_event(
                "coverage.drift",
                f"{drift} plane buckets disagree with the mirror; "
                "re-uploading")
            log.logf(0, "COVERAGE DRIFT: %d plane buckets disagree "
                        "with the host mirror (silent corruption); "
                        "rebuilding from the mirror", drift)
            self.invalidate_device_plane()
        return drift

    # -- the check path ----------------------------------------------------

    def check(self, fuzzer, prio_fn, infos, trace=None,
              source=None) -> list:
        """Drop-in for Fuzzer.cpu_check_new_signal: same (call_index,
        diff) list, same order, same max_signal/new_signal effects.
        `trace` is the executed mutant's lineage context: verdict
        delivery (device-filtered or CPU-confirmed) is one hop on its
        correlated track (telemetry/lineage.py).  `source` is the
        workqueue lane (fuzzer/proc.py _LANE_BY_STAT) — it rides the
        staged entries so the accounting ledger can attribute the
        novel_any device residency per lane (ISSUE 14)."""
        infos = list(infos)
        if not infos:
            return []
        self.stats.calls += len(infos)
        _M_CALLS.inc(len(infos))
        if not self._gate():
            self._note_demoted(f"circuit breaker {self.breaker.state}")
            news = self._cpu_all(fuzzer, prio_fn, infos)
            self._maybe_analytics_cpu()
            lineage.hop(trace, "triage.verdict")
            return news
        entries: dict[int, _Entry] = {}
        confirm_pos: list[int] = []
        staged: list[_Entry] = []
        req = _Request(0)
        for pos, info in enumerate(infos):
            edges = np.asarray(info.signal, dtype=np.uint32).ravel()
            if edges.size == 0:
                continue  # empty diff either way
            if edges.size > self.E:
                # Over the padded-edge budget: exact CPU diff directly
                # (rare; the budget exists to pin the device shape).
                self.stats.overflow_calls += 1
                _M_OVERFLOWS.inc()
                confirm_pos.append(pos)
                continue
            en = _Entry(edges, prio_fn(info.errno, info.call_index),
                        req, lane=source or "exploration")
            entries[pos] = en
            staged.append(en)
        if staged:
            req.pending = len(staged)
            self._flush(req, staged)
            confirm_pos.extend(pos for pos, en in entries.items()
                               if en.flagged)
        if not confirm_pos:
            lineage.hop(trace, "triage.verdict")
            return []
        confirm_pos.sort()
        with telemetry.span("triage.confirm"):
            news = fuzzer.cpu_check_new_signal(
                prio_fn, [infos[p] for p in confirm_pos])
        for _ci, diff in news:
            self.merge_signal(diff)
        lineage.hop(trace, "triage.verdict")
        return news

    def _cpu_all(self, fuzzer, prio_fn, infos) -> list:
        """The demoted path: today's exact CPU check for every call.
        Confirmed diffs still land in the mirror so re-promotion
        starts with a current plane."""
        self.stats.cpu_fallback_calls += len(infos)
        _M_CPU_FALLBACK.inc(len(infos))
        news = fuzzer.cpu_check_new_signal(prio_fn, infos)
        for _ci, diff in news:
            self.merge_signal(diff)
        return news

    def _gate(self) -> bool:
        if self.owns_breaker:
            # allow() admits the half-open probe once the backoff
            # elapses: the next staged batch IS the probe.
            return self.breaker.allow()
        return self.breaker.state == CLOSED

    # -- staging + flush ---------------------------------------------------

    def _flush(self, req: _Request, entries: list[_Entry]) -> None:
        """Stage these queries and drive flushes until they resolve.
        Whoever wins the device lock flushes EVERYTHING staged (its
        own entries and every other proc's) in padded B-sized chunks;
        losers wait on their request — the leader-follower shape that
        batches across procs without a dedicated thread."""
        with self._stage_lock:
            self._staged.extend(entries)
        while not req.done.is_set():
            if self._device_lock.acquire(timeout=0.01):
                try:
                    self._drain_staged(req)
                finally:
                    self._device_lock.release()
            else:
                req.done.wait(timeout=0.02)

    def _effective_depth(self) -> int:
        """H2D uploads kept in flight ahead of the verdict fetch.
        Demote-to-serial on anything but a closed breaker — probes
        and recovering backends fly one batch end to end, symmetric
        with the pipeline worker's probe depth and PipelineMutator's
        fast-demote."""
        depth = self._dispatch_depth \
            if self.breaker.state == CLOSED else 1
        note_dispatch_depth(depth)
        return depth

    def _drain_staged(self, req: _Request) -> None:
        """Drive staged chunks through the transfer plane (holds
        _device_lock).  Up to `_effective_depth()` chunks are staged +
        uploaded + dispatched before the oldest chunk's verdicts are
        fetched, so batch k's H2D overlaps batch k-1's in-flight
        novel_any; verdicts always resolve in strict dispatch (seq)
        order, and every chunk this leader dispatched is resolved by
        this leader before it returns."""
        inflight: deque = deque()
        try:
            while not req.done.is_set():
                if self.flush_s > 0 and not inflight:
                    deadline = time.monotonic() + self.flush_s
                    while time.monotonic() < deadline:
                        with self._stage_lock:
                            if len(self._staged) >= self.B:
                                break
                        time.sleep(min(0.001, self.flush_s))
                with self._stage_lock:
                    chunk = self._staged[:self.B]
                    del self._staged[:len(chunk)]
                if chunk:
                    while len(inflight) >= self._effective_depth():
                        self._resolve_chunk(inflight.popleft())
                    handle = self._dispatch_chunk(
                        chunk, overlapping=bool(inflight))
                    if handle is not None:
                        inflight.append(handle)
                    continue
                if inflight:
                    self._resolve_chunk(inflight.popleft())
                    continue
                return  # a previous leader resolved the rest
        finally:
            while inflight:
                self._resolve_chunk(inflight.popleft())
            # Flush-cadence coverage analytics: the leader already
            # holds the device lock and every dispatched verdict is
            # resolved — the cheapest point to read the plane.
            self._maybe_analytics_locked()

    def _dispatch_chunk(self, chunk: list[_Entry], overlapping=False):
        """Stage one padded batch into a persistent arena slot, upload
        it, and dispatch novel_any — the non-blocking half of a batch
        (XLA returns async; the verdict fetch is _resolve_chunk).  Any
        failure marks the whole chunk for exact CPU confirm — degraded
        throughput, zero lost signal — and feeds the breaker.  Returns
        an in-flight handle, or None when the chunk already resolved
        on the failure path."""
        with telemetry.span("triage.h2d_wait"):
            try:
                fault_point("device.triage")
                if self.owns_breaker and self.breaker.consume_rebuild():
                    self._plane_dev = None
                self._ensure_plane_locked()
                b = self._bucket(len(chunk))
                k = len(chunk)
                # Persistent pre-padded staging (ops/staging): rows
                # land IN PLACE in the bucket's rotating slot; stale
                # bytes beyond a row's edge count are masked by the
                # kernel's validity test, so nothing is re-zeroed and
                # nothing bucket-sized is allocated per flush.
                bufs = self._arena.acquire(b, {
                    "edges": ((b, self.E), np.uint32),
                    "nedges": ((b,), np.int32),
                    "prios": ((b,), np.uint8),
                    "mask": ((b, self.E), np.bool_),
                    "flat": ((b * self.E,), np.uint32),
                })
                edges, nedges = bufs["edges"], bufs["nedges"]
                nedges[:k] = [en.edges.size for en in chunk]
                nedges[k:] = 0
                bufs["prios"][:k] = [en.prio for en in chunk]
                # One ragged scatter instead of a per-row copy loop,
                # with the mask and the flattened payload written into
                # arena scratch instead of fresh temporaries.
                lens = nedges[:k]
                total = int(lens.sum())
                if total:
                    mask = bufs["mask"][:k]
                    np.less(self._cols[None, :], lens[:, None],
                            out=mask)
                    np.concatenate([en.edges for en in chunk],
                                   out=bufs["flat"][:total])
                    edges[:k][mask] = bufs["flat"][:total]
                plane = self._plane_dev
                fault_point("staging.h2d")
                ed, nd, pr = dsig.stage_batch(
                    edges, nedges, bufs["prios"])
                flags_dev = self.watchdog.call(
                    lambda: dsig.novel_any(plane, ed, nd, pr),
                    "device.triage", compile=not self._compiled)
                self._compiled = True
            except Exception as e:
                self._plane_dev = None  # buffers may be invalid now
                self._epoch += 1
                self.stats.device_errors += 1
                _M_ERRORS.inc()
                self.breaker.record_failure()
                log.logf(0, "triage device error (breaker %s): %s",
                         self.breaker.state, str(e)[:200])
                for en in chunk:
                    en.flagged = True  # exact CPU confirm: no loss
                    self._complete(en)
                return None
        if overlapping:
            self.stats.h2d_overlaps += 1
            _M_H2D_OVERLAPS.inc()
        seq = self._dispatch_seq
        self._dispatch_seq += 1
        return (seq, chunk, flags_dev, self._epoch)

    def _resolve_chunk(self, handle) -> None:
        """Fetch and deliver one in-flight batch's verdicts (holds
        _device_lock; strictly FIFO — the deque in _drain_staged and
        the leader-serializing device lock make seq monotonic).  A
        handle staled by a plane rebuild resolves as a full CPU
        confirm without feeding the breaker: invalidation is recovery
        bookkeeping, not a device failure."""
        seq, chunk, flags_dev, epoch = handle
        if seq != self._resolve_seq:  # pragma: no cover - invariant
            log.logf(0, "triage verdict order broke: resolving seq %d "
                        "expected %d", seq, self._resolve_seq)
        self._resolve_seq = seq + 1
        with telemetry.span("triage.device"):
            if epoch != self._epoch:
                # Rebuilt mid-flight (pipeline half-open re-entry or a
                # failed sibling batch): the verdicts were computed
                # against an invalidated plane/backend.
                self.stats.stale_slots += 1
                _M_STALE_SLOTS.inc()
                for en in chunk:
                    en.flagged = True  # exact CPU confirm: no loss
                    self._complete(en)
                return
            try:
                t_fetch = time.perf_counter()
                flags = self.watchdog.call(
                    lambda: np.asarray(flags_dev), "device.triage")
                # Always-on per-kernel attribution: the verdict fetch
                # is novel_any's sync point (telemetry/profiler.py).
                fetch_s = time.perf_counter() - t_fetch
                telemetry.PROFILER.note("novel_any", fetch_s)
                # Accounting ledger (ISSUE 14): the same residency,
                # row-weighted over the chunk's workqueue lanes.
                lanes: dict = {}
                for en in chunk:
                    lanes[en.lane] = lanes.get(en.lane, 0) + 1
                telemetry.ACCOUNTING.note_batch(fetch_s,
                                                lane_rows=lanes)
            except Exception as e:
                self._plane_dev = None
                self._epoch += 1
                self.stats.device_errors += 1
                _M_ERRORS.inc()
                self.breaker.record_failure()
                log.logf(0, "triage device error (breaker %s): %s",
                         self.breaker.state, str(e)[:200])
                for en in chunk:
                    en.flagged = True
                    self._complete(en)
                return
        if self.owns_breaker:
            self.breaker.record_success()
        self._note_promoted()
        hits = 0
        for en, flagged in zip(chunk, flags[:len(chunk)].tolist()):
            en.flagged = flagged
            hits += flagged
            self._complete(en)
        self.stats.device_batches += 1
        self.stats.plane_hits += hits
        self.stats.plane_misses += len(chunk) - hits
        _M_BATCHES.inc()
        _M_BATCH_SIZE.set(len(chunk))
        _M_HITS.inc(hits)
        _M_MISSES.inc(len(chunk) - hits)

    @staticmethod
    def _complete(en: _Entry) -> None:
        # Leader-only (device lock held), so the countdown is plain.
        req = en.req
        req.pending -= 1
        if req.pending == 0:
            req.done.set()

    # -- health ------------------------------------------------------------

    def _note_demoted(self, reason: str) -> None:
        if self._demoted:
            return
        self._demoted = True
        self.stats.demotions += 1
        _M_DEMOTIONS.inc()
        telemetry.record_event("triage.demote", reason)
        log.logf(0, "TRIAGE DEMOTED to CPU path: %s", reason)

    def _note_promoted(self) -> None:
        if not self._demoted:
            return
        self._demoted = False
        self.stats.repromotions += 1
        _M_REPROMOTIONS.inc()
        telemetry.record_event("triage.repromote", "device answering")
        log.logf(0, "triage re-promoted to the device plane")

    def demoted(self) -> bool:
        return self._demoted

    def snapshot(self) -> dict:
        """Engine state for health_snapshot surfaces and tests."""
        out = self._snapshot_base()
        if self._tenant_planes is not None:
            out["tenants"] = self._tenant_planes.analytics()
        if self._sim_prescore is not None:
            out["sim_prescore"] = self._sim_prescore.snapshot()
        if self._hint_lane is not None:
            out["hint_lane"] = self._hint_lane.snapshot()
        return out

    def _snapshot_base(self) -> dict:
        s = self.stats
        return {
            "demoted": self._demoted,
            "calls": s.calls,
            "device_batches": s.device_batches,
            "plane_hits": s.plane_hits,
            "plane_misses": s.plane_misses,
            "overflow_calls": s.overflow_calls,
            "cpu_fallback_calls": s.cpu_fallback_calls,
            "device_errors": s.device_errors,
            "demotions": s.demotions,
            "repromotions": s.repromotions,
            "plane_rebuilds": s.plane_rebuilds,
            "h2d_overlaps": s.h2d_overlaps,
            "stale_slots": s.stale_slots,
            "dispatch_depth": self._dispatch_depth,
            "staging_arena_bytes": self._arena.nbytes,
            "plane_occupancy": self._occupancy,
            "fold_false_negative_rate":
                self._occupancy / dsig.PLANE_SIZE,
        }
