"""Device-side batched coverage triage (ISSUE 4).

The production novelty path: procs hand raw per-call signal arrays to
one shared TriageEngine, which ships them H2D in padded static-shape
batches, runs the jitted dense-plane diff (ops/signal.diff_batch),
and routes only the calls the plane flags as possibly-novel through
the exact CPU Signal diff.  See engine.py for the contract.
"""

from syzkaller_tpu.triage.engine import TriageEngine, TriageStats

__all__ = ["TriageEngine", "TriageStats"]
