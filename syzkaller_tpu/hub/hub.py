"""Hub RPC service: partition-tolerant corpus exchange between managers.

Serves Hub.Connect/Hub.Sync with client/key auth over the shared RPC
transport (reference: syz-hub/hub.go:22-60 + pkg/rpctype Hub protocol
rpctype.go:75-114).  ISSUE 16 makes the service a federation plane:

  * Connect mints (epoch, lease_s) so managers drive `call_session`
    against the hub exactly like fuzzers against the manager — a
    duplicate Sync replays its cached (reply, annex) byte-for-byte,
    a stale epoch or expired lease answers ReconnectRequired.
  * Sessioned Sync replies ship program payloads in the frame annex
    (`progs` becomes [[offset, len], ...] refs) — no JSON/zlib pass
    over corpus bytes.  Legacy unsessioned calls keep the inline
    string shape for old clients.
  * A Sync may carry the manager's packed novelty digest; HubState
    withholds programs the digest says the receiver already has.
  * The state body runs inside the `hub.sync` fault seam + span; a
    failure feeds that manager's circuit breaker, and while the
    breaker is open the hub answers cheap throttle replies carrying a
    `backoff_s` hint instead of scanning the corpus — one flapping
    manager degrades alone, the pod keeps syncing.
  * serve_hub attaches a DurableStore (checkpoint + WAL) so a leader
    SIGKILL is a warm restart: the successor redelivers exactly the
    un-acked batches.  main() turns SIGTERM into a graceful drain:
    flight-recorder dump, RPC close, final checkpoint.
"""

from __future__ import annotations

import base64
import binascii
import threading
from typing import Optional

from syzkaller_tpu import telemetry
from syzkaller_tpu.health import FaultInjected, fault_point
from syzkaller_tpu.hub.state import HubState
from syzkaller_tpu.ops.signal import unpack_plane
from syzkaller_tpu.rpc import RPCServer

_M_ANNEX_BYTES = telemetry.counter(
    "tz_hub_annex_bytes_total",
    "program payload bytes shipped in sync reply annexes")


class Hub:
    """RPC receiver.  clients maps client name -> key."""

    def __init__(self, state: HubState, clients: Optional[dict] = None):
        self.state = state
        self.clients = clients or {}

    def _auth(self, params: dict) -> str:
        """Returns the canonical manager name "client-manager"
        (reference: hub.go auth + name mangling)."""
        client = params.get("client", "")
        key = params.get("key", "")
        if self.clients and self.clients.get(client) != key:
            raise PermissionError(f"unauthorized client {client!r}")
        manager = params.get("manager", "") or client
        return f"{client}-{manager}" if client else manager

    def Connect(self, params: dict) -> dict:
        name = self._auth(params)
        corpus = [p.encode() for p in params.get("corpus") or []]
        self.state.connect(name, bool(params.get("fresh")), corpus,
                           sigs=params.get("corpus_sigs"))
        if not params.get("session"):
            return {}  # legacy shape
        mgr = self.state.managers[name]
        return {"epoch": self.state.epoch,
                "lease_s": self.state.lease_s,
                "last_seq": mgr.last_seq,
                "digest_bits": self.state.digest_bits}

    def Stats(self, params: dict) -> dict:
        """Introspection for operators and the chaos drill: the pod's
        cursors, custody depths, and breaker states."""
        self._auth(params)
        return self.state.stats()

    def Sync(self, params: dict):
        name = self._auth(params)
        st = self.state
        sessioned = bool(params.get("epoch"))
        cached = st.session_precheck(name, params)
        if cached is not None:
            return cached  # (reply, annex) replayed byte-for-byte

        # Breaker gate: an open breaker answers a cheap backoff hint
        # instead of scanning the corpus.  The throttle reply is
        # session-committed too — its retry must replay, not re-gate.
        br = st.breaker_for(name) if sessioned else None
        if br is not None and not br.allow():
            reply = ({"progs": [], "repros": [], "more": 0,
                      "throttled": True,
                      "backoff_s": round(br.seconds_until_probe(), 3)},
                     None)
            return st.session_commit(name, params, reply)

        digest = None
        blob64 = params.get("digest")
        if blob64:
            try:
                bits = int(params.get("digest_bits")
                           or st.digest_bits)
                digest = unpack_plane(
                    base64.b64decode(blob64), 1 << bits)
            except (binascii.Error, ValueError):
                digest = None  # garbled digest: sync without diffing

        try:
            with telemetry.span("hub.sync"):
                fault_point("hub.sync")
                progs, repros, more = st.sync(
                    name,
                    add=[p.encode() for p in params.get("add") or []],
                    delete=list(params.get("delete") or []),
                    repros=[p.encode()
                            for p in params.get("repros") or []],
                    need_repros=bool(params.get("need_repros")),
                    add_sigs=params.get("add_sigs"),
                    digest=digest,
                    rseq=int(params.get("seq") or 0) if sessioned
                    else 0,
                    ack_seq=int(params.get("ack_seq") or 0),
                )
        except FaultInjected:
            st.record_sync_result(name, ok=False)
            raise
        st.record_sync_result(name, ok=True)

        if not sessioned:
            return {"progs": [p.decode() for p in progs],
                    "repros": [p.decode() for p in repros],
                    "more": more}

        # Sessioned reply: progs ride the annex as (offset, len) refs.
        refs = []
        off = 0
        for p in progs:
            refs.append([off, len(p)])
            off += len(p)
        annex = b"".join(progs) if progs else None
        if annex:
            _M_ANNEX_BYTES.inc(len(annex))
        reply = ({"progs": refs,
                  "repros": [p.decode() for p in repros],
                  "more": more}, annex)
        return st.session_commit(name, params, reply)


def _register_gauges(state: HubState) -> None:
    """Pull gauges over live hub state.  Re-registration rebinds fn,
    so a fresh serve_hub (tests, restart-in-process) never leaves a
    gauge reading a dead HubState."""
    telemetry.gauge(
        "tz_hub_managers_size",
        "managers holding a live hub session",
        fn=state.connected_managers)
    telemetry.gauge(
        "tz_hub_corpus_size", "programs in the global hub corpus",
        fn=lambda: len(state.corpus_db.records))
    telemetry.gauge(
        "tz_hub_pending_repros_depth",
        "repro payloads queued for delivery across all managers",
        fn=state.pending_repro_depth)


def serve_hub(workdir: str, addr: tuple[str, int] = ("127.0.0.1", 0),
              clients: Optional[dict] = None, target=None,
              durable=None) -> tuple[RPCServer, Hub]:
    if durable is None:
        from syzkaller_tpu.durable import DurableStore
        durable = DurableStore.open(workdir)
    state = HubState(workdir, target=target, durable=durable)
    if durable is not None:
        durable.start()
    hub = Hub(state, clients)
    _register_gauges(state)
    srv = RPCServer(addr)
    srv.register("Hub", hub)
    srv.serve_in_background()
    return srv, hub


def main(argv=None) -> None:
    import argparse
    import signal as _signal

    ap = argparse.ArgumentParser(prog="tz-hub")
    ap.add_argument("-workdir", required=True)
    ap.add_argument("-addr", default="127.0.0.1:0")
    ap.add_argument("-clients", default="",
                    help="comma-separated client:key pairs")
    args = ap.parse_args(argv)
    from syzkaller_tpu.manager.mgrconfig import parse_addr

    clients = {}
    for pair in args.clients.split(","):
        if ":" in pair:
            c, _, k = pair.partition(":")
            clients[c] = k
    srv, hub = serve_hub(args.workdir, parse_addr(args.addr), clients)
    print(f"hub serving on {srv.addr[0]}:{srv.addr[1]}", flush=True)

    # Graceful drain: SIGTERM/SIGINT stop the wait loop; shutdown
    # dumps the flight recorder (post-mortem context beats a silent
    # exit), closes the RPC listener, and takes a final checkpoint so
    # the successor warm-restarts instead of replaying the whole WAL.
    stop = threading.Event()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        _signal.signal(sig, lambda _s, _f: stop.set())
    while not stop.wait(1.0):
        pass
    telemetry.record_event("hub.shutdown", "signal received; draining")
    telemetry.FLIGHT.dump("hub_shutdown",
                          "graceful shutdown on signal",
                          extra=hub.state.stats())
    srv.close()
    if hub.state.durable is not None:
        hub.state.durable.close()


if __name__ == "__main__":
    main()
