"""Hub RPC service: corpus exchange between managers.

Serves Hub.Connect/Hub.Sync with client/key auth over the shared RPC
transport (reference: syz-hub/hub.go:22-60 + pkg/rpctype Hub protocol
rpctype.go:75-114).
"""

from __future__ import annotations

from typing import Optional

from syzkaller_tpu.hub.state import HubState
from syzkaller_tpu.rpc import RPCServer


class Hub:
    """RPC receiver.  clients maps client name -> key."""

    def __init__(self, state: HubState, clients: Optional[dict] = None):
        self.state = state
        self.clients = clients or {}

    def _auth(self, params: dict) -> str:
        """Returns the canonical manager name "client-manager"
        (reference: hub.go auth + name mangling)."""
        client = params.get("client", "")
        key = params.get("key", "")
        if self.clients and self.clients.get(client) != key:
            raise PermissionError(f"unauthorized client {client!r}")
        manager = params.get("manager", "") or client
        return f"{client}-{manager}" if client else manager

    def Connect(self, params: dict) -> dict:
        name = self._auth(params)
        corpus = [p.encode() for p in params.get("corpus") or []]
        self.state.connect(name, bool(params.get("fresh")), corpus)
        return {}

    def Sync(self, params: dict) -> dict:
        name = self._auth(params)
        progs, repros, more = self.state.sync(
            name,
            add=[p.encode() for p in params.get("add") or []],
            delete=list(params.get("delete") or []),
            repros=[p.encode() for p in params.get("repros") or []],
            need_repros=bool(params.get("need_repros")),
        )
        return {"progs": [p.decode() for p in progs],
                "repros": [p.decode() for p in repros],
                "more": more}


def serve_hub(workdir: str, addr: tuple[str, int] = ("127.0.0.1", 0),
              clients: Optional[dict] = None, target=None
              ) -> tuple[RPCServer, Hub]:
    state = HubState(workdir, target=target)
    hub = Hub(state, clients)
    srv = RPCServer(addr)
    srv.register("Hub", hub)
    srv.serve_in_background()
    return srv, hub


def main(argv=None) -> None:
    import argparse
    import time

    ap = argparse.ArgumentParser(prog="tz-hub")
    ap.add_argument("-workdir", required=True)
    ap.add_argument("-addr", default="127.0.0.1:0")
    ap.add_argument("-clients", default="",
                    help="comma-separated client:key pairs")
    args = ap.parse_args(argv)
    from syzkaller_tpu.manager.mgrconfig import parse_addr

    clients = {}
    for pair in args.clients.split(","):
        if ":" in pair:
            c, _, k = pair.partition(":")
            clients[c] = k
    srv, _hub = serve_hub(args.workdir, parse_addr(args.addr), clients)
    print(f"hub serving on {srv.addr[0]}:{srv.addr[1]}")
    while True:
        time.sleep(60)


if __name__ == "__main__":
    main()
