from syzkaller_tpu.hub.state import HubState
from syzkaller_tpu.hub.hub import Hub, serve_hub

__all__ = ["HubState", "Hub", "serve_hub"]
