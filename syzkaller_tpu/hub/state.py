"""Hub corpus-exchange state: the fault-domain federation plane.

The hub federates corpora across managers: every synced program gets a
monotonic sequence number in a global corpus; each manager tracks the
last sequence it has consumed, so a sync streams it everything new
from *other* managers (its own programs are filtered by hash).  Repro
requests fan out to every other connected manager's pending queue
(reference: syz-hub/state/state.go:54 Make, 144 Connect, 178 Sync,
200/228 repro queues, 341 purgeCorpus).

ISSUE 16 layers the pod-survival machinery on top of that exchange:

  * Sessions (the PR 8 discipline): Connect mints (epoch, lease);
    Sync carries (epoch, seq, ack_seq) with a byte-bounded per-manager
    ReplyCache so `call_session` retries are at-most-once.  A stale
    epoch or reaped lease answers ReconnectRequired and the manager
    resyncs from its durable `last_seq` — corpus adds dedup by
    program hash, so the resync re-upload is idempotent.
  * Delivery custody: a sessioned sync's cursor advance rides
    `inflight` as (reply seq, start, end, repros) until the manager's
    ack_seq confirms receipt.  An abandoned reply (ack skipped the
    seq: lost reply, dead manager) rolls the cursor back to the
    batch's start and returns its repros to the queue front — the
    selection scan is deterministic from the cursor, so rollback IS
    redelivery, with zero loss and zero duplication (acks are a
    monotonic high-water mark, so abandonment is suffix-shaped).
  * Plane-indexed novelty diffs: a Sync may carry the manager's
    packed signal digest (ops/signal.digest_* at TZ_HUB_DIGEST_BITS);
    the hub diffs each candidate program's stored folds (sig.db)
    against it and withholds predicted-known programs, cutting reply
    bytes.  Withheld programs still advance the cursor — the digest
    said the receiver has that signal already.
  * Leader failover (the PR 12 treatment): when a DurableStore is
    attached, cursor advances / settles / repro custody journal under
    the store barrier and the whole session plane is a checkpoint
    section; recovery COLLAPSES un-acked inflight back into the
    cursors (durable/recovery.py), so a SIGKILLed hub restarted
    behind the same port redelivers exactly the unconfirmed batches.
    The corpus itself (corpus.db / sig.db / per-manager own dbs) is
    already crash-safe through the fsynced db layer.
  * Per-manager circuit breakers: sync failures (the `hub.sync`
    fault seam) trip a per-manager breaker whose open state degrades
    THAT manager to backoff-hint replies without stalling the pod.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from syzkaller_tpu import telemetry
from syzkaller_tpu.db import open_db
from syzkaller_tpu.health import CircuitBreaker
from syzkaller_tpu.health.envsafe import env_float
from syzkaller_tpu.models.encoding import ParseError, deserialize_prog
from syzkaller_tpu.ops.signal import (digest_covers, fold_hash_np,
                                      resolve_digest_bits)
from syzkaller_tpu.rpc.replycache import ReplyCache
from syzkaller_tpu.rpc.rpc import ReconnectRequired
from syzkaller_tpu.utils import log
from syzkaller_tpu.utils.hashsig import hash_string

SYNC_BATCH = 1000  # progs per Sync response (state.go pendingBatch)
REPRO_BATCH = 100
#: Reaped managers' reply caches kept around (bounded, same rationale
#: as manager/rpcserver._MAX_TOMBSTONES).
_MAX_TOMBSTONES = 64
#: Settle sentinel: "every outstanding reply is abandoned" (reap,
#: re-Connect, recovery collapse).
SETTLE_ALL = 1 << 62

_M_SENT = telemetry.counter(
    "tz_hub_progs_sent_total", "programs shipped in sync replies")
_M_RECV = telemetry.counter(
    "tz_hub_progs_recv_total", "programs received from managers")
_M_REJECTED = telemetry.counter(
    "tz_hub_progs_rejected_total",
    "incoming programs refused by deserialize_prog (counted and "
    "skipped; the seq index never advances for them)")
_M_DIGEST_SKIPPED = telemetry.counter(
    "tz_hub_digest_skipped_total",
    "programs withheld from a sync reply as predicted-known by the "
    "receiver's novelty digest")
_M_SAVED_BYTES = telemetry.counter(
    "tz_hub_sync_saved_bytes_total",
    "reply payload bytes NOT shipped thanks to digest-diff sync")
_M_REPLAYS = telemetry.counter(
    "tz_hub_replays_total",
    "duplicate (epoch, seq) hub syncs answered from the reply cache")
_M_STALE = telemetry.counter(
    "tz_hub_stale_sessions_total",
    "hub calls answered ReconnectRequired (stale epoch or reaped "
    "lease)")
_M_REAPED = telemetry.counter(
    "tz_hub_leases_reaped_total",
    "manager leases reaped after TZ_HUB_LEASE_S without a sync")
_M_REQUEUED = telemetry.counter(
    "tz_hub_requeued_total",
    "abandoned sync batches rolled back into manager cursors for "
    "redelivery")
_G_FAILOVER = telemetry.gauge(
    "tz_hub_last_failover_ts",
    "wallclock of the last warm recovery from a previous hub "
    "generation (0 = never)")


def _breaker_gauge(name: str) -> object:
    return telemetry.gauge(
        "tz_hub_breaker_state",
        "one manager's hub-sync breaker (0 closed, 1 half_open, "
        "2 open)", labels={"manager": name})


_BREAKER_LEVEL = {"closed": 0, "half_open": 1, "open": 2}


@dataclass
class ManagerState:
    name: str
    last_seq: int = 0  # highest global seq already delivered
    own_hashes: set[str] = field(default_factory=set)
    pending_repros: list[bytes] = field(default_factory=list)
    seen_repros: set[str] = field(default_factory=set)
    connected: bool = False
    own_db: object = None  # cached open DB handle
    # Session/lease plane (sessioned managers only; legacy callers
    # leave these untouched).
    last_seen: float = 0.0
    reply_cache: ReplyCache = field(default_factory=ReplyCache)
    #: Un-acked sync custody: [reply seq, cursor start, cursor end,
    #: [repro payloads]].
    inflight: list[list] = field(default_factory=list)
    digest: Optional[np.ndarray] = None
    breaker: Optional[CircuitBreaker] = None


class HubState:
    def __init__(self, workdir: str, target=None, durable=None,
                 lease_s: Optional[float] = None,
                 clock=time.monotonic):
        os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.target = target  # optional: validates incoming programs
        self._lock = threading.Lock()
        self.corpus_db = open_db(os.path.join(workdir, "corpus.db"))
        #: Sidecar fold index: program hash -> packed uint32 plane
        #: folds of its signal, the digest-diff input.  Programs with
        #: no stored folds always ship (never silently withheld).
        self.sig_db = open_db(os.path.join(workdir, "sig.db"))
        self.managers: dict[str, ManagerState] = {}
        self.next_seq = 1
        # seq-ordered (seq, key) index so Sync streams deltas without
        # re-sorting the whole corpus every call; stale entries
        # (deleted/superseded) are skipped at read time.
        self._seq_order: list[tuple[int, str]] = []
        for key, rec in self.corpus_db.records.items():
            self.next_seq = max(self.next_seq, rec.seq + 1)
            self._seq_order.append((rec.seq, key))
        self._seq_order.sort()
        # Session plane: the epoch is re-minted per HubState instance,
        # so a hub restart (planned or SIGKILL) invalidates every
        # manager's session and forces the re-Connect resync.
        self.epoch = f"{random.getrandbits(64):016x}"
        self.lease_s = env_float("TZ_HUB_LEASE_S", 120.0) \
            if lease_s is None else lease_s
        self.digest_bits = resolve_digest_bits()
        self._clock = clock
        self.reaped_total = 0
        self.replays_total = 0
        self.rejected_total = 0
        self.digest_skipped_total = 0
        self.sync_saved_bytes = 0
        self.last_failover_ts = 0.0
        self._tombstones: dict[str, ReplyCache] = {}
        self._load_managers()
        # Durability (syzkaller_tpu/durable): cursor/custody records
        # journal under the store barrier; recovery overlays collapsed
        # custody onto the file/db-loaded baseline above.
        self.durable = durable
        if durable is not None:
            rec = (durable.recovered or {}).get("hub") \
                if durable.recovered is not None else None
            if rec:
                self._restore_locked(rec)
            durable.register("hub", self._provider)

    # -- durable plumbing --------------------------------------------------

    def _barrier(self):
        d = self.durable
        return d.barrier if d is not None else contextlib.nullcontext()

    def _journal(self, kind: str, meta: dict, blob: bytes = b"") -> None:
        d = self.durable
        if d is not None:
            d.journal(kind, meta, blob)

    def _provider(self) -> tuple[dict, bytes]:
        """The "hub" checkpoint section: per-manager cursors + custody
        (inflight batches, pending repros) with repro payloads packed
        into the blob.  The corpus dbs are NOT here — they are their
        own fsynced files; the section covers exactly the state a
        crash would otherwise lose: which deliveries were confirmed."""
        with self._lock:
            managers: dict[str, dict] = {}
            parts: list[bytes] = []
            off = 0
            for name, m in self.managers.items():
                infl = []
                for rseq, start, end, repros in m.inflight:
                    lens = [len(r) for r in repros]
                    parts.extend(repros)
                    infl.append([rseq, start, end, off, lens])
                    off += sum(lens)
                pend_lens = [len(r) for r in m.pending_repros]
                parts.extend(m.pending_repros)
                managers[name] = {
                    "last_seq": m.last_seq,
                    "inflight": infl,
                    "pending_off": off,
                    "pending_lens": pend_lens,
                    "seen": sorted(m.seen_repros),
                }
                off += sum(pend_lens)
            meta = {"next_seq": self.next_seq, "managers": managers}
            return meta, b"".join(parts)

    def _restore_locked(self, rec: dict) -> None:
        """Overlay recovered custody on the file/db baseline.  The WAL
        cursors are authoritative: they carry the rollback the seq
        files cannot (a file-persisted cursor may point past batches
        no manager ever confirmed)."""
        for name, st in (rec.get("managers") or {}).items():
            m = self.managers.get(name)
            if m is None:
                m = self.managers[name] = ManagerState(name=name)
            m.last_seq = int(st.get("last_seq") or 0)
            m.pending_repros = [bytes(b) for b in
                                st.get("pending_repros") or []]
            m.seen_repros = set(st.get("seen") or [])
            m.connected = False
            self._persist_manager(m)
        self.next_seq = max(self.next_seq,
                            int(rec.get("next_seq") or 1))
        self.last_failover_ts = time.time()
        _G_FAILOVER.set(self.last_failover_ts)
        telemetry.record_event(
            "hub.failover",
            f"{len(rec.get('managers') or {})} manager cursors "
            "recovered; un-acked batches collapsed for redelivery")
        log.logf(0, "hub: warm failover recovery (%d managers)",
                 len(rec.get("managers") or {}))

    # -- manager persistence (legacy files; durable-free baseline) ---------

    def _manager_dir(self, name: str) -> str:
        safe = hash_string(name.encode())[:16]
        d = os.path.join(self.workdir, "manager-" + safe)
        os.makedirs(d, exist_ok=True)
        return d

    def _load_managers(self) -> None:
        for entry in os.listdir(self.workdir):
            if not entry.startswith("manager-"):
                continue
            d = os.path.join(self.workdir, entry)
            try:
                name = open(os.path.join(d, "name")).read().strip()
                seq = int(open(os.path.join(d, "seq")).read().strip() or 0)
            except (OSError, ValueError):
                # Torn manager dir (half-written name/seq): skipped —
                # the manager re-Connects and re-uploads; dedup by
                # hash makes that idempotent.
                continue
            if not name:
                continue
            mgr = ManagerState(name=name, last_seq=seq)
            try:
                own = open_db(os.path.join(d, "corpus.db"))
                mgr.own_hashes = set(own.records)
            except OSError:
                # Stale dir with a missing/unreadable own-db: the
                # cursor survives; ownership rebuilds on re-upload.
                mgr.own_hashes = set()
            self.managers[name] = mgr

    def _persist_manager(self, mgr: ManagerState) -> None:
        d = self._manager_dir(mgr.name)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "name"), "w") as f:
            f.write(mgr.name)
        with open(os.path.join(d, "seq"), "w") as f:
            f.write(str(mgr.last_seq))

    def _own_db(self, mgr: ManagerState):
        """Cached per-manager DB handle — Sync runs every minute per
        manager and must not re-parse the whole file each time."""
        if mgr.own_db is None:
            mgr.own_db = open_db(os.path.join(
                self._manager_dir(mgr.name), "corpus.db"))
        return mgr.own_db

    # -- session plumbing (the PR 8 discipline) ----------------------------

    def session_precheck(self, name: str,
                         params: dict) -> Optional[tuple]:
        """Replay-or-admit gate for a sessioned Sync: the cached
        (reply, annex) for a duplicate (epoch, seq), None to execute,
        or ReconnectRequired (stale epoch / reaped lease).  Legacy
        callers (no epoch) pass through."""
        epoch = params.get("epoch")
        if not epoch:
            return None
        seq = int(params.get("seq") or 0)
        with self._lock:
            self._reap_locked()
            if epoch != self.epoch:
                _M_STALE.inc()
                raise ReconnectRequired(
                    f"hub epoch {epoch} is stale (hub epoch "
                    f"{self.epoch}); re-Connect")
            m = self.managers.get(name)
            if m is None or not m.connected:
                cache = self._tombstones.get(name)
                cached = cache.get(seq) if cache is not None else None
                if cached is not None:
                    _M_REPLAYS.inc()
                    self.replays_total += 1
                    return cached
                _M_STALE.inc()
                raise ReconnectRequired(
                    f"hub lease for {name!r} expired; re-Connect")
            m.last_seen = self._clock()
            cached = m.reply_cache.get(seq)
            if cached is not None:
                _M_REPLAYS.inc()
                self.replays_total += 1
                return cached
        return None

    def session_commit(self, name: str, params: dict,
                       reply: tuple) -> tuple:
        seq = int(params.get("seq") or 0)
        if not params.get("epoch") or not seq:
            return reply
        with self._lock:
            m = self.managers.get(name)
            if m is not None:
                m.reply_cache.put(seq, reply)
        return reply

    def breaker_for(self, name: str) -> Optional[CircuitBreaker]:
        with self._lock:
            m = self.managers.get(name)
            return m.breaker if m is not None else None

    def record_sync_result(self, name: str, ok: bool) -> None:
        """Feed one manager's sync outcome into its breaker (and the
        labeled state gauge) — hub/hub.py calls this around the
        `hub.sync` fault seam."""
        with self._lock:
            m = self.managers.get(name)
            if m is None or m.breaker is None:
                return
            br = m.breaker
        if ok:
            br.record_success()
        else:
            br.record_failure()
        _breaker_gauge(name).set(_BREAKER_LEVEL.get(br.state, 0))

    def _reap_locked(self) -> None:
        """Reap sessions idle past lease_s (caller holds self._lock).
        Unlike the manager's fuzzer reap, the ManagerState survives —
        cursors and corpus ownership are durable facts about the pod;
        only the SESSION dies: un-acked custody rolls back into the
        cursor, the reply cache is tombstoned."""
        now = self._clock()
        for m in list(self.managers.values()):
            if not (m.connected and m.last_seen
                    and now - m.last_seen > self.lease_s):
                continue
            m.connected = False
            self.reaped_total += 1
            _M_REAPED.inc()
            self._settle_locked(m, SETTLE_ALL, 0)
            self._journal("hub_reap", {"name": m.name})
            self._tombstones[m.name] = m.reply_cache
            m.reply_cache = ReplyCache()
            while len(self._tombstones) > _MAX_TOMBSTONES:
                del self._tombstones[next(iter(self._tombstones))]
            self._persist_manager(m)
            telemetry.record_event(
                "hub.lease_expire",
                f"{m.name} idle {now - m.last_seen:.0f}s; cursor "
                f"rolled back to {m.last_seq}")
            log.logf(0, "hub: reaped manager %s (idle %.0fs)",
                     m.name, now - m.last_seen)

    def _settle_locked(self, m: ManagerState, seq: int,
                       ack_seq: int) -> None:
        """Advance delivery custody: replies the manager confirmed
        (reply seq <= ack_seq) retire; abandoned replies (reply seq <
        current seq, never acked) roll the cursor back to their batch
        start — redelivery happens by re-scanning, not by caching
        payloads — and return their repros to the queue front."""
        keep: list[list] = []
        rollback: Optional[int] = None
        requeued: list[bytes] = []
        abandoned = 0
        for entry in m.inflight:
            rseq, start, _end, repros = entry
            if rseq <= ack_seq:
                continue  # delivered
            if rseq < seq:
                abandoned += 1
                rollback = start if rollback is None \
                    else min(rollback, start)
                requeued.extend(repros)
            else:
                keep.append(entry)
        m.inflight = keep
        if rollback is not None:
            m.last_seq = min(m.last_seq, rollback)
        if requeued:
            m.pending_repros[:0] = requeued
        if abandoned:
            _M_REQUEUED.inc(abandoned)

    # -- protocol ---------------------------------------------------------

    def connect(self, name: str, fresh: bool, corpus: list[bytes],
                sigs: Optional[list] = None) -> ManagerState:
        """(reference: state.go:144-176) + session arm: un-acked
        replies died with the old session, so custody settles (cursor
        rollback) before the fresh lease starts."""
        with self._barrier(), self._lock:
            self._reap_locked()
            mgr = self.managers.get(name)
            if mgr is None or fresh:
                prev = mgr
                mgr = ManagerState(name=name)
                if prev is not None:
                    mgr.own_db = prev.own_db
                    mgr.breaker = prev.breaker
                self.managers[name] = mgr
            else:
                self._settle_locked(mgr, SETTLE_ALL, 0)
                mgr.reply_cache = ReplyCache()
            self._tombstones.pop(name, None)
            mgr.connected = True
            mgr.last_seen = self._clock()
            if mgr.breaker is None:
                mgr.breaker = CircuitBreaker(failure_threshold=3,
                                             clock=self._clock)
            own_db = self._own_db(mgr)
            if fresh:
                for key in list(own_db.records):
                    own_db.delete(key)
                mgr.last_seq = 0
            for i, prog in enumerate(corpus):
                sig = sigs[i] if sigs and i < len(sigs) else None
                self._add_prog(name, mgr, prog, own_db, sig)
            own_db.flush()
            self.corpus_db.flush()
            self.sig_db.flush()
            mgr.own_hashes = set(own_db.records)
            self._persist_manager(mgr)
            self._journal("hub_connect",
                          {"name": name, "last_seq": mgr.last_seq})
            log.logf(0, "hub: manager %s connected (%d corpus, "
                     "fresh=%s)", name, len(corpus), fresh)
            return mgr

    def sync(self, name: str, add: list[bytes], delete: list[str],
             repros: list[bytes], need_repros: bool,
             add_sigs: Optional[list] = None,
             digest: Optional[np.ndarray] = None,
             rseq: int = 0, ack_seq: int = 0
             ) -> tuple[list[bytes], list[bytes], int]:
        """Returns (progs, repros, more) (reference: state.go:178-339).
        `rseq`/`ack_seq` arm the custody ledger (sessioned callers);
        legacy callers (rseq=0) get immediate-delivery semantics, as
        before sessions existed."""
        with self._barrier(), self._lock:
            mgr = self.managers.get(name)
            if mgr is None:
                raise KeyError(f"manager {name!r} never connected")
            if digest is not None:
                mgr.digest = digest
            if rseq:
                self._settle_locked(mgr, rseq, ack_seq)
                if ack_seq or mgr.inflight:
                    self._journal("hub_settle",
                                  {"name": name, "seq": rseq,
                                   "ack_seq": ack_seq})
            own_db = self._own_db(mgr)
            for i, prog in enumerate(add):
                sig = add_sigs[i] if add_sigs and i < len(add_sigs) \
                    else None
                self._add_prog(name, mgr, prog, own_db, sig)
            if add:
                _M_RECV.inc(len(add))
            for h in delete:
                own_db.delete(h)
                mgr.own_hashes.discard(h)
                self.corpus_db.delete(h)
                self.sig_db.delete(h)
            own_db.flush()
            self.corpus_db.flush()
            self.sig_db.flush()

            # repro fan-out to every other manager
            for rp in repros:
                h = hash_string(rp)
                for other in self.managers.values():
                    if other.name == name or h in other.seen_repros:
                        continue
                    other.seen_repros.add(h)
                    other.pending_repros.append(rp)
                    self._journal("hub_repro",
                                  {"to": other.name, "lens": [len(rp)],
                                   "hashes": [h]}, rp)

            # stream new progs from other managers (seq index walk;
            # bisect to the cursor instead of scanning from 0).  The
            # cursor also advances past own and digest-covered
            # entries — both are conscious non-deliveries, not work
            # left behind.
            import bisect as _bisect

            progs: list[bytes] = []
            start_cursor = mgr.last_seq
            max_seq = mgr.last_seq
            remaining = 0
            skipped = 0
            saved = 0
            start = _bisect.bisect_right(self._seq_order,
                                         (mgr.last_seq, "\xff"))
            for seq, key in self._seq_order[start:]:
                rec = self.corpus_db.records.get(key)
                if rec is None or rec.seq != seq:
                    continue  # stale index entry
                if len(progs) >= SYNC_BATCH:
                    if key not in mgr.own_hashes:
                        remaining += 1
                    continue
                if key in mgr.own_hashes:
                    max_seq = seq
                    continue
                if mgr.digest is not None and digest_covers(
                        mgr.digest, self._folds(key)):
                    skipped += 1
                    saved += len(rec.val)
                    max_seq = seq
                    continue
                progs.append(rec.val)
                max_seq = seq
            mgr.last_seq = max_seq

            out_repros: list[bytes] = []
            if need_repros:
                out_repros = mgr.pending_repros[:REPRO_BATCH]
                del mgr.pending_repros[:REPRO_BATCH]

            if rseq and (progs or out_repros
                         or max_seq != start_cursor):
                mgr.inflight.append(
                    [rseq, start_cursor, max_seq, list(out_repros)])
                self._journal(
                    "hub_issue",
                    {"name": name, "rseq": rseq,
                     "start": start_cursor, "end": max_seq,
                     "repro_lens": [len(r) for r in out_repros]},
                    b"".join(out_repros))
            self._persist_manager(mgr)
            if progs:
                _M_SENT.inc(len(progs))
            if skipped:
                self.digest_skipped_total += skipped
                self.sync_saved_bytes += saved
                _M_DIGEST_SKIPPED.inc(skipped)
                _M_SAVED_BYTES.inc(saved)
            return progs, out_repros, remaining

    def _folds(self, key: str) -> np.ndarray:
        rec = self.sig_db.records.get(key)
        if rec is None or not rec.val:
            return np.empty(0, np.int64)
        return np.frombuffer(bytes(rec.val),
                             dtype=np.uint32).astype(np.int64)

    def _add_prog(self, name: str, mgr: ManagerState, prog: bytes,
                  own_db, sig=None) -> Optional[str]:
        if self.target is not None:
            try:
                deserialize_prog(self.target, prog)
            except ParseError:
                # Count + skip; the seq index never advances for a
                # refused program, so one corrupt upload can't poison
                # every other manager's cursor.
                self.rejected_total += 1
                _M_REJECTED.inc()
                return None
        key = hash_string(prog)
        mgr.own_hashes.add(key)
        own_db.save(key, b"", 0)
        if sig and key not in self.sig_db.records:
            folds = np.unique(fold_hash_np(
                np.asarray(list(sig), dtype=np.int64)
                .astype(np.uint32)))
            self.sig_db.save(key, folds.astype(np.uint32).tobytes(), 0)
        if key not in self.corpus_db.records:
            self.corpus_db.save(key, prog, self.next_seq)
            self._seq_order.append((self.next_seq, key))
            self.next_seq += 1
        return key

    def purge_corpus(self) -> None:
        """Drop global progs no connected manager still owns
        (reference: state.go:341-365)."""
        with self._lock:
            owned: set[str] = set()
            for mgr in self.managers.values():
                owned |= mgr.own_hashes
            for key in list(self.corpus_db.records):
                if key not in owned:
                    self.corpus_db.delete(key)
                    self.sig_db.delete(key)
            self.corpus_db.flush()
            self.sig_db.flush()

    # -- introspection -----------------------------------------------------

    def connected_managers(self) -> int:
        with self._lock:
            return sum(1 for m in self.managers.values() if m.connected)

    def pending_repro_depth(self) -> int:
        with self._lock:
            return sum(len(m.pending_repros)
                       for m in self.managers.values())

    def stats(self) -> dict:
        with self._lock:
            return {
                "corpus": len(self.corpus_db.records),
                "next_seq": self.next_seq,
                "epoch": self.epoch,
                "reaped": self.reaped_total,
                "replays": self.replays_total,
                "rejected": self.rejected_total,
                "digest_skipped": self.digest_skipped_total,
                "sync_saved_bytes": self.sync_saved_bytes,
                "last_failover_ts": self.last_failover_ts,
                "managers": {
                    n: {"connected": m.connected, "seq": m.last_seq,
                        "own": len(m.own_hashes),
                        "pending_repros": len(m.pending_repros),
                        "inflight": len(m.inflight),
                        "breaker": m.breaker.state
                        if m.breaker is not None else "closed"}
                    for n, m in self.managers.items()
                },
            }
