"""Hub corpus-exchange state.

The hub federates corpora across managers: every synced program gets a
monotonic sequence number in a global corpus; each manager tracks the
last sequence it has consumed, so a sync streams it everything new
from *other* managers (its own programs are filtered by hash).  Repro
requests fan out to every other connected manager's pending queue.
All state is durable: global corpus + per-manager metadata live in
append-only DBs under the workdir (reference: syz-hub/state/state.go:54
Make, 144 Connect, 178 Sync, 200/228 repro queues, 341 purgeCorpus).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional

from syzkaller_tpu.db import open_db
from syzkaller_tpu.models.encoding import ParseError, deserialize_prog
from syzkaller_tpu.utils import log
from syzkaller_tpu.utils.hashsig import hash_string

SYNC_BATCH = 1000  # progs per Sync response (state.go pendingBatch)


@dataclass
class ManagerState:
    name: str
    last_seq: int = 0  # highest global seq already delivered
    own_hashes: set[str] = field(default_factory=set)
    pending_repros: list[bytes] = field(default_factory=list)
    seen_repros: set[str] = field(default_factory=set)
    connected: bool = False
    own_db: object = None  # cached open DB handle


class HubState:
    def __init__(self, workdir: str, target=None):
        os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.target = target  # optional: validates incoming programs
        self._lock = threading.Lock()
        self.corpus_db = open_db(os.path.join(workdir, "corpus.db"))
        self.managers: dict[str, ManagerState] = {}
        self.next_seq = 1
        # seq-ordered (seq, key) index so Sync streams deltas without
        # re-sorting the whole corpus every call; stale entries
        # (deleted/superseded) are skipped at read time.
        self._seq_order: list[tuple[int, str]] = []
        for key, rec in self.corpus_db.records.items():
            self.next_seq = max(self.next_seq, rec.seq + 1)
            self._seq_order.append((rec.seq, key))
        self._seq_order.sort()
        self._load_managers()

    def _manager_dir(self, name: str) -> str:
        safe = hash_string(name.encode())[:16]
        d = os.path.join(self.workdir, "manager-" + safe)
        os.makedirs(d, exist_ok=True)
        return d

    def _load_managers(self) -> None:
        for entry in os.listdir(self.workdir):
            if not entry.startswith("manager-"):
                continue
            d = os.path.join(self.workdir, entry)
            try:
                name = open(os.path.join(d, "name")).read().strip()
                seq = int(open(os.path.join(d, "seq")).read().strip() or 0)
            except (OSError, ValueError):
                continue
            mgr = ManagerState(name=name, last_seq=seq)
            own = open_db(os.path.join(d, "corpus.db"))
            mgr.own_hashes = set(own.records)
            self.managers[name] = mgr

    def _persist_manager(self, mgr: ManagerState) -> None:
        d = self._manager_dir(mgr.name)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "name"), "w") as f:
            f.write(mgr.name)
        with open(os.path.join(d, "seq"), "w") as f:
            f.write(str(mgr.last_seq))

    # -- protocol ---------------------------------------------------------

    def _own_db(self, mgr: ManagerState):
        """Cached per-manager DB handle — Sync runs every minute per
        manager and must not re-parse the whole file each time."""
        if mgr.own_db is None:
            mgr.own_db = open_db(os.path.join(
                self._manager_dir(mgr.name), "corpus.db"))
        return mgr.own_db

    def connect(self, name: str, fresh: bool,
                corpus: list[bytes]) -> None:
        """(reference: state.go:144-176)"""
        with self._lock:
            mgr = self.managers.get(name)
            if mgr is None or fresh:
                prev = mgr
                mgr = ManagerState(name=name)
                if prev is not None:
                    mgr.own_db = prev.own_db
                self.managers[name] = mgr
            mgr.connected = True
            own_db = self._own_db(mgr)
            if fresh:
                for key in list(own_db.records):
                    own_db.delete(key)
                mgr.last_seq = 0
            for prog in corpus:
                key = self._add_prog(name, mgr, prog, own_db)
            own_db.flush()
            mgr.own_hashes = set(own_db.records)
            self._persist_manager(mgr)
            log.logf(0, "hub: manager %s connected (%d corpus, fresh=%s)",
                     name, len(corpus), fresh)

    def sync(self, name: str, add: list[bytes], delete: list[str],
             repros: list[bytes], need_repros: bool
             ) -> tuple[list[bytes], list[bytes], int]:
        """Returns (progs, repros, more) (reference: state.go:178-339)."""
        with self._lock:
            mgr = self.managers.get(name)
            if mgr is None:
                raise KeyError(f"manager {name!r} never connected")
            own_db = self._own_db(mgr)
            for prog in add:
                self._add_prog(name, mgr, prog, own_db)
            for h in delete:
                own_db.delete(h)
                mgr.own_hashes.discard(h)
                self.corpus_db.delete(h)
            own_db.flush()
            self.corpus_db.flush()

            # repro fan-out to every other manager
            for rp in repros:
                h = hash_string(rp)
                for other in self.managers.values():
                    if other.name == name or h in other.seen_repros:
                        continue
                    other.seen_repros.add(h)
                    other.pending_repros.append(rp)

            # stream new progs from other managers (seq index walk;
            # bisect to the cursor instead of scanning from 0)
            import bisect as _bisect

            progs: list[bytes] = []
            max_seq = mgr.last_seq
            remaining = 0
            start = _bisect.bisect_right(self._seq_order,
                                         (mgr.last_seq, "\xff"))
            for seq, key in self._seq_order[start:]:
                rec = self.corpus_db.records.get(key)
                if rec is None or rec.seq != seq \
                        or key in mgr.own_hashes:
                    continue
                if len(progs) >= SYNC_BATCH:
                    remaining += 1
                    continue
                progs.append(rec.val)
                max_seq = max(max_seq, seq)
            mgr.last_seq = max_seq
            self._persist_manager(mgr)

            out_repros: list[bytes] = []
            if need_repros:
                out_repros = mgr.pending_repros[:100]
                del mgr.pending_repros[:100]
            return progs, out_repros, remaining

    def _add_prog(self, name: str, mgr: ManagerState, prog: bytes,
                  own_db) -> Optional[str]:
        if self.target is not None:
            try:
                deserialize_prog(self.target, prog)
            except ParseError:
                return None  # refuse broken programs into the corpus
        key = hash_string(prog)
        mgr.own_hashes.add(key)
        own_db.save(key, b"", 0)
        if key not in self.corpus_db.records:
            self.corpus_db.save(key, prog, self.next_seq)
            self._seq_order.append((self.next_seq, key))
            self.next_seq += 1
        return key

    def purge_corpus(self) -> None:
        """Drop global progs no connected manager still owns
        (reference: state.go:341-365)."""
        with self._lock:
            owned: set[str] = set()
            for mgr in self.managers.values():
                owned |= mgr.own_hashes
            for key in list(self.corpus_db.records):
                if key not in owned:
                    self.corpus_db.delete(key)
            self.corpus_db.flush()

    def stats(self) -> dict:
        with self._lock:
            return {
                "corpus": len(self.corpus_db.records),
                "managers": {
                    n: {"connected": m.connected, "seq": m.last_seq,
                        "own": len(m.own_hashes),
                        "pending_repros": len(m.pending_repros)}
                    for n, m in self.managers.items()
                },
            }
