"""Kernel coverage reporting: PC sets → per-function/line HTML.

Symbolizes the manager's accumulated raw cover PCs against the
vmlinux (nm symbol table + addr2line) and renders a coverage report:
covered/total per source file, per-function hit counts, and raw PC
dumps (reference: syz-manager/cover.go:58+ initAllCover/coverReport,
html endpoints /cover and /rawcover in html.go).
"""

from __future__ import annotations

import html as html_mod
import os
from collections import defaultdict
from typing import Iterable, Optional

from syzkaller_tpu.report.symbolizer import Symbolizer, read_symbols


class CoverReporter:
    def __init__(self, kernel_obj: str = ""):
        self.vmlinux = ""
        if kernel_obj:
            cand = os.path.join(kernel_obj, "vmlinux") \
                if os.path.isdir(kernel_obj) else kernel_obj
            if os.path.exists(cand):
                self.vmlinux = cand
        self._symbols = None  # name -> [Symbol]
        self._addr_index: Optional[list] = None  # sorted (addr, end, name)

    def _load_symbols(self) -> None:
        if self._addr_index is not None or not self.vmlinux:
            return
        self._symbols = read_symbols(self.vmlinux)
        index = []
        for name, syms in self._symbols.items():
            for s in syms:
                index.append((s.addr, s.addr + max(s.size, 1), name))
        index.sort()
        self._addr_index = index

    def func_of(self, pc: int) -> str:
        """Containing function by symbol-table binary search."""
        self._load_symbols()
        if not self._addr_index:
            return ""
        import bisect

        i = bisect.bisect_right(self._addr_index, (pc, float("inf"), "")) - 1
        if i >= 0:
            addr, end, name = self._addr_index[i]
            if addr <= pc < end:
                return name
        return ""

    def per_function(self, pcs: Iterable[int]) -> dict[str, int]:
        """Hit counts per function (the /cover summary table)."""
        counts: dict[str, int] = defaultdict(int)
        for pc in pcs:
            counts[self.func_of(pc) or f"0x{pc:x}"] += 1
        return dict(counts)

    def line_coverage(self, pcs: list[int],
                      limit: int = 4096) -> dict[str, list[int]]:
        """file -> covered lines via addr2line (capped; symbolization
        is ~1ms/PC)."""
        out: dict[str, set[int]] = defaultdict(set)
        if not self.vmlinux:
            return {}
        sym = Symbolizer()
        try:
            for frames in sym.symbolize(self.vmlinux, *pcs[:limit]):
                for f in frames:
                    if f.file and f.line:
                        out[f.file].add(f.line)
        finally:
            sym.close()
        return {k: sorted(v) for k, v in out.items()}

    def render_html(self, pcs: list[int]) -> str:
        """The /cover page."""
        pcs = sorted(set(pcs))
        rows = []
        if self.vmlinux:
            per_fn = self.per_function(pcs)
            for fn, n in sorted(per_fn.items(), key=lambda kv: -kv[1]):
                rows.append(f"<tr><td>{html_mod.escape(fn)}</td>"
                            f"<td>{n}</td></tr>")
            body = (f"<p>{len(pcs)} PCs covered</p><table>"
                    f"<tr><th>function</th><th>PCs</th></tr>"
                    + "".join(rows) + "</table>")
        else:
            # no vmlinux: raw PC dump (the /rawcover fallback)
            body = (f"<p>{len(pcs)} PCs covered (no kernel_obj "
                    f"configured — raw dump)</p><pre>"
                    + "\n".join(f"0x{pc:x}" for pc in pcs[:10000])
                    + "</pre>")
        return ("<html><head><title>coverage</title></head><body>"
                f"<h2>coverage</h2>{body}</body></html>")
