"""Manager configuration.

Strict-JSON config consumed by the manager daemon and tools
(reference: syz-manager/mgrconfig/mgrconfig.go:21-97 Config,
mgrconfig.go:99-178 LoadFile/validation/defaults).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Optional, Union

from syzkaller_tpu.utils.config import ConfigError, load_data, load_file


@dataclass
class Config:
    # instance identity
    name: str = ""
    target: str = "test/64"  # "os/arch" or "os"
    # services
    http: str = "127.0.0.1:0"  # web UI addr
    rpc: str = "127.0.0.1:0"  # manager RPC addr for fuzzers
    workdir: str = ""
    # VM/image plumbing (qemu/isolated types)
    image: str = ""
    sshkey: str = ""
    ssh_user: str = "root"
    kernel_obj: str = ""  # vmlinux dir for symbolization/coverage
    # fuzzing behavior
    procs: int = 1
    sandbox: str = "none"
    cover: bool = True
    leak: bool = False
    reproduce: bool = True
    engine: str = "cpu"  # mutation engine: "cpu" | "jax"
    enable_syscalls: list[str] = field(default_factory=list)
    disable_syscalls: list[str] = field(default_factory=list)
    suppressions: list[str] = field(default_factory=list)
    ignores: list[str] = field(default_factory=list)
    # federation
    hub_client: str = ""
    hub_addr: str = ""
    hub_key: str = ""
    # dashboard
    dashboard_client: str = ""
    dashboard_addr: str = ""
    dashboard_key: str = ""
    # VM backend
    type: str = "local"
    count: int = 1  # number of VM instances
    vm: dict = field(default_factory=dict)  # backend-specific blob

    @property
    def target_os(self) -> str:
        return self.target.split("/")[0]

    @property
    def target_arch(self) -> str:
        parts = self.target.split("/")
        return parts[1] if len(parts) > 1 else "64"


def parse_addr(addr: str) -> tuple[str, int]:
    host, sep, port = addr.rpartition(":")
    if not sep:
        return addr or "127.0.0.1", 0
    try:
        return host or "127.0.0.1", int(port or 0)
    except ValueError as e:
        raise ConfigError(f"bad address {addr!r}: {e}") from e


def load_config(path_or_data: Union[str, dict],
                data: Optional[str] = None) -> Config:
    if isinstance(path_or_data, dict):
        from syzkaller_tpu.utils.config import from_dict

        cfg = from_dict(path_or_data, Config)
    elif data is not None:
        cfg = load_data(data, Config)
    else:
        cfg = load_file(path_or_data, Config)
    return validate(cfg)


def validate(cfg: Config) -> Config:
    """Defaults + sanity (reference: mgrconfig.go:120-178)."""
    if not cfg.workdir:
        raise ConfigError("config param workdir is empty")
    cfg.workdir = os.path.abspath(os.path.expanduser(cfg.workdir))
    if not cfg.name:
        cfg.name = os.path.basename(cfg.workdir) or "manager"
    if cfg.procs < 1 or cfg.procs > 32:
        raise ConfigError("bad config param procs: must be [1, 32]")
    if cfg.count < 1 or cfg.count > 1000:
        raise ConfigError("bad config param count: must be [1, 1000]")
    if cfg.sandbox not in ("none", "setuid", "namespace"):
        raise ConfigError(f"config param sandbox must be "
                          f"none/setuid/namespace, not {cfg.sandbox!r}")
    if cfg.engine not in ("cpu", "jax"):
        raise ConfigError(f"config param engine must be cpu/jax, "
                          f"not {cfg.engine!r}")
    if (cfg.hub_client != "") != (cfg.hub_addr != ""):
        raise ConfigError("hub_client and hub_addr must be set together")
    if (cfg.dashboard_client != "") != (cfg.dashboard_addr != ""):
        raise ConfigError(
            "dashboard_client and dashboard_addr must be set together")
    from syzkaller_tpu.models.target import get_target

    get_target(cfg.target_os, cfg.target_arch)  # raises if unknown
    return cfg
