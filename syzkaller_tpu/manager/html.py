"""Manager HTTP UI.

Summary, corpus, crash and stats pages rendered server-side
(reference: syz-manager/html.go:30-41 endpoints: /, /syscalls,
/corpus, /crash, /cover, /prio, /file, /report, /rawcover).
"""

from __future__ import annotations

import html
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


def serve_http(mgr, addr: tuple[str, int]) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, body: str, ctype: str = "text/html") -> None:
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802
            url = urlparse(self.path)
            q = parse_qs(url.query)
            try:
                if url.path == "/":
                    self._send(_summary_page(mgr))
                elif url.path == "/stats":
                    self._send(json.dumps(mgr.stats_snapshot()),
                               "application/json")
                elif url.path == "/corpus":
                    self._send(_corpus_page(mgr))
                elif url.path == "/crash":
                    self._send(_crash_page(mgr, q.get("id", [""])[0]))
                elif url.path == "/syscalls":
                    self._send(_syscalls_page(mgr))
                elif url.path == "/cover":
                    self._send(_cover_page(mgr))
                elif url.path == "/rawcover":
                    with mgr.serv._lock:
                        pcs = sorted(mgr.serv.cover)
                    self._send("\n".join(f"0x{pc:x}" for pc in pcs),
                               "text/plain")
                else:
                    self.send_error(404)
            except BrokenPipeError:
                pass
            except Exception as e:
                self.send_error(500, str(e))

    srv = ThreadingHTTPServer(addr, Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


_STYLE = """<style>
body { font-family: monospace; margin: 2em; }
table { border-collapse: collapse; }
td, th { border: 1px solid #999; padding: 2px 8px; text-align: left; }
</style>"""


def _page(title: str, body: str) -> str:
    return (f"<html><head><title>{html.escape(title)}</title>{_STYLE}"
            f"</head><body><h2>{html.escape(title)}</h2>"
            f"<p><a href='/'>summary</a> | <a href='/corpus'>corpus</a> | "
            f"<a href='/syscalls'>syscalls</a> | "
            f"<a href='/stats'>stats.json</a></p>{body}</body></html>")


def _summary_page(mgr) -> str:
    s = mgr.stats_snapshot()
    rows = "".join(f"<tr><td>{html.escape(str(k))}</td>"
                   f"<td>{html.escape(str(v))}</td></tr>"
                   for k, v in sorted(s.items()) if not isinstance(v, dict))
    stats = s.get("stats", {})
    rows += "".join(f"<tr><td>{html.escape(k)}</td>"
                    f"<td>{v}</td></tr>" for k, v in sorted(stats.items()))
    crashes = ""
    with mgr._lock:
        items = sorted(mgr.crash_types.items(),
                       key=lambda kv: -kv[1].count)
    for title, entry in items:
        from syzkaller_tpu.utils.hashsig import hash_string

        sig = hash_string(title.encode())
        crashes += (f"<tr><td><a href='/crash?id={sig}'>"
                    f"{html.escape(title)}</a></td><td>{entry.count}</td>"
                    f"<td>{'yes' if entry.repro_done else ''}</td></tr>")
    body = (f"<table>{rows}</table><h3>Crashes</h3>"
            f"<table><tr><th>title</th><th>count</th><th>repro</th></tr>"
            f"{crashes}</table>")
    return _page(f"{mgr.cfg.name} syz-manager", body)


def _corpus_page(mgr) -> str:
    # copy under the lock, render outside it — the render escapes full
    # program texts and must not stall fuzzer RPCs
    with mgr.serv._lock:
        items = list(mgr.serv.corpus.items())[:1000]
    rows = ""
    for key, inp in items:
        sig_len = len(inp.get("signal", [[], []])[0])
        rows += (f"<tr><td>{key[:16]}</td><td>{sig_len}</td>"
                 f"<td><pre>{html.escape(inp.get('prog', ''))}"
                 f"</pre></td></tr>")
    return _page("corpus", f"<table><tr><th>sig</th><th>signal</th>"
                           f"<th>program</th></tr>{rows}</table>")


def _crash_page(mgr, crash_id: str) -> str:
    # crash ids are hex title-hashes; reject anything else so the
    # query param can't traverse out of crashdir.
    if not crash_id or any(c not in "0123456789abcdef" for c in crash_id):
        return _page("crash", "not found")
    dirpath = os.path.join(mgr.crashdir, crash_id)
    if not os.path.isdir(dirpath):
        return _page("crash", "not found")
    parts = []
    for name in sorted(os.listdir(dirpath)):
        with open(os.path.join(dirpath, name), "rb") as f:
            content = f.read(64 << 10).decode("utf-8", "replace")
        parts.append(f"<h3>{html.escape(name)}</h3>"
                     f"<pre>{html.escape(content)}</pre>")
    return _page("crash", "".join(parts))


def _cover_page(mgr) -> str:
    from syzkaller_tpu.manager.cover import CoverReporter

    with mgr.serv._lock:
        pcs = list(mgr.serv.cover)
    return CoverReporter(mgr.cfg.kernel_obj).render_html(pcs)


def _syscalls_page(mgr) -> str:
    rows = "".join(
        f"<tr><td>{html.escape(c.name)}</td><td>{c.nr}</td></tr>"
        for c in mgr.target.syscalls)
    return _page("syscalls",
                 f"<table><tr><th>call</th><th>nr</th></tr>{rows}</table>")
