"""Manager HTTP UI.

Server-side-rendered pages mirroring the reference endpoint set
(reference: syz-manager/html.go:30-41): / summary, /syscalls (with
per-call corpus counts), /corpus (filterable by call), /input (one
program by sig), /crash artifacts, /report (parsed report detail),
/cover, /rawcover, /prio (the priority matrix behind ChoiceTable
sampling), /stats JSON.
"""

from __future__ import annotations

import html
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from syzkaller_tpu import telemetry
# Health is imported for its registration side effect: a manager-only
# process (no device pipeline loaded) must still expose the breaker/
# watchdog transition counters on /metrics, at zero.
import syzkaller_tpu.health  # noqa: F401


def serve_http(mgr, addr: tuple[str, int]) -> ThreadingHTTPServer:
    # Pull-style gauges sampled at scrape time; re-registering rebinds
    # the callback to THIS manager (telemetry.Registry.gauge).
    telemetry.gauge("tz_manager_corpus_size",
                    "corpus programs held by the manager",
                    fn=lambda: len(mgr.serv.corpus))
    telemetry.gauge("tz_manager_connected_fuzzers",
                    "fuzzer processes that have connected",
                    fn=lambda: len(mgr.serv.fuzzers))
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, body: str, ctype: str = "text/html") -> None:
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802
            url = urlparse(self.path)
            q = parse_qs(url.query)
            try:
                if url.path == "/":
                    self._send(_summary_page(mgr))
                elif url.path == "/stats":
                    self._send(json.dumps(mgr.stats_snapshot()),
                               "application/json")
                elif url.path == "/metrics":
                    # Prometheus text exposition of the process-wide
                    # telemetry registry (docs/observability.md), plus
                    # the fleet rollup merged from the fuzzers' poll
                    # telemetry — same names, source="fleet" label.
                    body = telemetry.render_prometheus()
                    fleet = mgr.serv.fleet_telemetry()
                    if fleet.get("sources"):
                        body += telemetry.render_prometheus_snapshot(
                            fleet, {"source": "fleet"})
                    self._send(body, "text/plain; version=0.0.4")
                elif url.path == "/api/debug/flight":
                    # On-demand flight-recorder incident payload
                    # (telemetry/flight.py): the same structure the
                    # automatic DeviceWedged/breaker-open/SIGTERM
                    # dumps write, served live for a wedge-in-progress.
                    self._send(json.dumps(
                        telemetry.FLIGHT.snapshot("on_demand")),
                        "application/json")
                elif url.path == "/api/coverage":
                    # Coverage intelligence (ISSUE 7,
                    # telemetry/coverage.py): growth curve, heat
                    # regions, per-lane attribution, drift status —
                    # local tracker plus the fleet's tz_coverage_*
                    # series from poll telemetry.
                    self._send(json.dumps(_coverage_payload(mgr)),
                               "application/json")
                elif url.path == "/api/serve":
                    # Serving plane (ISSUE 12, serve/broker.py):
                    # tenant leases, demand/queue custody, QoS
                    # credits, plus the per-tenant novelty-plane
                    # analytics when planes are wired in.
                    self._send(json.dumps(_serve_payload(mgr)),
                               "application/json")
                elif url.path == "/api/accounting":
                    # Accounting & SLO plane (ISSUE 14,
                    # telemetry/accounting.py + slo.py): the
                    # device-time ledger, the top-consumers table,
                    # and the SLO scorecard with burn rates.
                    self._send(json.dumps(_accounting_payload(mgr)),
                               "application/json")
                elif url.path == "/api/device":
                    # Device residency observatory (ISSUE 17,
                    # telemetry/hbm.py + compiles.py): the HBM buffer
                    # ledger (per-owner live bytes, headroom forecast,
                    # last reconcile) and the compile-cache build
                    # ledger per graph family.
                    self._send(json.dumps(_device_payload(mgr)),
                               "application/json")
                elif url.path == "/api/stats":
                    # Machine-readable superset of /stats: the manager
                    # rollup plus the full telemetry snapshot
                    # (histogram percentiles, transition events) and
                    # the cross-process fleet merge.
                    self._send(json.dumps({
                        "manager": mgr.stats_snapshot(),
                        "telemetry": telemetry.snapshot(),
                        "fleet": mgr.serv.fleet_telemetry(),
                    }), "application/json")
                elif url.path == "/corpus":
                    self._send(_corpus_page(mgr, q.get("call", [""])[0]))
                elif url.path == "/input":
                    self._send(_input_page(mgr, q.get("sig", [""])[0]))
                elif url.path == "/crash":
                    self._send(_crash_page(mgr, q.get("id", [""])[0]))
                elif url.path == "/report":
                    self._send(_report_page(mgr, q.get("id", [""])[0]))
                elif url.path == "/syscalls":
                    self._send(_syscalls_page(mgr))
                elif url.path == "/prio":
                    self._send(_prio_page(mgr, q.get("call", [""])[0]))
                elif url.path == "/cover":
                    self._send(_cover_page(mgr))
                elif url.path == "/rawcover":
                    with mgr.serv._lock:
                        pcs = sorted(mgr.serv.cover)
                    self._send("\n".join(f"0x{pc:x}" for pc in pcs),
                               "text/plain")
                else:
                    self.send_error(404)
            except BrokenPipeError:
                pass
            except Exception as e:
                self.send_error(500, str(e))

    srv = ThreadingHTTPServer(addr, Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


_STYLE = """<style>
body { font-family: monospace; margin: 2em; }
table { border-collapse: collapse; }
td, th { border: 1px solid #999; padding: 2px 8px; text-align: left; }
</style>"""


def _page(title: str, body: str) -> str:
    return (f"<html><head><title>{html.escape(title)}</title>{_STYLE}"
            f"</head><body><h2>{html.escape(title)}</h2>"
            f"<p><a href='/'>summary</a> | <a href='/corpus'>corpus</a> | "
            f"<a href='/syscalls'>syscalls</a> | <a href='/prio'>prio</a> | "
            f"<a href='/cover'>cover</a> | "
            f"<a href='/stats'>stats.json</a> | "
            f"<a href='/metrics'>metrics</a></p>{body}</body></html>")


def _coverage_payload(mgr) -> dict:
    """The /api/coverage body: the local tracker's snapshot plus the
    fleet's tz_coverage_* counters/gauges (poll-telemetry merge), and
    one top-level stalled flag (local OR any fleet member)."""
    cov = telemetry.COVERAGE.snapshot()
    fleet = mgr.serv.fleet_telemetry()
    fl = {}
    for kind in ("counters", "gauges"):
        for name, v in (fleet.get(kind) or {}).items():
            if name.startswith("tz_coverage_"):
                fl[name] = v
    return {
        "local": cov,
        "fleet": fl,
        "stalled": bool(cov["stalled"]
                        or fl.get("tz_coverage_stalled", 0)),
    }


def _coverage_section(mgr) -> str:
    """Summary-page rollup of the coverage intelligence plane."""
    payload = _coverage_payload(mgr)
    cov = payload["local"]
    rows = [
        ("plane occupancy", f"{cov['occupancy']}"),
        ("novelty rate (EWMA)",
         f"{cov['novelty_rate_ewma']:.3f} edges/s"),
        ("novel edges total", f"{cov['novel_edges_total']}"),
        ("last novel edge", f"{cov['last_novel_age_s']:.0f}s ago"),
        ("stalled", "YES — plateau detector latched"
         if payload["stalled"] else "no"),
        ("stalls", f"{cov['stalls']}"),
        ("drift audit", f"{cov['drift']['buckets']} buckets "
                        f"({cov['drift']['audits']} audits)"),
    ]
    for src, n in sorted((cov["attribution"]["by_source"]).items(),
                         key=lambda kv: -kv[1]):
        rows.append((f"novel via {src}", f"{n}"))
    body = "".join(f"<tr><td>{html.escape(k)}</td>"
                   f"<td>{html.escape(str(v))}</td></tr>"
                   for k, v in rows)
    return (f"<h3>Coverage intelligence</h3><table>{body}</table>"
            f"<p><a href='/api/coverage'>coverage.json</a></p>")


def _serve_payload(mgr) -> dict:
    """The /api/serve body: the broker snapshot plus per-tenant
    novelty-plane analytics (serve/plane.py) when attached."""
    payload = {"serve": mgr.serve_plane.snapshot()}
    planes = getattr(mgr, "serve_planes", None)
    if planes is not None:
        payload["planes"] = planes.analytics()
    return payload


def _serve_section(mgr) -> str:
    """Summary-page rollup of the serving plane: one row per tenant
    with its demand, queue custody, credit, and plateau verdict."""
    snap = mgr.serve_plane.snapshot()
    tenants = snap.get("tenants") or {}
    if not tenants:
        return ""
    rows = "".join(
        f"<tr><td>{html.escape(name)}</td>"
        f"<td>{t['demand_rows']}</td><td>{t['queued']}</td>"
        f"<td>{t['inflight']}</td><td>{t['credit']:.3f}</td>"
        f"<td>{'stalled' if t['stalled'] else 'ok'}</td>"
        f"<td>{t['rows_spent']}</td><td>{t['delivered']}</td></tr>"
        for name, t in sorted(tenants.items()))
    return (f"<h3>Serving plane</h3>"
            f"<table><tr><th>tenant</th><th>demand</th><th>queued</th>"
            f"<th>inflight</th><th>credit</th><th>state</th>"
            f"<th>rows</th><th>delivered</th></tr>{rows}</table>"
            f"<p>reaped {snap.get('reaped', 0)}, replays "
            f"{snap.get('replays', 0)} &middot; "
            f"<a href='/api/serve'>serve.json</a></p>")


def _accounting_payload(mgr) -> dict:
    """The /api/accounting body: ledger + top consumers + SLO
    scorecard (ISSUE 14)."""
    from syzkaller_tpu import telemetry

    telemetry.SLO.tick()
    return {"ledger": telemetry.ACCOUNTING.snapshot(),
            "top_consumers": telemetry.ACCOUNTING.top_consumers(),
            "slo": telemetry.SLO.snapshot()}


def _accounting_section(mgr) -> str:
    """Summary-page scorecard: one row per SLO objective (value vs
    target, fast/slow burn, state) and the ledger's top device-time
    consumers per dimension."""
    from syzkaller_tpu import telemetry

    slo = telemetry.SLO.snapshot()
    top = telemetry.ACCOUNTING.top_consumers(5)
    srows = "".join(
        f"<tr><td>{html.escape(o['name'])}</td>"
        f"<td>{o['kind']}</td>"
        f"<td>{o['value'] if o['value'] is not None else '—'}</td>"
        f"<td>{o['target']:g}</td>"
        f"<td>{o['fast_burn']:.2f}x</td>"
        f"<td>{o['slow_burn']:.2f}x</td>"
        f"<td>{'BURNING' if o['burning'] else 'ok'}</td></tr>"
        for o in slo.get("objectives") or [])
    crows = ""
    for dim in ("tenant", "lane", "shard"):
        for row in top.get(dim) or []:
            crows += (f"<tr><td>{dim}</td>"
                      f"<td>{html.escape(str(row['key']))}</td>"
                      f"<td>{row['device_ms']:.1f}</td>"
                      f"<td>{row['share']:.1%}</td>"
                      f"<td>{row['yield']:g}</td></tr>")
    total = top.get("total_device_ms", 0)
    return (f"<h3>Accounting &amp; SLOs</h3>"
            f"<table><tr><th>objective</th><th>kind</th><th>value</th>"
            f"<th>target</th><th>fast burn</th><th>slow burn</th>"
            f"<th>state</th></tr>{srows}</table>"
            f"<table><tr><th>dim</th><th>key</th><th>device ms</th>"
            f"<th>share</th><th>yield</th></tr>{crows}</table>"
            f"<p>{total:.1f} device-ms metered &middot; "
            f"<a href='/api/accounting'>accounting.json</a></p>")


def _device_payload(mgr) -> dict:
    """The /api/device body: the HBM buffer ledger and the compile
    observatory (ISSUE 17).  A fresh reconcile is NOT run here — the
    payload reports the last audit-cadence pass so a dashboard poll
    never syncs the device."""
    from syzkaller_tpu import telemetry

    return {"hbm": telemetry.HBM.snapshot(),
            "compiles": telemetry.COMPILES.snapshot()}


def _device_section(mgr) -> str:
    """Summary-page residency block: one row per registered buffer
    group (owner/kind@device, MB), the capacity/headroom line with
    the last reconcile verdict, and the per-family compile ledger."""
    from syzkaller_tpu import telemetry

    hbm = telemetry.HBM.snapshot()
    comp = telemetry.COMPILES.snapshot()
    brows = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{v / 1e6:.1f}</td></tr>"
        for k, v in (hbm.get("buffers") or {}).items())
    rec = hbm.get("last_reconcile") or {}
    recline = ("never reconciled" if not rec else
               f"last reconcile drift {rec.get('drift_bytes', 0)} B "
               f"over {rec.get('entries', 0)} entries "
               f"({rec.get('seconds', 0) * 1e3:.1f} ms)")
    grows = "".join(
        f"<tr><td>{html.escape(g)}</td><td>{f['builds']}</td>"
        f"<td>{f['shapes']}</td></tr>"
        for g, f in (comp.get("graphs") or {}).items())
    return (f"<h3>Device residency</h3>"
            f"<table><tr><th>buffer (owner/kind@device)</th>"
            f"<th>MB</th></tr>{brows}</table>"
            f"<p>{hbm.get('device_resident_bytes', 0) / 1e6:.1f} MB "
            f"device-resident of "
            f"{hbm.get('capacity_bytes', 0) / 1e9:.1f} GB "
            f"(headroom {hbm.get('headroom_bytes', 0) / 1e9:.2f} GB) "
            f"&middot; {html.escape(recline)}</p>"
            f"<table><tr><th>graph</th><th>builds</th><th>shapes</th>"
            f"</tr>{grows}</table>"
            f"<p>{comp.get('total_builds', 0)} builds, "
            f"{comp.get('storms', 0)} storms &middot; "
            f"<a href='/api/device'>device.json</a></p>")


def _call_name(prog_line: str) -> str:
    """First call name of a serialized program line ('r0 = open(...)'
    or 'open(...)')."""
    line = prog_line.split("\n", 1)[0]
    if "=" in line.split("(", 1)[0]:
        line = line.split("=", 1)[1].lstrip()
    return line.split("(", 1)[0].strip()


def _summary_page(mgr) -> str:
    s = mgr.stats_snapshot()
    rows = "".join(f"<tr><td>{html.escape(str(k))}</td>"
                   f"<td>{html.escape(str(v))}</td></tr>"
                   for k, v in sorted(s.items()) if not isinstance(v, dict))
    stats = s.get("stats", {})
    rows += "".join(f"<tr><td>{html.escape(k)}</td>"
                    f"<td>{v}</td></tr>" for k, v in sorted(stats.items())
                    if not k.startswith("device "))
    # Device-engine health: the breaker/watchdog transition counters
    # the fuzzers sync up (demotions, breaker opens, ring rebuilds,
    # wedges) get their own section — this is the page an operator
    # checks when the flagship number looks off (docs/health.md).
    health = ""
    dev = s.get("device_health") or {}
    if dev:
        hrows = "".join(f"<tr><td>{html.escape(k)}</td><td>{v}</td></tr>"
                        for k, v in sorted(dev.items()))
        health = (f"<h3>Device engine health</h3>"
                  f"<table>{hrows}</table>")
    # Control plane (ISSUE 9): session epoch, admission-control
    # state, lease ages, candidate custody — the fleet-resilience
    # block an operator checks after a fuzzer VM dies or the device
    # side degrades (docs/health.md "control-plane sessions").
    control = ""
    cp = s.get("control_plane") or {}
    if cp:
        crows = [
            ("session epoch", cp.get("epoch", "")),
            ("admission control", cp.get("throttle", "closed")),
            ("live fuzzers", cp.get("live_fuzzers", 0)),
            ("reaped leases", cp.get("reaped_fuzzers", 0)),
            ("replayed from reply cache", cp.get("reply_replays", 0)),
            ("candidates in custody",
             cp.get("outstanding_candidates", 0)),
            ("lease", f"{cp.get('lease_s', 0):.0f}s"),
        ]
        for fname, st in sorted((cp.get("fuzzers") or {}).items()):
            idle = st.get("idle_s")
            crows.append((
                f"fuzzer {fname}",
                f"idle {idle:.0f}s, device {st.get('device_state')}, "
                f"{st.get('inputs_queued', 0)} inputs queued, "
                f"{st.get('candidates_held', 0)} candidates held"
                if idle is not None else "never polled"))
        cbody = "".join(f"<tr><td>{html.escape(str(k))}</td>"
                        f"<td>{html.escape(str(v))}</td></tr>"
                        for k, v in crows)
        control = f"<h3>Control plane</h3><table>{cbody}</table>"
    crashes = ""
    with mgr._lock:
        items = sorted(mgr.crash_types.items(),
                       key=lambda kv: -kv[1].count)
    for title, entry in items:
        from syzkaller_tpu.utils.hashsig import hash_string

        sig = hash_string(title.encode())
        crashes += (f"<tr><td><a href='/crash?id={sig}'>"
                    f"{html.escape(title)}</a></td><td>{entry.count}</td>"
                    f"<td>{'yes' if entry.repro_done else ''}</td>"
                    f"<td><a href='/report?id={sig}'>report</a></td></tr>")
    body = (f"<table>{rows}</table>{health}{control}"
            f"{_serve_section(mgr)}"
            f"{_coverage_section(mgr)}"
            f"{_accounting_section(mgr)}"
            f"{_device_section(mgr)}"
            f"<h3>Crashes</h3>"
            f"<table><tr><th>title</th><th>count</th><th>repro</th>"
            f"<th></th></tr>{crashes}</table>")
    return _page(f"{mgr.cfg.name} syz-manager", body)


def _prog_calls(text: str) -> list[str]:
    return [_call_name(line) for line in text.splitlines()
            if line.strip() and not line.startswith("#")]


def _corpus_page(mgr, call_filter: str = "") -> str:
    # copy under the lock, render outside it — the render escapes full
    # program texts and must not stall fuzzer RPCs
    with mgr.serv._lock:
        items = list(mgr.serv.corpus.items())
    rows = ""
    shown = 0
    for key, inp in items:
        text = inp.get("prog", "")
        if call_filter and call_filter not in _prog_calls(text):
            continue
        shown += 1
        if shown > 1000:
            break
        sig_len = len(inp.get("signal", [[], []])[0])
        rows += (f"<tr><td><a href='/input?sig={key}'>{key[:16]}</a></td>"
                 f"<td>{sig_len}</td>"
                 f"<td><pre>{html.escape(text)}</pre></td></tr>")
    title = f"corpus ({call_filter})" if call_filter else "corpus"
    return _page(title, f"<table><tr><th>sig</th><th>signal</th>"
                        f"<th>program</th></tr>{rows}</table>")


def _input_page(mgr, sig: str) -> str:
    """One corpus program by hash (reference: html.go /input)."""
    with mgr.serv._lock:
        inp = mgr.serv.corpus.get(sig)
    if inp is None:
        return _page("input", "not found")
    sig_elems = inp.get("signal", [[], []])[0]
    body = (f"<p>signal: {len(sig_elems)}</p>"
            f"<pre>{html.escape(inp.get('prog', ''))}</pre>")
    return _page(f"input {sig[:16]}", body)


def _crash_dir(mgr, crash_id: str):
    """Validated crash artifact dir for a hex title-hash id, or None.
    The hex check is the path-traversal guard for the query param."""
    if not crash_id or any(c not in "0123456789abcdef" for c in crash_id):
        return None
    dirpath = os.path.join(mgr.crashdir, crash_id)
    return dirpath if os.path.isdir(dirpath) else None


def _read_capped(dirpath: str, name: str, cap: int = 128 << 10) -> str:
    try:
        with open(os.path.join(dirpath, name), "rb") as f:
            return f.read(cap).decode("utf-8", "replace")
    except OSError:
        return ""


def _crash_page(mgr, crash_id: str) -> str:
    dirpath = _crash_dir(mgr, crash_id)
    if dirpath is None:
        return _page("crash", "not found")
    parts = []
    for name in sorted(os.listdir(dirpath)):
        content = _read_capped(dirpath, name, 64 << 10)
        parts.append(f"<h3>{html.escape(name)}</h3>"
                     f"<pre>{html.escape(content)}</pre>")
    return _page("crash", "".join(parts))


def _cover_page(mgr) -> str:
    from syzkaller_tpu.manager.cover import CoverReporter

    with mgr.serv._lock:
        pcs = list(mgr.serv.cover)
    return CoverReporter(mgr.cfg.kernel_obj).render_html(pcs)


def _syscalls_page(mgr) -> str:
    """Per-call table with corpus input counts (reference html.go
    /syscalls shows per-call inputs/cover)."""
    counts: dict[str, int] = {}
    with mgr.serv._lock:
        texts = [inp.get("prog", "") for inp in mgr.serv.corpus.values()]
    for text in texts:
        for name in set(_prog_calls(text)):
            counts[name] = counts.get(name, 0) + 1
    rows = "".join(
        f"<tr><td><a href='/corpus?call={html.escape(c.name)}'>"
        f"{html.escape(c.name)}</a></td><td>{c.nr}</td>"
        f"<td>{counts.get(c.name, 0)}</td>"
        f"<td><a href='/prio?call={html.escape(c.name)}'>prio</a></td>"
        f"</tr>"
        for c in mgr.target.syscalls)
    return _page("syscalls",
                 f"<table><tr><th>call</th><th>nr</th><th>inputs</th>"
                 f"<th></th></tr>{rows}</table>")


def _prio_page(mgr, call: str) -> str:
    """The static x dynamic priority matrix driving ChoiceTable
    sampling (reference: html.go /prio, prog/prio.go)."""
    names = [c.name for c in mgr.target.syscalls]
    prios = mgr.serv.prios
    if not prios:
        return _page("prio", "no priorities")
    if call:
        try:
            i = names.index(call)
        except ValueError:
            return _page("prio", "unknown call")
        if i >= len(prios):
            return _page("prio", "no priorities for call")
        row = prios[i]
        pairs = sorted(zip(names, row), key=lambda kv: -kv[1])[:50]
        rows = "".join(
            f"<tr><td>{html.escape(n)}</td><td>{p:.3f}</td></tr>"
            for n, p in pairs)
        return _page(f"prio: {call}",
                     f"<table><tr><th>target call</th><th>prio</th></tr>"
                     f"{rows}</table>")
    # overview: each call's top-3 priority partners
    rows = ""
    for i, name in enumerate(names[:400]):
        row = prios[i] if i < len(prios) else []
        top = sorted(zip(names, row), key=lambda kv: -kv[1])[:3]
        partners = ", ".join(f"{n} {p:.2f}" for n, p in top)
        rows += (f"<tr><td><a href='/prio?call={html.escape(name)}'>"
                 f"{html.escape(name)}</a></td>"
                 f"<td>{html.escape(partners)}</td></tr>")
    return _page("prio", f"<table><tr><th>call</th><th>top partners"
                         f"</th></tr>{rows}</table>")


def _report_page(mgr, crash_id: str) -> str:
    """Parsed report detail for one crash: title, report text, log
    tail (reference: html.go /report)."""
    dirpath = _crash_dir(mgr, crash_id)
    if dirpath is None:
        return _page("report", "not found")
    names = sorted(os.listdir(dirpath))

    def read(name):
        return _read_capped(dirpath, name)

    title = read("description").strip()
    reports = [n for n in names if n.startswith("report")]
    logs = [n for n in names if n.startswith("log")]
    body = f"<p><b>{html.escape(title)}</b></p>"
    if reports:
        body += f"<h3>report</h3><pre>{html.escape(read(reports[-1]))}</pre>"
    if logs:
        tail = read(logs[-1])[-16384:]
        body += f"<h3>log tail</h3><pre>{html.escape(tail)}</pre>"
    repro = [n for n in names if n.startswith("repro")]
    for n in repro:
        body += f"<h3>{html.escape(n)}</h3><pre>{html.escape(read(n))}</pre>"
    return _page("report", body)
