"""The manager daemon: host-side orchestration.

Loads config + corpus DB, serves the fuzzer RPC, runs the vmLoop that
interleaves fuzzing instances with repro jobs, saves/dedups crashes,
minimizes the corpus, snapshots bench stats, and serves the HTTP UI
(reference: syz-manager/manager.go:44-1305).

Phase machine (manager.go:92-103): init → loaded-corpus →
triaged-corpus → queried-hub → triaged-hub; repro is only allowed
once the local corpus is triaged so VMs aren't stolen from triage.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from syzkaller_tpu.db import open_db
from syzkaller_tpu.manager.mgrconfig import Config, parse_addr
from syzkaller_tpu.manager.rpcserver import ManagerRPC
from syzkaller_tpu.models.encoding import ParseError, deserialize_prog
from syzkaller_tpu.models.prio import calculate_priorities
from syzkaller_tpu.models.target import get_target
from syzkaller_tpu.report import Report, get_reporter
from syzkaller_tpu.rpc import RPCServer
from syzkaller_tpu.rpc.types import RPCCandidate, RPCInput
from syzkaller_tpu.signal import Signal, minimize_corpus
from syzkaller_tpu.utils import log
from syzkaller_tpu.utils.hashsig import hash_string

# Corpus DB format version; bumping triggers re-minimize/re-smash of
# the whole corpus on upgrade (reference: manager.go:105,192-207).
CURRENT_DB_VERSION = 1

PHASE_INIT = 0
PHASE_LOADED_CORPUS = 1
PHASE_TRIAGED_CORPUS = 2
PHASE_QUERIED_HUB = 3
PHASE_TRIAGED_HUB = 4

MAX_CRASH_LOGS = 100  # per-title artifact cap (manager.go:659-691)
MAX_REPRO_VMS = 4  # VMs handed to one repro job (manager.go:452)


@dataclass
class Crash:
    title: str
    report: Report
    vm_index: int
    first: bool


@dataclass
class CrashEntry:
    count: int = 0
    repro_attempted: bool = False
    repro_done: bool = False


class Manager:
    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.target = get_target(cfg.target_os, cfg.target_arch)
        os.makedirs(cfg.workdir, exist_ok=True)
        os.makedirs(self.crashdir, exist_ok=True)
        self.start_time = time.time()
        self.first_connect = 0.0
        self.phase = PHASE_INIT
        self._lock = threading.Lock()
        self.stats_extra = {"crashes": 0, "repro": 0, "vm restarts": 0}
        self.crash_types: dict[str, CrashEntry] = {}
        self.reporter = get_reporter(
            cfg.target_os, kernel_obj=cfg.kernel_obj,
            ignores=cfg.ignores, suppressions=cfg.suppressions)
        self.stop_ev = threading.Event()
        self.pending_repro: list[tuple[str, bytes]] = []  # (title, log)
        self.hub_repros: list[str] = []  # repro prog texts for the hub

        # RPC service + corpus
        prios = calculate_priorities(self.target, [])
        self.serv = ManagerRPC(
            prios=[list(map(float, row)) for row in prios],
            on_new_input=self._on_new_input)
        # Durable state (ISSUE 13): checkpoint + WAL under
        # workdir/durable.  Opening the store runs recovery (checksum
        # validation, torn-tail truncation, WAL replay) BEFORE the
        # corpus load so a warm image can skip the full re-triage.
        # TZ_CKPT_INTERVAL_S=0 disables the whole plane (cold starts
        # only, exactly the pre-ISSUE-13 behavior).
        from syzkaller_tpu.durable import DurableStore

        self.durable = DurableStore.open(cfg.workdir)
        recovered = self.durable.recovered \
            if self.durable is not None else None
        self.corpus_db = open_db(os.path.join(cfg.workdir, "corpus.db"),
                                 version=CURRENT_DB_VERSION)
        if recovered and recovered.get("control"):
            self._warm_restore(recovered)
        else:
            self._load_corpus()
        self.rpc_server = RPCServer(parse_addr(cfg.rpc))
        self.rpc_server.register("Manager", self.serv)
        # Serving plane (ISSUE 12): the multi-tenant request broker
        # rides the same transport under the "Serve" name; its
        # per-tenant admission quotas scale off the Manager throttle.
        from syzkaller_tpu.serve.broker import ServePlane

        self.serve_plane = ServePlane(
            throttle_fn=self.serv.throttle_state)
        self.rpc_server.register("Serve", self.serve_plane)
        if self.durable is not None:
            from syzkaller_tpu import telemetry

            if recovered and recovered.get("serve"):
                self.serve_plane.durable_restore(recovered["serve"])
            if recovered and recovered.get("coverage"):
                telemetry.COVERAGE.restore_state(recovered["coverage"])
            # Accounting & SLO plane (ISSUE 14): per-tenant cumulative
            # device-ms survives the restart, and a burning objective
            # stays latched instead of false-firing "clear".
            if recovered and recovered.get("accounting"):
                telemetry.ACCOUNTING.restore_state(
                    recovered["accounting"])
            if recovered and recovered.get("slo"):
                telemetry.SLO.restore_state(recovered["slo"])
            # Journal hooks + checkpoint providers, wired only after
            # every restore so recovery itself never journals.
            self.serv.durable = self.durable
            self.serve_plane.durable = self.durable
            telemetry.COVERAGE.journal = self.durable.journal
            self.durable.register("control", self.serv.durable_export)
            self.durable.register("serve",
                                  self.serve_plane.durable_provider)
            self.durable.register(
                "coverage",
                lambda: (telemetry.COVERAGE.export_state(), b""))
            self.durable.register(
                "accounting",
                lambda: (telemetry.ACCOUNTING.export_state(), b""))
            self.durable.register(
                "slo", lambda: (telemetry.SLO.export_state(), b""))
            self.durable.start()
        self.rpc_server.serve_in_background()
        self.rpc_addr = self.rpc_server.addr

        self.http_server = None
        if cfg.http:
            from syzkaller_tpu.manager.html import serve_http

            self.http_server = serve_http(self, parse_addr(cfg.http))

        self.hub = None
        if cfg.hub_client:
            try:
                from syzkaller_tpu.manager.hubsync import HubSyncer
            except ImportError:
                log.logf(0, "hub sync unavailable; running without hub")
            else:
                self.hub = HubSyncer(self)
                self.hub.start()

        self.dash = None
        if cfg.dashboard_client:
            from syzkaller_tpu.dashboard.dashapi import DashClient

            self.dash = DashClient(cfg.dashboard_addr,
                                   cfg.dashboard_client,
                                   cfg.dashboard_key)

        self.bench_file = None
        self._bench_thread = None

    # -- corpus persistence ----------------------------------------------

    @property
    def crashdir(self) -> str:
        return os.path.join(self.cfg.workdir, "crashes")

    def _load_corpus(self) -> None:
        """Deserialize every DB record; broken/disabled programs are
        dropped (with the same upgrade policy hooks as
        manager.go:185-243)."""
        minimized, smashed = True, True
        if self.corpus_db.version < CURRENT_DB_VERSION:
            minimized = False  # re-minimize entire corpus on upgrade
            self.corpus_db.bump_version(CURRENT_DB_VERSION)
        candidates = []
        broken = 0
        for key, rec in list(self.corpus_db.records.items()):
            try:
                deserialize_prog(self.target, rec.val)
            except ParseError:
                self.corpus_db.delete(key)
                broken += 1
                continue
            candidates.append(RPCCandidate(
                prog=rec.val.decode(), minimized=minimized,
                smashed=smashed))
        self.corpus_db.flush()
        if broken:
            log.logf(0, "dropped %d broken corpus programs", broken)
        self.serv.add_candidates(candidates)
        log.logf(0, "loaded %d corpus programs", len(candidates))
        self.phase = PHASE_LOADED_CORPUS

    def _warm_restore(self, recovered) -> None:
        """Warm restart (ISSUE 13): install the recovered control
        plane instead of re-queueing the whole corpus for triage,
        then reconcile against corpus.db in both directions — DB
        records the image never saw become cold-triage candidates
        (just the delta, not the corpus), and recovered corpus
        entries missing from the DB (a corpus_add journaled after the
        last db flush the crash outran) are re-persisted."""
        self.serv.durable_restore(recovered["control"])
        known = set(self.serv.corpus)
        with self.serv._lock:
            known.update(
                hash_string((c.get("prog") or "").encode())
                for c in self.serv.candidates)
        delta, broken = [], 0
        for key, rec in list(self.corpus_db.records.items()):
            if key in known:
                continue
            try:
                deserialize_prog(self.target, rec.val)
            except ParseError:
                self.corpus_db.delete(key)
                broken += 1
                continue
            delta.append(RPCCandidate(prog=rec.val.decode(),
                                      minimized=True, smashed=True))
        repersisted = 0
        for key, art in list(self.serv.corpus.items()):
            if key not in self.corpus_db.records:
                prog = (art.get("prog") or "").encode()
                if prog:
                    self.corpus_db.save(key, prog, 0)
                    repersisted += 1
        self.corpus_db.flush()
        if delta:
            self.serv.add_candidates(delta)
        log.logf(0, "warm restart: %d corpus programs restored, %d "
                 "candidates queued (%d db-only), %d re-persisted, "
                 "%d broken dropped",
                 len(self.serv.corpus), len(self.serv.candidates),
                 len(delta), repersisted, broken)
        self.phase = PHASE_LOADED_CORPUS

    def _on_new_input(self, inp: RPCInput) -> bool:
        data = inp.prog.encode()
        self.corpus_db.save(hash_string(data), data, 0)
        self.corpus_db.flush()
        return True

    # -- crash handling ---------------------------------------------------

    def save_crash(self, rep: Report, vm_index: int = 0) -> Crash:
        """Dedup by title hash, persist ≤MAX_CRASH_LOGS logs/reports
        per title (reference: manager.go:622-694)."""
        title = rep.title or "unknown crash"
        with self._lock:
            self.stats_extra["crashes"] += 1
            entry = self.crash_types.get(title)
            first = entry is None
            if entry is None:
                entry = self.crash_types[title] = CrashEntry()
            entry.count += 1
        sig = hash_string(title.encode())
        dirpath = os.path.join(self.crashdir, sig)
        os.makedirs(dirpath, exist_ok=True)
        desc_path = os.path.join(dirpath, "description")
        if not os.path.exists(desc_path):
            with open(desc_path, "w") as f:
                f.write(title + "\n")
        # round-robin slot under the log cap
        for i in range(MAX_CRASH_LOGS):
            logp = os.path.join(dirpath, f"log{i}")
            if not os.path.exists(logp):
                with open(logp, "wb") as f:
                    f.write(rep.output)
                if rep.report:
                    with open(os.path.join(dirpath, f"report{i}"),
                              "wb") as f:
                        f.write(rep.report)
                break
        log.logf(0, "crash: %s (%s)", title,
                 "new" if first else f"seen {entry.count}x")
        if self.dash is not None:
            try:
                self.dash.report_crash(
                    manager=self.cfg.name, title=title,
                    log=rep.output.decode("utf-8", "replace")[-65536:],
                    report=rep.report.decode("utf-8", "replace")[-65536:])
            except Exception as e:
                log.logf(0, "dashboard crash report failed: %s", e)
        return Crash(title=title, report=rep, vm_index=vm_index,
                     first=first)

    def need_repro(self, crash: Crash) -> bool:
        """(reference: manager.go:698-734)"""
        if not self.cfg.reproduce or crash.report.corrupted \
                or crash.report.suppressed:
            return False
        if crash.title in ("no output from test machine",
                           "lost connection to test machine",
                           "test machine is not executing programs"):
            return False
        with self._lock:
            entry = self.crash_types[crash.title]
            if entry.repro_attempted or entry.repro_done:
                return False
            entry.repro_attempted = True
        return True

    def save_repro(self, title: str, prog_text: bytes,
                   c_src: Optional[bytes], opts_desc: str) -> None:
        """(reference: manager.go:736-809)"""
        sig = hash_string(title.encode())
        dirpath = os.path.join(self.crashdir, sig)
        os.makedirs(dirpath, exist_ok=True)
        with open(os.path.join(dirpath, "repro.prog"), "wb") as f:
            f.write(opts_desc.encode() + b"\n" + prog_text)
        if c_src:
            with open(os.path.join(dirpath, "repro.c"), "wb") as f:
                f.write(c_src)
        with self._lock:
            self.stats_extra["repro"] += 1
            self.crash_types.setdefault(title, CrashEntry()).repro_done = True
            # queue the repro program for hub fan-out (hubsync drains
            # with ack-after-send semantics)
            self.hub_repros.append(prog_text.decode("utf-8", "replace"))

    def peek_hub_repros(self, limit: int = 100) -> list[str]:
        with self._lock:
            return self.hub_repros[:limit]

    def ack_hub_repros(self, n: int) -> None:
        with self._lock:
            del self.hub_repros[:n]

    # -- corpus minimization ----------------------------------------------

    def minimize_corpus(self) -> None:
        """Signal set-cover over the in-memory corpus, dropping DB
        records not in the cover (reference: manager.go:831-860)."""
        with self.serv._lock:
            items = [(Signal.deserialize(*RPCInput.from_dict(v).signal), k)
                     for k, v in self.serv.corpus.items()]
            keep = set(minimize_corpus(items))
            dropped = [k for k in self.serv.corpus if k not in keep]
            for k in dropped:
                del self.serv.corpus[k]
        for k in dropped:
            self.corpus_db.delete(k)
        self.corpus_db.flush()
        if dropped:
            log.logf(0, "corpus minimization: dropped %d of %d",
                     len(dropped), len(dropped) + len(keep))

    # -- stats / bench -----------------------------------------------------

    def stats_snapshot(self) -> dict:
        s = self.serv.snapshot()
        with self._lock:
            s.update(self.stats_extra)
        s["uptime"] = int(time.time() - self.start_time)
        s["fuzzing_time_s"] = int(time.time() - self.first_connect) \
            if self.first_connect else 0
        s["triaged"] = self.serv.triaged_candidates
        # Device-engine health rollup (fed by the fuzzers' breaker/
        # watchdog transition counters, fuzzer/proc.py
        # _sync_health_stats): its own block so the HTTP status page
        # and the bench snapshots can show engine health at a glance.
        s["device_health"] = {
            k[len("device "):]: v
            for k, v in (s.get("stats") or {}).items()
            if k.startswith("device ")}
        # Coverage status flag (ISSUE 7): the plateau detector's
        # verdict — local tracker OR any fleet member's polled
        # tz_coverage_stalled gauge — so "is it still learning?" is
        # answerable from the status page without a metrics scrape.
        from syzkaller_tpu import telemetry

        fleet = self.serv.fleet_telemetry()
        s["coverage_stalled"] = bool(
            telemetry.COVERAGE.stalled()
            or (fleet.get("gauges") or {}).get(
                "tz_coverage_stalled", 0))
        # Control-plane rollup (ISSUE 9): session epoch, lease/reap
        # counts, admission-control state, per-fuzzer custody — the
        # status page's "is the fleet healthy" block.
        s["control_plane"] = self.serv.control_snapshot()
        # Serving-plane rollup (ISSUE 12): tenant leases, demand,
        # queue custody, credits — the /api/serve body verbatim.
        s["serve"] = self.serve_plane.snapshot()
        # Accounting & SLO scorecard (ISSUE 14).  The stats path also
        # drives the SLO cadence on manager-only deployments (no
        # triage flush leader in-process); tick() self-rate-limits.
        telemetry.SLO.tick()
        s["accounting"] = telemetry.ACCOUNTING.snapshot()
        s["slo"] = telemetry.SLO.snapshot()
        return s

    def start_bench(self, path: str, period_s: float = 60.0) -> None:
        """Minutely JSON stat snapshots, append-only — the input to
        the benchcmp tool (reference: manager.go:299-333)."""
        self.bench_file = path

        def loop():
            while not self.stop_ev.wait(period_s):
                snap = self.stats_snapshot()
                snap["ts"] = int(time.time())
                with open(path, "a") as f:
                    f.write(json.dumps(snap) + "\n")

        self._bench_thread = threading.Thread(target=loop, daemon=True)
        self._bench_thread.start()

    # -- vm loop -----------------------------------------------------------

    def vm_loop(self, fuzzer_cmd_fn, max_iterations: int = 1 << 62,
                instance_timeout_s: float = 3600.0) -> None:
        """Boot instances, run the fuzzer in them, monitor consoles,
        save crashes, schedule repros (reference: manager.go:373-534).

        fuzzer_cmd_fn(inst, index) -> shell command to start the
        fuzzer inside the instance (after binaries are copied).
        """
        from syzkaller_tpu.vm.vm import create_pool, monitor_execution
        from syzkaller_tpu.vm.vmimpl import BootError

        pool = create_pool(self.cfg)
        n = pool.count()
        iteration = 0

        def run_instance(index: int) -> None:
            try:
                inst = pool.create(index)
            except BootError as e:
                log.logf(0, "VM %d boot failed: %s", index, e)
                time.sleep(10)
                return
            try:
                cmd = fuzzer_cmd_fn(inst, index)
                stop = threading.Event()
                stream = inst.run(instance_timeout_s, stop, cmd)
                if not self.first_connect:
                    self.first_connect = time.time()
                res = monitor_execution(stream, self.reporter)
                if res.report is not None:
                    crash = self.save_crash(res.report, vm_index=index)
                    if self.need_repro(crash):
                        with self._lock:
                            self.pending_repro.append(
                                (crash.title, res.output))
                stop.set()
            finally:
                inst.close()
                with self._lock:
                    self.stats_extra["vm restarts"] += 1

        threads: list[Optional[threading.Thread]] = [None] * n
        while not self.stop_ev.is_set() and iteration < max_iterations:
            for i in range(n):
                t = threads[i]
                if (t is None or not t.is_alive()) \
                        and iteration < max_iterations:
                    iteration += 1
                    threads[i] = threading.Thread(
                        target=run_instance, args=(i,), daemon=True)
                    threads[i].start()
            self.update_phase()
            # Lease maintenance: sessioned RPCs reap opportunistically,
            # but a fleet that stops calling entirely still needs its
            # dead leases collected (and their work requeued).
            self.serv.reap_expired()
            self.serve_plane.reap_expired()
            self._maybe_run_repro(fuzzer_cmd_fn)
            self.stop_ev.wait(1.0)
        for t in threads:
            if t is not None:
                t.join(timeout=10)

    def _maybe_run_repro(self, fuzzer_cmd_fn) -> None:
        """Kick one pending repro job (reference: manager.go:452-491;
        runs on its own thread with a private VM budget)."""
        with self._lock:
            if not self.pending_repro or self.phase < PHASE_TRIAGED_CORPUS:
                return
            title, crash_log = self.pending_repro.pop(0)

        def job():
            try:
                from syzkaller_tpu.repro import repro as repro_mod

                result = repro_mod.run_from_manager(self, title, crash_log)
                if result is not None:
                    self.save_repro(title, result.prog_text,
                                    result.c_src, result.opts_desc)
            except Exception as e:
                log.logf(0, "repro of %r failed: %s", title, e)

        threading.Thread(target=job, daemon=True).start()

    def update_phase(self) -> None:
        """Advance the phase machine as triage drains
        (reference: manager.go:1027-1060 Poll-side phase logic)."""
        if self.phase == PHASE_LOADED_CORPUS \
                and self.serv.candidate_backlog() == 0:
            self.phase = PHASE_TRIAGED_CORPUS
            self.minimize_corpus()
            log.logf(0, "triaged corpus")
        if self.phase == PHASE_TRIAGED_CORPUS and self.hub is None:
            self.phase = PHASE_TRIAGED_HUB

    def shutdown(self) -> None:
        self.stop_ev.set()
        self.rpc_server.close()
        if self.http_server is not None:
            self.http_server.shutdown()
        if self.durable is not None:
            from syzkaller_tpu import telemetry

            # Detach the process-global coverage hook before releasing
            # the WAL handle: the tracker outlives this manager.
            if telemetry.COVERAGE.journal == self.durable.journal:
                telemetry.COVERAGE.journal = None
            # Final checkpoint + WAL reset: a clean shutdown leaves a
            # complete image, so the next start is warm by default.
            self.durable.close()
        self.corpus_db.flush()
