"""Manager-side RPC service: the fuzzer-facing control plane.

Implements Manager.Connect/Check/NewInput/Poll over the rpc transport
(reference: syz-manager/manager.go:862-1081).  Shared mutable state
(corpus, signal, candidates, per-fuzzer queues) lives here under one
lock; the Manager object wires in persistence and crash handling via
callbacks so this service stays testable standalone.

The fleet-resilience layer (docs/health.md "control-plane sessions"):

  * Connect mints a (session-epoch, fuzzer-lease) pair.  Mutating
    calls (Poll/NewInput) carry (name, epoch, seq, ack_seq); a
    bounded per-fuzzer reply cache replays duplicate seqs so the
    client may retry after a completed send without double-applying
    stats or corpus inserts.  A stale epoch or reaped lease answers
    ReconnectRequired, driving the fuzzer's full re-Connect resync.
  * Leases past TZ_FUZZER_LEASE_S are reaped opportunistically on
    every sessioned call: the dead fuzzer's undelivered inputs and
    max-signal delta go to the survivors (receivers dedup corpus
    inserts by program hash, so redistribution is idempotent) and its
    unfinished candidates return to the candidate queue — replacing
    the old blind 2x duplication in add_candidates with lease-tracked
    reissue.
  * Candidate custody is a three-stage ledger per fuzzer: issued
    batches sit in `inflight` keyed by the reply seq until the
    client's ack_seq confirms delivery, then in `owned` until the
    drained "exec candidate" stat counts them executed.  A reply the
    client never processed (ack_seq skipped the seq) is requeued, so
    candidates survive lost replies, fuzzer death, and retries alike.
  * Poll replies carry a throttle hint from the breaker-driven
    admission controller: the worst device breaker state across the
    fleet (each fuzzer reports its own in PollArgs.device_state, plus
    an optional manager-local breaker) shrinks the candidate
    allotment and stretches the poll cadence while a chip is
    degraded.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from syzkaller_tpu import telemetry
from syzkaller_tpu.health.envsafe import env_float, env_int
from syzkaller_tpu.health.faultinject import FaultInjected, fault_point
from syzkaller_tpu.rpc.replycache import ReplyCache
from syzkaller_tpu.rpc.rpc import ReconnectRequired
from syzkaller_tpu.rpc.types import RPCCandidate, RPCInput
from syzkaller_tpu.signal import Signal
from syzkaller_tpu.utils import log
from syzkaller_tpu.utils.hashsig import hash_string

#: Admission-control tiers (docs/health.md): breaker state → per-poll
#: candidate allotment and poll-cadence stretch.  "open" still hands
#: out a trickle so a recovering fleet has probe work.
THROTTLE_QUOTA = {"closed": 100, "half_open": 25, "open": 10}
THROTTLE_POLL_MULT = {"closed": 1.0, "half_open": 2.0, "open": 4.0}
_STATE_LEVEL = {"closed": 0, "half_open": 1, "open": 2}
#: Reaped-fuzzer reply caches kept around (bounded) so a slow retry
#: of an already-applied seq replays instead of double-applying.
_MAX_TOMBSTONES = 64
#: The drained-stats key that acks candidate executions
#: (fuzzer.py STAT_NAMES[Stat.CANDIDATE]).
_CANDIDATE_STAT = "exec candidate"

_M_REPLAYS = telemetry.counter(
    "tz_manager_reply_replays_total",
    "duplicate (epoch, seq) calls answered from the reply cache")
_M_STALE = telemetry.counter(
    "tz_manager_stale_sessions_total",
    "calls answered ReconnectRequired (stale epoch or reaped lease)")
_M_REAPED = telemetry.counter(
    "tz_manager_leases_reaped_total",
    "fuzzer leases reaped after TZ_FUZZER_LEASE_S without a poll")
_M_INPUTS_DROPPED = telemetry.counter(
    "tz_manager_inputs_dropped_total",
    "pending per-fuzzer inputs trimmed by the queue cap (drop-oldest)")
_M_INPUTS_REDIST = telemetry.counter(
    "tz_manager_inputs_redistributed_total",
    "reaped fuzzers' undelivered inputs requeued to survivors")
_M_CAND_REISSUED = telemetry.counter(
    "tz_manager_candidates_reissued_total",
    "issued candidates returned to the queue (lost reply or reaped "
    "lease)")
_M_MERGE_RESETS = telemetry.counter(
    "tz_telemetry_merge_resets_total",
    "per-fuzzer counter regressions absorbed by the fleet merge (a "
    "restarted fuzzer reset its process-local counters)")
_M_SIGNAL_OVERFLOWS = telemetry.counter(
    "tz_manager_signal_overflows_total",
    "per-fuzzer max-signal deltas that overflowed the cap and "
    "latched a full resync")
_G_THROTTLE = telemetry.gauge(
    "tz_manager_throttle_state",
    "admission-control state (0 closed, 1 half_open, 2 open)")


@dataclass
class FuzzerState:
    """Per-connected-fuzzer distribution queues + session/lease state
    (reference: manager.go Fuzzer bookkeeping in Connect/Poll)."""
    name: str
    new_max_signal: Signal = field(default_factory=Signal)
    inputs: list[dict] = field(default_factory=list)  # pending RPCInput dicts
    # Latest telemetry snapshot from this fuzzer's poll (cumulative
    # counters/gauges/histograms with fixed shared buckets): the
    # fleet_telemetry merge is a vector add across these.
    telemetry: Optional[dict] = None
    # Session/lease bookkeeping (sessioned fuzzers only; all zero for
    # legacy unsessioned callers).
    last_seen: float = 0.0  # manager clock at the last call
    reply_cache: ReplyCache = field(default_factory=ReplyCache)
    inflight: list[tuple[int, list[dict]]] = field(default_factory=list)
    owned: list[dict] = field(default_factory=list)
    device_state: str = "closed"
    signal_resync: bool = False

    def outstanding_candidates(self) -> int:
        return sum(len(b) for _seq, b in self.inflight) + len(self.owned)


class ManagerRPC:
    """The "Manager" RPC receiver."""

    def __init__(self, prios: Optional[list] = None,
                 enabled_calls: Optional[list[int]] = None,
                 on_new_input: Optional[Callable[[RPCInput], bool]] = None,
                 on_stats: Optional[Callable[[dict], None]] = None,
                 candidate_source: Optional[Callable[[int],
                                                     list[dict]]] = None,
                 lease_s: Optional[float] = None,
                 inputs_cap: Optional[int] = None,
                 signal_cap: Optional[int] = None,
                 reply_cache_size: Optional[int] = None,
                 breaker=None,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self.prios = prios or []
        self.enabled_calls = enabled_calls or []
        self.fuzzers: dict[str, FuzzerState] = {}
        self.corpus: dict[str, dict] = {}  # sig -> RPCInput dict
        self.corpus_signal = Signal()
        self.max_signal = Signal()
        self.cover: set[int] = set()  # raw PCs for /cover reporting
        self.candidates: list[dict] = []  # RPCCandidate dicts
        self.on_new_input = on_new_input
        self.on_stats = on_stats
        self.candidate_source = candidate_source
        self.check_result: Optional[dict] = None
        self.stats_total: dict[str, int] = {}
        self.triaged_candidates = 0
        # Session/lease plane.  The epoch is re-minted per ManagerRPC
        # instance, so a manager restart invalidates every fuzzer's
        # session and forces the re-Connect resync.
        self.epoch = f"{random.getrandbits(64):016x}"
        self.lease_s = env_float("TZ_FUZZER_LEASE_S", 60.0) \
            if lease_s is None else lease_s
        self.inputs_cap = env_int("TZ_MANAGER_INPUTS_CAP", 1024) \
            if inputs_cap is None else inputs_cap
        self.signal_cap = env_int("TZ_MANAGER_SIGNAL_CAP", 1 << 20) \
            if signal_cap is None else signal_cap
        self.reply_cache_size = env_int("TZ_RPC_REPLY_CACHE", 128) \
            if reply_cache_size is None else reply_cache_size
        self.breaker = breaker  # optional manager-local CircuitBreaker
        self._clock = clock
        self.reaped_total = 0
        self.replays_total = 0
        self._throttle_state = "closed"
        # Reply caches of reaped fuzzers, so late retries of applied
        # seqs still replay (name -> reply_cache), insertion-ordered.
        self._tombstones: dict[str, ReplyCache] = {}
        # Fleet-merge monotonicity (ISSUE 14): per-fuzzer counter
        # high-water marks plus a retired accumulator, so a restarted
        # fuzzer resetting its process-local counters (or a reaped
        # one vanishing) never regresses the source="fleet" families.
        self._fleet_high: dict[str, dict[str, float]] = {}
        self._fleet_retired: dict[str, float] = {}
        # Durability (syzkaller_tpu/durable): when attached, custody-
        # ledger transitions journal under the store barrier and the
        # corpus/queue/ledgers become the "control" checkpoint section.
        self.durable = None

    def _barrier(self):
        """The store's journal barrier, or a no-op: ledger mutation +
        its WAL record must be atomic w.r.t. checkpoint snapshots
        (durable/store.py module doc)."""
        d = self.durable
        return d.barrier if d is not None else contextlib.nullcontext()

    def _journal(self, kind: str, meta: dict, blob: bytes = b"") -> None:
        d = self.durable
        if d is not None:
            d.journal(kind, meta, blob)

    # -- candidate feeding ------------------------------------------------

    def add_candidates(self, candidates: list[RPCCandidate]) -> None:
        """Queue corpus programs for fuzzer-side triage, shuffled for
        distribution spread.  Queued once: inputs lost to a crashing
        VM come back through lease-tracked reissue (reap/_settle), not
        the reference's blind 2x duplication (manager.go:245-256)."""
        cands = [c.to_dict() for c in candidates]
        with self._barrier(), self._lock:
            self.candidates.extend(cands)
            random.shuffle(self.candidates)
            if cands:
                self._journal("cand_add", {"cands": cands})

    def candidate_backlog(self) -> int:
        """Candidates not yet confirmed executed: the queue plus every
        fuzzer's issued-but-unacked ledger — the phase machine must
        not declare triage done while work is still in flight."""
        with self._lock:
            return len(self.candidates) + sum(
                f.outstanding_candidates() for f in self.fuzzers.values())

    # -- session plumbing --------------------------------------------------

    def _session_precheck(self, params: dict) -> Optional[dict]:
        """Replay-or-admit gate for a sessioned mutating call: returns
        the cached reply for a duplicate (epoch, seq), None to execute
        the call, or raises ReconnectRequired (stale epoch / reaped
        lease).  Legacy callers (no epoch in params) pass through."""
        epoch = params.get("epoch")
        if not epoch:
            return None
        name = params.get("name", "fuzzer")
        seq = int(params.get("seq") or 0)
        with self._lock:
            self._reap_locked()
            if epoch != self.epoch:
                _M_STALE.inc()
                raise ReconnectRequired(
                    f"session epoch {epoch} is stale (manager epoch "
                    f"{self.epoch}); re-Connect")
            f = self.fuzzers.get(name)
            if f is None:
                cache = self._tombstones.get(name)
                cached = cache.get(seq) if cache is not None else None
                if cached is not None:
                    _M_REPLAYS.inc()
                    self.replays_total += 1
                    return cached
                _M_STALE.inc()
                raise ReconnectRequired(
                    f"lease for {name!r} expired; re-Connect")
            f.last_seen = self._clock()
            cached = f.reply_cache.get(seq)
            if cached is not None:
                _M_REPLAYS.inc()
                self.replays_total += 1
                return cached
        return None

    def _session_commit(self, params: dict, reply: dict) -> dict:
        """Cache the reply under the call's seq so a retry replays it.
        The rpc.reply_cache seam sits AFTER the store: a scripted
        fault models the server dying post-apply/pre-reply — the
        recovery the retry+replay path exists for."""
        seq = int(params.get("seq") or 0)
        if not params.get("epoch") or not seq:
            return reply
        name = params.get("name", "fuzzer")
        with self._lock:
            f = self.fuzzers.get(name)
            if f is not None:
                # Entry + byte bounds live inside ReplyCache
                # (TZ_RPC_REPLY_CACHE / TZ_RPC_REPLY_CACHE_MB).
                f.reply_cache.put(seq, reply)
        fault_point("rpc.reply_cache")
        return reply

    def _reap_locked(self) -> None:
        """Reap leases idle past lease_s; requeue their work (caller
        holds self._lock)."""
        now = self._clock()
        expired = [f for f in self.fuzzers.values()
                   if f.last_seen and now - f.last_seen > self.lease_s]
        for f in expired:
            try:
                # Seam: a scripted fault defers THIS fuzzer's reap to
                # the next pass — the lease plane must tolerate its
                # own maintenance failing mid-stride.
                fault_point("manager.lease_expire")
            except FaultInjected:
                continue
            del self.fuzzers[f.name]
            self.reaped_total += 1
            _M_REAPED.inc()
            self._journal("cand_requeue", {"name": f.name})
            self._tombstones[f.name] = f.reply_cache
            while len(self._tombstones) > _MAX_TOMBSTONES:
                del self._tombstones[next(iter(self._tombstones))]
            held = f.outstanding_candidates()
            self._requeue_candidates_locked(f)
            # Undelivered inputs + max-signal delta go to survivors:
            # corpus inserts dedup by program hash fuzzer-side, so
            # handing every survivor the full backlog is idempotent.
            survivors = list(self.fuzzers.values())
            if survivors and f.inputs:
                _M_INPUTS_REDIST.inc(len(f.inputs))
                for other in survivors:
                    for inp in f.inputs:
                        self._queue_input_locked(other, inp)
            if survivors and not f.new_max_signal.empty():
                for other in survivors:
                    self._queue_signal_locked(other, f.new_max_signal)
            telemetry.record_event(
                "manager.lease_expire",
                f"{f.name} idle {now - f.last_seen:.0f}s; requeued "
                f"{held} candidates, {len(f.inputs)} inputs")
            log.logf(0, "reaped fuzzer lease %s (idle %.0fs)",
                     f.name, now - f.last_seen)

    def _requeue_candidates_locked(self, f: FuzzerState) -> None:
        """Return every candidate in a fuzzer's custody (undelivered
        and delivered-but-unexecuted) to the candidate queue."""
        returned = 0
        for _seq, batch in f.inflight:
            self.candidates.extend(batch)
            returned += len(batch)
        self.candidates.extend(f.owned)
        returned += len(f.owned)
        f.inflight = []
        f.owned = []
        if returned:
            _M_CAND_REISSUED.inc(returned)

    def _settle_candidates_locked(self, f: FuzzerState, seq: int,
                                  ack_seq: int, executed: int) -> None:
        """Advance the candidate custody ledger on a sessioned poll:
        batches the client confirmed receiving (reply seq <= ack_seq)
        become owned; batches whose reply the client abandoned
        (seq < current, never acked) are requeued; `executed`
        executions retire owned candidates FIFO."""
        keep: list[tuple[int, list[dict]]] = []
        requeued = 0
        for bseq, batch in f.inflight:
            if bseq <= ack_seq:
                f.owned.extend(batch)
            elif bseq < seq:
                # The client moved past this reply without processing
                # it (retries exhausted, reply lost): the candidates
                # never arrived — put them back for anyone.
                self.candidates.extend(batch)
                requeued += len(batch)
            else:
                keep.append((bseq, batch))
        f.inflight = keep
        if requeued:
            _M_CAND_REISSUED.inc(requeued)
        if executed:
            del f.owned[:min(executed, len(f.owned))]

    def _queue_input_locked(self, f: FuzzerState, inp: dict) -> None:
        """Append a pending input under the drop-oldest cap: one
        never-polling fuzzer must not grow manager memory unboundedly."""
        f.inputs.append(inp)
        if len(f.inputs) > self.inputs_cap:
            drop = len(f.inputs) - self.inputs_cap
            del f.inputs[:drop]
            _M_INPUTS_DROPPED.inc(drop)

    def _queue_signal_locked(self, f: FuzzerState, sig: Signal) -> None:
        """Merge into the fuzzer's pending max-signal delta under the
        cap; overflow clears the delta and latches a full resync —
        the next poll serves the complete max_signal (a superset of
        whatever was dropped), so correctness is preserved."""
        f.new_max_signal.merge(sig)
        if len(f.new_max_signal) > self.signal_cap:
            f.new_max_signal = Signal()
            f.signal_resync = True
            _M_SIGNAL_OVERFLOWS.inc()

    def _throttle_locked(self) -> str:
        """The admission controller's aggregate: worst breaker state
        across live fuzzers (their reported device_state) and the
        optional manager-local breaker; transitions hit the timeline."""
        worst = "closed"
        if self.breaker is not None:
            worst = self.breaker.state
        for f in self.fuzzers.values():
            if _STATE_LEVEL.get(f.device_state, 0) \
                    > _STATE_LEVEL[worst]:
                worst = f.device_state
        if worst != self._throttle_state:
            telemetry.record_event(
                "manager.throttle",
                f"{self._throttle_state} -> {worst}: candidate "
                f"allotment {THROTTLE_QUOTA[worst]}, poll x"
                f"{THROTTLE_POLL_MULT[worst]:g}")
            log.logf(0, "admission control: %s -> %s",
                     self._throttle_state, worst)
            self._throttle_state = worst
            _G_THROTTLE.set(_STATE_LEVEL[worst])
        return worst

    def _throttle_hint_locked(self) -> dict:
        state = self._throttle_locked()
        return {"state": state,
                "max_candidates": THROTTLE_QUOTA[state],
                "poll_interval_mult": THROTTLE_POLL_MULT[state]}

    def reap_expired(self) -> None:
        """Explicit reap pass (the Manager's periodic loop / tests);
        sessioned calls also reap opportunistically."""
        with self._barrier(), self._lock:
            self._reap_locked()

    def throttle_state(self) -> str:
        """Current admission-control tier — the serving plane's
        broker (serve/broker.ServePlane) scales per-tenant allotments
        from this, so individual tenants shrink before the global
        breaker trips."""
        with self._lock:
            return self._throttle_locked()

    def control_snapshot(self) -> dict:
        """Control-plane rollup for the status page / bench snapshots."""
        with self._lock:
            now = self._clock()
            return {
                "epoch": self.epoch,
                "throttle": self._throttle_state,
                "lease_s": self.lease_s,
                "live_fuzzers": len(self.fuzzers),
                "reaped_fuzzers": self.reaped_total,
                "reply_replays": self.replays_total,
                "outstanding_candidates": sum(
                    f.outstanding_candidates()
                    for f in self.fuzzers.values()),
                "fuzzers": {
                    name: {
                        "idle_s": round(now - f.last_seen, 1)
                        if f.last_seen else None,
                        "device_state": f.device_state,
                        "inputs_queued": len(f.inputs),
                        "candidates_held": f.outstanding_candidates(),
                    } for name, f in self.fuzzers.items()},
            }

    # -- RPC methods ------------------------------------------------------

    def Connect(self, params: dict) -> dict:
        """(reference: manager.go:862-918).  Mints the session: the
        reply carries (epoch, lease_s); a re-Connect under an existing
        name (fuzzer restart or post-reap resync) returns the old
        state's candidates to the queue and starts clean — the full
        corpus in this reply supersedes any queued inputs."""
        name = params.get("name", "fuzzer")
        with self._barrier(), self._lock:
            self._reap_locked()
            old = self.fuzzers.get(name)
            if old is not None:
                self._requeue_candidates_locked(old)
                self._journal("cand_requeue", {"name": name})
            self._tombstones.pop(name, None)
            f = FuzzerState(
                name=name, last_seen=self._clock(),
                reply_cache=ReplyCache(entries=self.reply_cache_size))
            self.fuzzers[name] = f
            elems, prios = self.max_signal.serialize()
            return {
                "prios": self.prios,
                "enabled_calls": self.enabled_calls,
                "corpus": [inp for inp in self.corpus.values()],
                "max_signal": [elems, prios],
                "need_check": self.check_result is None,
                "epoch": self.epoch,
                "lease_s": self.lease_s,
            }

    def Check(self, params: dict) -> dict:
        """First fuzzer reports capabilities; mismatches with the
        config are fatal in the reference (manager.go:920-974)."""
        with self._lock:
            if self.check_result is None:
                self.check_result = dict(params)
                log.logf(0, "machine check: %d calls enabled, kcov=%s, "
                         "comps=%s", len(params.get("calls", [])),
                         params.get("kcov"), params.get("comps"))
        return {}

    def NewInput(self, params: dict) -> dict:
        """A fuzzer triaged a new corpus input: dedup by signal diff,
        persist, broadcast to other fuzzers
        (reference: manager.go:976-1025)."""
        with self._barrier():
            cached = self._session_precheck(params)
            if cached is not None:
                return cached
            reply = self._new_input(params)
            return self._session_commit(params, reply)

    def _new_input(self, params: dict) -> dict:
        name = params.get("name", "fuzzer")
        inp = RPCInput.from_dict(params.get("input") or {})
        sig = Signal.deserialize(*inp.signal)
        with self._lock:
            # Drop if it adds nothing over current corpus signal at the
            # same prio (another fuzzer raced it in).
            diff = self.corpus_signal.diff(sig)
            if diff.empty():
                return {"accepted": False}
            key = hash_string(inp.prog.encode())
            art = self.corpus.get(key)
            if art is not None:
                # Same program, possibly better signal: merge.
                old = Signal.deserialize(*RPCInput.from_dict(art).signal)
                old.merge(sig)
                art["signal"] = list(old.serialize())
            else:
                art = self.corpus[key] = inp.to_dict()
            self.corpus_signal.merge(sig)
            self.max_signal.merge(sig)
            self.cover.update(int(pc) for pc in inp.cover)
            # The record carries the POST-merge artifact + the signal
            # diff, so replay is idempotent and order-independent
            # w.r.t. the checkpoint (durable/recovery.py module doc).
            self._journal("corpus_add",
                          {"key": key, "input": dict(art),
                           "diff": list(diff.serialize())})
            for fname, f in self.fuzzers.items():
                if fname != name:
                    self._queue_input_locked(f, inp.to_dict())
                    self._queue_signal_locked(f, sig)
        if self.on_new_input is not None:
            self.on_new_input(inp)
        return {"accepted": True}

    def Poll(self, params: dict) -> dict:
        """Periodic sync: stats up, candidates/new-inputs/max-signal
        down (reference: manager.go:1027-1081)."""
        with self._barrier():
            cached = self._session_precheck(params)
            if cached is not None:
                return cached
            reply = self._poll(params)
            return self._session_commit(params, reply)

    def _poll(self, params: dict) -> dict:
        name = params.get("name", "fuzzer")
        stats = params.get("stats") or {}
        fuzzer_max = params.get("max_signal") or [[], []]
        telemetry_snap = params.get("telemetry")
        seq = int(params.get("seq") or 0)
        ack_seq = int(params.get("ack_seq") or 0)
        with self._lock:
            f = self.fuzzers.get(name)
            if f is None:  # legacy fuzzer restarted without Connect
                f = FuzzerState(
                    name=name, last_seen=self._clock(),
                    reply_cache=ReplyCache(entries=self.reply_cache_size))
                self.fuzzers[name] = f
            if telemetry_snap:
                f.telemetry = telemetry_snap
                # High-water the counters NOW, not at scrape time: a
                # restart between two fleet reads would otherwise
                # overwrite the pre-restart life before anyone saw it.
                self._note_counters_locked(name, telemetry_snap)
            f.device_state = str(params.get("device_state")
                                 or "closed")
            if seq:
                executed = int(stats.get(_CANDIDATE_STAT) or 0)
                self._settle_candidates_locked(f, seq, ack_seq,
                                               executed)
                self._journal("cand_settle",
                              {"name": name, "seq": seq,
                               "ack_seq": ack_seq,
                               "executed": executed})
            new_sig = Signal.deserialize(fuzzer_max[0], fuzzer_max[1])
            diff = self.max_signal.diff(new_sig)
            if not diff.empty():
                self.max_signal.merge(diff)
                self._journal("max_sig",
                              {"sig": list(diff.serialize())})
                for fname, other in self.fuzzers.items():
                    if fname != name:
                        self._queue_signal_locked(other, diff)
            for k, v in stats.items():
                self.stats_total[k] = self.stats_total.get(k, 0) + int(v)
            throttle = self._throttle_hint_locked()
            candidates = []
            if params.get("need_candidates"):
                n = min(len(self.candidates),
                        throttle["max_candidates"])
                candidates = self.candidates[:n]
                del self.candidates[:n]
                self.triaged_candidates += n
                if seq and candidates:
                    f.inflight.append((seq, list(candidates)))
                    self._journal("cand_issue",
                                  {"name": name, "seq": seq,
                                   "cands": candidates})
            if f.signal_resync:
                # The pending delta overflowed its cap at some point:
                # serve the full max signal (a superset of everything
                # dropped) and clear the latch.
                max_out = self.max_signal.serialize()
                f.signal_resync = False
                f.new_max_signal = Signal()
            else:
                max_out = f.new_max_signal.serialize()
                f.new_max_signal = Signal()
            inputs, f.inputs = f.inputs[:100], f.inputs[100:]
        if self.on_stats is not None:
            self.on_stats(stats)
        return {"candidates": candidates, "new_inputs": inputs,
                "max_signal": list(max_out), "throttle": throttle}

    # -- durability (syzkaller_tpu/durable) --------------------------------

    def durable_export(self) -> tuple:
        """The "control" checkpoint section: candidate queue, corpus,
        signal aggregates, and the per-fuzzer custody ledgers — all
        JSON meta, no blob.  Called by DurableStore.checkpoint_now
        under the store barrier; taking self._lock here respects the
        barrier -> domain lock order."""
        with self._lock:
            meta = {
                "queue": [dict(c) for c in self.candidates],
                "corpus": {k: dict(v)
                           for k, v in self.corpus.items()},
                "corpus_signal": list(self.corpus_signal.serialize()),
                "max_signal": list(self.max_signal.serialize()),
                "cover": sorted(self.cover),
                "triaged": self.triaged_candidates,
                "fuzzers": {
                    name: {
                        "inflight": [[seq, [dict(c) for c in batch]]
                                     for seq, batch in f.inflight],
                        "owned": [dict(c) for c in f.owned],
                    } for name, f in self.fuzzers.items()},
            }
        return meta, b""

    def durable_restore(self, state: dict) -> None:
        """Install a recovered control plane (recovery.replay's
        "control" value).  Custody is already collapsed into the
        queue; fuzzer sessions are NOT restored — this instance's
        fresh epoch forces every fuzzer to re-Connect."""

        def _as_sig(v):
            if isinstance(v, Signal):
                return v
            return Signal.deserialize(v[0], v[1]) if v else Signal()

        with self._lock:
            self.candidates = [dict(c)
                               for c in state.get("queue") or []]
            self.corpus = {k: dict(v) for k, v
                           in (state.get("corpus") or {}).items()}
            self.corpus_signal = _as_sig(state.get("corpus_signal"))
            self.max_signal = _as_sig(state.get("max_signal"))
            self.cover = set(int(pc)
                             for pc in state.get("cover") or ())
            self.triaged_candidates = int(state.get("triaged") or 0)

    # -- introspection ----------------------------------------------------

    def _note_counters_locked(self, name: str, snap: dict) -> None:
        """Absorb one fuzzer's cumulative counters into the fleet
        high-water marks (caller holds self._lock).  A value below
        its mark means the process restarted: the old life's total
        retires into the monotonic accumulator and the mark restarts.
        Idempotent for already-seen values (max-merge)."""
        high = self._fleet_high.setdefault(name, {})
        for cname, v in (snap.get("counters") or {}).items():
            v = float(v)
            hi = high.get(cname)
            if hi is not None and v < hi - 1e-9:
                self._fleet_retired[cname] = \
                    self._fleet_retired.get(cname, 0.0) + hi
                _M_MERGE_RESETS.inc()
                high[cname] = v
            else:
                high[cname] = v if hi is None else max(hi, v)

    def fleet_telemetry(self) -> dict:
        """Cross-process rollup of the fuzzers' latest poll telemetry
        (the ROADMAP PR 2 leftover): counters/gauges sum, histograms
        vector-add over the fixed shared buckets, percentiles
        re-estimated from the merged counts.  Rendered on /metrics
        (source="fleet") and /api/stats.

        Monotonicity audit (ISSUE 14): merge_snapshots sums the
        LATEST cumulative snapshot per fuzzer, so a fuzzer restart
        (counters back to ~0) or a lease reap would regress the
        fleet counters.  The fleet counter families are instead
        derived from per-fuzzer high-water marks plus a retired
        accumulator: a counter seen BELOW its high-water means the
        process restarted — the old life's total retires (counted by
        tz_telemetry_merge_resets_total) and the mark restarts; a
        reaped fuzzer keeps its mark (so its work never leaves the
        sum, and a same-process re-Connect continues it without
        double-counting).  Gauges and histograms still merge from
        the live snapshots — they are legitimately non-monotonic."""
        from syzkaller_tpu.telemetry import merge_snapshots

        with self._lock:
            snaps = []
            for name, f in self.fuzzers.items():
                if not f.telemetry:
                    continue
                snaps.append(f.telemetry)
                # Legacy path: a snapshot that arrived outside _poll
                # (tests poking f.telemetry directly) still high-waters
                # here; _note_counters_locked is idempotent for values
                # already absorbed at poll time.
                self._note_counters_locked(name, f.telemetry)
            counters: dict[str, float] = dict(self._fleet_retired)
            for high in self._fleet_high.values():
                for cname, hi in high.items():
                    counters[cname] = counters.get(cname, 0.0) + hi
        merged = merge_snapshots(snaps)
        if counters:
            merged["counters"] = counters
        return merged

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "corpus": len(self.corpus),
                "signal": len(self.corpus_signal),
                "max_signal": len(self.max_signal),
                "candidates": len(self.candidates),
                "fuzzers": list(self.fuzzers),
                "stats": dict(self.stats_total),
            }
