"""Manager-side RPC service: the fuzzer-facing control plane.

Implements Manager.Connect/Check/NewInput/Poll over the rpc transport
(reference: syz-manager/manager.go:862-1081).  Shared mutable state
(corpus, signal, candidates, per-fuzzer queues) lives here under one
lock; the Manager object wires in persistence and crash handling via
callbacks so this service stays testable standalone.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from syzkaller_tpu.rpc.types import RPCCandidate, RPCInput
from syzkaller_tpu.signal import Signal
from syzkaller_tpu.utils import log
from syzkaller_tpu.utils.hashsig import hash_string


@dataclass
class FuzzerState:
    """Per-connected-fuzzer distribution queues
    (reference: manager.go Fuzzer bookkeeping in Connect/Poll)."""
    name: str
    new_max_signal: Signal = field(default_factory=Signal)
    inputs: list[dict] = field(default_factory=list)  # pending RPCInput dicts
    # Latest telemetry snapshot from this fuzzer's poll (cumulative
    # counters/gauges/histograms with fixed shared buckets): the
    # fleet_telemetry merge is a vector add across these.
    telemetry: Optional[dict] = None


class ManagerRPC:
    """The "Manager" RPC receiver."""

    def __init__(self, prios: Optional[list] = None,
                 enabled_calls: Optional[list[int]] = None,
                 on_new_input: Optional[Callable[[RPCInput], bool]] = None,
                 on_stats: Optional[Callable[[dict], None]] = None,
                 candidate_source: Optional[Callable[[int],
                                                     list[dict]]] = None):
        self._lock = threading.Lock()
        self.prios = prios or []
        self.enabled_calls = enabled_calls or []
        self.fuzzers: dict[str, FuzzerState] = {}
        self.corpus: dict[str, dict] = {}  # sig -> RPCInput dict
        self.corpus_signal = Signal()
        self.max_signal = Signal()
        self.cover: set[int] = set()  # raw PCs for /cover reporting
        self.candidates: list[dict] = []  # RPCCandidate dicts
        self.on_new_input = on_new_input
        self.on_stats = on_stats
        self.candidate_source = candidate_source
        self.check_result: Optional[dict] = None
        self.stats_total: dict[str, int] = {}
        self.triaged_candidates = 0

    # -- candidate feeding ------------------------------------------------

    def add_candidates(self, candidates: list[RPCCandidate]) -> None:
        """Queue corpus programs for fuzzer-side triage; duplicated and
        shuffled so inputs lost to a crashing VM get a second chance
        (reference: manager.go:245-256)."""
        with self._lock:
            batch = [c.to_dict() for c in candidates]
            self.candidates.extend(batch + batch)
            random.shuffle(self.candidates)

    def candidate_backlog(self) -> int:
        with self._lock:
            return len(self.candidates)

    # -- RPC methods ------------------------------------------------------

    def Connect(self, params: dict) -> dict:
        """(reference: manager.go:862-918)"""
        name = params.get("name", "fuzzer")
        with self._lock:
            self.fuzzers[name] = FuzzerState(name=name)
            elems, prios = self.max_signal.serialize()
            return {
                "prios": self.prios,
                "enabled_calls": self.enabled_calls,
                "corpus": [inp for inp in self.corpus.values()],
                "max_signal": [elems, prios],
                "need_check": self.check_result is None,
            }

    def Check(self, params: dict) -> dict:
        """First fuzzer reports capabilities; mismatches with the
        config are fatal in the reference (manager.go:920-974)."""
        with self._lock:
            if self.check_result is None:
                self.check_result = dict(params)
                log.logf(0, "machine check: %d calls enabled, kcov=%s, "
                         "comps=%s", len(params.get("calls", [])),
                         params.get("kcov"), params.get("comps"))
        return {}

    def NewInput(self, params: dict) -> dict:
        """A fuzzer triaged a new corpus input: dedup by signal diff,
        persist, broadcast to other fuzzers
        (reference: manager.go:976-1025)."""
        name = params.get("name", "fuzzer")
        inp = RPCInput.from_dict(params.get("input") or {})
        sig = Signal.deserialize(*inp.signal)
        with self._lock:
            # Drop if it adds nothing over current corpus signal at the
            # same prio (another fuzzer raced it in).
            diff = self.corpus_signal.diff(sig)
            if diff.empty():
                return {"accepted": False}
            key = hash_string(inp.prog.encode())
            art = self.corpus.get(key)
            if art is not None:
                # Same program, possibly better signal: merge.
                old = Signal.deserialize(*RPCInput.from_dict(art).signal)
                old.merge(sig)
                art["signal"] = list(old.serialize())
            else:
                self.corpus[key] = inp.to_dict()
            self.corpus_signal.merge(sig)
            self.max_signal.merge(sig)
            self.cover.update(int(pc) for pc in inp.cover)
            for fname, f in self.fuzzers.items():
                if fname != name:
                    f.inputs.append(inp.to_dict())
                    f.new_max_signal.merge(sig)
        if self.on_new_input is not None:
            self.on_new_input(inp)
        return {"accepted": True}

    def Poll(self, params: dict) -> dict:
        """Periodic sync: stats up, candidates/new-inputs/max-signal
        down (reference: manager.go:1027-1081)."""
        name = params.get("name", "fuzzer")
        stats = params.get("stats") or {}
        fuzzer_max = params.get("max_signal") or [[], []]
        telemetry = params.get("telemetry")
        with self._lock:
            f = self.fuzzers.get(name)
            if f is None:  # fuzzer restarted without Connect — re-add
                f = FuzzerState(name=name)
                self.fuzzers[name] = f
            if telemetry:
                f.telemetry = telemetry
            new_sig = Signal.deserialize(fuzzer_max[0], fuzzer_max[1])
            diff = self.max_signal.diff(new_sig)
            if not diff.empty():
                self.max_signal.merge(diff)
                for fname, other in self.fuzzers.items():
                    if fname != name:
                        other.new_max_signal.merge(diff)
            for k, v in stats.items():
                self.stats_total[k] = self.stats_total.get(k, 0) + int(v)
            candidates = []
            if params.get("need_candidates"):
                n = min(len(self.candidates), 100)
                candidates = self.candidates[:n]
                del self.candidates[:n]
                self.triaged_candidates += n
            max_out = f.new_max_signal.serialize()
            f.new_max_signal = Signal()
            inputs, f.inputs = f.inputs[:100], f.inputs[100:]
        if self.on_stats is not None:
            self.on_stats(stats)
        return {"candidates": candidates, "new_inputs": inputs,
                "max_signal": list(max_out)}

    # -- introspection ----------------------------------------------------

    def fleet_telemetry(self) -> dict:
        """Cross-process rollup of the fuzzers' latest poll telemetry
        (the ROADMAP PR 2 leftover): counters/gauges sum, histograms
        vector-add over the fixed shared buckets, percentiles
        re-estimated from the merged counts.  Rendered on /metrics
        (source="fleet") and /api/stats."""
        from syzkaller_tpu.telemetry import merge_snapshots

        with self._lock:
            snaps = [f.telemetry for f in self.fuzzers.values()
                     if f.telemetry]
        return merge_snapshots(snaps)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "corpus": len(self.corpus),
                "signal": len(self.corpus_signal),
                "max_signal": len(self.max_signal),
                "candidates": len(self.candidates),
                "fuzzers": list(self.fuzzers),
                "stats": dict(self.stats_total),
            }
