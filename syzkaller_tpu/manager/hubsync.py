"""Manager-side hub synchronization.

Periodically exchanges corpus programs and repros with a hub:
uploads locally-triaged minimized inputs, downloads other managers'
programs as candidates, and forwards crash repro programs both ways
(reference: syz-manager/manager.go:1083-1227 hubSync; gated on the
phase machine so hub inputs only arrive after the local corpus is
triaged, manager.go:92-103).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from syzkaller_tpu.manager.mgrconfig import parse_addr
from syzkaller_tpu.rpc import RPCClient
from syzkaller_tpu.rpc.types import RPCCandidate
from syzkaller_tpu.utils import log

SYNC_PERIOD_S = 60.0


class HubSyncer:
    def __init__(self, mgr, period_s: float = SYNC_PERIOD_S,
                 fresh: bool = False):
        self.mgr = mgr
        self.period_s = period_s
        self.fresh = fresh
        self.client = RPCClient(parse_addr(mgr.cfg.hub_addr),
                                name=mgr.cfg.hub_client)
        self._connected = False
        self._uploaded: set[str] = set()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        from syzkaller_tpu.manager.manager import PHASE_TRIAGED_CORPUS

        while not self.mgr.stop_ev.wait(self.period_s):
            if self.mgr.phase < PHASE_TRIAGED_CORPUS:
                continue
            try:
                self.sync_once()
            except Exception as e:
                log.logf(0, "hub sync failed: %s", e)
                self._connected = False

    def _ident(self) -> dict:
        return {"client": self.mgr.cfg.hub_client,
                "key": self.mgr.cfg.hub_key,
                "manager": self.mgr.cfg.name}

    def sync_once(self) -> dict:
        from syzkaller_tpu.manager.manager import (PHASE_QUERIED_HUB,
                                                   PHASE_TRIAGED_HUB)

        if not self._connected:
            with self.mgr.serv._lock:
                items = dict(self.mgr.serv.corpus)
            self.client.call_transient("Hub.Connect", {
                **self._ident(), "fresh": self.fresh,
                "corpus": [inp["prog"] for inp in items.values()],
            })
            self._uploaded = set(items)
            self._connected = True

        # new local inputs since the last sync
        with self.mgr.serv._lock:
            items = dict(self.mgr.serv.corpus)
        add = [inp["prog"] for h, inp in items.items()
               if h not in self._uploaded]

        # pending crash repro programs from the manager's repro
        # pipeline; acked only after a successful RPC so a failed
        # sync retransmits them
        repros = self.mgr.peek_hub_repros()

        res = self.client.call_transient("Hub.Sync", {
            **self._ident(), "need_repros": True,
            "repros": repros, "add": add, "delete": [],
        }) or {}
        self._uploaded |= set(items)
        self.mgr.ack_hub_repros(len(repros))

        progs = res.get("progs") or []
        if progs:
            self.mgr.serv.add_candidates(
                [RPCCandidate(prog=p, minimized=False) for p in progs])
        for rp in res.get("repros") or []:
            self.mgr.serv.add_candidates(
                [RPCCandidate(prog=rp, minimized=False)])
        log.logf(0, "hub sync: sent %d progs %d repros, recv %d progs "
                 "%d repros (more %d)", len(add), len(repros),
                 len(progs), len(res.get("repros") or []),
                 res.get("more", 0))
        if self.mgr.phase < PHASE_QUERIED_HUB:
            self.mgr.phase = PHASE_QUERIED_HUB
        if not progs and self.mgr.phase < PHASE_TRIAGED_HUB \
                and self.mgr.serv.candidate_backlog() == 0:
            self.mgr.phase = PHASE_TRIAGED_HUB
        return {"sent": len(add), "received": len(progs)}
