"""Manager-side hub synchronization.

Periodically exchanges corpus programs and repros with a hub:
uploads locally-triaged minimized inputs, downloads other managers'
programs as candidates, and forwards crash repro programs both ways
(reference: syz-manager/manager.go:1083-1227 hubSync; gated on the
phase machine so hub inputs only arrive after the local corpus is
triaged, manager.go:92-103).

ISSUE 16 drives the exchange over the session discipline the fuzzers
already use against the manager:

  * Hub.Connect mints (epoch, lease) and the syncer arms
    `call_session` with it — a retried Sync replays the hub's cached
    reply instead of double-applying, and a ReconnectRequired verdict
    (hub restart, reaped lease) runs `_connect` as the on_reconnect
    hook: re-upload (idempotent, the hub dedups by hash) and resume.
  * Each Sync carries a packed occupancy digest of this manager's
    corpus signal (ops/signal.digest_from_folds at the hub's
    advertised resolution) so the hub withholds programs predicted
    already-known here.
  * Sessioned replies ship program payloads in the frame annex as
    (offset, len) refs — decoded here with zero-copy memoryview
    slices; the legacy inline-strings shape still parses (old hubs).
  * A `backoff_s` hint in a throttled reply (the hub's per-manager
    circuit breaker is open) stretches this manager's next sync —
    the degraded manager slows down alone instead of hammering.
"""

from __future__ import annotations

import base64
import threading
from typing import Optional

import numpy as np

from syzkaller_tpu.manager.mgrconfig import parse_addr
from syzkaller_tpu.ops.signal import (digest_from_folds, fold_hash_np,
                                      pack_plane)
from syzkaller_tpu.rpc import RPCClient
from syzkaller_tpu.rpc.types import RPCCandidate
from syzkaller_tpu.utils import log

SYNC_PERIOD_S = 60.0


def _sig_elems(inp: dict) -> list:
    sig = inp.get("signal")
    return list(sig[0]) if sig else []


class HubSyncer:
    def __init__(self, mgr, period_s: float = SYNC_PERIOD_S,
                 fresh: bool = False):
        self.mgr = mgr
        self.period_s = period_s
        self.fresh = fresh
        self.client = RPCClient(parse_addr(mgr.cfg.hub_addr),
                                name=mgr.cfg.hub_client)
        self._connected = False
        self._uploaded: set[str] = set()
        self._thread: Optional[threading.Thread] = None
        self.digest_bits = 0  # advertised by the hub's Connect reply
        self.backoff_s = 0.0  # hub throttle hint, added to the period
        self.last_sync: dict = {}

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        from syzkaller_tpu.manager.manager import PHASE_TRIAGED_CORPUS

        while not self.mgr.stop_ev.wait(self.period_s + self.backoff_s):
            if self.mgr.phase < PHASE_TRIAGED_CORPUS:
                continue
            try:
                self.sync_once()
            except Exception as e:
                log.logf(0, "hub sync failed: %s", e)
                self._connected = False

    def _ident(self) -> dict:
        return {"client": self.mgr.cfg.hub_client,
                "key": self.mgr.cfg.hub_key,
                "manager": self.mgr.cfg.name}

    def _connect(self) -> None:
        """Connect (or re-Connect after ReconnectRequired): upload the
        whole corpus — the hub dedups by hash, so this is idempotent —
        and arm the client session with the minted epoch."""
        with self.mgr.serv._lock:
            items = dict(self.mgr.serv.corpus)
        res = self.client.call_transient("Hub.Connect", {
            **self._ident(), "fresh": self.fresh, "session": True,
            "corpus": [inp["prog"] for inp in items.values()],
            "corpus_sigs": [_sig_elems(inp) for inp in items.values()],
        }) or {}
        epoch = res.get("epoch")
        if epoch:
            self.client.set_session(epoch, on_reconnect=self._connect)
            self.digest_bits = int(res.get("digest_bits") or 0)
        self._uploaded = set(items)
        self._connected = True

    def _digest_b64(self, items: dict) -> Optional[str]:
        if not self.digest_bits:
            return None
        elems: list = []
        for inp in items.values():
            elems.extend(_sig_elems(inp))
        folds = fold_hash_np(np.asarray(elems, dtype=np.int64)
                             .astype(np.uint32)) \
            if elems else np.empty(0, np.int64)
        digest = digest_from_folds(folds, self.digest_bits)
        return base64.b64encode(pack_plane(digest)).decode()

    def sync_once(self) -> dict:
        from syzkaller_tpu.manager.manager import (PHASE_QUERIED_HUB,
                                                   PHASE_TRIAGED_HUB)

        if not self._connected:
            self._connect()

        # new local inputs since the last sync
        with self.mgr.serv._lock:
            items = dict(self.mgr.serv.corpus)
        new = {h: inp for h, inp in items.items()
               if h not in self._uploaded}
        add = [inp["prog"] for inp in new.values()]
        add_sigs = [_sig_elems(inp) for inp in new.values()]

        # pending crash repro programs from the manager's repro
        # pipeline; acked only after a successful RPC so a failed
        # sync retransmits them
        repros = self.mgr.peek_hub_repros()

        params = {**self._ident(), "need_repros": True,
                  "repros": repros, "add": add,
                  "add_sigs": add_sigs, "delete": []}
        digest = self._digest_b64(items)
        if digest is not None:
            params["digest"] = digest
            params["digest_bits"] = self.digest_bits
        res, annex = self.client.call_session(
            "Hub.Sync", params, want_annex=True)
        res = res or {}
        self._uploaded |= set(items)
        self.mgr.ack_hub_repros(len(repros))
        self.backoff_s = float(res.get("backoff_s") or 0.0)
        if res.get("throttled"):
            log.logf(0, "hub sync throttled; backoff %.1fs",
                     self.backoff_s)
            self.last_sync = {"sent": 0, "received": 0,
                              "throttled": True}
            return self.last_sync

        progs = self._decode_progs(res.get("progs") or [], annex)
        if progs:
            self.mgr.serv.add_candidates(
                [RPCCandidate(prog=p, minimized=False) for p in progs])
        for rp in res.get("repros") or []:
            self.mgr.serv.add_candidates(
                [RPCCandidate(prog=rp, minimized=False)])
        log.logf(0, "hub sync: sent %d progs %d repros, recv %d progs "
                 "%d repros (more %d)", len(add), len(repros),
                 len(progs), len(res.get("repros") or []),
                 res.get("more", 0))
        if self.mgr.phase < PHASE_QUERIED_HUB:
            self.mgr.phase = PHASE_QUERIED_HUB
        if not progs and self.mgr.phase < PHASE_TRIAGED_HUB \
                and self.mgr.serv.candidate_backlog() == 0:
            self.mgr.phase = PHASE_TRIAGED_HUB
        self.last_sync = {"sent": len(add), "received": len(progs)}
        return self.last_sync

    @staticmethod
    def _decode_progs(refs: list, annex) -> list[str]:
        """Sessioned replies carry [[offset, len], ...] refs into the
        frame annex; legacy hubs send inline strings.  Either way the
        result is program text."""
        if not refs:
            return []
        if isinstance(refs[0], (list, tuple)):
            view = memoryview(annex or b"")
            return [bytes(view[off:off + ln]).decode()
                    for off, ln in refs]
        return [str(p) for p in refs]
