"""Mutant lineage tracing: one trace context per device batch.

PR 2's spans are per-process and per-phase — nobody can follow ONE
mutant end to end across the four planes of the hot loop (mutate →
assemble → stage/H2D → novel_any → CPU confirm → exec → corpus add)
or across the three processes they run in.  This module is the
causal layer on top of the same registry:

  - a TraceContext (64-bit trace id + sampled flag) is minted at
    mutation-flush time — one per launched batch, never per mutant,
    so unsampled batches cost one `None` check and sampled batches
    one small object shared by every mutant they produce,
  - the context threads DeltaBatch → AssembledBatch → ExecMutant →
    the RPC frame header (rpc/rpc.py) → TriageEngine verdict
    delivery → corpus add.  ExecMutant reads it through its batch
    reference: zero per-mutant storage, zero per-mutant allocation,
  - each lifecycle hop records the wait since the previous hop into
    a fixed per-stage histogram (the cross-process queue-time view
    the spans cannot give) and, when TZ_TRACE_FILE is armed, emits an
    async-instant trace event keyed by the trace id — every hop of a
    sampled mutant renders as ONE correlated Perfetto track spanning
    the pipeline worker, the proc threads, and the far side of the
    RPC link.

Sampling: `TZ_TRACE_SAMPLE` (a probability in [0, 1], envsafe
semantics — malformed degrades to the default 0.0) gates minting.
Cross-process hops carry a wallclock stamp on the wire because
perf_counter timebases do not survive a process boundary.
"""

from __future__ import annotations

import os
import random
import struct
import threading
import time
from typing import Optional

ENV_SAMPLE = "TZ_TRACE_SAMPLE"

#: Wire form for the RPC frame header: trace id, flags (bit 0 =
#: sampled), wallclock stamp of the sender's last hop.
WIRE = struct.Struct("<QBd")

_rng = random.Random()
_rate_lock = threading.Lock()
_rate: Optional[float] = None  # None = re-read from the environment


class TraceContext:
    """One mutant batch's lineage identity.  Mutated only from the
    single thread currently advancing the lifecycle stage, so hops
    need no lock."""

    __slots__ = ("trace_id", "sampled", "born_wall", "last_ts",
                 "last_wall", "last_stage")

    def __init__(self, trace_id: int, sampled: bool = True):
        self.trace_id = trace_id
        self.sampled = sampled
        now = time.perf_counter()
        self.born_wall = time.time()
        self.last_ts = now
        self.last_wall = self.born_wall
        self.last_stage = "lineage.mint"


def sample_rate() -> float:
    """TZ_TRACE_SAMPLE, parsed once per process (envsafe discipline:
    malformed degrades to 0.0 — tracing off — never an exception)."""
    global _rate
    with _rate_lock:
        if _rate is None:
            raw = os.environ.get(ENV_SAMPLE)
            try:
                _rate = min(1.0, max(0.0, float(raw))) if raw else 0.0
            except (TypeError, ValueError):
                _rate = 0.0
        return _rate


def set_sample_rate(rate: Optional[float]) -> None:
    """Pin (or, with None, re-read from the environment) the sampling
    rate — tests and tools."""
    global _rate
    with _rate_lock:
        _rate = rate if rate is None else min(1.0, max(0.0, rate))


def _telemetry():
    # Late import: telemetry/__init__ imports this module, and the
    # registry handles live there.
    from syzkaller_tpu import telemetry

    return telemetry


def _hists():
    global _STAGE_WAITS, _M_SAMPLED
    if _STAGE_WAITS is None:
        t = _telemetry()
        _M_SAMPLED = t.counter(
            "tz_lineage_sampled_total",
            "sampled lineage trace contexts minted")
        _STAGE_WAITS = {
            "pipeline.deliver": t.histogram(
                "tz_lineage_deliver_wait_seconds",
                "flush -> assembled batch delivered to the prefetch "
                "queue (device + assembly residency)"),
            "proc.draw": t.histogram(
                "tz_lineage_draw_wait_seconds",
                "batch delivered -> first mutant drawn by a proc "
                "(prefetch-queue wait)"),
            "rpc.frame": t.histogram(
                "tz_lineage_rpc_wait_seconds",
                "previous hop -> trace context received on the far "
                "side of an RPC frame (wallclock; cross-process)"),
            "triage.verdict": t.histogram(
                "tz_lineage_verdict_wait_seconds",
                "previous hop -> novelty verdict delivered for a "
                "sampled mutant's exec result"),
            "corpus.add": t.histogram(
                "tz_lineage_corpus_wait_seconds",
                "previous hop -> triaged input landed in the corpus"),
        }
    return _STAGE_WAITS


_STAGE_WAITS: Optional[dict] = None
_M_SAMPLED = None

#: Thread-local carrier for the context decoded off the most recent
#: RPC frame on this thread — lets a server-side method (e.g.
#: Manager.NewInput) continue the chain without a signature change in
#: the dispatch layer.
_local = threading.local()


def mint() -> Optional[TraceContext]:
    """Mint a trace context at mutation-flush time.  Returns None when
    the sampling coin says no — the zero-overhead path: nothing is
    allocated and every downstream hop is one `is None` test."""
    rate = sample_rate()
    if rate <= 0.0 or _rng.random() >= rate:
        return None
    ctx = TraceContext(_rng.getrandbits(64) or 1)
    _hists()
    _M_SAMPLED.inc()
    t = _telemetry()
    if t.TRACE.enabled():
        t.TRACE.point("lineage.mint", ctx.trace_id)
    return ctx


def hop(ctx: Optional[TraceContext], stage: str) -> None:
    """Record one lifecycle hop: the wait since the previous hop goes
    into the stage's histogram, and (tracing armed) an async-instant
    event keyed by the trace id joins the mutant's correlated track."""
    if ctx is None or not ctx.sampled:
        return
    now = time.perf_counter()
    wait = max(0.0, now - ctx.last_ts)
    h = _hists().get(stage)
    if h is not None:
        h.observe(wait)
    t = _telemetry()
    if t.TRACE.enabled():
        t.TRACE.point(stage, ctx.trace_id,
                      {"wait_s": round(wait, 6),
                       "from": ctx.last_stage})
    ctx.last_ts = now
    ctx.last_wall = time.time()
    ctx.last_stage = stage


def to_wire(ctx: TraceContext) -> bytes:
    """Serialize for the RPC frame header (rpc/rpc.py _FLAG_TRACE)."""
    return WIRE.pack(ctx.trace_id, 1 if ctx.sampled else 0,
                     ctx.last_wall)


def from_wire(data: bytes) -> TraceContext:
    """Decode a frame-header context and record the `rpc.frame` hop —
    the cross-process edge.  The wait is wallclock (sender stamp to
    local receive) because perf_counter timebases are per-process."""
    trace_id, flags, sent_wall = WIRE.unpack(data)
    ctx = TraceContext(trace_id, sampled=bool(flags & 1))
    if ctx.sampled:
        wait = max(0.0, time.time() - sent_wall)
        h = _hists().get("rpc.frame")
        if h is not None:
            h.observe(wait)
        t = _telemetry()
        if t.TRACE.enabled():
            t.TRACE.point("rpc.frame", ctx.trace_id,
                          {"wait_s": round(wait, 6)})
        ctx.last_stage = "rpc.frame"
    return ctx


def set_current(ctx: Optional[TraceContext]) -> None:
    _local.ctx = ctx


def current() -> Optional[TraceContext]:
    """The context decoded off the most recent RPC frame received on
    THIS thread (None when the frame carried none)."""
    return getattr(_local, "ctx", None)
