"""Process-wide metrics registry: counters, gauges, histograms.

One registry is the source of truth for every runtime counter the
engine exposes — the pipeline hot-loop phases, the fuzzer Stat
counters, the RPC transport, and the health breaker/watchdog
transitions all register here, and the manager HTTP server renders
the same objects as Prometheus text (/metrics) and JSON (/api/stats).

Design constraints (ISSUE 2):
  - host-side only: nothing here may run inside jitted code, and all
    timing uses time.perf_counter on the host (no wallclock in
    kernels).  Wallclock (time.time) appears only in event timestamps
    and last-transition gauges, which exist for operator timelines.
  - cheap under contention: each metric has its own small lock;
    the registry lock guards only name->metric resolution, which
    callers do once at import/construction time.
  - histograms use FIXED log-spaced latency buckets (quarter-decade
    from 100 µs to 1000 s) so percentile estimates are comparable
    across processes and runs without coordination.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from collections import deque
from typing import Callable, Optional

#: Quarter-decade log-spaced bounds from 1e-4 s (100 µs) to 1e3 s.
#: Fixed (not configurable per call site) so every span histogram in
#: every process buckets identically — snapshots merge and compare.
DEFAULT_LATENCY_BUCKETS = tuple(10.0 ** (e / 4.0) for e in range(-16, 13))

#: Bounded transition-event timeline (breaker trips, wedges, demotions)
#: kept alongside the numeric metrics so a wedge window has a story,
#: not just counts.
EVENT_RING_SIZE = 256


class Counter:
    """Monotonic counter (float-valued: backoff-seconds accumulate
    here too).

    `labels` attaches a fixed label set to the series (ISSUE 7: the
    coverage plane exports one novelty family across workqueue lanes,
    `tz_coverage_novel_edges_total{source=...}`), mirroring the
    labeled-gauge support below: each label combination is its own
    metric object while the family shares one TYPE/HELP line."""

    __slots__ = ("name", "help", "_lock", "_value", "labels")

    def __init__(self, name: str, help: str = "",
                 labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0
        self.labels = dict(labels) if labels else None

    @property
    def full_name(self) -> str:
        return _labeled_name(self.name, self.labels)

    def inc(self, v: float = 1) -> None:
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


def _labeled_name(name: str, labels: Optional[dict]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class Gauge:
    """Point-in-time value.  Either set() push-style, or pull-style
    via `fn` (sampled at snapshot/render time — used for corpus size
    and queue depth owned by other objects).

    `labels` attaches a fixed label set to the series (ISSUE 6: the
    per-kernel profiler exports one family across kernels,
    `tz_device_kernel_ms_per_batch{kernel=...}`).  The registry keys
    labeled gauges by full_name, so each label combination is its own
    metric object while the family shares one TYPE/HELP line."""

    __slots__ = ("name", "help", "_lock", "_value", "fn", "labels")

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None,
                 labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0
        self.fn = fn
        self.labels = dict(labels) if labels else None

    @property
    def full_name(self) -> str:
        return _labeled_name(self.name, self.labels)

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return 0.0
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram:
    """Fixed-bound histogram with percentile estimation.

    Buckets are cumulative at render time (Prometheus `le` semantics);
    internally per-bucket counts.  percentile() linearly interpolates
    within the owning bucket and clamps to the observed min/max, so
    estimates never leave the data range."""

    __slots__ = ("name", "help", "bounds", "_lock", "_counts", "_sum",
                 "_count", "_min", "_max")

    def __init__(self, name: str, help: str = "",
                 bounds: Optional[tuple] = None):
        self.name = name
        self.help = help
        self.bounds = tuple(bounds) if bounds else DEFAULT_LATENCY_BUCKETS
        self._lock = threading.Lock()
        # one overflow bucket past the last bound (= +Inf)
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = float("inf")
        self._max = float("-inf")

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]) from bucket counts."""
        with self._lock:
            return self._percentile_locked(q)

    def _percentile_locked(self, q: float) -> float:
        return percentile_from_counts(self.bounds, self._counts,
                                      self._count, self._min,
                                      self._max, q)

    def snapshot(self) -> dict:
        with self._lock:
            cum, buckets = 0, []
            for i, b in enumerate(self.bounds):
                cum += self._counts[i]
                buckets.append([b, cum])
            buckets.append(["+Inf", cum + self._counts[-1]])
            return {
                "count": self._count,
                "sum": round(self._sum, 6),
                "min": round(self._min, 6) if self._count else 0.0,
                "max": round(self._max, 6) if self._count else 0.0,
                "p50": round(self._percentile_locked(0.50), 6),
                "p90": round(self._percentile_locked(0.90), 6),
                "p99": round(self._percentile_locked(0.99), 6),
                "buckets": buckets,
            }

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0
            self._min = float("inf")
            self._max = float("-inf")


def percentile_from_counts(bounds, counts, total: int, mn: float,
                           mx: float, q: float) -> float:
    """q-quantile estimate from per-bucket counts: linear
    interpolation within the owning bucket, clamped to [mn, mx].
    Shared by live Histograms and merged cross-process snapshots —
    the fixed shared buckets make both the same computation."""
    if total == 0:
        return 0.0
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else mx
            est = lo + (hi - lo) * ((rank - cum) / c)
            return min(max(est, mn), mx)
        cum += c
    return mx


def merge_histogram_snapshots(snaps: list) -> dict:
    """Vector-add Histogram.snapshot() dicts from N processes.

    This is the payoff of the fixed-shared-buckets design constraint:
    cross-process histogram merging is a per-bucket sum, with
    percentiles re-estimated from the merged counts.  Snapshots whose
    bucket bounds disagree (a version-skewed fuzzer) are skipped
    rather than corrupting the merge."""
    snaps = [s for s in snaps if s and s.get("buckets")]
    if not snaps:
        return {}
    les = [b[0] for b in snaps[0]["buckets"]]
    per = [0] * len(les)
    total, ssum = 0, 0.0
    mn, mx = float("inf"), float("-inf")
    for s in snaps:
        if [b[0] for b in s["buckets"]] != les:
            continue
        prev = 0
        for i, (_le, cum) in enumerate(s["buckets"]):
            per[i] += cum - prev
            prev = cum
        total += s.get("count", 0)
        ssum += s.get("sum", 0.0)
        if s.get("count"):
            mn = min(mn, s.get("min", 0.0))
            mx = max(mx, s.get("max", 0.0))
    if total == 0:
        mn = mx = 0.0
    bounds = tuple(le for le in les if le != "+Inf")
    cum, buckets = 0, []
    for i, le in enumerate(les):
        cum += per[i]
        buckets.append([le, cum])
    return {
        "count": total,
        "sum": round(ssum, 6),
        "min": round(mn, 6),
        "max": round(mx, 6),
        "p50": round(percentile_from_counts(
            bounds, per, total, mn, mx, 0.50), 6),
        "p90": round(percentile_from_counts(
            bounds, per, total, mn, mx, 0.90), 6),
        "p99": round(percentile_from_counts(
            bounds, per, total, mn, mx, 0.99), 6),
        "buckets": buckets,
    }


def merge_snapshots(snaps: list) -> dict:
    """Merge N processes' Registry.snapshot() payloads into one fleet
    rollup: counters and gauges sum (each process contributes its
    monotonic totals / current depths), histograms vector-add.  The
    manager runs this over the latest per-fuzzer poll snapshots —
    cumulative payloads make latest-wins idempotent, so a lost poll
    costs staleness, never correctness."""
    out: dict = {"sources": 0, "counters": {}, "gauges": {},
                 "histograms": {}}
    hists: dict[str, list] = {}
    for s in snaps:
        if not s:
            continue
        out["sources"] += 1
        for name, v in (s.get("counters") or {}).items():
            out["counters"][name] = out["counters"].get(name, 0) + v
        for name, v in (s.get("gauges") or {}).items():
            out["gauges"][name] = out["gauges"].get(name, 0) + v
        for name, h in (s.get("histograms") or {}).items():
            hists.setdefault(name, []).append(h)
    for name, hs in hists.items():
        merged = merge_histogram_snapshots(hs)
        if merged:
            out["histograms"][name] = merged
    return out


def _merge_label_suffix(name: str, pairs: str) -> str:
    """Attach extra `k="v",` pairs to a sample name that may already
    carry a label set (a labeled gauge riding a fleet merge):
    `fam{kernel="mutate"}` + `source="fleet",` →
    `fam{kernel="mutate",source="fleet"}`."""
    base, brace, rest = name.partition("{")
    base = base.replace(".", "_")
    inner = rest[:-1] if brace else ""
    extra = pairs.rstrip(",")
    merged = ",".join(p for p in (inner, extra) if p)
    return f"{base}{{{merged}}}" if merged else base


def render_prometheus_snapshot(snap: dict,
                               labels: Optional[dict] = None) -> str:
    """Prometheus text for a snapshot DICT (e.g. a fleet merge), with
    optional labels distinguishing it from the process-local series —
    the manager appends the fleet rollup to /metrics as
    `...{source="fleet"}` next to its own registry."""
    pairs = "".join(f'{k}="{v}",' for k, v in (labels or {}).items())
    lines = []
    for name, v in sorted((snap.get("counters") or {}).items()):
        lines.append(f"{_merge_label_suffix(name, pairs)} {_fmt(v)}")
    for name, v in sorted((snap.get("gauges") or {}).items()):
        lines.append(f"{_merge_label_suffix(name, pairs)} {_fmt(v)}")
    for name, h in sorted((snap.get("histograms") or {}).items()):
        name = name.replace(".", "_")
        for le, cum in h.get("buckets") or []:
            label = le if le == "+Inf" else format(le, ".6g")
            lines.append(_merge_label_suffix(
                f'{name}_bucket{{le="{label}"}}', pairs) + f" {cum}")
        lines.append(f"{_merge_label_suffix(name + '_sum', pairs)} "
                     f"{_fmt(h.get('sum', 0))}")
        lines.append(f"{_merge_label_suffix(name + '_count', pairs)} "
                     f"{h.get('count', 0)}")
    return "\n".join(lines) + ("\n" if lines else "")


def _fmt(v: float) -> str:
    """Render integral floats as ints (counter values are usually
    counts; backoff-seconds and gauges keep their fraction)."""
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class Registry:
    """Name -> metric map with get-or-create registration.

    Registration is idempotent (same name + same kind returns the
    existing object, so module-level registration in N instances of a
    class shares one metric) and kind-checked (same name + different
    kind raises — that is always a bug)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}
        self._events: deque = deque(maxlen=EVENT_RING_SIZE)

    def _get_or_create(self, name: str, kind, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, kind):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}, not {kind.__name__}")
                return m
            m = factory()
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[dict] = None) -> Counter:
        key = _labeled_name(name, labels)
        return self._get_or_create(key, Counter,
                                   lambda: Counter(name, help, labels))

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None,
              labels: Optional[dict] = None) -> Gauge:
        key = name
        if labels:
            key = Gauge(name, labels=labels).full_name
        g = self._get_or_create(
            key, Gauge, lambda: Gauge(name, help, fn, labels))
        if fn is not None:
            # Re-registering with a callback rebinds it: a fresh
            # manager in the same process must sample ITS corpus, not
            # a closed predecessor's.
            g.fn = fn
        return g

    def histogram(self, name: str, help: str = "",
                  bounds: Optional[tuple] = None) -> Histogram:
        return self._get_or_create(name, Histogram,
                                   lambda: Histogram(name, help, bounds))

    # -- events ------------------------------------------------------------

    def record_event(self, name: str, detail: str = "") -> None:
        """Append to the bounded transition timeline (wallclock ts —
        operators correlate these against logs and bench journals)."""
        with self._lock:
            self._events.append((time.time(), name, detail))

    def events(self) -> list[tuple[float, str, str]]:
        with self._lock:
            return list(self._events)

    # -- exposition --------------------------------------------------------

    def snapshot(self) -> dict:
        """One JSON-ready dict of everything: the API bench_watch and
        /api/stats consume."""
        with self._lock:
            metrics = list(self._metrics.values())
            events = list(self._events)
        out = {"ts": time.time(), "counters": {}, "gauges": {},
               "histograms": {},
               "events": [[round(ts, 3), n, d] for ts, n, d in events]}
        for m in metrics:
            if isinstance(m, Counter):
                out["counters"][m.full_name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][m.full_name] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][m.name] = m.snapshot()
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (the /metrics body)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        # HELP/TYPE are per FAMILY: labeled gauges sharing one family
        # name must emit the header exactly once (promcheck enforces).
        seen_families: set[str] = set()
        for m in metrics:
            name = m.name.replace(".", "_")
            if name not in seen_families:
                seen_families.add(name)
                if m.help:
                    lines.append(f"# HELP {name} {m.help}")
                kind = ("counter" if isinstance(m, Counter) else
                        "gauge" if isinstance(m, Gauge) else "histogram")
                lines.append(f"# TYPE {name} {kind}")
            if isinstance(m, Counter):
                lines.append(
                    f"{_merge_label_suffix(m.full_name, '')}"
                    f" {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(
                    f"{_merge_label_suffix(m.full_name, '')}"
                    f" {_fmt(m.value)}")
            elif isinstance(m, Histogram):
                snap = m.snapshot()
                for le, cum in snap["buckets"]:
                    label = le if le == "+Inf" else format(le, ".6g")
                    lines.append(
                        f'{name}_bucket{{le="{label}"}} {cum}')
                lines.append(f"{name}_sum {_fmt(snap['sum'])}")
                lines.append(f"{name}_count {snap['count']}")
        return "\n".join(lines) + "\n"

    def dump_snapshot(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.snapshot(), f)
            f.write("\n")

    def reset_values(self) -> None:
        """Zero every metric IN PLACE and clear the event ring.  For
        tests: module-level metric references stay valid (dropping the
        objects would silently disconnect already-imported modules)."""
        with self._lock:
            metrics = list(self._metrics.values())
            self._events.clear()
        for m in metrics:
            m._reset()
