"""Coverage intelligence: is the fuzzer still learning?

The runtime-observability spine (spans, lineage, profiler, flight
recorder) answers "is the engine healthy"; nothing before this module
answered the top-level question of a coverage-guided fuzzer.  The
reference tracks coverage as scalar stats (pkg/signal lengths on the
manager page); the fuzzing-evaluation literature (Klees et al.,
"Evaluating Fuzz Testing", CCS'18) established coverage-GROWTH
curves, not point totals, as the meaningful signal.  This module is
the host-side half of that layer:

  - a bounded growth-curve ring of (wallclock, plane occupancy,
    novel-edge delta) samples, fed at flush cadence by the triage
    engine's device reductions (ops/signal.coverage_stats) — the
    curve /api/coverage and bench_watch render,
  - an EWMA novelty rate (novel edges/s) — the scheduler-facing
    scalar the ROADMAP's multi-tenant QoS lanes will consume,
  - a plateau/stall detector: when a trailing window of
    TZ_COVERAGE_STALL_WINDOW_S seconds carries fewer than
    TZ_COVERAGE_STALL_EDGES novel edges, the tracker emits a
    `coverage.stall` timeline event, a structured flight-recorder
    incident (growth-curve tail + attribution table riding the
    payload), and flips the `tz_coverage_stalled` gauge the manager
    status page surfaces.  The first novel edge after a stall emits
    `coverage.resume` and clears the flag,
  - per-source novelty attribution: every novelty verdict carries its
    workqueue lane (fuzzer/workqueue.py bands + the generate/mutate
    fallback = "exploration"), counted into the labeled family
    `tz_coverage_novel_edges_total{lane=...}` plus a per-proc
    rollup — the demand signal the multi-tenant serving plane
    schedules on, and the per-source diff input for federated hub
    sync.  The label name is `lane`, not `source`: `source=` is the
    fleet merge's provenance label (render_prometheus_snapshot), and
    a colliding key would emit duplicate label names on /metrics.

Everything here is host-side float/dict math under one small lock —
no jits, no allocations beyond the bounded ring — and the tracker is
fed from the novelty-verdict path (Fuzzer.check_new_signal_fn) and
the triage engine's flush-cadence analytics, never from inside jitted
code.  `time_fn` is injectable so the stall detector is scriptable in
tests without sleeping through the window.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

#: The workqueue lanes novelty is attributed to (fuzzer/workqueue.py
#: priority bands; "exploration" is the generate/mutate fallback the
#: procs run when the queue is empty).  Fixed at import so the
#: labeled family renders completely (all-zero series included) on
#: the first /metrics scrape.
SOURCES = ("triage_candidate", "candidate", "triage", "smash",
           "exploration", "distill", "hints")

DEFAULT_STALL_WINDOW_S = 300.0
DEFAULT_STALL_EDGES = 1
DEFAULT_INTERVAL_S = 5.0
DEFAULT_AUDIT_S = 60.0
DEFAULT_RING = 512

#: EWMA weight per tick for the novelty rate (telemetry/profiler.py
#: uses the same settling-vs-straggler tradeoff).
EWMA_ALPHA = 0.2


def _env():
    # The envsafe SUBMODULE directly: the health package __init__
    # imports telemetry (watchdog metrics), and telemetry constructs
    # the COVERAGE singleton at import — going through the package
    # here would re-enter it half-initialized.
    from syzkaller_tpu.health.envsafe import env_float, env_int

    return env_float, env_int


class CoverageTracker:
    """Process-wide coverage growth/attribution state; see module doc.

    One tracker per process (`telemetry.COVERAGE`); tests construct
    their own with an injected clock.  All public methods are cheap
    and thread-safe: note_novel() runs on the novelty-verdict path
    (rare — >99.9% of checks carry nothing new) and tick()/sample()
    at flush cadence."""

    def __init__(self, time_fn: Callable[[], float] = time.time,
                 stall_window_s: Optional[float] = None,
                 stall_edges: Optional[int] = None,
                 interval_s: Optional[float] = None,
                 ring: Optional[int] = None):
        from syzkaller_tpu import telemetry

        env_float, env_int = _env()
        self._time = time_fn
        self.stall_window_s = max(1.0, env_float(
            "TZ_COVERAGE_STALL_WINDOW_S",
            DEFAULT_STALL_WINDOW_S if stall_window_s is None
            else stall_window_s))
        self.stall_edges = max(1, env_int(
            "TZ_COVERAGE_STALL_EDGES",
            DEFAULT_STALL_EDGES if stall_edges is None else stall_edges))
        self.interval_s = max(0.0, env_float(
            "TZ_COVERAGE_INTERVAL_S",
            DEFAULT_INTERVAL_S if interval_s is None else interval_s))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(
            16, env_int("TZ_COVERAGE_RING",
                        DEFAULT_RING if ring is None else ring)))
        now = self._time()
        self._t0 = now  # tracking start: the stall window needs history
        self._last_tick = now
        self._last_novel_ts = now
        self._novel_accum = 0  # novel edges since the last tick
        self._novel_total = 0
        self._ewma_rate = 0.0  # novel edges/s
        self._stalled = False
        self._stalls = 0
        self._occupancy = 0
        self._regions: Optional[list[int]] = None
        self._drift = {"ts": 0.0, "buckets": 0, "audits": 0}
        self._by_source: dict[str, int] = dict.fromkeys(SOURCES, 0)
        self._by_proc: dict[str, int] = {}
        # Durability (syzkaller_tpu/durable): a DurableStore.journal
        # callable; each growth-curve point journals a "cov" record so
        # the curve/EWMA survive a manager crash between checkpoints.
        self.journal = None
        self._src_counters = {
            s: telemetry.counter(
                "tz_coverage_novel_edges_total",
                "novel coverage edges confirmed, by originating "
                "workqueue lane", labels={"lane": s})
            for s in SOURCES}
        self._m_stalls = telemetry.counter(
            "tz_coverage_stalls_total",
            "coverage plateau incidents (the stall detector fired)")
        self._m_audits = telemetry.counter(
            "tz_coverage_audits_total",
            "device-vs-mirror drift audits run")
        self._g_occ = telemetry.gauge(
            "tz_coverage_occupancy",
            "occupied signal-plane buckets (exact device popcount at "
            "flush cadence)")
        self._g_rate = telemetry.gauge(
            "tz_coverage_novelty_rate",
            "EWMA novel coverage edges per second")
        self._g_stalled = telemetry.gauge(
            "tz_coverage_stalled",
            "1 while the plateau detector holds the fuzzer stalled")
        self._g_drift = telemetry.gauge(
            "tz_coverage_plane_drift",
            "plane buckets disagreeing with the host mirror at the "
            "last drift audit (nonzero = silent corruption caught)")

    # -- attribution (the novelty-verdict path) ---------------------------

    def note_novel(self, source: Optional[str], nedges: int,
                   proc=None) -> None:
        """`nedges` novel edges confirmed for one executed program;
        `source` is its workqueue lane (unknown/None folds into
        "exploration" — the label set stays bounded), `proc` the
        originating worker for the per-proc rollup."""
        if nedges <= 0:
            return
        src = source if source in self._by_source else "exploration"
        # Lane novelty joins the accounting ledger's yield EWMA
        # (ISSUE 14).  getattr: the ledger is constructed after this
        # tracker during telemetry import.
        from syzkaller_tpu import telemetry
        ledger = getattr(telemetry, "ACCOUNTING", None)
        if ledger is not None:
            ledger.note_novel("lane", src, nedges)
        resumed = False
        with self._lock:
            self._novel_accum += nedges
            self._novel_total += nedges
            self._last_novel_ts = self._time()
            self._by_source[src] += nedges
            if proc is not None:
                key = str(proc)
                self._by_proc[key] = self._by_proc.get(key, 0) + nedges
            if self._stalled:
                self._stalled = False
                resumed = True
        self._src_counters[src].inc(nedges)
        if resumed:
            from syzkaller_tpu import telemetry

            self._g_stalled.set(0)
            telemetry.record_event(
                "coverage.resume",
                f"{nedges} novel edges via {src} after a stall")

    # -- the growth curve + stall detector --------------------------------

    def sample(self, occupancy: int, regions=None, drift=None) -> None:
        """One flush-cadence analytics result (triage/engine): the
        exact plane occupancy, optionally the region heat map and a
        drift-audit verdict.  Appends a growth-curve point."""
        with self._lock:
            self._occupancy = int(occupancy)
            if regions is not None:
                self._regions = [int(r) for r in regions]
            if drift is not None:
                self._drift = {"ts": round(self._time(), 3),
                               "buckets": int(drift),
                               "audits": self._drift["audits"] + 1}
        self._g_occ.set(int(occupancy))
        if drift is not None:
            self._m_audits.inc()
            self._g_drift.set(int(drift))
        self.tick(force=True)

    def tick(self, force: bool = False) -> None:
        """Advance the growth curve / stall detector.  Rate-limited to
        interval_s unless forced; called from sample() and (cheaply)
        from the novelty-verdict path so a fuzzer whose engine never
        flushes still detects its own plateau."""
        stalled_now = None
        with self._lock:
            now = self._time()
            if not force and now - self._last_tick < self.interval_s:
                return
            delta, self._novel_accum = self._novel_accum, 0
            dt = max(1e-9, now - self._last_tick)
            self._last_tick = now
            self._ring.append(
                (round(now, 3), self._occupancy, delta))
            rate = delta / dt
            self._ewma_rate += EWMA_ALPHA * (rate - self._ewma_rate)
            # Stall: the trailing window carried fewer than
            # stall_edges novel edges — and only once the tracker has
            # a full window of history, so startup is never a
            # false plateau.
            window = self.stall_window_s
            in_window = sum(
                d for ts, _occ, d in self._ring if ts >= now - window)
            if not self._stalled and now - self._t0 >= window \
                    and now - self._last_novel_ts >= window \
                    and in_window < self.stall_edges:
                self._stalled = True
                self._stalls += 1
                stalled_now = (in_window, window)
            ewma = self._ewma_rate
            point = (round(now, 3), self._occupancy, delta,
                     ewma, self._novel_total)
        self._g_rate.set(round(ewma, 6))
        journal = self.journal
        if journal is not None:
            # After the mutation, outside the lock: the "cov" record
            # is an idempotent overwrite+append (durable/recovery.py),
            # so racing a checkpoint is harmless.
            ts, occ, delta, ewma, total = point
            journal("cov", {"ts": ts, "occ": occ, "delta": delta,
                            "ewma": round(ewma, 9), "total": total})
        if stalled_now is not None:
            self._note_stalled(*stalled_now)

    def _note_stalled(self, in_window: int, window: float) -> None:
        from syzkaller_tpu import telemetry

        detail = (f"{in_window} novel edges in the last {window:.0f}s "
                  f"(threshold {self.stall_edges})")
        self._m_stalls.inc()
        self._g_stalled.set(1)
        telemetry.record_event("coverage.stall", detail)
        telemetry.FLIGHT.dump(
            "coverage_stalled", detail,
            extra={"growth_curve": self.curve(64),
                   "attribution": self.attribution()})

    # -- read side ---------------------------------------------------------

    def curve(self, tail: Optional[int] = None) -> list:
        """The growth curve as [[ts, occupancy, novel_delta], ...]."""
        with self._lock:
            pts = list(self._ring)
        pts = pts[-tail:] if tail else pts
        return [[ts, occ, d] for ts, occ, d in pts]

    def attribution(self) -> dict:
        with self._lock:
            return {
                "by_source": {s: n for s, n in self._by_source.items()
                              if n},
                "by_proc": dict(self._by_proc),
                "total_novel_edges": self._novel_total,
            }

    def stalled(self) -> bool:
        with self._lock:
            return self._stalled

    def snapshot(self) -> dict:
        """The /api/coverage payload: growth curve, heat regions,
        attribution table, drift status, stall semantics."""
        with self._lock:
            out = {
                "occupancy": self._occupancy,
                "novelty_rate_ewma": round(self._ewma_rate, 6),
                "novel_edges_total": self._novel_total,
                "stalled": self._stalled,
                "stalls": self._stalls,
                "stall_window_s": self.stall_window_s,
                "stall_edges": self.stall_edges,
                "last_novel_age_s": round(
                    max(0.0, self._time() - self._last_novel_ts), 3),
                "heat_regions": list(self._regions)
                if self._regions is not None else None,
                "drift": dict(self._drift),
            }
        out["growth_curve"] = self.curve()
        out["attribution"] = self.attribution()
        return out

    def export_state(self) -> dict:
        """The durable checkpoint's "coverage" section meta (all-JSON,
        no blob): growth ring, EWMA, attribution, stall bookkeeping.
        Timestamps are absolute (the tracker's time_fn is wallclock in
        production), so a warm restart keeps the curve continuous."""
        with self._lock:
            return {
                "ring": [[ts, occ, d] for ts, occ, d in self._ring],
                "t0": self._t0,
                "last_tick": self._last_tick,
                "last_novel_ts": self._last_novel_ts,
                "novel_total": self._novel_total,
                "ewma_rate": self._ewma_rate,
                "stalled": self._stalled,
                "stalls": self._stalls,
                "occupancy": self._occupancy,
                "by_source": dict(self._by_source),
                "by_proc": dict(self._by_proc),
            }

    def restore_state(self, state: dict) -> None:
        """Install a recovered curve (recovery.replay's "coverage"
        value — export_state() plus any journaled "cov" points)."""
        with self._lock:
            self._ring.clear()
            for pt in state.get("ring") or []:
                self._ring.append((float(pt[0]), int(pt[1]),
                                   int(pt[2])))
            now = self._time()
            self._t0 = float(state.get("t0") or self._t0)
            self._last_tick = min(
                now, float(state.get("last_tick") or now))
            self._last_novel_ts = min(
                now, float(state.get("last_novel_ts") or now))
            self._novel_total = int(state.get("novel_total") or 0)
            self._ewma_rate = float(state.get("ewma_rate") or 0.0)
            self._stalled = bool(state.get("stalled", False))
            self._stalls = int(state.get("stalls") or 0)
            self._occupancy = int(state.get("occupancy") or 0)
            for s, n in (state.get("by_source") or {}).items():
                if s in self._by_source:
                    self._by_source[s] = int(n)
            self._by_proc = {str(k): int(v) for k, v
                             in (state.get("by_proc") or {}).items()}
            ewma, occ, stalled = (self._ewma_rate, self._occupancy,
                                  self._stalled)
        self._g_rate.set(round(ewma, 6))
        self._g_occ.set(occ)
        self._g_stalled.set(1 if stalled else 0)

    def reset(self) -> None:
        """Back to construction state (tests); registry counters are
        reset separately via telemetry.reset()."""
        with self._lock:
            now = self._time()
            self._ring.clear()
            self._t0 = self._last_tick = self._last_novel_ts = now
            self._novel_accum = self._novel_total = 0
            self._ewma_rate = 0.0
            self._stalled = False
            self._stalls = 0
            self._occupancy = 0
            self._regions = None
            self._drift = {"ts": 0.0, "buckets": 0, "audits": 0}
            self._by_source = dict.fromkeys(SOURCES, 0)
            self._by_proc = {}
