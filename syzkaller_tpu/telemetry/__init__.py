"""Unified telemetry layer: metrics registry, spans, trace export.

The observability spine of the engine (ISSUE 2).  Three consumers,
one source of truth:

  - the manager HTTP server renders the process registry as
    Prometheus text (/metrics) and JSON (/api/stats),
  - tools/bench_watch consumes snapshot() dumps for per-phase latency
    percentiles and breaker-transition timelines in its wedge
    diagnostics,
  - TZ_TRACE_FILE streams every span as a Chrome trace event so a
    wedge window opens in Perfetto (telemetry/trace.py).

Usage: metrics register once at import/construction time and are
cheap to update from any thread; spans wrap host-side hot-loop phases
(NEVER jitted code — timing is host perf_counter only):

    _M_BATCHES = telemetry.counter("tz_pipeline_batches_total", "...")
    with telemetry.span("pipeline.drain"):
        buf = np.asarray(rows_dev)

A span named "pipeline.drain" records into the histogram
`tz_pipeline_drain_seconds`; docs/observability.md catalogues every
name, and tools/lint_metrics.py keeps code and catalogue in sync.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from syzkaller_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    merge_histogram_snapshots,
    merge_snapshots,
    render_prometheus_snapshot,
)
from syzkaller_tpu.telemetry.flight import FlightRecorder
from syzkaller_tpu.telemetry.trace import ENV_VAR, TraceWriter

#: The process-wide registry.  Tests needing isolation construct their
#: own Registry; everything in-tree registers here.
REGISTRY = Registry()

#: The process-wide trace writer, armed by TZ_TRACE_FILE.
TRACE = TraceWriter(os.environ.get(ENV_VAR) or None)

#: The process-wide flight recorder (telemetry/flight.py): every
#: completed span lands in its bounded ring; incident dumps fire on
#: DeviceWedged / breaker-open / SIGTERM once a dump dir is armed
#: (TZ_FLIGHT_DIR or FLIGHT.set_dir()).
FLIGHT = FlightRecorder(registry=REGISTRY)


def counter(name: str, help: str = "", labels=None) -> Counter:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", fn=None, labels=None) -> Gauge:
    return REGISTRY.gauge(name, help, fn, labels)


def histogram(name: str, help: str = "", bounds=None) -> Histogram:
    return REGISTRY.histogram(name, help, bounds)


def record_event(name: str, detail: str = "") -> None:
    """Transition timeline entry + trace instant event (breaker
    trips, wedges, demotions)."""
    REGISTRY.record_event(name, detail)
    TRACE.instant(name, {"detail": detail} if detail else None)


def span_metric_name(span_name: str) -> str:
    """Canonical histogram name for a span: 'pipeline.drain' times
    into `tz_pipeline_drain_seconds`."""
    return "tz_" + span_name.replace(".", "_") + "_seconds"


class span:
    """Timing context for one host-side hot-loop phase.  Records the
    duration into the span's latency histogram and, when tracing is
    armed, emits a complete trace event."""

    __slots__ = ("name", "_hist", "_t0")

    def __init__(self, name: str):
        self.name = name
        self._hist = REGISTRY.histogram(span_metric_name(name))

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        self._hist.observe(dur)
        FLIGHT.note_span(self.name, dur)
        if TRACE.enabled():
            TRACE.emit(self.name, self._t0, dur)
        return False


def snapshot() -> dict:
    return REGISTRY.snapshot()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


def dump_snapshot(path: str) -> None:
    REGISTRY.dump_snapshot(path)


def set_trace_file(path: Optional[str]) -> None:
    TRACE.set_path(path)


def reset() -> None:
    """Zero every registered metric in place (tests)."""
    REGISTRY.reset_values()


# The causal layer on top of the registry (ISSUE 6): lineage trace
# contexts, the per-kernel device profiler, and the flight recorder.
# Imported AFTER the module-level handles exist — lineage/profiler
# resolve the registry lazily through this module.
from syzkaller_tpu.telemetry import lineage  # noqa: E402
from syzkaller_tpu.telemetry.profiler import (  # noqa: E402
    KernelProfiler,
    ShardProfiler,
)

#: Process-wide per-kernel device-time attribution
#: (tz_device_kernel_ms_per_batch{kernel=...}).
PROFILER = KernelProfiler()

#: Process-wide per-shard mesh device-time attribution
#: (tz_mesh_shard_ms_per_batch{shard=...}, parallel/fault_domain).
SHARD_PROFILER = ShardProfiler()

# The coverage intelligence layer (ISSUE 7): growth curve, novelty
# EWMA, plateau detector, per-lane attribution.  Same late-import
# shape as lineage/profiler.
from syzkaller_tpu.telemetry.coverage import (  # noqa: E402
    CoverageTracker,
)

#: Process-wide coverage growth/attribution tracker, fed by the
#: novelty-verdict path and the triage engine's flush-cadence
#: analytics (tz_coverage_*).
COVERAGE = CoverageTracker()

# The accounting & SLO plane (ISSUE 14): the device-time chargeback
# ledger and the burn-rate objective engine.  Same late-import shape.
from syzkaller_tpu.telemetry.accounting import (  # noqa: E402
    DeviceTimeLedger,
)
from syzkaller_tpu.telemetry.slo import SloEngine  # noqa: E402

#: Process-wide device-time ledger: per-tenant/lane/shard chargeback
#: (tz_acct_*), fed by the pipeline/triage/mesh/serve sync points.
ACCOUNTING = DeviceTimeLedger()

#: Process-wide SLO engine (tz_slo_*), ticked from the triage flush
#: leader and the manager stats path.
SLO = SloEngine()

# The device-residency plane (ISSUE 17): the HBM buffer ledger and
# the compile-cache observatory.  Same late-import shape.
from syzkaller_tpu.telemetry.compiles import (  # noqa: E402
    CompileObservatory,
    assert_no_new_compiles,
)
from syzkaller_tpu.telemetry.hbm import DeviceBufferLedger  # noqa: E402

#: Process-wide HBM residency ledger (tz_hbm_*): every long-lived
#: device buffer registers here under {owner, device, kind}; the
#: triage analytics cadence reconciles it against the backend's
#: live-buffer report.
HBM = DeviceBufferLedger(registry=REGISTRY, flight=FLIGHT)

#: Process-wide compile observatory (tz_compile_*): every XLA build
#: at the shared compile points, with storm detection — and the
#: single authority the warm-rig jit-count guards assert against.
COMPILES = CompileObservatory(registry=REGISTRY, flight=FLIGHT)

# Both residency tables ride EVERY flight incident (wedge / SIGTERM /
# slo-burn / plateau): a dump always answers "what was resident and
# what was compiling when this happened?".
FLIGHT.add_context("hbm", HBM.snapshot)
FLIGHT.add_context("compiles", COMPILES.snapshot)


__all__ = [
    "ACCOUNTING",
    "COMPILES",
    "COVERAGE",
    "CompileObservatory",
    "Counter",
    "CoverageTracker",
    "DEFAULT_LATENCY_BUCKETS",
    "DeviceBufferLedger",
    "DeviceTimeLedger",
    "FLIGHT",
    "FlightRecorder",
    "Gauge",
    "HBM",
    "Histogram",
    "KernelProfiler",
    "PROFILER",
    "REGISTRY",
    "Registry",
    "SHARD_PROFILER",
    "SLO",
    "ShardProfiler",
    "SloEngine",
    "TRACE",
    "TraceWriter",
    "assert_no_new_compiles",
    "lineage",
    "counter",
    "dump_snapshot",
    "gauge",
    "histogram",
    "merge_histogram_snapshots",
    "merge_snapshots",
    "record_event",
    "render_prometheus",
    "render_prometheus_snapshot",
    "reset",
    "set_trace_file",
    "snapshot",
    "span",
    "span_metric_name",
]
