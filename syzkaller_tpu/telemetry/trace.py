"""Chrome trace-event exporter: spans as Perfetto-loadable JSONL.

When TZ_TRACE_FILE names a path, every completed span() writes one
complete event ("ph": "X") line, so a wedge window can be opened in
Perfetto / chrome://tracing and read as a per-thread timeline — which
phase stalled, for how long, and what the other threads were doing.

File shape: the Chrome JSON array format with the closing "]" omitted
(explicitly allowed by the trace-event spec so crashed processes
still leave a loadable file — exactly our wedge use case).  Each
event is one line; timestamps are microseconds on the process-local
perf_counter timebase, with the wallclock origin recorded in the
leading metadata event so timelines can be correlated against logs.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

ENV_VAR = "TZ_TRACE_FILE"

#: Process-track name override for merged multi-process traces.  The
#: default derives from argv[0], which tells manager / fuzzer / hub
#: apart already; the knob is for launchers that exec one binary in
#: several roles.
ENV_PROCESS = "TZ_TRACE_PROCESS"


def _process_name() -> str:
    name = os.environ.get(ENV_PROCESS)
    if name:
        return name
    base = os.path.basename(sys.argv[0] or "") if sys.argv else ""
    return os.path.splitext(base)[0] or "tz"


class TraceWriter:
    """Thread-safe append-only trace-event writer.  Cheap when
    disabled: enabled() is one attribute load."""

    def __init__(self, path=None):
        self._lock = threading.Lock()
        self._file = None
        self._path = path
        self._t0 = time.perf_counter()

    def enabled(self) -> bool:
        return self._path is not None

    def set_path(self, path) -> None:
        """Install (or clear, with None) the trace target; closes any
        open file.  Tests and tools call this; production picks the
        path up from TZ_TRACE_FILE at import."""
        with self._lock:
            self._close_locked()
            self._path = path

    def _open_locked(self):
        if self._file is None and self._path is not None:
            self._file = open(self._path, "w")
            self._file.write("[\n")
            pid = os.getpid()
            meta = {"name": "process_start", "ph": "i", "ts": 0,
                    "pid": pid, "tid": 0, "s": "g",
                    "args": {"wallclock": time.time(),
                             "perf_counter": time.perf_counter()}}
            self._file.write(json.dumps(meta) + ",\n")
            # Chrome metadata events ("ph": "M"): concatenated
            # multi-process traces (manager + fuzzers + hub merged in
            # Perfetto) render each pid as its own NAMED process
            # track instead of interleaving anonymous ones.  The
            # sort_index keeps track order stable by pid.
            name = f"{_process_name()}/{pid}"
            for ev in (
                {"name": "process_name", "ph": "M", "pid": pid,
                 "tid": 0, "args": {"name": name}},
                {"name": "process_sort_index", "ph": "M", "pid": pid,
                 "tid": 0, "args": {"sort_index": pid}},
                {"name": "thread_name", "ph": "M", "pid": pid,
                 "tid": threading.get_ident(),
                 "args": {"name": threading.current_thread().name}},
            ):
                self._file.write(json.dumps(ev) + ",\n")
        return self._file

    def emit(self, name: str, t0: float, dur: float,
             args=None) -> None:
        """One complete event: t0 is the span's perf_counter start,
        dur its duration in seconds."""
        if self._path is None:
            return
        ev = {"name": name, "cat": "tz", "ph": "X",
              "ts": round((t0 - self._t0) * 1e6, 1),
              "dur": round(dur * 1e6, 1),
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        line = json.dumps(ev) + ",\n"
        with self._lock:
            f = self._open_locked()
            if f is None:
                return
            try:
                f.write(line)
                f.flush()  # wedge forensics: events must hit disk
            except OSError:
                self._close_locked()

    def instant(self, name: str, args=None) -> None:
        """Instant event ('i') — breaker trips, wedges, demotions."""
        self.emit(name, time.perf_counter(), 0.0, args)

    def point(self, name: str, trace_id: int, args=None) -> None:
        """Async-instant event ('n') keyed by a lineage trace id: every
        point sharing an id renders as one correlated track in
        Perfetto, across threads AND processes — the mechanism behind
        the per-mutant lifecycle view (telemetry/lineage.py)."""
        if self._path is None:
            return
        ev = {"name": name, "cat": "tz.lineage", "ph": "n",
              "ts": round((time.perf_counter() - self._t0) * 1e6, 1),
              "id": format(trace_id & 0xFFFFFFFFFFFFFFFF, "016x"),
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        line = json.dumps(ev) + ",\n"
        with self._lock:
            f = self._open_locked()
            if f is None:
                return
            try:
                f.write(line)
                f.flush()
            except OSError:
                self._close_locked()

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
