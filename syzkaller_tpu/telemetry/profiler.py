"""Per-kernel device-time attribution.

The ROADMAP's top perf item ("device kernel rate is now the
bottleneck — Pallas the mutation inner loop") gates on a measurement
that did not exist: the 16.9k mutations/s on-chip number is a
whole-pipeline residual, not a per-kernel attribution.  Two paths,
one exported family:

  always-on   the hot loops feed `note(kernel, seconds)` with the
              host-observed dispatch→ready latency of each kernel's
              sync point: the pipeline's fused mutate step ("mutate",
              dispatch to delta-rows-ready), the compacted payload
              pool fetch ("emit_compact"), and the triage verdict
              fetch ("novel_any").  Pure host float math — an EWMA
              per kernel into a labeled gauge — so the steady state
              adds no jit compiles and no allocations (pinned by a
              compile-count + container-growth regression test).
              These are host-observed numbers: on an async backend
              they include queue + transfer residency, which is
              exactly the operator question ("where does a batch's
              wall time go") but NOT a pure kernel microbenchmark.
              With the ISSUE 10 fused drain (mutate→emit-compact→
              novel_any in ONE dispatch, mutant plane on device),
              "mutate" covers dispatch to novel-rows-prefix-ready —
              the whole fused graph — and the `mutate.fused` span
              separately times the novel-count sync that gates the
              prefix fetch; per-kernel isolation inside the fused
              graph remains bench.py --profile's job.

  bench.py --profile
              the precise per-kernel numbers: each kernel dispatched
              alone on a warm pipeline at the flagship shape, timed
              around block_until_ready — the before/after measurement
              the Pallas rewrite is judged by.

Exported as `tz_device_kernel_ms_per_batch{kernel=...}` (one family,
a label per kernel — the registry's labeled-gauge support exists for
this series).
"""

from __future__ import annotations

import threading

KERNELS = ("mutate", "emit_compact", "novel_any", "hints")

#: EWMA weight for the always-on path: heavy enough to settle within
#: tens of batches, light enough to ride out a single straggler.
EWMA_ALPHA = 0.2


class KernelProfiler:
    """Process-wide per-kernel ms/batch EWMAs behind labeled gauges.

    The kernel set is FIXED at construction: note() on a steady-state
    hot loop touches only pre-allocated slots (no dict growth, no
    gauge registration) — the zero-allocation contract the regression
    guard pins."""

    __slots__ = ("_lock", "_ewma", "_counts", "_gauges")

    def __init__(self):
        from syzkaller_tpu import telemetry

        self._lock = threading.Lock()
        self._ewma = {k: 0.0 for k in KERNELS}
        self._counts = {k: 0 for k in KERNELS}
        self._gauges = {
            k: telemetry.gauge(
                "tz_device_kernel_ms_per_batch",
                "host-observed per-kernel device time per batch "
                "(EWMA ms; dispatch to ready at each kernel's sync "
                "point)", labels={"kernel": k})
            for k in KERNELS}

    def note(self, kernel: str, seconds: float) -> None:
        """One batch's host-observed device residency for `kernel`.
        Unknown kernels are ignored (the fixed-slot contract)."""
        if kernel not in self._ewma:
            return
        ms = seconds * 1e3
        with self._lock:
            n = self._counts[kernel]
            self._counts[kernel] = n + 1
            prev = self._ewma[kernel]
            cur = ms if n == 0 else prev + EWMA_ALPHA * (ms - prev)
            self._ewma[kernel] = cur
        self._gauges[kernel].set(cur)

    def snapshot(self) -> dict:
        with self._lock:
            return {k: {"ms_per_batch": round(self._ewma[k], 4),
                        "batches": self._counts[k]}
                    for k in KERNELS}


class ShardProfiler:
    """Per-SHARD device-time EWMAs for the fault-domain mesh
    (parallel/fault_domain), exported as
    `tz_mesh_shard_ms_per_batch{shard=...}` — the same labeled-gauge
    family pattern as KernelProfiler, keyed by mesh shard index
    instead of kernel name.

    Slots are created by ensure() when the mesh engine (re)builds its
    topology — never on the hot path — so note() in steady state
    touches only pre-allocated slots, keeping the zero-allocation
    contract the compile/container-growth guards pin."""

    __slots__ = ("_lock", "_ewma", "_counts", "_gauges")

    def __init__(self):
        self._lock = threading.Lock()
        self._ewma: dict = {}
        self._counts: dict = {}
        self._gauges: dict = {}

    def ensure(self, shard: int) -> None:
        """Pre-allocate the slot for a shard index (topology build
        time, not the hot path)."""
        from syzkaller_tpu import telemetry

        with self._lock:
            if shard in self._ewma:
                return
            self._ewma[shard] = 0.0
            self._counts[shard] = 0
            self._gauges[shard] = telemetry.gauge(
                "tz_mesh_shard_ms_per_batch",
                "host-observed per-shard device time per mesh batch "
                "(EWMA ms)", labels={"shard": str(shard)})

    def note(self, shard: int, seconds: float) -> None:
        """One batch's host-observed residency for one shard.
        Unknown shards are ignored (the fixed-slot contract)."""
        if shard not in self._ewma:
            return
        ms = seconds * 1e3
        with self._lock:
            n = self._counts[shard]
            self._counts[shard] = n + 1
            prev = self._ewma[shard]
            cur = ms if n == 0 else prev + EWMA_ALPHA * (ms - prev)
            self._ewma[shard] = cur
        self._gauges[shard].set(cur)

    def snapshot(self) -> dict:
        with self._lock:
            return {str(s): {"ms_per_batch": round(self._ewma[s], 4),
                             "batches": self._counts[s]}
                    for s in sorted(self._ewma)}
