"""Device-residency ledger: every long-lived HBM buffer, accounted.

Every observability layer before this one accounts for *time* —
spans (ISSUE 2), lineage waits (ISSUE 6), device-ms chargeback
(ISSUE 14) — but the scarcest resource on the chip is HBM, and until
now nothing could answer "what is resident right now, who owns it,
and how much headroom is left for the flagship batch?".  This module
is that answer (ISSUE 17): a process-wide ledger of long-lived
buffers — the signal plane and its mesh shards, the mutant and
speculation planes, sim table stacks, per-tenant planes, the pipeline
corpus/flag/prio tables, and the StagingArena's pinned host staging —
each registered under `{owner, device, kind}` labels.

Exports:
  - `tz_hbm_live_bytes{owner=,device=,kind=}` — current resident bytes
  - `tz_hbm_peak_bytes{owner=}`               — per-owner high-water
  - `tz_hbm_transient_bytes`                  — per-batch working-set
    estimate at the CURRENT batch shape (fed by the pipeline drain)
  - `tz_hbm_headroom_bytes`                   — capacity − resident −
    transient: the projected free bytes at the flagship batch shape,
    the direct sizing input for the ROADMAP's HBM corpus arena

Registration is handle-based: an owner registers once and updates the
handle when its buffer is rebuilt (plane invalidation, half-open ring
rebuild, mesh re-shard), so a rebuilt buffer REPLACES its ledger entry
instead of double-counting.  Handles hold weakrefs to the registered
arrays — never strong refs, so the ledger can never extend a buffer's
lifetime — and those weakrefs are what `reconcile()` checks against
the backend's live-buffer report (`jax.live_arrays()`): tracked bytes
must equal the backend-reported bytes for exactly those buffers, or an
`hbm.drift` flight incident fires (leaks and orphaned shards become
visible, not latent).  The triage engine runs reconcile at its
analytics cadence; nothing here ever runs inside jitted code.

Knobs (flight.py-style envsafe degradation — malformed values keep
the default; names live in health.envsafe.KNOWN_TZ_VARS):
  - TZ_HBM_CAPACITY_BYTES: HBM capacity for the headroom forecast.
    0 (default) probes the backend's memory_stats and falls back to
    16 GiB on backends that report none (CPU tests).
  - TZ_HBM_DRIFT_TOLERANCE_BYTES: reconcile mismatch tolerance
    (default 0 — conservation is exact).
  - TZ_HBM_RECONCILE: 0 disarms the cadence reconcile (default 1).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Optional

ENV_CAPACITY = "TZ_HBM_CAPACITY_BYTES"
ENV_TOLERANCE = "TZ_HBM_DRIFT_TOLERANCE_BYTES"
ENV_RECONCILE = "TZ_HBM_RECONCILE"

#: Headroom fallback when the backend reports no memory_stats (CPU
#: tests, older plugins).  Deliberately conservative — a v4 chip has
#: 32 GiB/core and a v5p 95 GiB; the knob restores any real value.
DEFAULT_CAPACITY_BYTES = 16 << 30

#: The closed set of ledger owners.  tools/lint_metrics.py cross-checks
#: every `HBM.register(...)`/`ledger.register(...)` call site against
#: this table — an owner string outside it (or an entry with no call
#: site) is a lint failure, so a new subsystem holding persistent
#: device state must declare itself here.
OWNERS = ("arena", "mesh", "pipeline", "serve", "sim", "staging",
          "triage")

#: Buffers living in host memory (pinned staging arenas, host
#: mirrors, per-tenant planes) register under device="host": they are
#: accounted and surfaced like everything else but excluded from the
#: headroom forecast and the backend reconcile — the live-buffer
#: report covers device allocations only.
DEVICE_HOST = "host"


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    try:
        return int(raw, 0) if raw else default
    except (TypeError, ValueError):
        return default


def _nbytes_and_refs(buffers) -> tuple[int, list]:
    """Total bytes + weakrefs for one registration payload: a single
    array, a list/tuple of arrays, a dict of arrays, or a plain byte
    count (no refs — excluded from identity reconcile)."""
    if buffers is None:
        return 0, []
    if isinstance(buffers, int):
        return buffers, []
    if isinstance(buffers, dict):
        buffers = list(buffers.values())
    elif not isinstance(buffers, (list, tuple)):
        buffers = [buffers]
    total, refs = 0, []
    for a in buffers:
        total += int(a.nbytes)
        refs.append(weakref.ref(a))
    return total, refs


def _device_label(buffers) -> str:
    """Device label for a payload: the owning device id, an id range
    for sharded arrays (mesh planes), or "host" for numpy/plain-byte
    registrations."""
    if buffers is None or isinstance(buffers, int):
        return DEVICE_HOST
    if isinstance(buffers, dict):
        buffers = list(buffers.values())
    elif not isinstance(buffers, (list, tuple)):
        buffers = [buffers]
    ids: set[int] = set()
    for a in buffers:
        devs = getattr(a, "devices", None)
        if devs is None:
            continue
        try:
            ids.update(d.id for d in a.devices())
        except Exception:
            continue
    if not ids:
        return DEVICE_HOST
    lo, hi = min(ids), max(ids)
    return str(lo) if lo == hi else f"{lo}-{hi}"


class BufferHandle:
    """One owner's registration for one buffer (or buffer group).
    `update()` when the buffer is rebuilt; `close()` when it is gone
    for good.  Both are cheap — a lock, a weakref sweep over the
    payload, and a per-label gauge refresh."""

    __slots__ = ("_ledger", "owner", "kind", "device", "nbytes",
                 "_refs", "closed")

    def __init__(self, ledger, owner: str, kind: str, device: str):
        self._ledger = ledger
        self.owner = owner
        self.kind = kind
        self.device = device
        self.nbytes = 0
        self._refs: list = []
        self.closed = False

    def update(self, buffers, device: Optional[str] = None) -> None:
        self._ledger._update(self, buffers, device)

    def close(self) -> None:
        self._ledger._close(self)

    def _close_quiet(self) -> None:
        """Finalizer-path close (register's bound_to): runs inside the
        garbage collector, which can fire while ANY thread holds the
        ledger lock (the publish sweep allocates), so it must never
        take that lock — flag only; the next locked publish prunes
        the entry and refreshes the gauges."""
        self.closed = True
        self.nbytes = 0
        self._refs = []

    def live_refs(self) -> list:
        """The registered arrays still alive (reconcile identity)."""
        return [a for a in (r() for r in self._refs) if a is not None]


class DeviceBufferLedger:
    """The process-wide {owner, device, kind} residency ledger."""

    def __init__(self, registry=None, flight=None):
        self._lock = threading.Lock()
        self._registry = registry
        self._flight = flight
        self._handles: list[BufferHandle] = []
        self._peaks: dict[str, int] = {}
        self._transients: dict[str, int] = {}
        self._published: set[tuple] = set()
        self._gauges: dict = {}
        self.last_reconcile: dict = {}
        self._headroom_gauge = None
        self._strikes = 0

    # -- registry plumbing -------------------------------------------------

    def _reg(self):
        if self._registry is None:
            from syzkaller_tpu import telemetry

            self._registry = telemetry.REGISTRY
        return self._registry

    def _flt(self):
        if self._flight is None:
            from syzkaller_tpu import telemetry

            self._flight = telemetry.FLIGHT
        return self._flight

    def _gauge(self, name: str, help: str, labels=None):
        key = (name, tuple(sorted((labels or {}).items())))
        g = self._gauges.get(key)
        if g is None:
            g = self._reg().gauge(name, help, labels=labels)
            self._gauges[key] = g
        return g

    # -- registration ------------------------------------------------------

    def register(self, owner: str, kind: str, buffers=None,
                 device: Optional[str] = None,
                 bound_to=None) -> BufferHandle:
        """Register one long-lived buffer (group) under
        {owner, device, kind}; returns the handle the owner keeps for
        rebuild updates.  `buffers`: array / list / dict of arrays, or
        a plain byte count for opaque host allocations.  `bound_to`
        ties the handle's lifetime to the owning engine object: when
        that object is collected the handle closes itself, so a
        transient engine (a re-created triage engine, a dropped sim
        prescorer) cannot rot the ledger with orphaned entries that
        reconcile would forever flag as drift."""
        h = BufferHandle(self, owner, kind,
                         device or _device_label(buffers))
        with self._lock:
            self._handles.append(h)
            self._set_locked(h, buffers, device)
        if bound_to is not None:
            weakref.finalize(bound_to, h._close_quiet)
        return h

    def _update(self, h: BufferHandle, buffers,
                device: Optional[str]) -> None:
        with self._lock:
            if h.closed:
                return
            self._set_locked(h, buffers, device)

    def _set_locked(self, h: BufferHandle, buffers,
                    device: Optional[str]) -> None:
        h.nbytes, h._refs = _nbytes_and_refs(buffers)
        if device is not None:
            h.device = device
        elif h._refs:
            h.device = _device_label(buffers)
        self._publish_locked()

    def _close(self, h: BufferHandle) -> None:
        with self._lock:
            if h.closed:
                return
            h.closed = True
            h.nbytes, h._refs = 0, []
            try:
                self._handles.remove(h)
            except ValueError:
                pass
            self._publish_locked()

    def _publish_locked(self) -> None:
        """Refresh the labeled gauge families from the handle list.
        The per-batch ledger tax IS this sweep — a dict sum over a
        handful of handles (bench.py --device pins it ≤ 50 µs)."""
        if any(h.closed for h in self._handles):
            # Entries flag-closed lock-free by the finalizer path
            # (bound_to engines collected since the last sweep).
            self._handles = [h for h in self._handles if not h.closed]
        sums: dict[tuple, int] = {}
        owners: dict[str, int] = {}
        for h in self._handles:
            k = (h.owner, h.device, h.kind)
            sums[k] = sums.get(k, 0) + h.nbytes
            owners[h.owner] = owners.get(h.owner, 0) + h.nbytes
        for k, v in sums.items():
            owner, device, kind = k
            self._gauge("tz_hbm_live_bytes",
                        "resident bytes per registered buffer group",
                        labels={"owner": owner, "device": device,
                                "kind": kind}).set(v)
        for k in self._published - set(sums):
            owner, device, kind = k
            self._gauge("tz_hbm_live_bytes", "",
                        labels={"owner": owner, "device": device,
                                "kind": kind}).set(0)
        self._published = set(sums)
        for owner, v in owners.items():
            peak = max(self._peaks.get(owner, 0), v)
            self._peaks[owner] = peak
            self._gauge("tz_hbm_peak_bytes",
                        "per-owner resident high-water mark",
                        labels={"owner": owner}).set(peak)
        if self._headroom_gauge is None:
            self._headroom_gauge = self._reg().gauge(
                "tz_hbm_headroom_bytes",
                "projected free HBM at the flagship batch shape",
                fn=self.headroom)
            self._reg().gauge(
                "tz_hbm_transient_bytes",
                "per-batch transient working-set estimate",
                fn=lambda: sum(self._transients.values()))

    # -- the headroom forecast ---------------------------------------------

    def note_transient(self, owner: str, nbytes: int) -> None:
        """Per-batch transient working set at the current (flagship)
        batch shape — the pipeline drain feeds its observed per-batch
        bytes here, so the headroom forecast subtracts what one
        in-flight batch needs on top of the resident set."""
        with self._lock:
            self._transients[owner] = int(nbytes)

    def capacity_bytes(self) -> int:
        cap = _env_int(ENV_CAPACITY, 0)
        if cap > 0:
            return cap
        try:
            import jax

            stats = jax.devices()[0].memory_stats()
            if stats and stats.get("bytes_limit"):
                return int(stats["bytes_limit"])
        except Exception:
            pass
        return DEFAULT_CAPACITY_BYTES

    def live_bytes(self, owner: Optional[str] = None,
                   device_only: bool = False) -> int:
        with self._lock:
            return sum(
                h.nbytes for h in self._handles
                if (owner is None or h.owner == owner)
                and not (device_only and h.device == DEVICE_HOST))

    def headroom(self) -> int:
        """capacity − device-resident − per-batch transient: the
        projected free bytes at the flagship batch shape (the sizing
        input for the device-resident corpus arena)."""
        with self._lock:
            resident = sum(h.nbytes for h in self._handles
                           if h.device != DEVICE_HOST)
            transient = sum(self._transients.values())
        return self.capacity_bytes() - resident - transient

    # -- reconcile ---------------------------------------------------------

    def reconcile(self, live_arrays=None,
                  tolerance: Optional[int] = None) -> dict:
        """Check conservation against the backend's live-buffer
        report: the bytes this ledger tracks for device buffers must
        equal the backend-reported bytes of exactly those buffers.  A
        mismatch beyond TZ_HBM_DRIFT_TOLERANCE_BYTES (an entry whose
        array died without an update — an orphaned shard — or bytes
        the backend no longer reports — a leak upstream of a handle)
        raises an `hbm.drift` flight incident.  Runs at the triage
        engine's analytics cadence; never raises."""
        t0 = time.perf_counter()
        with self._lock:
            # Identity-checkable device entries only: host memory
            # is outside the backend report, and an opaque byte-count
            # registration (no refs) has no identity to check.
            entries = [(h, h.nbytes, list(h._refs))
                       for h in self._handles
                       if h.device != DEVICE_HOST and h._refs]
        tracked, dead, tracked_ids = 0, 0, set()
        for _h, nbytes, refs in entries:
            live = [a for a in (r() for r in refs) if a is not None]
            if refs and not live:
                dead += 1
                continue
            tracked += nbytes
            tracked_ids.update(id(a) for a in live)
        if live_arrays is None:
            try:
                import jax

                live_arrays = jax.live_arrays()
            except Exception:
                live_arrays = []
        backend = sum(int(a.nbytes) for a in live_arrays
                      if id(a) in tracked_ids)
        drift = tracked - backend
        if tolerance is None:
            tolerance = _env_int(ENV_TOLERANCE, 0)
        seconds = time.perf_counter() - t0
        flagged = abs(drift) > tolerance or dead > 0
        out = {
            "tracked_bytes": tracked,
            "backend_bytes": backend,
            "drift_bytes": drift,
            "dead_entries": dead,
            "entries": len(entries),
            "flagged": flagged,
            "seconds": round(seconds, 6),
        }
        self.last_reconcile = out
        # Two-strike incident rule: an owner legitimately replacing a
        # buffer between the array swap and its handle update (the
        # pipeline worker races the analytics thread) reads as drift
        # for one pass and self-heals; a real leak or orphaned shard
        # persists.  Only the second consecutive flagged reconcile
        # fires the incident — and only ONCE per episode (same muting
        # as the compile-storm detector): a persistent leak must not
        # flood the event ring and the flight dir at every analytics
        # pass.  A clean reconcile re-arms.
        if flagged:
            self._strikes += 1
        else:
            self._strikes = 0
        if flagged and self._strikes == 2:
            from syzkaller_tpu import telemetry

            detail = (f"ledger drift {drift} bytes "
                      f"({dead} orphaned entries)")
            telemetry.counter(
                "tz_hbm_drift_total",
                "reconcile mismatches vs the backend report").inc()
            telemetry.record_event("hbm.drift", detail)
            self._flt().dump("hbm_drift", detail,
                             extra={"hbm": self.snapshot()})
        return out

    def reconcile_armed(self) -> bool:
        return _env_int(ENV_RECONCILE, 1) != 0

    # -- exposition --------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready residency table: per-owner totals and peaks,
        the per-{owner, device, kind} breakdown, and the headroom
        forecast (manager /api/device, flight incidents)."""
        with self._lock:
            rows = {}
            owners: dict[str, int] = {}
            for h in self._handles:
                if h.closed:
                    continue
                k = f'{h.owner}/{h.kind}@{h.device}'
                rows[k] = rows.get(k, 0) + h.nbytes
                owners[h.owner] = owners.get(h.owner, 0) + h.nbytes
            peaks = dict(self._peaks)
            transient = sum(self._transients.values())
            resident_dev = sum(h.nbytes for h in self._handles
                               if h.device != DEVICE_HOST)
        return {
            "owners": {o: {"live_bytes": v,
                           "peak_bytes": peaks.get(o, v)}
                       for o, v in sorted(owners.items())},
            "buffers": dict(sorted(rows.items())),
            "device_resident_bytes": resident_dev,
            "transient_bytes": transient,
            "capacity_bytes": self.capacity_bytes(),
            "headroom_bytes": self.headroom(),
            "last_reconcile": dict(self.last_reconcile),
        }
