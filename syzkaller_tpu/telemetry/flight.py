"""Flight recorder: the wedge evidence that collects itself.

BENCH_r05's wedge diagnosis was hand-collected (thread tables, log
archaeology — BENCH_WEDGE_DIAGNOSIS.md); the "reading a wedge"
procedure existed as prose, not as a mechanism that fires at wedge
time.  This module is that mechanism: a bounded per-process ring of
recent span samples plus periodic queue-depth/gauge samples, dumped
as ONE structured incident file the moment something goes wrong —

  - `DeviceWedged` (the watchdog converted a hung PJRT call),
  - a circuit-breaker trip to open,
  - SIGTERM (the supervisor is killing a process that may be mid-
    incident — the dump is the black box it leaves behind),
  - on demand via the manager's `/api/debug/flight` endpoint.

The incident file carries the breaker/transition timeline, the
last-N spans with durations, the queue-depth history, and the full
registry snapshot (per-phase percentiles) — everything the round-5
diagnosis needed, collected in milliseconds instead of hours.
`tools/bench_watch.py diagnose_wedge` renders it as its final layer.

Hot-path cost: one deque append per completed span (no allocation
beyond the tuple), one gauge sample sweep every GAUGE_SAMPLE_EVERY
spans.  Dump-to-disk is armed only when a dump directory is set
(`TZ_FLIGHT_DIR`, or set_dir() — bench.py and fuzzer/main arm it;
test fixtures stay silent), and is rate-limited per reason so a
failure storm costs one file, not a disk.

The snapshot embedded in a dump comes from `Registry.snapshot()` —
the same single-lock-acquisition read the PR 2 grab_stats race fix
mandates — never from iterating live counters mid-mutation
(tests/test_flight.py pins the conservation property under a
concurrent increment hammer).

`TZ_FLIGHT_RING` bounds the span ring (default 512, envsafe
semantics: malformed degrades to the default).
"""

from __future__ import annotations

import json
import os
import signal as _signal
import threading
import time
from collections import deque
from typing import Optional

ENV_RING = "TZ_FLIGHT_RING"
ENV_DIR = "TZ_FLIGHT_DIR"

DEFAULT_RING = 512
GAUGE_SAMPLE_EVERY = 32
GAUGE_HISTORY = 128

#: The queue/depth gauges sampled into the history ring — the "was
#: the producer or the consumer stalled?" question a wedge window
#: always starts with (docs/observability.md "Reading a wedge").
WATCH_GAUGES = (
    "tz_pipeline_queue_depth",
    "tz_pipeline_assemble_queue_depth",
    "tz_pipeline_batch_size",
    "tz_triage_batch_size",
    "tz_staging_assemble_depth",
    "tz_staging_h2d_dispatch_depth",
)


def _ring_size() -> int:
    raw = os.environ.get(ENV_RING)
    try:
        return max(16, int(raw, 0)) if raw else DEFAULT_RING
    except (TypeError, ValueError):
        return DEFAULT_RING


class FlightRecorder:
    """Bounded in-memory recorder + structured incident dumps."""

    def __init__(self, registry=None, size: Optional[int] = None):
        self._registry = registry
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=size or _ring_size())
        self._gauges: deque = deque(maxlen=GAUGE_HISTORY)
        self._notes = 0
        self._last_dump: dict[str, float] = {}
        self._dir = os.environ.get(ENV_DIR) or None
        self.min_interval_s = 30.0
        self.dumps = 0
        self._context: dict[str, object] = {}

    def attach_registry(self, registry) -> None:
        self._registry = registry

    def add_context(self, key: str, fn) -> None:
        """Register a payload provider folded into EVERY incident
        snapshot under `key` (ISSUE 17: the HBM residency table and
        the compile ledger ride every wedge/SIGTERM/slo-burn dump
        this way).  Providers run at snapshot time, best-effort — a
        provider that raises contributes nothing, never a failed
        dump."""
        with self._lock:
            self._context[key] = fn

    # -- recording ---------------------------------------------------------

    def note_span(self, name: str, dur: float) -> None:
        """One completed span (called from telemetry.span.__exit__):
        a deque append, plus a gauge sample sweep every Nth note."""
        with self._lock:
            self._spans.append((time.time(), name, round(dur, 6)))
            self._notes += 1
            if self._notes % GAUGE_SAMPLE_EVERY == 0:
                self._sample_gauges_locked()

    def _sample_gauges_locked(self) -> None:
        if self._registry is None:
            return
        sample = {"ts": round(time.time(), 3)}
        for name in WATCH_GAUGES:
            m = self._registry._metrics.get(name)
            if m is not None:
                # Push-gauge read: one small lock, no pull callbacks
                # (a pull gauge could re-enter a consumer lock from
                # the hot loop).
                sample[name] = m._value
        self._gauges.append(sample)

    # -- the incident payload ----------------------------------------------

    def snapshot(self, reason: str = "on_demand",
                 detail: str = "", extra: Optional[dict] = None) -> dict:
        """The structured incident payload: breaker/transition
        timeline, last-N spans, queue-depth history, and the full
        registry snapshot (the race-fixed single-acquisition read).
        `extra` keys are merged into the payload — the coverage
        plateau incident attaches its growth-curve tail and
        attribution table this way (telemetry/coverage.py)."""
        with self._lock:
            spans = list(self._spans)
            gauges = list(self._gauges)
            context = dict(self._context)
        reg_snap = self._registry.snapshot() if self._registry else {}
        events = reg_snap.get("events") or []
        payload = {
            "reason": reason,
            "detail": detail,
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "spans": [[round(ts, 3), n, d] for ts, n, d in spans],
            "queue_depths": gauges,
            "breaker_timeline": [
                e for e in events
                if e[1].startswith(("breaker.", "watchdog.",
                                    "triage.demote",
                                    "triage.repromote"))],
            "events": events,
            "registry": {k: reg_snap.get(k) for k in
                         ("counters", "gauges", "histograms")},
        }
        for key, fn in context.items():
            try:
                payload[key] = fn()
            except Exception:
                pass
        if extra:
            payload.update(extra)
        return payload

    # -- dumping -----------------------------------------------------------

    def set_dir(self, path: Optional[str]) -> None:
        """Arm (or, with None, disarm) incident dumps to disk."""
        with self._lock:
            self._dir = path

    def armed(self) -> bool:
        with self._lock:
            return self._dir is not None

    def dump(self, reason: str, detail: str = "",
             extra: Optional[dict] = None) -> Optional[str]:
        """Write one incident file; returns its path, or None when
        disarmed / rate-limited / the write failed.  Never raises —
        forensics must not compound the failure being recorded."""
        try:
            now = time.time()
            with self._lock:
                if self._dir is None:
                    return None
                last = self._last_dump.get(reason, 0.0)
                if now - last < self.min_interval_s:
                    return None
                self._last_dump[reason] = now
                dirpath = self._dir
            payload = self.snapshot(reason, detail, extra)
            path = os.path.join(
                dirpath, f"tz_flight_{reason}_{os.getpid()}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.write("\n")
            os.replace(tmp, path)
            self.dumps += 1
            _m_dumps().inc()
            return path
        except Exception:
            return None


def _m_dumps():
    from syzkaller_tpu import telemetry

    return telemetry.counter(
        "tz_flight_dumps_total", "flight-recorder incident dumps")


# -- SIGTERM hook ----------------------------------------------------------

_sigterm_installed = False
_sigterm_lock = threading.Lock()


def install_signal_handler(recorder=None) -> bool:
    """Dump a final incident file on SIGTERM, then deliver the signal
    to the previous handler (or the default).  Installed once per
    process, only from the main thread (signal module restriction);
    returns whether the handler is installed."""
    global _sigterm_installed
    with _sigterm_lock:
        if _sigterm_installed:
            return True
        if recorder is None:
            from syzkaller_tpu import telemetry

            recorder = telemetry.FLIGHT
        try:
            prev = _signal.getsignal(_signal.SIGTERM)

            def _on_sigterm(signum, frame):
                recorder.dump("sigterm", "SIGTERM received")
                if callable(prev):
                    prev(signum, frame)
                else:
                    _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
                    os.kill(os.getpid(), _signal.SIGTERM)

            _signal.signal(_signal.SIGTERM, _on_sigterm)
        except ValueError:  # not the main thread
            return False
        _sigterm_installed = True
        return True


def append_attempt(path: str, record: dict) -> None:
    """Append one measurement/probe attempt to a shared incident file
    (bench_watch's lease-catching journal: every wedged attempt is
    recorded instead of failing the round on the first one).  The
    file holds {"attempts": [...]}; created on first use.  Best
    effort — never raises."""
    try:
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            payload = {}
        attempts = payload.setdefault("attempts", [])
        record = dict(record)
        record.setdefault("ts", round(time.time(), 3))
        attempts.append(record)
        # Bounded: an unattended watcher must not grow this forever.
        del attempts[:-256]
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.write("\n")
        os.replace(tmp, path)
    except Exception:
        pass
