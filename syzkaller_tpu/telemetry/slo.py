"""SLO engine: declarative objectives + multi-window burn-rate
alerting (ISSUE 14, tentpole part 3).

Answers the second fleet-operator question: *is the fleet meeting
its service objectives*.  A small declarative table (SLO_TABLE)
defines floors and ceilings over metrics the registry already
carries; the engine samples each objective at flush cadence
(TZ_SLO_INTERVAL_S), keeps a ring of (ts, bad) verdicts per
objective, and computes error-budget burn over two windows in the
SRE multi-window style:

    burn(window) = breach_fraction(window) / budget

An objective FIRES when both the fast window (TZ_SLO_FAST_S,
page-grade signal) and the slow window (TZ_SLO_SLOW_S, sustained
confirmation) burn at ≥ TZ_SLO_BURN — the fast window alone reacting
to a blip never pages, and a window only votes once its ring spans
it (a freshly started manager can't fire on thirty seconds of
history).  Firing emits ONE `slo.burn` timeline event, latches
`tz_slo_burn{slo=...}` to 1, increments `tz_slo_burns_total`, and
dumps a `slo_burn` flight-recorder incident carrying the accounting
ledger's top-consumers table, so the page is self-diagnosing: the
alert names the objective, the attachment names who was eating the
device when it burned.  The latch clears with hysteresis (fast burn
back under TZ_SLO_BURN/2) and emits `slo.clear`.

Objectives (targets are env-tunable; tools/lint_slo.py validates the
table shape in tier-1):

  * device_util       — floor on device-seconds metered per wall
                        second (accounting ledger rate),
  * mutant_rate       — floor on exec-ready mutants per second
                        (tz_pipeline_mutants_total rate),
  * triage_p99        — ceiling on the novel_any verdict p99
                        (tz_triage_device_seconds),
  * breaker_open_ratio— ceiling on breaker opens per device batch
                        (tz_breaker_opens_total over triage+pipeline
                        batches),
  * delivery_p99      — ceiling on the serving drain p99
                        (tz_serve_dispatch_seconds, the per-tenant
                        delivery path).

Warm restart (durable provider in manager/manager.py) restores the
rings and latches: a burning objective stays latched through the
restart instead of false-firing `slo.clear` on recovery.

Import-cycle note: constructed at telemetry import; all telemetry
and envsafe access is late, like coverage.py.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

FAST_S_DEFAULT = 300.0
SLOW_S_DEFAULT = 3600.0
BURN_DEFAULT = 2.0
INTERVAL_S_DEFAULT = 5.0
BUDGET_DEFAULT = 0.1
#: Clear hysteresis: a latched burn clears only when the fast-window
#: burn drops under threshold * CLEAR_FRACTION.
CLEAR_FRACTION = 0.5

#: The declarative objective table.  `env`/`default` set the target,
#: `lo`/`hi` bound it (lint_slo), `metric` names the registry family
#: the value derives from (lint_slo checks it exists), `budget` is
#: the tolerated breach fraction.
SLO_TABLE = (
    {"name": "device_util", "kind": "floor",
     "env": "TZ_SLO_UTIL_FLOOR", "default": 0.001,
     "lo": 0.0, "hi": 1.0, "budget": BUDGET_DEFAULT,
     "metric": "tz_acct_device_ms_total",
     "help": "device-seconds metered per wall second"},
    {"name": "mutant_rate", "kind": "floor",
     "env": "TZ_SLO_MUTANT_RATE", "default": 1.0,
     "lo": 0.0, "hi": 1e9, "budget": BUDGET_DEFAULT,
     "metric": "tz_pipeline_mutants_total",
     "help": "exec-ready mutants produced per second"},
    {"name": "triage_p99", "kind": "ceiling",
     "env": "TZ_SLO_TRIAGE_P99_S", "default": 1.0,
     "lo": 1e-4, "hi": 60.0, "budget": BUDGET_DEFAULT,
     "metric": "tz_triage_device_seconds",
     "help": "novel_any verdict latency p99 (seconds)"},
    {"name": "breaker_open_ratio", "kind": "ceiling",
     "env": "TZ_SLO_BREAKER_RATIO", "default": 0.1,
     "lo": 0.0, "hi": 1.0, "budget": BUDGET_DEFAULT,
     "metric": "tz_breaker_opens_total",
     "help": "breaker opens per device batch"},
    {"name": "delivery_p99", "kind": "ceiling",
     "env": "TZ_SLO_DELIVERY_P99_S", "default": 1.0,
     "lo": 1e-4, "hi": 60.0, "budget": BUDGET_DEFAULT,
     "metric": "tz_serve_dispatch_seconds",
     "help": "serving-drain delivery latency p99 (seconds)"},
)


def _env():
    # Late import: health imports telemetry, and this module is
    # constructed at telemetry import time (coverage.py idiom).
    from syzkaller_tpu.health import envsafe
    return envsafe


class _SloState:
    __slots__ = ("obj", "target", "ring", "burning", "fired_ts",
                 "value", "fast_burn", "slow_burn", "gauge")

    def __init__(self, obj: dict, target: float, gauge):
        self.obj = obj
        self.target = target
        self.ring: list = []      # [(ts, bad)] pruned to the slow window
        self.burning = False
        self.fired_ts = 0.0
        self.value: Optional[float] = None
        self.fast_burn = 0.0
        self.slow_burn = 0.0
        self.gauge = gauge        # tz_slo_burn{slo=name}


class SloEngine:
    """See module doc.  Singleton lives at `telemetry.SLO`; ticked
    from the triage flush leader (_maybe_analytics_locked) and the
    manager stats path.  Tests construct private engines with
    injected `time_fn`, shrunk windows, and `value_overrides`."""

    def __init__(self, time_fn: Optional[Callable[[], float]] = None,
                 fast_s: Optional[float] = None,
                 slow_s: Optional[float] = None,
                 burn: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 table=None, value_overrides: Optional[dict] = None,
                 ledger=None):
        env = _env()
        import time as _time
        self._time = time_fn or _time.time
        self.fast_s = env.env_float("TZ_SLO_FAST_S", FAST_S_DEFAULT) \
            if fast_s is None else float(fast_s)
        self.slow_s = env.env_float("TZ_SLO_SLOW_S", SLOW_S_DEFAULT) \
            if slow_s is None else float(slow_s)
        self.burn_threshold = env.env_float("TZ_SLO_BURN", BURN_DEFAULT) \
            if burn is None else float(burn)
        self.interval_s = env.env_float(
            "TZ_SLO_INTERVAL_S", INTERVAL_S_DEFAULT) \
            if interval_s is None else float(interval_s)
        self._overrides = value_overrides or {}
        self._ledger = ledger
        self._lock = threading.Lock()
        self._last_tick = 0.0
        self._prev: dict = {}     # counter/ledger values at last tick
        from syzkaller_tpu import telemetry
        self._m_burns = telemetry.counter(
            "tz_slo_burns_total", "SLO burn alerts fired")
        self._slos: dict[str, _SloState] = {}
        for obj in (SLO_TABLE if table is None else table):
            target = env.env_float(obj["env"], obj["default"])
            gauge = telemetry.gauge(
                "tz_slo_burn",
                "1 while the objective's error budget is burning "
                "(fast AND slow window over TZ_SLO_BURN)",
                labels={"slo": obj["name"]})
            self._slos[obj["name"]] = _SloState(obj, target, gauge)

    # -- ledger resolution -------------------------------------------------

    def _acct(self):
        if self._ledger is not None:
            return self._ledger
        from syzkaller_tpu import telemetry
        return getattr(telemetry, "ACCOUNTING", None)

    # -- values ------------------------------------------------------------

    def _values(self, now: float, dt: float, snap: dict) -> dict:
        """One sample per objective; None means "not evaluable this
        tick" (no traffic on a latency ceiling) and appends nothing."""
        counters = snap.get("counters") or {}
        hists = snap.get("histograms") or {}

        def rate(name: str) -> float:
            cur = float(counters.get(name) or 0.0)
            prev = self._prev.get(name, cur)
            self._prev[name] = cur
            return max(0.0, cur - prev) / dt

        def p99(name: str) -> Optional[float]:
            h = hists.get(name)
            if not h or not h.get("count"):
                return None
            return float(h.get("p99") or 0.0)

        vals: dict = {}
        ledger = self._acct()
        ms = float(ledger.total_ms) if ledger is not None else 0.0
        prev_ms = self._prev.get("__ledger_ms__", ms)
        self._prev["__ledger_ms__"] = ms
        vals["device_util"] = max(0.0, ms - prev_ms) / 1e3 / dt
        vals["mutant_rate"] = rate("tz_pipeline_mutants_total")
        vals["triage_p99"] = p99("tz_triage_device_seconds")
        opens = rate("tz_breaker_opens_total") * dt
        batches = (rate("tz_triage_batches_total")
                   + rate("tz_pipeline_batches_total")) * dt
        vals["breaker_open_ratio"] = opens / max(1.0, batches)
        vals["delivery_p99"] = p99("tz_serve_dispatch_seconds")
        for name, fn in self._overrides.items():
            vals[name] = fn()
        return vals

    # -- burn math ---------------------------------------------------------

    def _window_burn(self, st: _SloState, now: float,
                     window: float) -> float:
        ring = st.ring
        if not ring or now - ring[0][0] < window * 0.9:
            # The ring doesn't span the window yet: no vote.  A
            # window must see its own history before it can page.
            return 0.0
        lo = now - window
        bad = n = 0
        for ts, b in ring:
            if ts >= lo:
                n += 1
                bad += b
        if n == 0:
            return 0.0
        budget = float(st.obj.get("budget") or BUDGET_DEFAULT)
        return (bad / n) / max(budget, 1e-9)

    # -- the tick ----------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> bool:
        """Sample + evaluate every objective; rate-limited to
        TZ_SLO_INTERVAL_S.  Never raises — alerting must not break
        the flush path that hosts it.  Returns True when a sample
        round ran."""
        try:
            return self._tick(now)
        except Exception as e:
            from syzkaller_tpu.utils import log
            log.logf(0, "slo: tick error: %s", e)
            return False

    def _tick(self, now: Optional[float]) -> bool:
        from syzkaller_tpu import telemetry
        with self._lock:
            t = self._time() if now is None else now
            if self._last_tick and t - self._last_tick \
                    < self.interval_s:
                return False
            dt = max(t - self._last_tick, 1e-9) \
                if self._last_tick else self.interval_s or 1.0
            self._last_tick = t
            snap = telemetry.REGISTRY.snapshot()
            vals = self._values(t, dt, snap)
            horizon = t - self.slow_s
            fired = []
            for name, st in self._slos.items():
                v = vals.get(name)
                st.value = v
                if v is not None:
                    bad = (v < st.target) \
                        if st.obj["kind"] == "floor" else \
                        (v > st.target)
                    st.ring.append((t, 1 if bad else 0))
                while st.ring and st.ring[0][0] < horizon:
                    st.ring.pop(0)
                st.fast_burn = self._window_burn(st, t, self.fast_s)
                st.slow_burn = self._window_burn(st, t, self.slow_s)
                if not st.burning and \
                        st.fast_burn >= self.burn_threshold and \
                        st.slow_burn >= self.burn_threshold:
                    st.burning = True
                    st.fired_ts = t
                    st.gauge.set(1)
                    self._m_burns.inc()
                    fired.append(st)
                elif st.burning and st.fast_burn <= \
                        self.burn_threshold * CLEAR_FRACTION:
                    st.burning = False
                    st.gauge.set(0)
                    telemetry.record_event(
                        "slo.clear",
                        f"{name} fast_burn={st.fast_burn:.2f}x")
        # Fire outside the lock: record_event and FLIGHT.dump take
        # their own locks, and the incident snapshot reads the whole
        # registry.
        for st in fired:
            val_s = f"{st.value:.4g}" if st.value is not None else "n/a"
            detail = (f"{st.obj['name']} value={val_s} "
                      f"target={st.target:.4g} "
                      f"fast={st.fast_burn:.2f}x "
                      f"slow={st.slow_burn:.2f}x")
            telemetry.record_event("slo.burn", detail)
            ledger = self._acct()
            telemetry.FLIGHT.dump(
                "slo_burn", detail,
                extra={"slo": {"name": st.obj["name"],
                               "kind": st.obj["kind"],
                               "target": st.target,
                               "value": st.value,
                               "fast_burn": round(st.fast_burn, 3),
                               "slow_burn": round(st.slow_burn, 3)},
                       "top_consumers": ledger.top_consumers()
                       if ledger is not None else {}})
        return True

    # -- reads -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The /api/accounting scorecard block."""
        with self._lock:
            return {
                "fast_s": self.fast_s,
                "slow_s": self.slow_s,
                "burn_threshold": self.burn_threshold,
                "interval_s": self.interval_s,
                "last_tick_ts": round(self._last_tick, 3),
                "objectives": [
                    {"name": st.obj["name"],
                     "kind": st.obj["kind"],
                     "target": st.target,
                     "value": round(st.value, 6)
                     if st.value is not None else None,
                     "fast_burn": round(st.fast_burn, 3),
                     "slow_burn": round(st.slow_burn, 3),
                     "burning": st.burning,
                     "samples": len(st.ring)}
                    for st in self._slos.values()],
            }

    # -- durability --------------------------------------------------------

    def export_state(self) -> dict:
        with self._lock:
            return {"slos": {name: {"burning": st.burning,
                                    "fired_ts": st.fired_ts,
                                    "ring": [[ts, b]
                                             for ts, b in st.ring]}
                             for name, st in self._slos.items()}}

    def restore_state(self, state: dict) -> None:
        """Warm restart: re-latch burning objectives and re-seed the
        sample rings SILENTLY — no `slo.clear` (or re-`slo.burn`)
        events fire from recovery itself; the next real tick
        re-evaluates against the restored history."""
        if not state:
            return
        with self._lock:
            for name, rec in (state.get("slos") or {}).items():
                st = self._slos.get(name)
                if st is None:
                    continue
                st.burning = bool(rec.get("burning"))
                st.fired_ts = float(rec.get("fired_ts") or 0.0)
                st.ring = [(float(ts), int(b))
                           for ts, b in rec.get("ring") or []]
                st.gauge.set(1 if st.burning else 0)
