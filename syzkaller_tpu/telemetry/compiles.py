"""Compile-cache observatory: every XLA build, counted and timed.

The tier-1 suite has twice brushed its 870 s ceiling on silent
re-compiles that only ad-hoc per-test jit-count guards caught after
the fact (PR 9/10/12/14 each grew its own).  This module (ISSUE 17)
makes the compile cache a first-class observable plane: the
process-wide compile points — `ops/pipeline._shared_step`'s first
dispatch, the mesh topology graphs (`parallel/fault_domain._build`),
and the triage/sim analytics builders — report every build here,
with the cache key that produced it.

Exports:
  - `tz_compile_builds_total{graph=}`  — builds per graph family
  - `tz_compile_seconds_total{graph=}` — wall seconds spent building
  - `tz_compile_cache_size{graph=}`    — live executables per family
  - `tz_compile_storms_total`          — storm incidents fired

Storm detection: TZ_COMPILE_STORM_N builds of the SAME graph family
at the SAME cache key inside TZ_COMPILE_STORM_WINDOW_S means the
executable cache is being lost and rebuilt — the exact failure mode
that ate the tier-1 budget.  The incident (`compile_storm` flight
dump + `compile.storm` event) is self-diagnosing: it carries the
storming key and its diff against the family's previous distinct key,
so "what shape keeps changing?" (or "nothing — the cache itself was
dropped") is in the payload, not in an afternoon of log archaeology.
One incident per storm episode, not one per build.

This observatory is also the single authority the warm-rig jit-count
guards assert against: `assert_no_new_compiles` replaces the
scattered `_cache_size()` tuple snapshots in tests/test_health_faults
— it watches both the caller's jit caches AND the process build
ledger, and a failure names the graphs that built instead of leaving
a bare tuple mismatch.

Host-side only; nothing here runs inside jitted code.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Optional

ENV_STORM_N = "TZ_COMPILE_STORM_N"
ENV_STORM_WINDOW = "TZ_COMPILE_STORM_WINDOW_S"

DEFAULT_STORM_N = 2
DEFAULT_STORM_WINDOW_S = 600.0

#: Bounded recent-build ring (diagnosis payloads; guards read deltas).
BUILD_RING = 128


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    try:
        return max(2, int(raw, 0)) if raw else default
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    try:
        return float(raw) if raw else default
    except (TypeError, ValueError):
        return default


def _canon_key(key) -> tuple:
    """Canonical hashable form of a cache key: dicts sort into
    (field, value) pairs so equal shapes compare equal regardless of
    construction order; everything else is wrapped as given."""
    if isinstance(key, dict):
        return tuple(sorted((str(k), str(v)) for k, v in key.items()))
    if isinstance(key, tuple):
        return tuple(str(v) for v in key)
    return (str(key),)


def key_diff(a: tuple, b: tuple) -> dict:
    """Field-wise diff of two canonical cache keys.  {} means the
    keys are identical — for a storm that reads "same shape rebuilt:
    the executable cache was dropped", the worst of the two causes."""
    da = dict(a) if a and all(isinstance(p, tuple) and len(p) == 2
                              for p in a) else {"key": a}
    db = dict(b) if b and all(isinstance(p, tuple) and len(p) == 2
                              for p in b) else {"key": b}
    out = {}
    for f in sorted(set(da) | set(db)):
        if da.get(f) != db.get(f):
            out[f] = [da.get(f), db.get(f)]
    return out


class CompileObservatory:
    """The process-wide build ledger + storm detector."""

    def __init__(self, registry=None, flight=None):
        self._lock = threading.Lock()
        self._registry = registry
        self._flight = flight
        self._total = 0
        self._storms = 0
        self._counts: dict[tuple, int] = {}  # (graph, key) -> builds
        self._recent: deque = deque(maxlen=BUILD_RING)
        self._stamps: dict[tuple, deque] = {}
        self._storm_mute: dict[tuple, float] = {}
        self._last_key: dict[str, tuple] = {}
        self._metrics: dict = {}

    def _reg(self):
        if self._registry is None:
            from syzkaller_tpu import telemetry

            self._registry = telemetry.REGISTRY
        return self._registry

    def _flt(self):
        if self._flight is None:
            from syzkaller_tpu import telemetry

            self._flight = telemetry.FLIGHT
        return self._flight

    def _counter(self, name: str, help: str, graph: str):
        key = (name, graph)
        m = self._metrics.get(key)
        if m is None:
            m = self._reg().counter(name, help,
                                    labels={"graph": graph})
            self._metrics[key] = m
        return m

    # -- recording ---------------------------------------------------------

    def note(self, graph: str, key=None, seconds: float = 0.0) -> None:
        """One build of `graph` at cache key `key` (a dict of the
        static shape fields), taking `seconds` of wall time.  Called
        from the compile points only — a warm dispatch that reuses an
        executable must NOT note."""
        ck = _canon_key(key)
        now = time.monotonic()
        storm = None
        with self._lock:
            self._total += 1
            self._counts[(graph, ck)] = \
                self._counts.get((graph, ck), 0) + 1
            self._recent.append((round(time.time(), 3), graph, ck,
                                 round(seconds, 4)))
            # The family's previous DISTINCT key: the storm payload
            # diffs the storming shape against it, so "what field
            # keeps churning?" is answerable from the incident alone.
            cur = self._last_key.get(graph)
            if cur is not None and cur[1] != ck:
                prev = cur[1]
                self._last_key[graph] = (prev, ck)
            elif cur is None:
                prev = None
                self._last_key[graph] = (None, ck)
            else:
                prev = cur[0]
            stamps = self._stamps.setdefault(
                (graph, ck), deque(maxlen=_env_int(
                    ENV_STORM_N, DEFAULT_STORM_N)))
            stamps.append(now)
            window = _env_float(ENV_STORM_WINDOW,
                                DEFAULT_STORM_WINDOW_S)
            n = _env_int(ENV_STORM_N, DEFAULT_STORM_N)
            if (len(stamps) >= n and now - stamps[0] <= window
                    and now >= self._storm_mute.get((graph, ck), 0.0)):
                # One incident per episode: mute this (graph, key)
                # until the window drains past the storming builds.
                self._storm_mute[(graph, ck)] = now + window
                self._storms += 1
                storm = (len(stamps), now - stamps[0], prev)
        self._counter("tz_compile_builds_total",
                      "executable builds per graph family", graph).inc()
        self._counter("tz_compile_seconds_total",
                      "wall seconds spent building executables",
                      graph).inc(seconds)
        if storm is not None:
            self._fire_storm(graph, ck, *storm)

    def _fire_storm(self, graph: str, ck: tuple, n: int,
                    span_s: float, prev: Optional[tuple]) -> None:
        from syzkaller_tpu import telemetry

        diff = key_diff(prev, ck) if prev is not None else {}
        cause = ("identical cache key — the executable cache was "
                 "dropped" if not diff else
                 f"key churn on {sorted(diff)}")
        detail = (f"{graph}: {n} builds of one shape in "
                  f"{span_s:.1f}s ({cause})")
        telemetry.counter("tz_compile_storms_total",
                          "compile-storm incidents").inc()
        telemetry.record_event("compile.storm", detail)
        self._flt().dump("compile_storm", detail, extra={
            "compile_storm": {
                "graph": graph,
                "key": list(ck),
                "builds": n,
                "span_s": round(span_s, 3),
                "key_diff": diff,
            },
            "compiles": self.snapshot(),
        })

    def set_cache_size(self, graph: str, size: int) -> None:
        """Live executable count for one family (the `_shared_step`
        lru and the mesh `_graphs` dict publish theirs here)."""
        key = ("tz_compile_cache_size", graph)
        g = self._metrics.get(key)
        if g is None:
            g = self._reg().gauge("tz_compile_cache_size",
                                  "live executables per graph family",
                                  labels={"graph": graph})
            self._metrics[key] = g
        g.set(size)

    @contextlib.contextmanager
    def observe(self, graph: str, key=None, sizer=None):
        """Time a potential compile point: notes a build only when
        `sizer()` (a jit `_cache_size` callable) grew across the body
        — a warm dispatch that reuses the executable records nothing,
        so warm rigs stay storm-silent.  With no sizer the body IS
        the build (a cache-miss branch)."""
        before = sizer() if sizer is not None else None
        t0 = time.perf_counter()
        yield
        dur = time.perf_counter() - t0
        if sizer is None or sizer() > before:
            self.note(graph, key, dur)

    # -- the guard authority -----------------------------------------------

    def total_builds(self) -> int:
        with self._lock:
            return self._total

    def builds(self, graph: Optional[str] = None) -> int:
        with self._lock:
            if graph is None:
                return self._total
            return sum(c for (g, _k), c in self._counts.items()
                       if g == graph)

    def shapes(self, graph: str) -> dict:
        """key -> build count for one family (the mesh drill pins its
        exactly-2-graphs invariant on len() of this)."""
        with self._lock:
            return {k: c for (g, k), c in self._counts.items()
                    if g == graph}

    def recent(self, n: int = 8) -> list:
        with self._lock:
            return list(self._recent)[-n:]

    def snapshot(self) -> dict:
        with self._lock:
            fams: dict[str, dict] = {}
            for (g, k), c in self._counts.items():
                f = fams.setdefault(g, {"builds": 0, "shapes": 0})
                f["builds"] += c
                f["shapes"] += 1
            return {
                "total_builds": self._total,
                "storms": self._storms,
                "graphs": dict(sorted(fams.items())),
                "recent": list(self._recent)[-8:],
            }


@contextlib.contextmanager
def assert_no_new_compiles(*sizers, observatory=None):
    """The shared warm-rig compile guard (replaces the per-test
    `_cache_size()` tuple snapshots of PR 9/10/12/14): no watched jit
    cache may grow and the process CompileObservatory must record
    zero new builds across the body.  A violation names the graphs
    that built — the observatory is the authority, so the assertion
    message is the diagnosis."""
    if observatory is None:
        from syzkaller_tpu import telemetry

        observatory = telemetry.COMPILES
    before = [s() for s in sizers]
    builds0 = observatory.total_builds()
    yield
    after = [s() for s in sizers]
    new_builds = observatory.total_builds() - builds0
    problems = []
    for i, (b, a) in enumerate(zip(before, after)):
        if a != b:
            problems.append(f"watched jit cache #{i} grew {b} -> {a}")
    if new_builds:
        problems.append(
            f"{new_builds} new build(s): "
            f"{observatory.recent(new_builds)}")
    if problems:
        raise AssertionError(
            "new jit compiles on a warm rig: " + "; ".join(problems))
