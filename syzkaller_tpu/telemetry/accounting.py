"""Device-time accounting ledger (ISSUE 14, tentpole part 1).

Answers the first question a fleet operator asks: *who is consuming
the device-seconds*.  The ledger consumes the instrumentation that
already exists — the KernelProfiler sync points (ops/pipeline.py),
the triage novel_any fetch (triage/engine.py), the mesh collective
elapsed (parallel/fault_domain.py), and the serving drain
(serve/composer.py) — and attributes each batch's device
milliseconds to three independent dimensions:

  * ``tenant`` — which serving-plane tenant the rows belonged to
    (row-weighted over the composer's allocation; the manager's own
    work books under "local"),
  * ``lane``   — which workqueue lane produced the work (the
    _LANE_BY_STAT tags from fuzzer/proc.py; default "exploration"),
  * ``shard``  — which mesh shard executed it (fault_domain indices;
    default "0" on single-chip).

Every dimension conserves: the per-key splits of one batch sum to
the batch's milliseconds EXACTLY (largest-share key absorbs the
float residual), so Σ tz_acct_device_ms_total{tenant=...} ==
Σ tz_acct_device_ms_total{lane=...} == total metered ms.  The
conservation error is exported for tests and the scorecard.

Novelty joins the ledger through `note_novel` (fed by
CoverageTracker per lane and the composer per tenant); each
attribution of device time folds the novelty accumulated since the
key's last attribution into a yield EWMA — novel edges per device
second — exported as `tz_acct_novel_edges_per_device_sec{tenant|lane}`
and consumed by `TZ_SERVE_PRICE=yield` credit pricing
(serve/composer.py) and the SLO top-consumers incident table
(telemetry/slo.py).

Label cardinality is bounded: at most MAX_KEYS live keys per
dimension; later keys fold into "overflow" (lanes are a fixed small
set — the workqueue bands plus distill and hints; tenants are capped
by TZ_SERVE_MAX_TENANTS; shards by the
mesh width — the cap is a leak backstop, not a working limit).

Import-cycle note: like coverage.py, this module is constructed at
telemetry import time, so all telemetry access is late
(`from syzkaller_tpu import telemetry` inside methods).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

#: Same smoothing as the KernelProfiler: ~5-batch memory.
EWMA_ALPHA = 0.2

DIMENSIONS = ("tenant", "lane", "shard")

#: Where a batch books when the caller has no attribution for a
#: dimension (single-tenant pipeline work, single-chip, no lane tag).
DEFAULT_KEY = {"tenant": "local", "lane": "exploration", "shard": "0"}

#: Per-dimension live-key cap; past it, new keys fold into
#: OVERFLOW_KEY so a label leak can't grow /metrics unboundedly.
MAX_KEYS = 64
OVERFLOW_KEY = "overflow"

#: Dimensions that carry a novelty join (shards discover nothing on
#: their own — novelty is a property of the work, not the chip).
YIELD_DIMS = ("tenant", "lane")


class _Slot:
    """One (dimension, key) accumulator.  Fixed slots, mutated in
    place — the hot path allocates nothing after first touch."""

    __slots__ = ("ms", "novel", "pending_novel", "ewma", "seen",
                 "counter", "gauge")

    def __init__(self, counter, gauge):
        self.ms = 0.0              # cumulative attributed device ms
        self.novel = 0             # cumulative novel edges joined
        self.pending_novel = 0     # novelty since the last attribution
        self.ewma = 0.0            # novel edges per device second
        self.seen = False          # first attribution sets the EWMA
        self.counter = counter     # tz_acct_device_ms_total{dim=key}
        self.gauge = gauge         # yield gauge, or None (shard)


class DeviceTimeLedger:
    """See module doc.  Singleton lives at `telemetry.ACCOUNTING`;
    tests construct private instances (the registry families are
    shared get-or-create, so a private ledger re-uses the same
    metric objects)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._dims: Dict[str, Dict[str, _Slot]] = \
            {d: {} for d in DIMENSIONS}
        self._dim_ms: Dict[str, float] = {d: 0.0 for d in DIMENSIONS}
        self.total_ms = 0.0
        self.batches = 0
        # Pre-create the default slots so the unattributed hot path
        # (pipeline _fetch on a single-tenant manager) never grows a
        # container after construction (test_health_faults guard).
        with self._lock:
            for d in DIMENSIONS:
                self._slot_locked(d, DEFAULT_KEY[d])

    # -- slots -------------------------------------------------------------

    def _slot_locked(self, dim: str, key: str) -> _Slot:
        slots = self._dims[dim]
        s = slots.get(key)
        if s is None:
            if len(slots) >= MAX_KEYS and key != OVERFLOW_KEY:
                return self._slot_locked(dim, OVERFLOW_KEY)
            from syzkaller_tpu import telemetry
            counter = telemetry.counter(
                "tz_acct_device_ms_total",
                "device milliseconds attributed by the accounting "
                "ledger (conserving row-weighted split per dimension)",
                labels={dim: key})
            gauge = None
            if dim in YIELD_DIMS:
                gauge = telemetry.gauge(
                    "tz_acct_novel_edges_per_device_sec",
                    "novelty yield: novel edges discovered per device "
                    "second, EWMA per ledger key",
                    labels={dim: key})
            s = slots[key] = _Slot(counter, gauge)
        return s

    # -- metering ----------------------------------------------------------

    def note_batch(self, seconds: float,
                   tenant_rows: Optional[dict] = None,
                   lane_rows: Optional[dict] = None,
                   shard_rows: Optional[dict] = None) -> None:
        """Attribute one batch's device time.  Each `*_rows` dict is
        an independent row-weighted split ({key: row_count}); a
        missing/empty dimension books the whole batch to its default
        key.  Never raises past bad input — metering must not break
        the drain it measures."""
        if seconds is None or seconds <= 0.0:
            return
        ms = seconds * 1e3
        with self._lock:
            self.total_ms += ms
            self.batches += 1
            self._accrue_locked("tenant", tenant_rows, ms)
            self._accrue_locked("lane", lane_rows, ms)
            self._accrue_locked("shard", shard_rows, ms)

    def _accrue_locked(self, dim: str, rows: Optional[dict],
                       ms: float) -> None:
        items = None
        if rows:
            items = [(str(k), r) for k, r in rows.items()
                     if r and r > 0]
        if not items:
            items = [(DEFAULT_KEY[dim], 1)]
        total = 0
        best_i, best_r = 0, -1
        for i, (_k, r) in enumerate(items):
            total += r
            if r > best_r:
                best_i, best_r = i, r
        # Largest-remainder conservation: every key but the biggest
        # takes its proportional share; the biggest takes the exact
        # remainder, so the splits sum to `ms` bit-for-bit.
        acc = 0.0
        for i, (key, r) in enumerate(items):
            if i == best_i:
                continue
            share = ms * (r / total)
            acc += share
            self._credit_locked(dim, key, share)
        self._credit_locked(dim, items[best_i][0], ms - acc)

    def _credit_locked(self, dim: str, key: str, share: float) -> None:
        if share <= 0.0:
            return
        s = self._slot_locked(dim, key)
        s.ms += share
        self._dim_ms[dim] += share
        s.counter.inc(share)
        if s.gauge is not None:
            # Fold the novelty accumulated since this key last held
            # the device into an instantaneous yield, then EWMA it
            # (profiler idiom: the first observation sets the value).
            inst = s.pending_novel / (share / 1e3)
            s.pending_novel = 0
            s.ewma = inst if not s.seen \
                else s.ewma + EWMA_ALPHA * (inst - s.ewma)
            s.seen = True
            s.gauge.set(round(s.ewma, 6))

    def note_novel(self, dim: str, key: str, nedges: int) -> None:
        """Join `nedges` novel edges to a ledger key; they price into
        the yield EWMA when the key next accrues device time."""
        if nedges is None or nedges <= 0 or dim not in YIELD_DIMS:
            return
        with self._lock:
            s = self._slot_locked(dim, str(key))
            s.pending_novel += int(nedges)
            s.novel += int(nedges)

    # -- reads -------------------------------------------------------------

    def yield_ewmas(self, dim: str) -> Dict[str, float]:
        """{key: novel-edges-per-device-sec EWMA} for one dimension —
        the TZ_SERVE_PRICE=yield weight source."""
        with self._lock:
            return {k: s.ewma for k, s in self._dims[dim].items()}

    def dimension_snapshot(self, dim: str) -> dict:
        with self._lock:
            return {k: {"device_ms": round(s.ms, 3),
                        "novel": s.novel,
                        "yield_ewma": round(s.ewma, 4)}
                    for k, s in self._dims[dim].items()}

    def conservation_error(self) -> float:
        """Max relative |Σ per-key ms − metered ms| across dimensions
        (the acceptance invariant: ≤ 1e-6)."""
        with self._lock:
            if self.total_ms <= 0.0:
                return 0.0
            return max(abs(self._dim_ms[d] - self.total_ms)
                       for d in DIMENSIONS) / self.total_ms

    def top_consumers(self, n: int = 8) -> dict:
        """The self-diagnosing incident table: per-dimension top keys
        by cumulative device ms, with share and yield.  Attached to
        every `slo_burn` flight dump and the /api scorecard."""
        with self._lock:
            total = self.total_ms or 1.0
            out: dict = {"total_device_ms": round(self.total_ms, 3)}
            for d in DIMENSIONS:
                ranked = sorted(self._dims[d].items(),
                                key=lambda kv: kv[1].ms, reverse=True)
                out[d] = [{"key": k,
                           "device_ms": round(s.ms, 3),
                           "share": round(s.ms / total, 4),
                           "yield": round(s.ewma, 4)}
                          for k, s in ranked[:n] if s.ms > 0.0]
            return out

    def snapshot(self) -> dict:
        """The /api/accounting ledger block."""
        out = {"device_ms_total": round(self.total_ms, 3),
               "batches": self.batches,
               "conservation_error": self.conservation_error()}
        for d in DIMENSIONS:
            out[d] = self.dimension_snapshot(d)
        return out

    # -- durability (ISSUE 14 satellite; manager/manager.py wires it) ------

    def export_state(self) -> dict:
        """Checkpoint section meta: the cumulative ledger (per-key
        ms/novel/EWMA) a warm restart restores from."""
        with self._lock:
            return {
                "total_ms": self.total_ms,
                "batches": self.batches,
                "dims": {d: {k: [s.ms, s.novel, s.ewma]
                             for k, s in self._dims[d].items()}
                         for d in DIMENSIONS},
            }

    def restore_state(self, state: dict) -> None:
        """Warm restart: re-seed cumulative per-key device-ms (the
        counters re-climb to their pre-crash values, preserving
        chargeback continuity) and the yield EWMAs."""
        if not state:
            return
        with self._lock:
            self.total_ms = float(state.get("total_ms") or 0.0)
            self.batches = int(state.get("batches") or 0)
            for d in DIMENSIONS:
                self._dim_ms[d] = 0.0
                for k, rec in (state.get("dims") or {}).get(
                        d, {}).items():
                    s = self._slot_locked(d, str(k))
                    ms, novel, ewma = (float(rec[0]), int(rec[1]),
                                       float(rec[2]))
                    delta = ms - s.ms
                    if delta > 0:
                        s.counter.inc(delta)
                    s.ms = ms
                    s.novel = novel
                    s.ewma = ewma
                    s.seen = s.seen or ms > 0.0
                    self._dim_ms[d] += ms
                    if s.gauge is not None:
                        s.gauge.set(round(s.ewma, 6))

    def reset(self) -> None:
        """Zero the ledger state (tests).  The registry counter
        families stay monotonic — only the ledger's own accumulators
        reset."""
        with self._lock:
            for d in DIMENSIONS:
                for s in self._dims[d].values():
                    s.ms = 0.0
                    s.novel = 0
                    s.pending_novel = 0
                    s.ewma = 0.0
                    s.seen = False
                    if s.gauge is not None:
                        s.gauge.set(0.0)
                self._dim_ms[d] = 0.0
            self.total_ms = 0.0
            self.batches = 0
