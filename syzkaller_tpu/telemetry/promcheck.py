"""Prometheus text-exposition validator.

The manager's `/metrics` body is assembled from three generators (the
process registry, the fleet merge, labeled gauge families), and a
malformed line fails silently at scrape time — the scraper drops the
whole body and the operator loses every series at once.  This
validator is the tier-1 guard: it parses the exposition the way a
scraper would and returns every violation it finds, so a fleet-merge
or new-gauge regression fails a fast host-only test instead of a
production scrape.

Checks:
  - comment lines are well-formed `# HELP name text` / `# TYPE name
    kind` with a known kind, at most one TYPE per family,
  - sample lines parse as `name[{label="value",...}] value`, names
    and label names legal, label values quote-escaped,
  - every sample's family agrees with its TYPE declaration
    (histogram samples use the `_bucket`/`_sum`/`_count` suffixes),
  - histogram families carry a `+Inf` bucket and cumulative,
    monotonically non-decreasing bucket counts.

Pure stdlib, no imports from the registry — it must be able to
condemn output the registry believes is fine.
"""

from __future__ import annotations

import re

NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"      # metric name
    r"(?:\{(.*)\})?"                     # optional label set
    r" "                                 # exactly one space
    r"(-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf)|NaN|[+-]Inf)"
    r"(?: -?[0-9]+)?$")                  # optional timestamp
LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
KINDS = {"counter", "gauge", "histogram", "summary", "untyped"}
HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _family(name: str, types: dict) -> str:
    """The TYPE family a sample belongs to: histogram samples carry
    the _bucket/_sum/_count suffixes of their declared family."""
    for suf in HIST_SUFFIXES:
        if name.endswith(suf):
            base = name[: -len(suf)]
            if types.get(base) == "histogram":
                return base
    return name


def _parse_labels(raw: str, lineno: int, problems: list) -> dict:
    out = {}
    rest = raw.strip()
    while rest:
        m = LABEL_RE.match(rest)
        if not m:
            problems.append(
                f"line {lineno}: malformed label set at {rest[:40]!r}")
            return out
        out[m.group(1)] = m.group(2)
        rest = rest[m.end():]
        if rest.startswith(","):
            rest = rest[1:]
        elif rest:
            problems.append(
                f"line {lineno}: expected ',' between labels, got "
                f"{rest[:20]!r}")
            return out
    return out


def validate_exposition(text: str) -> list[str]:
    """Every violation found, as printable strings (empty = valid)."""
    problems: list[str] = []
    types: dict[str, str] = {}
    # family -> list of (labels-without-le, le, cum) for bucket checks
    buckets: dict[str, list] = {}
    seen_inf: set[tuple] = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                problems.append(
                    f"line {lineno}: malformed comment {line[:60]!r}")
                continue
            name = parts[2]
            if not NAME_RE.match(name):
                problems.append(
                    f"line {lineno}: illegal metric name {name!r}")
            if parts[1] == "TYPE":
                kind = parts[3].strip() if len(parts) > 3 else ""
                if kind not in KINDS:
                    problems.append(
                        f"line {lineno}: unknown TYPE kind {kind!r}")
                if name in types:
                    problems.append(
                        f"line {lineno}: duplicate TYPE for {name}")
                types[name] = kind
            continue
        m = SAMPLE_RE.match(line)
        if not m:
            problems.append(
                f"line {lineno}: malformed sample {line[:60]!r}")
            continue
        name, raw_labels, _value = m.group(1), m.group(2), m.group(3)
        labels = _parse_labels(raw_labels, lineno, problems) \
            if raw_labels else {}
        fam = _family(name, types)
        kind = types.get(fam)
        if kind == "histogram":
            if not any(name.endswith(s) for s in HIST_SUFFIXES):
                problems.append(
                    f"line {lineno}: histogram family {fam} sample "
                    f"{name} lacks _bucket/_sum/_count suffix")
            if name.endswith("_bucket"):
                if "le" not in labels:
                    problems.append(
                        f"line {lineno}: {name} without an le label")
                else:
                    key = tuple(sorted((k, v) for k, v in labels.items()
                                       if k != "le"))
                    buckets.setdefault(fam, []).append(
                        (key, labels["le"], float(m.group(3))))
                    if labels["le"] == "+Inf":
                        seen_inf.add((fam, key))
    for fam, rows in buckets.items():
        series: dict[tuple, list] = {}
        for key, _le, cum in rows:
            series.setdefault(key, []).append(cum)
        for key, cums in series.items():
            if (fam, key) not in seen_inf:
                problems.append(
                    f"{fam}{dict(key)}: histogram without a +Inf "
                    "bucket")
            if any(a > b for a, b in zip(cums, cums[1:])):
                problems.append(
                    f"{fam}{dict(key)}: bucket counts are not "
                    "cumulative/monotone")
    return problems
