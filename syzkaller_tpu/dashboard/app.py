"""Dashboard service: fleet-wide bug tracking ("syzbot").

Aggregates crashes from many managers into deduplicated bugs, tracks
their reporting lifecycle, accepts build info, and hands out patch-test
jobs to CI — a filesystem-backed reimplementation of the reference's
App Engine service (reference: dashboard/app/main.go handlers,
api.go API entry points, reporting.go state machine; entities
dashboard/app/entities.go: Build/Bug/Crash/Job).

Bug lifecycle: new → (reporting due) reported → open until a fix
commit is attached or it is invalidated; dup-marking folds a bug into
another.  Crash dedup is by (normalized title); per-bug crash logs are
capped like the manager's (max_crashes).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Optional

from syzkaller_tpu.utils.hashsig import hash_string

MAX_CRASHES_PER_BUG = 20

STATUS_NEW = "new"
STATUS_REPORTED = "reported"
STATUS_FIXED = "fixed"      # fix commit attached, not yet in a build
STATUS_CLOSED = "closed"    # fix commit observed in an uploaded build
STATUS_INVALID = "invalid"
STATUS_DUP = "dup"

# Access levels gate which bugs a viewer sees: a bug is visible at a
# level iff its CURRENT reporting stage's access <= the viewer's
# (reference: dashboard/app/access.go AccessPublic/User/Admin).
ACCESS_PUBLIC = "public"
ACCESS_USER = "user"
ACCESS_ADMIN = "admin"
_ACCESS_RANK = {ACCESS_PUBLIC: 0, ACCESS_USER: 1, ACCESS_ADMIN: 2}


@dataclass
class ReportingStage:
    """One stage of a namespace's reporting pipeline (reference:
    dashboard/app/reporting.go Reporting + config.go namespace
    Reporting lists).  Typical two-stage setup: a moderation stage
    (access admin, short delay) that a human upstreams, then the
    public stage.

    email_to: per-stage destination list — together with the
    per-namespace stage lists this forms the reporting-config matrix
    (namespace x stage -> access/delay/destination; reference:
    config.go Reporting{Name, AccessLevel, Embargo, Config{Email}})."""
    name: str = "public"
    access: str = ACCESS_PUBLIC
    delay_s: float = 0.0
    email_to: str = ""

    def __post_init__(self):
        if self.access not in _ACCESS_RANK:
            raise ValueError(f"unknown access level {self.access!r} "
                             f"(one of {sorted(_ACCESS_RANK)})")


@dataclass
class Build:
    """(reference: dashboard/app entities Build)"""
    id: str = ""
    manager: str = ""
    os: str = ""
    arch: str = ""
    kernel_repo: str = ""
    kernel_branch: str = ""
    kernel_commit: str = ""
    compiler: str = ""
    time: float = 0.0


@dataclass
class Crash:
    manager: str = ""
    build_id: str = ""
    log: str = ""  # stored file name
    report: str = ""
    repro_prog: str = ""
    repro_c: str = ""
    time: float = 0.0


@dataclass
class Bug:
    id: str = ""
    title: str = ""
    namespace: str = "default"
    status: str = STATUS_NEW
    first_time: float = 0.0
    last_time: float = 0.0
    num_crashes: int = 0
    reporting_due: float = 0.0
    reported_time: float = 0.0
    # index into the namespace's reporting-stage list; the bug's
    # moderation->public progress (reference: reporting.go bugReporting)
    reporting_idx: int = 0
    reporting_stage: str = ""  # stage name at which last reported
    fix_commit: str = ""
    dup_of: str = ""
    # crashes folded into dup_of at dup time — undup subtracts exactly
    # this, not the current count (crashes keep deduping into THIS bug
    # after the dup, never forwarded)
    dup_folded: int = 0
    # Message-IDs of the report mails (one per reporting stage);
    # threads replies back to the bug across restarts — a reply to an
    # older stage's thread must still resolve (reference:
    # reporting.go Reporting.ID).
    report_msg_id: str = ""
    report_msg_ids: list[str] = field(default_factory=list)
    crashes: list[Crash] = field(default_factory=list)


@dataclass
class Job:
    """Patch-test job (reference: dashboard/app/jobs.go)."""
    id: str = ""
    bug_id: str = ""
    namespace: str = "default"
    manager: str = ""
    patch: str = ""
    kernel_repo: str = ""
    kernel_branch: str = ""
    status: str = "pending"  # pending → claimed → done
    claimed_by: str = ""
    result_ok: bool = False
    result_error: str = ""


class Dashboard:
    """Multi-namespace bug tracker: each client is bound to a
    namespace (kernel flavor: upstream, stable, android, ...); bugs
    dedup and report within their namespace only, the same partition
    the reference's syzbot runs (reference: dashboard/app config
    namespaces + access levels).

    `clients` maps client -> key (single-namespace legacy form) or
    client -> {"key": ..., "namespace": ...}."""

    def __init__(self, workdir: str, clients: Optional[dict] = None,
                 reporting_delay_s: float = 0.0,
                 reporting: Optional[dict] = None,
                 upstream_ns: Optional[str] = None):
        os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.clients = clients or {}
        self.reporting_delay_s = reporting_delay_s
        # Cross-namespace dedup target: bugs that exhaust their own
        # namespace's stage ladder upstream into this namespace, so
        # the same crash title seen by several downstream namespaces
        # converges to ONE upstream bug (reference: reporting.go
        # originalNS -> upstream reporting chains).
        self.upstream_ns = upstream_ns
        # Per-namespace reporting pipelines; "*" is the fallback.  The
        # default is the single public stage (legacy single-reporting
        # behavior); pass e.g. {"ns": [ReportingStage("moderation",
        # ACCESS_ADMIN, 0), ReportingStage("public", ACCESS_PUBLIC,
        # 3600)]} for the two-stage syzbot flow.
        self.reporting: dict[str, list[ReportingStage]] = {}
        for ns, stages in (reporting or {}).items():
            self.reporting[ns] = [
                st if isinstance(st, ReportingStage)
                else ReportingStage(**st) for st in stages]
        self._lock = threading.Lock()
        self.bugs: dict[str, Bug] = {}
        self.builds: dict[str, Build] = {}
        self.jobs: dict[str, Job] = {}
        self._load()

    def stages_for(self, namespace: str) -> list[ReportingStage]:
        return self.reporting.get(namespace) or self.reporting.get("*")             or [ReportingStage(delay_s=self.reporting_delay_s)]

    def bug_stage(self, bug: Bug) -> ReportingStage:
        stages = self.stages_for(bug.namespace)
        return stages[min(bug.reporting_idx, len(stages) - 1)]

    def bug_access(self, bug: Bug) -> str:
        return self.bug_stage(bug).access

    def visible_bugs(self, access: str = ACCESS_ADMIN) -> list[Bug]:
        """Bugs visible at the given access level (reference:
        access.go checkAccessLevel applied to bug listings)."""
        rank = _ACCESS_RANK.get(access, 0)
        with self._lock:
            return [b for b in self.bugs.values()
                    if _ACCESS_RANK[self.bug_access(b)] <= rank]

    # -- persistence ------------------------------------------------------

    def _state_path(self) -> str:
        return os.path.join(self.workdir, "state.json")

    def _load(self) -> None:
        try:
            raw = json.load(open(self._state_path()))
        except (OSError, json.JSONDecodeError):
            return
        remap = {}
        for b in raw.get("bugs", []):
            crashes = [Crash(**c) for c in b.pop("crashes", [])]
            bug = Bug(**b)
            bug.crashes = crashes
            # state written before dup_folded existed: approximate the
            # folded count with the dup's current crash count (crashes
            # that landed on the dup after folding inflate this, but
            # undup clamps at zero — better than subtracting nothing
            # and leaving the canonical bug inflated forever).
            if bug.status == "dup" and not bug.dup_folded:
                bug.dup_folded = bug.num_crashes
            # migrate pre-namespace ids (hash(title)) to the
            # namespaced scheme so dedup/reporting state survives the
            # upgrade instead of orphaning every existing bug
            legacy = hash_string(bug.title.encode())[:16]
            if bug.id == legacy:
                new_id = hash_string(
                    f"{bug.namespace}\x00{bug.title}".encode())[:16]
                remap[legacy] = new_id
                bug.id = new_id
            self.bugs[bug.id] = bug
        for b in raw.get("builds", []):
            build = Build(**b)
            self.builds[build.id] = build
        for j in raw.get("jobs", []):
            job = Job(**j)
            job.bug_id = remap.get(job.bug_id, job.bug_id)
            self.jobs[job.id] = job

    def _save(self) -> None:
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "bugs": [asdict(b) for b in self.bugs.values()],
                "builds": [asdict(b) for b in self.builds.values()],
                "jobs": [asdict(j) for j in self.jobs.values()],
            }, f)
        os.replace(tmp, self._state_path())

    # -- API (reference: dashboard/app/api.go) ---------------------------

    def _auth(self, params: dict) -> str:
        """Authenticate and return the client's NAMESPACE."""
        client = params.get("client", "")
        if not self.clients:
            return "default"
        ent = self.clients.get(client)
        if ent is None:
            raise PermissionError(f"unauthorized client {client!r}")
        if isinstance(ent, dict):
            # fail CLOSED on a missing/empty configured key: None ==
            # None must never authenticate
            key = ent.get("key")
            if not key or key != params.get("key"):
                raise PermissionError(f"unauthorized client {client!r}")
            return ent.get("namespace", "default")
        if ent != params.get("key"):
            raise PermissionError(f"unauthorized client {client!r}")
        return "default"

    def upload_build(self, params: dict) -> dict:
        ns = self._auth(params)
        b = Build(id=params.get("id") or hash_string(
            json.dumps(params, sort_keys=True).encode())[:16],
            manager=params.get("manager", ""),
            os=params.get("os", ""), arch=params.get("arch", ""),
            kernel_repo=params.get("kernel_repo", ""),
            kernel_branch=params.get("kernel_branch", ""),
            kernel_commit=params.get("kernel_commit", ""),
            compiler=params.get("compiler", ""), time=time.time())
        closed = []
        with self._lock:
            self.builds[b.id] = b
            # Fix detection (reference: dashboard/app fix flow): a bug
            # whose attached fix commit appears in this build's commit
            # list (or head commit) is now verified fixed -> closed.
            commits = set(params.get("commits") or [])
            if b.kernel_commit:
                commits.add(b.kernel_commit)
            for bug in self.bugs.values():
                if bug.namespace == ns and bug.status == STATUS_FIXED \
                        and bug.fix_commit and bug.fix_commit in commits:
                    bug.status = STATUS_CLOSED
                    closed.append(bug.id)
            self._save()
        return {"id": b.id, "closed_bugs": closed}

    def report_crash(self, params: dict) -> dict:
        """Dedup by (namespace, title) into a Bug; returns whether a
        repro is wanted (reference: api.go apiReportCrash +
        needRepro logic)."""
        ns = self._auth(params)
        title = params.get("title", "unknown")
        bug_id = hash_string(f"{ns}\x00{title}".encode())[:16]
        now = time.time()
        crash = Crash(manager=params.get("manager", ""),
                      build_id=params.get("build_id", ""),
                      repro_prog=params.get("repro_prog", ""),
                      repro_c=params.get("repro_c", ""), time=now)
        with self._lock:
            bug = self.bugs.get(bug_id)
            if bug is None:
                # configured pipelines use their stage-0 delay verbatim
                # (0.0 means report immediately); only the legacy
                # single-stage default inherits reporting_delay_s
                configured = ns in self.reporting or "*" in self.reporting
                stage0 = self.stages_for(ns)[0]
                delay = stage0.delay_s if configured \
                    else self.reporting_delay_s
                bug = Bug(id=bug_id, title=title, namespace=ns,
                          first_time=now,
                          reporting_due=now + delay)
                self.bugs[bug_id] = bug
            bug.last_time = now
            bug.num_crashes += 1
            # Store under the cap; a crash carrying a repro always
            # lands, evicting a repro-less one if the bug is full —
            # otherwise need_repro would stay true forever.
            stored = False
            if len(bug.crashes) < MAX_CRASHES_PER_BUG:
                bug.crashes.append(crash)
                stored = True
            elif crash.repro_prog:
                for i, old in enumerate(bug.crashes):
                    if not old.repro_prog:
                        bug.crashes[i] = crash
                        stored = True
                        break
            has_repro = any(c.repro_prog for c in bug.crashes)
        # blob files only for crashes actually kept, outside the lock
        if stored:
            for attr, key in (("log", "log"), ("report", "report")):
                data = params.get(key) or ""
                if data:
                    d = os.path.join(self.workdir, "bug-" + bug_id)
                    os.makedirs(d, exist_ok=True)
                    fname = os.path.join(d, f"{key}-{int(now)}")
                    with open(fname, "w") as f:
                        f.write(data)
                    setattr(crash, attr, fname)
        with self._lock:
            self._save()
        return {"bug_id": bug_id, "need_repro": not has_repro
                and bug.status not in (STATUS_INVALID, STATUS_DUP)}

    def need_repro(self, params: dict) -> dict:
        ns = self._auth(params)
        title = params.get("title", "")
        bug_id = hash_string(f"{ns}\x00{title}".encode())[:16]
        with self._lock:
            bug = self.bugs.get(bug_id)
            if bug is None:
                return {"need_repro": False}
            return {"need_repro": not any(c.repro_prog
                                          for c in bug.crashes)}

    def manager_stats(self, params: dict) -> dict:
        self._auth(params)
        name = params.get("manager", "")
        path = os.path.join(self.workdir, f"stats-{name}.jsonl")
        rec = {k: v for k, v in params.items()
               if k not in ("client", "key")}
        rec["ts"] = time.time()
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return {}

    # -- reporting state machine (reference: reporting.go) ---------------

    def poll_reports(self, namespace: Optional[str] = None) -> list[dict]:
        """Bugs due for (email-style) reporting; transitions them to
        reported.  Optionally restricted to one namespace (each
        reporting loop serves its own)."""
        now = time.time()
        out = []
        with self._lock:
            for bug in self.bugs.values():
                if namespace is not None and bug.namespace != namespace:
                    continue
                if bug.status == STATUS_NEW and bug.reporting_due <= now:
                    stage = self.bug_stage(bug)
                    bug.status = STATUS_REPORTED
                    bug.reported_time = now
                    bug.reporting_stage = stage.name
                    stages = self.stages_for(bug.namespace)
                    out.append({"id": bug.id, "title": bug.title,
                                "namespace": bug.namespace,
                                "num_crashes": bug.num_crashes,
                                "stage": stage.name,
                                "access": stage.access,
                                "email_to": stage.email_to,
                                "moderation": bug.reporting_idx
                                < len(stages) - 1})
            if out:
                self._save()
        return out

    def set_report_msg_id(self, bug_id: str, msg_id: str) -> None:
        """Persist the report-mail threading id on the bug (appended:
        earlier stages' threads stay resolvable)."""
        with self._lock:
            bug = self.bugs[bug_id]
            # backfill a legacy single id (pre-list state files) so
            # older threads keep resolving after this stage reports
            if bug.report_msg_id and \
                    bug.report_msg_id not in bug.report_msg_ids:
                bug.report_msg_ids.append(bug.report_msg_id)
            bug.report_msg_id = msg_id
            if msg_id not in bug.report_msg_ids:
                bug.report_msg_ids.append(msg_id)
            self._save()

    def report_threads(self) -> dict[str, str]:
        """msg_id -> bug_id map rebuilt from persisted bugs (restart
        recovery for the email reporting loop)."""
        with self._lock:
            out = {}
            for b in self.bugs.values():
                for mid in b.report_msg_ids or \
                        ([b.report_msg_id] if b.report_msg_id else []):
                    out[mid] = b.id
            return out

    def bug_report_payload(self, bug_id: str) -> dict:
        """Report-mail payload for a bug: title, counts, best repro
        (used by email.reporting; reference: reporting.go
        createBugReport)."""
        with self._lock:
            bug = self.bugs[bug_id]
            best = None
            for c in bug.crashes:
                if c.repro_prog:
                    best = c
                    break
            if best is None and bug.crashes:
                best = bug.crashes[0]
            out = {"id": bug.id, "title": bug.title,
                   "num_crashes": bug.num_crashes}
            if best is not None and best.repro_prog:
                out["repro_prog"] = best.repro_prog
            return out

    def _resolve_bug(self, ident: str, prefer_ns: str) -> Optional[Bug]:
        """Resolve a bug by id or by exact title — the '#syz dup:'
        command carries a TITLE, and the duplicate may live in another
        namespace (same-namespace match preferred, then the upstream
        namespace, then any).  Caller holds the lock."""
        b = self.bugs.get(ident)
        if b is not None:
            return b
        candidates = [x for x in self.bugs.values() if x.title == ident]
        for ns in (prefer_ns, self.upstream_ns):
            for x in candidates:
                if ns and x.namespace == ns:
                    return x
        return candidates[0] if candidates else None

    def update_bug(self, bug_id: str, status: Optional[str] = None,
                   fix_commit: str = "", dup_of: str = "",
                   undup: bool = False) -> None:
        """Operator/email commands: fix/invalid/dup/undup
        (reference: reporting.go incomingCommand).  dup_of accepts a
        bug id or an exact title, cross-namespace; the duplicate's
        crash count folds into the canonical bug."""
        with self._lock:
            bug = self.bugs[bug_id]
            if fix_commit:
                bug.fix_commit = fix_commit
                bug.status = STATUS_FIXED
            elif dup_of:
                if bug.status == STATUS_DUP:
                    # correcting a dup requires an undup first —
                    # silently re-folding would double-count into the
                    # new target while the old stays inflated
                    raise KeyError(
                        f"bug {bug_id} is already a dup; undup first")
                target = self._resolve_bug(dup_of, bug.namespace)
                if target is None or target.id == bug.id:
                    raise KeyError(f"dup target {dup_of!r} not found")
                # folding into a dup would hide the chain's tail;
                # point at the canonical end instead.  A walk that
                # reaches the bug being duped (or revisits a node)
                # would create a dup CYCLE — reject the command, the
                # same way a self-dup is rejected.
                seen = {bug.id}
                while target.status == STATUS_DUP and target.dup_of:
                    if target.id in seen:
                        raise KeyError(
                            f"dup of {dup_of!r} would create a cycle")
                    seen.add(target.id)
                    nxt = self.bugs.get(target.dup_of)
                    if nxt is None:
                        break
                    target = nxt
                if target.id in seen:
                    raise KeyError(
                        f"dup of {dup_of!r} would create a cycle")
                bug.dup_of = target.id
                bug.status = STATUS_DUP
                bug.dup_folded = bug.num_crashes
                target.num_crashes += bug.num_crashes
            elif undup:
                # un-fold exactly what dup folded, so round-trips do
                # not drift the canonical bug's count either way
                target = self.bugs.get(bug.dup_of)
                if target is not None:
                    target.num_crashes = max(
                        0, target.num_crashes - bug.dup_folded)
                bug.dup_of = ""
                bug.dup_folded = 0
                bug.status = status or STATUS_REPORTED
            elif status:
                bug.status = status
            self._save()

    def upstream_bug(self, bug_id: str) -> bool:
        """Advance a moderation-stage bug to the next reporting stage:
        it goes back to NEW with the next stage's delay and will be
        re-reported (and re-emailed, with a fresh thread) at that
        stage's access level (reference: reporting.go
        incomingCommandCmd upstream -> bugReporting advance).
        Returns False if the bug is already at the last stage."""
        now = time.time()
        with self._lock:
            bug = self.bugs.get(bug_id)
            if bug is None:
                return False
            # only live bugs advance: a fixed/invalid/dup bug must not
            # be reopened by a stray '#syz upstream' reply
            if bug.status not in (STATUS_NEW, STATUS_REPORTED):
                return False
            stages = self.stages_for(bug.namespace)
            if bug.reporting_idx >= len(stages) - 1:
                # Past the namespace's own ladder: cross-namespace
                # upstreaming.  The bug merges into (or creates) the
                # upstream namespace's bug for the same title and
                # becomes its dup — so every downstream namespace
                # seeing this title converges on ONE upstream bug.
                if not self.upstream_ns \
                        or bug.namespace == self.upstream_ns:
                    return False
                up_id = hash_string(
                    f"{self.upstream_ns}\x00{bug.title}".encode())[:16]
                up = self.bugs.get(up_id)
                if up is None:
                    up_stage0 = self.stages_for(self.upstream_ns)[0]
                    up = Bug(id=up_id, title=bug.title,
                             namespace=self.upstream_ns,
                             first_time=bug.first_time, last_time=now,
                             reporting_due=now + up_stage0.delay_s)
                    self.bugs[up_id] = up
                    # (crash evidence lands via the merge loop below)
                up.num_crashes += bug.num_crashes
                up.last_time = max(up.last_time, bug.last_time)
                # merge crash evidence: a later namespace may carry
                # the only reproducer — a repro crash always lands,
                # evicting a repro-less one when the bug is full
                for c in bug.crashes:
                    if len(up.crashes) < MAX_CRASHES_PER_BUG:
                        up.crashes.append(c)
                    elif c.repro_prog:
                        for i, old in enumerate(up.crashes):
                            if not old.repro_prog:
                                up.crashes[i] = c
                                break
                bug.status = STATUS_DUP
                bug.dup_of = up_id
                bug.dup_folded = bug.num_crashes
                self._save()
                return True
            bug.reporting_idx += 1
            nxt = stages[bug.reporting_idx]
            bug.status = STATUS_NEW
            bug.reporting_due = now + nxt.delay_s
            # next stage threads a fresh mail; the moderation thread's
            # id stays in report_msg_ids so late replies still resolve
            bug.report_msg_id = ""
            self._save()
        return True

    # -- jobs (reference: dashboard/app/jobs.go:105) ---------------------

    def add_job(self, bug_id: str, patch: str, kernel_repo: str = "",
                kernel_branch: str = "", manager: str = "") -> str:
        jid = hash_string(f"{bug_id}{patch}{time.time()}".encode())[:16]
        with self._lock:
            ns = self.bugs[bug_id].namespace \
                if bug_id in self.bugs else "default"
            self.jobs[jid] = Job(id=jid, bug_id=bug_id, namespace=ns,
                                 patch=patch,
                                 kernel_repo=kernel_repo,
                                 kernel_branch=kernel_branch,
                                 manager=manager)
            self._save()
        return jid

    def job_poll(self, params: dict) -> dict:
        # a client only receives jobs from its own namespace (the
        # partition covers the whole lifecycle, not just bugs)
        ns = self._auth(params)
        managers = params.get("managers") or []
        with self._lock:
            for job in self.jobs.values():
                if job.status == "pending" and job.namespace == ns and \
                        (not job.manager or job.manager in managers):
                    job.status = "claimed"
                    job.claimed_by = params.get("client", "")
                    self._save()
                    return {"id": job.id, "bug_id": job.bug_id,
                            "patch": job.patch,
                            "kernel_repo": job.kernel_repo,
                            "kernel_branch": job.kernel_branch}
        return {}

    def job_done(self, params: dict) -> dict:
        self._auth(params)
        with self._lock:
            job = self.jobs.get(params.get("id", ""))
            if job is None:
                return {}
            job.status = "done"
            job.result_ok = bool(params.get("ok"))
            # a JSON null must not poison the persisted state (the UI
            # escapes this field)
            job.result_error = params.get("error") or ""
            self._save()
        return {}


def serve_dashboard(workdir: str, addr: tuple[str, int] = ("127.0.0.1", 0),
                    clients: Optional[dict] = None):
    """HTTP JSON API + minimal HTML UI for a Dashboard."""
    import html as html_mod
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    dash = Dashboard(workdir, clients)
    api = {
        "upload_build": dash.upload_build,
        "report_crash": dash.report_crash,
        "need_repro": dash.need_repro,
        "manager_stats": dash.manager_stats,
        "job_poll": dash.job_poll,
        "job_done": dash.job_done,
    }

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _reply(self, code: int, body: bytes,
                   ctype: str = "application/json") -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802
            method = self.path.strip("/").removeprefix("api/")
            fn = api.get(method)
            if fn is None:
                return self._reply(404, b'{"error": "no such method"}')
            try:
                length = int(self.headers.get("Content-Length") or 0)
                params = json.loads(self.rfile.read(length) or b"{}")
                res = fn(params)
                self._reply(200, json.dumps(res).encode())
            except PermissionError as e:
                self._reply(403, json.dumps({"error": str(e)}).encode())
            except Exception as e:
                self._reply(500, json.dumps({"error": str(e)}).encode())

        def _html(self, title: str, body: str) -> None:
            nav = ("<p><a href='/'>bugs</a> | <a href='/builds'>builds"
                   "</a> | <a href='/jobs'>jobs</a></p>")
            page = (f"<html><head><title>{html_mod.escape(title)}"
                    f"</title></head><body><h2>"
                    f"{html_mod.escape(title)}</h2>{nav}{body}"
                    f"</body></html>")
            self._reply(200, page.encode(), "text/html")

        def do_GET(self):  # noqa: N802
            from urllib.parse import parse_qs, urlparse

            url = urlparse(self.path)
            q = parse_qs(url.query)
            # snapshot under the lock, render outside it so API POSTs
            # from the fleet aren't blocked by UI traffic
            if url.path == "/":
                status_filter = q.get("status", [""])[0]
                ns_filter = q.get("ns", [""])[0]
                with dash._lock:
                    snap = [(b.id, b.title, b.namespace, b.status,
                             b.num_crashes,
                             any(c.repro_prog for c in b.crashes))
                            for b in dash.bugs.values()
                            if (not status_filter
                                or b.status == status_filter)
                            and (not ns_filter
                                 or b.namespace == ns_filter)]
                    ns_counts: dict = {}
                    for b in dash.bugs.values():
                        row = ns_counts.setdefault(
                            b.namespace, {"open": 0, "fixed": 0,
                                          "other": 0})
                        key = ("open" if b.status in ("new", "open")
                               else "fixed" if b.status == "fixed"
                               else "other")
                        row[key] += 1
                snap.sort(key=lambda r: -r[4])
                from urllib.parse import quote

                # namespace summary header (reference: main.go
                # handleMain renders per-namespace bug groups)
                summary = "".join(
                    f"<tr><td><a href='/?ns={quote(ns, safe='')}'>"
                    f"{html_mod.escape(ns)}</a></td>"
                    f"<td>{c['open']}</td><td>{c['fixed']}</td>"
                    f"<td>{c['other']}</td></tr>"
                    for ns, c in sorted(ns_counts.items()))
                head = ("<table border=1><tr><th>namespace</th>"
                        "<th>open</th><th>fixed</th><th>other</th>"
                        f"</tr>{summary}</table><hr>")
                rows = "".join(
                    f"<tr><td><a href='/bug?id={bid}'>"
                    f"{html_mod.escape(title)}</a></td>"
                    f"<td><a href='/?ns={quote(ns, safe='')}'>"
                    f"{html_mod.escape(ns)}</a></td>"
                    f"<td>{status}</td><td>{n}</td>"
                    f"<td>{'yes' if has_repro else ''}</td></tr>"
                    for bid, title, ns, status, n, has_repro in snap)
                self._html("bugs", head + "<table border=1>"
                           "<tr><th>title</th><th>namespace</th>"
                           "<th>status</th>"
                           f"<th>crashes</th><th>repro</th></tr>{rows}"
                           "</table>")
            elif url.path == "/bug":
                bid = q.get("id", [""])[0]
                with dash._lock:
                    bug = dash.bugs.get(bid)
                    if bug is None:
                        return self._reply(404, b"no such bug",
                                           "text/plain")
                    crashes = list(bug.crashes)
                    info = (bug.title, bug.status, bug.num_crashes,
                            bug.fix_commit, bug.dup_of)
                title, status, n, fix, dup = info
                # dup_of holds a free-text bug TITLE from the email
                # command, not an id: escape it, don't link it
                body = (f"<p>status: {status} | crashes: {n}"
                        + (f" | fix: {html_mod.escape(fix)}" if fix
                           else "")
                        + (f" | dup of: {html_mod.escape(dup)}"
                           if dup else "") + "</p>")
                body += ("<table border=1><tr><th>manager</th>"
                         "<th>time</th><th>repro</th></tr>")
                for c in crashes:
                    body += (f"<tr><td>{html_mod.escape(c.manager)}"
                             f"</td><td>{time.ctime(c.time)}</td>"
                             f"<td>{'prog' if c.repro_prog else ''}"
                             f"{' C' if c.repro_c else ''}</td></tr>")
                body += "</table>"
                # text-blob links per crash (reference: main.go
                # /x/log.txt /x/repro.syz /x/repro.c)
                links = []
                for i, c in enumerate(crashes):
                    if c.log:
                        links.append(f"<a href='/x/log.txt?id={bid}"
                                     f"&crash={i}'>log{i}</a>")
                    if c.report:
                        links.append(f"<a href='/x/report.txt?id={bid}"
                                     f"&crash={i}'>report{i}</a>")
                    if c.repro_prog:
                        links.append(f"<a href='/x/repro.syz?id={bid}"
                                     f"&crash={i}'>repro{i}.syz</a>")
                    if c.repro_c:
                        links.append(f"<a href='/x/repro.c?id={bid}"
                                     f"&crash={i}'>repro{i}.c</a>")
                if links:
                    body += "<p>" + " | ".join(links) + "</p>"
                repro = next((c.repro_prog for c in crashes
                              if c.repro_prog), "")
                if repro:
                    body += (f"<h3>reproducer</h3><pre>"
                             f"{html_mod.escape(repro)}</pre>")
                self._html(title, body)
            elif url.path in ("/text", "/x/log.txt", "/x/report.txt",
                              "/x/repro.syz", "/x/repro.c",
                              "/x/patch.diff"):
                tag = {"/x/log.txt": "log", "/x/report.txt": "report",
                       "/x/repro.syz": "repro_syz",
                       "/x/repro.c": "repro_c",
                       "/x/patch.diff": "patch"}.get(url.path) \
                    or q.get("tag", [""])[0]
                ident = q.get("id", [""])[0]
                try:
                    ci = int(q.get("crash", ["0"])[0] or 0)
                except ValueError:
                    ci = 0
                if tag not in ("log", "report", "repro_syz",
                               "repro_c", "patch"):
                    return self._reply(404, b"no such text",
                                       "text/plain")
                if tag == "patch":
                    with dash._lock:
                        job = dash.jobs.get(ident)
                        data = job.patch if job else None
                else:
                    with dash._lock:
                        bug = dash.bugs.get(ident)
                        crash = bug.crashes[ci] if bug \
                            and 0 <= ci < len(bug.crashes) else None
                        if crash is None:
                            data = None
                        elif tag == "repro_syz":
                            data = crash.repro_prog
                        elif tag == "repro_c":
                            data = crash.repro_c
                        else:
                            data = getattr(crash, tag, "")
                    if tag in ("log", "report") and data:
                        # stored as a blob file; confine to workdir in
                        # case state.json was tampered with
                        path = os.path.realpath(data)
                        root = os.path.realpath(dash.workdir)
                        if path.startswith(root + os.sep):
                            try:
                                with open(path) as f:
                                    data = f.read()
                            except OSError:
                                data = None
                        else:
                            data = None
                if not data:
                    return self._reply(404, b"no such text",
                                       "text/plain")
                self._reply(200, data.encode(), "text/plain")
            elif url.path == "/builds":
                with dash._lock:
                    snap = sorted(dash.builds.values(),
                                  key=lambda b: -b.time)
                rows = "".join(
                    f"<tr><td>{b.id[:12]}</td>"
                    f"<td>{html_mod.escape(b.manager)}</td>"
                    f"<td>{html_mod.escape(b.kernel_repo)}</td>"
                    f"<td>{html_mod.escape(b.kernel_commit[:12])}</td>"
                    f"<td>{time.ctime(b.time)}</td></tr>"
                    for b in snap[:200])
                self._html("builds", "<table border=1><tr><th>id</th>"
                           "<th>manager</th><th>repo</th><th>commit"
                           f"</th><th>time</th></tr>{rows}</table>")
            elif url.path == "/jobs":
                with dash._lock:
                    snap = list(dash.jobs.values())
                rows = "".join(
                    f"<tr><td>{j.id[:12]}</td>"
                    f"<td><a href='/bug?id={j.bug_id}'>{j.bug_id[:12]}"
                    f"</a></td><td>{j.status}</td>"
                    f"<td>{'ok' if j.result_ok else html_mod.escape(j.result_error)}"
                    f"</td></tr>" for j in snap)
                self._html("jobs", "<table border=1><tr><th>id</th>"
                           "<th>bug</th><th>status</th><th>result"
                           f"</th></tr>{rows}</table>")
            else:
                self._reply(404, b"not found", "text/plain")

    srv = ThreadingHTTPServer(addr, Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, dash
