"""Dashboard client API used by managers and CI
(reference: dashboard/dashapi/dashapi.go:22-240 — UploadBuild,
ReportCrash, NeedRepro, JobPoll/JobDone, ManagerStats over HTTPS)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional


class DashboardError(Exception):
    pass


class DashClient:
    def __init__(self, addr: str, client: str = "", key: str = "",
                 timeout_s: float = 30.0):
        # addr: "host:port" or full http(s) URL
        if not addr.startswith("http"):
            addr = "http://" + addr
        self.base = addr.rstrip("/")
        self.client = client
        self.key = key
        self.timeout_s = timeout_s

    def _call(self, method: str, params: Optional[dict] = None) -> dict:
        payload = dict(params or {})
        payload.setdefault("client", self.client)
        payload.setdefault("key", self.key)
        req = urllib.request.Request(
            f"{self.base}/api/{method}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            raise DashboardError(
                f"{method}: HTTP {e.code}: {e.read().decode()[:256]}") \
                from e
        except (urllib.error.URLError, OSError) as e:
            raise DashboardError(f"{method}: {e}") from e

    # -- API surface (dashapi.go) ----------------------------------------

    def upload_build(self, manager: str, os: str, arch: str,
                     kernel_commit: str = "", kernel_repo: str = "",
                     kernel_branch: str = "", compiler: str = "") -> str:
        res = self._call("upload_build", {
            "manager": manager, "os": os, "arch": arch,
            "kernel_commit": kernel_commit, "kernel_repo": kernel_repo,
            "kernel_branch": kernel_branch, "compiler": compiler})
        return res.get("id", "")

    def report_crash(self, manager: str, title: str, log: str = "",
                     report: str = "", build_id: str = "",
                     repro_prog: str = "", repro_c: str = "") -> dict:
        return self._call("report_crash", {
            "manager": manager, "title": title, "log": log,
            "report": report, "build_id": build_id,
            "repro_prog": repro_prog, "repro_c": repro_c})

    def need_repro(self, title: str) -> bool:
        return bool(self._call("need_repro",
                               {"title": title}).get("need_repro"))

    def manager_stats(self, manager: str, **stats) -> None:
        self._call("manager_stats", {"manager": manager, **stats})

    def job_poll(self, managers: list[str]) -> dict:
        return self._call("job_poll", {"managers": managers})

    def job_done(self, job_id: str, ok: bool, error: str = "") -> None:
        self._call("job_done", {"id": job_id, "ok": ok, "error": error})
