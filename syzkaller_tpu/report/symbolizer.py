"""Kernel symbolization: addr2line/nm wrappers.

Inline-symbolizes raw PC values found in crash reports against a
vmlinux with debug info (reference: pkg/symbolizer/symbolizer.go
addr2line batch pipe + ReadSymbols via nm; consumed by
pkg/report/linux.go:265-371 and syz-manager/cover.go).
"""

from __future__ import annotations

import os
import re
import subprocess
from dataclasses import dataclass
from typing import Optional


@dataclass
class Frame:
    func: str
    file: str
    line: int
    inline: bool = False


@dataclass
class Symbol:
    addr: int
    size: int


class Symbolizer:
    """Long-lived addr2line pipe; one process per binary
    (reference: symbolizer.go Symbolizer.Symbolize)."""

    def __init__(self, addr2line: str = "addr2line"):
        self.addr2line = addr2line
        self._procs: dict[str, subprocess.Popen] = {}

    def _proc(self, binary: str) -> Optional[subprocess.Popen]:
        p = self._procs.get(binary)
        if p is not None and p.poll() is None:
            return p
        try:
            p = subprocess.Popen(
                [self.addr2line, "-afi", "-e", binary],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True)
        except OSError:
            return None
        self._procs[binary] = p
        return p

    # addr2line prints no frame count, so every query is followed by a
    # sentinel address whose -a echo line delimits the answer
    # (reference: symbolizer.go uses the same trick with 0xffffffffffffffff).
    SENTINEL = 0xFFFFFFFFFFFFFFFE

    def symbolize(self, binary: str, *pcs: int) -> list[list[Frame]]:
        """Per-PC inline frame stacks (innermost first)."""
        proc = self._proc(binary)
        if proc is None:
            return [[] for _ in pcs]
        out: list[list[Frame]] = []
        for pc in pcs:
            try:
                proc.stdin.write(f"0x{pc:x}\n0x{self.SENTINEL:x}\n")
                proc.stdin.flush()
                frames = self._read_frames(proc)
            except (OSError, ValueError):
                frames = []
            out.append(frames)
        return out

    def _read_frames(self, proc: subprocess.Popen) -> list[Frame]:
        sentinel_echo = f"0x{self.SENTINEL:016x}"
        proc.stdout.readline()  # address echo of the queried pc
        lines: list[str] = []
        while True:
            line = proc.stdout.readline()
            if not line:
                break
            line = line.strip()
            if line.lower() == sentinel_echo:
                # consume the sentinel's own (??, ??:0) answer
                proc.stdout.readline()
                proc.stdout.readline()
                break
            lines.append(line)
        frames: list[Frame] = []
        for i in range(0, len(lines) - 1, 2):
            func, loc = lines[i], lines[i + 1]
            if func == "??":
                continue
            m = re.match(r"(.*?):(\d+)", loc)
            file, line_no = (m.group(1), int(m.group(2))) if m else (loc, 0)
            frames.append(Frame(func=func, file=_clean_path(file),
                                line=line_no, inline=bool(frames)))
        return frames

    def close(self) -> None:
        for p in self._procs.values():
            try:
                p.stdin.close()
                p.kill()
            except OSError:
                pass
        self._procs.clear()


def _clean_path(path: str) -> str:
    # Strip build-dir prefixes: ".../linux/net/ipv4/ip_output.c" →
    # "net/ipv4/ip_output.c" (reference: linux.go cleanPath).
    m = re.search(r"(?:^|/)((?:arch|block|crypto|drivers|fs|include|ipc|"
                  r"kernel|lib|mm|net|security|sound|virt)/.*)", path)
    return m.group(1) if m else path


def read_symbols(binary: str, nm: str = "nm") -> dict[str, list[Symbol]]:
    """Text-section symbol table (reference: symbolizer.go ReadSymbols)."""
    symbols: dict[str, list[Symbol]] = {}
    try:
        out = subprocess.run([nm, "-nS", binary], capture_output=True,
                             text=True, check=True).stdout
    except (OSError, subprocess.CalledProcessError):
        return symbols
    for line in out.splitlines():
        parts = line.split()
        if len(parts) != 4 or parts[2] not in ("t", "T"):
            continue
        try:
            addr, size = int(parts[0], 16), int(parts[1], 16)
        except ValueError:
            continue
        symbols.setdefault(parts[3], []).append(Symbol(addr, size))
    return symbols


_PC_RE = re.compile(rb"\[<([0-9a-f]{8,16})>\]")


def make_report_symbolizer(kernel_obj: str):
    """Returns a Report post-processor appending file:line to stack
    frames with raw PC values (reference: linux.go:265-371)."""
    vmlinux = os.path.join(kernel_obj, "vmlinux") \
        if os.path.isdir(kernel_obj) else kernel_obj

    def symbolize_report(rep) -> None:
        if not os.path.exists(vmlinux):
            return
        sym = Symbolizer()
        try:
            lines = []
            for line in rep.report.splitlines(keepends=True):
                m = _PC_RE.search(line)
                if m:
                    pc = int(m.group(1), 16)
                    frames = sym.symbolize(vmlinux, pc)[0]
                    if frames and frames[0].func != "??":
                        f = frames[0]
                        line = line.rstrip(b"\n") + \
                            f" {f.file}:{f.line}\n".encode()
                lines.append(line)
            rep.report = b"".join(lines)
        finally:
            sym.close()

    return symbolize_report
