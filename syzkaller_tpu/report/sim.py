"""Crash reporter for the simulated-kernel executor backend.

The sim kernel (executor/sim_kernel.h) emits linux-shaped oopses
("BUG: sim-kernel: use-after-free in sim_call_N" + Call Trace), so the
test OS reuses the linux oops table — the same pattern as the
reference's "test" targets reusing real parsers for hermetic tests.
"""

from __future__ import annotations

from syzkaller_tpu.report.linux import make_linux_reporter
from syzkaller_tpu.report.report import Reporter, register_reporter


def make_sim_reporter(kernel_obj: str = "", ignores=None,
                      suppressions=None) -> Reporter:
    return make_linux_reporter(kernel_obj="", ignores=ignores,
                               suppressions=suppressions)


register_reporter("test", make_sim_reporter)
register_reporter("sim", make_sim_reporter)
