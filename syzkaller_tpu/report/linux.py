"""Linux kernel console-log crash recognition.

The oops table covers the sanitizer and core-kernel report families
the reference recognizes (pkg/report/linux.go:449+ oopses table):
KASAN/KMSAN/KFENCE, kernel BUG, WARNING, general protection fault,
page faults, RCU/soft-lockup/task-hang stalls, lockdep, panics,
divide error, OOM and memory-leak reports.  Titles are templated so
one bug dedups across runs.
"""

from __future__ import annotations

import re
from typing import Optional

from syzkaller_tpu.report.report import (Oops, OopsFormat, Report, Reporter,
                                         register_reporter, sanitize_title)

_FUNC = rb"([a-zA-Z0-9_.]+)"


def _fmt(pat: bytes, fmt: str, **kw) -> OopsFormat:
    return OopsFormat(report=re.compile(pat), fmt=fmt, **kw)


LINUX_OOPSES = [
    Oops(b"KASAN:", [
        _fmt(rb"KASAN: (double-free or invalid-free) in " + _FUNC,
             "KASAN: %s in %s"),
        _fmt(rb"KASAN: ([a-z\-]+) in " + _FUNC, "KASAN: %s in %s"),
        _fmt(rb"KASAN: ([a-z\-]+) on address", "KASAN: %s"),
        _fmt(rb"KASAN: (\S+)", "KASAN: %s"),
    ]),
    Oops(b"KMSAN:", [
        _fmt(rb"KMSAN: ([a-z\-]+) in " + _FUNC, "KMSAN: %s in %s"),
    ]),
    Oops(b"BUG: KFENCE:", [
        _fmt(rb"BUG: KFENCE: ([a-z\- ]+) in " + _FUNC, "KFENCE: %s in %s"),
    ]),
    Oops(b"BUG: memory leak", [  # kmemleak (before the generic BUG:)
        _fmt(rb"BUG: memory leak\n(?:.*\n)*?.*?backtrace:\s*\n\s*\[<[0-9a-fx]+>\] "
             + _FUNC, "memory leak in %s"),
        _fmt(rb"BUG: memory leak", "memory leak"),
    ]),
    Oops(b"BUG:", [
        _fmt(rb"BUG: stack guard page was hit", "kernel stack overflow"),
        _fmt(rb"BUG: unable to handle kernel paging request.*\n.*?(?:IP|RIP):? "
             rb"(?:\[<[0-9a-f]+>\] )?(?:\w+:)?" + _FUNC,
             "BUG: unable to handle kernel paging request in %s"),
        _fmt(rb"BUG: unable to handle kernel NULL pointer dereference"
             rb".*\n.*?(?:IP|RIP):? (?:\[<[0-9a-f]+>\] )?(?:\w+:)?" + _FUNC,
             "BUG: unable to handle kernel NULL pointer dereference in %s"),
        _fmt(rb"BUG: spinlock ([a-z ]+) on CPU", "BUG: spinlock %s"),
        _fmt(rb"BUG: soft lockup - CPU#\d+ stuck for \d+s! \[([^\]:]+)",
             "BUG: soft lockup in %s", stack_title=True),
        _fmt(rb"BUG: workqueue lockup", "BUG: workqueue lockup"),
        _fmt(rb"BUG: sleeping function called from invalid context"
             rb" (?:at|in) ([a-zA-Z0-9_/.\-]+)",
             "BUG: sleeping function called from invalid context in %s"),
        _fmt(rb"BUG: using ([a-z_]+)\(\) in preemptible",
             "BUG: using %s() in preemptible code"),
        _fmt(rb"BUG: sim-kernel: ([a-z\-]+) in " + _FUNC,
             "BUG: sim-kernel: %s in %s"),
        _fmt(rb"BUG: (.*)", "BUG: %s"),
    ], suppressions=[re.compile(rb"DEBUG_PAGEALLOC")]),
    Oops(b"kernel BUG", [
        _fmt(rb"kernel BUG at ([a-zA-Z0-9_/.\-]+):\d+",
             "kernel BUG at %s"),
    ]),
    Oops(b"WARNING:", [
        _fmt(rb"WARNING: CPU: \d+ PID: \d+ at [a-zA-Z0-9_/.\-]+:?\d* "
             + _FUNC, "WARNING in %s"),
        _fmt(rb"WARNING: possible circular locking dependency detected",
             "possible deadlock (circular locking)"),
        _fmt(rb"WARNING: possible recursive locking detected",
             "possible deadlock (recursive locking)"),
        _fmt(rb"WARNING: inconsistent lock state",
             "inconsistent lock state"),
        _fmt(rb"WARNING: suspicious RCU usage",
             "WARNING: suspicious RCU usage"),
        _fmt(rb"WARNING: kernel stack regs .* has bad '(\w+)' value",
             "WARNING: kernel stack regs has bad %s value",
             corrupted=True),
        _fmt(rb"WARNING: (.*)", "WARNING: %s"),
    ], suppressions=[re.compile(rb"WARNING: Audit")]),
    Oops(b"INFO:", [
        _fmt(rb"INFO: rcu_(?:preempt|sched|bh) (?:self-)?detected"
             rb"(?: expedited)? stalls?", "INFO: rcu detected stall"),
        _fmt(rb"INFO: task ([^ :]+):\d+ blocked for more than \d+ seconds",
             "INFO: task hung in %s", stack_title=True),
        _fmt(rb"INFO: possible circular locking dependency detected",
             "possible deadlock (circular locking)"),
        _fmt(rb"INFO: trying to register non-static key",
             "INFO: trying to register non-static key"),
    ], suppressions=[re.compile(rb"INFO: NMI handler")]),
    Oops(b"general protection fault", [
        _fmt(rb"general protection fault.*\n(?:.*\n)*?.*?RIP: "
             rb"(?:\d+:)?" + _FUNC, "general protection fault in %s"),
        _fmt(rb"general protection fault", "general protection fault"),
    ]),
    Oops(b"divide error:", [
        _fmt(rb"divide error.*\n(?:.*\n)*?.*?RIP: (?:\d+:)?" + _FUNC,
             "divide error in %s"),
    ]),
    Oops(b"Unable to handle kernel", [  # arm64 phrasing
        _fmt(rb"Unable to handle kernel ([a-z ]+) at virtual address",
             "unable to handle kernel %s"),
    ]),
    Oops(b"Kernel panic", [
        _fmt(rb"Kernel panic - not syncing: Attempted to kill init",
             "kernel panic: Attempted to kill init", corrupted=True),
        _fmt(rb"Kernel panic - not syncing: ([^\n\r]*)",
             "kernel panic: %s"),
    ]),
    Oops(b"kernel stack overflow", [
        _fmt(rb"kernel stack overflow", "kernel stack overflow"),
    ]),
    Oops(b"Out of memory: Kill process", [
        _fmt(rb"Out of memory: Kill process", "OOM kill"),
    ], suppressions=[re.compile(rb"lowmemorykiller")]),
    Oops(b"unregister_netdevice: waiting for", [
        _fmt(rb"unregister_netdevice: waiting for (\S+)",
             "unregister_netdevice: waiting for %s"),
    ]),
    Oops(b"UBSAN:", [
        _fmt(rb"UBSAN: ([a-z\-_ ]+) in ([a-zA-Z0-9_/.\-]+):\d+",
             "UBSAN: %s in %s"),
        _fmt(rb"UBSAN: (.*)", "UBSAN: %s"),
    ]),
]


# Frames never guilty of a crash: allocation/reporting machinery
# (reference: linux.go:373-447 guilty-file skip lists).
_NON_GUILTY = re.compile(
    r"^(dump_stack|print_|report_|kasan|kmsan|check_memory_region|"
    r"__asan|__kasan|__kmsan|__ubsan|memcpy|memset|memmove|__warn|"
    r"warn_slowpath|panic|_raw_spin|lock_acquire|lock_release|"
    r"debug_|should_fail|fail_dump|slab_|kmalloc|kfree|krealloc|"
    r"__alloc|page_alloc|stack_trace|save_stack|show_stack|"
    r"schedule|__schedule|context_switch|io_schedule|__switch_to)")

_FRAME_RE = re.compile(
    rb"^(?:\[[\s\d.]+\])?\s+(?:\[<[0-9a-fx]+>\]\s*)?\??\s*"
    rb"([a-zA-Z0-9_.]+)\+0x[0-9a-f]+", re.M)


_RIP_RE = re.compile(rb"(?:RIP|IP|pc)\s*:\s*(?:0010:|\[<[0-9a-f]+>\]\s*)?"
                     + _FUNC + rb"\+0x", re.M)


def guilty_function(region: bytes) -> str:
    """First non-infrastructure frame of the first call trace, with
    the faulting RIP/IP as fallback when the trace has no usable
    frames (inline-only traces, truncated logs)."""
    idx = region.find(b"Call Trace:")
    if idx < 0:
        idx = region.find(b"Backtrace:")
    if idx < 0:
        idx = region.find(b"backtrace:")
    if idx >= 0:
        for m in _FRAME_RE.finditer(region[idx:idx + (16 << 10)]):
            fn = m.group(1).decode("utf-8", "replace")
            if not _NON_GUILTY.match(fn):
                return fn
    m = _RIP_RE.search(region)
    if m is not None:
        fn = m.group(1).decode("utf-8", "replace")
        if not _NON_GUILTY.match(fn):
            return fn
    return ""


# Source paths named in oops lines ("kernel BUG at fs/ext4/inode.c:123",
# "WARNING: ... at net/core/dev.c:2345 fn+0x..").  Report-machinery
# files are never the guilty one (reference: linux.go:373-447).
_SRC_PATH_RE = re.compile(
    rb"\b((?:kernel|mm|fs|net|drivers|sound|block|crypto|security|lib|"
    rb"arch|ipc|io_uring|virt)/[A-Za-z0-9_/.\-]+\.[chS])[:!,]")

_NON_GUILTY_SRC = re.compile(
    r"^(mm/kasan/|mm/kmsan/|mm/kfence/|kernel/locking/lockdep|"
    r"lib/dump_stack|kernel/panic|lib/ubsan|mm/page_alloc|mm/slab|"
    r"mm/slub|kernel/rcu/|lib/fault-inject)")


def guilty_source(region: bytes) -> str:
    """First source path named by the report that isn't reporting
    machinery (the file get_maintainer would be asked about)."""
    for m in _SRC_PATH_RE.finditer(region[:16 << 10]):
        path = m.group(1).decode("utf-8", "replace")
        if not _NON_GUILTY_SRC.match(path):
            return path
    return ""


# Subsystem routing when no kernel tree (with scripts/get_maintainer.pl)
# is configured: the longest matching path prefix wins, everything also
# goes to LKML — the same routing shape get_maintainer.pl yields.
LKML = "linux-kernel@vger.kernel.org"
_MAINTAINERS_TABLE = [
    ("net/ipv4/", ["netdev@vger.kernel.org"]),
    ("net/ipv6/", ["netdev@vger.kernel.org"]),
    ("net/sctp/", ["linux-sctp@vger.kernel.org",
                   "netdev@vger.kernel.org"]),
    ("net/", ["netdev@vger.kernel.org"]),
    ("fs/ext4/", ["linux-ext4@vger.kernel.org"]),
    ("fs/btrfs/", ["linux-btrfs@vger.kernel.org"]),
    ("fs/xfs/", ["linux-xfs@vger.kernel.org"]),
    ("fs/f2fs/", ["linux-f2fs-devel@lists.sourceforge.net"]),
    ("fs/", ["linux-fsdevel@vger.kernel.org"]),
    ("mm/", ["linux-mm@kvack.org"]),
    ("drivers/usb/", ["linux-usb@vger.kernel.org"]),
    ("drivers/input/", ["linux-input@vger.kernel.org"]),
    ("drivers/media/", ["linux-media@vger.kernel.org"]),
    ("drivers/block/", ["linux-block@vger.kernel.org"]),
    ("drivers/net/", ["netdev@vger.kernel.org"]),
    ("sound/", ["alsa-devel@alsa-project.org"]),
    ("block/", ["linux-block@vger.kernel.org"]),
    ("crypto/", ["linux-crypto@vger.kernel.org"]),
    ("security/selinux/", ["selinux@vger.kernel.org"]),
    ("kernel/bpf/", ["bpf@vger.kernel.org"]),
    ("kernel/trace/", ["linux-trace-kernel@vger.kernel.org"]),
    ("arch/x86/kvm/", ["kvm@vger.kernel.org"]),
    ("virt/kvm/", ["kvm@vger.kernel.org"]),
]


def maintainers_for(path: str, kernel_src: str = "") -> list[str]:
    """Maintainer addresses for a guilty source file (reference:
    linux.go getMaintainers via scripts/get_maintainer.pl)."""
    if not path:
        return []
    if kernel_src:
        import os
        import subprocess
        script = os.path.join(kernel_src, "scripts", "get_maintainer.pl")
        if os.path.exists(script):
            try:
                out = subprocess.run(
                    [script, "--no-n", "--no-rolestats", "-f", path],
                    capture_output=True, text=True, timeout=60,
                    cwd=kernel_src)
                addrs = [ln.strip() for ln in out.stdout.splitlines()
                         if "@" in ln]
                if addrs:
                    return addrs
            except (OSError, subprocess.SubprocessError):
                pass
    best: list[str] = []
    best_len = -1
    for prefix, addrs in _MAINTAINERS_TABLE:
        if path.startswith(prefix) and len(prefix) > best_len:
            best, best_len = addrs, len(prefix)
    return best + [LKML] if best else [LKML]


def corrupted_reason(title: str, region: bytes) -> Optional[str]:
    """Heuristics for truncated/interleaved reports
    (reference: linux.go:449-520 isCorrupted)."""
    # A report whose oops line appears with no stack trace within its
    # region is likely cut off by a reboot or log loss.
    needs_trace = any(k in title for k in
                      ("KASAN", "WARNING in", "general protection",
                       "paging request", "sim-kernel"))
    has_trace = (b"Call Trace:" in region or b"Backtrace:" in region
                 or b"call trace:" in region.lower())
    if needs_trace and not has_trace:
        return "no stack trace in report"
    if b"Code: Bad RIP value" in region:
        return "corrupted RIP"
    if title.endswith(("ADDR", "NUM")) and "in" not in title:
        return "title carries no symbol"
    return None


def make_linux_reporter(kernel_obj: str = "", ignores=None,
                        suppressions=None,
                        kernel_src: str = "") -> Reporter:
    symbolize_fn = None
    if kernel_obj:
        from syzkaller_tpu.report.symbolizer import make_report_symbolizer

        symbolize_fn = make_report_symbolizer(kernel_obj)

    def attribution_fn(region: bytes) -> tuple[str, list[str]]:
        src = guilty_source(region)
        return src, maintainers_for(src, kernel_src=kernel_src)

    return Reporter(LINUX_OOPSES, ignores=ignores,
                    suppressions=suppressions,
                    symbolize_fn=symbolize_fn,
                    guilty_fn=guilty_function,
                    corrupted_fn=corrupted_reason,
                    attribution_fn=attribution_fn)


register_reporter("linux", make_linux_reporter)
