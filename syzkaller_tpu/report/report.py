"""Crash report extraction from console output.

Per-OS parsers turn raw console logs into deduplicatable reports with
templated titles (reference: pkg/report/report.go:18-28 Reporter
interface, 125-161 oops scanning machinery).  The generic scanner
works off a per-OS table of oops patterns; each pattern carries title
formats that extract and normalize the crash identity (addresses and
counters templated away so the same bug dedups across runs).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Optional, Pattern, Union


@dataclass
class Report:
    """(reference: pkg/report/report.go:30-47)"""
    title: str = ""
    report: bytes = b""  # the oops region of the console output
    output: bytes = b""  # full console output
    start_pos: int = 0
    end_pos: int = 0
    corrupted: bool = False
    corrupted_reason: str = ""
    suppressed: bool = False
    maintainers: list[str] = field(default_factory=list)
    guilty_file: str = ""  # guilty function (first non-infra frame)
    guilty_src: str = ""  # guilty source path (maintainer routing key)


@dataclass
class OopsFormat:
    """One title extractor under an oops pattern
    (reference: pkg/report/report.go oopsFormat)."""
    report: Pattern  # matched against the oops region
    fmt: str  # title template with %s per capture group
    alt: Optional[Pattern] = None
    no_stack_trace: bool = False
    corrupted: bool = False
    # Title the crash by the first guilty stack frame instead of the
    # regex capture (reference: report.go oopsFormat.stack extraction
    # for hang/lockup reports whose header names only the comm).
    stack_title: bool = False


@dataclass
class Oops:
    """(reference: pkg/report/report.go oops)"""
    header: bytes
    formats: list[OopsFormat]
    suppressions: list[Pattern] = field(default_factory=list)


# Text fragments whose presence in a line disqualifies it as an oops
# start (log echoes, fuzzer's own prints, etc.).
_GENERIC_IGNORES = [
    re.compile(rb"executing program"),
    re.compile(rb"Slab corruption reporter"),
]


class Reporter:
    """Generic per-OS console parser driven by an oops table."""

    def __init__(self, oopses: list[Oops],
                 ignores: Optional[list[Union[str, Pattern]]] = None,
                 suppressions: Optional[list[Union[str, Pattern]]] = None,
                 symbolize_fn: Optional[Callable[[Report], None]] = None,
                 guilty_fn: Optional[Callable[[bytes], str]] = None,
                 corrupted_fn: Optional[
                     Callable[[str, bytes], Optional[str]]] = None,
                 attribution_fn: Optional[
                     Callable[[bytes], tuple[str, list[str]]]] = None):
        self.oopses = oopses
        self.ignores = [re.compile(p.encode() if isinstance(p, str) else p)
                        if isinstance(p, (str, bytes)) else p
                        for p in (ignores or [])]
        self.suppressions = [
            re.compile(p.encode() if isinstance(p, str) else p)
            if isinstance(p, (str, bytes)) else p
            for p in (suppressions or [])]
        self._symbolize = symbolize_fn
        self._guilty = guilty_fn
        self._corrupted = corrupted_fn
        self._attribution = attribution_fn

    # -- detection --------------------------------------------------------

    def contains_crash(self, output: bytes) -> bool:
        """Fast scan used by the VM monitor on every console chunk
        (reference: report.go:18-21, vm/vm.go MonitorExecution)."""
        return self._find_oops(output) is not None

    def _line_ignored(self, line: bytes) -> bool:
        return any(p.search(line) for p in self.ignores + _GENERIC_IGNORES)

    def _find_oops(self, output: bytes,
                   start: int = 0) -> Optional[tuple[int, Oops]]:
        pos = start
        n = len(output)
        while pos < n:
            end = output.find(b"\n", pos)
            if end == -1:
                end = n
            line = output[pos:end]
            for oops in self.oopses:
                if oops.header in line and not self._line_ignored(line):
                    if not any(s.search(line) for s in oops.suppressions):
                        return pos, oops
            pos = end + 1
        return None

    # -- parsing ----------------------------------------------------------

    def parse(self, output: bytes) -> Optional[Report]:
        """Extract the first crash (reference: linux.go:105 Parse)."""
        found = self._find_oops(output)
        if found is None:
            return None
        start, oops = found
        # Report region: from the oops line to EOF, capped.
        region = output[start:start + (512 << 10)]
        rep = Report(output=output, start_pos=start,
                     end_pos=min(len(output), start + len(region)),
                     report=region)
        guilty = self._guilty(region) if self._guilty is not None else ""
        rep.title, corrupted_fmt = self._extract_title(region, oops,
                                                       guilty)
        if any(s.search(rep.title.encode()) for s in self.suppressions):
            rep.suppressed = True
        if corrupted_fmt:
            rep.corrupted = True
            rep.corrupted_reason = "matched corrupted-output format"
        elif self._corrupted is not None:
            reason = self._corrupted(rep.title, region)
            if reason:
                rep.corrupted = True
                rep.corrupted_reason = reason
        rep.guilty_file = guilty
        if self._attribution is not None:
            rep.guilty_src, rep.maintainers = self._attribution(region)
        return rep

    def _extract_title(self, region: bytes, oops: Oops,
                       guilty: str = "") -> tuple[str, bool]:
        for f in oops.formats:
            m = f.report.search(region)
            if m is None and f.alt is not None:
                m = f.alt.search(region)
            if m is None:
                continue
            groups = [g.decode("utf-8", "replace") if g is not None else ""
                      for g in m.groups()]
            if f.stack_title and guilty and groups:
                # Title by the guilty stack frame; the regex capture
                # (usually the comm name) is only the fallback.
                groups[-1] = guilty
            title = f.fmt
            for g in groups:
                title = title.replace("%s", sanitize_symbol(g), 1)
            return title, f.corrupted
        # Fallback: the raw first line of the oops.
        first = region.split(b"\n", 1)[0].decode("utf-8", "replace")
        return sanitize_title(first), False

    def symbolize(self, rep: Report) -> None:
        """(reference: report.go:26-28 + linux.go:265-371)"""
        if self._symbolize is not None:
            self._symbolize(rep)


def sanitize_symbol(sym: str) -> str:
    """Strip instantiation suffixes like .isra.5/.constprop.2 and
    offsets so the same function dedups (reference: linux.go title
    replacement logic)."""
    sym = re.sub(r"\.(isra|constprop|part|cold)\.?\d*", "", sym)
    sym = re.sub(r"\+0x[0-9a-f]+(/0x[0-9a-f]+)?", "", sym)
    return sym


def sanitize_title(title: str) -> str:
    """Template away run-specific values: hex addresses → ADDR,
    decimals → NUM (reference: report.go sanitization in oopsFormat
    fmt usage)."""
    title = re.sub(r"0x[0-9a-f]{4,}", "ADDR", title)
    title = re.sub(r"\b[0-9a-f]{8,16}\b", "ADDR", title)
    title = re.sub(r"\b\d+\b", "NUM", title)
    return title.strip()


_REPORTER_CTORS: dict[str, Callable[..., Reporter]] = {}


def register_reporter(os: str, ctor: Callable[..., Reporter]) -> None:
    _REPORTER_CTORS[os] = ctor


def get_reporter(os: str, kernel_obj: str = "",
                 ignores: Optional[list] = None,
                 suppressions: Optional[list] = None) -> Reporter:
    """(reference: pkg/report/report.go:49-76 NewReporter)"""
    from syzkaller_tpu.report import linux, sim  # noqa: F401 (registration)

    ctor = _REPORTER_CTORS.get(os)
    if ctor is None:
        raise ValueError(f"no crash reporter for OS {os!r}")
    return ctor(kernel_obj=kernel_obj, ignores=ignores or [],
                suppressions=suppressions or [])
