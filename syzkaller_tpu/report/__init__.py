from syzkaller_tpu.report.report import (Report, Reporter, get_reporter)

__all__ = ["Report", "Reporter", "get_reporter"]
