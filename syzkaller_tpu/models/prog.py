"""Program representation: Arg graph, Call, Prog.

Mirrors the reference data model (reference: prog/prog.go:10-503).
Six concrete arg kinds; ResultArg carries the cross-call dataflow graph
(res/uses edges) that drives both mutation legality and exec-format
copyout indices.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from syzkaller_tpu.models.types import (
    ArrayKind,
    ArrayType,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    Syscall,
    Type,
    UnionType,
    VmaType,
    is_pad,
)

MASK64 = (1 << 64) - 1


class Arg:
    """Base class of argument values."""

    __slots__ = ("typ",)

    def __init__(self, typ: Type):
        self.typ = typ

    def size(self) -> int:
        return self.typ.size()


class ConstArg(Arg):
    """Value of ConstType, IntType, FlagsType, LenType, ProcType, CsumType
    (reference: prog/prog.go:36-92)."""

    __slots__ = ("val",)

    def __init__(self, typ: Type, val: int):
        super().__init__(typ)
        self.val = val & MASK64

    def value(self) -> tuple[int, int, bool]:
        """Returns (value, pid_stride, big_endian) for exec encoding."""
        t = self.typ
        if isinstance(t, CsumType):
            # Checksums are computed dynamically in the executor.
            return 0, 0, False
        if isinstance(t, ProcType):
            if self.val == t.default():
                return 0, 0, False
            return (t.values_start + self.val) & MASK64, t.values_per_proc, t.big_endian
        if isinstance(t, ResourceType):
            assert t.desc is not None and t.desc.type is not None
            return self.val, 0, t.desc.type.big_endian  # type: ignore[attr-defined]
        big_endian = getattr(t, "big_endian", False)
        return self.val, 0, big_endian


class PointerArg(Arg):
    """Value of PtrType and VmaType (reference: prog/prog.go:95-136)."""

    __slots__ = ("address", "vma_size", "res")

    def __init__(self, typ: Type, address: int = 0, res: Optional[Arg] = None,
                 vma_size: int = 0):
        super().__init__(typ)
        self.address = address
        self.vma_size = vma_size  # size of referenced region for vma args
        self.res = res  # pointee (None for vma and null pointers)

    @classmethod
    def make_null(cls, typ: Type) -> "PointerArg":
        return cls(typ)

    @classmethod
    def make_vma(cls, typ: Type, addr: int, size: int) -> "PointerArg":
        assert addr % 1024 == 0, "unaligned vma address"
        return cls(typ, address=addr, vma_size=size)

    def is_null(self) -> bool:
        return self.address == 0 and self.vma_size == 0 and self.res is None


class DataArg(Arg):
    """Value of BufferType; holds bytes for in/inout, only a size for out
    (reference: prog/prog.go:139-171)."""

    __slots__ = ("data", "out_size")

    def __init__(self, typ: Type, data: bytes = b"", out_size: int = 0):
        super().__init__(typ)
        if typ.dir == Dir.OUT:
            assert not data, "non-empty output data arg"
        self.data = bytearray(data)
        self.out_size = out_size

    def size(self) -> int:
        if len(self.data) != 0:
            return len(self.data)
        return self.out_size


class GroupArg(Arg):
    """Value of StructType and ArrayType (reference: prog/prog.go:175-221)."""

    __slots__ = ("inner",)

    def __init__(self, typ: Type, inner: list[Arg]):
        super().__init__(typ)
        self.inner = inner

    def size(self) -> int:
        t = self.typ
        if not t.varlen:
            return t.size()
        if isinstance(t, StructType):
            sz = sum(f.size() for f in self.inner if not f.typ.bitfield_middle())
            if t.align_attr and sz % t.align_attr:
                sz += t.align_attr - sz % t.align_attr
            return sz
        if isinstance(t, ArrayType):
            return sum(e.size() for e in self.inner)
        raise TypeError(f"bad group arg type {t}")

    def fixed_inner_size(self) -> bool:
        t = self.typ
        if isinstance(t, StructType):
            return True
        if isinstance(t, ArrayType):
            return t.kind == ArrayKind.RANGE_LEN and t.range_begin == t.range_end
        raise TypeError(f"bad group arg type {t}")


class UnionArg(Arg):
    __slots__ = ("option",)

    def __init__(self, typ: Type, option: Arg):
        super().__init__(typ)
        self.option = option

    def size(self) -> int:
        if not self.typ.varlen:
            return self.typ.size()
        return self.option.size()


class ResultArg(Arg):
    """Value of ResourceType; the only arg usable as a syscall return.
    Holds either a constant or a reference to the producing ResultArg,
    maintaining the uses back-edges (reference: prog/prog.go:243-272)."""

    __slots__ = ("res", "op_div", "op_add", "val", "uses")

    def __init__(self, typ: Type, res: Optional["ResultArg"] = None, val: int = 0):
        super().__init__(typ)
        self.res = res
        self.op_div = 0
        self.op_add = 0
        self.val = val & MASK64
        self.uses: set[ResultArg] = set()
        if res is not None:
            res.uses.add(self)

    def __hash__(self) -> int:
        return id(self)

    def __eq__(self, other: object) -> bool:
        return self is other


def make_return_arg(typ: Optional[Type]) -> Optional[ResultArg]:
    if typ is None:
        return None
    assert typ.dir == Dir.OUT, "return arg is not out"
    return ResultArg(typ)


@dataclass
class Call:
    meta: Syscall
    args: list[Arg] = field(default_factory=list)
    ret: Optional[ResultArg] = None
    # Comparison operands observed for this call (hints mode), set by ipc.
    comps: Optional[dict] = None


@dataclass
class Prog:
    target: "Target"  # noqa: F821
    calls: list[Call] = field(default_factory=list)

    # -- structural edits ------------------------------------------------

    def insert_before(self, c: Optional[Call], calls: list[Call]) -> None:
        """Insert calls before c (or append if c is None/absent)
        (reference: prog/prog.go:410-425)."""
        idx = len(self.calls)
        for i, cc in enumerate(self.calls):
            if cc is c:
                idx = i
                break
        self.calls[idx:idx] = calls

    def remove_call(self, idx: int) -> None:
        """Remove call idx, redirecting dangling resource uses to default
        values (reference: prog/prog.go:492-502)."""
        c = self.calls[idx]
        for arg in c.args:
            remove_arg(arg)
        if c.ret is not None:
            remove_arg(c.ret)
        del self.calls[idx]

    def clone(self) -> "Prog":
        return clone_prog(self)

    def __len__(self) -> int:
        return len(self.calls)


@dataclass
class ArgCtx:
    """Walk context (reference: prog/analysis.go:100-105).

    parent is the list of sibling args: the enclosing struct's fields or
    the call's top-level args (not set for arrays) — len-type mutation
    and size assignment look up the measured buffer among these.
    """

    parent: Optional[list[Arg]] = None
    base: Optional[PointerArg] = None  # pointer to the heap object containing arg
    offset: int = 0  # offset of arg within the base object
    stop: bool = False  # set by callback to stop descending


def foreach_sub_arg(arg: Arg, fn: Callable[[Arg, ArgCtx], None]) -> None:
    _foreach_arg_impl(arg, ArgCtx(), fn)


def foreach_arg(c: Call, fn: Callable[[Arg, ArgCtx], None]) -> None:
    """Visit ret (if any), then each top-level arg and its subtree
    (reference: prog/analysis.go:111-120)."""
    if c.ret is not None:
        _foreach_arg_impl(c.ret, ArgCtx(), fn)
    ctx = ArgCtx(parent=c.args)
    for arg in c.args:
        _foreach_arg_impl(arg, ctx, fn)


def _foreach_arg_impl(arg: Arg, ctx: ArgCtx, fn: Callable[[Arg, ArgCtx], None]) -> None:
    # Each node sees its own copy of the context so callbacks can't
    # corrupt siblings; offsets accumulate within the current base
    # object (reference: prog/analysis.go:122-156).
    ctx = ArgCtx(parent=ctx.parent, base=ctx.base, offset=ctx.offset)
    fn(arg, ctx)
    if ctx.stop:
        return
    if isinstance(arg, GroupArg):
        if isinstance(arg.typ, StructType):
            ctx.parent = arg.inner
        for f in arg.inner:
            _foreach_arg_impl(f, ctx, fn)
            if not f.typ.bitfield_middle():
                ctx.offset += f.size()
    elif isinstance(arg, PointerArg):
        if arg.res is not None:
            ctx.base = arg
            ctx.offset = 0
            _foreach_arg_impl(arg.res, ctx, fn)
    elif isinstance(arg, UnionArg):
        _foreach_arg_impl(arg.option, ctx, fn)


def inner_arg(arg: Arg) -> Optional[Arg]:
    """Chase pointers to the pointee (reference: prog/prog.go:279-293)."""
    if isinstance(arg.typ, PtrType):
        if isinstance(arg, PointerArg):
            if arg.res is None:
                assert arg.typ.optional, "non-optional pointer is nil"
                return None
            return inner_arg(arg.res)
        return None
    return arg


# -- replace/remove maintaining the ResultArg graph ----------------------


def replace_arg(arg: Arg, arg1: Arg) -> None:
    """In-place overwrite of arg with arg1, fixing uses edges
    (reference: prog/prog.go:428-470)."""
    if isinstance(arg, ResultArg):
        replace_result_arg(arg, arg1)  # type: ignore[arg-type]
    elif isinstance(arg, GroupArg):
        a1 = arg1
        assert isinstance(a1, GroupArg)
        assert len(arg.inner) == len(a1.inner), "group fields don't match"
        arg.typ = a1.typ
        for sub, sub1 in zip(arg.inner, a1.inner):
            replace_arg(sub, sub1)
    elif isinstance(arg, ConstArg):
        assert isinstance(arg1, ConstArg)
        arg.typ, arg.val = arg1.typ, arg1.val
    elif isinstance(arg, PointerArg):
        assert isinstance(arg1, PointerArg)
        arg.typ, arg.address, arg.vma_size, arg.res = (
            arg1.typ, arg1.address, arg1.vma_size, arg1.res)
    elif isinstance(arg, UnionArg):
        assert isinstance(arg1, UnionArg)
        arg.typ, arg.option = arg1.typ, arg1.option
    elif isinstance(arg, DataArg):
        assert isinstance(arg1, DataArg)
        arg.typ, arg.data, arg.out_size = arg1.typ, arg1.data, arg1.out_size
    else:
        raise TypeError(f"replace_arg: bad arg kind {arg}")


def replace_result_arg(arg: ResultArg, arg1: ResultArg) -> None:
    if arg.res is not None:
        arg.res.uses.discard(arg)
    # Copy everything except the set of users of arg itself.
    arg.typ, arg.res, arg.op_div, arg.op_add, arg.val = (
        arg1.typ, arg1.res, arg1.op_div, arg1.op_add, arg1.val)
    if arg.res is not None:
        arg.res.uses.discard(arg1)
        arg.res.uses.add(arg)


def remove_arg(arg0: Arg) -> None:
    """Drop all graph references to/from arg0's subtree
    (reference: prog/prog.go:473-489)."""

    def visit(arg: Arg, ctx: ArgCtx) -> None:
        if isinstance(arg, ResultArg):
            if arg.res is not None:
                assert arg in arg.res.uses, "broken ResultArg tree"
                arg.res.uses.discard(arg)
            for user in list(arg.uses):
                repl = ResultArg(user.typ, None, user.typ.default())
                replace_result_arg(user, repl)

    foreach_sub_arg(arg0, visit)


# -- deep copy -----------------------------------------------------------


def clone_prog(p: Prog) -> Prog:
    """Deep copy preserving the ResultArg reference graph
    (reference: prog/clone.go:6-32)."""
    newargs: dict[int, ResultArg] = {}
    p1 = Prog(target=p.target)
    for c in p.calls:
        c1 = Call(meta=c.meta,
                  args=[_clone_arg(a, newargs) for a in c.args],
                  ret=_clone_arg(c.ret, newargs) if c.ret is not None else None)
        p1.calls.append(c1)
    _patch_res_refs(p1, newargs)
    return p1


def clone_call(c: Call) -> Call:
    """Deep copy of a single call; external resource refs become local
    constants."""
    newargs: dict[int, ResultArg] = {}
    c1 = Call(meta=c.meta,
              args=[_clone_arg(a, newargs) for a in c.args],
              ret=_clone_arg(c.ret, newargs) if c.ret is not None else None)
    p = Prog(target=None, calls=[c1])  # type: ignore[arg-type]
    _patch_res_refs(p, newargs)
    return c1


def _clone_arg(arg: Arg, newargs: dict[int, ResultArg]):
    if isinstance(arg, ConstArg):
        return ConstArg(arg.typ, arg.val)
    if isinstance(arg, PointerArg):
        res = _clone_arg(arg.res, newargs) if arg.res is not None else None
        return PointerArg(arg.typ, arg.address, res, arg.vma_size)
    if isinstance(arg, DataArg):
        a = DataArg(arg.typ, out_size=arg.out_size)
        a.data = bytearray(arg.data)
        return a
    if isinstance(arg, GroupArg):
        return GroupArg(arg.typ, [_clone_arg(x, newargs) for x in arg.inner])
    if isinstance(arg, UnionArg):
        return UnionArg(arg.typ, _clone_arg(arg.option, newargs))
    if isinstance(arg, ResultArg):
        a = ResultArg(arg.typ, None, arg.val)
        a.op_div, a.op_add = arg.op_div, arg.op_add
        # Temporarily alias res to the old producer; fixed in _patch_res_refs.
        a.res = arg.res  # type: ignore[assignment]
        newargs[id(arg)] = a
        return a
    raise TypeError(f"clone: bad arg kind {arg}")


def _patch_res_refs(p: Prog, newargs: dict[int, ResultArg]) -> None:
    for a in newargs.values():
        if a.res is not None:
            new_res = newargs.get(id(a.res))
            a.res = new_res
            if new_res is not None:
                new_res.uses.add(a)
            else:
                # Reference to an arg outside the cloned region: degrade
                # to the type's default constant.
                a.val = a.typ.default()


def iter_args(p: Prog) -> Iterator[tuple[Call, Arg, ArgCtx]]:
    for c in p.calls:
        collected: list[tuple[Arg, ArgCtx]] = []
        foreach_arg(c, lambda a, ctx: collected.append((a, ctx)))
        for a, ctx in collected:
            yield c, a, ctx


# -- default args --------------------------------------------------------


def default_arg(target: "Target", t: Type) -> Arg:  # noqa: F821
    """The neutral value of a type (reference: prog/prog.go:295-343)."""
    if isinstance(t, ResourceType):
        return ResultArg(t, None, t.default())
    if isinstance(t, (IntType, ConstType, FlagsType, LenType, ProcType, CsumType)):
        return ConstArg(t, t.default())
    if isinstance(t, BufferType):
        if t.dir == Dir.OUT:
            sz = 0 if t.varlen else t.size()
            return DataArg(t, out_size=sz)
        data = b"" if t.varlen else bytes(t.size())
        return DataArg(t, data)
    if isinstance(t, ArrayType):
        elems: list[Arg] = []
        if t.kind == ArrayKind.RANGE_LEN and t.range_begin == t.range_end:
            elems = [default_arg(target, t.elem) for _ in range(t.range_begin)]
        return GroupArg(t, elems)
    if isinstance(t, StructType):
        return GroupArg(t, [default_arg(target, f) for f in t.fields])
    if isinstance(t, UnionType):
        return UnionArg(t, default_arg(target, t.fields[0]))
    if isinstance(t, VmaType):
        if t.optional:
            return PointerArg.make_null(t)
        return PointerArg.make_vma(t, 0, target.page_size)
    if isinstance(t, PtrType):
        if t.optional:
            return PointerArg.make_null(t)
        return PointerArg(t, 0, default_arg(target, t.elem))
    raise TypeError(f"unknown arg type: {t}")


def is_default_arg(target: "Target", arg: Arg) -> bool:  # noqa: F821
    """True if arg holds its type's neutral value
    (reference: prog/prog.go:345-408)."""
    if is_pad(arg.typ):
        return True
    if isinstance(arg, ConstArg):
        return arg.val == arg.typ.default()
    if isinstance(arg, GroupArg):
        if not arg.fixed_inner_size() and len(arg.inner) != 0:
            return False
        return all(is_default_arg(target, e) for e in arg.inner)
    if isinstance(arg, UnionArg):
        t = arg.typ
        assert isinstance(t, UnionType)
        return (arg.option.typ.field_name == t.fields[0].field_name
                and is_default_arg(target, arg.option))
    if isinstance(arg, DataArg):
        if arg.size() == 0:
            return True
        if arg.typ.varlen:
            return False
        if arg.typ.dir == Dir.OUT:
            return True
        return all(v == 0 for v in arg.data)
    if isinstance(arg, PointerArg):
        t = arg.typ
        if isinstance(t, PtrType):
            if t.optional:
                return arg.is_null()
            return arg.address == 0 and is_default_arg(target, arg.res)
        if isinstance(t, VmaType):
            if t.optional:
                return arg.is_null()
            return arg.address == 0 and arg.vma_size == target.page_size
        raise TypeError(f"unknown pointer type {t}")
    if isinstance(arg, ResultArg):
        return (arg.res is None and arg.op_div == 0 and arg.op_add == 0
                and len(arg.uses) == 0 and arg.val == arg.typ.default())
    return False
