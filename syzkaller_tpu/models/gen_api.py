"""Helper API handed to target-specific special-type generators
(reference: prog/target.go:155-210)."""

from __future__ import annotations

from syzkaller_tpu.models.prog import Arg, Call
from syzkaller_tpu.models.size import assign_sizes_array
from syzkaller_tpu.models.types import Type


class Gen:
    def __init__(self, rng, state):
        self.rng = rng
        self.state = state

    @property
    def target(self):
        return self.rng.target

    def n_out_of(self, n: int, out_of: int) -> bool:
        return self.rng.n_out_of(n, out_of)

    def alloc(self, ptr_type: Type, data: Arg) -> tuple[Arg, list[Call]]:
        from syzkaller_tpu.models.generation import alloc_addr

        return alloc_addr(self.rng, self.state, ptr_type, data.size(), data), []

    def generate_arg(self, typ: Type, pcalls: list[Call]) -> Arg:
        return self._generate_arg(typ, pcalls, ignore_special=False)

    def generate_special_arg(self, typ: Type, pcalls: list[Call]) -> Arg:
        return self._generate_arg(typ, pcalls, ignore_special=True)

    def _generate_arg(self, typ: Type, pcalls: list[Call], ignore_special: bool) -> Arg:
        from syzkaller_tpu.models.generation import generate_arg_impl

        arg, calls = generate_arg_impl(self.rng, self.state, typ, ignore_special)
        pcalls.extend(calls)
        assign_sizes_array([arg])
        return arg

    def mutate_arg(self, arg0: Arg) -> list[Call]:
        """(reference: prog/target.go:191-210)"""
        from syzkaller_tpu.models.mutation import MutationArgs, mutate_arg
        from syzkaller_tpu.models.prog import foreach_sub_arg

        calls: list[Call] = []
        update_sizes = [True]
        while True:
            ma = MutationArgs(self.target, ignore_special=True)
            foreach_sub_arg(arg0, ma.collect)
            if not ma.args:
                return calls
            idx = self.rng.intn(len(ma.args))
            arg, ctx = ma.args[idx], ma.ctxes[idx]
            new_calls, ok = mutate_arg(self.rng, self.state, arg, ctx, update_sizes)
            if ok:
                calls.extend(new_calls)
            if self.rng.one_of(3):
                return calls
