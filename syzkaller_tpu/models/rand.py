"""Fuzzing RNG distributions.

The biased distributions here define the reference's carefully-tuned
mutation statistics (reference: prog/rand.go:17-151).  The CPU engine
uses them directly; the batched TPU engine (ops/rng.py) re-derives the
same distributions from jax.random primitives and is parity-tested
against this module.
"""

from __future__ import annotations

import math
import random
from typing import Optional

MASK64 = (1 << 64) - 1

# Potentially interesting integers (reference: prog/rand.go:57-65).
SPECIAL_INTS: tuple[int, ...] = (
    0, 1, 31, 32, 63, 64, 127, 128,
    129, 255, 256, 257, 511, 512,
    1023, 1024, 1025, 2047, 2048, 4095, 4096,
    (1 << 15) - 1, (1 << 15), (1 << 15) + 1,
    (1 << 16) - 1, (1 << 16), (1 << 16) + 1,
    (1 << 31) - 1, (1 << 31), (1 << 31) + 1,
    (1 << 32) - 1, (1 << 32), (1 << 32) + 1,
)

SPECIAL_INTS_SET = frozenset(SPECIAL_INTS)


class RandGen:
    """Wraps a seeded PRNG with the fuzzing distributions
    (reference: prog/rand.go:17-54)."""

    def __init__(self, target, seed_or_rng=None):
        self.target = target
        if isinstance(seed_or_rng, random.Random):
            self.r = seed_or_rng
        else:
            self.r = random.Random(seed_or_rng)
        self.in_create_resource = False
        self.rec_depth: dict[str, int] = {}

    # -- primitives ------------------------------------------------------

    def intn(self, n: int) -> int:
        return self.r.randrange(n)

    def rand(self, n: int) -> int:
        return self.r.randrange(n)

    def rand_range(self, begin: int, end: int) -> int:
        return begin + self.r.randrange(end - begin + 1)

    def bin(self) -> bool:
        return self.r.randrange(2) == 0

    def one_of(self, n: int) -> bool:
        return self.r.randrange(n) == 0

    def n_out_of(self, n: int, out_of: int) -> bool:
        assert 0 < n < out_of, "bad probability"
        return self.r.randrange(out_of) < n

    def uint64(self) -> int:
        return self.r.getrandbits(64)

    def int31(self) -> int:
        return self.r.getrandbits(31)

    def rand64(self) -> int:
        """63 random bits, top bit set half the time
        (reference: prog/rand.go:48-54)."""
        v = self.r.getrandbits(63)
        if self.bin():
            v |= 1 << 63
        return v

    # -- biased distributions --------------------------------------------

    def rand_int(self) -> int:
        """The magic integer distribution: strongly favors small values
        and special constants, with occasional negation/shifts
        (reference: prog/rand.go:67-91)."""
        v = self.rand64()
        if self.n_out_of(100, 182):
            v %= 10
        elif self.n_out_of(50, 82):
            v = SPECIAL_INTS[self.intn(len(SPECIAL_INTS))]
        elif self.n_out_of(10, 32):
            v %= 256
        elif self.n_out_of(10, 22):
            v %= 4 << 10
        elif self.n_out_of(10, 12):
            v %= 64 << 10
        else:
            v %= 1 << 31
        if self.n_out_of(100, 107):
            pass
        elif self.n_out_of(5, 7):
            v = (-v) & MASK64
        else:
            v = (v << self.intn(63)) & MASK64
        return v

    def rand_range_int(self, begin: int, end: int) -> int:
        """(reference: prog/rand.go:93-98).  Negative range bounds
        arrive as two's-complement uint64s (begin > end numerically,
        e.g. int32[-20:19]); the span must be computed with Go-style
        uint64 wraparound or the Python modulus goes negative and the
        result is ~uniform 64-bit garbage."""
        if self.one_of(100):
            return self.rand_int()
        span = ((end - begin) & MASK64) + 1
        return (begin + self.uint64() % span) & MASK64

    def biased_rand(self, n: int, k: int) -> int:
        """Random int in [0, n); probability of n-1 is k times higher
        than of 0 (reference: prog/rand.go:100-107)."""
        nf, kf = float(n), float(k)
        rf = nf * (kf / 2 + 1) * self.r.random()
        bf = (-1 + math.sqrt(1 + 2 * kf * rf / nf)) * nf / kf
        return min(int(bf), n - 1)

    def rand_array_len(self) -> int:
        """Favors short arrays, 0 least likely
        (reference: prog/rand.go:109-114)."""
        max_len = 10
        return (max_len - self.biased_rand(max_len + 1, 10) + 1) % (max_len + 1)

    def rand_buf_len(self) -> int:
        """(reference: prog/rand.go:116-124)"""
        if self.n_out_of(50, 56):
            return self.rand(256)
        if self.n_out_of(5, 6):
            return 4 << 10
        return 0

    def rand_page_count(self) -> int:
        """(reference: prog/rand.go:126-136)"""
        if self.n_out_of(100, 106):
            return self.rand(4) + 1
        if self.n_out_of(5, 6):
            return self.rand(20) + 1
        return (self.rand(3) + 1) * 512

    def flags(self, vv: tuple[int, ...]) -> int:
        """OR a few flag values together most of the time
        (reference: prog/rand.go:138-152)."""
        if self.n_out_of(90, 111):
            v = 0
            while True:
                v |= vv[self.rand(len(vv))]
                if self.bin():
                    return v
        if self.n_out_of(10, 21):
            return vv[self.rand(len(vv))]
        if self.n_out_of(10, 11):
            return 0
        return self.rand64()

    # -- strings/files ---------------------------------------------------

    SPECIAL_FILES = ("", "/", ".")
    PUNCT = b"!@#$%^&*()-+\\/:.,-'[]{}"

    def filename(self, s, typ) -> str:
        """(reference: prog/rand.go:154-169)"""
        fn = self._filename_impl(s)
        assert not (fn and fn[-1] == "\x00"), "zero-terminated filename"
        if not typ.varlen:
            size = typ.size()
            if len(fn) < size:
                fn += "\x00" * (size - len(fn))
            fn = fn[:size]
        elif not typ.no_z:
            fn += "\x00"
        return fn

    def _filename_impl(self, s) -> str:
        """(reference: prog/rand.go:173-202)"""
        if self.one_of(100):
            return self.SPECIAL_FILES[self.intn(len(self.SPECIAL_FILES))]
        if not s.files or self.one_of(10):
            dir_ = "."
            if self.one_of(2) and s.files:
                files = sorted(s.files)
                dir_ = files[self.intn(len(files))]
                if dir_ and dir_[-1] == "\x00":
                    dir_ = dir_[:-1]
            i = 0
            while True:
                f = f"{dir_}/file{i}"
                if f not in s.files:
                    return f
                i += 1
        files = sorted(s.files)
        return files[self.intn(len(files))]

    def rand_string(self, s, typ) -> bytes:
        """(reference: prog/rand.go:204-237)"""
        if typ.values:
            return typ.values[self.intn(len(typ.values))]
        if s.strings and self.bin():
            strs = sorted(s.strings)
            return strs[self.intn(len(strs))].encode("latin-1")
        buf = bytearray()
        while self.n_out_of(3, 4):
            if self.n_out_of(10, 21):
                d = self.target.string_dictionary
                if d:
                    buf.extend(d[self.intn(len(d))].encode("latin-1"))
            elif self.n_out_of(10, 11):
                buf.append(self.PUNCT[self.intn(len(self.PUNCT))])
            else:
                buf.append(self.intn(256))
        if self.one_of(100) == typ.no_z:
            buf.append(0)
        return bytes(buf)

    # -- machine text ----------------------------------------------------

    def generate_text(self, kind) -> bytes:
        """Machine-code blobs for text args; a byte-soup stand-in plus
        structured x86 prefixes (reference: prog/rand.go:323-336 routes
        to pkg/ifuzz; ops-level instruction modeling lives in
        utils/ifuzz.py)."""
        from syzkaller_tpu.utils import ifuzz

        return ifuzz.generate(kind, self.r)

    def mutate_text(self, kind, text: bytes) -> bytes:
        from syzkaller_tpu.utils import ifuzz

        return ifuzz.mutate(kind, self.r, text)
