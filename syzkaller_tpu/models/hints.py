"""Comparison-hint mutation.

The kernel's KCOV_TRACE_CMP feed gives us (operand, operand) pairs per
call; shrink/expand models int truncation/sign-extension/endianness to
match program bytes against observed operands and substitute the other
side (reference: prog/hints.go:27-218).
"""

from __future__ import annotations

from typing import Callable

from syzkaller_tpu.models.prog import (
    Arg,
    Call,
    ConstArg,
    DataArg,
    Prog,
    foreach_arg,
)
from syzkaller_tpu.models.rand import SPECIAL_INTS_SET
from syzkaller_tpu.models.types import CsumType, Dir, ProcType
from syzkaller_tpu.utils.ints import MASK64, load_int, store_int, swap_int

MAX_DATA_LENGTH = 100


class CompMap:
    """op1 -> set of operands op1 was compared against
    (reference: prog/hints.go:27-48)."""

    __slots__ = ("m",)

    def __init__(self):
        self.m: dict[int, set[int]] = {}

    def add_comp(self, arg1: int, arg2: int) -> None:
        self.m.setdefault(arg1 & MASK64, set()).add(arg2 & MASK64)

    def __len__(self) -> int:
        return len(self.m)

    def __str__(self) -> str:
        return ", ".join(
            f"0x{v:x}: " + " ".join(f"0x{c:x}" for c in comps)
            for v, comps in self.m.items())


def mutate_with_hints(p: Prog, call_index: int, comps: CompMap,
                      exec_cb: Callable[[Prog], None]) -> None:
    """For every matchable arg byte-window of call `call_index`, execute
    each replacement mutant (reference: prog/hints.go:66-80)."""
    p = p.clone()
    c = p.calls[call_index]

    def exec_validate() -> None:
        from syzkaller_tpu.models import validation

        if validation.debug:
            validation.validate_prog(p)
        exec_cb(p)

    def visit(arg: Arg, ctx) -> None:
        generate_hints(comps, arg, exec_validate)

    foreach_arg(c, visit)


def generate_hints(comp_map: CompMap, arg: Arg, exec_cb: Callable[[], None]) -> None:
    """(reference: prog/hints.go:82-103)"""
    typ = arg.typ
    if typ is None or typ.dir == Dir.OUT:
        return
    if isinstance(typ, ProcType):
        return  # random proc will not pass validation
    if isinstance(typ, CsumType):
        return  # computed dynamically, never matches
    if isinstance(arg, ConstArg):
        _check_const_arg(arg, comp_map, exec_cb)
    elif isinstance(arg, DataArg):
        _check_data_arg(arg, comp_map, exec_cb)


def _check_const_arg(arg: ConstArg, comp_map: CompMap,
                     exec_cb: Callable[[], None]) -> None:
    original = arg.val
    for replacer in sorted(shrink_expand(original, comp_map)):
        arg.val = replacer
        exec_cb()
    arg.val = original


def _check_data_arg(arg: DataArg, comp_map: CompMap,
                    exec_cb: Callable[[], None]) -> None:
    data = arg.data
    size = min(len(data), MAX_DATA_LENGTH)
    for i in range(size):
        window = min(8, len(data) - i)
        original = bytes(data[i:i + 8]).ljust(8, b"\x00")
        val = load_int(original, 0, 8)
        for replacer in sorted(shrink_expand(val, comp_map)):
            store_int(data, i, replacer, window)
            exec_cb()
        data[i:i + window] = original[:window]


def shrink_expand(v: int, comp_map: CompMap) -> set[int]:
    """Model the casts the kernel may apply to the argument before
    comparing: truncation to 1/2/4/8 bytes and sign extension from
    1/2/4, in both endiannesses; replace the matching low bits with the
    other comparison operand (reference: prog/hints.go:164-218)."""
    replacers: set[int] = set()
    for iwidth in (8, 4, 2, 1, -4, -2, -1):
        if iwidth > 0:
            width = iwidth
            size = width * 8
            mutant = v & ((1 << size) - 1)
        else:
            width = -iwidth
            size = width * 8
            mutant = (v | (MASK64 ^ ((1 << size) - 1))) & MASK64
        for big_endian in (False, True):
            if big_endian:
                if width == 1:
                    continue
                mutant = swap_int(mutant, width)
            for new_v in comp_map.m.get(mutant, ()):
                mask = (1 << size) - 1
                new_hi = new_v & ~mask & MASK64
                new_v &= mask
                # The other operand is wider than the cast value:
                # no valid code does that; skip (unless sign extension).
                if new_hi != 0 and (new_hi ^ (~mask & MASK64)) != 0:
                    continue
                if big_endian:
                    new_v = swap_int(new_v, width)
                if new_v in SPECIAL_INTS_SET:
                    continue
                # Replace size low bits of v with new_v.
                replacer = ((v & ~mask) | new_v) & MASK64
                replacers.add(replacer)
    return replacers
