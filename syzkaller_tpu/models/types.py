"""Syscall type system.

Semantics follow the reference type model (reference: prog/types.go:10-397):
a Syscall has typed arguments; there are 14 type kinds (resource, const,
int, flags, len, proc, csum, vma, buffer, array, ptr, struct, union +
bitfields/padding expressed on int-like types).  Unlike the reference,
types here are plain data (no generate/mutate virtuals): behaviour lives
in models/generation.py and models/mutation.py, which keeps type objects
directly serializable into the device-side type tables used by the
batched TPU kernels (ops/tensor.py).

All integer values are Python ints interpreted modulo 2**64; helpers in
utils/ints.py do the masking.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Optional


class Dir(enum.IntEnum):
    IN = 0
    OUT = 1
    INOUT = 2

    def __str__(self) -> str:
        return {Dir.IN: "in", Dir.OUT: "out", Dir.INOUT: "inout"}[self]


@dataclass(eq=False)
class Type:
    """Base of all syscall argument types.

    type_size is the static byte size, 0 for variable-size types
    (reference: prog/types.go:64-110).
    """

    name: str = ""
    field_name: str = ""
    type_size: int = 0
    dir: Dir = Dir.IN
    optional: bool = False
    varlen: bool = False

    def size(self) -> int:
        if self.varlen:
            raise ValueError(f"static size of varlen type {self.name} is unknown")
        return self.type_size

    def default(self) -> int:
        return 0

    # Bitfield accessors; only int-like types carry real values
    # (reference: prog/types.go:100-110).
    def bitfield_offset(self) -> int:
        return 0

    def bitfield_length(self) -> int:
        return 0

    def bitfield_middle(self) -> bool:
        return False

    def __str__(self) -> str:
        return self.name


@dataclass(eq=False)
class IntCommon(Type):
    """Shared by all integer-backed types: bitfields and endianness
    (reference: prog/types.go:140-162)."""

    bitfield_off: int = 0
    bitfield_len: int = 0
    big_endian: bool = False
    bitfield_mdl: bool = False  # True for all but the last bitfield in a group

    def bitfield_offset(self) -> int:
        return self.bitfield_off

    def bitfield_length(self) -> int:
        return self.bitfield_len

    def bitfield_middle(self) -> bool:
        return self.bitfield_mdl


@dataclass(eq=False)
class ResourceDesc:
    """A kernel-object kind (fd, sock, pid...). kind is the subtyping
    chain, most general first; values are special fallback values
    (reference: prog/types.go:116-121)."""

    name: str = ""
    type: Optional[Type] = None
    kind: tuple[str, ...] = ()
    values: tuple[int, ...] = (0,)


@dataclass(eq=False)
class ResourceType(IntCommon):
    desc: Optional[ResourceDesc] = None

    def default(self) -> int:
        assert self.desc is not None
        return self.desc.values[0]

    def special_values(self) -> tuple[int, ...]:
        assert self.desc is not None
        return self.desc.values


@dataclass(eq=False)
class ConstType(IntCommon):
    val: int = 0
    is_pad: bool = False

    def default(self) -> int:
        return self.val

    def __str__(self) -> str:
        if self.is_pad:
            return f"pad[{self.type_size}]"
        return f"const[{self.val:#x}, {self.name}]"


class IntKind(enum.IntEnum):
    PLAIN = 0
    FILEOFF = 1  # offset within a file
    RANGE = 2


@dataclass(eq=False)
class IntType(IntCommon):
    kind: IntKind = IntKind.PLAIN
    range_begin: int = 0
    range_end: int = 0


@dataclass(eq=False)
class FlagsType(IntCommon):
    vals: tuple[int, ...] = ()


@dataclass(eq=False)
class LenType(IntCommon):
    """Length of the field named buf (or "parent"/ancestor-struct path).
    bit_size: 0 = element count, 8*k = size in k-byte units, 1 = bits
    (reference: prog/types.go:197-201)."""

    bit_size: int = 0
    buf: str = ""


@dataclass(eq=False)
class ProcType(IntCommon):
    """Per-process disjoint value ranges (reference: prog/types.go:203-212)."""

    values_start: int = 0
    values_per_proc: int = 0

    def default(self) -> int:
        # Special value meaning "0 for all procs".
        return 0xFFFFFFFFFFFFFFFF


class CsumKind(enum.IntEnum):
    INET = 0
    PSEUDO = 1


@dataclass(eq=False)
class CsumType(IntCommon):
    kind: CsumKind = CsumKind.INET
    buf: str = ""
    protocol: int = 0  # for PSEUDO


@dataclass(eq=False)
class VmaType(Type):
    # Page-count range; 0/0 = unconstrained.
    range_begin: int = 0
    range_end: int = 0


class BufferKind(enum.IntEnum):
    BLOB_RAND = 0
    BLOB_RANGE = 1
    STRING = 2
    FILENAME = 3
    TEXT = 4


class TextKind(enum.IntEnum):
    X86_REAL = 0
    X86_16 = 1
    X86_32 = 2
    X86_64 = 3
    ARM64 = 4


@dataclass(eq=False)
class BufferType(Type):
    kind: BufferKind = BufferKind.BLOB_RAND
    range_begin: int = 0  # for BLOB_RANGE
    range_end: int = 0
    text: TextKind = TextKind.X86_64  # for TEXT
    sub_kind: str = ""
    values: tuple[bytes, ...] = ()  # possible values for STRING
    no_z: bool = False  # non-zero-terminated STRING/FILENAME


class ArrayKind(enum.IntEnum):
    RAND_LEN = 0
    RANGE_LEN = 1


@dataclass(eq=False)
class ArrayType(Type):
    elem: Optional[Type] = None
    kind: ArrayKind = ArrayKind.RAND_LEN
    range_begin: int = 0
    range_end: int = 0

    def __str__(self) -> str:
        return f"array[{self.elem}]"


@dataclass(eq=False)
class PtrType(Type):
    elem: Optional[Type] = None

    def __str__(self) -> str:
        return f"ptr[{self.dir}, {self.elem}]"


@dataclass(eq=False)
class StructType(Type):
    """Struct with computed field layout.  The compiler (or builder)
    resolves alignment/padding at target-build time by inserting
    explicit pad fields, so layout here is final
    (reference: prog/types.go:305-337 + pkg/compiler layout)."""

    fields: list[Type] = field(default_factory=list)
    align_attr: int = 0


@dataclass(eq=False)
class UnionType(Type):
    fields: list[Type] = field(default_factory=list)


@dataclass(frozen=True)
class ConstValue:
    name: str
    value: int


@dataclass(eq=False)
class Syscall:
    """Syscall metadata (reference: prog/types.go:10-17)."""

    id: int = -1
    nr: int = 0
    name: str = ""
    call_name: str = ""
    args: list[Type] = field(default_factory=list)
    ret: Optional[Type] = None
    # attrs used by fuzzing policy
    disabled: bool = False

    def __repr__(self) -> str:
        return f"<Syscall {self.name}>"


def is_pad(t: Type) -> bool:
    return isinstance(t, ConstType) and t.is_pad


def foreach_type(meta: Syscall, fn: Callable[[Type], None]) -> None:
    """Visit every type reachable from a syscall, pruning struct/union
    recursion (reference: prog/types.go:358-396)."""
    seen: set[int] = set()

    def rec(t: Type) -> None:
        fn(t)
        if isinstance(t, (PtrType, ArrayType)):
            assert t.elem is not None
            rec(t.elem)
        elif isinstance(t, (StructType, UnionType)):
            if id(t) in seen:
                return
            seen.add(id(t))
            for f in t.fields:
                rec(f)

    for t in meta.args:
        rec(t)
    if meta.ret is not None:
        rec(meta.ret)
