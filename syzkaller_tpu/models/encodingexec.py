"""Exec wire format: the binary uint64 stream consumed by executors.

This is the contract with the (unchanged) in-VM executor and the
output format the TPU engine emits for mutated batches.  Layout
(reference: prog/encodingexec.go:7-51):

  stream   := { copyin | csum-copyin | call | copyout } EOF
  copyin   := COPYIN addr arg
  call     := call_word copyout_idx nargs arg*
              call_word = table_id | kernel_nr << 32 (the executor
              dispatches real syscalls by nr; ids key results/sim)
  copyout  := COPYOUT idx addr size
  arg      := const | result | data | csum
  const    := ARG_CONST meta val            meta = size | be<<8 |
              bf_off<<16 | bf_len<<24 | pid_stride<<32
  result   := ARG_RESULT size idx op_div op_add default
  data     := ARG_DATA (len | cap<<32) byte* (8-byte padded to
              max(cap, len); cap=0 means cap=len).  The capacity
              field is a TPU-first extension: the device mutation
              engine emits data regions at a fixed per-template
              capacity so mutated lengths never reshape the stream
              (the executor copies len bytes and advances by cap).
  csum     := ARG_CSUM size CSUM_INET nchunks
              { chunk_kind (addr|value) size }*

The serializer can also record an ExecRecord of patch positions —
per-arg word indices of value/meta/data words plus per-call word
ranges — which ops/emit.py uses to re-emit mutated program tensors
as exec bytes with a memcpy + scatter instead of a tree walk
(SURVEY.md §7: "serialize-to-exec is a gather").
"""

from __future__ import annotations

import struct
from typing import Optional

from syzkaller_tpu.models.checksum import CsumChunkKind, calc_checksums_call
from syzkaller_tpu.models.prog import (
    Arg,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    UnionArg,
    foreach_arg,
)
from syzkaller_tpu.models.types import CsumKind, Dir, ResourceType, is_pad
from syzkaller_tpu.utils.ints import MASK64

EXEC_INSTR_EOF = MASK64
EXEC_INSTR_COPYIN = MASK64 - 1
EXEC_INSTR_COPYOUT = MASK64 - 2

EXEC_ARG_CONST = 0
EXEC_ARG_RESULT = 1
EXEC_ARG_DATA = 2
EXEC_ARG_CSUM = 3

EXEC_ARG_CSUM_INET = 0
EXEC_ARG_CSUM_CHUNK_DATA = 0
EXEC_ARG_CSUM_CHUNK_CONST = 1

EXEC_BUFFER_SIZE = 2 << 20
EXEC_NO_COPYOUT = MASK64


class ExecBufferTooSmall(Exception):
    pass


class ExecRecord:
    """Patch positions collected during serialization (all are word
    indices into the emitted uint64 stream):

      val_word[id(arg)]   index of a ConstArg's value word
      meta_word[id(arg)]  index of the same arg's meta word
      data_word[id(arg)]  (len_word_idx, payload_word_idx, cap)
      call_bounds         per-call [start, end) word ranges covering
                          the call's copyins, csums, call instr and
                          copyouts (the EOF word is outside all)
      copyout_words       word indices whose VALUE is a copyout index
                          (call ret slot, COPYOUT instrs, RESULT arg
                          refs) — the set to rebase when splicing one
                          program's segment into another
      ncopyouts           copyout indices consumed by the program
    """

    def __init__(self):
        self.val_word: dict[int, int] = {}
        self.meta_word: dict[int, int] = {}
        self.data_word: dict[int, tuple[int, int, int]] = {}
        self.call_bounds: list[tuple[int, int]] = []
        self.copyout_words: list[int] = []
        self.ncopyouts: int = 0


class _Writer:
    def __init__(self, limit: int):
        self.words: list[int] = []
        self.limit = limit
        self.nbytes = 0

    def write(self, v: int) -> None:
        self.nbytes += 8
        if self.nbytes > self.limit:
            raise ExecBufferTooSmall()
        self.words.append(v & MASK64)

    def write_data(self, data: bytes, cap: int = 0) -> None:
        region = max(len(data), cap)
        padded = region + (-region) % 8
        self.nbytes += padded
        if self.nbytes > self.limit:
            raise ExecBufferTooSmall()
        buf = data + bytes(padded - len(data))
        for i in range(0, padded, 8):
            self.words.append(int.from_bytes(buf[i:i + 8], "little"))


def serialize_for_exec(p: Prog, buffer_size: int = EXEC_BUFFER_SIZE,
                       data_caps: Optional[dict[int, int]] = None,
                       record: Optional[ExecRecord] = None) -> bytes:
    """Serialize p for execution (reference: prog/encodingexec.go:57-192).
    Returns the encoded byte stream (little-endian uint64 words).

    data_caps maps id(DataArg) -> fixed region capacity (bytes); such
    args are emitted cap-padded so the device engine can grow them in
    place.  record, if given, collects patch positions (ExecRecord)."""
    from syzkaller_tpu.models import validation

    if validation.debug:
        validation.validate_prog(p)
    target = p.target
    w = _Writer(buffer_size)
    copyout_seq = 0
    # arg id -> (addr, copyout idx)
    args_info: dict[int, dict] = {}

    for c in p.calls:
        call_start = len(w.words)
        csum_map = calc_checksums_call(c)
        csum_uses: set[int] = set()
        if csum_map is not None:
            for _, (arg, info) in csum_map.items():
                csum_uses.add(id(arg))
                if info.kind == CsumKind.INET:
                    for chunk in info.chunks:
                        if chunk.kind == CsumChunkKind.ARG:
                            csum_uses.add(id(chunk.arg))

        # Copyin instructions for everything reachable through pointers.
        def copyin(arg: Arg, ctx) -> None:
            if ctx.base is None:
                return
            addr = target.physical_addr(ctx.base) + ctx.offset
            if (isinstance(arg, ResultArg) and len(arg.uses) != 0) \
                    or id(arg) in csum_uses:
                args_info[id(arg)] = {"addr": addr}
            if isinstance(arg, (GroupArg, UnionArg)):
                return
            t = arg.typ
            if t.dir == Dir.OUT or is_pad(t):
                return
            if arg.size() == 0 and not (
                    isinstance(arg, DataArg) and data_caps is not None
                    and data_caps.get(id(arg), 0)):
                # Zero-size args have nothing to copy in — except a
                # cap-padded data region, whose stream footprint is
                # fixed by the template so mutated lengths (including
                # len 0) never reshape the stream.
                return
            w.write(EXEC_INSTR_COPYIN)
            w.write(addr)
            _write_arg(w, target, arg, args_info, data_caps, record)

        foreach_arg(c, copyin)

        # Checksum instructions, last-to-first by address since later
        # checksums feed earlier ones (reference: encodingexec.go:112-152).
        if csum_map is not None:
            entries = sorted(csum_map.values(),
                             key=lambda e: args_info[id(e[0])]["addr"])
            for arg, info in reversed(entries):
                w.write(EXEC_INSTR_COPYIN)
                w.write(args_info[id(arg)]["addr"])
                w.write(EXEC_ARG_CSUM)
                w.write(arg.size())
                assert info.kind == CsumKind.INET
                w.write(EXEC_ARG_CSUM_INET)
                w.write(len(info.chunks))
                for chunk in info.chunks:
                    if chunk.kind == CsumChunkKind.ARG:
                        w.write(EXEC_ARG_CSUM_CHUNK_DATA)
                        w.write(args_info[id(chunk.arg)]["addr"])
                        w.write(chunk.arg.size())
                    else:
                        w.write(EXEC_ARG_CSUM_CHUNK_CONST)
                        w.write(chunk.value)
                        w.write(chunk.size)

        # The call itself: table id in the low word keys sim dispatch
        # and result attribution; the kernel NR in the high word is
        # what the real-OS executor backend passes to syscall(2).
        w.write(c.meta.id | (max(c.meta.nr, 0) << 32))
        if c.ret is not None and len(c.ret.uses) != 0:
            assert id(c.ret) not in args_info, "arg info exists for ret"
            args_info[id(c.ret)] = {"idx": copyout_seq, "ret": True}
            if record is not None:
                record.copyout_words.append(len(w.words))
            w.write(copyout_seq)
            copyout_seq += 1
        else:
            w.write(EXEC_NO_COPYOUT)
        w.write(len(c.args))
        for arg in c.args:
            _write_arg(w, target, arg, args_info, data_caps, record)

        # Copyout instructions persisting referenced results.
        def copyout(arg: Arg, ctx) -> None:
            nonlocal copyout_seq
            if isinstance(arg, ResultArg) and len(arg.uses) != 0:
                info = args_info.get(id(arg), {})
                if info.get("ret"):
                    return  # idx already assigned above
                info["idx"] = copyout_seq
                copyout_seq += 1
                args_info[id(arg)] = info
                w.write(EXEC_INSTR_COPYOUT)
                if record is not None:
                    record.copyout_words.append(len(w.words))
                w.write(info["idx"])
                w.write(info.get("addr", 0))
                w.write(arg.size())

        foreach_arg(c, copyout)
        if record is not None:
            record.call_bounds.append((call_start, len(w.words)))

    if record is not None:
        record.ncopyouts = copyout_seq
    w.write(EXEC_INSTR_EOF)
    return struct.pack(f"<{len(w.words)}Q", *w.words)


def _write_arg(w: _Writer, target, arg: Arg, args_info: dict,
               data_caps: Optional[dict] = None,
               record: Optional[ExecRecord] = None) -> None:
    """(reference: prog/encodingexec.go:230-272)"""
    if isinstance(arg, ConstArg):
        val, pid_stride, big_endian = arg.value()
        if record is not None:
            record.meta_word[id(arg)] = len(w.words) + 1
            record.val_word[id(arg)] = len(w.words) + 2
        _write_const_arg(w, arg.size(), val, arg.typ.bitfield_offset(),
                         arg.typ.bitfield_length(), pid_stride, big_endian)
    elif isinstance(arg, ResultArg):
        if arg.res is None:
            if record is not None:
                record.meta_word[id(arg)] = len(w.words) + 1
                record.val_word[id(arg)] = len(w.words) + 2
            _write_const_arg(w, arg.size(), arg.val, 0, 0, 0, False)
        else:
            info = args_info.get(id(arg.res))
            assert info is not None and "idx" in info, "no copyout index"
            w.write(EXEC_ARG_RESULT)
            w.write(arg.size())
            if record is not None:
                record.copyout_words.append(len(w.words))
            w.write(info["idx"])
            w.write(arg.op_div)
            w.write(arg.op_add)
            t = arg.typ
            assert isinstance(t, ResourceType)
            w.write(t.default())
    elif isinstance(arg, PointerArg):
        _write_const_arg(w, arg.size(), target.physical_addr(arg), 0, 0, 0, False)
    elif isinstance(arg, DataArg):
        data = bytes(arg.data)
        cap = 0
        if data_caps is not None:
            cap = data_caps.get(id(arg), 0)
        if record is not None:
            record.data_word[id(arg)] = (len(w.words) + 1, len(w.words) + 2,
                                         max(cap, len(data)))
        w.write(EXEC_ARG_DATA)
        w.write(len(data) | (cap << 32))
        w.write_data(data, cap)
    elif isinstance(arg, UnionArg):
        _write_arg(w, target, arg.option, args_info, data_caps, record)
    else:
        raise TypeError(f"unknown arg type {arg!r}")


def _write_const_arg(w: _Writer, size: int, val: int, bf_off: int, bf_len: int,
                     pid_stride: int, big_endian: bool) -> None:
    w.write(EXEC_ARG_CONST)
    meta = size | (bf_off << 16) | (bf_len << 24) | (pid_stride << 32)
    if big_endian:
        meta |= 1 << 8
    w.write(meta)
    w.write(val)


def words_of(stream: bytes) -> list[int]:
    """Decode a stream back into uint64 words (test/debug helper)."""
    assert len(stream) % 8 == 0
    return [int.from_bytes(stream[i:i + 8], "little")
            for i in range(0, len(stream), 8)]
